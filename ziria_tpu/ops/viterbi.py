"""Soft-decision Viterbi decoder for the 802.11 K=7 convolutional code.

Counterpart of the reference's SORA Viterbi brick (`sora_ext_viterbi.c`,
SSE-parallel ACS — SURVEY.md §2.2), the hottest RX kernel. TPU-native
design:

- the 64-state trellis (state = the 6 most recent input bits,
  newest in the MSB) is precomputed as numpy edge tables at module load;
- add-compare-select runs as one ``lax.scan`` over time with the state
  axis fully vectorized (64-wide VPU ops), and *frames batched via
  vmap* — the reference parallelizes ACS across SSE lanes, we
  parallelize across states x frames;
- traceback is a second (backward) scan over the stored per-step
  decisions; metrics are renormalized every step by subtracting the max
  to keep f32 well-conditioned.

Soft input: LLR-like reliabilities, positive = bit more likely 1 (so a
hard bit b maps to 2b-1). Punctured positions carry 0 (erasure), which
``ops.coding.depuncture`` inserts.

A Pallas VMEM-resident kernel of the same trellis lives in
ops/viterbi_pallas.py (bench path); this module is the reference
implementation both backends are tested against.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ziria_tpu.ops.coding import G0, G1, K
from ziria_tpu.utils import geometry as _geometry

N_STATES = 64


def _edge_tables():
    """For each next-state t and decision d in {0,1}: predecessor state
    and the two coded output bits on that edge (as +-1 floats)."""
    pred = np.zeros((N_STATES, 2), np.int32)
    out_a = np.zeros((N_STATES, 2), np.float32)
    out_b = np.zeros((N_STATES, 2), np.float32)
    for t in range(N_STATES):
        b = t >> 5                     # input bit of any edge into t
        for d in range(2):             # d = low bit of the predecessor
            s = ((t & 31) << 1) | d
            pred[t, d] = s
            # window [x_k, x_{k-1..k-6}] = [b] + bits of s (MSB=newest)
            window = [b] + [(s >> (5 - i)) & 1 for i in range(6)]
            a = sum(g * w for g, w in zip(G0, window)) % 2
            bb = sum(g * w for g, w in zip(G1, window)) % 2
            out_a[t, d] = 2.0 * a - 1.0
            out_b[t, d] = 2.0 * bb - 1.0
    return pred, out_a, out_b


_PRED, _OUT_A, _OUT_B = _edge_tables()

# --------------------------------------------------------------- quantized
# int16 saturating path metrics — the reference's SORA discipline
# (sora_ext_viterbi.c ran 16-bit metrics in SSE lanes; SURVEY.md §2.2).
# Soft inputs quantize to [-QUANT_MAX, QUANT_MAX] integers; every branch
# metric is then an exact small integer, so int arithmetic and f32
# arithmetic agree bit-for-bit on the same quantized inputs as long as
# the metrics stay in range (docs/quantized_viterbi.md derives the
# bound). METRIC_DTYPES is the knob's whole legal surface — every layer
# (kernel, externals, CLI) validates against it so a typo'd mode can
# never silently fall back to f32.

QUANT_MAX = 127                  # 8-bit soft values, like SORA's bricks
I16_MIN, I16_MAX = -(1 << 15), (1 << 15) - 1

# int8 saturating metrics — one storage level below the int16 path.
# The soft values quantize to +-INT8_QUANT_MAX = 15 (4-bit soft
# decisions, the classic hardware-decoder operating point): coarser
# than the int16 path's +-127 because the int8 rail at -128 is
# shallow — the renormed max sits at 0 and a state 128/(2*qmax) ≈ 4
# worst-case branch metrics behind saturates. Measured across the
# operating range, that clip never touches a surviving path (the
# kernel's block-cadence renorm keeps contenders well clear of the
# rail; tools/rx_dispatch_bench.viterbi_kernel_stats gates it), but
# unlike int16 there is no PROOF it cannot, and the 4-bit rounding
# itself legitimately moves near-tie decisions vs the f32 decode on
# raw inputs — so the int8 contract is the statistical BER envelope
# (tests/test_viterbi_radix4.py), not bit identity.
INT8_QUANT_MAX = 15
I8_MIN, I8_MAX = -(1 << 7), (1 << 7) - 1
# the valid-metric set lives with the geometry object (the declared
# search space of the autotuner) — aliased here so kernel code and
# error messages keep their historical spelling
METRIC_DTYPES = _geometry.VITERBI_METRICS

# radix of the Pallas ACS sweep: 2 = one trellis step per kernel
# iteration (the oracle), 4 = two steps fused per iteration (butterfly
# pairs collapsed — half the sequential dependency chain), decode
# bit-identical to radix 2 at float32 and int16 by construction
# (ops/viterbi_pallas.py derives it). The lax.scan decoders ignore it.
RADIXES = _geometry.VITERBI_RADIXES


def quantize_llrs(llrs, qmax: int = QUANT_MAX):
    """(…, 2) float LLRs -> (int16 quantized LLRs, f32 scale).

    The scale maps the max |llr| onto ``qmax`` PER FRAME — for a
    (B, T, 2) batch each lane gets its own scale (shape (B, 1, 1));
    a lone (T, 2)/(2T,) frame gets a scalar. A positive uniform
    scaling of one frame never changes its ACS decisions or end-state
    argmax, so any per-frame scale is decode-equivalent and rounding
    is the only lossy step. Per-frame (not batch-global) scaling is
    what makes a frame's quantized decode independent of its
    batch-mates: receive_many lanes match per-capture receive()
    bit for bit. Traced-shape safe: scales are jnp values.
    """
    llrs = jnp.asarray(llrs, jnp.float32)
    if llrs.ndim == 3:
        peak = jnp.max(jnp.abs(llrs), axis=(1, 2), keepdims=True)
    else:
        peak = jnp.max(jnp.abs(llrs))
    scale = qmax / jnp.maximum(peak, 1e-12)
    q = jnp.clip(jnp.round(llrs * scale), -qmax, qmax)
    return q.astype(jnp.int16), scale


def _check_metric_dtype(metric_dtype):
    md = metric_dtype or "float32"
    if md not in METRIC_DTYPES:
        raise ValueError(
            f"metric_dtype {metric_dtype!r} is not one of {METRIC_DTYPES}")
    return md


def _check_radix(radix) -> int:
    """Validate/resolve the ACS radix knob. ``None`` reads the
    ZIRIA_VITERBI_RADIX env default (2 when unset — the oracle). The
    resolved integer is what the jit-factory caches key on, so every
    surface resolves BEFORE building a cache key (the viterbi_metric
    discipline: an env change after tracing must re-trace, never
    silently reuse the other radix's program)."""
    if radix is None:
        # the env default lives with the geometry object's designated
        # readers (utils/geometry — validation included, same raises)
        return _geometry.env_viterbi_radix()
    radix = int(radix)
    if radix not in RADIXES:
        raise ValueError(f"viterbi radix {radix!r} is not one of {RADIXES}")
    return radix


def viterbi_decode_int16(qllrs, n_bits: int = None) -> jnp.ndarray:
    """Decode pre-quantized int LLR pairs with int16 saturating
    metrics — the lax.scan ORACLE of the quantized semantics (the
    Pallas int16 kernel in ops/viterbi_pallas.py is tested against
    this, and this against the f32 decode on the same inputs).

    Arithmetic runs in int32 and every renormalized metric saturates
    into [I16_MIN, I16_MAX] — exactly what the kernel's int16 VMEM
    scratch enforces. Saturation only ever touches unreachable states
    (see docs/quantized_viterbi.md), so the decoded path matches the
    f32 decode bit-for-bit on in-range inputs.
    """
    q = jnp.asarray(qllrs, jnp.int32)
    if q.ndim == 1:
        q = q.reshape(-1, 2)

    pred = jnp.asarray(_PRED)
    out_a = jnp.asarray(_OUT_A, np.float32).astype(jnp.int32)
    out_b = jnp.asarray(_OUT_B, np.float32).astype(jnp.int32)

    init = jnp.full((N_STATES,), I16_MIN, jnp.int32).at[0].set(0)

    def acs(metrics, llr):
        cand = metrics[pred] + out_a * llr[0] + out_b * llr[1]
        best = jnp.argmax(cand, axis=1).astype(jnp.uint8)
        new = jnp.max(cand, axis=1)
        new = new - jnp.max(new)           # renormalize: max pinned at 0
        new = jnp.clip(new, I16_MIN, I16_MAX)   # saturating int16 store
        return new, best

    metrics, decisions = jax.lax.scan(acs, init, q)
    end_state = jnp.argmax(metrics).astype(jnp.int32)

    def back(state, dec):
        bit = (state >> 5).astype(jnp.uint8)
        prev = pred[state, dec[state]]
        return prev, bit

    _, bits = jax.lax.scan(back, end_state, decisions, reverse=True)
    if n_bits is not None:
        bits = bits[:n_bits]
    return bits


def viterbi_decode_int8(qllrs, n_bits: int = None) -> jnp.ndarray:
    """Decode pre-quantized int LLR pairs (|q| <= INT8_QUANT_MAX) with
    int8 saturating metrics — the readable lax.scan REFERENCE of the
    int8 discipline. Arithmetic runs in int32; every renormalized
    metric saturates into [I8_MIN, I8_MAX] (per step here; the Pallas
    kernel saturates at its block cadence — a strictly SOFTER clip).
    The int8 rail is shallow enough that clipping can, on adversarial
    inputs, touch states that later matter, which is why this path's
    contract is a BER envelope rather than the int16 path's bit
    identity (docs/quantized_viterbi.md §int8)."""
    q = jnp.asarray(qllrs, jnp.int32)
    if q.ndim == 1:
        q = q.reshape(-1, 2)

    pred = jnp.asarray(_PRED)
    out_a = jnp.asarray(_OUT_A, np.float32).astype(jnp.int32)
    out_b = jnp.asarray(_OUT_B, np.float32).astype(jnp.int32)

    init = jnp.full((N_STATES,), I8_MIN, jnp.int32).at[0].set(0)

    def acs(metrics, llr):
        cand = metrics[pred] + out_a * llr[0] + out_b * llr[1]
        best = jnp.argmax(cand, axis=1).astype(jnp.uint8)
        new = jnp.max(cand, axis=1)
        new = new - jnp.max(new)           # renormalize: max pinned at 0
        new = jnp.clip(new, I8_MIN, I8_MAX)     # saturating int8 store
        return new, best

    metrics, decisions = jax.lax.scan(acs, init, q)
    end_state = jnp.argmax(metrics).astype(jnp.int32)

    def back(state, dec):
        bit = (state >> 5).astype(jnp.uint8)
        prev = pred[state, dec[state]]
        return prev, bit

    _, bits = jax.lax.scan(back, end_state, decisions, reverse=True)
    if n_bits is not None:
        bits = bits[:n_bits]
    return bits


def viterbi_decode(llrs, n_bits: int = None,
                   metric_dtype: str = None) -> jnp.ndarray:
    """Decode soft values.

    llrs: (2T,) or (T, 2) float — reliabilities for coded bits (A_k, B_k);
    positive means "more likely 1". Assumes the encoder started in state
    0 (initial metric pins state 0); traceback starts from the
    highest-metric end state — for a zero-terminated (802.11 tail)
    stream that IS state 0 at reasonable SNR, and argmax degrades more
    gracefully when it isn't. Returns (T,) decoded bits; the caller
    slices off tail/pad (or passes n_bits to do it here).

    ``metric_dtype="int16"`` quantizes the LLRs (quantize_llrs) and
    decodes with int16 saturating metrics — the SORA trade; see
    viterbi_decode_int16 for the semantics.
    """
    md = _check_metric_dtype(metric_dtype)
    if md == "int16":
        q, _scale = quantize_llrs(llrs)
        return viterbi_decode_int16(q, n_bits)
    if md == "int8":
        q, _scale = quantize_llrs(llrs, qmax=INT8_QUANT_MAX)
        return viterbi_decode_int8(q, n_bits)
    llrs = jnp.asarray(llrs, jnp.float32)
    if llrs.ndim == 1:
        llrs = llrs.reshape(-1, 2)
    T = llrs.shape[0]

    pred = jnp.asarray(_PRED)
    out_a = jnp.asarray(_OUT_A)
    out_b = jnp.asarray(_OUT_B)

    neg = jnp.float32(-1e30)
    init = jnp.full((N_STATES,), neg).at[0].set(0.0)

    def acs(metrics, llr):
        # candidate metric for each (next-state, decision)
        cand = metrics[pred] + out_a * llr[0] + out_b * llr[1]  # (64, 2)
        best = jnp.argmax(cand, axis=1).astype(jnp.uint8)
        new = jnp.max(cand, axis=1)
        new = new - jnp.max(new)  # renormalize
        return new, best

    metrics, decisions = jax.lax.scan(acs, init, llrs)  # decisions (T, 64)

    end_state = jnp.argmax(metrics).astype(jnp.int32)

    def back(state, dec):
        bit = (state >> 5).astype(jnp.uint8)
        prev = pred[state, dec[state]]
        return prev, bit

    _, bits_rev = jax.lax.scan(back, end_state, decisions, reverse=True)
    bits = bits_rev  # scan(reverse=True) already yields outputs in order
    if n_bits is not None:
        bits = bits[:n_bits]
    return bits


def viterbi_decode_bits(coded_bits, n_bits: int = None) -> jnp.ndarray:
    """Hard-decision convenience: 0/1 coded bits -> decoded bits."""
    b = jnp.asarray(coded_bits, jnp.float32)
    return viterbi_decode(2.0 * b - 1.0, n_bits)


def np_viterbi_decode(llrs: np.ndarray, n_bits: int = None) -> np.ndarray:
    """Host-side numpy decode, same trellis/semantics as viterbi_decode.

    The single numpy ACS implementation shared by the interpreter
    backend's `viterbi_soft` external (frontend/externals.py) and the
    bench's CPU baseline — vectorized over the 64 states, python loop
    over time (the C baseline in runtime/native is the fast host path).
    """
    dep = np.asarray(llrs, np.float32)
    if dep.ndim == 1:
        dep = dep.reshape(-1, 2)
    T = dep.shape[0]
    pred = np.asarray(_PRED)
    out_a = np.asarray(_OUT_A, np.float32)
    out_b = np.asarray(_OUT_B, np.float32)
    metrics = np.full(N_STATES, -1e30, np.float32)
    metrics[0] = 0.0
    decisions = np.zeros((T, N_STATES), np.uint8)
    for k in range(T):
        cand = metrics[pred] + out_a * dep[k, 0] + out_b * dep[k, 1]
        decisions[k] = np.argmax(cand, 1)
        metrics = cand.max(1)
        metrics -= metrics.max()
    state = int(np.argmax(metrics))
    bits = np.zeros(T, np.uint8)
    for k in range(T - 1, -1, -1):
        bits[k] = state >> 5
        state = pred[state, decisions[k, state]]
    return bits[:n_bits] if n_bits is not None else bits


def np_viterbi_ref(llrs: np.ndarray) -> np.ndarray:
    """Independent oracle: dict-based python Viterbi. Tests only."""
    llrs = np.asarray(llrs, np.float64).reshape(-1, 2)
    T = llrs.shape[0]
    metrics = {0: 0.0}
    paths = {0: []}
    for k in range(T):
        new_m, new_p = {}, {}
        for s, m in metrics.items():
            for b in (0, 1):
                window = [b] + [(s >> (5 - i)) & 1 for i in range(6)]
                a = sum(g * w for g, w in zip(G0, window)) % 2
                bb = sum(g * w for g, w in zip(G1, window)) % 2
                t = (b << 5) | (s >> 1)
                cand = (m + (2 * a - 1) * llrs[k, 0]
                        + (2 * bb - 1) * llrs[k, 1])
                if t not in new_m or cand > new_m[t]:
                    new_m[t] = cand
                    new_p[t] = paths[s] + [b]
        metrics, paths = new_m, new_p
    best = max(metrics, key=metrics.get)
    return np.array(paths[best], np.uint8)
