"""OFDM symbol assembly: subcarrier mapping, pilots, DFT/IDFT, cyclic
prefix, and the PLCP preamble (STS/LTS).

Counterpart of the reference's `map_ofdm.blk` + `ifft.blk` + preamble
generation (SURVEY.md §2.3), with MXU matmul-DFTs (ops/cplx.dft_pair)
replacing the SORA SSE FFT bricks (§2.2).

All sample data uses the framework's pair representation
(`(..., 2) float32`, ops/cplx): the axon TPU backend has no complex
dtype, and the reference likewise carries complex as integer pairs.
Everything is batched over leading symbol/frame axes — a whole frame of
symbols is one (n_sym, 64) x (64, 64) GEMM per re/im component.

Constants follow IEEE 802.11a-1999 §17.3 (values reproduced from
standard knowledge; the reference mount was empty so no file:line
citations are possible — see SURVEY.md evidence note).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ziria_tpu.ops import cplx
from ziria_tpu.ops.scramble import np_lfsr_sequence_127

N_FFT = 64
N_CP = 16
N_DATA = 48

# subcarrier indices (FFT bin, negative = N_FFT + k)
PILOT_SC = np.array([-21, -7, 7, 21])
PILOT_VALS = np.array([1.0, 1.0, 1.0, -1.0])
_used = [k for k in range(-26, 27) if k != 0]
DATA_SC = np.array([k for k in _used if k not in set(PILOT_SC.tolist())])
assert DATA_SC.size == N_DATA

DATA_BINS = np.where(DATA_SC < 0, DATA_SC + N_FFT, DATA_SC)
PILOT_BINS = np.where(PILOT_SC < 0, PILOT_SC + N_FFT, PILOT_SC)

# pilot polarity sequence p_0..p_126: scrambler sequence with all-ones
# seed, mapped 0 -> +1, 1 -> -1 (host-side constant, no JAX at import)
_seq = np_lfsr_sequence_127(np.ones(7, np.uint8))
PILOT_POLARITY = (1.0 - 2.0 * _seq.astype(np.float64))

# long training symbol, subcarriers -26..26 (0 at DC)
LTS_FREQ = np.array(
    [1, 1, -1, -1, 1, 1, -1, 1, -1, 1, 1, 1, 1, 1, 1, -1, -1, 1, 1, -1,
     1, -1, 1, 1, 1, 1,
     0,
     1, -1, -1, 1, 1, -1, 1, -1, 1, -1, -1, -1, -1, -1, 1, 1, -1, -1, 1,
     -1, 1, -1, 1, 1, 1, 1], np.float64)

# short training symbol: nonzero every 4th subcarrier in -24..24
STS_SC = np.array([-24, -20, -16, -12, -8, -4, 4, 8, 12, 16, 20, 24])
STS_VALS = np.sqrt(13.0 / 6.0) * np.array(
    [1 + 1j, -1 - 1j, 1 + 1j, -1 - 1j, -1 - 1j, 1 + 1j,
     -1 - 1j, -1 - 1j, 1 + 1j, 1 + 1j, 1 + 1j, 1 + 1j])

# TX time-domain scaling: unit average sample power over 52 used tones
TIME_SCALE = N_FFT / np.sqrt(52.0)


def map_subcarriers(data_syms, symbol_index0: int = 1) -> jnp.ndarray:
    """(..., n_sym, 48, 2) data symbols -> (..., n_sym, 64, 2) frequency
    bins with pilots inserted. ``symbol_index0`` is the polarity index of
    the first symbol (SIGNAL uses 0; DATA symbols start at 1)."""
    syms = jnp.asarray(data_syms, jnp.float32)
    n_sym = syms.shape[-3]
    bins = jnp.zeros(syms.shape[:-2] + (N_FFT, 2), jnp.float32)
    bins = bins.at[..., jnp.asarray(DATA_BINS), :].set(syms)
    pol = jnp.asarray(PILOT_POLARITY, jnp.float32)[
        (jnp.arange(n_sym) + symbol_index0) % 127]
    pilots_re = jnp.asarray(PILOT_VALS, jnp.float32)[None, :] * pol[:, None]
    pilots = jnp.stack([pilots_re, jnp.zeros_like(pilots_re)], axis=-1)
    bins = bins.at[..., jnp.asarray(PILOT_BINS), :].set(pilots)
    return bins


def extract_subcarriers(bins):
    """(..., 64, 2) bins -> ((..., 48, 2) data, (..., 4, 2) pilots)."""
    bins = jnp.asarray(bins)
    return (bins[..., jnp.asarray(DATA_BINS), :],
            bins[..., jnp.asarray(PILOT_BINS), :])


def ofdm_modulate(bins) -> jnp.ndarray:
    """(..., 64, 2) frequency bins -> (..., 80, 2) time samples (CP +
    symbol), via the IDFT matmul; scaled for unit average power."""
    t = cplx.ifft_pair(jnp.asarray(bins, jnp.float32)) * TIME_SCALE
    return jnp.concatenate([t[..., N_FFT - N_CP:, :], t], axis=-2)


def ofdm_demodulate(samples) -> jnp.ndarray:
    """(..., 80, 2) time samples (CP + symbol) -> (..., 64, 2) bins."""
    sym = jnp.asarray(samples)[..., N_CP:, :]
    return cplx.fft_pair(sym) / TIME_SCALE


def _freq_to_bins(sc: np.ndarray, vals: np.ndarray) -> np.ndarray:
    bins = np.zeros(N_FFT, np.complex128)
    bins[np.where(sc < 0, sc + N_FFT, sc)] = vals
    return bins


def _preamble_np() -> np.ndarray:
    """numpy complex build (host-side constant), converted to pairs."""
    sts_bins = _freq_to_bins(STS_SC, STS_VALS)
    sts_time = (np.fft.ifft(sts_bins) * N_FFT / np.sqrt(12.0)
                / np.sqrt(13.0 / 6.0))
    short = np.tile(sts_time[:16], 10)

    lts_bins = _freq_to_bins(np.arange(-26, 27), LTS_FREQ)
    lts_time = np.fft.ifft(lts_bins) * N_FFT / np.sqrt(52.0)
    long = np.concatenate([lts_time[-32:], lts_time, lts_time])
    return np.concatenate([short, long])


_PREAMBLE = cplx.from_complex(_preamble_np())


def preamble() -> jnp.ndarray:
    """The 320-sample PLCP preamble as pairs (320, 2): 10 short symbols
    (160) + GI2 + 2 long symbols (160)."""
    return jnp.asarray(_PREAMBLE)


_LTS_TIME = cplx.from_complex(
    np.fft.ifft(_freq_to_bins(np.arange(-26, 27), LTS_FREQ))
    * N_FFT / np.sqrt(52.0))


def lts_time_symbol() -> np.ndarray:
    """One 64-sample long-training symbol as pairs (64, 2) (for RX
    channel estimation)."""
    return _LTS_TIME
