"""Checkpoint/resume of pipeline stream state.

The reference has no persistence — component state lives in the
generated C global state struct for the life of the process
(SURVEY.md §5). Here that state is an explicit value: the carry
returned by ``backend.execute.run_jit_carry`` — a dict of the
per-stage state pytree (``"stages"``) plus the input items that did
not yet fill a steady-state iteration (``"leftover"``). Checkpointing
is flatten + save:

    ys1, carry = run_jit_carry(prog, first_half)
    save_state("ckpt.npz", carry)
    ...process restarts...
    carry = load_state("ckpt.npz", like=lower(prog).init_carry)
    ys2, carry = run_jit_carry(prog, second_half, carry=carry)

`ys1 ++ ys2` equals the one-shot run for any split point (tested).
The template (`like`) restores the stage pytree structure — obtained
by lowering the same program, so a checkpoint is only loadable against
the pipeline that wrote it; a structure, shape, or dtype mismatch is
reported, not silently accepted.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


def save_state(path: str, carry: Any) -> None:
    """Serialize a run_jit_carry carry (or bare stage pytree) to .npz."""
    if isinstance(carry, dict) and "stages" in carry:
        stages = carry["stages"]
        leftover = np.asarray(carry.get("leftover", np.empty(0)))
    else:
        stages, leftover = carry, np.empty(0)
    leaves = jax.tree.leaves(stages)
    arrs = {f"leaf{i}": np.asarray(v) for i, v in enumerate(leaves)}
    np.savez(path, n_leaves=np.int64(len(leaves)), leftover=leftover,
             **arrs)


def load_state(path: str, like: Any) -> Any:
    """Load a carry saved by save_state, using `like` (the pipeline's
    ``lower(comp).init_carry``) as the stage-structure template."""
    with np.load(path) as z:
        n = int(z["n_leaves"])
        leaves = [z[f"leaf{i}"] for i in range(n)]
        leftover = z["leftover"] if "leftover" in z else np.empty(0)
    template_leaves, treedef = jax.tree.flatten(like)
    if len(template_leaves) != n:
        raise ValueError(
            f"checkpoint has {n} state leaves but the pipeline has "
            f"{len(template_leaves)} — wrong program for this checkpoint")
    for i, (a, b) in enumerate(zip(leaves, template_leaves)):
        b = np.asarray(b)
        if np.shape(a) != b.shape:
            raise ValueError(
                f"state leaf {i} shape {np.shape(a)} does not match the "
                f"pipeline's {b.shape} — wrong program for this "
                f"checkpoint")
        if np.asarray(a).dtype != b.dtype:
            raise ValueError(
                f"state leaf {i} dtype {np.asarray(a).dtype} does not "
                f"match the pipeline's {b.dtype} — wrong program for "
                f"this checkpoint")
    return {"stages": jax.tree.unflatten(treedef, leaves),
            "leftover": leftover}
