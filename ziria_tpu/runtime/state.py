"""Checkpoint/resume of pipeline stream state.

The reference has no persistence — component state lives in the
generated C global state struct for the life of the process
(SURVEY.md §5). Here that state is an explicit value: the carry
returned by ``backend.execute.run_jit_carry`` — a dict of the
per-stage state pytree (``"stages"``) plus the input items that did
not yet fill a steady-state iteration (``"leftover"``). Checkpointing
is flatten + save:

    ys1, carry = run_jit_carry(prog, first_half)
    save_state("ckpt.npz", carry)
    ...process restarts...
    carry = load_state("ckpt.npz", like=lower(prog).init_carry)
    ys2, carry = run_jit_carry(prog, second_half, carry=carry)

`ys1 ++ ys2` equals the one-shot run for any split point (tested).
The template (`like`) restores the stage pytree structure; leaf
count/shape/dtype mismatches are reported. Because two *different*
programs can coincidentally share a state layout, callers may also
pass ``fingerprint=program_fingerprint(comp)`` to both save and load —
the checkpoint then records which program wrote it and a mismatch is
an error (ADVICE r1: layout checks alone are not identity checks).
The CLI does this for --state-in/--state-out.
"""

from __future__ import annotations

import hashlib
from typing import Any, Optional

import jax
import numpy as np


def program_fingerprint(comp: Any) -> str:
    """A stable identity hash of a core-IR pipeline: node types, static
    counts/arities, bound names, stage function *code* and captured
    constants — enough to distinguish two programs whose state pytrees
    happen to have identical layouts, including two `zmap(lambda ...)`
    pipelines whose lambdas differ only in body.

    Deliberately excludes anything process-dependent (object addresses,
    dict order): the fingerprint must match across interpreter restarts
    or checkpoints would never load."""
    from ziria_tpu.core import ir

    parts: list = []

    def add_callable(fn: Any, depth: int) -> None:
        code = getattr(fn, "__code__", None)
        parts.append(getattr(fn, "__qualname__",
                             getattr(fn, "__name__", "fn")))
        if code is None or depth > 6:
            return
        parts.append(hashlib.sha256(code.co_code).hexdigest()[:12])
        for const in code.co_consts:
            if isinstance(const, (int, float, bool, str, bytes)) \
                    or const is None:
                parts.append(repr(const))
        # captured cells carry the distinguishing data for the shared
        # elab closures (the `run` functions all have identical co_code;
        # the AST lives in their cells)
        for cell in (fn.__closure__ or ()):
            try:
                add_value(cell.cell_contents, depth + 1)
            except ValueError:
                pass
        for dflt in (fn.__defaults__ or ()):
            add_value(dflt, depth + 1)

    def add_value(v: Any, depth: int) -> None:
        if depth > 6:
            return
        if isinstance(v, ir.Comp):
            walk(v, depth)
        elif isinstance(v, (str, int, bool, float)) or v is None:
            parts.append(repr(v))
        elif isinstance(v, (list, tuple)):
            for it in v[:64]:
                add_value(it, depth + 1)
        elif callable(v):
            add_callable(v, depth)
        elif hasattr(v, "dtype"):
            a = np.asarray(v)
            parts.append(f"arr{a.shape}{a.dtype}")
            # content hash for EVERY captured array — a big LUT edited
            # between runs must change the fingerprint too (review r2)
            parts.append(hashlib.sha256(
                np.ascontiguousarray(a).tobytes()).hexdigest()[:12])
        elif type(v).__module__.startswith("ziria_tpu"):
            # AST / IR dataclasses: frozen plain-data nodes whose repr
            # is deterministic — but guard against default object reprs,
            # whose addresses would make the fingerprint process-local
            r = repr(v)
            if " at 0x" not in r:
                parts.append(r[:4096])
            else:
                parts.append(type(v).__name__)

    def walk(x: Any, depth: int = 0) -> None:
        parts.append(type(x).__name__)
        d = getattr(x, "__dict__", None)
        if d is None or depth > 12:
            return
        for k in sorted(d):
            parts.append(k)
            add_value(d[k], depth + 1)
    walk(comp)
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def save_state(path: str, carry: Any,
               fingerprint: Optional[str] = None) -> None:
    """Serialize a run_jit_carry carry (or bare stage pytree) to .npz."""
    if isinstance(carry, dict) and "stages" in carry:
        stages = carry["stages"]
        leftover = np.asarray(carry.get("leftover", np.empty(0)))
    else:
        stages, leftover = carry, np.empty(0)
    leaves = jax.tree.leaves(stages)
    arrs = {f"leaf{i}": np.asarray(v) for i, v in enumerate(leaves)}
    if fingerprint is not None:
        arrs["fingerprint"] = np.asarray(fingerprint)
    np.savez(path, n_leaves=np.int64(len(leaves)), leftover=leftover,
             **arrs)


def load_state(path: str, like: Any,
               fingerprint: Optional[str] = None) -> Any:
    """Load a carry saved by save_state, using `like` (the pipeline's
    ``lower(comp).init_carry``) as the stage-structure template. When
    both the file and the caller provide a program fingerprint, they
    must agree."""
    with np.load(path) as z:
        n = int(z["n_leaves"])
        leaves = [z[f"leaf{i}"] for i in range(n)]
        leftover = z["leftover"] if "leftover" in z else np.empty(0)
        saved_fp = (str(z["fingerprint"]) if "fingerprint" in z
                    else None)
    if fingerprint is not None and saved_fp is not None \
            and fingerprint != saved_fp:
        raise ValueError(
            f"checkpoint was written by a different program "
            f"(fingerprint {saved_fp} != {fingerprint}); refusing to "
            f"load it even though the state layout matches")
    template_leaves, treedef = jax.tree.flatten(like)
    if len(template_leaves) != n:
        raise ValueError(
            f"checkpoint has {n} state leaves but the pipeline has "
            f"{len(template_leaves)} — wrong program for this checkpoint")
    for i, (a, b) in enumerate(zip(leaves, template_leaves)):
        b = np.asarray(b)
        if np.shape(a) != b.shape:
            raise ValueError(
                f"state leaf {i} shape {np.shape(a)} does not match the "
                f"pipeline's {b.shape} — wrong program for this "
                f"checkpoint")
        if np.asarray(a).dtype != b.dtype:
            raise ValueError(
                f"state leaf {i} dtype {np.asarray(a).dtype} does not "
                f"match the pipeline's {b.dtype} — wrong program for "
                f"this checkpoint")
    return {"stages": jax.tree.unflatten(treedef, leaves),
            "leftover": leftover}
