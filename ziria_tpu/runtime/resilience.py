"""Fault-tolerant dispatch runtime: guarded dispatch with watchdog +
retry/backoff, transient-vs-fatal classification, and stream-carry
checkpoint/restore (docs/robustness.md).

The streaming hot path (PR 5/11) keeps the steady state on the device
with the host at data-dependent control points — Ziria's placement
discipline. Those control points are also the *containment* points:
when a compiled dispatch fails, the host is the only layer that can
classify the failure, retry it, or swap in a degraded twin without
poisoning the rest of the fleet. This module is that layer:

- :func:`guarded` wraps a compiled-program call site. Each attempt
  runs inside ``dispatch.timed(label)`` (so per-attempt latency keeps
  feeding the telemetry histograms and the jaxlint R3 contract —
  instrumented sites stay inside ``timed()``), behind the chaos seam
  (``faults.maybe_fail``) and, when a watchdog timeout is set, on a
  watchdog thread whose abandonment contains a *hung* dispatch.
  Transient failures retry with exponential backoff and
  **deterministic jitter** (hashed from (label, seed, attempt) — a
  chaos replay backs off identically); fatal failures (and exhausted
  retries) raise :class:`DispatchFailed` — or return ``fallback()``
  when the caller has a degraded twin (the fused link's staged oracle,
  the streaming decode's per-capture path).
- :func:`classify_error` is the transient/fatal split: retry only
  what may heal. Retryable = injected transients, watchdog timeouts,
  and runtime errors carrying a retryable status marker
  (``UNAVAILABLE``, ``RESOURCE_EXHAUSTED``, ...); everything else —
  including an ``XlaRuntimeError`` with ``INVALID_ARGUMENT`` — is
  fatal (recompiling the same wrong program cannot help).
- :func:`checkpoint_carry` / :func:`restore_carry` serialize a
  streaming receiver's :class:`~ziria_tpu.backend.framebatch.StreamCarry`
  (tail samples, offset, emitted count, dedupe watermark — plus the
  live dedupe set and a geometry fingerprint) so a crashed or
  restarted receiver resumes mid-stream with bit-identical subsequent
  emissions — into a lone ``StreamReceiver(checkpoint=...)``, or
  into a fleet lane via ``MultiStreamReceiver.restore_stream(i,
  blob)`` (the serving runtime's eviction-recovery path,
  docs/serving.md: ``ServeRuntime.evict`` checkpoints a session out,
  ``connect(sid, checkpoint=blob)`` restores it into whatever lane
  frees next).

Telemetry rides throughout (free when idle): ``resilience.retries`` /
``resilience.recovered`` / ``resilience.fallbacks`` /
``resilience.fatal`` counters, a ``resilience.backoff_seconds``
histogram, and the receivers' ``rx.degraded_mode`` /
``rx.quarantined_streams`` gauges — all visible in ``trace_report``.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import threading
import time
import zlib
from collections import Counter
from typing import Any, Callable, NamedTuple, Optional, Tuple

import numpy as np

from ziria_tpu.utils import dispatch, faults, telemetry

#: status markers that mean "the failure may heal on retry" — the
#: retryable gRPC/absl status families an XlaRuntimeError-shaped
#: message leads with, plus transport flaps seen through the tunnel
TRANSIENT_MARKERS = ("UNAVAILABLE", "RESOURCE_EXHAUSTED",
                     "DEADLINE_EXCEEDED", "ABORTED", "CANCELLED",
                     "connection reset", "socket closed")


class DispatchTimeout(TimeoutError):
    """A guarded dispatch exceeded its watchdog timeout. Transient by
    classification: a hung tunnel often heals, and the watchdog thread
    holding the hung call is abandoned (daemon), never joined."""


class DispatchFailed(RuntimeError):
    """A guarded dispatch failed past its retry budget (or fatally).
    Carries the site label, attempts spent, the classification, and
    the last underlying error (also the ``__cause__``)."""

    def __init__(self, label: str, attempts: int, kind: str,
                 last: BaseException):
        super().__init__(
            f"guarded dispatch '{label}' failed ({kind}) after "
            f"{attempts} attempt(s): {type(last).__name__}: {last}")
        self.label = label
        self.attempts = attempts
        self.kind = kind
        self.last = last


class FaultPolicy(NamedTuple):
    """The retry/backoff/watchdog policy of a guarded site.
    ``max_retries`` transient retries follow the first attempt;
    backoff for attempt ``a`` is ``min(base * 2**a, max) * (0.5 +
    0.5 * u)`` with ``u`` the deterministic unit hash of
    (label, seed, a). ``timeout_s = None`` disables the watchdog
    thread (the production default — zero thread overhead); a value
    bounds every attempt and converts a hang into a retryable
    :class:`DispatchTimeout`."""
    max_retries: int = 2
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    timeout_s: Optional[float] = None
    seed: int = 0


def env_max_retries() -> Optional[int]:
    """The ONE reading of the ``ZIRIA_MAX_RETRIES`` knob (the CLI's
    ``--max-retries`` writes it via the scoped-env pattern): the
    transient retry budget of every guarded dispatch site."""
    import os

    v = os.environ.get("ZIRIA_MAX_RETRIES")
    if v is None or v == "":
        return None
    return int(v)


def default_policy(max_retries: Optional[int] = None,
                   timeout_s: Optional[float] = None,
                   seed: int = 0) -> FaultPolicy:
    """The resolved site policy: an explicit ``max_retries`` wins,
    else ``ZIRIA_MAX_RETRIES``, else the 2-retry default."""
    if max_retries is None:
        max_retries = env_max_retries()
    if max_retries is None:
        max_retries = FaultPolicy._field_defaults["max_retries"]
    if max_retries < 0:
        raise ValueError(f"max_retries {max_retries} must be >= 0")
    return FaultPolicy(max_retries=int(max_retries),
                       timeout_s=timeout_s, seed=seed)


def classify_error(e: BaseException) -> str:
    """``"transient"`` (retry may heal it) or ``"fatal"`` (it will
    not). Injected faults classify by their class; timeouts are
    transient (the watchdog cut a hang); runtime errors classify by
    the retryable status markers their message leads with —
    an ``XlaRuntimeError`` saying ``INVALID_ARGUMENT`` is fatal, one
    saying ``UNAVAILABLE`` is not."""
    if isinstance(e, faults.InjectedFatalError):
        return "fatal"
    if isinstance(e, (faults.InjectedTransientError, TimeoutError)):
        return "transient"
    msg = str(e)
    if any(m in msg for m in TRANSIENT_MARKERS):
        return "transient"
    return "fatal"


def backoff_delay(label: str, attempt: int,
                  policy: FaultPolicy) -> float:
    """Attempt ``attempt``'s backoff: exponential with deterministic
    jitter in [0.5, 1.0) of the exponential value — hashed, never
    drawn, so a chaos replay waits the identical schedule."""
    base = min(policy.backoff_base_s * (2 ** attempt),
               policy.backoff_max_s)
    h = hashlib.sha256(
        f"{label}\x00{policy.seed}\x00{attempt}".encode()).digest()
    u = int.from_bytes(h[:8], "big") / float(1 << 64)
    return base * (0.5 + 0.5 * u)


# process-wide counter totals: telemetry counters are per-registry,
# but the trace counter tracks want cumulative levels
_COUNTS: Counter = Counter()
_CLOCK = threading.Lock()


def _count(name: str, n: int = 1) -> None:
    if not telemetry.active():
        return
    with _CLOCK:
        _COUNTS[name] += n
        tot = _COUNTS[name]
    telemetry.count(name, n, total=tot)


def _call_with_watchdog(label: str, call: Callable[[], Any],
                        timeout_s: float) -> Any:
    """Run ``call`` on a watchdog thread; on timeout abandon the
    thread (daemon — a genuinely hung dispatch never blocks the
    caller again) and raise :class:`DispatchTimeout`. The abandoned
    runner checks the flag after the chaos seam so an injected hang
    never fires a stray late dispatch on wake."""
    box: dict = {}
    done = threading.Event()
    abandoned = threading.Event()

    def run():
        try:
            box["out"] = call(abandoned)
        except BaseException as e:   # noqa: BLE001 - relayed below
            box["exc"] = e
        finally:
            done.set()

    t = threading.Thread(target=run, daemon=True,
                         name=f"ziria-watchdog-{label}")
    t.start()
    if not done.wait(timeout_s):
        abandoned.set()
        raise DispatchTimeout(
            f"DEADLINE_EXCEEDED: dispatch '{label}' exceeded its "
            f"{timeout_s}s watchdog")
    if "exc" in box:
        raise box["exc"]
    return box.get("out")


def guarded(label: str, fn: Callable, *args,
            policy: Optional[FaultPolicy] = None,
            fallback: Optional[Callable[[], Any]] = None,
            _sleep: Callable[[float], None] = time.sleep) -> Any:
    """Fire ``fn(*args)`` as a guarded dispatch at site ``label``.

    Every attempt runs inside ``dispatch.timed(label)`` (the
    per-attempt latency lands in the site's telemetry histogram, and
    retries count as the extra dispatches they are) behind the chaos
    seam (``faults.maybe_fail(label)``). Transient failures retry up
    to ``policy.max_retries`` times with deterministic-jitter
    exponential backoff; a fatal failure (or exhaustion) returns
    ``fallback()`` when given — the degraded-twin hook — else raises
    :class:`DispatchFailed` with the last error chained."""
    policy = policy if policy is not None else default_policy()
    last: Optional[BaseException] = None
    kind = "fatal"
    attempt = 0
    for attempt in range(policy.max_retries + 1):
        try:
            with dispatch.timed(label):
                if policy.timeout_s is not None:
                    def call(abandoned):
                        faults.maybe_fail(label)
                        if abandoned.is_set():
                            return None   # hang cut: no stray dispatch
                        return fn(*args)
                    out = _call_with_watchdog(label, call,
                                              policy.timeout_s)
                else:
                    faults.maybe_fail(label)
                    out = fn(*args)
            if attempt:
                _count("resilience.recovered")
            return out
        except Exception as e:    # noqa: BLE001 - classified below
            last = e
            kind = classify_error(e)
            if kind == "transient" and attempt < policy.max_retries:
                d = backoff_delay(label, attempt, policy)
                _count("resilience.retries")
                telemetry.observe("resilience.backoff_seconds", d)
                _sleep(d)
                continue
            break
    _count("resilience.fatal")
    if fallback is not None:
        _count("resilience.fallbacks")
        return fallback()
    raise DispatchFailed(label, attempt + 1, kind, last) from last


# ------------------------------------------------ carry checkpoint/restore

#: checkpoint container format tag (bump on incompatible layout change)
CARRY_FORMAT = "ziria-stream-carry-v1"


class CarryCheckpointError(ValueError):
    """A checkpoint blob failed validation (wrong format tag, missing
    field, geometry mismatch surfaced by the restoring receiver)."""


class CarryState(NamedTuple):
    """A deserialized stream checkpoint: the :class:`StreamCarry`
    fields plus the live dedupe set, the geometry fingerprint the
    restoring receiver must match, and the receiver's runtime state
    (quarantine health, degraded flags, counters) — without which a
    quarantined receiver would restore un-quarantined and diverge
    from the uninterrupted run."""
    tail: np.ndarray          # (n, 2) float32 not-yet-owned samples
    offset: int               # stream coordinate of tail[0]
    emitted: int              # frames emitted so far
    watermark: int            # dedupe prune bound
    seen: frozenset           # live dedupe starts (>= watermark)
    geometry: dict            # receiver geometry fingerprint
    state: dict               # health/degraded runtime state


def _carry_crc(tail: np.ndarray, scalars: np.ndarray,
               seen: np.ndarray, geo: bytes, state: bytes) -> int:
    """CRC32 over the checkpoint's canonical payload bytes — the
    integrity field a torn or bit-rotted blob fails against at
    restore time (docs/robustness.md durability section)."""
    c = zlib.crc32(tail.tobytes())
    c = zlib.crc32(scalars.tobytes(), c)
    c = zlib.crc32(seen.tobytes(), c)
    c = zlib.crc32(geo, c)
    return zlib.crc32(state, c) & 0xFFFFFFFF


def checkpoint_carry(carry, seen=(), geometry: Optional[dict] = None,
                     state: Optional[dict] = None) -> bytes:
    """Serialize a stream carry (anything with ``tail`` / ``offset`` /
    ``emitted`` / ``watermark`` fields — ``StreamReceiver.carry``)
    plus the dedupe set, a geometry fingerprint, and the receiver's
    runtime ``state`` dict into a compact npz-container blob with a
    CRC32 integrity field over the payload (a torn write fails
    loudly at restore; pre-integrity blobs still load, counted on
    ``resilience.checkpoint_legacy``). ``StreamReceiver.checkpoint()``
    and ``MultiStreamReceiver.checkpoint(i)`` are the receiver-level
    wrappers (they drain the in-flight chunk first, so the blob never
    silently drops a launched chunk's frames, and they fill ``state``
    so quarantine/degraded status survives the restart)."""
    tail = np.asarray(carry.tail, np.float32).reshape(-1, 2)
    scalars = np.asarray([int(carry.offset), int(carry.emitted),
                          int(carry.watermark)], np.int64)
    seen_a = np.asarray(sorted(int(s) for s in seen), np.int64)
    geo = json.dumps(geometry or {}, sort_keys=True).encode()
    state_b = json.dumps(state or {}, sort_keys=True).encode()
    buf = io.BytesIO()
    np.savez(
        buf,
        fmt=np.frombuffer(CARRY_FORMAT.encode(), np.uint8),
        tail=tail,
        scalars=scalars,
        seen=seen_a,
        geometry=np.frombuffer(geo, np.uint8),
        state=np.frombuffer(state_b, np.uint8),
        crc=np.asarray(
            [_carry_crc(tail, scalars, seen_a, geo, state_b)],
            np.uint32))
    return buf.getvalue()


def restore_carry(data: bytes) -> CarryState:
    """Deserialize a :func:`checkpoint_carry` blob. Raises
    :class:`CarryCheckpointError` on a malformed or wrong-format blob
    — a truncated file must fail loudly, never resume at garbage
    state."""
    try:
        z = np.load(io.BytesIO(bytes(data)), allow_pickle=False)
        fmt = bytes(z["fmt"]).decode()
        if fmt != CARRY_FORMAT:
            raise CarryCheckpointError(
                f"checkpoint format {fmt!r} != {CARRY_FORMAT!r}")
        tail = np.asarray(z["tail"], np.float32).reshape(-1, 2)
        scalars = np.asarray(z["scalars"], np.int64)
        off, emitted, watermark = (int(v) for v in scalars)
        seen_a = np.asarray(z["seen"], np.int64)
        seen = frozenset(int(s) for s in seen_a)
        geo_b = bytes(z["geometry"])
        geometry = json.loads(geo_b.decode() or "{}")
        state_b = bytes(z["state"]) if "state" in z.files else b"{}"
        state = json.loads(state_b.decode() or "{}")
        if "crc" in z.files:
            want = int(np.asarray(z["crc"], np.uint32)[0])
            got = _carry_crc(tail, scalars, seen_a, geo_b, state_b)
            if got != want:
                raise CarryCheckpointError(
                    f"checkpoint integrity failure: payload CRC32 "
                    f"{got:#010x} != recorded {want:#010x} (torn or "
                    f"corrupted blob)")
        else:
            # pre-integrity blob (ISSUE 14 satellite): still loads —
            # format tag unchanged — but the gap is counted so a fleet
            # quietly running CRC-less checkpoints is visible
            telemetry.count("resilience.checkpoint_legacy")
    except CarryCheckpointError:
        raise
    except Exception as e:
        raise CarryCheckpointError(
            f"unreadable stream checkpoint: {type(e).__name__}: {e}"
        ) from e
    return CarryState(tail, off, emitted, watermark, seen, geometry,
                      state)


def save_checkpoint(path: str, blob: bytes,
                    io_site: str = "checkpoint.write") -> None:
    """Write a checkpoint blob to ``path`` ATOMICALLY — tmp + fsync +
    rename (ISSUE 14 satellite: the direct write left a torn file on
    a crash mid-write, which `restore_carry` then reported as
    garbage). A reader never observes a partial file: it sees the old
    content or the new, nothing between. The payload passes the
    durability chaos seam (``faults.io_fault``) so soak campaigns can
    inject torn/ENOSPC writes here; a torn injected payload still
    lands atomically and fails loudly at restore via the CRC field."""
    from ziria_tpu.runtime.durability import _fsync_dir

    data = faults.io_fault(io_site, bytes(blob))
    d = os.path.dirname(os.path.abspath(path)) or "."
    tmp = os.path.join(
        d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(d)


def load_checkpoint(path: str) -> CarryState:
    """Read + validate a checkpoint file written by
    :func:`save_checkpoint` (or any `checkpoint_carry` blob on disk).
    Raises :class:`CarryCheckpointError` on torn/corrupt content —
    the CRC integrity field catches what atomicity cannot (bit rot,
    an injected torn payload)."""
    with open(path, "rb") as f:
        return restore_carry(f.read())


