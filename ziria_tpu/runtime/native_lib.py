"""ctypes loader for the native runtime library.

The reference's runtime is C (`csrc/` — SURVEY.md §2.2); this module
holds the framework's native CPU components: currently the K=7 Viterbi
decoder (SORA-brick analogue), used as the honest C baseline in
bench.py and as a host-side fallback decoder. Builds on demand with
``make`` (gcc); everything degrades gracefully to the numpy/jax paths
if no toolchain is present.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_DIR = os.path.join(os.path.dirname(__file__), "native")
_SO = os.path.join(_DIR, "libziria_native.so")

_lib: Optional[ctypes.CDLL] = None
_failed = False

# every symbol the bindings below touch; a stale .so missing any of them
# (built before a source was added, rebuild failing) means the library is
# unusable and callers must take their numpy fallbacks
_REQUIRED_SYMS = (
    "ziria_viterbi_decode", "ziria_pack_bits", "ziria_unpack_bits",
    "ziria_parse_dbg_bits", "ziria_format_dbg_bits",
    "ziria_parse_dbg_ints", "ziria_format_dbg_ints",
)


def load(build: bool = True) -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable.
    A failed build attempt is cached so stream I/O doesn't re-spawn make
    on every call."""
    global _lib, _failed
    if _lib is not None or _failed:
        return _lib
    if build:
        # always delegate to make: it no-ops when the .so is newer than
        # the sources and rebuilds after edits (the .so is built with
        # -march=native, so it must never ship prebuilt — .gitignore'd)
        try:
            subprocess.run(["make", "-C", _DIR], check=True,
                           capture_output=True)
        except (OSError, subprocess.CalledProcessError):
            pass
    if not os.path.exists(_SO):
        _failed = _failed or build
        return None
    lib = ctypes.CDLL(_SO)
    if not all(hasattr(lib, s) for s in _REQUIRED_SYMS):
        _failed = _failed or build   # stale .so and rebuild didn't fix it
        return None
    lib.ziria_viterbi_decode.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8)]
    lib.ziria_viterbi_decode.restype = ctypes.c_int
    u8p, i64p = ctypes.POINTER(ctypes.c_uint8), \
        ctypes.POINTER(ctypes.c_int64)
    lib.ziria_pack_bits.argtypes = [u8p, ctypes.c_int64, u8p]
    lib.ziria_unpack_bits.argtypes = [u8p, ctypes.c_int64, u8p]
    lib.ziria_parse_dbg_bits.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                         u8p]
    lib.ziria_parse_dbg_bits.restype = ctypes.c_int64
    lib.ziria_format_dbg_bits.argtypes = [u8p, ctypes.c_int64,
                                          ctypes.c_char_p]
    lib.ziria_parse_dbg_ints.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                         i64p]
    lib.ziria_parse_dbg_ints.restype = ctypes.c_int64
    lib.ziria_format_dbg_ints.argtypes = [i64p, ctypes.c_int64,
                                          ctypes.c_char_p]
    lib.ziria_format_dbg_ints.restype = ctypes.c_int64
    _lib = lib
    return _lib


def viterbi_decode_native(llrs: np.ndarray) -> np.ndarray:
    """Native C Viterbi: llrs (T,2) or (2T,) float32 -> (T,) uint8 bits.
    Raises RuntimeError if the library is unavailable."""
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable (no gcc/make?)")
    llrs = np.ascontiguousarray(np.asarray(llrs, np.float32).reshape(-1, 2))
    T = llrs.shape[0]
    out = np.zeros(T, np.uint8)
    rc = lib.ziria_viterbi_decode(
        llrs.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_int64(T),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    if rc != 0:
        raise RuntimeError(f"native viterbi failed rc={rc}")
    return out


# --------------------------------------------------------------------------
# Stream buffer helpers (buf.c): dbg parse/format + bit pack/unpack.
# Each returns None when the native library is unavailable, so callers
# (runtime/buffers.py) keep their numpy fallback.
# --------------------------------------------------------------------------


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def parse_dbg_bits_native(text: str) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    raw = text.encode("ascii", "replace")
    out = np.empty(len(raw), np.uint8)
    n = lib.ziria_parse_dbg_bits(raw, len(raw), _u8p(out))
    return out[:n].copy()


def format_dbg_bits_native(bits: np.ndarray) -> Optional[str]:
    lib = load()
    if lib is None:
        return None
    bits = np.ascontiguousarray(np.asarray(bits, np.uint8).ravel())
    buf = ctypes.create_string_buffer(bits.size + 1)
    lib.ziria_format_dbg_bits(_u8p(bits), bits.size, buf)
    return buf.value.decode("ascii")


def parse_dbg_ints_native(text: str) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    raw = text.encode("ascii", "replace")
    out = np.empty(len(raw) // 2 + 2, np.int64)
    n = lib.ziria_parse_dbg_ints(
        raw, len(raw), out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if n < 0:
        raise ValueError("malformed dbg integer stream")
    return out[:n].copy()


def format_dbg_ints_native(vals: np.ndarray) -> Optional[str]:
    lib = load()
    if lib is None:
        return None
    vals = np.ascontiguousarray(np.asarray(vals, np.int64).ravel())
    buf = ctypes.create_string_buffer(int(vals.size) * 21 + 1)
    n = lib.ziria_format_dbg_ints(
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        vals.size, buf)
    return buf.raw[:n].decode("ascii")


def pack_bits_native(bits: np.ndarray) -> Optional[bytes]:
    lib = load()
    if lib is None:
        return None
    bits = np.ascontiguousarray(np.asarray(bits, np.uint8).ravel())
    out = np.zeros((bits.size + 7) // 8, np.uint8)
    lib.ziria_pack_bits(_u8p(bits), bits.size, _u8p(out))
    return out.tobytes()


def unpack_bits_native(data: bytes) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    src = np.frombuffer(data, np.uint8)
    out = np.empty(src.size * 8, np.uint8)
    lib.ziria_unpack_bits(_u8p(np.ascontiguousarray(src)), src.size,
                          _u8p(out))
    return out
