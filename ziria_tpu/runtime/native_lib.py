"""ctypes loader for the native runtime library.

The reference's runtime is C (`csrc/` — SURVEY.md §2.2); this module
holds the framework's native CPU components: currently the K=7 Viterbi
decoder (SORA-brick analogue), used as the honest C baseline in
bench.py and as a host-side fallback decoder. Builds on demand with
``make`` (gcc); everything degrades gracefully to the numpy/jax paths
if no toolchain is present.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_DIR = os.path.join(os.path.dirname(__file__), "native")
_SO = os.path.join(_DIR, "libziria_native.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def load(build: bool = True) -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    if _lib is not None or (_tried and not build):
        return _lib
    _tried = True
    if build:
        # always delegate to make: it no-ops when the .so is newer than
        # the sources and rebuilds after edits (the .so is built with
        # -march=native, so it must never ship prebuilt — .gitignore'd)
        try:
            subprocess.run(["make", "-C", _DIR], check=True,
                           capture_output=True)
        except (OSError, subprocess.CalledProcessError):
            pass
    if not os.path.exists(_SO):
        return None
    lib = ctypes.CDLL(_SO)
    lib.ziria_viterbi_decode.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8)]
    lib.ziria_viterbi_decode.restype = ctypes.c_int
    _lib = lib
    return _lib


def viterbi_decode_native(llrs: np.ndarray) -> np.ndarray:
    """Native C Viterbi: llrs (T,2) or (2T,) float32 -> (T,) uint8 bits.
    Raises RuntimeError if the library is unavailable."""
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable (no gcc/make?)")
    llrs = np.ascontiguousarray(np.asarray(llrs, np.float32).reshape(-1, 2))
    T = llrs.shape[0]
    out = np.zeros(T, np.uint8)
    rc = lib.ziria_viterbi_decode(
        llrs.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_int64(T),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    if rc != 0:
        raise RuntimeError(f"native viterbi failed rc={rc}")
    return out
