"""Typed stream I/O: the reference's buf_* family, host-side.

Counterpart of `csrc/buf_bit.c` / `buf_numerics{8,16,32}.c` (SURVEY.md
§2.2): typed get/put of stream items in the reference's two file modes —
``dbg`` (human-readable comma-separated text) and ``bin`` (raw
little-endian) — plus ``dummy`` (discard / zeros) and ``memory``
(in-process arrays). Bit streams pack 8 bits per byte in bin mode
(LSB-first, padded up to a byte boundary — there is no length header,
same as the reference), one '0'/'1' character per item in dbg mode.

TPU-first difference: there is no per-item get/put hot path — the whole
stream is materialized as one numpy array at the host boundary and
shipped to the device in bulk (the device-side analogue of the
reference's buffers is the chunked scan in backend/execute.py).

Item types:

  bit        uint8 0/1 items        (packed in bin mode)
  int8/int16/int32                  little-endian in bin mode
  complex16  (2,) int16 re,im pairs (interleaved in both modes)
  complex32  (2,) int32 re,im pairs
  float32/float64                   '%g' text in dbg mode
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

_SCALAR_DTYPES = {
    "bit": np.uint8,
    "int8": np.int8,
    "int16": np.int16,
    "int32": np.int32,
    "float32": np.float32,
    "float64": np.float64,
}
_PAIR_DTYPES = {"complex16": np.int16, "complex32": np.int32}
ITEM_TYPES = tuple(_SCALAR_DTYPES) + tuple(_PAIR_DTYPES)


def _check_ty(ty: str) -> None:
    if ty not in ITEM_TYPES:
        raise ValueError(f"unknown item type {ty!r}; one of {ITEM_TYPES}")


def item_shape(ty: str) -> tuple:
    """Trailing (non-stream) shape of one item of type `ty`."""
    _check_ty(ty)
    return (2,) if ty in _PAIR_DTYPES else ()


# --------------------------------------------------------------------------
# dbg (text) mode
# --------------------------------------------------------------------------


def _parse_dbg(text: str, ty: str) -> np.ndarray:
    from ziria_tpu.runtime import native_lib
    if ty == "bit":
        bits = native_lib.parse_dbg_bits_native(text)
        if bits is not None:
            return bits
        vals = [c for c in text if c in "01"]
        return np.array([int(c) for c in vals], np.uint8)
    base = _SCALAR_DTYPES.get(ty) or _PAIR_DTYPES[ty]
    if np.issubdtype(base, np.integer):
        flat64 = native_lib.parse_dbg_ints_native(text)
        if flat64 is not None:
            flat = flat64.astype(base)
            if ty in _PAIR_DTYPES:
                if flat.size % 2:
                    raise ValueError(
                        f"dbg {ty} stream has odd value count {flat.size} "
                        f"(items are re,im pairs)")
                return flat.reshape(-1, 2)
            return flat
    toks = text.replace(",", " ").split()
    if np.issubdtype(base, np.floating):
        flat = np.array([float(t) for t in toks], base)
    else:
        flat = np.array([int(t) for t in toks], base)
    if ty in _PAIR_DTYPES:
        if flat.size % 2:
            raise ValueError(
                f"dbg {ty} stream has odd value count {flat.size} "
                f"(items are re,im pairs)")
        return flat.reshape(-1, 2)
    return flat


def _format_dbg(arr: np.ndarray, ty: str) -> str:
    from ziria_tpu.runtime import native_lib
    if ty == "bit":
        s = native_lib.format_dbg_bits_native(arr.ravel())
        if s is not None:
            return s
        return "".join("1" if v else "0" for v in arr.ravel())
    flat = arr.ravel()
    if ty in ("float32", "float64"):
        # repr-faithful digits so dbg text round-trips exactly
        prec = ".9g" if flat.dtype == np.float32 else ".17g"
        return ",".join(f"{float(v):{prec}}" for v in flat)
    # integer item type: round float pipeline outputs, don't truncate
    if np.issubdtype(flat.dtype, np.floating):
        flat = np.rint(flat)
    s = native_lib.format_dbg_ints_native(flat.astype(np.int64))
    if s is not None:
        return s
    return ",".join(str(int(round(float(v)))) for v in flat)


# --------------------------------------------------------------------------
# bin mode
# --------------------------------------------------------------------------


def _parse_bin(data: bytes, ty: str) -> np.ndarray:
    if ty == "bit":
        from ziria_tpu.runtime import native_lib
        bits = native_lib.unpack_bits_native(data)
        if bits is not None:
            return bits
        packed = np.frombuffer(data, np.uint8)
        return np.unpackbits(packed, bitorder="little")
    base = _SCALAR_DTYPES.get(ty) or _PAIR_DTYPES[ty]
    flat = np.frombuffer(data, np.dtype(base).newbyteorder("<"))
    flat = flat.astype(base)
    if ty in _PAIR_DTYPES:
        return flat.reshape(-1, 2)
    return flat


def _format_bin(arr: np.ndarray, ty: str) -> bytes:
    if ty == "bit":
        from ziria_tpu.runtime import native_lib
        bits = np.asarray(arr, np.uint8).ravel()
        packed = native_lib.pack_bits_native(bits)
        if packed is not None:
            return packed
        return np.packbits(bits, bitorder="little").tobytes()
    base = _SCALAR_DTYPES.get(ty) or _PAIR_DTYPES[ty]
    a = np.asarray(arr)
    if (np.issubdtype(a.dtype, np.floating)
            and np.issubdtype(np.dtype(base), np.integer)):
        a = np.rint(a)  # round float pipeline outputs, don't truncate
    return np.asarray(a, base).astype(
        np.dtype(base).newbyteorder("<")).tobytes()


# --------------------------------------------------------------------------
# Spec + top-level read/write
# --------------------------------------------------------------------------


@dataclass
class StreamSpec:
    """One side of the driver's I/O, in reference params style:
    --input=file --input-file-name=... --input-file-mode=dbg|bin."""

    kind: str = "file"          # file | dummy | memory
    ty: str = "int32"
    path: Optional[str] = None
    mode: str = "dbg"           # dbg | bin
    data: Optional[np.ndarray] = None   # memory kind
    dummy_items: int = 0        # dummy input length

    def __post_init__(self):
        _check_ty(self.ty)
        if self.kind not in ("file", "dummy", "memory"):
            raise ValueError(f"unknown stream kind {self.kind!r}")
        if self.mode not in ("dbg", "bin"):
            raise ValueError(f"unknown file mode {self.mode!r}")
        if self.kind == "file" and not self.path:
            raise ValueError("file stream needs a path")


def read_stream(spec: StreamSpec) -> np.ndarray:
    """Read the whole input stream as (items, *item_shape)."""
    if spec.kind == "memory":
        if spec.data is None:
            raise ValueError("memory input spec has no data")
        return np.asarray(spec.data)
    if spec.kind == "dummy":
        return np.zeros((spec.dummy_items,) + item_shape(spec.ty),
                        _SCALAR_DTYPES.get(spec.ty)
                        or _PAIR_DTYPES[spec.ty])
    if spec.mode == "dbg":
        with open(spec.path, "r") as fh:
            return _parse_dbg(fh.read(), spec.ty)
    with open(spec.path, "rb") as fh:
        return _parse_bin(fh.read(), spec.ty)


def write_stream(spec: StreamSpec, arr: np.ndarray) -> Optional[np.ndarray]:
    """Write the whole output stream; returns the array for kind=memory."""
    arr = np.asarray(arr)
    if spec.kind == "dummy":
        return None
    if spec.kind == "memory":
        return arr
    if spec.mode == "dbg":
        with open(spec.path, "w") as fh:
            fh.write(_format_dbg(arr, spec.ty))
    else:
        with open(spec.path, "wb") as fh:
            fh.write(_format_bin(arr, spec.ty))
    return None
