/* Soft-decision Viterbi decoder, K=7 (g0=133o, g1=171o), 64 states.
 *
 * Native CPU baseline implementation — the role the SORA SSE Viterbi
 * brick plays in the reference system (SURVEY.md §2.2): a SIMD-parallel
 * C decoder the accelerator path is benchmarked against, and the
 * host-side decoder for the runtime. Loaded via ctypes
 * (ziria_tpu/runtime/native_lib.py).
 *
 * Two ACS paths, REQUIRED to be bit-exact with each other (same
 * operation order — mul then add, no FMA contraction; same tie-break
 * d = (c1 > c0); same per-step renormalisation):
 *
 * - AVX2 (the default on this box): the 64-state ACS runs as 8 float
 *   vectors per trellis step. Butterfly layout: children t and t+32
 *   share predecessor pair (2(t&31), 2(t&31)+1), so the predecessor
 *   metrics are one even/odd deinterleave of the metric array and the
 *   branch metrics are contiguous loads of per-child constant tables.
 *   Decisions pack to one uint64 per step (movemask), which also cuts
 *   traceback memory 8x vs byte-per-state. This is the same
 *   within-frame SIMD parallelisation strategy as SORA's SSE brick.
 * - Portable scalar fallback (non-AVX2 builds).
 *
 * State convention matches ziria_tpu/ops/viterbi.py: state = the 6 most
 * recent input bits, newest in bit 5; edge into state t consumes input
 * bit t>>5 from predecessor ((t&31)<<1)|d.
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#define N_STATES 64
#define NEG_INF (-1e30f)

static int g_init = 0;
static int pred[N_STATES][2];
/* branch output tables in child-state order: out_x[d][t] */
static float out_a0[N_STATES] __attribute__((aligned(32)));
static float out_b0[N_STATES] __attribute__((aligned(32)));
static float out_a1[N_STATES] __attribute__((aligned(32)));
static float out_b1[N_STATES] __attribute__((aligned(32)));

static const int G0[7] = {1, 0, 1, 1, 0, 1, 1}; /* 133 octal */
static const int G1[7] = {1, 1, 1, 1, 0, 0, 1}; /* 171 octal */

static void init_tables(void) {
    if (g_init) return;
    for (int t = 0; t < N_STATES; t++) {
        int b = t >> 5;
        for (int d = 0; d < 2; d++) {
            int s = ((t & 31) << 1) | d;
            pred[t][d] = s;
            int w[7];
            w[0] = b;
            for (int i = 0; i < 6; i++) w[i + 1] = (s >> (5 - i)) & 1;
            int a = 0, bb = 0;
            for (int i = 0; i < 7; i++) {
                a ^= G0[i] & w[i];
                bb ^= G1[i] & w[i];
            }
            if (d == 0) {
                out_a0[t] = 2.0f * a - 1.0f;
                out_b0[t] = 2.0f * bb - 1.0f;
            } else {
                out_a1[t] = 2.0f * a - 1.0f;
                out_b1[t] = 2.0f * bb - 1.0f;
            }
        }
    }
    g_init = 1;
}

#if defined(__AVX2__)
#include <immintrin.h>

/* m[2j] / m[2j+1] for one block of 8 consecutive j from m[16..]:
 * v0 = m[base..base+7], v1 = m[base+8..base+15]. */
static inline __m256 deint_even(__m256 v0, __m256 v1) {
    __m256 s = _mm256_shuffle_ps(v0, v1, _MM_SHUFFLE(2, 0, 2, 0));
    return _mm256_permutevar8x32_ps(
        s, _mm256_setr_epi32(0, 1, 4, 5, 2, 3, 6, 7));
}

static inline __m256 deint_odd(__m256 v0, __m256 v1) {
    __m256 s = _mm256_shuffle_ps(v0, v1, _MM_SHUFFLE(3, 1, 3, 1));
    return _mm256_permutevar8x32_ps(
        s, _mm256_setr_epi32(0, 1, 4, 5, 2, 3, 6, 7));
}

static int decode_avx2(const float *llrs, int64_t T, uint8_t *out) {
    uint64_t *dec = (uint64_t *)malloc((size_t)T * sizeof(uint64_t));
    if (!dec) return -1;

    float m[N_STATES] __attribute__((aligned(32)));
    float nm[N_STATES] __attribute__((aligned(32)));
    for (int s = 0; s < N_STATES; s++) m[s] = NEG_INF;
    m[0] = 0.0f;

    for (int64_t k = 0; k < T; k++) {
        const __m256 la = _mm256_set1_ps(llrs[2 * k]);
        const __m256 lb = _mm256_set1_ps(llrs[2 * k + 1]);
        uint64_t word = 0;
        __m256 vbest = _mm256_set1_ps(NEG_INF);
        for (int jb = 0; jb < 4; jb++) {
            const int j = 8 * jb;            /* j .. j+7 */
            __m256 v0 = _mm256_load_ps(m + 2 * j);
            __m256 v1 = _mm256_load_ps(m + 2 * j + 8);
            __m256 me = deint_even(v0, v1);  /* m[2j]   */
            __m256 mo = deint_odd(v0, v1);   /* m[2j+1] */
            /* children t = j..j+7 (lower half) and t+32 (upper) */
            for (int half = 0; half < 2; half++) {
                const int t = j + 32 * half;
                /* scalar order: (m + a*la) + b*lb — mul then adds */
                __m256 c0 = _mm256_add_ps(
                    _mm256_add_ps(
                        me, _mm256_mul_ps(_mm256_load_ps(out_a0 + t),
                                          la)),
                    _mm256_mul_ps(_mm256_load_ps(out_b0 + t), lb));
                __m256 c1 = _mm256_add_ps(
                    _mm256_add_ps(
                        mo, _mm256_mul_ps(_mm256_load_ps(out_a1 + t),
                                          la)),
                    _mm256_mul_ps(_mm256_load_ps(out_b1 + t), lb));
                __m256 gt = _mm256_cmp_ps(c1, c0, _CMP_GT_OQ);
                __m256 c = _mm256_blendv_ps(c0, c1, gt);
                _mm256_store_ps(nm + t, c);
                vbest = _mm256_max_ps(vbest, c);
                word |= (uint64_t)(uint32_t)_mm256_movemask_ps(gt)
                        << t;
            }
        }
        dec[k] = word;
        /* renormalise exactly like the scalar path: subtract the step
         * maximum from every metric, every step */
        __m128 lo = _mm256_castps256_ps128(vbest);
        __m128 hi = _mm256_extractf128_ps(vbest, 1);
        __m128 mx = _mm_max_ps(lo, hi);
        mx = _mm_max_ps(mx, _mm_movehl_ps(mx, mx));
        mx = _mm_max_ss(mx, _mm_shuffle_ps(mx, mx, 1));
        __m256 vb = _mm256_set1_ps(_mm_cvtss_f32(mx));
        for (int t = 0; t < N_STATES; t += 8)
            _mm256_store_ps(
                m + t, _mm256_sub_ps(_mm256_load_ps(nm + t), vb));
    }

    int state = 0;
    float best = NEG_INF;
    for (int t = 0; t < N_STATES; t++)
        if (m[t] > best) { best = m[t]; state = t; }

    for (int64_t k = T - 1; k >= 0; k--) {
        out[k] = (uint8_t)(state >> 5);
        int d = (int)((dec[k] >> state) & 1u);
        state = pred[state][d];
    }
    free(dec);
    return 0;
}
#endif /* __AVX2__ */

static int decode_scalar(const float *llrs, int64_t T, uint8_t *out) {
    float m[N_STATES], nm[N_STATES];
    uint8_t *dec = (uint8_t *)malloc((size_t)T * N_STATES);
    if (!dec) return -1;
    for (int s = 0; s < N_STATES; s++) m[s] = NEG_INF;
    m[0] = 0.0f;

    for (int64_t k = 0; k < T; k++) {
        const float la = llrs[2 * k], lb = llrs[2 * k + 1];
        float best = NEG_INF;
        uint8_t *dk = dec + k * N_STATES;
        for (int t = 0; t < N_STATES; t++) {
            float c0 = m[pred[t][0]] + out_a0[t] * la + out_b0[t] * lb;
            float c1 = m[pred[t][1]] + out_a1[t] * la + out_b1[t] * lb;
            int d = c1 > c0;
            float c = d ? c1 : c0;
            dk[t] = (uint8_t)d;
            nm[t] = c;
            if (c > best) best = c;
        }
        for (int t = 0; t < N_STATES; t++) m[t] = nm[t] - best;
    }

    int state = 0;
    float best = NEG_INF;
    for (int t = 0; t < N_STATES; t++)
        if (m[t] > best) { best = m[t]; state = t; }

    for (int64_t k = T - 1; k >= 0; k--) {
        out[k] = (uint8_t)(state >> 5);
        state = pred[state][dec[k * N_STATES + state]];
    }
    free(dec);
    return 0;
}

/* llrs: T pairs (A,B); out: T decoded bits. Returns 0 on success. */
int ziria_viterbi_decode(const float *llrs, int64_t T, uint8_t *out) {
    init_tables();
#if defined(__AVX2__)
    return decode_avx2(llrs, T, out);
#else
    return decode_scalar(llrs, T, out);
#endif
}

/* test hook: run the portable path regardless of build ISA, so the
 * SIMD path can be asserted bit-exact against it */
int ziria_viterbi_decode_scalar(const float *llrs, int64_t T,
                                uint8_t *out) {
    init_tables();
    return decode_scalar(llrs, T, out);
}
