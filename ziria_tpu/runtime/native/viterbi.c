/* Soft-decision Viterbi decoder, K=7 (g0=133o, g1=171o), 64 states.
 *
 * Native CPU reference/baseline implementation — the role the SORA SSE
 * Viterbi brick plays in the reference system (SURVEY.md §2.2): a
 * C-speed decoder the accelerator path is benchmarked against, and the
 * host-side fallback decoder for the runtime. Loaded via ctypes
 * (ziria_tpu/runtime/native.py). Plain portable C; the compiler
 * auto-vectorizes the 64-wide ACS inner loops.
 *
 * State convention matches ziria_tpu/ops/viterbi.py: state = the 6 most
 * recent input bits, newest in bit 5; edge into state t consumes input
 * bit t>>5 from predecessor ((t&31)<<1)|d.
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#define N_STATES 64
#define NEG_INF (-1e30f)

static int g_init = 0;
static int pred[N_STATES][2];
static float out_a[N_STATES][2];
static float out_b[N_STATES][2];

static const int G0[7] = {1, 0, 1, 1, 0, 1, 1}; /* 133 octal */
static const int G1[7] = {1, 1, 1, 1, 0, 0, 1}; /* 171 octal */

static void init_tables(void) {
    if (g_init) return;
    for (int t = 0; t < N_STATES; t++) {
        int b = t >> 5;
        for (int d = 0; d < 2; d++) {
            int s = ((t & 31) << 1) | d;
            pred[t][d] = s;
            int w[7];
            w[0] = b;
            for (int i = 0; i < 6; i++) w[i + 1] = (s >> (5 - i)) & 1;
            int a = 0, bb = 0;
            for (int i = 0; i < 7; i++) {
                a ^= G0[i] & w[i];
                bb ^= G1[i] & w[i];
            }
            out_a[t][d] = 2.0f * a - 1.0f;
            out_b[t][d] = 2.0f * bb - 1.0f;
        }
    }
    g_init = 1;
}

/* llrs: T pairs (A,B); out: T decoded bits. Returns 0 on success. */
int ziria_viterbi_decode(const float *llrs, int64_t T, uint8_t *out) {
    init_tables();
    float m[N_STATES], nm[N_STATES];
    uint8_t *dec = (uint8_t *)malloc((size_t)T * N_STATES);
    if (!dec) return -1;
    for (int s = 0; s < N_STATES; s++) m[s] = NEG_INF;
    m[0] = 0.0f;

    for (int64_t k = 0; k < T; k++) {
        const float la = llrs[2 * k], lb = llrs[2 * k + 1];
        float best = NEG_INF;
        uint8_t *dk = dec + k * N_STATES;
        for (int t = 0; t < N_STATES; t++) {
            float c0 = m[pred[t][0]] + out_a[t][0] * la + out_b[t][0] * lb;
            float c1 = m[pred[t][1]] + out_a[t][1] * la + out_b[t][1] * lb;
            int d = c1 > c0;
            float c = d ? c1 : c0;
            dk[t] = (uint8_t)d;
            nm[t] = c;
            if (c > best) best = c;
        }
        for (int t = 0; t < N_STATES; t++) m[t] = nm[t] - best;
    }

    int state = 0;
    float best = NEG_INF;
    for (int t = 0; t < N_STATES; t++)
        if (m[t] > best) { best = m[t]; state = t; }

    for (int64_t k = T - 1; k >= 0; k--) {
        out[k] = (uint8_t)(state >> 5);
        state = pred[state][dec[k * N_STATES + state]];
    }
    free(dec);
    return 0;
}
