/* Typed stream buffer helpers: the reference's buf_*.c / bit.c roles.
 *
 * The reference runtime reads and writes typed streams through C
 * buffer modules (csrc/buf_bit.c, buf_numerics{8,16,32}.c, bit.c —
 * SURVEY.md §2.2): text "dbg" mode and raw "bin" mode, with bit
 * streams packed 8-per-byte. Here the same hot paths — dbg text
 * parse/format and bit pack/unpack — are native C behind ctypes
 * (ziria_tpu/runtime/native_lib.py), used by runtime/buffers.py as the
 * fast path with a numpy fallback. The TPU compute path never touches
 * these; they are host I/O, exactly like the reference's.
 *
 * Conventions (must match buffers.py):
 *   - bit dbg: one '0'/'1' character per item, other bytes ignored;
 *   - bit bin: LSB-first packing within each byte, zero-padded tail;
 *   - int dbg: items separated by commas and/or whitespace.
 */

#include <stdint.h>
#include <stdio.h>
#include <string.h>

/* ---------------------------------------------------------------- bits */

void ziria_pack_bits(const uint8_t *bits, int64_t n, uint8_t *out) {
    int64_t nb = (n + 7) / 8;
    memset(out, 0, (size_t)nb);
    for (int64_t i = 0; i < n; i++)
        out[i >> 3] |= (uint8_t)((bits[i] & 1u) << (i & 7));
}

void ziria_unpack_bits(const uint8_t *bytes, int64_t n_bytes, uint8_t *out) {
    for (int64_t i = 0; i < n_bytes; i++) {
        uint8_t b = bytes[i];
        uint8_t *o = out + i * 8;
        for (int k = 0; k < 8; k++)
            o[k] = (b >> k) & 1u;
    }
}

/* dbg text -> bit items; returns count written (<= text_len). */
int64_t ziria_parse_dbg_bits(const char *text, int64_t text_len,
                             uint8_t *out) {
    int64_t n = 0;
    for (int64_t i = 0; i < text_len; i++) {
        char c = text[i];
        if (c == '0' || c == '1')
            out[n++] = (uint8_t)(c - '0');
    }
    return n;
}

void ziria_format_dbg_bits(const uint8_t *bits, int64_t n, char *out) {
    for (int64_t i = 0; i < n; i++)
        out[i] = bits[i] ? '1' : '0';
    out[n] = '\0';
}

/* ---------------------------------------------------------------- ints */

/* dbg text -> int64 items (commas/whitespace separators, optional sign,
 * 0x hex). Returns count, or -1 on malformed input. Caller sizes `out`
 * for at most (text_len + 1) / 2 + 1 items. */
int64_t ziria_parse_dbg_ints(const char *text, int64_t text_len,
                             int64_t *out) {
    int64_t n = 0, i = 0;
    while (i < text_len) {
        char c = text[i];
        if (c == ',' || c == ' ' || c == '\t' || c == '\n' || c == '\r') {
            i++;
            continue;
        }
        int neg = 0;
        if (c == '-' || c == '+') {
            neg = (c == '-');
            i++;
            if (i >= text_len) return -1;
            c = text[i];
        }
        if (c < '0' || c > '9') return -1;
        /* accumulate the magnitude unsigned so overflow is detected
         * without UB, and INT64_MIN (magnitude 2^63, one past
         * INT64_MAX) still parses when negated */
        uint64_t v = 0;
        uint64_t lim = neg ? (uint64_t)INT64_MAX + 1u : (uint64_t)INT64_MAX;
        if (c == '0' && i + 1 < text_len &&
            (text[i + 1] == 'x' || text[i + 1] == 'X')) {
            i += 2;
            int digits = 0;
            while (i < text_len) {
                char d = text[i];
                unsigned hv;
                if (d >= '0' && d <= '9') hv = (unsigned)(d - '0');
                else if (d >= 'a' && d <= 'f') hv = (unsigned)(d - 'a' + 10);
                else if (d >= 'A' && d <= 'F') hv = (unsigned)(d - 'A' + 10);
                else break;
                if (v > (lim - hv) / 16) return -1; /* overflow */
                v = v * 16 + hv;
                digits++;
                i++;
            }
            if (!digits) return -1;
        } else {
            while (i < text_len && text[i] >= '0' && text[i] <= '9') {
                unsigned d = (unsigned)(text[i] - '0');
                if (v > (lim - d) / 10) return -1; /* overflow: a
                    literal beyond int64 is a malformed stream */
                v = v * 10 + d;
                i++;
            }
        }
        out[n++] = neg ? (int64_t)(0u - v) : (int64_t)v;
    }
    return n;
}

/* int64 items -> dbg text (comma separated). Returns chars written
 * (excluding NUL). Caller sizes `out` for at least n * 21 + 1 bytes. */
int64_t ziria_format_dbg_ints(const int64_t *vals, int64_t n, char *out) {
    char *p = out;
    for (int64_t i = 0; i < n; i++) {
        if (i) *p++ = ',';
        p += sprintf(p, "%lld", (long long)vals[i]);
    }
    *p = '\0';
    return (int64_t)(p - out);
}
