"""CLI driver: the reference's params.c + driver.c, re-designed.

The reference's compiled executables all share one CLI
(`csrc/params.c`, SURVEY.md §2.2): ``--input=file --input-file-name=X
--input-file-mode=dbg|bin --output=...``. This driver keeps that flag
surface (so reference muscle-memory transfers) and adds the compiler
flags that in the reference live on `wplc` (`src/Opts.hs`): backend
selection (``--backend=interp|jit`` — the codegen-backend switch the
north star pins), vectorization width, ``--fold``/``--autolut``, and
pass-dump flags.

The program to run is a named pipeline from the registry
(``--prog=NAME``; `--list-progs` enumerates) — the analogue of picking
a compiled .blk executable. A textual frontend (.zir source via
``--src``) plugs in here when the parser lands.

Example:

    python -m ziria_tpu --prog=wifi_tx_sym_6 \
        --input=file --input-file-name=bits.dbg --input-file-mode=dbg \
        --input-type=bit \
        --output=file --output-file-name=out.bin --output-file-mode=bin \
        --output-type=complex16 --backend=jit
"""

from __future__ import annotations

# ziria: lint-ignore-file[R4] this module OWNS the scoped-env pattern:
# its flag writes are paired with the finally-restore in main(), and its
# reads mirror argparse defaults for the same invocation-scoped knobs
import argparse
import os
import sys
import time
from typing import Callable, Dict, Optional

try:
    import fcntl
except ImportError:                   # pragma: no cover - non-POSIX
    fcntl = None

import numpy as np

from ziria_tpu.runtime.buffers import ITEM_TYPES, StreamSpec, read_stream, \
    write_stream


# --------------------------------------------------------------------------
# Program registry
# --------------------------------------------------------------------------


def _prog_fir():
    """BASELINE config #1: FIR low-pass over a scalar float stream."""
    import jax.numpy as jnp
    import ziria_tpu as z

    taps = np.array([0.0625, 0.25, 0.375, 0.25, 0.0625], np.float32)

    def fir_step(state, x):
        state = jnp.roll(state, 1).at[0].set(x)
        return state, (state * jnp.asarray(taps)).sum()

    return z.map_accum(fir_step, np.zeros(5, np.float32), name="fir5")


def _prog_fft64():
    """BASELINE config #2: 64-point FFT blocks over complex16 pairs."""
    import jax.numpy as jnp
    import ziria_tpu as z
    from ziria_tpu.ops import cplx

    def fft_block(v):
        return cplx.fft_pair(jnp.asarray(v, jnp.float32))

    return z.zmap(fft_block, in_arity=64, out_arity=64, name="fft64")


def _prog_ifft64():
    import jax.numpy as jnp
    import ziria_tpu as z
    from ziria_tpu.ops import cplx

    def ifft_block(v):
        return cplx.ifft_pair(jnp.asarray(v, jnp.float32))

    return z.zmap(ifft_block, in_arity=64, out_arity=64, name="ifft64")


def _prog_scramble():
    """802.11 LFSR scrambler over a bit stream (default seed)."""
    import jax.numpy as jnp
    import ziria_tpu as z
    from ziria_tpu.ops import scramble
    from ziria_tpu.phy.wifi.tx import DEFAULT_SCRAMBLER_SEED, _seed_bits_np

    seq_np = scramble.np_lfsr_sequence_127(
        _seed_bits_np(DEFAULT_SCRAMBLER_SEED))

    def step(phase, b):
        out = jnp.asarray(b, jnp.uint8) ^ jnp.asarray(seq_np)[phase % 127]
        return phase + 1, out

    return z.map_accum(step, 0, name="scramble")


def _wifi_tx_sym(rate_mbps: int):
    def build():
        from ziria_tpu.phy.wifi.tx import tx_symbol_pipeline
        return tx_symbol_pipeline(rate_mbps)
    return build


PROGS: Dict[str, Callable] = {
    "fir": _prog_fir,
    "fft64": _prog_fft64,
    "ifft64": _prog_ifft64,
    "scramble": _prog_scramble,
}
for _r in (6, 9, 12, 18, 24, 36, 48, 54):
    PROGS[f"wifi_tx_sym_{_r}"] = _wifi_tx_sym(_r)


# --------------------------------------------------------------------------
# Arg parsing (reference params.c flag names)
# --------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ziria_tpu",
        description="TPU-native stream pipeline driver "
                    "(reference-style params)",
        epilog="subcommands: `python -m ziria_tpu lint [paths...]` runs "
               "the jaxlint static analysis (pure AST, no jax import; "
               "docs/static_analysis.md); `python -m ziria_tpu programs "
               "[--json] [--hlo-dump DIR]` runs the compiled-program "
               "observatory (CPU-pinned XLA cost/memory attribution; "
               "docs/observability.md); `python -m ziria_tpu serve "
               "[--sessions N] [--chaos SPEC]` runs the "
               "continuous-batching serving demo (docs/serving.md); "
               "`python -m ziria_tpu autotune [--frames N] [--reps N]` "
               "runs the cost-pruned measured geometry search and "
               "records the per-device winner in the bench ledger "
               "(docs/autotune.md)")
    p.add_argument("--prog", help="registered pipeline name")
    p.add_argument("--src", help="Ziria-like source file (.zir) to compile")
    p.add_argument("--list-progs", action="store_true")

    # `memory` streams are the programmatic API (StreamSpec(data=...));
    # argv has no way to carry an array, so the CLI offers file|dummy only
    p.add_argument("--input", default="file", choices=["file", "dummy"])
    p.add_argument("--input-file-name")
    p.add_argument("--input-file-mode", default="dbg",
                   choices=["dbg", "bin"])
    p.add_argument("--input-type", default=None, choices=ITEM_TYPES,
                   help="item type (default: from the program's read[t], "
                        "else int32)")
    p.add_argument("--dummy-samples", type=int, default=0)

    p.add_argument("--output", default="file", choices=["file", "dummy"])
    p.add_argument("--output-file-name")
    p.add_argument("--output-file-mode", default="dbg",
                   choices=["dbg", "bin"])
    p.add_argument("--output-type", default=None, choices=ITEM_TYPES,
                   help="item type (default: from the program's write[t], "
                        "else int32)")

    p.add_argument("--scan", action="store_true",
                   help="treat the input as one LONG capture: find "
                        "every packet (sp-sharded STS metric when "
                        "--sp=N is given) and decode them all as one "
                        "frame batch through the in-language receiver "
                        "(phy/search.scan_and_decode); the output "
                        "stream is the concatenated validated "
                        "payloads, packet starts print with --verbose")
    p.add_argument("--batch-input-files", metavar="F1,F2,...",
                   help="decode N independent input streams in ONE "
                        "process, batching the compiled program's "
                        "device steps across them (backend/framebatch; "
                        "implies --backend=hybrid); pairs with "
                        "--batch-output-files")
    p.add_argument("--batch-output-files", metavar="F1,F2,...",
                   help="per-stream output files for "
                        "--batch-input-files (same count)")

    p.add_argument("--backend", default="jit",
                   choices=["interp", "jit", "hybrid"])
    p.add_argument("--width", type=int, default=None,
                   help="vectorization width (default: planner)")
    p.add_argument("--sp", type=int, default=None, metavar="N",
                   help="split the stream over N devices (sequence "
                        "parallelism; jit backend, stateless or "
                        "fast-forwardable pipelines)")
    p.add_argument("--pp", type=int, default=None, metavar="N",
                   help="auto-pipeline the stages across N devices "
                        "(balanced |>>>| placement decided by the "
                        "compiler; jit backend)")
    p.add_argument("--pp-costs", choices=("proxy", "measured"),
                   default="proxy",
                   help="stage-cost model for --pp placement: 'proxy' "
                        "(items moved per steady-state iteration) or "
                        "'measured' (time each stage on a sample of "
                        "the real input before deciding)")
    p.add_argument("--fold", action="store_true", default=True)
    p.add_argument("--no-fold", dest="fold", action="store_false")
    p.add_argument("--autolut", action="store_true")
    p.add_argument("--fxp-complex16", action="store_true",
                   help="int16 fixed-point complex16 policy: stream "
                        "items and arithmetic are integer IQ pairs "
                        "with C shorts semantics (wrap at store); "
                        "f32 is retained only inside explicitly "
                        "complex-typed ext calls such as v_fft")
    p.add_argument("--ddump-fold", action="store_true",
                   help="dump the IR after folding")
    p.add_argument("--ddump-vect", action="store_true",
                   help="dump the vectorizer's scored candidate table")
    p.add_argument("--ddump-hybrid", action="store_true",
                   help="dump the hybrid executor's per-do-block "
                        "decisions (weight, jit/effects/below-threshold)")
    p.add_argument("--stats", action="store_true",
                   help="print the fused plan: per-stage firing counts, "
                        "rates, width (jit backend)")
    p.add_argument("--profile", action="store_true",
                   help="per-stage wall time + item counts: each top-"
                        "level pipeline stage runs separately (warm-up "
                        "+ timed pass); totals differ from the fused run")
    p.add_argument("--profile-trace", metavar="DIR",
                   help="write a jax.profiler trace of the run to DIR "
                        "(view with TensorBoard / xprof)")
    p.add_argument("--trace", metavar="PATH",
                   help="write a Chrome trace-event JSON of this "
                        "invocation's instrumented host spans — every "
                        "dispatch site, gauge counter track, and "
                        "compile event — to PATH "
                        "(utils/telemetry; load in Perfetto / "
                        "chrome://tracing or summarize with "
                        "tools/trace_report.py); also via ZIRIA_TRACE")
    p.add_argument("--metrics-dump", action="store_true",
                   help="print a Prometheus-style text exposition of "
                        "the invocation's metrics registry — dispatch "
                        "counters, per-site latency histograms "
                        "(power-of-two buckets, p50/p99 bounds), "
                        "gauges — to stderr at exit (utils/telemetry; "
                        "docs/observability.md)")
    p.add_argument("--chaos", metavar="SPEC",
                   help="run this invocation under a seeded fault-"
                        "injection plan (utils/faults; "
                        "docs/robustness.md): semicolon-separated "
                        "'[seed=N;]site:kind[:key=val,...]' specs — "
                        "kinds nan_slab/truncate (push seams), "
                        "transient/fatal/delay/hang (dispatch "
                        "seams); selectors every=N / calls=i+j / "
                        "p=F; deterministic by (site, seed, "
                        "call-index) so every chaos run replays "
                        "exactly. Also via ZIRIA_CHAOS")
    p.add_argument("--max-retries", type=int, default=None,
                   metavar="N",
                   help="transient-failure retry budget of every "
                        "guarded dispatch site (runtime/resilience "
                        "guarded dispatch: watchdog + exponential "
                        "backoff with deterministic jitter; default "
                        "2). Also via ZIRIA_MAX_RETRIES")
    p.add_argument("--channel-profile", metavar="NAME[,NAME...]",
                   help="default physical-channel profile of the "
                        "stimulus surfaces (phy/profiles; "
                        "docs/robustness.md): named multipath / "
                        "sampling-clock-offset / Doppler-drift / "
                        "interference-burst parameter sets — flat, "
                        "mild, urban, severe, sco, doppler, bursty, "
                        "hostile — applied as vmapped per-lane taps "
                        "inside the existing channel dispatches "
                        "('flat' IS the unprofiled channel, bit-"
                        "identical by construction; a comma list "
                        "assigns per lane/stream, cycling). Also via "
                        "ZIRIA_CHANNEL_PROFILE")
    p.add_argument("--rx-sco-track", dest="rx_sco_track",
                   action="store_true", default=None,
                   help="pilot phase-RAMP tracking in the RX DATA "
                        "decode (the sampling-clock-offset hardening; "
                        "docs/robustness.md). Default off — the flat-"
                        "channel decode is pinned bit-identical and "
                        "a fitted slope is never exactly zero. Also "
                        "via ZIRIA_RX_SCO_TRACK=1")
    p.add_argument("--no-rx-sco-track", dest="rx_sco_track",
                   action="store_false",
                   help="force SCO tracking off (overrides an "
                        "exported ZIRIA_RX_SCO_TRACK=1)")
    p.add_argument("--state-in",
                   help="resume stream state from this checkpoint "
                        "(runtime/state.py; jit backend)")
    p.add_argument("--state-out",
                   help="write final stream state to this checkpoint")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent XLA compilation cache directory "
                        "(or env ZIRIA_COMPILE_CACHE): repeat driver "
                        "invocations of the same program skip "
                        "first-compile costs")
    p.add_argument("--platform", default=None,
                   help="pin the JAX platform (e.g. cpu, tpu) before "
                        "backend init; also via ZIRIA_PLATFORM env var")
    p.add_argument("--viterbi-window", type=int, default=None,
                   metavar="N",
                   help="decode every staged viterbi_soft ext with the "
                        "sliding-window PARALLEL Pallas Viterbi "
                        "(window N, e.g. 1024): ~T/N less sequential "
                        "trellis depth on chip, same result at "
                        "operating SNR; also via ZIRIA_VITERBI_WINDOW")
    # choices mirror ops.viterbi.METRIC_DTYPES (asserted by
    # tests/test_viterbi_int16.py::test_cli_choices_mirror_metric_dtypes)
    # — not imported here so --help stays cheap
    p.add_argument("--viterbi-metric", default=None,
                   choices=["float32", "int16", "int8"],
                   help="path-metric dtype for every staged "
                        "viterbi_soft ext: int16 runs the quantized "
                        "saturating-metric Pallas kernel (the SORA "
                        "trade — half the LLR stream and metric "
                        "footprint; docs/quantized_viterbi.md), int8 "
                        "the 4-bit-soft LUT-branch-metric kernel "
                        "below it (half the resident metric state "
                        "again; BER-envelope accuracy, not bit "
                        "identity), float32 the exact oracle "
                        "(default); also via ZIRIA_VITERBI_METRIC")
    # choices mirror ops.viterbi.RADIXES (same pinned-mirror rule)
    p.add_argument("--viterbi-radix", type=int, default=None,
                   choices=[2, 4],
                   help="trellis steps per Pallas ACS iteration for "
                        "every staged viterbi_soft ext and library "
                        "decode surface: 4 collapses butterfly pairs "
                        "into one 4-way compare — half the sequential "
                        "dependency chain of the decode core's "
                        "hottest kernel, bit-identical to 2 (the "
                        "default/oracle) at float32 and int16; also "
                        "via ZIRIA_VITERBI_RADIX")
    p.add_argument("--fused-demap", dest="fused_demap",
                   action="store_true", default=None,
                   help="run demap + deinterleave + depuncture as an "
                        "in-kernel prologue of the Pallas Viterbi on "
                        "the known-rate DATA decodes (receive / "
                        "decode_data_batch): LLRs are produced and "
                        "consumed in VMEM and never round-trip HBM "
                        "between the front end and the ACS "
                        "(docs/architecture.md decode-roofline "
                        "section; the mixed-rate switch decode keeps "
                        "the XLA front end). Also via "
                        "ZIRIA_FUSED_DEMAP=1")
    p.add_argument("--no-fused-demap", dest="fused_demap",
                   action="store_false",
                   help="force the XLA front end (the fused "
                        "prologue's bit-identical oracle; the "
                        "default); also via ZIRIA_FUSED_DEMAP=0")
    p.add_argument("--batched-acquire", dest="batched_acquire",
                   action="store_true", default=None,
                   help="one-dispatch batched acquisition for the "
                        "frame-batched library receiver "
                        "(framebatch.receive_many): detect + align + "
                        "CFO + SIGNAL parse for ALL captures as ONE "
                        "vmapped device call, then gather+derotate "
                        "and the mixed-rate decode — O(1) dispatches "
                        "per batch instead of ~3 per capture (the "
                        "default; docs/architecture.md). Also via "
                        "ZIRIA_BATCHED_ACQUIRE=1")
    p.add_argument("--no-batched-acquire", dest="batched_acquire",
                   action="store_false",
                   help="force the host-driven per-capture "
                        "acquisition loop (the batched path's "
                        "bit-identical oracle); also via "
                        "ZIRIA_BATCHED_ACQUIRE=0")
    p.add_argument("--batched-tx", dest="batched_tx",
                   action="store_true", default=None,
                   help="one-dispatch batched TX for the frame-batch "
                        "surfaces (tx.encode_many / link.loopback_many "
                        "/ framebatch.transmit_many): an N-frame "
                        "mixed-rate, mixed-length batch encodes as "
                        "ONE vmapped lax.switch device call, and the "
                        "loopback link runs TX->channel->RX in ~5 "
                        "dispatches total (the default; "
                        "docs/architecture.md). Also via "
                        "ZIRIA_BATCHED_TX=1")
    p.add_argument("--no-batched-tx", dest="batched_tx",
                   action="store_false",
                   help="force the per-frame encode/loopback loop "
                        "(the batched TX path's bit-identical "
                        "oracle); also via ZIRIA_BATCHED_TX=0")
    p.add_argument("--streaming-rx", dest="streaming_rx",
                   action="store_true", default=None,
                   help="chunked one-dispatch streaming receiver for "
                        "the library stream surface "
                        "(framebatch.receive_stream): a long multi-"
                        "frame capture is scanned in fixed overlapping "
                        "chunks, each chunk costing <= 2 device "
                        "dispatches (multi-peak detect + align + "
                        "acquire + gather fused, then one mixed-rate "
                        "decode), with the host<->device transfer "
                        "double-buffered behind compute (the default; "
                        "docs/architecture.md). Also via "
                        "ZIRIA_STREAMING_RX=1")
    p.add_argument("--no-streaming-rx", dest="streaming_rx",
                   action="store_false",
                   help="force the per-capture oracle over the same "
                        "detected windows (>= 3 dispatches per frame "
                        "— the streaming path's bit-identical "
                        "contract); also via ZIRIA_STREAMING_RX=0")
    p.add_argument("--multi-stream", dest="multi_stream", type=int,
                   default=None, metavar="S",
                   help="S-stream fleet mode for the library stream "
                        "surface (framebatch.receive_streams / "
                        "MultiStreamReceiver): S concurrent I/Q "
                        "streams' chunks stack on a leading stream "
                        "axis through stream-axis-vmapped twins of "
                        "the two compiled streaming programs — <= 2 "
                        "device dispatches per chunk-step independent "
                        "of S, shardable over the dp device mesh "
                        "(the default; docs/architecture.md). S=0 "
                        "disables (same as --no-multi-stream). Also "
                        "via ZIRIA_MULTI_STREAM=S")
    p.add_argument("--no-multi-stream", dest="multi_stream",
                   action="store_const", const=0,
                   help="force S independent single-stream receivers "
                        "(the fleet path's bit-identical oracle, "
                        ">= S x the dispatch count); also via "
                        "ZIRIA_MULTI_STREAM=0")
    p.add_argument("--fused-link", dest="fused_link",
                   action="store_true", default=None,
                   help="ONE-dispatch fused loopback link "
                        "(phy/link.loopback_many): the whole "
                        "TX -> channel -> acquire -> classify -> "
                        "gather -> mixed decode -> batched-CRC chain "
                        "as a single jitted device program — the "
                        "acquisition decision tree traced on-device, "
                        "1 dispatch per N-frame all-rates multi-SNR "
                        "batch (the default; docs/architecture.md). "
                        "Also via ZIRIA_FUSED_LINK=1")
    p.add_argument("--no-fused-link", dest="fused_link",
                   action="store_false",
                   help="force the staged ~5-dispatch loopback "
                        "(encode_many + impair_many + acquire/gather/"
                        "decode — the fused graph's bit-identical "
                        "oracle); also via ZIRIA_FUSED_LINK=0")
    return p


def _resolve_prog(args):
    """Returns (comp, default_in_ty, default_out_ty)."""
    if args.src:
        from ziria_tpu.frontend import compile_file
        prog = compile_file(args.src,
                            fxp_complex16=args.fxp_complex16,
                            autolut=args.autolut)
        return prog.comp, prog.in_ty, prog.out_ty
    if not args.prog:
        raise SystemExit("need --prog=NAME or --src=FILE "
                         "(--list-progs to enumerate)")
    if args.prog not in PROGS:
        raise SystemExit(
            f"unknown prog {args.prog!r}; known: {', '.join(sorted(PROGS))}")
    return PROGS[args.prog](), None, None


def _apply_compile_cache(path: Optional[str]) -> None:
    """Persistent XLA compilation cache for the driver: repeat CLI
    invocations of the same program skip the first-compile cost
    (20-40 s for the receiver's machines on a TPU, minutes on CPU).
    Opt-in via --compile-cache=DIR or ZIRIA_COMPILE_CACHE; best-effort
    — some PJRT plugins reject the config."""
    path = path or os.environ.get("ZIRIA_COMPILE_CACHE")
    if not path:
        return
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.abspath(path))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
    except Exception as e:
        print(f"warning: compile cache unavailable: {e}",
              file=sys.stderr)


def _apply_platform(name: Optional[str]) -> None:
    """Pin the JAX platform BEFORE backend init. Needed because an
    installed PJRT plugin can win over the JAX_PLATFORMS env var; the
    flag (or ZIRIA_PLATFORM) goes through jax.config, which the plugin
    cannot override. No-op once the backend is live."""
    name = name or os.environ.get("ZIRIA_PLATFORM")
    if not name:
        return
    import jax
    try:
        jax.config.update("jax_platforms", name)
    except RuntimeError:
        live = jax.default_backend()
        if live != name:
            print(f"warning: --platform={name} requested but the JAX "
                  f"backend is already initialized ({live}); running "
                  f"on {live}", file=sys.stderr)


# the box-wide TPU mutual-exclusion flag (same path bench.py and
# tools/tpu_watcher.sh serialize on); module-level so tests can inject
TPU_BUSY_FLAG = "/tmp/tpu_busy"
BUSY_STALE_S = 35 * 60          # bench.py's leaked-flag threshold

# a successful backend probe this recent is trusted without re-probing:
# the healthy path used to pay a full extra backend init per CLI
# invocation of a long-lived embedder process (ADVICE r5 #2)
PROBE_OK_TTL_S = 300.0
_probe_ok_t = 0.0


def _backend_probe_failed(timeout_s: float, probe_argv=None) -> bool:
    """Bounded default-backend health probe. Returns True if the
    backend failed to come up within ``timeout_s``.

    The probe runs in its own process GROUP and the whole group is
    killed on timeout: the axon runtime spawns helpers that inherit
    the pipes, and killing only the direct child would leave us
    blocked on pipe EOF — the exact hang this probe exists to avoid.
    ``probe_argv`` is injectable for tests.
    """
    import signal
    import subprocess
    # device enumeration alone can succeed on a dead axon tunnel; only
    # a computation + device->host copy proves the backend is live
    # (same lesson as bench.py's probe child)
    argv = probe_argv or [
        sys.executable, "-c",
        "import jax, jax.numpy as jnp, numpy\n"
        "x = jnp.ones((8, 8), jnp.float32)\n"
        "numpy.asarray((x @ x).ravel()[:1])\n"]
    proc = subprocess.Popen(argv, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL,
                            start_new_session=True)
    try:
        return proc.wait(timeout=timeout_s) != 0
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        return True


def _jax_platforms_pinned() -> bool:
    """True when the in-process jax_platforms pin makes backend init
    hang-proof: tests and embedders set it to "cpu" via jax.config
    before calling main. An "axon,..."/"tpu,..." value (this box
    exports JAX_PLATFORMS=axon) is exactly the configuration that CAN
    hang, so it does NOT count as pinned here. One shared parse with
    the vectorizer's platform resolution."""
    from ziria_tpu.core.vectorize import active_platform
    return active_platform() == "cpu"


def _fastfail_dead_backend(args) -> Optional[int]:
    """Dead-backend fast-fail (VERDICT r4 weak #8).

    When the axon TPU tunnel is down, backend init hangs every
    default-platform invocation for minutes — and the plugin wins over
    the JAX_PLATFORMS env var, so users cannot escape via environment
    alone. If no platform is pinned, health-check the default backend
    in a bounded subprocess first and fail in seconds with the
    actionable hint. ``ZIRIA_BACKEND_PROBE_TIMEOUT=0`` disables the
    probe (wait for the backend however long it takes).
    """
    if args.platform or os.environ.get("ZIRIA_PLATFORM"):
        return None      # pinned via jax.config — init cannot hang
    if _jax_platforms_pinned():
        return None      # already pinned in-process (tests, embedders)
    # only a non-cpu env routing (JAX_PLATFORMS=axon/tpu — a tunnelled
    # plugin) can hang init; an ordinary machine with no such routing
    # resolves to a local backend and must not pay a probe subprocess
    env_first = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip()
    if env_first in ("", "cpu"):
        return None
    try:
        tmo = float(os.environ.get("ZIRIA_BACKEND_PROBE_TIMEOUT", "12"))
    except ValueError:
        tmo = 12.0
    if tmo <= 0:
        return None
    # honor the box's TPU serialization contract: a fresh busy flag
    # means another client (watcher harvest, bench) holds the backend —
    # it is busy, not dead, and a second axon client would hang BOTH.
    # Diagnose without touching the backend.
    if _busy_flag_fresh():
        return _report_held()
    global _probe_ok_t
    if time.time() - _probe_ok_t < PROBE_OK_TTL_S:
        return None   # a recent probe already proved the tunnel live
    # close the check-then-probe TOCTOU (ADVICE r5 #2): CLAIM the busy
    # flag with an O_EXCL create BEFORE spawning the probe, so a
    # watcher harvest starting in the gap sees the flag held and waits
    # instead of attaching a second axon client (which hangs both).
    # Losing the create race means another client just took the
    # backend — report held, exactly as if the flag had been fresh
    # at the first check.
    claimed = _claim_busy_flag()
    if claimed is None:
        return _report_held()
    try:
        if _backend_probe_failed(tmo):
            print(f"error: the default JAX backend did not initialize "
                  f"within {tmo:.0f}s — the axon TPU tunnel is likely "
                  f"down. Pass --platform=cpu to run on the host, or "
                  f"set ZIRIA_BACKEND_PROBE_TIMEOUT=0 to wait "
                  f"indefinitely.", file=sys.stderr)
            return 2
        _probe_ok_t = time.time()
    finally:
        if claimed:
            _release_busy_flag()
    return None


def _report_held() -> int:
    """The one 'backend is busy, not dead' diagnostic (fresh flag and
    lost-claim race are the same condition to the user)."""
    print("error: the TPU backend is held by another client "
          "(/tmp/tpu_busy, a watcher harvest or bench run). "
          "Pass --platform=cpu to run on the host, or retry "
          "when the harvest finishes.", file=sys.stderr)
    return 2


def _busy_flag_fresh() -> bool:
    """True when TPU_BUSY_FLAG exists and is younger than the leaked-
    flag threshold (i.e. another client genuinely holds the backend)."""
    try:
        return time.time() - os.path.getmtime(TPU_BUSY_FLAG) \
            < BUSY_STALE_S
    except OSError:
        return False


def _claim_busy_flag():
    """Atomically claim TPU_BUSY_FLAG for the probe's duration.

    Returns True on success, None when another client holds the flag
    (the caller reports "held"), False when the flag path is unusable
    (unwritable dir) — probe unguarded, the pre-fix behavior. A stale
    leftover flag is taken over via _takeover_stale_flag (which never
    deletes a LIVE flag) and the claim retried ONCE; a second
    FileExistsError means a live client won the race."""
    for attempt in (0, 1):
        try:
            fd = os.open(TPU_BUSY_FLAG,
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, f"ziria_tpu cli probe pid={os.getpid()}\n"
                     .encode())
            os.close(fd)
            return True
        except FileExistsError:
            if attempt or _busy_flag_fresh():
                return None
            if not _takeover_stale_flag():
                return None            # a live client owns it after all
        except OSError:
            return False
    return False        # pragma: no cover - loop always returns


def _takeover_stale_flag() -> bool:
    """Remove a LEAKED busy flag without ever deleting a live one.

    A bare ``unlink(path)`` here would race a concurrent takeover:
    another client can remove the stale flag and create a FRESH one in
    the gap after our staleness check, and our unlink would then
    delete the live flag — exactly the double-axon-client hang the
    claim exists to prevent. Instead: flock the EXISTING file, re-check
    staleness on the locked fd, and unlink only while the path still
    names that locked inode; a recreated flag has a new inode and
    survives (we report held). Returns True when the caller may retry
    the O_EXCL claim, False when a live holder was found."""
    if fcntl is None:       # pragma: no cover - non-POSIX best effort
        try:
            os.unlink(TPU_BUSY_FLAG)
        except OSError:
            pass
        return True
    try:
        fd = os.open(TPU_BUSY_FLAG, os.O_RDONLY)
    except OSError:
        return True          # already gone: retry the claim
    try:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            return False     # another takeover in flight: treat as held
        st = os.fstat(fd)
        if time.time() - st.st_mtime < BUSY_STALE_S:
            return False     # freshened while we looked: live holder
        try:
            if os.stat(TPU_BUSY_FLAG).st_ino != st.st_ino:
                return False  # replaced by a new live flag
        except OSError:
            return True      # unlinked underneath us: retry the claim
        os.unlink(TPU_BUSY_FLAG)
        return True
    finally:
        os.close(fd)


def _release_busy_flag() -> None:
    try:
        with open(TPU_BUSY_FLAG) as f:
            if "ziria_tpu cli probe" not in f.read():
                return       # not ours — never release another holder
        os.unlink(TPU_BUSY_FLAG)
    except OSError:
        pass


def _run_profiled(comp, xs, args):
    """Per-stage observability (SURVEY.md §5 tracing row): run each
    top-level pipeline stage separately — one warm-up pass (compile),
    one timed pass — reporting wall time and item counts per stage.
    Stages are composition-independent (their state is internal), so
    the final output equals the fused run's; only the *timing* loses
    cross-stage fusion, which is the point of a per-stage breakdown."""
    from ziria_tpu.core.ir import pipeline_stages

    stages = list(pipeline_stages(comp))
    rows = []
    cur = np.asarray(xs)
    for st in stages:
        if args.backend == "interp":
            from ziria_tpu.interp.interp import run

            def go(_st=st, _cur=cur):
                return np.asarray(run(_st, list(_cur)).out_array())
        else:
            # jit when the stage lowers, hybrid otherwise — the shared
            # stage-timing discipline (autosplit.stage_runner, also
            # behind --pp-costs=measured)
            from ziria_tpu.parallel.autosplit import stage_runner
            go = stage_runner(st, cur, width=args.width)

        go()                                   # warm-up / compile
        t0 = time.perf_counter()
        out = go()
        dt = time.perf_counter() - t0
        rows.append((st.label(), cur.shape[0], out.shape[0], dt))
        cur = out

    total = sum(r[3] for r in rows) or 1e-12
    print(f"profile: {len(rows)} stage(s), backend={args.backend} "
          f"(stages timed unfused)", file=sys.stderr)
    for lbl, n_in, n_out, dt in rows:
        print(f"  stage {lbl:<28s} {n_in:>8d} -> {n_out:>8d} items  "
              f"{dt * 1e3:>9.3f} ms  {100 * dt / total:>5.1f}%  "
              f"({n_in / max(dt, 1e-12):,.0f} items/s)", file=sys.stderr)
    return cur


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "lint":
        # jaxlint subcommand: pure-AST static analysis of the jit
        # disciplines (docs/static_analysis.md). Dispatched BEFORE
        # argparse and without touching jax, so the gate runs even
        # when the TPU backend probe hangs.
        from ziria_tpu.analysis.__main__ import main as lint_main
        return lint_main(argv[1:])
    if argv and argv[0] == "programs":
        # compiled-program observatory subcommand: XLA cost/memory
        # attribution per jit factory. Dispatched BEFORE argparse,
        # mirroring `lint`; the observatory pins the CPU backend
        # itself, so cost attribution works while the TPU probe hangs.
        from ziria_tpu.utils.programs import main as programs_main
        return programs_main(argv[1:])
    if argv and argv[0] == "autotune":
        # geometry autotuner (utils/autotune, docs/autotune.md):
        # cost-pruned measured search; pre-argparse like `lint` —
        # the winner lands keyed by device_kind in the bench ledger
        from ziria_tpu.utils.autotune import main as autotune_main
        return autotune_main(argv[1:])
    if argv and argv[0] == "serve":
        # continuous-batching serving demo (runtime/serve,
        # docs/serving.md): synthetic many-client load through the
        # real fleet, SIGINT-safe drain + final stats/exposition,
        # chaos-injectable. Own arg surface, dispatched BEFORE
        # argparse like `lint`/`programs`.
        from ziria_tpu.runtime.serve import main as serve_main
        return serve_main(argv[1:])
    args = build_parser().parse_args(argv)
    _apply_platform(args.platform)
    _apply_compile_cache(args.compile_cache)
    if args.list_progs:
        for name in sorted(PROGS):
            print(name)
        return 0

    rc = _fastfail_dead_backend(args)
    if rc is not None:
        return rc

    # the staged viterbi_soft ext reads the env pair at trace time
    # (frontend/externals.viterbi_mode, folded into the backend's
    # compile cache keys); scope the writes to this invocation so
    # in-process callers (tests, embedders) never inherit them, and
    # let --viterbi-window=0 / --viterbi-metric=float32 force-disable
    # an exported env value (review r5)
    overrides = {}
    if args.viterbi_window is not None:
        overrides["ZIRIA_VITERBI_WINDOW"] = str(args.viterbi_window)
    if args.viterbi_metric is not None:
        overrides["ZIRIA_VITERBI_METRIC"] = args.viterbi_metric
    if args.viterbi_radix is not None:
        # --viterbi-radix=2 force-disables an exported env value, the
        # same force-off semantics as --viterbi-metric=float32
        overrides["ZIRIA_VITERBI_RADIX"] = str(args.viterbi_radix)
    if args.fused_demap is not None:
        overrides["ZIRIA_FUSED_DEMAP"] = \
            "1" if args.fused_demap else "0"
    if args.batched_acquire is not None:
        # receive_many reads this at call time; scoping the write
        # keeps in-process callers from inheriting the flag, same as
        # the viterbi pair above
        overrides["ZIRIA_BATCHED_ACQUIRE"] = \
            "1" if args.batched_acquire else "0"
    if args.batched_tx is not None:
        # link.batched_tx_enabled reads this at call time (the TX
        # twin of the batched-acquire knob)
        overrides["ZIRIA_BATCHED_TX"] = \
            "1" if args.batched_tx else "0"
    if args.fused_link is not None:
        # link.fused_link_enabled reads this at call time (the
        # one-dispatch loopback vs its staged 5-dispatch oracle)
        overrides["ZIRIA_FUSED_LINK"] = \
            "1" if args.fused_link else "0"
    if args.streaming_rx is not None:
        # framebatch.streaming_rx_enabled reads this at call time
        # (the chunked streaming receiver vs its per-capture oracle)
        overrides["ZIRIA_STREAMING_RX"] = \
            "1" if args.streaming_rx else "0"
    if args.multi_stream is not None:
        # framebatch.multi_stream_enabled reads this at call time (the
        # S-stream fleet vs S independent single-stream receivers);
        # the value is the declared lane count, "0" disables
        overrides["ZIRIA_MULTI_STREAM"] = str(args.multi_stream)
    if args.chaos is not None:
        # faults.env_chaos reads this inside _main_run's shell; the
        # scoped write keeps in-process callers from inheriting a
        # fault plan, same as every knob above. Validate NOW so a
        # malformed spec is a flag error, not a traceback from deep
        # inside the run (parse_chaos_spec self-validates kinds and
        # selectors)
        from ziria_tpu.utils import faults as _faults
        try:
            _faults.parse_chaos_spec(args.chaos)
        except ValueError as e:
            raise SystemExit(f"--chaos: {e}")
        overrides["ZIRIA_CHAOS"] = args.chaos
    if args.max_retries is not None:
        # resilience.env_max_retries reads this at guarded-site
        # policy resolution time
        if args.max_retries < 0:
            raise SystemExit(
                f"--max-retries: {args.max_retries} must be >= 0")
        overrides["ZIRIA_MAX_RETRIES"] = str(args.max_retries)
    if args.channel_profile is not None:
        # profiles.env_channel_profile reads this at the stimulus
        # surfaces (link.stream_many[_multi], loopback_many). Validate
        # NOW so an unknown profile is a flag error naming the known
        # registry, not a traceback from deep inside the run
        from ziria_tpu.phy import profiles as _profiles
        try:
            _profiles.parse_profile_spec(args.channel_profile)
        except ValueError as e:
            raise SystemExit(f"--channel-profile: {e}")
        overrides["ZIRIA_CHANNEL_PROFILE"] = args.channel_profile
    if args.rx_sco_track is not None:
        # rx.sco_track_enabled reads this at decode-surface entry
        # (resolved once, part of every decode factory's cache key);
        # --no-rx-sco-track force-disables an exported env value
        overrides["ZIRIA_RX_SCO_TRACK"] = \
            "1" if args.rx_sco_track else "0"
    if args.trace:
        # telemetry.env_trace_path reads this inside _main_run; the
        # scoped write keeps in-process callers from inheriting an
        # always-on trace, same as every knob above
        overrides["ZIRIA_TRACE"] = args.trace
    if not overrides:
        return _main_run(args)
    prev = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        return _main_run(args)
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _main_run(args) -> int:
    """The telemetry shell around every command path: when --trace /
    ZIRIA_TRACE names a path, the whole run is recorded as a Chrome
    trace and exported there (even on failure — a crashed run's trace
    is the one you want most); --metrics-dump collects the run's
    metrics registry and prints its Prometheus-style exposition to
    stderr at exit."""
    from ziria_tpu.utils import faults, telemetry

    tpath = telemetry.env_trace_path()
    try:
        chaos = faults.env_chaos()
    except ValueError as e:
        # a directly-exported malformed ZIRIA_CHAOS must be a clean
        # error, never a silent no-chaos run or a raw traceback
        raise SystemExit(f"ZIRIA_CHAOS: {e}")
    if not tpath and not args.metrics_dump and chaos is None:
        return _run_cmd(args)
    import contextlib
    reg = None
    try:
        with contextlib.ExitStack() as stack:
            if tpath:
                stack.enter_context(telemetry.tracing(tpath))
            if args.metrics_dump:
                reg = stack.enter_context(telemetry.collect())
            if chaos is not None:
                # the whole invocation runs under the described fault
                # plan (utils/faults; --chaos / ZIRIA_CHAOS)
                specs, seed = chaos
                stack.enter_context(faults.inject(*specs, seed=seed))
            return _run_cmd(args)
    finally:
        # the crashed run's telemetry is the telemetry you want most:
        # tracing() exports in its own finally, and the exposition /
        # hint print here so ^C or a failing command still reports
        if tpath:
            print(f"telemetry trace written to {tpath} "
                  f"(summarize: python tools/trace_report.py {tpath})",
                  file=sys.stderr)
        if reg is not None:
            print("metrics exposition (utils/telemetry):",
                  file=sys.stderr)
            print(reg.exposition(), file=sys.stderr, end="")


def _run_cmd(args) -> int:
    if args.scan:
        return _run_scan(args)

    comp, src_in_ty, src_out_ty = _resolve_prog(args)
    in_ty = args.input_type or src_in_ty or "int32"
    out_ty = args.output_type or src_out_ty or "int32"

    pre_read = None      # input parsed early by --pp-costs=measured
    # autolut first: fold's map-map fusion erases in_domain declarations,
    # so the LUT rewrite must see the maps before they fuse
    if args.autolut:
        from ziria_tpu.core.autolut import autolut
        comp = autolut(comp)
    if args.pp is not None and args.pp >= 1:
        # decide |>>>| placement BEFORE folding: fold fuses across >>>
        # (collapsing the stages we want to distribute) but respects
        # ParPipe boundaries, so each decided segment still fuses
        # internally. --pp=1 also goes through the pass: any existing
        # |>>>| annotations are flattened onto the single device
        from ziria_tpu.parallel.autosplit import (AutoSplitError,
                                                  auto_pipeline)
        sample = None
        if args.pp_costs == "measured":
            # validate flag compatibility BEFORE spending seconds of
            # per-stage sampling that _run_backend would reject anyway
            if args.backend != "jit" or args.profile:
                raise SystemExit("--pp needs --backend=jit and cannot "
                                 "combine with --profile")
            # time each stage on (a slice of) the real input instead
            # of the items-moved proxy; the full array is kept so the
            # run below does not parse the file a second time
            spec = StreamSpec(kind=args.input, ty=in_ty,
                              path=args.input_file_name,
                              mode=args.input_file_mode,
                              dummy_items=args.dummy_samples)
            pre_read = read_stream(spec)
            if pre_read.shape[0] == 0:
                raise SystemExit("--pp-costs=measured: input sample is "
                                 "empty (nothing to time)")
            sample = pre_read[: 1 << 15]
        try:
            comp = auto_pipeline(comp, args.pp, sample=sample,
                                 width=args.width or 1)
        except AutoSplitError as e:
            raise SystemExit(f"--pp={args.pp}: {e}")
    if args.fold:
        from ziria_tpu.core.opt import fold
        comp = fold(comp)
    if args.ddump_fold:
        print(comp, file=sys.stderr)
    if args.ddump_vect:
        from ziria_tpu.core.vectorize import vectorize
        print(vectorize(comp).dump(), file=sys.stderr)
    if args.ddump_hybrid:
        from ziria_tpu.backend.hybrid import hybridize
        print("hybrid plan:", file=sys.stderr)
        hybridize(comp, dump=lambda s: print(s, file=sys.stderr))

    if args.batch_input_files or args.batch_output_files:
        return _run_batch_files(comp, args, in_ty, out_ty)

    in_spec = StreamSpec(kind=args.input, ty=in_ty,
                         path=args.input_file_name,
                         mode=args.input_file_mode,
                         dummy_items=args.dummy_samples)
    out_spec = StreamSpec(kind=args.output, ty=out_ty,
                          path=args.output_file_name,
                          mode=args.output_file_mode)

    if args.profile and (args.state_in or args.state_out):
        raise SystemExit("--profile runs stages separately and "
                         "cannot combine with --state-in/--state-out")
    xs = pre_read if pre_read is not None else read_stream(in_spec)
    tracing = False
    if args.profile_trace:
        import jax
        jax.profiler.start_trace(args.profile_trace)
        tracing = True
    t0 = time.perf_counter()
    try:
        ys, dt = _run_backend(comp, xs, args, t0)
    finally:
        if tracing:
            import jax
            jax.profiler.stop_trace()
            print(f"profiler trace written to {args.profile_trace}",
                  file=sys.stderr)

    write_stream(out_spec, ys)
    if args.verbose:
        print(f"items in: {xs.shape[0]}, items out: {ys.shape[0]}, "
              f"time: {dt:.4f}s "
              f"({xs.shape[0] / max(dt, 1e-12):,.0f} items/s)",
              file=sys.stderr)
    return 0


def _seq_of(comp):
    """ParPipe pipeline -> plain Pipe of the same segments (the fused
    single-device equivalent, sharing carry structure stage-for-stage)."""
    from ziria_tpu.core import ir as _ir
    return _ir.pipe(*_ir.par_segments(comp))


def _run_auto_pp(comp, xs, args, t0):
    """--pp=N: compiler-decided stage placement across N devices (the
    reference's auto-pipelining pass, minus the hand-written |>>>|)."""
    import jax

    from ziria_tpu.backend.lower import LowerError
    from ziria_tpu.parallel.stages import lower_stage_parallel
    from ziria_tpu.parallel.streampar import (StreamParError,
                                              stream_mesh)

    if args.stats:
        print("note: --stats reports the fused single-device plan and "
              "is unavailable under --pp", file=sys.stderr)
    if args.width is not None and args.width < 1:
        raise SystemExit(f"--width={args.width}: must be >= 1")
    if args.width is None:
        print("note: --pp segments run at width 1; pass --width=W to "
              "vectorize each segment (widths multiply the macro "
              "chunk the input length must divide)", file=sys.stderr)
    try:
        mesh = stream_mesh(args.pp, axis="pp")
        # main() already decided the ParPipe placement (pre-fold)
        pp = lower_stage_parallel(
            comp, mesh, width=args.width if args.width else 1,
            in_item=jax.ShapeDtypeStruct(xs.shape[1:], xs.dtype))
    except (LowerError, StreamParError) as e:
        raise SystemExit(f"--pp={args.pp}: {e}")
    m = xs.shape[0] // pp.take
    r = xs.shape[0] - m * pp.take
    if r == 0:
        ys = np.asarray(pp.run(xs.reshape((m, pp.take) + xs.shape[1:])))
        return (ys.reshape((m * pp.emit,) + ys.shape[2:]),
                time.perf_counter() - t0)
    # remainder path: the reference's queues had no length restriction
    # (SURVEY.md §2.2 TS queues). Run the whole macro chunks through
    # the pipeline, then continue the tail on the fused single-device
    # path seeded with the segments' exit carries — exact vs run_jit
    # for any length.
    from ziria_tpu.backend.execute import run_jit_carry
    seq = _seq_of(comp)
    outs = []
    carry = None
    if m:
        ys, carry = pp.run_carry(
            xs[: m * pp.take].reshape((m, pp.take) + xs.shape[1:]))
        ys = np.asarray(ys)
        outs.append(ys.reshape((m * pp.emit,) + ys.shape[2:]))
    tail, _ = run_jit_carry(seq, xs[m * pp.take:], carry=carry,
                            width=args.width)
    tail = np.asarray(tail)
    if tail.shape[0]:
        outs.append(tail)
    ys = (np.concatenate(outs, axis=0) if outs
          else np.empty((0,) + xs.shape[1:], xs.dtype))
    return ys, time.perf_counter() - t0


def _run_scan(args) -> int:
    """--scan: long-capture workflow — sp-shardable packet search +
    frame-batched decode of every hit (phy/search.scan_and_decode).
    The program is fixed (the in-language receiver); --src/--prog are
    rejected so a mismatch cannot pass silently."""
    if args.src or args.prog:
        raise SystemExit("--scan uses the in-language receiver; drop "
                         "--src/--prog")
    if args.profile or args.profile_trace or args.stats \
            or args.pp is not None or args.state_in \
            or args.state_out or args.batch_input_files \
            or args.batch_output_files:
        raise SystemExit("--scan cannot combine with --pp/--profile/"
                         "--profile-trace/--stats/--state-*/--batch-*")
    if args.input != "file" or not args.input_file_name:
        raise SystemExit("--scan needs --input=file with "
                         "--input-file-name (a complex16 capture)")
    if args.sp is not None and args.sp < 1:
        raise SystemExit(f"--sp={args.sp}: need at least 1 device")
    # fail on a bad output spec BEFORE the scan spends minutes
    out_spec = StreamSpec(kind=args.output, ty="bit",
                          path=args.output_file_name,
                          mode=args.output_file_mode)
    from ziria_tpu.parallel.streampar import StreamParError
    from ziria_tpu.phy.search import scan_and_decode

    xs = read_stream(StreamSpec(kind="file", ty="complex16",
                                path=args.input_file_name,
                                mode=args.input_file_mode))
    try:
        mesh = None
        if args.sp is not None:
            from ziria_tpu.parallel.streampar import stream_mesh
            mesh = stream_mesh(args.sp)
        t0 = time.perf_counter()
        hits = scan_and_decode(xs, mesh=mesh)
    except StreamParError as e:
        raise SystemExit(f"--sp={args.sp}: {e}")
    dt = time.perf_counter() - t0
    payload = (np.concatenate([b for _s, b in hits])
               if hits else np.empty((0,), np.uint8))
    write_stream(out_spec, payload)
    if args.verbose:
        print(f"scan: {xs.shape[0]} samples, {len(hits)} packet(s) "
              f"validated at {[s for s, _b in hits]}, "
              f"{payload.shape[0]} payload bits, time: {dt:.3f}s",
              file=sys.stderr)
    return 0


def _run_batch_files(comp, args, in_ty, out_ty) -> int:
    """--batch-input-files: N independent streams through one
    hybridized program, chunk-machine device steps batched across them
    (backend/framebatch.py) — the driver surface of frame batching.
    Each stream's output goes to the matching --batch-output-files
    entry, bit-identical to N separate runs."""
    if not (args.batch_input_files and args.batch_output_files):
        raise SystemExit("--batch-input-files and --batch-output-files "
                         "must be given together")
    ins = [f for f in args.batch_input_files.split(",") if f]
    outs = [f for f in args.batch_output_files.split(",") if f]
    if len(ins) != len(outs):
        raise SystemExit(
            f"--batch-*: {len(ins)} inputs but {len(outs)} outputs")
    if args.backend == "jit":
        args.backend = "hybrid"           # the documented implication
    if args.backend != "hybrid" or args.profile or args.profile_trace \
            or args.stats or args.sp is not None \
            or args.pp is not None or args.state_in or args.state_out:
        raise SystemExit("--batch-input-files runs the hybrid backend "
                         "and cannot combine with --sp/--pp/--profile/"
                         "--profile-trace/--stats/--state-*")

    from ziria_tpu.backend.framebatch import StepBatcher, run_many
    from ziria_tpu.backend.hybrid import hybridize

    frames = [read_stream(StreamSpec(kind="file", ty=in_ty, path=f,
                                     mode=args.input_file_mode))
              for f in ins]
    hyb = hybridize(comp)
    t0 = time.perf_counter()
    b = StepBatcher(len(frames))
    results = run_many(hyb, [list(x) for x in frames], batcher=b)
    dt = time.perf_counter() - t0
    for f, res in zip(outs, results):
        write_stream(StreamSpec(kind="file", ty=out_ty, path=f,
                                mode=args.output_file_mode),
                     np.asarray(res.out_array()))
    if args.verbose:
        n_in = sum(x.shape[0] for x in frames)
        n_out = sum(len(r.outputs) for r in results)
        print(f"batch: {len(frames)} streams, items in: {n_in}, "
              f"items out: {n_out}, device calls: {b.device_calls} "
              f"(group sizes {b.group_sizes}), time: {dt:.4f}s",
              file=sys.stderr)
    return 0


def _run_backend(comp, xs, args, t0):
    """Dispatch to --profile / interp / jit; returns (ys, seconds)."""
    if args.sp is not None:
        # validate up front so the flag can never be silently ignored
        if args.sp < 1:
            raise SystemExit(f"--sp={args.sp}: need at least 1 device")
        if args.backend != "jit" or args.profile:
            raise SystemExit("--sp needs --backend=jit (sequence "
                             "parallelism shards the fused pipeline) "
                             "and cannot combine with --profile")
    if args.pp is not None:
        if args.pp < 1:
            raise SystemExit(f"--pp={args.pp}: need at least 1 device")
        if args.backend != "jit" or args.profile or args.sp is not None \
                or args.state_in or args.state_out:
            raise SystemExit("--pp needs --backend=jit and cannot "
                             "combine with --sp/--profile/--state-*")
        return _run_auto_pp(comp, xs, args, t0)
    if args.profile:
        ys = _run_profiled(comp, xs, args)
        return ys, time.perf_counter() - t0
    if args.backend in ("interp", "hybrid"):
        if args.state_in or args.state_out:
            raise SystemExit("--state-in/--state-out need --backend=jit "
                             "(stream state is the jit carry pytree)")
        if args.backend == "hybrid":
            # interpreter-driven control, jit-compiled heavy do-blocks
            # (backend/hybrid.py) — for dynamic-control programs like
            # the flagship receiver that the fused jit path refuses
            from ziria_tpu.backend.hybrid import hybridize
            comp = hybridize(comp)
        from ziria_tpu.interp.interp import run
        res = run(comp, list(xs))
        ys = np.asarray(res.out_array())
    else:
        from ziria_tpu.backend.execute import lower, run_jit_carry
        from ziria_tpu.backend.lower import LowerError
        if args.sp is not None:
            if args.state_in or args.state_out:
                raise SystemExit("--sp cannot combine with "
                                 "--state-in/--state-out (the sharded "
                                 "run has no single carry)")
            from ziria_tpu.parallel.streampar import (StreamParError,
                                                      stream_mesh,
                                                      stream_parallel)
            if args.stats:
                print("note: --stats reports the single-device fused "
                      "plan and is unavailable under --sp",
                      file=sys.stderr)
            try:
                ys = stream_parallel(comp, xs, stream_mesh(args.sp),
                                     width=args.width)
            except (StreamParError, LowerError) as e:
                raise SystemExit(f"--sp={args.sp}: {e}")
            return np.asarray(ys), time.perf_counter() - t0
        stats: Optional[dict] = {} if args.stats else None
        try:
            carry = None
            if args.state_in:
                from ziria_tpu.runtime.state import (load_state,
                                                     program_fingerprint)
                carry = load_state(args.state_in,
                                   like=lower(comp, width=args.width)
                                   .init_carry,
                                   fingerprint=program_fingerprint(comp))
            ys, carry = run_jit_carry(comp, xs, carry=carry,
                                      width=args.width, stats_out=stats)
        except LowerError as e:
            # dynamic-control programs can't fuse; instead of refusing
            # (the reference's compiler compiles everything), fall back
            # to the hybrid executor — same results, control on the
            # host, heavy blocks still jit-compiled. (LowerError is
            # raised before any execution, so nothing ran twice.)
            if args.state_in or args.state_out:
                raise SystemExit(
                    f"--state-in/--state-out need a fusable pipeline "
                    f"({e})")
            print(f"note: program has dynamic control "
                  f"({e}); falling back to --backend=hybrid",
                  file=sys.stderr)
            if args.stats:
                print("note: --stats reports the fused plan and is "
                      "unavailable under the hybrid fallback "
                      "(try --ddump-hybrid)", file=sys.stderr)
            from ziria_tpu.backend.hybrid import hybridize
            from ziria_tpu.interp.interp import run
            res = run(hybridize(comp), list(xs))
            return (np.asarray(res.out_array()),
                    time.perf_counter() - t0)
        ys = np.asarray(ys)
        if args.state_out:
            from ziria_tpu.runtime.state import (program_fingerprint,
                                                 save_state)
            save_state(args.state_out, carry,
                       fingerprint=program_fingerprint(comp))
        if args.stats:
            # printed straight from the executor's own split arithmetic
            print(f"plan: width={stats['width']} take={stats['take']} "
                  f"emit={stats['emit']} "
                  f"bulk_steps={stats['bulk_steps']} "
                  f"remainder_iters={stats['remainder_iters']}",
                  file=sys.stderr)
            for lbl, reps in zip(stats["labels"], stats["reps"]):
                print(f"  stage {lbl:<28s} {reps:>6d} firings/iter "
                      f"({reps * stats['width']} per bulk step)",
                      file=sys.stderr)
    return ys, time.perf_counter() - t0


if __name__ == "__main__":
    sys.exit(main())
