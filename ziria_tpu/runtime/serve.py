"""Continuous-batching serving runtime: N client sessions multiplexed
onto one fixed (S, K, chunk) compiled fleet geometry.

The ROADMAP's last missing layer between the device-side fleet
(`backend/framebatch.MultiStreamReceiver`, PR 11) and "heavy traffic
from millions of users": production traffic is many clients pushing
ragged I/Q slabs concurrently under latency SLOs, and the device side
must never see that raggedness — Ziria's ``|>>>|`` discipline keeps
the steady-state stream on the engine with the host touched only at
control points, and this scheduler IS that host control point (Sora's
dedicated-core streaming lineage: admission/eviction happen off the
hot dispatch loop). The compiled geometry never changes:

- **Admission** is a bounded queue with explicit backpressure. A
  session gets a free lane immediately, waits in the queue, or is
  REJECTED with a deterministic ``retry_after_s`` hint — never
  unbounded buffering, never a silent stall.
- **Scheduling** is continuous batching: each :meth:`ServeRuntime.step`
  moves at most one chunk's worth of each session's staged samples
  into its lane and fires ``push_many`` — the fleet packer dispatches
  one chunk-step for whichever lanes filled a chunk, idle lanes ride
  the existing valid-mask. Session count never enters the dispatch
  budget (≤ 2 dispatches per chunk-step, the PR 11 pin).
- **Deadlines + load shedding**: a session past its SLO deadline is
  SHED — removed, counted, and attributed in the shed log — not
  silently stalled. Shedding is deterministic: every decision reads
  the injectable ``clock`` at step boundaries, so a replay sheds the
  identical sessions at the identical steps.
- **Fault containment** rides PR 12's machinery unchanged: NaN slabs
  quarantine ONE lane behind the valid-mask (healthy sessions stay
  bit-identical to independent receivers, pinned), dispatch faults
  retry/degrade through `runtime/resilience.guarded`.
- **Eviction + recovery**: :meth:`ServeRuntime.evict` checkpoints a
  session's lane (`resilience.checkpoint_carry` blob, quarantine
  rider included); ``connect(sid, checkpoint=blob)`` restores it into
  a fresh lane with bit-identical subsequent emissions (the
  `restore_stream` contract).
- **Graceful drain**: :meth:`ServeRuntime.drain` stops admitting,
  flushes every in-flight chunk and session tail, and leaves the
  final stats — the SIGINT path of the ``python -m ziria_tpu serve``
  demo.
- **Crash durability** (ISSUE 14, docs/robustness.md): with
  ``snapshot_dir`` set, every state transition journals
  (runtime/durability write-ahead log) and the fleet snapshots
  atomically every ``snapshot_every`` chunk-steps —
  :meth:`ServeRuntime.recover` rebuilds the whole fleet after a
  ``kill -9`` with bit-identical emissions (at-least-once, deduped
  against the journaled delivery watermarks), elastically repacking
  onto fewer lanes when devices shrank.

All SLO metrics report through the PR 7 `utils/telemetry` registry —
:meth:`ServeRuntime.scrape` is the registry's Prometheus-style
``exposition()``, not a parallel stats path: ``serve.*`` counters
(admitted/queued/rejected/shed/evicted/restored/closed/frames, shed
reasons as labels), ``serve.active_sessions`` / ``serve.queue_depth``
gauges, and the ``serve.chunk_seconds`` latency histogram whose
p50/p99 are the SLO numbers, next to the per-dispatch
``ziria_dispatch_seconds{site="rx.stream_chunk_multi"}`` series the
receiver already emits. Use the runtime as a context manager — it
activates its registry for its lifetime and drains on exit.

The module imports no jax: the receiver is injectable (the default
builds a `MultiStreamReceiver` lazily), so `tools/serve_smoke.py`
exercises the whole admission/shed/evict/drain state machine against
a stub receiver in milliseconds, through TPU probe hangs.
"""

from __future__ import annotations

import base64
import bisect
import os
import time
from collections import deque
from typing import Any, Callable, Dict, List, NamedTuple, Optional, \
    Tuple

import numpy as np

from ziria_tpu.runtime import durability, resilience
from ziria_tpu.utils import dispatch, faults, geometry as _geometry, \
    telemetry

# the single source of the fleet-geometry defaults below (jax-free,
# like this module) — ServeConfig() and StreamReceiver() can never
# drift apart on chunk_len/frame_len/K/S again
_GEO = _geometry.DEFAULT


class ServeConfig(NamedTuple):
    """The server's fixed shape. The first five fields are the
    compiled fleet geometry (`MultiStreamReceiver`'s, defaults
    inherited from :data:`ziria_tpu.utils.geometry.DEFAULT` —
    admission churn never changes them, so the two fleet programs
    compile once); the rest are host-side protocol bounds. Build
    from a tuned geometry with :meth:`from_geometry`."""
    n_lanes: int = _GEO.n_streams    # S: concurrent sessions on device
    chunk_len: int = _GEO.chunk_len
    frame_len: int = _GEO.frame_len
    max_frames_per_chunk: int = _GEO.max_frames_per_chunk
    check_fcs: bool = False
    queue_cap: int = 16              # admission queue bound
    max_slab_samples: int = 1 << 16  # oversized-slab reject bound
    max_backlog_samples: int = 1 << 18   # per-session staged bound
    default_slo_s: Optional[float] = None  # deadline = connect + slo
    retry_after_s: float = 0.05      # base backpressure hint
    sanitize: bool = True            # NaN slabs quarantine, not crash
    max_retries: Optional[int] = None    # guarded-dispatch budget
    watchdog_s: Optional[float] = None   # hang-cut timeout
    blowup_limit: int = 2
    rejoin_after: int = 3
    # durability (ISSUE 14): a snapshot_dir activates the write-ahead
    # journal; snapshot_every > 0 adds automatic fleet snapshots every
    # N chunk-steps (ServeRuntime.recover(dir) resumes after a crash)
    snapshot_dir: Optional[str] = None
    snapshot_every: int = 0
    snapshot_keep: int = 2
    journal_segment_records: int = 256
    jitter_seed: int = 0             # retry-after hint jitter seed
    shard: bool = False              # elastic dp mesh over the lanes

    @classmethod
    def from_geometry(cls, geo: "_geometry.Geometry",
                      **overrides: Any) -> "ServeConfig":
        """Config whose fleet-geometry fields come from ``geo`` (e.g.
        ``Geometry.tuned(device_kind)``); host-protocol fields keep
        their defaults unless overridden."""
        fields = dict(n_lanes=geo.n_streams, chunk_len=geo.chunk_len,
                      frame_len=geo.frame_len,
                      max_frames_per_chunk=geo.max_frames_per_chunk)
        fields.update(overrides)
        return cls(**fields)


class AdmitResult(NamedTuple):
    """:meth:`ServeRuntime.connect`'s answer. Exactly one of
    ``admitted``/``queued`` is True on success; both False means the
    client should retry after ``retry_after_s`` (``reason`` says
    why: ``queue_full`` / ``draining`` / ``duplicate``)."""
    sid: Any
    admitted: bool
    queued: bool = False
    retry_after_s: float = 0.0
    reason: str = ""


class SubmitResult(NamedTuple):
    """:meth:`ServeRuntime.submit`'s answer. ``accepted=False`` with
    a ``retry_after_s`` is backpressure (``backlog_full``); with
    ``reason`` ``oversized`` the slab violated the protocol bound;
    a terminal reason (``shed:deadline`` / ``evicted`` / ``closed`` /
    ``draining``) means the session is gone — reconnect or move on.
    Backpressure and shedding are protocol results, not exceptions:
    only a malformed slab or an unknown session id raises."""
    sid: Any
    accepted: bool
    retry_after_s: float = 0.0
    reason: str = ""


class ServeStats(NamedTuple):
    """The final report (:meth:`ServeRuntime.stats`): exact session
    accounting read back FROM the telemetry registry (the counters
    ARE the record — ``admitted == closed + shed_active + evicted +
    active`` by construction; a still-queued session that closes or
    evicts lands on the separate ``serve.closed_queued`` /
    ``serve.evicted_queued`` counters, visible in the scrape, so the
    balance holds) plus the receiver's dispatch-side numbers."""
    admitted: int
    queued: int
    rejected_admissions: int
    rejected_slabs: int
    shed: int
    evicted: int
    restored: int
    closed: int
    frames: int
    chunk_steps: int
    active_sessions: int
    queue_depth: int
    quarantined_sessions: int
    shed_log: Tuple
    snapshots: int = 0
    restarts: int = 0
    deduped: int = 0
    journal_errors: int = 0


class _Session:
    __slots__ = ("sid", "lane", "staged", "staged_samples", "deadline",
                 "connected_t", "frames", "restore_blob", "slo_s",
                 "dedupe_until", "acked", "unacked")

    def __init__(self, sid, now: float, slo_s: Optional[float],
                 restore_blob: Optional[bytes]):
        self.sid = sid
        self.lane: Optional[int] = None
        self.staged: deque = deque()      # accepted, not yet scheduled
        self.staged_samples = 0
        self.connected_t = now
        self.slo_s = None if slo_s is None else float(slo_s)
        self.deadline = None if slo_s is None else now + float(slo_s)
        self.frames = 0                   # per-session emission index
        self.restore_blob = restore_blob
        # durability bookkeeping (ISSUE 14): re-emissions with index
        # <= dedupe_until were already delivered before a crash and
        # are suppressed on recovery; `acked` is the stream coordinate
        # durably consumed (the client resubmits from it); `unacked`
        # holds (index, frame) pairs emitted but not yet journal-
        # marked — they ride the next snapshot as the rider
        self.dedupe_until = 0
        self.acked = 0
        self.unacked: List[Tuple[int, Any]] = []


def _slab(samples, sid) -> np.ndarray:
    """The ingress shape gate (the receiver's `_slab_array` rule,
    jax-free): coerce to (n, 2) float32 I/Q pairs or raise a
    ValueError NAMING the session — malformed input fails at the
    front door, never inside the scheduler."""
    try:
        arr = np.asarray(samples, np.float32)
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"session {sid!r}: submitted slab is not "
            f"float-convertible ((n, 2) I/Q sample pairs expected): "
            f"{e}") from None
    if arr.size == 0:
        return arr.reshape(0, 2)
    if arr.ndim != 2 or arr.shape[1] != 2:
        raise ValueError(
            f"session {sid!r}: submitted slab has shape {arr.shape}, "
            f"want (n, 2) I/Q sample pairs")
    return arr


def _known(ids, cap: int = 16) -> str:
    ids = sorted(ids, key=repr)
    shown = ", ".join(repr(i) for i in ids[:cap])
    more = f", ... {len(ids) - cap} more" if len(ids) > cap else ""
    return f"[{shown}{more}]" if ids else "[] (none connected)"


class ServeRuntime:
    """The continuous-batching server. Single-threaded and
    deterministic by design: every admission/shed/evict decision is a
    pure function of the call sequence and the injectable ``clock``,
    so a chaos replay reproduces the run decision for decision.

    Use as a context manager::

        with ServeRuntime(ServeConfig(n_lanes=8, ...)) as srv:
            srv.connect("alice", slo_s=2.0)
            srv.submit("alice", slab)
            frames = srv.step()        # the scheduler tick
            ...
            final = srv.drain()        # or leave the block: auto-drain
        print(srv.scrape())            # Prometheus exposition

    ``receiver`` injects a duck-typed fleet (tests, the jax-free
    smoke); the default builds a `MultiStreamReceiver` at the config
    geometry on first use."""

    def __init__(self, config: Optional[ServeConfig] = None,
                 receiver=None,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[telemetry.MetricsRegistry] = None):
        self.cfg = config if config is not None else ServeConfig()
        if self.cfg.n_lanes < 1:
            raise ValueError(f"n_lanes {self.cfg.n_lanes} must be >= 1")
        self.clock = clock
        self.registry = registry if registry is not None \
            else telemetry.MetricsRegistry()
        self._rx = receiver if receiver is not None \
            else self._default_receiver()
        self._free = list(range(self.cfg.n_lanes))
        self._lane_sid: Dict[int, Any] = {}
        self._sessions: Dict[Any, _Session] = {}
        self._queue: deque = deque()
        self._gone: Dict[Any, str] = {}   # sid -> terminal reason
        self._spill: List = []            # (lane, frame) off-step
        self._shed_log: List[Tuple] = []
        self._steps_seen = 0
        self._draining = False
        self._drained = False
        self._cm = None
        self._rejects: Dict[Any, int] = {}   # sid -> reject attempts
        # durability (ISSUE 14): the write-ahead journal + snapshot
        # cadence; recovery state lives on `recovered`/`replayed`
        self._journal: Optional[durability.Journal] = None
        if self.cfg.snapshot_dir:
            self._journal = durability.Journal(
                os.path.join(self.cfg.snapshot_dir, "journal"),
                segment_records=self.cfg.journal_segment_records)
        self._marked: Dict[Any, int] = {}      # sid -> journaled mark
        self._pending_marks: Dict[Any, int] = {}
        # snapshot steps are ABSOLUTE across restarts: a recovered
        # runtime's receiver restarts chunk_steps at 0, so recover()
        # sets _step_base to the recovered snapshot's step — without
        # it, post-recovery snapshots would be numbered BELOW the
        # pre-crash ones and pruned as "oldest" (second-crash rollback)
        self._step_base = 0
        self._last_snap_step = 0
        self._last_snap_t: Optional[float] = None
        self.recovered: Dict[Any, dict] = {}   # recovery info per sid
        self.replayed: List[Tuple[Any, Any]] = []  # rider re-delivery

    def _default_receiver(self):
        # lazy: jax (through framebatch) is only imported when the
        # real fleet is wanted — the smoke's stub path never pays it
        from ziria_tpu.backend import framebatch
        c = self.cfg
        mesh = None
        if c.shard:
            # the ELASTIC placement rule: shard the lane axis over
            # the widest S-divisible mesh the surviving devices
            # support — a recovery onto fewer chips rebuilds the
            # fleet instead of refusing to start (ISSUE 14)
            from ziria_tpu.parallel import batch as pbatch
            mesh = pbatch.elastic_mesh(c.n_lanes)
        return framebatch.MultiStreamReceiver(
            c.n_lanes, chunk_len=c.chunk_len, frame_len=c.frame_len,
            max_frames_per_chunk=c.max_frames_per_chunk,
            check_fcs=c.check_fcs, sanitize=c.sanitize,
            max_retries=c.max_retries, watchdog_s=c.watchdog_s,
            blowup_limit=c.blowup_limit,
            rejoin_after=c.rejoin_after, mesh=mesh)

    # -- lifecycle -------------------------------------------------------

    def __enter__(self) -> "ServeRuntime":
        self._cm = telemetry.collect(self.registry)
        self._cm.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        try:
            if not self._drained:
                self.drain()
        finally:
            cm, self._cm = self._cm, None
            cm.__exit__(*exc)

    # -- telemetry helpers ----------------------------------------------

    def _count(self, name: str, n: int = 1,
               labels: Optional[dict] = None) -> None:
        telemetry.count(name, n, labels=labels)

    def _counter_total(self, name: str) -> int:
        return sum(m.value for (n, _l), m in self.registry.metrics()
                   if n == name
                   and isinstance(m, telemetry.CounterMetric))

    def _gauges(self) -> None:
        dispatch.record_gauge("serve.active_sessions",
                              len(self._lane_sid))
        dispatch.record_gauge("serve.queue_depth", len(self._queue))
        dispatch.record_gauge(
            "serve.quarantined_sessions",
            sum(1 for ln in self._lane_sid
                if self._rx.quarantined(ln)))

    def _retry_after(self, sid=None) -> float:
        """Deterministic backpressure hint, scaled by the queue the
        rejected client would have stood behind — with PER-SESSION
        HASHED JITTER (ISSUE 14 satellite): an unjittered hint is the
        same for every client at the same depth, so a flood of
        synchronized rejects re-arrives in lockstep and floods again.
        The jitter is the resilience backoff discipline — a unit hash
        of (label, seed, attempt), never drawn — so a replay hints
        identically: hint = base * (1 + depth) * (0.5 + 0.5 * u)."""
        base = self.cfg.retry_after_s * (1 + len(self._queue))
        attempt = self._rejects.get(sid, 0)
        self._rejects[sid] = attempt + 1
        # bound the attempt table: a flood of unique-sid rejects is
        # exactly the overload this hint exists for, and must not
        # leak memory — an evicted entry just restarts that client's
        # jitter sequence (harmless)
        while len(self._rejects) > 4096:
            self._rejects.pop(next(iter(self._rejects)))
        u = faults._unit(f"{sid!r}", self.cfg.jitter_seed, attempt)
        return base * (0.5 + 0.5 * u)

    # -- durability: the write-ahead journal --------------------------

    def _j(self, ev: dict) -> None:
        """Best-effort durable journal append: a failed write (a full
        disk, an injected ``io_enospc``) is counted and contained —
        the fleet keeps serving; the lost record only WIDENS the
        recovery dedupe window (at-least-once, never a crash)."""
        if self._journal is None:
            return
        try:
            self._journal.append(ev)
        except OSError:
            self._count("serve.journal_errors")

    def _flush_marks(self) -> None:
        """Journal the delivery watermarks of everything returned by
        the PREVIOUS public call. Marks are deferred one call on
        purpose: a mark written before the caller actually received
        the frames would, after a crash in between, dedupe away
        frames nobody ever got (silent loss). Deferred, the crash
        window yields a re-delivery instead (at-least-once; the
        (sid, frame.start) pair is the idempotency key)."""
        if not self._pending_marks:
            return
        marks, self._pending_marks = self._pending_marks, {}
        self._j({"ev": "mark",
                 "d": {str(sid): n for sid, n in marks.items()}})
        for sid, n in marks.items():
            self._marked[sid] = n
            s = self._sessions.get(sid)
            if s is not None:
                while s.unacked and s.unacked[0][0] <= n:
                    s.unacked.pop(0)

    @staticmethod
    def _b64(blob: Optional[bytes]) -> Optional[str]:
        return None if blob is None \
            else base64.b64encode(blob).decode()

    def scrape(self) -> str:
        """The server's Prometheus-style scrape page — the PR 7
        registry exposition, serve.* series next to the receiver's
        dispatch/latency series. No parallel stats path."""
        return self.registry.exposition()

    def stats(self) -> ServeStats:
        ct = self._counter_total
        return ServeStats(
            admitted=ct("serve.admitted"),
            queued=ct("serve.queued"),
            rejected_admissions=ct("serve.rejected_admissions"),
            rejected_slabs=ct("serve.rejected_slabs"),
            shed=ct("serve.shed"),
            evicted=ct("serve.evicted"),
            restored=ct("serve.restored"),
            closed=ct("serve.closed"),
            frames=ct("serve.frames"),
            chunk_steps=int(self._rx.stats.chunk_steps),
            active_sessions=len(self._lane_sid),
            queue_depth=len(self._queue),
            quarantined_sessions=sum(
                1 for ln in self._lane_sid
                if self._rx.quarantined(ln)),
            shed_log=tuple(self._shed_log),
            snapshots=ct("serve.snapshots"),
            restarts=ct("serve.restarts"),
            deduped=ct("serve.deduped"),
            journal_errors=ct("serve.journal_errors"))

    # -- admission -------------------------------------------------------

    def connect(self, sid, slo_s: Optional[float] = None,
                checkpoint: Optional[bytes] = None) -> AdmitResult:
        """Admit a session: a free lane immediately, the bounded
        queue, or an explicit reject with a retry hint — never
        unbounded buffering. ``slo_s`` sets the deadline (connect
        time + slo; the config default applies when None);
        ``checkpoint`` restores an evicted session's blob into the
        granted lane (`restore_stream` — bit-identical resumption,
        quarantine rider included)."""
        self._flush_marks()
        if self._draining or self._drained:
            self._count("serve.rejected_admissions",
                        labels={"reason": "draining"})
            return AdmitResult(sid, False, False,
                               self._retry_after(sid), "draining")
        if sid in self._sessions:
            return AdmitResult(sid, False, False, 0.0, "duplicate")
        now = self.clock()
        slo = slo_s if slo_s is not None else self.cfg.default_slo_s
        s = _Session(sid, now, slo, checkpoint)
        if self._free:
            self._gone.pop(sid, None)  # reconnect after shed/evict
            self._sessions[sid] = s
            self._admit(s)
            self._j({"ev": "admit", "sid": sid, "slo": slo,
                     "ckpt": self._b64(checkpoint)})
            self._rejects.pop(sid, None)
            self._gauges()
            return AdmitResult(sid, True)
        if len(self._queue) >= self.cfg.queue_cap:
            # a REJECTED reconnect keeps its terminal _gone record:
            # submits keep answering with the old reason, not a raise
            self._count("serve.rejected_admissions",
                        labels={"reason": "queue_full"})
            return AdmitResult(sid, False, False,
                               self._retry_after(sid), "queue_full")
        self._gone.pop(sid, None)      # reconnect after shed/evict
        self._sessions[sid] = s
        self._queue.append(sid)
        self._count("serve.queued")
        self._j({"ev": "admit", "sid": sid, "slo": slo,
                 "ckpt": self._b64(checkpoint)})
        self._rejects.pop(sid, None)
        self._gauges()
        return AdmitResult(sid, False, True, 0.0, "queued")

    def _admit(self, s: _Session) -> None:
        lane = self._free.pop(0)
        s.lane = lane
        self._lane_sid[lane] = s.sid
        if s.restore_blob is not None:
            blob = s.restore_blob
            self._spill += self._rx.restore_stream(lane, blob)
            s.restore_blob = None
            try:
                st = resilience.restore_carry(blob)
                # the session's emission index resumes at the lane's
                # (the 1:1 emit rule), and `acked` names the stream
                # coordinate the blob durably consumed — the client
                # resubmits from there
                s.frames = int(st.emitted)
                s.acked = int(st.offset) + int(st.tail.shape[0])
            except resilience.CarryCheckpointError:
                pass    # duck-typed stub blob: counters stay fresh
            self._count("serve.restored")
        self._marked.setdefault(s.sid, s.frames)
        self._count("serve.admitted")

    def _admit_waiting(self) -> None:
        while self._free and self._queue:
            sid = self._queue.popleft()
            self._admit(self._sessions[sid])

    # -- ingress ---------------------------------------------------------

    def is_active(self, sid) -> bool:
        """True while ``sid`` holds a lane (admitted, not yet
        closed/shed/evicted) — the client-visible promotion signal:
        a queued session becomes active when a lane frees. Closing a
        session before it is active discards its staged data (it was
        never served), so well-behaved clients close active sessions
        only."""
        s = self._sessions.get(sid)
        return s is not None and s.lane is not None

    def _get_session(self, sid) -> _Session:
        s = self._sessions.get(sid)
        if s is None:
            raise KeyError(
                f"unknown session {sid!r}: known sessions are "
                f"{_known(self._sessions)}")
        return s

    def submit(self, sid, samples) -> SubmitResult:
        """Stage one slab of samples for ``sid``. Bounded end to end:
        an oversized slab is rejected (``max_slab_samples``), a slab
        that would overflow the session's staging bound is rejected
        with a retry hint (``max_backlog_samples`` — the per-session
        backpressure that contains floods). A slab for a shed/
        evicted/closed session returns its terminal reason; a truly
        unknown session raises a KeyError naming the known ones."""
        self._flush_marks()
        s = self._sessions.get(sid)
        if s is None:
            reason = self._gone.get(sid)
            if reason is not None:
                return SubmitResult(sid, False, 0.0, reason)
            self._get_session(sid)     # raises the named KeyError
        arr = _slab(samples, sid)
        n = int(arr.shape[0])
        if n > self.cfg.max_slab_samples:
            self._count("serve.rejected_slabs",
                        labels={"reason": "oversized"})
            return SubmitResult(sid, False, 0.0, "oversized")
        if s.staged_samples + n > self.cfg.max_backlog_samples:
            self._count("serve.rejected_slabs",
                        labels={"reason": "backlog_full"})
            return SubmitResult(sid, False, self._retry_after(sid),
                                "backlog_full")
        if n:
            s.staged.append(arr)
            s.staged_samples += n
        return SubmitResult(sid, True)

    # -- the scheduler tick ---------------------------------------------

    def _take_staged(self, s: _Session,
                     budget: int) -> Optional[np.ndarray]:
        """Pop exactly up to one chunk's worth of staged samples —
        the continuous-batching rate limit: a flooding client
        advances at MOST one chunk per tick (a slab crossing the
        budget is split, its tail pushed back), its excess held
        (bounded) in staging. Push-boundary invariance (the
        ragged-push pin) makes the re-slabbing bit-invisible to the
        receiver."""
        if not s.staged:
            return None
        take, got = [], 0
        while s.staged and got < budget:
            a = s.staged.popleft()
            need = budget - got
            if a.shape[0] > need:
                s.staged.appendleft(a[need:])
                a = a[:need]
            take.append(a)
            got += a.shape[0]
        s.staged_samples -= got
        return take[0] if len(take) == 1 else np.concatenate(take)

    def _emit(self, pairs) -> List[Tuple[Any, Any]]:
        """Map receiver (lane, frame) emissions back to sessions.
        Re-emissions already delivered before a crash (index at or
        below the session's journaled dedupe watermark) are SUPPRESSED
        and counted — the recovery dedupe window, docs/robustness.md.
        Delivered frames ride ``unacked`` until their mark is durably
        journaled (the next public call), so a snapshot in between
        can carry them as the rider."""
        out = []
        for lane, fr in pairs:
            sid = self._lane_sid.get(lane)
            if sid is None:            # pragma: no cover - drained
                continue               # lanes are emptied before free
            s = self._sessions[sid]
            s.frames += 1
            if s.frames <= s.dedupe_until:
                self._count("serve.deduped")
                continue
            s.unacked.append((s.frames, fr))
            self._pending_marks[sid] = s.frames
            out.append((sid, fr))
        if out:
            self._count("serve.frames", len(out))
        return out

    def _take_spill(self) -> List[Tuple[Any, Any]]:
        if not self._spill:
            return []
        spill, self._spill = self._spill, []
        return self._emit(spill)

    def _note_steps(self, dt: float) -> None:
        d = int(self._rx.stats.chunk_steps) - self._steps_seen
        if d <= 0:
            return
        self._steps_seen += d
        per = dt / d
        for _ in range(d):
            telemetry.observe("serve.chunk_seconds", per)

    def _push(self, push: Dict[int, np.ndarray]) -> List:
        t0 = time.perf_counter()
        got = self._rx.push_many(push)
        self._note_steps(time.perf_counter() - t0)
        return self._emit(got)

    def step(self) -> List[Tuple[Any, Any]]:
        """One scheduler tick: shed expired sessions, admit from the
        queue into freed lanes, move up to one chunk's worth of each
        session's staged samples into its lane, and fire the fleet
        packer (one ``push_many`` — chunk-steps dispatch for
        whichever lanes filled, idle lanes ride the valid-mask).
        Returns the ``(sid, StreamFrame)`` pairs that became
        decodable this tick."""
        if self._drained:
            raise RuntimeError("step after drain")
        self._flush_marks()
        out = self._take_spill()
        out += self._shed_expired()
        self._admit_waiting()
        push = {}
        for lane, sid in self._lane_sid.items():
            take = self._take_staged(self._sessions[sid],
                                     self.cfg.chunk_len)
            if take is not None:
                push[lane] = take
        if push:
            out += self._push(push)
        out += self._maybe_snapshot()
        self._gauges()
        return out

    # -- durability: snapshots + recovery -------------------------------

    def _maybe_snapshot(self) -> List[Tuple[Any, Any]]:
        """The automatic cadence: every ``snapshot_every`` chunk-steps
        the whole fleet snapshots (ISSUE 14 tentpole). Between
        snapshots the age gauges keep the staleness visible."""
        if self._journal is None or self.cfg.snapshot_every <= 0:
            return []
        steps = self._step_base + int(self._rx.stats.chunk_steps)
        if steps - self._last_snap_step < self.cfg.snapshot_every:
            if self._last_snap_t is not None:
                dispatch.record_gauge("serve.snapshot_age_s",
                                      self.clock()
                                      - self._last_snap_t)
                dispatch.record_gauge("serve.snapshot_age_steps",
                                      steps - self._last_snap_step)
            return []
        return self.snapshot()

    def snapshot(self) -> List[Tuple[Any, Any]]:
        """Write one atomic fleet snapshot: drain the in-flight
        chunk-step (its emissions are returned — they belong to the
        caller, never to the snapshot alone), then persist every
        occupied lane's checkpoint blob, the session table (SLO
        remainders, delivery watermarks, queued sessions' restore
        blobs), the terminal-reason map, the undelivered-frame rider,
        and the journal watermark — one atomic directory rename
        (runtime/durability.py). A failed write (full disk, injected
        ``io_enospc``) is contained: counted, the previous snapshot
        stays authoritative, serving continues."""
        if self._journal is None:
            raise RuntimeError(
                "snapshot without a snapshot_dir (set "
                "ServeConfig.snapshot_dir)")
        lanes, got = self._rx.checkpoint_fleet(
            sorted(self._lane_sid))
        out = self._emit(got)
        now = self.clock()
        step = self._step_base + int(self._rx.stats.chunk_steps)
        sessions = []
        for sid in ([self._lane_sid[ln]
                     for ln in sorted(self._lane_sid)]
                    + list(self._queue)):
            s = self._sessions[sid]
            sessions.append({
                "sid": sid, "lane": s.lane, "slo": s.slo_s,
                "slo_rem": None if s.deadline is None
                else max(0.0, s.deadline - now),
                "delivered": self._marked.get(sid, 0),
                "ckpt": self._b64(s.restore_blob)})
        rider, skipped = [], 0
        for sid, s in self._sessions.items():
            for idx, fr in s.unacked:
                try:
                    rider.append({"sid": sid, "idx": idx,
                                  "frame": durability.encode_frame(
                                      fr)})
                except Exception:    # noqa: BLE001 - duck-typed stub
                    skipped += 1
        if skipped:
            self._count("serve.rider_skipped", skipped)
        body = {"config": dict(self.cfg._asdict()),
                "jseq": int(self._journal.seq),
                "sessions": sessions,
                "gone": [[sid, r] for sid, r in self._gone.items()],
                "rider": rider}
        try:
            durability.write_snapshot(
                self.cfg.snapshot_dir, step, lanes, body,
                keep=self.cfg.snapshot_keep)
        except OSError:
            self._count("serve.snapshot_errors")
            return out
        self._journal.prune(body["jseq"])
        self._last_snap_step = step
        self._last_snap_t = now
        self._count("serve.snapshots")
        dispatch.record_gauge("serve.snapshot_age_s", 0.0)
        dispatch.record_gauge("serve.snapshot_age_steps", 0)
        return out

    def acked(self, sid) -> int:
        """The stream coordinate durably consumed for ``sid`` — after
        :meth:`recover`, the client resubmits its stream from here
        (everything before it is inside the restored lane state;
        everything after was lost with the process and must be pushed
        again)."""
        return self._get_session(sid).acked

    @classmethod
    def recover(cls, snapshot_dir: str,
                config: Optional[ServeConfig] = None,
                receiver=None,
                clock: Callable[[], float] = time.monotonic,
                registry: Optional[telemetry.MetricsRegistry] = None
                ) -> "ServeRuntime":
        """Rebuild a crashed server from its durability directory —
        the ISSUE 14 acceptance path: load the newest VALID snapshot,
        replay journal records past its watermark to reconstruct the
        session table exactly (admissions after the snapshot restore
        as fresh sessions; shed/evicted/closed sessions stay gone
        with their terminal reasons; delivery watermarks advance to
        the last durable mark), restore every lane blob into the new
        fleet, and re-deliver the snapshot's undelivered-frame rider
        (``.replayed``) — at-least-once, deduped against the
        journaled watermarks.

        ``config`` overrides the snapshot's recorded config — the
        ELASTIC failover lever: recover with a smaller ``n_lanes``
        (devices shrank) and sessions beyond the surviving lanes are
        repacked into the admission queue, restoring as lanes free
        (zero recompiles beyond the new geometry's two programs).
        ``.recovered`` maps every live session to its ``acked``
        resubmission coordinate and dedupe watermark."""
        snap = durability.load_snapshot(snapshot_dir)
        base_seq = int(snap.body.get("jseq", 0)) if snap else 0
        events, rstats = durability.replay(
            os.path.join(snapshot_dir, "journal"),
            after_seq=base_seq)
        if config is None:
            if snap is None:
                raise ValueError(
                    f"{snapshot_dir}: no usable snapshot — journal-"
                    f"only recovery needs an explicit config")
            config = ServeConfig(**snap.body["config"])
        config = config._replace(snapshot_dir=snapshot_dir)

        # reduce snapshot + journal into the final session table
        live: Dict[Any, dict] = {}
        delivered: Dict[Any, int] = {}
        order: List[Any] = []
        by_str: Dict[str, Any] = {}
        gone: Dict[Any, str] = {}

        def note(sid):
            by_str[str(sid)] = sid
            if sid not in order:
                order.append(sid)

        if snap is not None:
            for ent in snap.body.get("sessions", []):
                sid = ent["sid"]
                blob = None
                if ent.get("lane") is not None:
                    blob = snap.lanes.get(int(ent["lane"]))
                elif ent.get("ckpt"):
                    blob = base64.b64decode(ent["ckpt"])
                live[sid] = {"slo": ent.get("slo"),
                             "slo_rem": ent.get("slo_rem"),
                             "blob": blob}
                delivered[sid] = int(ent.get("delivered", 0))
                note(sid)
            gone.update({sid: r
                         for sid, r in snap.body.get("gone", [])})
        for ev in events:
            k = ev.get("ev")
            if k == "admit":
                sid = ev["sid"]
                blob = base64.b64decode(ev["ckpt"]) \
                    if ev.get("ckpt") else None
                live[sid] = {"slo": ev.get("slo"), "slo_rem": None,
                             "blob": blob}
                delivered[sid] = max(delivered.get(sid, 0),
                                     int(ev.get("delivered", 0)))
                gone.pop(sid, None)
                note(sid)
            elif k == "mark":
                for key, n in ev.get("d", {}).items():
                    sid = by_str.get(key, key)
                    delivered[sid] = max(delivered.get(sid, 0),
                                         int(n))
            elif k in ("shed", "close", "evict"):
                sid = ev["sid"]
                live.pop(sid, None)
                gone[sid] = ev.get("reason",
                                   "closed" if k == "close"
                                   else "evicted")

        srv = cls(config, receiver=receiver, clock=clock,
                  registry=registry)
        if snap is not None:
            # continue the ABSOLUTE step/sequence lines: the fresh
            # receiver restarts chunk_steps at 0 and a fully-pruned
            # journal restarts seq at 0 — both must resume past the
            # recovered snapshot or a SECOND crash rolls back to it
            srv._step_base = int(snap.step)
            srv._last_snap_step = int(snap.step)
            if srv._journal is not None:
                srv._journal.bump_seq(base_seq)
        now = srv.clock()
        with telemetry.collect(srv.registry):
            srv._count("serve.restarts")
            if rstats.dropped:
                srv._count("serve.journal_torn_drops",
                           rstats.dropped)
            srv._gone.update(gone)
            marks: Dict[str, int] = {}
            for sid in order:
                ent = live.get(sid)
                if ent is None:
                    continue
                slo = ent["slo_rem"] if ent["slo_rem"] is not None \
                    else ent["slo"]
                s = _Session(sid, now, slo, ent["blob"])
                s.dedupe_until = delivered.get(sid, 0)
                if ent["blob"] is not None:
                    try:
                        st = resilience.restore_carry(ent["blob"])
                        s.acked = int(st.offset) \
                            + int(st.tail.shape[0])
                    except resilience.CarryCheckpointError:
                        pass
                srv._sessions[sid] = s
                srv._marked[sid] = delivered.get(sid, 0)
                if srv._free:
                    srv._admit(s)
                else:
                    # elastic repack: more live sessions than
                    # surviving lanes — the scheduler's queue takes
                    # the rest, restoring as lanes free
                    srv._queue.append(sid)
                    srv._count("serve.queued")
                srv._j({"ev": "admit", "sid": sid, "slo": slo,
                        "ckpt": srv._b64(
                            ent["blob"]),
                        "delivered": delivered.get(sid, 0)})
                marks[str(sid)] = delivered.get(sid, 0)
                srv.recovered[sid] = {
                    "acked": s.acked,
                    "dedupe_until": s.dedupe_until,
                    "active": s.lane is not None}
            if marks:
                srv._j({"ev": "mark", "d": marks})
            # rider replay: frames emitted before the crash but never
            # durably marked delivered — re-delivered at-least-once
            for entry in (snap.body.get("rider", [])
                          if snap else []):
                sid = entry["sid"]
                if sid not in srv._sessions:
                    continue
                idx = int(entry["idx"])
                if idx <= delivered.get(sid, 0):
                    continue
                fr = durability.decode_frame(entry["frame"])
                srv.replayed.append((sid, fr))
                srv._pending_marks[sid] = max(
                    srv._pending_marks.get(sid, 0), idx)
            if srv.replayed:
                srv._count("serve.replayed", len(srv.replayed))
            srv._gauges()
        return srv

    # -- deadlines / shedding -------------------------------------------

    def _shed_expired(self) -> List[Tuple[Any, Any]]:
        """SLO-aware load shedding, deterministic and attributable:
        every session past its deadline — queued or active — is
        removed NOW, counted under its reason label, and logged
        ``(sid, reason, t)``. Never a silent stall."""
        now = self.clock()
        out: List[Tuple[Any, Any]] = []
        for sid in [q for q in self._queue
                    if self._expired(q, now)]:
            self._queue.remove(sid)
            del self._sessions[sid]
            self._shed(sid, "deadline_queued", now)
        for lane in [ln for ln, sid in self._lane_sid.items()
                     if self._expired(sid, now)]:
            sid = self._lane_sid[lane]
            out += self._release(sid, shed_reason="deadline", t=now)
        return out

    def _expired(self, sid, now: float) -> bool:
        d = self._sessions[sid].deadline
        return d is not None and now > d

    def _shed(self, sid, reason: str, t: float) -> None:
        self._gone[sid] = f"shed:{reason}"
        self._shed_log.append((sid, reason, t))
        self._j({"ev": "shed", "sid": sid,
                 "reason": f"shed:{reason}"})
        self._count("serve.shed", labels={"reason": reason})

    def _release(self, sid, shed_reason: Optional[str] = None,
                 t: Optional[float] = None,
                 counted: Optional[str] = None) -> List:
        """Free a session's lane: drain anything it still rides in
        the in-flight step (attributed before the mapping goes away),
        reset the lane for recycling, and unmap."""
        s = self._sessions[sid]
        lane = s.lane
        out = self._emit(self._rx.reset_stream(lane))
        del self._lane_sid[lane]
        bisect.insort(self._free, lane)
        del self._sessions[sid]
        if shed_reason is not None:
            self._shed(sid, shed_reason, t)
        elif counted is not None:
            self._gone[sid] = counted
            self._j({"ev": "close" if counted == "closed"
                     else "evict", "sid": sid, "reason": counted})
            self._count(f"serve.{counted}")
        return out

    # -- close / evict / drain ------------------------------------------

    def close(self, sid) -> List[Tuple[Any, Any]]:
        """Graceful per-session end: push everything the session
        still has staged, flush its lane (the final zero-padded
        chunk), free the lane, and admit the next queued session.
        Returns the emissions (any session may ride along — the
        in-flight step drains)."""
        self._flush_marks()
        s = self._get_session(sid)
        if s.lane is None:
            # closing a still-QUEUED session: it was never admitted,
            # so it gets its own counter — serve.closed stays in the
            # admitted == closed + evicted + shed_active balance
            self._queue.remove(sid)
            del self._sessions[sid]
            self._gone[sid] = "closed"
            self._j({"ev": "close", "sid": sid, "reason": "closed"})
            self._count("serve.closed_queued")
            return []
        out = []
        while True:
            take = self._take_staged(s, self.cfg.chunk_len)
            if take is None:
                break
            out += self._push({s.lane: take})
        t0 = time.perf_counter()
        got = self._rx.flush_stream(s.lane)
        self._note_steps(time.perf_counter() - t0)
        out += self._emit(got)
        out += self._release(sid, counted="closed")
        self._admit_waiting()
        self._gauges()
        return out

    def evict(self, sid) -> Tuple[Optional[bytes], List, List]:
        """Evict a session, preserving it: checkpoint its lane (the
        in-flight step drains; quarantine rider travels in the blob),
        free the lane, and return ``(blob, emissions,
        staged_slabs)`` — the staged-but-unscheduled slabs hand back
        so the recovering client resubmits them after
        ``connect(sid, checkpoint=blob)``. Evicting a still-QUEUED
        session returns ``(None, [], staged)`` (no lane state
        exists yet)."""
        self._flush_marks()
        s = self._get_session(sid)
        staged = list(s.staged)
        s.staged.clear()
        s.staged_samples = 0
        if s.lane is None:
            # evicting a still-QUEUED session: never admitted, no
            # lane state — own counter, same balance rule as close
            self._queue.remove(sid)
            del self._sessions[sid]
            self._gone[sid] = "evicted"
            self._j({"ev": "evict", "sid": sid, "reason": "evicted"})
            self._count("serve.evicted_queued")
            return None, [], staged
        blob, got = self._rx.checkpoint(s.lane)
        out = self._emit(got)
        out += self._release(sid, counted="evicted")
        self._admit_waiting()
        self._gauges()
        return blob, out, staged

    def drain(self) -> List[Tuple[Any, Any]]:
        """Graceful shutdown: stop admitting (queued sessions are
        shed with reason ``draining`` — they never held device
        state), flush every active session's staged samples and lane,
        drain the in-flight chunk, and close the fleet. Idempotent;
        the final :meth:`stats`/:meth:`scrape` survive it."""
        if self._drained:
            return []
        self._flush_marks()
        self._draining = True
        out = self._take_spill()
        now = self.clock()
        while self._queue:
            sid = self._queue.popleft()
            del self._sessions[sid]
            self._shed(sid, "draining", now)
        for sid in [self._lane_sid[ln]
                    for ln in sorted(self._lane_sid)]:
            out += self.close(sid)
        got = self._rx.flush()
        # the fleet is closed: anything still pending drained above
        out += self._emit(got)
        self._drained = True
        if self._journal is not None:
            # every session closed above; seal the active segment so
            # the directory holds only sealed, replay-clean files
            self._flush_marks()
            self._journal.close()
        self._gauges()
        return out


# ---------------------------------------------------------- load generator


class ClientSpec(NamedTuple):
    """One synthetic client of the load generator: an id, a seeded
    arrival schedule (``[(tick, slab), ...]``), the ground-truth
    stream it was cut from, an optional SLO, and a misbehavior mode
    (``"ok"`` / ``"nan"`` poisoned slab / ``"flood"`` everything at
    tick 0 / ``"stall"`` delivers only the first half then goes
    silent / ``"oversize"`` one protocol-violating giant slab)."""
    sid: Any
    schedule: List
    stream: np.ndarray
    slo_s: Optional[float] = None
    mode: str = "ok"


def synth_load(n_sessions: int, frames_per_session: int = 3,
               n_bytes: int = 12, snr_db: float = 30.0,
               seed: int = 0, add_fcs: bool = True,
               tail: int = 1024, arrival=None,
               misbehave: Optional[Dict[int, str]] = None,
               slo_s: Optional[float] = None,
               channel_profile=None) -> List[ClientSpec]:
    """The many-client load generator (built on
    `link.stream_many_multi`'s arrival schedules): ``n_sessions``
    independent mixed-rate streams cut into seeded ragged slab
    schedules, with ``misbehave`` marking sessions by int index —
    ``{3: "nan"}``-style modes rewrite that session's schedule into
    the corresponding bad-client behavior. Fully deterministic per
    seed. Imports jax (through the PHY) — the jax-free smoke uses its
    own stub traffic instead."""
    from ziria_tpu.phy import link
    from ziria_tpu.phy.wifi.params import RATES

    if arrival is None:
        arrival = link.ArrivalSpec()
    misbehave = dict(misbehave or {})
    rng = np.random.default_rng(seed)
    rates_all = sorted(RATES)
    psdus_per, rates_per = [], []
    for i in range(n_sessions):
        rates = [rates_all[(i + j) % len(rates_all)]
                 for j in range(frames_per_session)]
        rates_per.append(rates)
        psdus_per.append([rng.integers(0, 256, n_bytes)
                          .astype(np.uint8) for _ in rates])
    # channel_profile (name / per-stream list / None -> the
    # ZIRIA_CHANNEL_PROFILE default) rides stream_many_multi's
    # per-stream physical channel: the serving load generator can
    # campaign multipath/SCO/Doppler/burst clients alongside the
    # misbehave modes (the soak harness's multipath-active rounds)
    streams, _starts, schedules = link.stream_many_multi(
        psdus_per, rates_per, snr_db=snr_db, cfo=1e-4, delay=60,
        seed=seed, add_fcs=add_fcs, tail=tail, arrival=arrival,
        channel_profile=channel_profile)

    out = []
    for i in range(n_sessions):
        mode = misbehave.get(i, "ok")
        sched = schedules[i]
        if mode == "flood":
            # everything at once, one giant burst of max-size slabs
            whole = streams[i]
            sched = [(0, whole[a: a + (1 << 14)])
                     for a in range(0, whole.shape[0], 1 << 14)]
        elif mode == "stall":
            sched = sched[: max(1, len(sched) // 2)]
        elif mode == "nan":
            # poison a deterministic slab mid-schedule
            j = len(sched) // 2
            t, bad = sched[j]
            bad = np.array(bad, copy=True)
            bad[:: 7] = np.nan
            sched = sched[:j] + [(t, bad)] + sched[j + 1:]
        elif mode == "oversize":
            t0 = sched[0][0] if sched else 0
            sched = [(t0, np.zeros((1 << 20, 2), np.float32))] + sched
        elif mode != "ok":
            raise ValueError(f"unknown misbehave mode {mode!r}")
        out.append(ClientSpec(f"s{i}", sched, streams[i], slo_s,
                              mode))
    return out


def run_clients(srv: ServeRuntime, clients: List[ClientSpec],
                max_ticks: int = 10000) -> Dict[Any, List]:
    """Drive a client set against a server, tick by tick: connect
    everyone up front (rejected clients retry each tick — the
    backpressure protocol), deliver each schedule's due slabs
    (resubmitting on backpressure), step the scheduler, close
    clients whose schedule is done (stalled clients never close —
    the deadline shed or the drain collects them), then drain.
    Returns ``{sid: [StreamFrame, ...]}`` per session. Deterministic
    for a deterministic server clock."""
    frames: Dict[Any, List] = {c.sid: [] for c in clients}

    def collect(pairs):
        for sid, fr in pairs:
            frames[sid].append(fr)

    # a recovered runtime re-delivers its snapshot rider up front
    # (at-least-once; dedupe by frame.start if exactness matters)
    collect((sid, fr) for sid, fr in srv.replayed
            if sid in frames)

    todo = {c.sid: deque(c.schedule) for c in clients}
    pending = {c.sid: c for c in clients}       # not yet connected
    unclosed = {c.sid: c for c in clients}

    def fast_forward(sid):
        """A RECOVERED session is already live ('duplicate'): resume
        its schedule from the server's acked coordinate — everything
        below it is inside the restored lane state (the documented
        resubmission protocol, docs/robustness.md)."""
        skip = srv.acked(sid)
        q = todo[sid]
        while q and skip > 0:
            t, slab = q[0]
            n = slab.shape[0]
            if n <= skip:
                q.popleft()
                skip -= n
            else:
                q[0] = (t, slab[skip:])
                skip = 0

    tick = 0
    while tick <= max_ticks:
        for sid in list(pending):
            r = srv.connect(sid, slo_s=pending[sid].slo_s)
            if r.admitted or r.queued:
                del pending[sid]
            elif r.reason == "duplicate":
                # recovered session (active, or queued behind the
                # elastic repack): resume, don't re-stream
                fast_forward(sid)
                del pending[sid]
        for c in clients:
            if c.sid in pending:
                continue
            q = todo[c.sid]
            while q and q[0][0] <= tick:
                t, slab = q[0]
                r = srv.submit(c.sid, slab)
                if r.accepted or not r.retry_after_s:
                    q.popleft()     # accepted, or terminally refused
                else:
                    break           # backpressure: retry next tick
        collect(srv.step())
        for done in [s for s, c in unclosed.items()
                     if c.mode != "stall" and not todo[s]
                     and s not in pending]:
            if srv.is_active(done):
                collect(srv.close(done))
                del unclosed[done]
            elif done in srv._gone:
                del unclosed[done]   # shed/evicted — accounted there
            # else: still queued — close once a lane frees it in
        tick += 1
        if not unclosed and not any(todo.values()):
            break
        if all(c.mode == "stall" for c in unclosed.values()) \
                and not any(todo[s] for s in unclosed) \
                and not pending:
            break
    collect(srv.drain())
    return frames


# ------------------------------------------------------------------- CLI


def main(argv=None) -> int:
    """``python -m ziria_tpu serve`` — the serving demo: a synthetic
    many-client load (misbehaving clients included) through the real
    fleet, SIGINT-safe (a ^C drains gracefully and still prints the
    final stats + exposition), chaos-injectable via ``--chaos``."""
    import argparse
    import json
    import sys

    from ziria_tpu.utils import faults

    p = argparse.ArgumentParser(
        prog="ziria_tpu serve",
        description="continuous-batching serving demo "
                    "(docs/serving.md)")
    p.add_argument("--lanes", type=int, default=4,
                   help="device lanes S (compiled fleet width)")
    p.add_argument("--sessions", type=int, default=6,
                   help="client sessions to serve")
    p.add_argument("--frames", type=int, default=2,
                   help="frames per session")
    p.add_argument("--chunk-len", type=int, default=4096)
    p.add_argument("--frame-len", type=int, default=1024)
    p.add_argument("--slo", type=float, default=None,
                   help="per-session deadline seconds (default none)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--nan-client", action="store_true",
                   help="make session 0 push a NaN-poisoned slab "
                        "(quarantine demo)")
    p.add_argument("--chaos", metavar="SPEC", default=None,
                   help="fault-injection spec (utils/faults grammar)")
    p.add_argument("--channel-profile", metavar="NAME[,NAME...]",
                   default=None,
                   help="physical-channel profile(s) for the client "
                        "load (phy/profiles; comma lists cycle per "
                        "session — the multipath/SCO/Doppler/burst "
                        "campaign stimulus, docs/robustness.md)")
    p.add_argument("--metrics-dump", action="store_true",
                   help="print the Prometheus exposition to stderr "
                        "at exit")
    p.add_argument("--snapshot-dir", metavar="DIR", default=None,
                   help="durability directory: write-ahead journal + "
                        "automatic fleet snapshots (docs/robustness.md"
                        "; ServeRuntime.recover(DIR) resumes a "
                        "crashed run)")
    p.add_argument("--snapshot-every", type=int, default=8,
                   metavar="N",
                   help="chunk-steps between automatic snapshots "
                        "(with --snapshot-dir; default 8)")
    p.add_argument("--recover", action="store_true",
                   help="recover the fleet from --snapshot-dir "
                        "instead of starting fresh")
    args = p.parse_args(argv)

    if args.recover and not args.snapshot_dir:
        raise SystemExit("--recover needs --snapshot-dir")
    cfg = ServeConfig(n_lanes=args.lanes, chunk_len=args.chunk_len,
                      frame_len=args.frame_len, check_fcs=True,
                      default_slo_s=args.slo,
                      snapshot_dir=args.snapshot_dir,
                      snapshot_every=args.snapshot_every)
    misbehave = {0: "nan"} if args.nan_client else {}
    if args.channel_profile is not None:
        from ziria_tpu.phy.profiles import parse_profile_spec
        try:
            parse_profile_spec(args.channel_profile)
        except ValueError as e:
            raise SystemExit(f"--channel-profile: {e}")
    clients = synth_load(args.sessions, args.frames, seed=args.seed,
                         misbehave=misbehave, tail=args.frame_len,
                         channel_profile=args.channel_profile)
    chaos = None
    if args.chaos is not None:
        try:
            chaos = faults.parse_chaos_spec(args.chaos)
        except ValueError as e:
            raise SystemExit(f"--chaos: {e}")

    srv = ServeRuntime.recover(args.snapshot_dir, config=cfg) \
        if args.recover else ServeRuntime(cfg)
    frames: Dict[Any, List] = {}
    import contextlib
    try:
        with contextlib.ExitStack() as stack:
            if chaos is not None:
                specs, seed = chaos
                stack.enter_context(faults.inject(*specs, seed=seed))
            stack.enter_context(srv)
            try:
                frames = run_clients(srv, clients)
            except KeyboardInterrupt:
                # SIGINT-safe drain: stop admitting, flush in-flight
                # chunks, fall through to the final stats
                srv.drain()
                frames = {}
    finally:
        st = srv.stats()
        lat = srv.registry.find("serve.chunk_seconds")
        report = {
            "sessions": args.sessions, "lanes": args.lanes,
            "frames": sum(len(v) for v in frames.values()),
            "stats": {k: (list(v) if isinstance(v, tuple) else v)
                      for k, v in st._asdict().items()},
            "chunk_latency_ms": lat.summary(scale=1e3)
            if lat is not None else {"count": 0},
        }
        print(json.dumps(report))
        if args.metrics_dump:
            print("metrics exposition (utils/telemetry):",
                  file=sys.stderr)
            print(srv.scrape(), file=sys.stderr, end="")
    return 0
