"""Crash durability for the serving runtime: a write-ahead journal of
state transitions plus atomic fleet snapshots (docs/robustness.md,
ISSUE 14).

PRs 12-13 made the serving runtime *fault*-tolerant — bad input,
flaky dispatches, hangs are contained in-process — but a ``kill -9``,
an OOM, or a device loss still destroyed every live session: all the
host control-point state (session table, lane carries, dedupe sets)
lived in Python memory. Ziria's discipline keeps the steady-state
stream on the engine and the host at control points; this module
makes those control points *durable*, so the whole fleet survives the
process:

- **Journal** appends CRC-framed records (``ZWAL`` magic + length +
  CRC32 + JSON payload) of every serve-runtime transition —
  admit/queue/shed/evict/close plus per-session delivery watermarks —
  to segment files. The ACTIVE segment (``wal-<firstseq>.open``) is
  append+fsync; ROTATION seals it atomically (fsync, close, rename to
  ``wal-<firstseq>.log`` — a reader never sees a half-sealed
  segment). Replay (:func:`replay`) tolerates a torn tail and even
  mid-segment garbage: a record that fails its length/CRC/JSON gate
  is dropped and the scanner RESYNCS on the next magic, so one torn
  write (an injected ``io_torn``, a crash mid-append) never corrupts
  the records around it.
- **Snapshots** (:func:`write_snapshot`) persist the whole fleet at a
  chunk-step boundary: every lane's checkpoint blob (the
  ``ziria-stream-carry-v1`` format, CRC field included), the
  undelivered-frame rider, and a CRC'd ``meta.json`` (session table,
  journal watermark) — written into a temp directory, fsync'd file by
  file, then atomically ``rename``\\ d to ``snap-<step>``. A crash at
  ANY byte leaves either the previous snapshot or the new one, never
  a half-written directory (half-written temps are ignored and
  garbage-collected). :func:`load_snapshot` walks newest-first and
  falls back past any snapshot that fails validation.
- **Recovery** composes the two: ``ServeRuntime.recover(dir)``
  (runtime/serve.py) loads the newest valid snapshot, replays journal
  records past its watermark to reconstruct the session table
  exactly, and restores every lane blob — emissions after the
  snapshot replay at-least-once, deduped by the journaled delivery
  watermarks (the pinned dedupe window, docs/robustness.md).

Every byte written here passes the chaos layer's IO seam
(``faults.io_fault``: ``io_torn`` truncated writes, ``io_enospc``
full-disk errors), so the soak harness (tools/soak.py) can prove the
recovery path against the exact failure modes it exists for. The
module imports no jax — `tools/durability_smoke.py` exercises all of
it against a stub receiver in milliseconds.
"""

from __future__ import annotations

import base64
import json
import os
import shutil
import struct
import zlib
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

from ziria_tpu.utils import faults

#: journal record frame: MAGIC + uint32 LE payload length +
#: uint32 LE CRC32(payload) + payload (JSON, carries its seq as "q")
MAGIC = b"ZWAL"
_HDR = struct.Struct("<II")

#: refuse absurd record lengths during resync — a garbage length
#: field must not make the scanner skip a segment's worth of records
MAX_RECORD = 1 << 24

#: snapshot manifest format tag (bump on incompatible layout change)
SNAP_FORMAT = "ziria-serve-snap-v1"


class JournalError(RuntimeError):
    """The journal directory is unusable (not: a torn record — torn
    records are dropped cleanly and counted, never raised)."""


class ReplayStats(NamedTuple):
    """What :func:`replay` saw: valid records returned, distinct
    garbage regions dropped (torn tails, injected torn writes), and
    segments read."""
    records: int
    dropped: int
    segments: int


def _fsync_dir(d: str) -> None:
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _frame(payload: bytes) -> bytes:
    return MAGIC + _HDR.pack(len(payload),
                             zlib.crc32(payload) & 0xFFFFFFFF) \
        + payload


def _segments(dirpath: str) -> List[Tuple[int, str]]:
    """(firstseq, path) for every journal segment, sealed and open,
    sorted by first sequence number."""
    out = []
    try:
        names = os.listdir(dirpath)
    except FileNotFoundError:
        return []
    for n in names:
        if n.startswith("wal-") and (n.endswith(".log")
                                     or n.endswith(".open")):
            try:
                first = int(n[4:].split(".")[0])
            except ValueError:
                continue
            out.append((first, os.path.join(dirpath, n)))
    out.sort()
    return out


def _scan_segment(path: str):
    """Parse one segment with RESYNC: yield (record, end_offset);
    return (records, dropped_regions, clean_end). A record failing
    its magic/length/CRC/JSON gate is skipped and scanning resumes at
    the next magic — a torn last record is simply never yielded."""
    with open(path, "rb") as f:
        data = f.read()
    recs: List[dict] = []
    dropped = 0
    in_garbage = False
    pos = 0
    clean_end = 0
    n = len(data)
    while pos < n:
        m = data.find(MAGIC, pos)
        if m < 0:
            if not in_garbage:
                dropped += 1
            break
        if m > pos and not in_garbage:
            dropped += 1
            in_garbage = True
        hdr_end = m + len(MAGIC) + _HDR.size
        if hdr_end > n:
            if not in_garbage:
                dropped += 1
            break
        ln, crc = _HDR.unpack(data[m + len(MAGIC): hdr_end])
        end = hdr_end + ln
        if ln > MAX_RECORD or end > n:
            if not in_garbage:
                dropped += 1
                in_garbage = True
            pos = m + 1
            continue
        payload = data[hdr_end:end]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            if not in_garbage:
                dropped += 1
                in_garbage = True
            pos = m + 1
            continue
        try:
            ev = json.loads(payload.decode())
        except Exception:
            if not in_garbage:
                dropped += 1
                in_garbage = True
            pos = m + 1
            continue
        recs.append(ev)
        in_garbage = False
        pos = end
        clean_end = end
    return recs, dropped, clean_end


class Journal:
    """Append-only CRC-framed write-ahead journal over segment files.

    One writer per directory (the serving process). Construction
    SEALS any leftover ``.open`` segment from a crashed predecessor —
    its torn tail (if any) is truncated away, the valid prefix
    renamed to a sealed ``.log`` — and the sequence counter resumes
    past every record on disk, so a recovered runtime keeps
    journaling into the same directory without ever rewriting
    history. ``append`` raises ``OSError`` on a genuinely failed
    write (ENOSPC — injected or real); the serving runtime contains
    that (counted, journaling continues best-effort) rather than
    crashing the fleet over a full disk."""

    def __init__(self, dirpath: str, segment_records: int = 256,
                 fsync: bool = True):
        if segment_records < 1:
            raise ValueError(
                f"segment_records {segment_records} must be >= 1")
        self.dir = dirpath
        self.segment_records = int(segment_records)
        self.fsync = bool(fsync)
        os.makedirs(dirpath, exist_ok=True)
        last = 0
        for first, path in _segments(dirpath):
            recs, _d, clean_end = _scan_segment(path)
            if recs:
                last = max(last, max(int(r.get("q", 0))
                                     for r in recs))
            if path.endswith(".open"):
                # a crashed writer's active segment: truncate the
                # torn tail, seal the valid prefix atomically
                sealed = path[: -len(".open")] + ".log"
                if clean_end == 0:
                    os.unlink(path)
                    continue
                with open(path, "rb+") as f:
                    f.truncate(clean_end)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(path, sealed)
        _fsync_dir(dirpath)
        self._seq = last
        self._f = None
        self._records_in_segment = 0

    @property
    def seq(self) -> int:
        """Sequence number of the last appended (or on-disk) record."""
        return self._seq

    def bump_seq(self, floor: int) -> None:
        """Raise the sequence counter to at least ``floor`` — the
        recovery path calls this with the recovered snapshot's
        journal watermark. Without it, a journal whose segments were
        all pruned by that snapshot would restart numbering BELOW
        the watermark, and the NEXT recovery's ``replay(after_seq=
        watermark)`` would silently drop every post-recovery record
        (resurrected sessions, lost delivery marks)."""
        self._seq = max(self._seq, int(floor))

    def _open_segment(self) -> None:
        # called from append() AFTER the record's seq was assigned:
        # the segment is named by its first record's sequence number
        first = self._seq
        path = os.path.join(self.dir, f"wal-{first:012d}.open")
        self._f = open(path, "wb")
        self._path = path
        self._records_in_segment = 0

    def _seal(self) -> None:
        if self._f is None:
            return
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self._f = None
        sealed = self._path[: -len(".open")] + ".log"
        os.replace(self._path, sealed)
        _fsync_dir(self.dir)

    def append(self, event: dict) -> int:
        """Durably append one record; returns its sequence number.
        The frame passes the chaos IO seam (site ``journal.append``)
        — an injected ``io_torn`` lands a torn record that replay
        drops and resyncs past; ``io_enospc`` raises to the caller."""
        self._seq += 1
        ev = dict(event)
        ev["q"] = self._seq
        payload = json.dumps(ev, sort_keys=True).encode()
        frame = faults.io_fault("journal.append", _frame(payload))
        if self._f is None:
            self._open_segment()
        try:
            self._f.write(frame)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())
        except OSError:
            # the active segment may now hold a partial frame; replay
            # resyncs past it, and the NEXT append starts clean after
            # whatever landed — never rewrite history in place
            raise
        self._records_in_segment += 1
        if self._records_in_segment >= self.segment_records:
            self._seal()
        return self._seq

    def prune(self, upto_seq: int) -> int:
        """Delete SEALED segments every record of which is covered by
        ``upto_seq`` (a snapshot's journal watermark) — replay after
        the snapshot never needs them. Returns segments deleted."""
        segs = _segments(self.dir)
        deleted = 0
        for i, (first, path) in enumerate(segs):
            if path.endswith(".open"):
                continue
            nxt = segs[i + 1][0] if i + 1 < len(segs) \
                else self._seq + 1
            if nxt - 1 <= upto_seq:
                os.unlink(path)
                deleted += 1
        if deleted:
            _fsync_dir(self.dir)
        return deleted

    def close(self) -> None:
        """Seal the active segment (idempotent)."""
        self._seal()


def replay(dirpath: str,
           after_seq: int = 0) -> Tuple[List[dict], ReplayStats]:
    """Read every valid journal record with sequence > ``after_seq``,
    in order. Torn records — a truncated tail from a crash or an
    injected ``io_torn`` — are dropped cleanly and counted; records
    around them survive (the resync scan). An absent directory is an
    empty journal."""
    recs: List[dict] = []
    dropped = 0
    segs = _segments(dirpath)
    for _first, path in segs:
        r, d, _end = _scan_segment(path)
        recs.extend(r)
        dropped += d
    recs = [r for r in recs if int(r.get("q", 0)) > after_seq]
    recs.sort(key=lambda r: int(r.get("q", 0)))
    return recs, ReplayStats(len(recs), dropped, len(segs))


# ----------------------------------------------------------- snapshots


def _write_file(path: str, data: bytes, site: str,
                do_fsync: bool = True) -> None:
    data = faults.io_fault(site, data)
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        if do_fsync:
            os.fsync(f.fileno())


class Snapshot(NamedTuple):
    """One loaded fleet snapshot: the chunk-step it was taken at, the
    per-lane checkpoint blobs, and the manifest body the serving
    runtime wrote (session table, journal watermark, rider)."""
    step: int
    lanes: Dict[int, bytes]
    body: dict
    path: str


def snapshot_name(step: int) -> str:
    return f"snap-{step:010d}"


def write_snapshot(root: str, step: int, lanes: Dict[int, bytes],
                   body: dict, keep: int = 2) -> str:
    """Persist one fleet snapshot ATOMICALLY: lane blobs + a CRC'd
    ``meta.json`` manifest land in a temp directory (each file
    fsync'd, each write through the chaos IO seam), the directory is
    fsync'd, then ``rename``\\ d into place — a reader (and a crash)
    sees the whole snapshot or none of it. Older snapshots beyond
    ``keep`` are pruned; stale temp directories from crashed writers
    are garbage-collected. Returns the final snapshot path."""
    os.makedirs(root, exist_ok=True)
    final = os.path.join(root, snapshot_name(step))
    tmp = os.path.join(root, f".tmp-{snapshot_name(step)}.{os.getpid()}")
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        lane_names = {}
        for i, blob in sorted(lanes.items()):
            name = f"lane-{int(i):04d}.ckpt"
            lane_names[str(int(i))] = name
            _write_file(os.path.join(tmp, name), bytes(blob),
                        "snapshot.lane")
        full = {"fmt": SNAP_FORMAT, "step": int(step),
                "lanes": lane_names, "body": body}
        payload = json.dumps(full, sort_keys=True).encode()
        manifest = json.dumps(
            {"crc": zlib.crc32(payload) & 0xFFFFFFFF,
             "payload": payload.decode()}).encode()
        _write_file(os.path.join(tmp, "meta.json"), manifest,
                    "snapshot.meta")
        _fsync_dir(tmp)
        if os.path.isdir(final):
            # same-step overwrite: move the old snapshot ASIDE (to a
            # loader-invisible name) before renaming the new one in —
            # never rmtree-then-rename, which a crash in between
            # would turn into "neither version survives"
            aside = os.path.join(
                root, f".old-{snapshot_name(step)}.{os.getpid()}")
            if os.path.isdir(aside):
                shutil.rmtree(aside)
            os.replace(final, aside)
            os.replace(tmp, final)
            shutil.rmtree(aside, ignore_errors=True)
        else:
            os.replace(tmp, final)
        _fsync_dir(root)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    _prune_snapshots(root, keep)
    return final


def _snapshot_dirs(root: str, prefix: str = "snap-"
                   ) -> List[Tuple[int, str]]:
    out = []
    try:
        names = os.listdir(root)
    except FileNotFoundError:
        return []
    for n in names:
        if n.startswith(prefix):
            try:
                step = int(n[len(prefix):].split(".")[0])
            except ValueError:
                continue
            p = os.path.join(root, n)
            if os.path.isdir(p):
                out.append((step, p))
    out.sort()
    return out


def _prune_snapshots(root: str, keep: int) -> None:
    snaps = _snapshot_dirs(root)
    for _step, p in snaps[: max(0, len(snaps) - keep)]:
        shutil.rmtree(p, ignore_errors=True)
    for n in os.listdir(root):
        if n.startswith(".tmp-snap-") or n.startswith(".old-snap-"):
            # a crashed writer's temp (never renamed in) or aside
            # (already superseded): garbage whatever it contains
            shutil.rmtree(os.path.join(root, n), ignore_errors=True)


def _load_one(step: int, path: str) -> Snapshot:
    with open(os.path.join(path, "meta.json"), "rb") as f:
        manifest = json.loads(f.read().decode())
    payload = manifest["payload"].encode()
    if zlib.crc32(payload) & 0xFFFFFFFF != int(manifest["crc"]):
        raise JournalError(f"{path}: manifest CRC mismatch")
    full = json.loads(payload.decode())
    if full.get("fmt") != SNAP_FORMAT:
        raise JournalError(
            f"{path}: snapshot format {full.get('fmt')!r} != "
            f"{SNAP_FORMAT!r}")
    lanes = {}
    for i, name in full["lanes"].items():
        with open(os.path.join(path, name), "rb") as f:
            lanes[int(i)] = f.read()
    return Snapshot(int(full["step"]), lanes, full["body"], path)


def load_snapshot(root: str) -> Optional[Snapshot]:
    """The newest snapshot that VALIDATES (manifest present, CRC
    good, every listed lane file readable) — walking past any that
    does not, because a snapshot that cannot be trusted whole must
    not be restored in part. Falls back to ``.old-snap-*`` asides as
    a last resort: a crash INSIDE a same-step overwrite (old moved
    aside, new not yet renamed in) leaves the previous complete
    snapshot there, and it must stay loadable — the all-or-nothing
    guarantee has no window. None when no usable snapshot exists
    (recovery then starts from the journal alone)."""
    for step, path in reversed(_snapshot_dirs(root)):
        try:
            return _load_one(step, path)
        except Exception:
            continue
    for step, path in reversed(_snapshot_dirs(root, ".old-snap-")):
        try:
            return _load_one(step, path)
        except Exception:
            continue
    return None


# ------------------------------------------- frame rider serialization
#
# A snapshot's drain (and the delivery-mark lag, docs/robustness.md)
# leaves frames that are EMITTED by the receiver — so its restored
# carry will never re-emit them — but not yet durably marked
# delivered. Those ride the snapshot verbatim ("the rider") and are
# re-delivered on recovery: at-least-once, deduped by the journaled
# delivery watermark, never silently lost.


def encode_frame(frame) -> dict:
    """StreamFrame -> JSON-safe dict (psdu bits as base64)."""
    r = frame.result
    psdu = None
    if getattr(r, "psdu_bits", None) is not None:
        import numpy as np
        a = np.asarray(r.psdu_bits, np.uint8)
        psdu = base64.b64encode(a.tobytes()).decode()
    return {"start": int(frame.start), "ok": bool(r.ok),
            "rate": int(r.rate_mbps), "len": int(r.length_bytes),
            "psdu": psdu,
            "crc": None if r.crc_ok is None else bool(r.crc_ok)}


def decode_frame(d: dict):
    """The inverse of :func:`encode_frame` (imports the PHY types
    lazily — rider decode only happens in real-fleet recovery, where
    jax is already resident)."""
    import numpy as np

    from ziria_tpu.backend.framebatch import StreamFrame
    from ziria_tpu.phy.wifi.rx import RxResult

    psdu = None
    if d.get("psdu") is not None:
        psdu = np.frombuffer(base64.b64decode(d["psdu"]), np.uint8)
    return StreamFrame(int(d["start"]), RxResult(
        bool(d["ok"]), int(d["rate"]), int(d["len"]), psdu,
        None if d.get("crc") is None else bool(d["crc"])))
