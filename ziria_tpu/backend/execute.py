"""Execute a lowered pipeline over a finite input stream.

The analogue of the reference's driver main loop (SURVEY.md §3.2): where
that loop ticks the compiled state machine once per (vectorized) chunk,
this packs the bulk of the stream into a ``(T, chunk, ...)`` array and
runs one ``lax.scan`` over it inside a single jit — the host touches the
data twice (feed, fetch), everything in between stays on device.

Tail semantics match the reference's *vectorized* mode: input that doesn't
fill a whole steady-state iteration produces no output (the vectorized
read fails at EOF and the pipeline terminates). Full iterations beyond the
last bulk chunk are processed by a width-1 step so no whole iteration is
dropped; the interpreter oracle agrees with this on any input whose length
is a multiple of the steady-state take count.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ziria_tpu.core import ir
from ziria_tpu.backend.lower import Lowered, LowerError, lower


def _jit_step(lowered: Lowered):
    return jax.jit(lowered.step)


def _jit_scan(lowered: Lowered):
    return jax.jit(lowered.scan_steps())


def run_jit(comp: ir.Comp, inputs, width: Optional[int] = None,
            target_items: int = 8192, optimize: bool = False) -> np.ndarray:
    """Run pipeline `comp` over `inputs` (array, leading axis = stream) on
    the jit backend; returns the output stream as a numpy array.

    `optimize=True` runs the fold/fusion pass (core/opt.py) first — the
    reference's `--fold` flag; output is invariant (tested) but folded
    programs can lower where raw ones can't (const branches) and fuse to
    fewer stages."""
    ys, _ = run_jit_carry(comp, inputs, width=width,
                          target_items=target_items, optimize=optimize)
    return ys


def run_jit_carry(comp: ir.Comp, inputs, carry=None,
                  width: Optional[int] = None, target_items: int = 8192,
                  optimize: bool = False, stats_out: Optional[dict] = None):
    """Like run_jit, but stream-resumable: returns ``(outputs, carry)``
    where carry is ``{"stages": <per-stage state pytree>, "leftover":
    <input items not yet forming a full steady-state iteration>}``.
    Feeding a stream in pieces with the carry threaded through produces
    exactly the one-shot output for ANY chunk boundaries — sub-iteration
    remainders ride along in "leftover" instead of being dropped (the
    vectorized-EOF drop applies only to the true end of stream). This is
    the basis of the runtime's checkpoint/resume (runtime/state.py). The
    carry's structure is width-independent, so chunk sizes may differ
    call to call."""
    if optimize:
        from ziria_tpu.core.opt import fold
        comp = fold(comp)
    inputs = np.asarray(inputs)
    stage_carry = None
    if carry is not None:
        if isinstance(carry, dict):
            if "stages" not in carry:
                raise ValueError(
                    "carry dict has no 'stages' key — not a "
                    "run_jit_carry/load_state carry (malformed "
                    "checkpoint?)")
            stage_carry = carry["stages"]
            lef = carry.get("leftover")
            lef = np.empty(0) if lef is None else np.asarray(lef)
            if lef.size:
                # the leftover's dtype/item-shape are authoritative (it
                # came from the same stream); never silently cast in a
                # lossy direction
                if inputs.shape[0] == 0:
                    inputs = lef
                elif inputs.shape[1:] != lef.shape[1:]:
                    raise ValueError(
                        f"resumed chunk item shape {inputs.shape[1:]} "
                        f"does not match the checkpoint leftover's "
                        f"{lef.shape[1:]}")
                else:
                    if inputs.dtype != lef.dtype and not np.can_cast(
                            inputs.dtype, lef.dtype, casting="safe"):
                        raise ValueError(
                            f"resumed chunk dtype {inputs.dtype} cannot "
                            f"be losslessly cast to the checkpoint "
                            f"leftover's {lef.dtype}; cast the chunk "
                            f"explicitly if the narrowing is intended")
                    inputs = np.concatenate(
                        [lef, inputs.astype(lef.dtype, copy=False)],
                        axis=0)
        else:                       # bare stage pytree (no leftover)
            stage_carry = carry
    big = lower(comp, width=width, target_items=target_items)
    n_iters = inputs.shape[0] // big.ss.take
    if stats_out is not None:
        # the executed plan, from the executor's own arithmetic (the CLI
        # --stats report prints this rather than re-deriving the split)
        n_bulk0 = n_iters // big.width
        stats_out.update(
            width=big.width, take=big.take, emit=big.emit,
            labels=big.labels, reps=big.ss.reps, n_iters=n_iters,
            bulk_steps=n_bulk0, remainder_iters=n_iters - n_bulk0
            * big.width)
    outs = []

    if stage_carry is None:
        carry = big.init_carry
    else:
        carry = jax.tree.map(jnp.asarray, stage_carry)
    from ziria_tpu.utils import dispatch

    n_bulk = n_iters // big.width
    if n_bulk:
        scan_fn = _jit_scan(big)
        bulk = inputs[: n_bulk * big.take].reshape(
            (n_bulk, big.take) + inputs.shape[1:])
        with dispatch.timed("execute.scan_bulk"):
            carry, ys = scan_fn(carry, jnp.asarray(bulk))
        ys = np.asarray(ys)
        outs.append(ys.reshape((n_bulk * big.emit,) + ys.shape[2:]))

    rem_iters = n_iters - n_bulk * big.width
    if rem_iters:
        # one scan of the width-1 step over all remaining full iterations;
        # carry pytree structure is width-independent (scan carries don't
        # depend on the number of firings), so the bulk carry threads on
        small = lower(comp, width=1)
        pos = n_bulk * big.take
        rem = inputs[pos: pos + rem_iters * small.take].reshape(
            (rem_iters, small.take) + inputs.shape[1:])
        with dispatch.timed("execute.scan_rem"):
            carry, ys = _jit_scan(small)(carry, jnp.asarray(rem))
        ys = np.asarray(ys)
        outs.append(ys.reshape((rem_iters * small.emit,) + ys.shape[2:]))

    leftover = inputs[n_iters * big.ss.take:]
    carry_out = {"stages": carry, "leftover": np.asarray(leftover)}
    if not outs:
        # no full steady-state iteration: no output yet; the items wait
        # in leftover (they are only dropped at true end-of-stream — the
        # vectorized-EOF rule). Item shape of the output is unknown
        # without running, so report 0 items with the input's item shape
        return np.empty((0,) + inputs.shape[1:]), carry_out
    return np.concatenate(outs, axis=0), carry_out


def run_vect(comp: ir.Comp, inputs, plan=None, optimize: bool = False,
             item_bytes: int = 4) -> np.ndarray:
    """Run a pipeline under the vectorizer's plan (core/vectorize.py).

    Static segments run fused under jit at their searched widths;
    dynamic segments (no static cardinality) run under the hybrid
    executor (interpreter-driven control, heavy do-blocks jitted) —
    the host boundary between segments is the mitigator. A fully
    static pipeline degenerates to ``run_jit`` at the planned width; a
    fully dynamic one to the hybrid executor. This is the executable
    form of the reference's "vectorize what you can, skip what you
    can't" (SURVEY.md §2.1 Vectorize).
    """
    from ziria_tpu.core.vectorize import vectorize

    if optimize:
        from ziria_tpu.core.opt import fold
        comp = fold(comp)
    if plan is None:
        plan = vectorize(comp, item_bytes=item_bytes)
    stream = np.asarray(inputs)
    for seg in plan.segments:
        if seg.dynamic:
            # dynamic segments run under the interpreter driver, but
            # with their heavy do-blocks jit-compiled (backend/hybrid)
            # — the mitigator boundary stays a host boundary, the math
            # inside still reaches XLA
            from ziria_tpu.backend.hybrid import run_hybrid
            stream = run_hybrid(seg.comp, stream).out_array()
        else:
            stream = run_jit(seg.comp, stream, width=seg.width)
        if stream.shape[0] == 0:
            return stream
    return stream
