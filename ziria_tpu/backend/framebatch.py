"""Frame batching for chunked state machines: N independent streams,
one device call per step.

The reference ran one PHY pipeline per thread and scaled frames by
adding threads (SURVEY.md §2.2 thread separators); a TPU behind a host
link scales the other way — batch the *device work* of many frames into
single calls so the per-call round-trip (tens of ms through the axon
tunnel) amortizes across frames. The library receiver already does this
with a leading frame axis (phy/wifi/rx.py). This module gives the same
economics to ANY compiled `.zir` program (VERDICT r3 next #3): a
1000-byte DSL receive costs ~8 device calls; 16 frames through
`run_many` cost ~the same 8 vmapped calls, not 128.

Design — continuation batching over the interpreter:

- each frame runs the normal interpreter/hybrid executor in its own
  thread (host control flow stays per-frame Python: divergent rates,
  ragged lengths, interpreter EOF tails all Just Work);
- when a frame's `_ChunkLoop` needs a device step it *parks* its
  request in the shared :class:`StepBatcher` (`chunked._step_call`
  routes here via a thread-local);
- when every unfinished frame is parked, the quorum thread fires:
  requests are grouped by (machine, jit key, operand shapes), each
  group's operands are stacked and run through ONE `jax.vmap`-ped step
  — JAX's `lax.while_loop` batching rule executes while ANY lane's
  guard holds and `select`s per-lane carries, so lanes consume their
  own cursors/iteration counts and bit-exactness per lane is preserved
  — and every parked frame resumes with its lane of the result.

Frames that drift to different program points simply land in different
groups (two smaller calls); frames in lockstep — the common case for
same-shape captures — ride one call. Lane counts are padded to the
next power of two (lane 0 repeated) so XLA compiles O(log N) batched
variants, not one per group size.
"""

from __future__ import annotations

import threading
from typing import Any, List, NamedTuple, Optional, Sequence

import numpy as np

from ziria_tpu.backend import chunked as C
from ziria_tpu.core import ir
from ziria_tpu.utils import geometry as _geometry
from ziria_tpu.utils.dispatch import pad_lanes, pow2_ceil


def _shape_sig(args):
    import jax
    return tuple(
        (tuple(np.shape(x)), np.asarray(x).dtype.str) if not hasattr(
            x, "aval") else (tuple(x.shape), x.dtype.str)
        for x in jax.tree_util.tree_leaves(args))


class _Req:
    __slots__ = ("node", "key", "args", "done", "result", "exc")

    def __init__(self, node, key, args):
        self.node = node
        self.key = key
        self.args = args
        self.done = False
        self.result = None
        self.exc: Optional[BaseException] = None


class StepBatcher:
    """Collects concurrent chunk-step requests from frame threads and
    services them in vmapped groups. `device_calls` counts actual
    device dispatches (one per fired group) — the number the frame-
    batching contract is about."""

    def __init__(self, n_frames: int):
        self._cv = threading.Condition()
        self._active = n_frames
        self._parked: List[_Req] = []
        self._vfns = {}
        self.device_calls = 0
        self.group_sizes: List[int] = []   # fired lane counts (stats)

    # -- frame lifecycle ------------------------------------------------

    def frame_finished(self) -> None:
        with self._cv:
            self._active -= 1
            if self._parked and len(self._parked) >= self._active:
                self._fire_locked()

    # -- the park point (called from chunked._step_call) ---------------

    def call(self, node, key, args):
        req = _Req(node, key, args)
        with self._cv:
            self._parked.append(req)
            if len(self._parked) >= self._active:
                self._fire_locked()
            while not req.done:
                self._cv.wait()
        if req.exc is not None:
            raise req.exc
        return req.result

    # -- firing ---------------------------------------------------------

    def _vfn(self, node, key):
        import jax
        k = (id(node), key)
        f = self._vfns.get(k)
        if f is None:
            f = jax.jit(jax.vmap(node._steps[key]))
            self._vfns[k] = f
        return f

    def _fire_locked(self) -> None:
        batch, self._parked = self._parked, []
        try:
            self._service(batch)
        finally:
            # every parked thread MUST wake whatever happened above —
            # a request left done=False would wait forever
            for r in batch:
                if not r.done:
                    if r.exc is None and r.result is None:
                        r.exc = RuntimeError(
                            "step batch aborted before this lane ran")
                    r.done = True
            self._cv.notify_all()

    def _service(self, batch: List[_Req]) -> None:
        import jax
        import jax.numpy as jnp

        groups = {}
        for r in batch:
            sig = (id(r.node), r.key, _shape_sig(r.args))
            groups.setdefault(sig, []).append(r)
        for reqs in groups.values():
            try:
                if len(reqs) == 1:
                    r = reqs[0]
                    r.result = r.node._fns[r.key](*r.args)
                else:
                    lanes = len(reqs)
                    padded = pad_lanes(reqs)
                    stacked = jax.tree_util.tree_map(
                        lambda *xs: jnp.stack(xs),
                        *[r.args for r in padded])
                    it_b, pos_b, out_n_b, out_buf_b, rvals_b = \
                        self._vfn(reqs[0].node, reqs[0].key)(*stacked)
                    # every lane's (it, pos, out_n) in ONE transfer,
                    # and every lane's emitted prefix in one more: per
                    # -lane scalar reads and per-lane buffer flushes
                    # through a high-latency host link would cost a
                    # round trip each and dwarf the batched call
                    metas = np.asarray(jnp.stack(
                        [it_b, pos_b, out_n_b], axis=1))
                    bufs = None
                    if getattr(out_buf_b, "ndim", 0) >= 2:
                        max_k = int(metas[:lanes, 2].max())
                        if max_k:
                            bufs = np.asarray(
                                out_buf_b[:lanes, :max_k])
                    for i, r in enumerate(reqs):
                        ob = bufs[i] if bufs is not None \
                            else out_buf_b[i]
                        r.result = (metas[i, 0], metas[i, 1],
                                    metas[i, 2], ob,
                                    jax.tree_util.tree_map(
                                        lambda x, i=i: x[i], rvals_b))
                C.STATS["device_calls"] += 1
                self.device_calls += 1
                from ziria_tpu.utils import dispatch
                dispatch.record("framebatch.step")
                self.group_sizes.append(len(reqs))
            except Exception:
                # a vmap-only failure must not abort frames whose
                # per-frame step is fine (or worse, mark the shared
                # machine broken): retry each lane unbatched; only a
                # lane whose OWN direct call fails gets the exception
                for r in reqs:
                    try:
                        r.result = r.node._fns[r.key](*r.args)
                        C.STATS["device_calls"] += 1
                        self.device_calls += 1
                        from ziria_tpu.utils import dispatch
                        dispatch.record("framebatch.step")
                        self.group_sizes.append(1)
                    except Exception as le:
                        r.exc = le
            for r in reqs:
                r.done = True


def batched_acquire_enabled(batched_acquire: Optional[bool] = None) -> bool:
    """The ONE reading of the --batched-acquire / ZIRIA_BATCHED_ACQUIRE
    knob (default ON): whether `receive_many` runs the one-dispatch
    vmapped acquisition front end or the host-driven per-capture loop.
    Hoisted out of `receive_many`'s body by the jaxlint R4 audit — the
    single-reader discipline every other knob here already follows."""
    import os

    if batched_acquire is not None:
        return batched_acquire
    return os.environ.get("ZIRIA_BATCHED_ACQUIRE", "1") != "0"


def receive_many(captures: Sequence[Any], check_fcs: bool = False,
                 max_samples: int = 1 << 16,
                 viterbi_window: int = None,
                 viterbi_metric: str = None,
                 viterbi_radix: int = None,
                 batched_acquire: Optional[bool] = None,
                 sco_track: Optional[bool] = None,
                 fused_demap: Optional[bool] = None) -> List[Any]:
    """Frame-batched library receiver: N independent captures -> N
    :class:`rx.RxResult`s in O(1) device dispatches — acquire ->
    gather -> mixed-rate decode:

    1. **acquire** (`rx.acquire_many`): STS detect, LTS peak-pick,
       CFO, on-device alignment, and SIGNAL decode for ALL lanes as
       ONE vmapped dispatch; the host does only the integer header
       parsing and the symbol-bucket choice.
    2. **gather** (`rx.gather_segments_many`): every decodable lane's
       data region sliced at its own offset and derotated by its own
       CFO phase at ONE common symbol bucket — one dispatch, output
       device-resident.
    3. **decode** (`rx.decode_data_mixed`): the one-``lax.switch``
       mixed-rate DATA decode — lanes with DIFFERENT rates share the
       same device call and the same Pallas Viterbi batch.

    ``batched_acquire=False`` (or env ``ZIRIA_BATCHED_ACQUIRE=0``)
    falls back to the host-driven per-capture acquisition loop (~3
    round trips per capture — the pre-batched oracle). Either way,
    results are bit-identical to per-capture ``rx.receive`` lane for
    lane, including no-detect / bad-parity / truncated lanes; lane
    counts pad to the next power of two (lane 0 repeated) so XLA
    compiles O(log N) batch variants.

    ``viterbi_radix=4`` runs the mixed decode's Pallas ACS two trellis
    steps per iteration (bit-identical); ``fused_demap=True`` (env
    ``ZIRIA_FUSED_DEMAP``) runs the rate-SWITCHED fused front end —
    the stacked 8-rate constant bank row-selected in-kernel, LLRs
    never leaving VMEM (rx.viterbi_decode_mixed_fused) — on the same
    one-dispatch mixed decode, bit-identical lane for lane.
    """
    import jax.numpy as jnp

    from ziria_tpu.phy.wifi import rx as _rx

    batched_acquire = batched_acquire_enabled(batched_acquire)
    sco_track = _rx.sco_track_enabled(sco_track)
    fused_demap = _rx.fused_demap_enabled(fused_demap)

    results: List[Any] = [None] * len(captures)
    if batched_acquire:
        results, x_dev, acqs = _rx.acquire_many(captures, max_samples)
    else:
        acqs = []
        for i, s in enumerate(captures):
            res, acq = _rx._acquire_frame(s, max_samples)
            if acq is None:
                results[i] = res
            else:
                acqs.append((i, acq))
    if not acqs:
        return results

    # one common bucket = one compiled geometry for the whole batch;
    # smaller frames pay pad symbols (zero-LLR erasures), not a second
    # compile or a second dispatch
    n_sym_b = max(_rx._sym_bucket(a.n_sym) for _i, a in acqs)
    padded = pad_lanes(acqs)
    if batched_acquire:
        segs = _rx.gather_segments_many(
            x_dev, [a for _i, a in padded], n_sym_b)
    else:
        segs = jnp.stack([_rx._padded_segment(a, n_sym_b)
                          for _i, a in padded])
    return _mixed_decode_tail(acqs, padded, segs, n_sym_b, results,
                              check_fcs, viterbi_window, viterbi_metric,
                              viterbi_radix, sco_track, fused_demap)


def _mixed_decode_tail(acqs, padded, segs, n_sym_b: int,
                       results: List[Any], check_fcs: bool,
                       viterbi_window, viterbi_metric,
                       viterbi_radix=None, sco_track: bool = False,
                       fused_demap: bool = False):
    """The shared tail of every batched receive surface: ONE
    mixed-rate decode dispatch over the lane-padded segments, plus —
    when FCS checking is on — ONE vmapped masked-CRC dispatch at the
    common bucket over the still-device-resident decode output
    (previously a hidden host `check_crc32` dispatch PER LANE), then
    the per-lane PSDU slice. CRC booleans are bit-identical to the
    per-lane path (`ops/crc.check_crc32_masked` is the same table
    scan, masked). `acqs` is [(i, acq)] for the real lanes (acq needs
    .rate_mbps/.n_sym/.length_bytes — both the host `_Acquired` and
    batched `_LaneAcq` shapes qualify); `padded` is THE pad_lanes
    list the caller built `segs` from — passed in, not recomputed, so
    the ridx/nbits rows can never disagree with the segment rows."""
    import jax.numpy as jnp

    from ziria_tpu.ops.viterbi import _check_radix
    from ziria_tpu.phy.wifi import rx as _rx
    from ziria_tpu.phy.wifi.params import N_SERVICE_BITS, RATES
    from ziria_tpu.utils import dispatch, programs

    ridx = jnp.asarray([_rx.RATE_INDEX[a.rate_mbps] for _i, a in padded],
                       jnp.int32)
    nbits = jnp.asarray(
        [a.n_sym * RATES[a.rate_mbps].n_dbps for _i, a in padded],
        jnp.int32)
    dec = _rx._jit_decode_data_mixed(n_sym_b, viterbi_window,
                                     viterbi_metric,
                                     _check_radix(viterbi_radix),
                                     sco_track, fused_demap)
    programs.note_site("rx.decode_mixed", dec, segs, ridx, nbits)
    with dispatch.timed("rx.decode_mixed"):
        clear_dev = dec(segs, ridx, nbits)
    crc_b = None
    if check_fcs:
        npsdu = jnp.asarray([8 * a.length_bytes for _i, a in padded],
                            jnp.int32)
        crc_fn = _rx._jit_crc_many()
        programs.note_site("rx.crc_many", crc_fn, clear_dev, npsdu)
        # host pull outside the timed block (jaxlint R2): the site
        # times the dispatch, not the device wait
        with dispatch.timed("rx.crc_many"):
            crc_dev = crc_fn(clear_dev, npsdu)
        crc_b = np.asarray(crc_dev)
    clear = np.asarray(clear_dev, np.uint8)
    for k, (i, a) in enumerate(acqs):
        psdu = clear[k][N_SERVICE_BITS: N_SERVICE_BITS
                        + 8 * a.length_bytes]
        crc = bool(crc_b[k]) if check_fcs else None
        results[i] = _rx.RxResult(True, a.rate_mbps, a.length_bytes,
                                  psdu, crc)
    return results


def receive_many_device(x_dev, n_lanes: int, check_fcs: bool = False,
                        viterbi_window: int = None,
                        viterbi_metric: str = None,
                        viterbi_radix: int = None,
                        sco_track: Optional[bool] = None,
                        fused_demap: Optional[bool] = None) -> List[Any]:
    """Batched receive over an ALREADY device-resident capture batch —
    the RX side of the loopback link (phy/link.py): the channel's
    output feeds acquisition without the samples ever crossing the
    host link.

    x_dev: (R, L, 2) device array, R a power-of-two lane count (rows
    past `n_lanes` repeating row 0 — the pad_lanes rule) and L a
    power-of-two >= 512 capture bucket; the WHOLE buffer of every lane
    is its capture (n_valid = L: the batched channel fills it with
    real air samples). Three dispatches — acquire -> gather -> mixed
    decode — with results bit-identical to per-capture `rx.receive`
    over `np.asarray(x_dev[i])`."""
    from ziria_tpu.phy.wifi import rx as _rx

    l_cap = int(x_dev.shape[1])
    if l_cap != _rx._stream_bucket(l_cap):
        raise ValueError(
            f"capture length {l_cap} is not a power-of-two >= 512 "
            f"bucket; per-capture receive would pad to "
            f"{_rx._stream_bucket(l_cap)} and the identity contract "
            f"needs identical geometry")
    nv = np.full((int(x_dev.shape[0]),), l_cap, np.int32)
    results, lanes = _rx.acquire_batch(x_dev, nv, nv, n_lanes)
    if not lanes:
        return results
    n_sym_b = max(_rx._sym_bucket(a.n_sym) for _i, a in lanes)
    padded = pad_lanes(lanes)
    segs = _rx.gather_segments_many(
        x_dev, [a for _i, a in padded], n_sym_b)
    return _mixed_decode_tail(lanes, padded, segs, n_sym_b, results,
                              check_fcs, viterbi_window, viterbi_metric,
                              viterbi_radix,
                              _rx.sco_track_enabled(sco_track),
                              _rx.fused_demap_enabled(fused_demap))


# ------------------------------------------------------ streaming receiver
#
# `receive_many` serves a *batch of pre-segmented captures*; the
# reference runtime serves a *stream* — an unbounded I/Q sample flow
# with many frames at unknown offsets. `receive_stream` closes that
# gap: the stream is cut into fixed-size overlapping chunks, each
# chunk costs AT MOST TWO device dispatches (the fused multi-peak
# scan `rx.stream_chunk_graph`, then the fixed-geometry mixed-rate
# decode — skipped entirely on all-noise chunks), and a carried
# (tail samples, sample offset, frames emitted) state threads across
# chunks so every frame is owned by exactly one chunk and decodes
# bit-identically to slicing `stream[start:start+frame_len]` out and
# calling per-capture `rx.receive` on it. The dispatch loop is
# double-buffered: chunk i+1's upload+dispatch is issued BEFORE the
# host blocks on chunk i's scalars, so the host<->device transfer
# hides behind compute (in-flight depth on the
# `utils/dispatch.record_gauge("rx.stream_inflight")` gauge).


def streaming_rx_enabled(streaming: Optional[bool] = None) -> bool:
    """The ONE reading of the --streaming-rx / ZIRIA_STREAMING_RX knob
    (default ON): whether `receive_stream` runs the two-dispatch
    chunk path or the per-capture oracle (same detected windows, each
    sliced to the host and fed through `rx.receive` — >= 3 dispatches
    per frame, the identity contract made runnable)."""
    import os

    if streaming is not None:
        return streaming
    return os.environ.get("ZIRIA_STREAMING_RX", "1") != "0"


class StreamFrame(NamedTuple):
    """One emitted frame of a streamed receive: `start` is the
    stream-coordinate window start (the LTS-aligned frame start for
    clean frames), `result` the `rx.RxResult` of per-capture
    `rx.receive(stream[start : start + frame_len])` — bit-identical
    by construction, failures included."""
    start: int
    result: Any


class StreamCarry(NamedTuple):
    """The cross-chunk carry the receiver threads internally: the
    not-yet-owned tail samples, the stream coordinate of their first
    sample, the frames emitted so far, and the dedupe watermark (the
    offset below which no future chunk can re-own a start — the
    `_seen` set holds only entries at or above it, O(K) per stream).
    Exposed read-only via :attr:`StreamReceiver.carry` (and per lane
    via :meth:`MultiStreamReceiver.carry`) for observability and
    tests — to continue a stream across slabs, keep pushing into the
    SAME receiver (the carry is its live state, not a detached resume
    token)."""
    tail: np.ndarray
    offset: int
    emitted: int
    watermark: int = 0


def _chunk_candidates(seen, off, own, starts, k: int):
    """The shared dedupe/ownership core of the streaming drains —
    single-stream and per-fleet-lane alike, so the two receivers can
    never drift on the trickiest host logic: prune `seen` to the
    watermark `off` (starts are non-decreasing across chunks, so no
    future chunk can re-own a start below it — the receiver holds
    O(K) entries, not one per frame ever emitted), then collect the
    chunk's owned, unseen (abs_start, lane row) candidates in stream
    order. Returns (pruned seen, candidates); the caller stores the
    pruned set and records `off` as the carry's watermark."""
    seen = {s for s in seen if s >= off}
    cands = []
    for j in range(k):
        if not own[j]:
            continue
        abs_start = off + int(starts[j])
        if abs_start in seen:
            continue             # safety net; ownership + dead
        seen.add(abs_start)      # zone already make starts unique
        cands.append((abs_start, j))
    cands.sort()
    return seen, cands


def _slab_array(samples, name: str) -> np.ndarray:
    """The push-seam shape/dtype gate (docs/robustness.md): coerce a
    pushed slab to (n, 2) float32 I/Q pairs or raise a ValueError
    NAMING the stream — malformed input fails at the seam, never as
    garbage inside the detector."""
    try:
        arr = np.asarray(samples, np.float32)
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"{name}: pushed slab is not float-convertible "
            f"((n, 2) I/Q sample pairs expected): {e}") from None
    if arr.size == 0:
        return arr.reshape(0, 2)
    if arr.ndim != 2 or arr.shape[1] != 2:
        raise ValueError(
            f"{name}: pushed slab has shape {arr.shape}, want (n, 2) "
            f"I/Q sample pairs")
    return arr


class _LaneHealth:
    """Per-stream quarantine state (shared by the single-stream and
    fleet receivers so the two can never drift): non-finite input
    poisons the lane immediately; ``blowup_limit`` repeated per-lane
    decode blowups poison it too; a poisoned lane rides behind the
    valid-mask (``valid == 0`` — its chunks scan to nothing, healthy
    lanes untouched by construction) and rejoins after
    ``rejoin_after`` consecutive clean chunks."""

    __slots__ = ("blowup_limit", "rejoin_after", "quarantined",
                 "clean", "blowups", "quarantines")

    def __init__(self, blowup_limit: int = 2, rejoin_after: int = 3):
        self.blowup_limit = max(1, int(blowup_limit))
        self.rejoin_after = max(1, int(rejoin_after))
        self.quarantined = False
        self.clean = 0          # consecutive clean chunks in quarantine
        self.blowups = 0        # consecutive per-lane decode blowups
        self.quarantines = 0    # times this lane entered quarantine

    def poison(self) -> None:
        if not self.quarantined:
            self.quarantines += 1
            from ziria_tpu.utils import telemetry
            telemetry.count("resilience.quarantines")
        self.quarantined = True
        self.clean = 0

    def blowup(self) -> None:
        self.blowups += 1
        if self.blowups >= self.blowup_limit:
            self.poison()
            self.blowups = 0

    def step(self, dirty: bool) -> bool:
        """Advance one consumed chunk; True = this chunk rides
        quarantined (valid 0). A dirty chunk resets the clean streak;
        rejoin takes effect from the chunk AFTER the streak fills.
        Blowups are NOT reset here: a chunk's blowups are delivered
        one drain later than its step (the double buffer), so a
        per-step reset could never see two in a row — the count
        accumulates until the lane is poisoned or rejoins."""
        if dirty:
            self.clean = 0
            return self.quarantined
        if self.quarantined:
            self.clean += 1
            if self.clean >= self.rejoin_after:
                self.quarantined = False
                self.clean = 0
                self.blowups = 0
            return True
        return False


#: geometry keys that postdate shipped checkpoint blobs, mapped to
#: the behavior the pre-key code had (see _validate_checkpoint)
_LEGACY_GEOMETRY_DEFAULTS = {"sco_track": False, "fused_demap": False}


def _validate_checkpoint(st, mine: dict) -> None:
    """The ONE checkpoint-geometry gate of every restore surface
    (``StreamReceiver(checkpoint=...)`` and the fleet's
    ``restore_stream`` share it, so the two can never drift): refuse
    a blob whose fingerprint is partial/absent (a raw
    ``checkpoint_carry`` without geometry must not restore into an
    arbitrary receiver) or disagrees with the restoring receiver."""
    from ziria_tpu.runtime import resilience

    # geometry fields added AFTER a blob format shipped, with the
    # value the old code behaved as: a legacy blob missing one of
    # these restores as that default instead of refusing — the old
    # decode program IS the default-mode program, so refusing would
    # throw away valid saved state on every deploy of a new knob
    geo = dict(st.geometry)
    for k_, v_ in _LEGACY_GEOMETRY_DEFAULTS.items():
        geo.setdefault(k_, v_)
    missing = [k_ for k_ in mine if k_ not in geo]
    if missing:
        raise resilience.CarryCheckpointError(
            f"checkpoint lacks geometry fields {missing}; "
            f"use StreamReceiver.checkpoint() (or pass the "
            f"receiver geometry to checkpoint_carry) so the "
            f"restore can be validated")
    bad = {k_: (geo[k_], mine[k_]) for k_ in mine
           if geo[k_] != mine[k_]}
    if bad:
        raise resilience.CarryCheckpointError(
            f"checkpoint geometry mismatch (checkpoint, "
            f"receiver): {bad}")


def _stream_geometry(r) -> dict:
    """The ONE checkpoint geometry fingerprint, shared by the single-
    stream and fleet receivers (so a fleet lane's checkpoint restores
    into a lone receiver): everything a restoring receiver must match
    for bit-identical resumption — the detector parameters included,
    since different thresholds detect different frame starts."""
    return {"chunk_len": r.chunk_len, "frame_len": r.frame_len,
            "k": r.k, "n_sym_bucket": r.n_sym_bucket,
            "check_fcs": bool(r.check_fcs),
            "threshold": r._threshold, "min_run": r._min_run,
            "dead_zone": r._dead_zone,
            "viterbi_window": r.viterbi_window,
            "viterbi_metric": r.viterbi_metric,
            "viterbi_radix": r.viterbi_radix,
            "sco_track": bool(r.sco_track),
            "fused_demap": bool(r.fused_demap)}


def _pull_chunk(outs):
    """Materialize a chunk scan's per-lane scalars on the host. On an
    ASYNC backend a runtime failure mid-execution surfaces HERE, at
    the first host pull, not inside the guarded dispatch — callers
    wrap this and re-run the chunk through the guarded path when it
    throws (the launched results are lost either way). `segs` stays
    device-resident for the decode dispatch."""
    (own, starts, overflow, found, fstart, _eps, rb, ln, pk, nv,
     segs) = outs
    return (np.asarray(own), np.asarray(starts), np.asarray(overflow),
            np.asarray(found), np.asarray(fstart), np.asarray(rb),
            np.asarray(ln), np.asarray(pk), np.asarray(nv), segs)


def _record_degraded(entered: bool) -> None:
    """The ONE degrade-visibility ritual (both receivers and both
    link sites share it, so the recording can never drift): the
    rx.degraded_mode gauge level plus — on entry — the
    resilience.degraded counter."""
    from ziria_tpu.utils import dispatch, telemetry
    dispatch.record_gauge("rx.degraded_mode", 1.0 if entered else 0.0)
    if entered:
        telemetry.count("resilience.degraded")


def _guarded_decode(r, label: str, dec, *args):
    """The ONE guarded decode dispatch + SYNCHRONOUS host pull
    (single-stream and fleet receivers share it): an async runtime
    failure surfaces at the pull, after the dispatch returned, so the
    pull lives inside the same containment — one guarded re-dispatch,
    then None, with the receiver marked degraded so the caller (and
    the rest of the stream) runs the oracle twin. Returns (clear,
    crc) as host arrays, or None."""
    from ziria_tpu.runtime import resilience
    from ziria_tpu.utils import telemetry

    for attempt in (0, 1):
        try:
            clear, crc = resilience.guarded(label, dec, *args,
                                            policy=r._policy)
            return np.asarray(clear, np.uint8), np.asarray(crc)
        except resilience.DispatchFailed:
            break
        except Exception:        # noqa: BLE001 - async pull loss
            if attempt:
                break
            telemetry.count("resilience.async_rescans")
    r._mark_degraded(scan=False)
    return None


def _gate_finite(arr: np.ndarray, name: str, sanitize: bool,
                 health: "_LaneHealth"):
    """The ONE non-finite gate behind the shape gate (single-stream
    and fleet push seams share it, so the two can never drift):
    reject with an error NAMING the stream — or, under
    ``sanitize=True``, zero the poisoned samples and quarantine the
    lane. Returns ``(arr, n_bad)``; the caller owns its own dirty
    flag and sanitized counter."""
    if arr.size == 0:
        return arr, 0
    bad = ~np.isfinite(arr)
    if not bad.any():
        return arr, 0
    n_bad = int(bad.any(axis=-1).sum())
    if not sanitize:
        raise ValueError(
            f"{name}: pushed slab carries {n_bad} non-finite "
            f"sample(s); reject at the source or construct the "
            f"receiver with sanitize=True to zero-and-quarantine")
    arr = np.where(bad, np.float32(0), arr)
    health.poison()
    from ziria_tpu.utils import telemetry
    telemetry.count("resilience.sanitized", n_bad)
    return arr, n_bad


class StreamStats(NamedTuple):
    chunks: int                # chunk dispatch-1 scans issued
    frames: int                # StreamFrames emitted
    overflow_chunks: int       # chunks reporting > K eligible plateaus
    max_in_flight: int         # high-water chunk dispatches in flight
    sanitized: int = 0         # non-finite samples zeroed (sanitize=True)
    quarantines: int = 0       # times the stream entered quarantine
    lane_blowups: int = 0      # per-window oracle decode blowups caught
    degraded: bool = False     # a compiled program degraded to its twin


class StreamReceiver:
    """Push-driven streaming receiver: feed arbitrary sample slabs
    with :meth:`push`, close the stream with :meth:`flush`; both
    return the :class:`StreamFrame`\\ s that became decodable.

    Geometry: `chunk_len` samples per scan with `frame_len` of
    overlap between consecutive chunks (`frame_len` must be a
    power-of-two >= 512 capture bucket covering the longest frame the
    stream may carry, so a frame starting anywhere in a chunk's OWNED
    region — the first `chunk_len - frame_len` samples — lies fully
    inside that chunk). Starts detected in the overlap re-detect
    fully inside the next chunk and are owned there: every frame is
    decoded exactly once. Up to `max_frames_per_chunk` frames are
    extracted per chunk; more raises the chunk's overflow flag
    (counted in :class:`StreamStats` — reported, never silently
    dropped; widen K or shorten the chunk).
    """

    def __init__(self, chunk_len: Optional[int] = None,
                 frame_len: Optional[int] = None,
                 max_frames_per_chunk: Optional[int] = None,
                 check_fcs: bool = False,
                 threshold: Optional[float] = None,
                 min_run: Optional[int] = None,
                 dead_zone: Optional[int] = None,
                 viterbi_window: int = None,
                 viterbi_metric: str = None,
                 viterbi_radix: int = None,
                 streaming: Optional[bool] = None,
                 sanitize: bool = False,
                 max_retries: Optional[int] = None,
                 watchdog_s: Optional[float] = None,
                 blowup_limit: int = 2, rejoin_after: int = 3,
                 checkpoint: Optional[bytes] = None,
                 sco_track: Optional[bool] = None,
                 fused_demap: Optional[bool] = None,
                 geometry: Optional[_geometry.Geometry] = None):
        from ziria_tpu.ops.viterbi import _check_radix
        from ziria_tpu.phy.wifi import rx as _rx
        from ziria_tpu.runtime import resilience

        # ONE declarative geometry supplies every default the caller
        # leaves None (explicit per-knob args still win); the default
        # Geometry IS the historical constants, so StreamReceiver()
        # builds exactly yesterday's receiver — same compiled
        # programs, same checkpoint fingerprint, same bits.
        geo = geometry if geometry is not None else _geometry.DEFAULT
        chunk_len = geo.chunk_len if chunk_len is None else chunk_len
        frame_len = geo.frame_len if frame_len is None else frame_len
        max_frames_per_chunk = (geo.max_frames_per_chunk
                                if max_frames_per_chunk is None
                                else max_frames_per_chunk)
        threshold = geo.threshold if threshold is None else threshold
        min_run = geo.min_run if min_run is None else min_run
        dead_zone = geo.dead_zone if dead_zone is None else dead_zone
        viterbi_window = (geo.viterbi_window if viterbi_window is None
                          else viterbi_window)
        viterbi_metric = (geo.viterbi_metric if viterbi_metric is None
                          else viterbi_metric)
        viterbi_radix = (geo.viterbi_radix if viterbi_radix is None
                         else viterbi_radix)
        sco_track = geo.sco_track if sco_track is None else sco_track
        fused_demap = (geo.fused_demap if fused_demap is None
                       else fused_demap)

        if frame_len != geo.capture_bucket(frame_len):
            raise ValueError(
                f"frame_len {frame_len} is not a power-of-two >= "
                f"{geo.capture_bucket_min} capture bucket; per-capture "
                f"receive would pad to {geo.capture_bucket(frame_len)} "
                f"and the identity contract needs identical geometry")
        if chunk_len <= frame_len:
            raise ValueError(
                f"chunk_len {chunk_len} must exceed the frame_len "
                f"{frame_len} overlap (the owned region would be empty)")
        self.chunk_len = int(chunk_len)
        self.frame_len = int(frame_len)
        self.stride = self.chunk_len - self.frame_len
        self.k = int(max_frames_per_chunk)
        # the largest DATA field a frame_len window can hold, bucketed:
        # the stream's ONE fixed decode geometry (longer frames are
        # ACQ_TRUNCATED in both paths — the window cannot hold them)
        self.n_sym_bucket = geo.sym_bucket(
            max(1, (self.frame_len - _rx.FRAME_DATA_START) // 80))
        self.check_fcs = check_fcs
        self.viterbi_window = viterbi_window
        self.viterbi_metric = viterbi_metric
        # resolved ONCE at construction: the radix, sco_track, and
        # fused_demap are part of the stream's fixed compiled
        # geometry (decode jit cache key AND the checkpoint
        # fingerprint — a different decode program emits different
        # bits)
        self.viterbi_radix = _check_radix(viterbi_radix)
        self.sco_track = _rx.sco_track_enabled(sco_track)
        self.fused_demap = _rx.fused_demap_enabled(fused_demap)
        self.streaming = streaming_rx_enabled(streaming)
        # detector params kept for the degraded eager twin (the same
        # chunk graph run op-by-op when the compiled program fails)
        self._threshold = float(threshold)
        self._min_run = int(min_run)
        self._dead_zone = int(dead_zone)
        self._jit1 = _rx._jit_stream_chunk(
            self.k, self.frame_len, self.n_sym_bucket,
            float(threshold), int(min_run), int(dead_zone))
        self.sanitize = bool(sanitize)
        self._policy = resilience.default_policy(
            max_retries=max_retries, timeout_s=watchdog_s)
        self._health = _LaneHealth(blowup_limit, rejoin_after)
        self._dirty = False        # non-finite input since last chunk
        self._sanitized = 0
        self._lane_blowups = 0
        self._degraded = False        # decode program -> oracle twin
        self._scan_degraded = False   # chunk program -> eager twin
        self._tail = np.zeros((0, 2), np.float32)
        self._offset = 0
        self._emitted = 0
        self._watermark = 0
        self._seen = set()
        self._pending = None       # (offset, host chunk, valid, outs)
        self._inflight = 0
        self._chunks = 0
        self._overflow_chunks = 0
        self._max_in_flight = 0
        self._flushed = False
        if checkpoint is not None:
            st = resilience.restore_carry(checkpoint)
            _validate_checkpoint(st, self._geometry())
            self._tail = np.asarray(st.tail, np.float32)
            self._offset = int(st.offset)
            self._emitted = int(st.emitted)
            self._watermark = int(st.watermark)
            self._seen = set(st.seen)
            rs = st.state   # quarantine/degraded runtime state: a
            #                 quarantined receiver must RESUME
            #                 quarantined or emissions diverge from
            #                 the uninterrupted run
            self._health.quarantined = bool(rs.get("quarantined",
                                                   False))
            self._health.clean = int(rs.get("clean", 0))
            self._health.blowups = int(rs.get("blowups", 0))
            self._health.quarantines = int(rs.get("quarantines", 0))
            self._dirty = bool(rs.get("dirty", False))
            self._sanitized = int(rs.get("sanitized", 0))
            self._lane_blowups = int(rs.get("lane_blowups", 0))
            self._degraded = bool(rs.get("degraded", False))
            self._scan_degraded = bool(rs.get("scan_degraded", False))

    # -- state ----------------------------------------------------------

    @property
    def carry(self) -> StreamCarry:
        return StreamCarry(self._tail, self._offset, self._emitted,
                           self._watermark)

    @property
    def stats(self) -> StreamStats:
        return StreamStats(self._chunks, self._emitted,
                           self._overflow_chunks, self._max_in_flight,
                           self._sanitized, self._health.quarantines,
                           self._lane_blowups,
                           self._degraded or self._scan_degraded)

    def _geometry(self) -> dict:
        return _stream_geometry(self)

    def _runtime_state(self) -> dict:
        """The checkpoint's runtime-state rider: quarantine health +
        degraded flags + containment counters, so a restored receiver
        keeps behaving exactly as the uninterrupted one would."""
        return {"quarantined": self._health.quarantined,
                "clean": self._health.clean,
                "blowups": self._health.blowups,
                "quarantines": self._health.quarantines,
                "dirty": self._dirty,
                "sanitized": self._sanitized,
                "lane_blowups": self._lane_blowups,
                "degraded": self._degraded,
                "scan_degraded": self._scan_degraded}

    def checkpoint(self):
        """Serialize the live stream state (runtime/resilience
        checkpoint blob): the in-flight chunk is DRAINED first — its
        frames belong to the pre-checkpoint past and are returned
        alongside, so nothing launched is silently dropped. The blob
        carries the quarantine/degraded runtime state too. Returns
        ``(state_bytes, frames)``; a new
        ``StreamReceiver(checkpoint=state_bytes, ...)`` at the same
        geometry resumes with bit-identical subsequent emissions."""
        if self._flushed:
            raise RuntimeError("checkpoint after flush")
        out: List[StreamFrame] = []
        if self._pending is not None:
            pend, self._pending = self._pending, None
            out = self._drain(pend)
        from ziria_tpu.runtime import resilience
        return resilience.checkpoint_carry(
            self.carry, seen=self._seen, geometry=self._geometry(),
            state=self._runtime_state()), out

    # -- the push surface -----------------------------------------------

    def push(self, samples) -> List[StreamFrame]:
        """Append samples ((n, 2) float pairs) to the stream; scan
        every full chunk that completes. Returns the frames emitted.
        Malformed slabs fail loudly at the seam (`_slab_array`);
        non-finite samples reject — or, with ``sanitize=True``, zero
        and quarantine the stream (docs/robustness.md)."""
        if self._flushed:
            raise RuntimeError("push after flush")
        from ziria_tpu.utils import dispatch, faults

        arr = _slab_array(samples, "stream")
        arr, _kinds = faults.corrupt_slab("rx.push", arr)
        arr, n_bad = _gate_finite(arr, "stream", self.sanitize,
                                  self._health)
        if n_bad:
            self._sanitized += n_bad
            self._dirty = True
        if arr.size:
            self._tail = np.concatenate([self._tail, arr], axis=0)

        out: List[StreamFrame] = []
        while self._tail.shape[0] >= self.chunk_len:
            q = self._health.step(self._dirty)
            self._dirty = False
            out += self._launch(self._tail[:self.chunk_len],
                                0 if q else self.chunk_len,
                                self.stride)
            self._tail = self._tail[self.stride:]
            self._offset += self.stride
            # carry depth after each chunk consumption: with telemetry
            # active this is a plottable counter track (does the push
            # cadence keep up with the chunk stride, or does the tail
            # grow?); a plain high-water mark under count_dispatches
            dispatch.record_gauge("rx.stream_carry_depth",
                                  self._tail.shape[0])
        return out

    def flush(self) -> List[StreamFrame]:
        """Close the stream: scan the carried tail (zero-padded to the
        chunk geometry, owning every remaining start) and drain the
        in-flight chunk. Idempotent."""
        if self._flushed:
            return []
        self._flushed = True
        out: List[StreamFrame] = []
        valid = self._tail.shape[0]
        if valid:
            q = self._health.step(self._dirty)
            self._dirty = False
            arr = np.zeros((self.chunk_len, 2), np.float32)
            arr[:valid] = self._tail
            out += self._launch(arr, 0 if q else valid, valid)
        if self._pending is not None:
            pend, self._pending = self._pending, None
            out += self._drain(pend)
        return out

    # -- chunk lifecycle ------------------------------------------------

    def _launch(self, arr, valid: int, own_hi: int) -> List[StreamFrame]:
        """Issue chunk upload + scan dispatch, THEN drain the previous
        chunk: while the host blocks on chunk i-1's scalars, chunk i's
        transfer and compute are already in flight (the double
        buffer). Returns chunk i-1's emissions."""
        import jax
        import jax.numpy as jnp

        from ziria_tpu.utils import dispatch, programs

        # the stream's FIRST chunk owns head-truncated preambles whose
        # LTS alignment lands below 0 (clamped to 0 on device, exactly
        # as per-capture locate_frame clamps); on any later chunk a
        # negative start is the previous chunk's frame
        own_lo = -192 if self._offset == 0 else 0
        dev = jax.device_put(arr)
        chunk_args = (dev, jnp.int32(valid), jnp.int32(own_lo),
                      jnp.int32(own_hi))
        programs.note_site("rx.stream_chunk", self._jit1, *chunk_args)
        outs = self._scan_dispatch(chunk_args)
        dispatch.record_gauge(
            "rx.degraded_mode",
            1.0 if (self._degraded or self._scan_degraded) else 0.0)
        dispatch.record_gauge(
            "rx.quarantined_streams",
            1.0 if self._health.quarantined else 0.0)
        self._chunks += 1
        self._inflight += 1
        self._max_in_flight = max(self._max_in_flight, self._inflight)
        dispatch.record_gauge("rx.stream_inflight", self._inflight)
        pend, self._pending = self._pending, (self._offset, arr, valid,
                                              own_hi, outs)
        return self._drain(pend) if pend is not None else []

    def _scan_dispatch(self, chunk_args):
        """The ONE guarded chunk-scan dispatch (shared by `_launch`
        and the async-rescan path): the compiled program behind the
        guard, degrading to the eager twin when it fails for good."""
        from ziria_tpu.runtime import resilience

        if self._scan_degraded:
            return self._eager_chunk(*chunk_args)
        try:
            return resilience.guarded(
                "rx.stream_chunk", self._jit1, *chunk_args,
                policy=self._policy)
        except resilience.DispatchFailed:
            self._mark_degraded(scan=True)
            return self._eager_chunk(*chunk_args)


    def _rescan(self, arr, valid: int, off: int, own_hi: int):
        """Re-run a chunk whose ASYNC results were lost: a runtime
        failure mid-execution surfaces at the host pull in `_drain`,
        after the guarded dispatch already returned — the launched
        results are gone, so the chunk re-dispatches through the same
        guarded/degraded path (counted as an async rescan)."""
        import jax
        import jax.numpy as jnp

        from ziria_tpu.utils import telemetry

        telemetry.count("resilience.async_rescans")
        own_lo = -192 if off == 0 else 0
        return self._scan_dispatch(
            (jax.device_put(arr), jnp.int32(valid),
             jnp.int32(own_lo), jnp.int32(own_hi)))

    def _drain(self, pend) -> List[StreamFrame]:
        """Block on a launched chunk's per-lane scalars, run the host
        integer decision tree, and emit its frames (dispatching the
        chunk's ONE fixed-geometry decode when any lane is decodable;
        per-capture `rx.receive` per window in oracle mode)."""
        from ziria_tpu.phy.wifi import rx as _rx
        from ziria_tpu.phy.wifi.params import N_SERVICE_BITS, RATES
        from ziria_tpu.utils import dispatch, programs

        off, arr, valid, own_hi, outs = pend
        try:
            (own, starts, overflow, found, fstart, rb, ln, pk, nv,
             segs) = _pull_chunk(outs)
        except Exception:    # noqa: BLE001 - async loss, re-dispatch
            (own, starts, overflow, found, fstart, rb, ln, pk, nv,
             segs) = _pull_chunk(self._rescan(arr, valid, off,
                                              own_hi))
        self._inflight -= 1
        if bool(overflow):
            self._overflow_chunks += 1

        self._watermark = off
        self._seen, cands = _chunk_candidates(self._seen, off, own,
                                              starts, self.k)

        if not self.streaming or self._degraded:
            # the per-capture oracle: the SAME detected windows, each
            # sliced to the host and pushed through `rx.receive` — the
            # ">= 3 dispatches per frame" path the streaming mode's
            # identity (and speedup) is measured against, and the
            # degraded twin when the compiled decode fails for good
            return self._decode_oracle(cands, starts, arr, valid)

        emit = {}
        lanes = []                   # (abs_start, lane row, rate, len)
        for abs_start, j in cands:
            avail = int(nv[j]) - int(fstart[j])
            res, ok = _rx._classify_acquire(
                bool(found[j]), avail, int(rb[j]), int(ln[j]),
                bool(pk[j]))
            if ok is None:
                emit[abs_start] = res
            else:
                lanes.append((abs_start, j, ok[0], ok[1], int(ln[j])))
        if lanes:
            import jax.numpy as jnp

            # rows always pad to K (lane 0 repeated): ONE compiled
            # decode geometry serves every chunk of the stream
            def row_pad(vals):
                vals = list(vals) + [vals[0]] * (self.k - len(vals))
                return jnp.asarray(np.asarray(vals, np.int32))

            rows = row_pad([j for _s, j, _m, _n, _lb in lanes])
            ridx = row_pad([_rx.RATE_INDEX[m] for _s, _j, m, _n, _lb
                            in lanes])
            nbits = row_pad([n_sym * RATES[m].n_dbps
                             for _s, _j, m, n_sym, _lb in lanes])
            npsdu = row_pad([8 * lb for _s, _j, _m, _n, lb in lanes])
            dec = _rx._jit_stream_decode(self.n_sym_bucket,
                                         self.viterbi_window,
                                         self.viterbi_metric,
                                         self.viterbi_radix,
                                         self.sco_track,
                                         self.fused_demap)
            programs.note_site("rx.stream_decode", dec, segs, rows,
                               ridx, nbits, npsdu)
            got = _guarded_decode(
                self, "rx.stream_decode", dec, segs, rows, ridx,
                nbits, npsdu)
            if got is None:
                # the compiled decode failed for good (at dispatch OR
                # at the async host pull): degrade to the per-capture
                # oracle for this chunk AND the rest of the stream
                # (bit-identical by the pinned contract)
                return self._decode_oracle(cands, starts, arr, valid)
            clear, crc = got
            for i, (abs_start, _j, m, _n, lb) in enumerate(lanes):
                psdu = clear[i][N_SERVICE_BITS: N_SERVICE_BITS + 8 * lb]
                emit[abs_start] = _rx.RxResult(
                    True, m, lb, psdu,
                    bool(crc[i]) if self.check_fcs else None)
        out = [StreamFrame(s, emit[s]) for s in sorted(emit)]
        self._emitted += len(out)
        self._note_emitted(len(out))
        return out

    def _decode_oracle(self, cands, starts, arr,
                       valid: int) -> List[StreamFrame]:
        """The per-capture decode twin over the chunk's owned windows
        — the ``streaming=False`` oracle AND the degraded mode the
        compiled decode falls back to. Under the resilience opt-ins
        (``sanitize=True`` or degraded mode) a window whose
        per-capture receive blows up is counted
        (`resilience.lane_blowups`), dropped loudly, and charged to
        the stream's health (repeated blowups quarantine it) — never
        a crash, never a silent wrong answer. In the PLAIN
        ``streaming=False`` oracle (no opt-in) exceptions propagate
        unchanged: a genuine decoder defect must surface, not
        masquerade as frame loss."""
        from ziria_tpu.phy.wifi import rx as _rx
        from ziria_tpu.utils import telemetry

        contain = (self.sanitize or self._degraded
                   or self._scan_degraded)
        out: List[StreamFrame] = []
        for abs_start, j in cands:
            s = int(starts[j])
            win = arr[s: min(s + self.frame_len, valid)]
            try:
                res = _rx.receive(
                    win, check_fcs=self.check_fcs,
                    viterbi_window=self.viterbi_window,
                    viterbi_metric=self.viterbi_metric,
                    viterbi_radix=self.viterbi_radix,
                    sco_track=self.sco_track)
            except Exception:    # noqa: BLE001 - counted containment
                if not contain:
                    raise
                self._lane_blowups += 1
                self._health.blowup()
                telemetry.count("resilience.lane_blowups")
                continue
            out.append(StreamFrame(abs_start, res))
        self._emitted += len(out)
        self._note_emitted(len(out))
        return out

    def _eager_chunk(self, dev, valid, own_lo, own_hi):
        """The degraded scan twin: the SAME chunk graph run op-by-op
        (eager jax) — no dependence on the failed compiled program.
        Slower (many small dispatches) but available; labelled
        ``rx.stream_chunk.eager`` so chaos plans targeting the
        compiled site never block the fallback."""
        from ziria_tpu.phy.wifi import rx as _rx
        from ziria_tpu.utils import dispatch

        with dispatch.timed("rx.stream_chunk.eager"):
            return _rx.stream_chunk_graph(
                dev, valid, own_lo, own_hi, self.k, self.frame_len,
                self.n_sym_bucket, self._threshold, self._min_run,
                self._dead_zone)

    def _mark_degraded(self, scan: bool) -> None:
        """Enter degraded mode for one of the two compiled streaming
        programs: recorded as the ``rx.degraded_mode`` gauge plus a
        counter — a fleet quietly running its slow twin must be
        visible in trace_report, not discovered in a latency graph."""
        if scan:
            self._scan_degraded = True
        else:
            self._degraded = True
        _record_degraded(True)

    def reset_degraded(self) -> None:
        """Leave degraded mode (re-probe the compiled programs on the
        next chunk) — the operator's lever after the underlying fault
        (a tunnel flap, a wedged device) is known to be fixed."""
        self._degraded = False
        self._scan_degraded = False
        _record_degraded(False)

    def _note_emitted(self, k: int) -> None:
        """Frames-emitted counter into the telemetry layer (registry
        increment + cumulative counter track in active traces). Free
        when nothing is collecting."""
        if k:
            from ziria_tpu.utils import telemetry
            telemetry.count("rx.stream_frames", k, total=self._emitted)


def receive_stream(samples, chunk_len: Optional[int] = None,
                   frame_len: Optional[int] = None,
                   max_frames_per_chunk: Optional[int] = None,
                   check_fcs: bool = False,
                   threshold: Optional[float] = None,
                   min_run: Optional[int] = None,
                   dead_zone: Optional[int] = None,
                   viterbi_window: int = None,
                   viterbi_metric: str = None,
                   viterbi_radix: int = None,
                   streaming: Optional[bool] = None,
                   sco_track: Optional[bool] = None,
                   fused_demap: Optional[bool] = None,
                   geometry: Optional[_geometry.Geometry] = None):
    """Decode every frame of a long multi-frame sample stream in
    O(chunks) device dispatches (<= 2 per chunk; 1 for all-noise
    chunks). Returns ``(frames, stats)``: a position-ordered list of
    :class:`StreamFrame` — each bit-identical, RxResult field for
    field including the FCS status, to per-capture
    ``rx.receive(stream[start : start + frame_len], check_fcs=...)``
    — and the :class:`StreamStats` (chunks scanned, frames emitted,
    overflow chunks, in-flight high-water mark).

    ``streaming=False`` (or ``--no-streaming-rx`` /
    ``ZIRIA_STREAMING_RX=0``) runs the per-capture oracle over the
    same detected windows (>= 3 dispatches per frame). The convenience
    wrapper over :class:`StreamReceiver` — push-driven callers (a live
    capture feed) use the class directly, pushing slabs into one
    receiver whose :class:`StreamCarry` state threads across chunks
    internally (visible via ``.carry``). ``geometry`` supplies the
    default for every knob the caller leaves None (one declarative
    object; explicit arguments win)."""
    sr = StreamReceiver(chunk_len=chunk_len, frame_len=frame_len,
                        max_frames_per_chunk=max_frames_per_chunk,
                        check_fcs=check_fcs, threshold=threshold,
                        min_run=min_run, dead_zone=dead_zone,
                        viterbi_window=viterbi_window,
                        viterbi_metric=viterbi_metric,
                        viterbi_radix=viterbi_radix,
                        streaming=streaming, sco_track=sco_track,
                        fused_demap=fused_demap, geometry=geometry)
    frames = sr.push(samples)
    frames += sr.flush()
    return frames, sr.stats


# ------------------------------------------------- multi-stream receiver
#
# `receive_stream` decodes ONE stream per process; "millions of users"
# is MANY concurrent streams on one device fleet. `receive_streams` +
# the push-driven `MultiStreamReceiver` stack S independent streams'
# chunks on a leading STREAM AXIS and run them through the stream-
# axis-vmapped twins of the two compiled streaming programs
# (`rx._jit_stream_chunk_multi` / `rx._jit_stream_decode_multi`), so
# an entire S-stream fleet still runs on TWO compiled programs at
# <= 2 dispatches per CHUNK-STEP — independent of S. Ragged arrival
# is handled host-side by a packer: a chunk-step fires only when at
# least one stream has a full chunk, streams without one ride the
# step as idle lanes behind a valid-mask (`valid == 0` → the detector
# caps their positions to nothing), and the all-noise fast path is
# preserved (a step with zero decodable lanes across the WHOLE fleet
# skips the decode dispatch entirely). The stream axis shards over
# the dp mesh (`parallel/batch.frame_mesh` / `lane_sharding`,
# shard_map via the utils/compat shim — multihost-ready through
# `parallel/multihost.build_mesh`, dp being the axis with no
# steady-state collectives). Every emitted frame is bit-identical to
# S separate single-stream `StreamReceiver`s BY CONSTRUCTION: the
# per-stream chunk boundaries, ownership windows, and per-lane graphs
# are exactly the single-stream ones — the vmap only adds the axis.


def multi_stream_enabled(multi: Optional[bool] = None) -> bool:
    """The ONE reading of the --multi-stream / ZIRIA_MULTI_STREAM knob
    (default ON): whether `receive_streams` runs the stream-axis fleet
    path or falls back to S independent single-stream
    `StreamReceiver`s (the bit-identity oracle — >= S x the fleet's
    dispatch count). The env value is the CLI's declared lane count;
    only ``"0"`` disables."""
    import os

    if multi is not None:
        return multi
    return os.environ.get("ZIRIA_MULTI_STREAM", "1") != "0"


class MultiStreamStats(NamedTuple):
    streams: int               # S, the fleet width
    chunk_steps: int           # fleet scan dispatches issued (oracle
    #                            mode: per-stream chunks, summed)
    frames: int                # StreamFrames emitted, all streams
    overflow_chunks: int       # per-stream chunk overflow flags raised
    max_in_flight: int         # high-water chunk-steps in flight
    max_active_streams: int    # high-water active lanes in one step
    sanitized: int = 0         # non-finite samples zeroed, fleet-wide
    quarantines: int = 0       # quarantine entries, fleet-wide
    quarantined_streams: int = 0   # streams quarantined RIGHT NOW
    lane_blowups: int = 0      # per-window oracle blowups caught
    degraded: bool = False     # a compiled fleet program degraded


class MultiStreamReceiver:
    """Push-driven S-stream receiver: feed per-stream sample slabs
    with :meth:`push` (one stream) or :meth:`push_many` (a slab per
    stream), close with :meth:`flush`; all return the
    ``(stream, StreamFrame)`` pairs that became decodable.

    Geometry is the single-stream receiver's (`chunk_len` windows
    overlapping by `frame_len`, up to `max_frames_per_chunk` frames
    per chunk per stream), applied PER STREAM: each stream steps
    through exactly the chunk boundaries a lone `StreamReceiver`
    would, so lane-for-lane bit-identity with S separate receivers
    holds by construction. One chunk-step = one stacked
    (S, chunk_len, 2) upload + ONE vmapped scan dispatch (+ ONE
    flattened decode dispatch when any stream has a decodable frame),
    double-buffered like the single-stream loop. `mesh` shards the
    stream axis over dp (`S % mesh.size == 0`); per-stream carries
    (:class:`StreamCarry`, dedupe watermark included) are visible via
    :meth:`carry`/:attr:`carries`."""

    def __init__(self, n_streams: Optional[int] = None,
                 chunk_len: Optional[int] = None,
                 frame_len: Optional[int] = None,
                 max_frames_per_chunk: Optional[int] = None,
                 check_fcs: bool = False,
                 threshold: Optional[float] = None,
                 min_run: Optional[int] = None,
                 dead_zone: Optional[int] = None,
                 viterbi_window: int = None, viterbi_metric: str = None,
                 viterbi_radix: int = None, mesh=None,
                 axis: str = "dp", sanitize: bool = False,
                 max_retries: Optional[int] = None,
                 watchdog_s: Optional[float] = None,
                 blowup_limit: int = 2, rejoin_after: int = 3,
                 sco_track: Optional[bool] = None,
                 fused_demap: Optional[bool] = None,
                 geometry: Optional[_geometry.Geometry] = None):
        from ziria_tpu.ops.viterbi import _check_radix
        from ziria_tpu.phy.wifi import rx as _rx
        from ziria_tpu.runtime import resilience

        # the declarative-geometry defaults (see StreamReceiver): the
        # fleet width S rides the same object as the chunk geometry,
        # so MultiStreamReceiver(geometry=g) builds the whole fleet
        geo = geometry if geometry is not None else _geometry.DEFAULT
        n_streams = geo.n_streams if n_streams is None else n_streams
        chunk_len = geo.chunk_len if chunk_len is None else chunk_len
        frame_len = geo.frame_len if frame_len is None else frame_len
        max_frames_per_chunk = (geo.max_frames_per_chunk
                                if max_frames_per_chunk is None
                                else max_frames_per_chunk)
        threshold = geo.threshold if threshold is None else threshold
        min_run = geo.min_run if min_run is None else min_run
        dead_zone = geo.dead_zone if dead_zone is None else dead_zone
        viterbi_window = (geo.viterbi_window if viterbi_window is None
                          else viterbi_window)
        viterbi_metric = (geo.viterbi_metric if viterbi_metric is None
                          else viterbi_metric)
        viterbi_radix = (geo.viterbi_radix if viterbi_radix is None
                         else viterbi_radix)
        sco_track = geo.sco_track if sco_track is None else sco_track
        fused_demap = (geo.fused_demap if fused_demap is None
                       else fused_demap)

        if n_streams < 1:
            raise ValueError(f"n_streams {n_streams} must be >= 1")
        if frame_len != geo.capture_bucket(frame_len):
            raise ValueError(
                f"frame_len {frame_len} is not a power-of-two >= "
                f"{geo.capture_bucket_min} capture bucket; per-capture "
                f"receive would pad to {geo.capture_bucket(frame_len)} "
                f"and the identity contract needs identical geometry")
        if chunk_len <= frame_len:
            raise ValueError(
                f"chunk_len {chunk_len} must exceed the frame_len "
                f"{frame_len} overlap (the owned region would be empty)")
        if mesh is not None and n_streams % mesh.size:
            raise ValueError(
                f"n_streams {n_streams} must divide the mesh "
                f"({mesh.size} devices): the stream axis shards evenly "
                f"(shard_batch's rule)")
        self.s = int(n_streams)
        self.chunk_len = int(chunk_len)
        self.frame_len = int(frame_len)
        self.stride = self.chunk_len - self.frame_len
        self.k = int(max_frames_per_chunk)
        self.n_sym_bucket = geo.sym_bucket(
            max(1, (self.frame_len - _rx.FRAME_DATA_START) // 80))
        self.check_fcs = check_fcs
        self.viterbi_window = viterbi_window
        self.viterbi_metric = viterbi_metric
        self.viterbi_radix = _check_radix(viterbi_radix)
        self.sco_track = _rx.sco_track_enabled(sco_track)
        self.fused_demap = _rx.fused_demap_enabled(fused_demap)
        self.mesh = mesh
        self.axis = axis
        self._threshold = float(threshold)
        self._min_run = int(min_run)
        self._dead_zone = int(dead_zone)
        self._jit1 = _rx._jit_stream_chunk_multi(
            self.k, self.frame_len, self.n_sym_bucket,
            float(threshold), int(min_run), int(dead_zone), mesh, axis)
        self.sanitize = bool(sanitize)
        self._policy = resilience.default_policy(
            max_retries=max_retries, timeout_s=watchdog_s)
        self._health = [_LaneHealth(blowup_limit, rejoin_after)
                        for _ in range(self.s)]
        self._dirty = [False] * self.s
        self._sanitized = 0
        self._lane_blowups = 0
        self._degraded = False        # fleet decode -> oracle twin
        self._scan_degraded = False   # fleet scan -> eager twin
        self._tails = [np.zeros((0, 2), np.float32)
                       for _ in range(self.s)]
        self._offsets = [0] * self.s
        self._emitted = [0] * self.s
        self._watermarks = [0] * self.s
        self._seen = [set() for _ in range(self.s)]
        self._pending = None   # (offsets, active, arrs, valid, outs)
        self._inflight = 0
        self._chunk_steps = 0
        self._overflow_chunks = 0
        self._max_in_flight = 0
        self._max_active = 0
        self._retired = 0      # frames credited to recycled lanes
        self._flushed = False

    # -- state ----------------------------------------------------------

    def _check_stream(self, stream, exc=IndexError) -> int:
        """The ONE unknown-stream-id gate of every per-lane surface:
        at S=64 an error naming only the bad id is useless — every
        raise here names the fleet's known id range too."""
        if not (isinstance(stream, (int, np.integer))
                and 0 <= int(stream) < self.s):
            raise exc(
                f"unknown stream id {stream!r}: this fleet's known "
                f"ids are 0..{self.s - 1} ({self.s} streams)")
        return int(stream)

    def carry(self, stream: int) -> StreamCarry:
        """Stream `stream`'s live :class:`StreamCarry` (tail, offset,
        emitted, dedupe watermark) — read-only observability, exactly
        like the single-stream receiver's."""
        stream = self._check_stream(stream)
        return StreamCarry(self._tails[stream], self._offsets[stream],
                           self._emitted[stream],
                           self._watermarks[stream])

    @property
    def carries(self) -> List[StreamCarry]:
        return [self.carry(i) for i in range(self.s)]

    @property
    def stats(self) -> MultiStreamStats:
        return MultiStreamStats(
            self.s, self._chunk_steps,
            sum(self._emitted) + self._retired,
            self._overflow_chunks, self._max_in_flight,
            self._max_active, self._sanitized,
            sum(h.quarantines for h in self._health),
            sum(1 for h in self._health if h.quarantined),
            self._lane_blowups,
            self._degraded or self._scan_degraded)

    def quarantined(self, stream: int) -> bool:
        """True while `stream` rides behind the valid-mask (poisoned
        input or repeated decode blowups; docs/robustness.md)."""
        return self._health[self._check_stream(stream)].quarantined

    def _geometry(self) -> dict:
        return _stream_geometry(self)

    def _lane_state(self, stream: int) -> dict:
        """The checkpoint runtime-state rider of one lane (quarantine
        health + fleet degraded flags), shared by the per-lane and
        whole-fleet checkpoint surfaces so the two can never drift."""
        h = self._health[stream]
        return {"quarantined": h.quarantined, "clean": h.clean,
                "blowups": h.blowups, "quarantines": h.quarantines,
                "dirty": self._dirty[stream],
                "degraded": self._degraded,
                "scan_degraded": self._scan_degraded}

    def _lane_blob(self, stream: int) -> bytes:
        from ziria_tpu.runtime import resilience
        return resilience.checkpoint_carry(
            self.carry(stream), seen=self._seen[stream],
            geometry=self._geometry(), state=self._lane_state(stream))

    def checkpoint(self, stream: int):
        """Serialize one fleet lane's live stream state (the in-flight
        chunk-step is drained first; its fleet-wide emissions return
        alongside). The blob restores into a lone
        ``StreamReceiver(checkpoint=...)`` at the same geometry —
        a crashed fleet lane resumes on its own receiver with
        bit-identical subsequent emissions. Returns
        ``(state_bytes, (stream, frame) pairs)``."""
        if self._flushed:
            raise RuntimeError("checkpoint after flush")
        stream = self._check_stream(stream)
        out = self.drain_pending()
        return self._lane_blob(stream), out

    def checkpoint_fleet(self, lanes=None):
        """Serialize the fleet's live stream state in one pass — the
        serving runtime's automatic-snapshot surface (ISSUE 14): the
        in-flight chunk-step is drained ONCE (its emissions returned
        alongside — they belong to the pre-snapshot past and must
        reach the caller, never be silently dropped), then the lane
        blobs are taken against the now-quiescent state. ``lanes``
        restricts serialization to a subset (the server passes its
        OCCUPIED lanes — idle lanes' blobs would be built only to be
        discarded); None means all S. Returns ``({stream:
        state_bytes}, (stream, frame) pairs)``; each blob is exactly
        what :meth:`checkpoint` would produce, so any lane restores
        into a lone receiver or another fleet's :meth:`restore_stream`
        at the same geometry."""
        if self._flushed:
            raise RuntimeError("checkpoint after flush")
        out = self.drain_pending()
        which = range(self.s) if lanes is None \
            else [self._check_stream(i) for i in lanes]
        return {i: self._lane_blob(i) for i in which}, out

    # -- the push surface -----------------------------------------------

    def _ingest(self, stream: int, samples) -> None:
        """The per-stream push seam: shape gate, chaos corruption
        seam (site ``rx.push.s<i>``), non-finite gate (reject, or
        ``sanitize=True`` zero-and-quarantine), then append."""
        from ziria_tpu.utils import faults

        name = f"stream {stream}"
        arr = _slab_array(samples, name)
        arr, _kinds = faults.corrupt_slab(f"rx.push.s{stream}", arr)
        arr, n_bad = _gate_finite(arr, name, self.sanitize,
                                  self._health[stream])
        if n_bad:
            self._sanitized += n_bad
            self._dirty[stream] = True
        if arr.size:
            self._tails[stream] = np.concatenate(
                [self._tails[stream], arr], axis=0)

    def push(self, stream: int, samples) -> List:
        """Append samples ((n, 2) float pairs) to one stream; fire
        every chunk-step that completes. Returns the emitted
        ``(stream, StreamFrame)`` pairs (any stream may emit — a
        completed step drains the previous step's emissions).
        Malformed slabs and non-finite samples fail loudly at the
        seam, naming the stream (or quarantine under
        ``sanitize=True``; docs/robustness.md)."""
        if self._flushed:
            raise RuntimeError("push after flush")
        self._ingest(self._check_stream(stream), samples)
        return self._pump()

    def push_many(self, slabs) -> List:
        """Append one slab per stream (empty slabs fine), THEN pump:
        streams that filled a chunk together ride the same chunk-step
        — the packer's lockstep fast path for synchronized feeds.
        ``slabs`` is a length-S sequence, or a ``{stream_id: slab}``
        dict for sparse arrival; an unknown stream id raises a named
        KeyError."""
        if self._flushed:
            raise RuntimeError("push after flush")
        if isinstance(slabs, dict):
            items = [(self._check_stream(i, KeyError), s)
                     for i, s in slabs.items()]
        else:
            if len(slabs) != self.s:
                raise ValueError(
                    f"{self.s} streams need {self.s} slabs, "
                    f"got {len(slabs)}")
            items = list(enumerate(slabs))
        for i, s in items:
            self._ingest(i, s)
        return self._pump()

    def flush(self) -> List:
        """Close every stream: scan the carried tails (zero-padded to
        the chunk geometry, each stream owning every remaining start)
        as one final chunk-step, then drain the in-flight step.
        Idempotent."""
        if self._flushed:
            return []
        out = self._pump()
        self._flushed = True
        active = [i for i in range(self.s)
                  if self._tails[i].shape[0]]
        if active:
            out += self._step(active, flushing=True)
        if self._pending is not None:
            pend, self._pending = self._pending, None
            out += self._drain(pend)
        return out

    # -- per-lane lifecycle (the serving runtime's lane recycle) --------
    #
    # runtime/serve.py maps client SESSIONS onto this fleet's fixed S
    # lanes: a closing session flushes ITS lane (`flush_stream`), an
    # evicted one checkpoints it (`checkpoint`), and the freed lane is
    # recycled for the next admitted session (`reset_stream`) or a
    # recovering one (`restore_stream`). None of these disturb the
    # other lanes: per-lane state is exactly the single-stream
    # receiver's, and the in-flight chunk-step is drained first only
    # when the touched lane actually rides in it — an idle lane's
    # recycle preserves the double buffer.

    def drain_pending(self) -> List:
        """Block on the in-flight chunk-step (if any) and emit it —
        the double buffer's explicit drain point. Returns the
        ``(stream, StreamFrame)`` pairs; safe to call any time."""
        if self._pending is None:
            return []
        pend, self._pending = self._pending, None
        return self._drain(pend)

    def _pending_touches(self, stream: int) -> bool:
        return self._pending is not None and stream in self._pending[1]

    def flush_stream(self, stream: int) -> List:
        """Close ONE stream: scan its carried tail (zero-padded, the
        lane owning every remaining start — the per-lane twin of
        :meth:`flush`) and drain through it, leaving every other lane
        live. Returns the emitted ``(stream, frame)`` pairs (any lane
        may emit — the in-flight step drains first). The lane's state
        is NOT reset; :meth:`reset_stream` recycles it."""
        stream = self._check_stream(stream)
        if self._flushed:
            raise RuntimeError("flush_stream after flush")
        out = self.drain_pending()
        if self._tails[stream].shape[0]:
            out += self._step([stream], flushing=True)
            out += self.drain_pending()
        return out

    def reset_stream(self, stream: int) -> List:
        """Return one lane to the fresh-stream state (offset 0, empty
        tail/dedupe, clean health) so a NEW session can ride it —
        after :meth:`flush_stream` or an eviction's :meth:`checkpoint`.
        Frames the lane emitted stay credited in :attr:`stats` (the
        ``retired`` accounting). Drains the in-flight step first ONLY
        when this lane rides in it, so recycling an idle lane never
        costs the fleet its double-buffer overlap. Returns the drained
        ``(stream, frame)`` pairs."""
        stream = self._check_stream(stream)
        out = self.drain_pending() if self._pending_touches(stream) \
            else []
        h = self._health[stream]
        self._health[stream] = _LaneHealth(h.blowup_limit,
                                           h.rejoin_after)
        self._dirty[stream] = False
        self._retired += self._emitted[stream]
        self._tails[stream] = np.zeros((0, 2), np.float32)
        self._offsets[stream] = 0
        self._emitted[stream] = 0
        self._watermarks[stream] = 0
        self._seen[stream] = set()
        return out

    def restore_stream(self, stream: int, checkpoint: bytes) -> List:
        """Restore a checkpointed session into lane ``stream`` — the
        eviction-recovery path: a blob from ``checkpoint(i)`` (or a
        lone ``StreamReceiver.checkpoint()``) at the same geometry
        resumes on this lane with bit-identical subsequent emissions
        (per-lane graphs under vmap ARE the single-stream graphs —
        the pinned fleet contract). The quarantine rider restores
        per-lane: a session checkpointed quarantined RESUMES
        quarantined, its lane-mates untouched. The blob's
        degraded/scan_degraded flags deliberately do NOT transfer —
        they describe the OLD runtime's compiled-program health, the
        degraded twins are bit-identical by the pinned contracts (so
        emissions cannot diverge), and importing them would punish
        this fleet's healthy lane-mates with the slow twin. Returns
        the drained ``(stream, frame)`` pairs (the reset's rule)."""
        from ziria_tpu.runtime import resilience

        stream = self._check_stream(stream)
        st = resilience.restore_carry(checkpoint)
        _validate_checkpoint(st, self._geometry())
        out = self.reset_stream(stream)
        self._tails[stream] = np.asarray(st.tail, np.float32)
        self._offsets[stream] = int(st.offset)
        self._emitted[stream] = int(st.emitted)
        # the restored frames were emitted elsewhere: keep this
        # fleet's stats.frames counting ITS emissions only
        self._retired -= int(st.emitted)
        self._watermarks[stream] = int(st.watermark)
        self._seen[stream] = set(st.seen)
        rs = st.state
        h = self._health[stream]
        h.quarantined = bool(rs.get("quarantined", False))
        h.clean = int(rs.get("clean", 0))
        h.blowups = int(rs.get("blowups", 0))
        h.quarantines = int(rs.get("quarantines", 0))
        self._dirty[stream] = bool(rs.get("dirty", False))
        return out

    # -- chunk-step lifecycle -------------------------------------------

    def _pump(self) -> List:
        out: List = []
        while True:
            active = [i for i in range(self.s)
                      if self._tails[i].shape[0] >= self.chunk_len]
            if not active:
                return out
            out += self._step(active, flushing=False)

    def _step(self, active, flushing: bool) -> List:
        """Build one stacked chunk-step over the `active` streams
        (idle lanes ride zeros behind `valid == 0`), launch it, and
        advance the active streams' host carries."""
        from ziria_tpu.utils import dispatch

        arrs = np.zeros((self.s, self.chunk_len, 2), np.float32)
        valid = np.zeros(self.s, np.int32)
        own_lo = np.zeros(self.s, np.int32)
        own_hi = np.zeros(self.s, np.int32)
        adv = {}
        for i in active:
            t = self._tails[i]
            if flushing:
                v = t.shape[0]
                arrs[i, :v] = t
                valid[i] = own_hi[i] = v
                adv[i] = v
            else:
                arrs[i] = t[:self.chunk_len]
                valid[i] = self.chunk_len
                own_hi[i] = self.stride
                adv[i] = self.stride
            # a quarantined stream rides behind the existing valid-
            # mask: its chunk advances (samples consumed) but the
            # detector sees zero valid samples — healthy lanes are
            # untouched by construction (per-lane graphs under vmap),
            # and the <= 2-dispatch budget is preserved
            if self._health[i].step(self._dirty[i]):
                valid[i] = 0
            self._dirty[i] = False
            # the stream's FIRST chunk owns head-truncated preambles
            # (start clamps to 0), exactly the single-stream rule
            own_lo[i] = -192 if self._offsets[i] == 0 else 0
        offs = list(self._offsets)          # snapshot BEFORE advancing
        res = self._launch(arrs, valid, own_lo, own_hi, active, offs)
        for i in active:
            self._tails[i] = self._tails[i][adv[i]:]
            self._offsets[i] += adv[i]
            # per-stream carry depth: with telemetry active these are
            # the per-stream counter-track rows next to the aggregate
            dispatch.record_gauge(f"rx.stream_carry_depth[s{i}]",
                                  self._tails[i].shape[0])
        dispatch.record_gauge("rx.stream_carry_depth",
                              sum(t.shape[0] for t in self._tails))
        return res

    def _put(self, x):
        """Host array -> device, stream axis sharded when a mesh is
        set (the `sweep_ber_sharded` placement rule)."""
        import jax

        if self.mesh is None:
            return jax.device_put(x)
        from ziria_tpu.parallel import batch as pbatch
        return pbatch.shard_batch(self.mesh, x, self.axis)

    def _launch(self, arrs, valid, own_lo, own_hi, active, offs) -> List:
        """Issue the stacked upload + scan dispatch, THEN drain the
        previous chunk-step — the single-stream double buffer, per
        fleet step: step t's transfer and compute are in flight while
        the host blocks on step t-1's scalars."""
        from ziria_tpu.utils import dispatch, programs

        chunk_args = (self._put(arrs), self._put(valid),
                      self._put(own_lo), self._put(own_hi))
        programs.note_site("rx.stream_chunk_multi", self._jit1,
                           *chunk_args)
        outs = self._scan_dispatch(chunk_args)
        self._chunk_steps += 1
        self._inflight += 1
        self._max_in_flight = max(self._max_in_flight, self._inflight)
        self._max_active = max(self._max_active, len(active))
        dispatch.record_gauge("rx.stream_inflight", self._inflight)
        # the fleet-level time series: how many lanes carried real
        # samples this step (idle lanes are the valid-mask riders)
        dispatch.record_gauge("rx.active_streams", len(active))
        dispatch.record_gauge(
            "rx.quarantined_streams",
            float(sum(1 for h in self._health if h.quarantined)))
        dispatch.record_gauge(
            "rx.degraded_mode",
            1.0 if (self._degraded or self._scan_degraded) else 0.0)
        pend, self._pending = self._pending, (
            offs, list(active), arrs, valid.copy(), own_lo.copy(),
            own_hi.copy(), outs)
        return self._drain(pend) if pend is not None else []

    def _scan_dispatch(self, chunk_args):
        """The ONE guarded fleet-scan dispatch (shared by `_launch`
        and the async-rescan path), degrading to the eager twin when
        the compiled program fails for good."""
        from ziria_tpu.runtime import resilience

        if self._scan_degraded:
            return self._eager_chunk(*chunk_args)
        try:
            return resilience.guarded(
                "rx.stream_chunk_multi", self._jit1, *chunk_args,
                policy=self._policy)
        except resilience.DispatchFailed:
            self._mark_degraded(scan=True)
            return self._eager_chunk(*chunk_args)

    def _rescan(self, arrs, valid, own_lo, own_hi):
        """Re-run a chunk-step whose ASYNC results were lost at the
        host pull (the fleet twin of StreamReceiver._rescan)."""
        from ziria_tpu.utils import telemetry

        telemetry.count("resilience.async_rescans")
        return self._scan_dispatch(
            (self._put(arrs), self._put(valid), self._put(own_lo),
             self._put(own_hi)))

    def _drain(self, pend) -> List:
        """Block on a launched chunk-step's per-lane scalars, run the
        host integer decision tree per active stream, and emit —
        dispatching the step's ONE flattened fleet decode when ANY
        stream has a decodable lane (the all-noise fast path skips it
        for the whole fleet)."""
        from ziria_tpu.phy.wifi import rx as _rx
        from ziria_tpu.phy.wifi.params import N_SERVICE_BITS, RATES
        from ziria_tpu.utils import dispatch, programs

        offs, active, arrs, valids, own_lo, own_hi, outs = pend
        try:
            (own, starts, overflow, found, fstart, rb, ln, pk, nv,
             segs) = _pull_chunk(outs)
        except Exception:    # noqa: BLE001 - async loss, re-dispatch
            (own, starts, overflow, found, fstart, rb, ln, pk, nv,
             segs) = _pull_chunk(self._rescan(arrs, valids, own_lo,
                                              own_hi))
        self._inflight -= 1
        self._overflow_chunks += int(overflow[active].sum())

        allcands = []        # (stream, abs_start, row j) in emit order
        for i in active:
            off = offs[i]
            self._watermarks[i] = off
            self._seen[i], cands = _chunk_candidates(
                self._seen[i], off, own[i], starts[i], self.k)
            allcands += [(i, abs_start, j) for abs_start, j in cands]
        if self._degraded:
            # compiled fleet decode already failed for good: the
            # per-capture oracle twin serves every window
            return self._decode_oracle(allcands, starts, arrs, valids)

        emit = {}            # (stream, abs_start) -> RxResult
        lanes = []           # (stream, abs_start, row j, rate, n_sym, lb)
        for i, abs_start, j in allcands:
            avail = int(nv[i, j]) - int(fstart[i, j])
            res, ok = _rx._classify_acquire(
                bool(found[i, j]), avail, int(rb[i, j]),
                int(ln[i, j]), bool(pk[i, j]))
            if ok is None:
                emit[(i, abs_start)] = res
            else:
                lanes.append((i, abs_start, j, ok[0], ok[1],
                              int(ln[i, j])))
        if lanes:
            # (S, K) row tables, zero-filled past each stream's real
            # lanes (ridx 0 / nbits 0 = a full-erasure pad decode —
            # discarded, like every pad lane here); row 0 is safe for
            # idle streams because segs always holds K rows per stream
            rows = np.zeros((self.s, self.k), np.int32)
            ridx = np.zeros((self.s, self.k), np.int32)
            nbits = np.zeros((self.s, self.k), np.int32)
            npsdu = np.zeros((self.s, self.k), np.int32)
            slots = {}
            for i, abs_start, j, m, n_sym, lb in lanes:
                sl = slots.setdefault(i, [])
                pos = len(sl)
                sl.append((abs_start, m, lb))
                rows[i, pos] = j
                ridx[i, pos] = _rx.RATE_INDEX[m]
                nbits[i, pos] = n_sym * RATES[m].n_dbps
                npsdu[i, pos] = 8 * lb
            dec = _rx._jit_stream_decode_multi(
                self.n_sym_bucket, self.viterbi_window,
                self.viterbi_metric, self.viterbi_radix,
                self.mesh, self.axis, self.sco_track,
                self.fused_demap)
            dec_args = (segs, self._put(rows), self._put(ridx),
                        self._put(nbits), self._put(npsdu))
            programs.note_site("rx.stream_decode_multi", dec, *dec_args)
            got = _guarded_decode(self, "rx.stream_decode_multi",
                                  dec, *dec_args)
            if got is None:
                # degrade the WHOLE fleet's decode to the per-capture
                # oracle (bit-identical by the pinned contract), this
                # chunk-step included — healthy lanes keep flowing
                return self._decode_oracle(allcands, starts, arrs,
                                           valids)
            clear, crc = got
            for i, sl in slots.items():
                for pos, (abs_start, m, lb) in enumerate(sl):
                    psdu = clear[i, pos][
                        N_SERVICE_BITS: N_SERVICE_BITS + 8 * lb]
                    emit[(i, abs_start)] = _rx.RxResult(
                        True, m, lb, psdu,
                        bool(crc[i, pos]) if self.check_fcs else None)
        out = []
        for key in sorted(emit):
            i, abs_start = key
            out.append((i, StreamFrame(abs_start, emit[key])))
            self._emitted[i] += 1
        if out:
            from ziria_tpu.utils import telemetry
            telemetry.count("rx.stream_frames", len(out),
                            total=sum(self._emitted))
        return out

    def _decode_oracle(self, allcands, starts, arrs, valids) -> List:
        """The fleet's per-capture decode twin (degraded mode): each
        owned window sliced from its stream's host chunk and pushed
        through per-capture `rx.receive` — the single-stream oracle
        rule, per lane. A window whose receive blows up is counted,
        dropped loudly, and charged to ITS stream's health (repeated
        blowups quarantine that stream; the rest of the fleet keeps
        flowing). Reached only from degraded mode (this path IS the
        resilience opt-in), so containment always applies here."""
        from ziria_tpu.phy.wifi import rx as _rx
        from ziria_tpu.utils import telemetry

        out: List = []
        for i, abs_start, j in sorted(allcands,
                                      key=lambda c: (c[0], c[1])):
            s = int(starts[i, j])
            win = arrs[i][s: min(s + self.frame_len, int(valids[i]))]
            try:
                res = _rx.receive(
                    win, check_fcs=self.check_fcs,
                    viterbi_window=self.viterbi_window,
                    viterbi_metric=self.viterbi_metric,
                    viterbi_radix=self.viterbi_radix,
                    sco_track=self.sco_track)
            except Exception:    # noqa: BLE001 - counted containment
                self._lane_blowups += 1
                self._health[i].blowup()
                telemetry.count("resilience.lane_blowups")
                continue
            out.append((i, StreamFrame(abs_start, res)))
            self._emitted[i] += 1
        if out:
            telemetry.count("rx.stream_frames", len(out),
                            total=sum(self._emitted))
        return out

    def _eager_chunk(self, chunks, valid, own_lo, own_hi):
        """The degraded fleet scan: the SAME stream-axis graph run
        op-by-op (eager vmap, unsharded — results are bit-identical
        on any mesh, so dropping the mesh in the degraded twin loses
        throughput, never correctness)."""
        from ziria_tpu.phy.wifi import rx as _rx
        from ziria_tpu.utils import dispatch

        with dispatch.timed("rx.stream_chunk_multi.eager"):
            return _rx.multi_stream_chunk_graph(
                chunks, valid, own_lo, own_hi, self.k, self.frame_len,
                self.n_sym_bucket, self._threshold, self._min_run,
                self._dead_zone)

    def _mark_degraded(self, scan: bool) -> None:
        if scan:
            self._scan_degraded = True
        else:
            self._degraded = True
        _record_degraded(True)

    def reset_degraded(self) -> None:
        """Leave degraded mode (re-probe the compiled fleet programs
        on the next chunk-step)."""
        self._degraded = False
        self._scan_degraded = False
        _record_degraded(False)


def receive_streams(streams, chunk_len: Optional[int] = None,
                    frame_len: Optional[int] = None,
                    max_frames_per_chunk: Optional[int] = None,
                    check_fcs: bool = False,
                    threshold: Optional[float] = None,
                    min_run: Optional[int] = None,
                    dead_zone: Optional[int] = None,
                    viterbi_window: int = None,
                    viterbi_metric: str = None,
                    viterbi_radix: int = None,
                    multi: Optional[bool] = None, mesh=None,
                    axis: str = "dp",
                    sco_track: Optional[bool] = None,
                    fused_demap: Optional[bool] = None,
                    geometry: Optional[_geometry.Geometry] = None):
    """Decode S concurrent multi-frame I/Q streams in O(chunk-steps)
    device dispatches — <= 2 per chunk-step *independent of S*.
    Returns ``(per_stream_frames, stats)``: a per-stream position-
    ordered list of :class:`StreamFrame` (each bit-identical, RxResult
    field for field, to what a lone single-stream receiver — and hence
    per-capture ``rx.receive`` over the slice — emits for that
    stream) and the :class:`MultiStreamStats`.

    ``multi=False`` (or ``--no-multi-stream`` / ``ZIRIA_MULTI_STREAM=0``)
    runs S independent single-stream :class:`StreamReceiver`\\ s — the
    bit-identity oracle, >= S x the dispatch count. ``mesh`` shards
    the stream axis over the dp device mesh
    (`parallel/batch.frame_mesh`; S must divide it). Push-driven
    callers (live feeds with ragged arrival) use
    :class:`MultiStreamReceiver` directly."""
    s = len(streams)
    if s == 0:
        return [], MultiStreamStats(0, 0, 0, 0, 0, 0)
    kw = dict(chunk_len=chunk_len, frame_len=frame_len,
              max_frames_per_chunk=max_frames_per_chunk,
              check_fcs=check_fcs, threshold=threshold,
              min_run=min_run, dead_zone=dead_zone,
              viterbi_window=viterbi_window,
              viterbi_metric=viterbi_metric,
              viterbi_radix=viterbi_radix, sco_track=sco_track,
              fused_demap=fused_demap, geometry=geometry)
    if not multi_stream_enabled(multi):
        if mesh is not None:
            # a sharded-vs-oracle comparison must never silently
            # measure the wrong configuration: the oracle is S
            # unsharded single-stream receivers by definition
            raise ValueError(
                "mesh sharding needs the fleet path: multi=False / "
                "ZIRIA_MULTI_STREAM=0 runs S independent single-"
                "stream receivers, which cannot honor a stream-axis "
                "mesh")
        per, chunks, frames, ovf, infl = [], 0, 0, 0, 0
        for st in streams:
            got, stats = receive_stream(np.asarray(st, np.float32),
                                        **kw)
            per.append(got)
            chunks += stats.chunks
            frames += stats.frames
            ovf += stats.overflow_chunks
            infl = max(infl, stats.max_in_flight)
        return per, MultiStreamStats(s, chunks, frames, ovf, infl,
                                     1 if chunks else 0)
    msr = MultiStreamReceiver(s, mesh=mesh, axis=axis, **kw)
    got = msr.push_many([np.asarray(st, np.float32) for st in streams])
    got += msr.flush()
    per = [[] for _ in range(s)]
    for i, fr in got:
        per[i].append(fr)
    return per, msr.stats


def transmit_many(psdus, rates_mbps, add_fcs: bool = False,
                  batched_tx: Optional[bool] = None) -> List[np.ndarray]:
    """One-dispatch mixed-rate TX batch surface (thin re-export of
    phy/link.transmit_many, next to its RX twin `receive_many`): N
    frames encoded as ONE vmap(lax.switch) device call, returned at
    their true lengths — or the per-frame oracle loop under
    ``ZIRIA_BATCHED_TX=0`` — bit-identical either way."""
    from ziria_tpu.phy import link
    return link.transmit_many(psdus, rates_mbps, add_fcs=add_fcs,
                              batched_tx=batched_tx)


def loopback_many(psdus, rates_mbps, **kw) -> List[Any]:
    """The full device-resident N-frame loopback (thin re-export of
    phy/link.loopback_many): ONE fused dispatch by default, or the
    staged encode -> per-lane channel -> batched receive ~5-dispatch
    oracle under ``fused=False`` / ``ZIRIA_FUSED_LINK=0``."""
    from ziria_tpu.phy import link
    return link.loopback_many(psdus, rates_mbps, **kw)


def run_many(comp: ir.Comp, frames: Sequence[Sequence[Any]],
             max_out: Optional[int] = None,
             batcher: Optional[StepBatcher] = None) -> List[Any]:
    """Run `comp` once per entry of `frames` (each an independent input
    stream), batching chunk-machine device steps across frames. Returns
    the per-frame :class:`interp.Result`s, bit-identical to running
    each frame alone. Pass a hybridized comp (`hybrid.hybridize`) —
    a plain comp works too, it just has no device steps to batch."""
    from ziria_tpu.interp.interp import run

    n = len(frames)
    if n == 0:
        return []
    if n == 1:   # no threads, no batcher: exactly the single-frame path
        return [run(comp, list(frames[0]), max_out=max_out)]

    b = batcher if batcher is not None else StepBatcher(n)
    with b._cv:
        b._active = n   # reconcile a caller-supplied/reused batcher:
        b._parked.clear()  # a stale count deadlocks or defeats batching
    results: List[Any] = [None] * n
    errors: List[Optional[BaseException]] = [None] * n

    def worker(i: int, xs) -> None:
        C._TLS.batcher = b
        try:
            results[i] = run(comp, list(xs), max_out=max_out)
        except BaseException as e:
            errors[i] = e
        finally:
            C._TLS.batcher = None
            b.frame_finished()

    threads = [threading.Thread(target=worker, args=(i, xs),
                                name=f"ziria-frame-{i}", daemon=True)
               for i, xs in enumerate(frames)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for e in errors:
        if e is not None:
            raise e
    return results
