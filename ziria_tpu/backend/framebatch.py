"""Frame batching for chunked state machines: N independent streams,
one device call per step.

The reference ran one PHY pipeline per thread and scaled frames by
adding threads (SURVEY.md §2.2 thread separators); a TPU behind a host
link scales the other way — batch the *device work* of many frames into
single calls so the per-call round-trip (tens of ms through the axon
tunnel) amortizes across frames. The library receiver already does this
with a leading frame axis (phy/wifi/rx.py). This module gives the same
economics to ANY compiled `.zir` program (VERDICT r3 next #3): a
1000-byte DSL receive costs ~8 device calls; 16 frames through
`run_many` cost ~the same 8 vmapped calls, not 128.

Design — continuation batching over the interpreter:

- each frame runs the normal interpreter/hybrid executor in its own
  thread (host control flow stays per-frame Python: divergent rates,
  ragged lengths, interpreter EOF tails all Just Work);
- when a frame's `_ChunkLoop` needs a device step it *parks* its
  request in the shared :class:`StepBatcher` (`chunked._step_call`
  routes here via a thread-local);
- when every unfinished frame is parked, the quorum thread fires:
  requests are grouped by (machine, jit key, operand shapes), each
  group's operands are stacked and run through ONE `jax.vmap`-ped step
  — JAX's `lax.while_loop` batching rule executes while ANY lane's
  guard holds and `select`s per-lane carries, so lanes consume their
  own cursors/iteration counts and bit-exactness per lane is preserved
  — and every parked frame resumes with its lane of the result.

Frames that drift to different program points simply land in different
groups (two smaller calls); frames in lockstep — the common case for
same-shape captures — ride one call. Lane counts are padded to the
next power of two (lane 0 repeated) so XLA compiles O(log N) batched
variants, not one per group size.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional, Sequence

import numpy as np

from ziria_tpu.backend import chunked as C
from ziria_tpu.core import ir
from ziria_tpu.utils.dispatch import pad_lanes, pow2_ceil


def _shape_sig(args):
    import jax
    return tuple(
        (tuple(np.shape(x)), np.asarray(x).dtype.str) if not hasattr(
            x, "aval") else (tuple(x.shape), x.dtype.str)
        for x in jax.tree_util.tree_leaves(args))


class _Req:
    __slots__ = ("node", "key", "args", "done", "result", "exc")

    def __init__(self, node, key, args):
        self.node = node
        self.key = key
        self.args = args
        self.done = False
        self.result = None
        self.exc: Optional[BaseException] = None


class StepBatcher:
    """Collects concurrent chunk-step requests from frame threads and
    services them in vmapped groups. `device_calls` counts actual
    device dispatches (one per fired group) — the number the frame-
    batching contract is about."""

    def __init__(self, n_frames: int):
        self._cv = threading.Condition()
        self._active = n_frames
        self._parked: List[_Req] = []
        self._vfns = {}
        self.device_calls = 0
        self.group_sizes: List[int] = []   # fired lane counts (stats)

    # -- frame lifecycle ------------------------------------------------

    def frame_finished(self) -> None:
        with self._cv:
            self._active -= 1
            if self._parked and len(self._parked) >= self._active:
                self._fire_locked()

    # -- the park point (called from chunked._step_call) ---------------

    def call(self, node, key, args):
        req = _Req(node, key, args)
        with self._cv:
            self._parked.append(req)
            if len(self._parked) >= self._active:
                self._fire_locked()
            while not req.done:
                self._cv.wait()
        if req.exc is not None:
            raise req.exc
        return req.result

    # -- firing ---------------------------------------------------------

    def _vfn(self, node, key):
        import jax
        k = (id(node), key)
        f = self._vfns.get(k)
        if f is None:
            f = jax.jit(jax.vmap(node._steps[key]))
            self._vfns[k] = f
        return f

    def _fire_locked(self) -> None:
        batch, self._parked = self._parked, []
        try:
            self._service(batch)
        finally:
            # every parked thread MUST wake whatever happened above —
            # a request left done=False would wait forever
            for r in batch:
                if not r.done:
                    if r.exc is None and r.result is None:
                        r.exc = RuntimeError(
                            "step batch aborted before this lane ran")
                    r.done = True
            self._cv.notify_all()

    def _service(self, batch: List[_Req]) -> None:
        import jax
        import jax.numpy as jnp

        groups = {}
        for r in batch:
            sig = (id(r.node), r.key, _shape_sig(r.args))
            groups.setdefault(sig, []).append(r)
        for reqs in groups.values():
            try:
                if len(reqs) == 1:
                    r = reqs[0]
                    r.result = r.node._fns[r.key](*r.args)
                else:
                    lanes = len(reqs)
                    padded = pad_lanes(reqs)
                    stacked = jax.tree_util.tree_map(
                        lambda *xs: jnp.stack(xs),
                        *[r.args for r in padded])
                    it_b, pos_b, out_n_b, out_buf_b, rvals_b = \
                        self._vfn(reqs[0].node, reqs[0].key)(*stacked)
                    # every lane's (it, pos, out_n) in ONE transfer,
                    # and every lane's emitted prefix in one more: per
                    # -lane scalar reads and per-lane buffer flushes
                    # through a high-latency host link would cost a
                    # round trip each and dwarf the batched call
                    metas = np.asarray(jnp.stack(
                        [it_b, pos_b, out_n_b], axis=1))
                    bufs = None
                    if getattr(out_buf_b, "ndim", 0) >= 2:
                        max_k = int(metas[:lanes, 2].max())
                        if max_k:
                            bufs = np.asarray(
                                out_buf_b[:lanes, :max_k])
                    for i, r in enumerate(reqs):
                        ob = bufs[i] if bufs is not None \
                            else out_buf_b[i]
                        r.result = (metas[i, 0], metas[i, 1],
                                    metas[i, 2], ob,
                                    jax.tree_util.tree_map(
                                        lambda x, i=i: x[i], rvals_b))
                C.STATS["device_calls"] += 1
                self.device_calls += 1
                from ziria_tpu.utils import dispatch
                dispatch.record("framebatch.step")
                self.group_sizes.append(len(reqs))
            except Exception:
                # a vmap-only failure must not abort frames whose
                # per-frame step is fine (or worse, mark the shared
                # machine broken): retry each lane unbatched; only a
                # lane whose OWN direct call fails gets the exception
                for r in reqs:
                    try:
                        r.result = r.node._fns[r.key](*r.args)
                        C.STATS["device_calls"] += 1
                        self.device_calls += 1
                        from ziria_tpu.utils import dispatch
                        dispatch.record("framebatch.step")
                        self.group_sizes.append(1)
                    except Exception as le:
                        r.exc = le
            for r in reqs:
                r.done = True


def receive_many(captures: Sequence[Any], check_fcs: bool = False,
                 max_samples: int = 1 << 16,
                 viterbi_window: int = None,
                 viterbi_metric: str = None,
                 batched_acquire: Optional[bool] = None) -> List[Any]:
    """Frame-batched library receiver: N independent captures -> N
    :class:`rx.RxResult`s in O(1) device dispatches — acquire ->
    gather -> mixed-rate decode:

    1. **acquire** (`rx.acquire_many`): STS detect, LTS peak-pick,
       CFO, on-device alignment, and SIGNAL decode for ALL lanes as
       ONE vmapped dispatch; the host does only the integer header
       parsing and the symbol-bucket choice.
    2. **gather** (`rx.gather_segments_many`): every decodable lane's
       data region sliced at its own offset and derotated by its own
       CFO phase at ONE common symbol bucket — one dispatch, output
       device-resident.
    3. **decode** (`rx.decode_data_mixed`): the one-``lax.switch``
       mixed-rate DATA decode — lanes with DIFFERENT rates share the
       same device call and the same Pallas Viterbi batch.

    ``batched_acquire=False`` (or env ``ZIRIA_BATCHED_ACQUIRE=0``)
    falls back to the host-driven per-capture acquisition loop (~3
    round trips per capture — the pre-batched oracle). Either way,
    results are bit-identical to per-capture ``rx.receive`` lane for
    lane, including no-detect / bad-parity / truncated lanes; lane
    counts pad to the next power of two (lane 0 repeated) so XLA
    compiles O(log N) batch variants.
    """
    import os

    import jax.numpy as jnp

    from ziria_tpu.phy.wifi import rx as _rx

    if batched_acquire is None:
        batched_acquire = os.environ.get(
            "ZIRIA_BATCHED_ACQUIRE", "1") != "0"

    results: List[Any] = [None] * len(captures)
    if batched_acquire:
        results, x_dev, acqs = _rx.acquire_many(captures, max_samples)
    else:
        acqs = []
        for i, s in enumerate(captures):
            res, acq = _rx._acquire_frame(s, max_samples)
            if acq is None:
                results[i] = res
            else:
                acqs.append((i, acq))
    if not acqs:
        return results

    # one common bucket = one compiled geometry for the whole batch;
    # smaller frames pay pad symbols (zero-LLR erasures), not a second
    # compile or a second dispatch
    n_sym_b = max(_rx._sym_bucket(a.n_sym) for _i, a in acqs)
    padded = pad_lanes(acqs)
    if batched_acquire:
        segs = _rx.gather_segments_many(
            x_dev, [a for _i, a in padded], n_sym_b)
    else:
        segs = jnp.stack([_rx._padded_segment(a, n_sym_b)
                          for _i, a in padded])
    return _mixed_decode_tail(acqs, padded, segs, n_sym_b, results,
                              check_fcs, viterbi_window, viterbi_metric)


def _mixed_decode_tail(acqs, padded, segs, n_sym_b: int,
                       results: List[Any], check_fcs: bool,
                       viterbi_window, viterbi_metric):
    """The shared tail of every batched receive surface: ONE
    mixed-rate decode dispatch over the lane-padded segments, plus —
    when FCS checking is on — ONE vmapped masked-CRC dispatch at the
    common bucket over the still-device-resident decode output
    (previously a hidden host `check_crc32` dispatch PER LANE), then
    the per-lane PSDU slice. CRC booleans are bit-identical to the
    per-lane path (`ops/crc.check_crc32_masked` is the same table
    scan, masked). `acqs` is [(i, acq)] for the real lanes (acq needs
    .rate_mbps/.n_sym/.length_bytes — both the host `_Acquired` and
    batched `_LaneAcq` shapes qualify); `padded` is THE pad_lanes
    list the caller built `segs` from — passed in, not recomputed, so
    the ridx/nbits rows can never disagree with the segment rows."""
    import jax.numpy as jnp

    from ziria_tpu.phy.wifi import rx as _rx
    from ziria_tpu.phy.wifi.params import N_SERVICE_BITS, RATES
    from ziria_tpu.utils import dispatch

    ridx = jnp.asarray([_rx.RATE_INDEX[a.rate_mbps] for _i, a in padded],
                       jnp.int32)
    nbits = jnp.asarray(
        [a.n_sym * RATES[a.rate_mbps].n_dbps for _i, a in padded],
        jnp.int32)
    dec = _rx._jit_decode_data_mixed(n_sym_b, viterbi_window,
                                     viterbi_metric)
    with dispatch.timed("rx.decode_mixed"):
        clear_dev = dec(segs, ridx, nbits)
    crc_b = None
    if check_fcs:
        npsdu = jnp.asarray([8 * a.length_bytes for _i, a in padded],
                            jnp.int32)
        with dispatch.timed("rx.crc_many"):
            crc_b = np.asarray(_rx._jit_crc_many()(clear_dev, npsdu))
    clear = np.asarray(clear_dev, np.uint8)
    for k, (i, a) in enumerate(acqs):
        psdu = clear[k][N_SERVICE_BITS: N_SERVICE_BITS
                        + 8 * a.length_bytes]
        crc = bool(crc_b[k]) if check_fcs else None
        results[i] = _rx.RxResult(True, a.rate_mbps, a.length_bytes,
                                  psdu, crc)
    return results


def receive_many_device(x_dev, n_lanes: int, check_fcs: bool = False,
                        viterbi_window: int = None,
                        viterbi_metric: str = None) -> List[Any]:
    """Batched receive over an ALREADY device-resident capture batch —
    the RX side of the loopback link (phy/link.py): the channel's
    output feeds acquisition without the samples ever crossing the
    host link.

    x_dev: (R, L, 2) device array, R a power-of-two lane count (rows
    past `n_lanes` repeating row 0 — the pad_lanes rule) and L a
    power-of-two >= 512 capture bucket; the WHOLE buffer of every lane
    is its capture (n_valid = L: the batched channel fills it with
    real air samples). Three dispatches — acquire -> gather -> mixed
    decode — with results bit-identical to per-capture `rx.receive`
    over `np.asarray(x_dev[i])`."""
    from ziria_tpu.phy.wifi import rx as _rx

    l_cap = int(x_dev.shape[1])
    if l_cap != _rx._stream_bucket(l_cap):
        raise ValueError(
            f"capture length {l_cap} is not a power-of-two >= 512 "
            f"bucket; per-capture receive would pad to "
            f"{_rx._stream_bucket(l_cap)} and the identity contract "
            f"needs identical geometry")
    nv = np.full((int(x_dev.shape[0]),), l_cap, np.int32)
    results, lanes = _rx.acquire_batch(x_dev, nv, nv, n_lanes)
    if not lanes:
        return results
    n_sym_b = max(_rx._sym_bucket(a.n_sym) for _i, a in lanes)
    padded = pad_lanes(lanes)
    segs = _rx.gather_segments_many(
        x_dev, [a for _i, a in padded], n_sym_b)
    return _mixed_decode_tail(lanes, padded, segs, n_sym_b, results,
                              check_fcs, viterbi_window, viterbi_metric)


def transmit_many(psdus, rates_mbps, add_fcs: bool = False,
                  batched_tx: Optional[bool] = None) -> List[np.ndarray]:
    """One-dispatch mixed-rate TX batch surface (thin re-export of
    phy/link.transmit_many, next to its RX twin `receive_many`): N
    frames encoded as ONE vmap(lax.switch) device call, returned at
    their true lengths — or the per-frame oracle loop under
    ``ZIRIA_BATCHED_TX=0`` — bit-identical either way."""
    from ziria_tpu.phy import link
    return link.transmit_many(psdus, rates_mbps, add_fcs=add_fcs,
                              batched_tx=batched_tx)


def loopback_many(psdus, rates_mbps, **kw) -> List[Any]:
    """The full device-resident N-frame loopback (thin re-export of
    phy/link.loopback_many): ONE fused dispatch by default, or the
    staged encode -> per-lane channel -> batched receive ~5-dispatch
    oracle under ``fused=False`` / ``ZIRIA_FUSED_LINK=0``."""
    from ziria_tpu.phy import link
    return link.loopback_many(psdus, rates_mbps, **kw)


def run_many(comp: ir.Comp, frames: Sequence[Sequence[Any]],
             max_out: Optional[int] = None,
             batcher: Optional[StepBatcher] = None) -> List[Any]:
    """Run `comp` once per entry of `frames` (each an independent input
    stream), batching chunk-machine device steps across frames. Returns
    the per-frame :class:`interp.Result`s, bit-identical to running
    each frame alone. Pass a hybridized comp (`hybrid.hybridize`) —
    a plain comp works too, it just has no device steps to batch."""
    from ziria_tpu.interp.interp import run

    n = len(frames)
    if n == 0:
        return []
    if n == 1:   # no threads, no batcher: exactly the single-frame path
        return [run(comp, list(frames[0]), max_out=max_out)]

    b = batcher if batcher is not None else StepBatcher(n)
    with b._cv:
        b._active = n   # reconcile a caller-supplied/reused batcher:
        b._parked.clear()  # a stale count deadlocks or defeats batching
    results: List[Any] = [None] * n
    errors: List[Optional[BaseException]] = [None] * n

    def worker(i: int, xs) -> None:
        C._TLS.batcher = b
        try:
            results[i] = run(comp, list(xs), max_out=max_out)
        except BaseException as e:
            errors[i] = e
        finally:
            C._TLS.batcher = None
            b.frame_finished()

    threads = [threading.Thread(target=worker, args=(i, xs),
                                name=f"ziria-frame-{i}", daemon=True)
               for i, xs in enumerate(frames)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for e in errors:
        if e is not None:
            raise e
    return results
