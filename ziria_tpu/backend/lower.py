"""JAX lowering: fuse a static-rate pipeline into one jit step function.

Where the reference compiles each component to C init/tick/process state
machines glued by buffer calls (SURVEY.md §2.1 CgMonad/CgExpr and §3.2's
tick/process hot loop), this backend turns the *whole* static-cardinality
pipeline segment into a single pure function

    step : (carry, in_chunk) -> (carry, out_chunk)

and lets XLA fuse it. The synchronous-dataflow steady state (core/card.py)
gives each stage a firing count per iteration; a planner width ``W``
multiplies that by how many steady-state iterations one step processes.
Per stage:

- stateless stages (``Map``, ``Repeat`` of a static computer) become
  ``reshape (F, arity, ...) -> vmap -> reshape`` — F = reps*W parallel
  firings on the VPU/MXU, the analogue of the reference vectorizer's
  widened take/emit arrays;
- stateful stages (``MapAccum``, ``JaxBlock``) become ``lax.scan`` over
  their F firings (sequential by data dependence, exactly like the
  reference's stateful blocks);
- ``Repeat`` bodies are turned into firing functions by *tracing the
  interpreter* with jax values — the oracle and the compiler share one
  semantics, so they cannot drift.

Vectorization is therefore *planning, not rewriting*: no AST transform,
no mitigator insertion — rate mismatches are handled by the reshape
algebra, and W is a tuning knob (see ``plan_width``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ziria_tpu.core import ir
from ziria_tpu.core.card import CCard, SteadyState, cardinality, steady_state
from ziria_tpu.core.ir import Env
from ziria_tpu.interp.interp import _run


class LowerError(Exception):
    """A pipeline (segment) can't be lowered to the jit backend. The
    message says which node and why; such programs still run on the
    interpreter backend."""


# --------------------------------------------------------------------------
# Computer body -> firing function, by tracing the interpreter
# --------------------------------------------------------------------------


def firing_fn(body: ir.Comp) -> Tuple[Callable, int, int]:
    """Build ``fire(in_items) -> out_items`` for a static computer body.

    in_items has shape (take, *item); out_items (emit, *item_out) — for
    take/emit == 1 the bare item is used. The body is executed by the
    streaming interpreter with xp=jnp, so jax tracers flow through it;
    data-dependent control flow (While / value Branch) raises a
    TracerBoolConversionError, which we re-raise as LowerError with
    guidance.
    """
    c = cardinality(body)
    if not isinstance(c, CCard):
        raise LowerError(
            f"cannot lower computer body {body.label()}: cardinality is "
            f"not static")
    n_take, n_emit = c.take, c.emit
    if n_emit == 0:
        raise LowerError(
            f"cannot lower pure-sink body {body.label()} (emits nothing): "
            f"jit segments produce output chunks; run sink computations on "
            f"the interpreter backend")

    def fire(in_items):
        idx = [0]

        def src():
            if idx[0] >= n_take:
                raise LowerError(
                    f"body {body.label()} took more than its static "
                    f"cardinality {n_take}")
            x = in_items if n_take == 1 else in_items[idx[0]]
            idx[0] += 1
            return x

        outs = []
        gen = _run(body, Env(), src, xp=jnp)
        try:
            while True:
                outs.append(next(gen))
        except StopIteration:
            pass
        except (jax.errors.TracerBoolConversionError,
                jax.errors.ConcretizationTypeError) as e:
            raise LowerError(
                f"body {body.label()} has data-dependent control flow; "
                f"express it with lax.cond/select inside a map/jax_block "
                f"instead, or run on the interpreter backend") from e
        if len(outs) != n_emit:
            raise LowerError(
                f"body {body.label()} emitted {len(outs)} items, static "
                f"cardinality says {n_emit}")
        if n_emit == 1:
            return jnp.asarray(outs[0])
        return jnp.stack([jnp.asarray(o) for o in outs])

    return fire, n_take, n_emit


# --------------------------------------------------------------------------
# Per-stage lowering
# --------------------------------------------------------------------------


def _apply_parallel(f: Callable, chunk, a: int, b: int, F: int):
    """Apply stateless per-firing f over F firings packed in `chunk`
    ((F*a, *item) -> (F*b, *item_out)) via reshape + vmap."""
    xs = chunk if a == 1 else chunk.reshape((F, a) + chunk.shape[1:])
    ys = jax.vmap(f)(xs)
    return ys if b == 1 else ys.reshape((F * b,) + ys.shape[2:])


def _apply_scan(f: Callable, state, chunk, a: int, b: int, F: int):
    """Apply stateful per-firing f over F firings sequentially (lax.scan)."""
    xs = chunk if a == 1 else chunk.reshape((F, a) + chunk.shape[1:])
    state, ys = lax.scan(f, state, xs)
    return state, (ys if b == 1 else ys.reshape((F * b,) + ys.shape[2:]))


@dataclass
class _Stage:
    fn: Callable  # (state, chunk) -> (state, out_chunk)
    init_state: Any
    label: str


def _lower_stage(stage: ir.Comp, F: int) -> _Stage:
    if isinstance(stage, ir.Map):
        a, b = stage.in_arity, stage.out_arity

        def fn(state, chunk, _f=stage.f, _a=a, _b=b, _F=F):
            return state, _apply_parallel(_f, chunk, _a, _b, _F)

        return _Stage(fn, None, stage.label())

    if isinstance(stage, (ir.MapAccum, ir.JaxBlock)):
        a, b = stage.in_arity, stage.out_arity

        def fn(state, chunk, _f=stage.f, _a=a, _b=b, _F=F):
            return _apply_scan(_f, state, chunk, _a, _b, _F)

        init = jax.tree.map(jnp.asarray, stage.init_state())
        return _Stage(fn, init, stage.label())

    if isinstance(stage, ir.Repeat):
        fire, a, b = firing_fn(stage.body)
        if a == 0:
            raise LowerError(
                "cannot lower a pure-source repeat inside a fused segment")

        def fn(state, chunk, _f=fire, _a=a, _b=b, _F=F):
            return state, _apply_parallel(_f, chunk, _a, _b, _F)

        return _Stage(fn, None, f"repeat({stage.body.label()})")

    raise LowerError(
        f"stage {stage.label()} ({type(stage).__name__}) is not lowerable: "
        f"jit segments are built from Map/MapAccum/JaxBlock/Repeat-of-"
        f"static-computer; run dynamic structure on the interpreter or "
        f"wrap it in a jax_block")


# --------------------------------------------------------------------------
# Whole-pipeline lowering
# --------------------------------------------------------------------------


@dataclass
class Lowered:
    """A fused pipeline segment: call ``step(carry, in_chunk)``; in_chunk
    carries ``take`` items (leading axis), out ``emit`` items."""

    step: Callable
    init_carry: Tuple
    take: int
    emit: int
    width: int
    ss: SteadyState
    labels: Tuple[str, ...]

    def scan_steps(self):
        """(carry, chunks[T, take, ...]) -> (carry, outs[T, emit, ...]) —
        the whole bulk of a stream in one XLA while-loop."""

        def many(carry, chunks):
            return lax.scan(self.step, carry, chunks)

        return many


def plan_width(ss: SteadyState, target_items: int = 8192) -> int:
    """Pick how many steady-state iterations one step processes.

    The reference's vectorizer searches per-segment (in,out) scale factors
    with a utility model (SURVEY.md §2.1 VecSF); on TPU the considerations
    collapse to "make the fused chunk big enough to fill the VPU/MXU and
    amortize dispatch": default to ~target_items items per chunk.
    """
    per_iter = max(ss.take, ss.emit, 1)
    return max(1, target_items // per_iter)


def lower(comp: ir.Comp, width: Optional[int] = None,
          target_items: int = 8192) -> Lowered:
    """Lower a static-rate pipeline to a fused step function."""
    stages = ir.pipeline_stages(comp)
    ss = steady_state(stages)
    if ss is None:
        raise LowerError(
            "pipeline has no static steady state; stages: "
            + ", ".join(s.label() for s in stages))
    W = width if width is not None else plan_width(ss, target_items)
    lowered = [_lower_stage(s, r * W) for s, r in zip(stages, ss.reps)]
    init_carry = tuple(s.init_state for s in lowered)

    def step(carry, chunk):
        new_carry = []
        for st, c in zip(lowered, carry):
            c, chunk2 = st.fn(c, chunk)
            new_carry.append(c)
            chunk = chunk2
        return tuple(new_carry), chunk

    return Lowered(step=step, init_carry=init_carry, take=ss.take * W,
                   emit=ss.emit * W, width=W, ss=ss,
                   labels=tuple(s.label for s in lowered))
