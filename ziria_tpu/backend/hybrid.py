"""Hybrid executor: interpreter-driven stream control, jit-compiled
do-blocks.

The reference compiles EVERYTHING to C — including the dynamic control
the fused jit backend here refuses (value-dependent branches, dynamic
trip counts, per-item takes; SURVEY.md §2.1 CgComp's state machines).
The TPU-native middle ground: keep the streaming interpreter as the
control driver (items, binds, branches run concretely on the host) but
execute each *heavy imperative do-block* as one cached `jax.jit`
function over the environment it touches. The flagship receiver
(`examples/wifi_rx.zir`) is exactly this shape — a few hundred
samples of per-item control around multi-thousand-op DSP blocks (LTS
correlation, per-symbol FFT/equalize/demap) — so the hot math runs as
compact XLA (with the evaluator's fori_loop staging keeping graphs
small) while header-driven dispatch stays host-level and exact.

Mechanism: `hybridize(comp)` rewrites `ir.Return(closure)` nodes whose
attached surface statements (``closure.z_stmts``, set by the
elaborator) weigh above a threshold into `_JitDo` wrappers. The wrapper
flattens the `ir.Env` chain to a pytree argument, rebuilds an identical
chain of traced values inside jit, runs the SAME staged evaluator the
fused backend traces (one semantics, shared with the oracle), and
writes updated refs back. Each distinct env signature compiles once.

Blocks containing `print`/`println`/`error` are never wrapped (side
effects must fire per execution, and `error` must raise
data-dependently), and any wrapper failure falls back to the direct
closure — the interpreter semantics are always the fallback.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ziria_tpu.core import ir
from ziria_tpu.frontend import ast as A

# a do-block is worth a jit round-trip when its (loop-weighted) op
# count clears this; below it host dispatch overhead wins
MIN_JIT_WEIGHT = 300

# literal loop counts multiply body weight, capped so one huge loop
# does not dominate the decision arithmetic
_LOOP_W_CAP = 256


def _expr_weight(e: Optional[A.Expr]) -> int:
    if e is None:
        return 0
    base = 2 if isinstance(e, A.ECall) else 1
    return base + sum(_expr_weight(k) for k in A.child_exprs(e))


def _loop_mult(count: Optional[A.Expr]) -> int:
    if isinstance(count, A.EInt):
        return max(1, min(int(count.val), _LOOP_W_CAP))
    return 8                                  # unknown count: assume some


def _stmts_weight(stmts) -> int:
    w = 0
    for st in stmts:
        w += sum(_expr_weight(e) for e in A.stmt_exprs(st)) + 1
        if isinstance(st, A.SFor):
            w += _loop_mult(st.count) * (1 + _stmts_weight(st.body))
        elif isinstance(st, A.SWhile):
            w += 8 * (1 + _stmts_weight(st.body))
        elif isinstance(st, A.SIf):
            w += _stmts_weight(st.then) + _stmts_weight(st.els)
    return w


def _has_effects(stmts, ctx=None, _seen: Optional[set] = None) -> bool:
    """print/println/error anywhere in the block — including inside
    user functions it calls (recursing through ctx.funs, like the LUT
    purity analysis) — such blocks must run un-jitted so effects fire
    per execution, not once at trace time."""
    seen = _seen if _seen is not None else set()
    for e in A.iter_stmt_exprs(stmts):
        if not isinstance(e, A.ECall):
            continue
        if e.name in ("print", "println", "error"):
            return True
        if ctx is not None and e.name in getattr(ctx, "funs", {}) \
                and e.name not in seen:
            seen.add(e.name)
            if _has_effects(ctx.funs[e.name].decl.body, ctx, seen):
                return True
    return False


# ------------------------------------------------------------ env pytree


def _env_signature(env: ir.Env, keep=None,
                   writes=None) -> Tuple[Tuple, List[Any]]:
    """Flatten the env chain to (structure, values). Structure is a
    hashable per-level tuple of (var names, ref names, written-ref
    names) outermost-first; values align with the first two.

    `keep`/`writes` slice the env to the block's syntactic read/write
    sets: a do-block next to a 131072-entry frame buffer it never
    touches must not ship that buffer to the device and back on every
    firing (measured: the whole win disappeared into env traffic)."""
    levels = []
    e = env
    while e is not None:
        levels.append(e)
        e = e._parent
    levels.reverse()
    struct, vals = [], []
    for lv in levels:
        vnames = tuple(n for n in lv._vars
                       if keep is None or n in keep)
        rnames = tuple(n for n in lv._refs
                       if keep is None or n in keep)
        wnames = tuple(n for n in rnames
                       if writes is None or n in writes)
        struct.append((vnames, rnames, wnames))
        vals.extend(lv._vars[n] for n in vnames)
        vals.extend(lv._refs[n] for n in rnames)
    return tuple(struct), vals


def _env_rebuild(struct: Tuple, vals: List[Any]) -> ir.Env:
    env = None
    it = iter(vals)
    for vnames, rnames, _wn in struct:
        env = ir.Env(env)
        for n in vnames:
            env.bind(n, next(it))
        for n in rnames:
            env.bind_ref(n, next(it))
    return env


def _env_refs(env: ir.Env, struct: Tuple) -> List[Any]:
    """WRITTEN ref values in structure order (outermost level first)."""
    levels = []
    e = env
    while e is not None:
        levels.append(e)
        e = e._parent
    levels.reverse()
    out = []
    for lv, (_vn, _rn, wnames) in zip(levels, struct):
        out.extend(lv._refs[n] for n in wnames)
    return out


def _env_write_refs(env: ir.Env, struct: Tuple, vals: List[Any]) -> None:
    levels = []
    e = env
    while e is not None:
        levels.append(e)
        e = e._parent
    levels.reverse()
    it = iter(vals)
    for lv, (_vn, _rn, wnames) in zip(levels, struct):
        for n in wnames:
            lv._refs[n] = next(it)


class _JitDo:
    """Wraps one do-block closure: env -> jit(env-pytree) with ref
    write-back. Falls back to the direct closure on any staging
    failure (recorded so it does not retry every firing)."""

    def __init__(self, closure):
        self.closure = closure
        self._fns: Dict[Tuple, Any] = {}
        self._ok: set = set()      # structs that completed a real call
        self._broken = False
        # syntactic read/write sets slice the env: only touched names
        # cross the host<->device boundary per firing
        stmts = getattr(closure, "z_stmts", None)
        if stmts is not None:
            from ziria_tpu.frontend.eval import _stmt_reads, _stmt_writes
            reads: set = set()
            writes: set = set()
            _stmt_reads(stmts, reads)
            _stmt_writes(stmts, writes)
            self._keep = frozenset(reads | writes)
            self._writes = frozenset(writes)
        else:                     # pragma: no cover - wrapped closures
            self._keep = self._writes = None

    def __call__(self, env: ir.Env):
        if self._broken:
            return self.closure(env)
        import jax
        try:
            struct, vals = _env_signature(env, self._keep, self._writes)
        except Exception:
            self._broken = True
            return self.closure(env)
        # the staged viterbi_soft ext reads its window/metric mode from
        # the environment at trace time — fold it into the do-block
        # cache key so an in-process change re-traces (ADVICE r5 #1)
        from ziria_tpu.frontend.externals import viterbi_mode
        key = (struct, viterbi_mode())
        fn = self._fns.get(key)
        if fn is None:
            closure = self.closure

            def raw(vals):
                env2 = _env_rebuild(struct, list(vals))
                r = closure(env2)
                return r, _env_refs(env2, struct)

            fn = jax.jit(raw)
            self._fns[key] = fn
        try:
            ret, refs = fn(tuple(vals))
            self._ok.add(key)
        except Exception:
            if key in self._ok:
                # this block has compiled and run before: the failure is
                # a runtime execution error (device OOM, backend flake),
                # not un-jittable structure. Silently demoting to the
                # interpreter would hide it and erase the hybrid win
                # with no diagnostic (ADVICE r2) — surface it.
                raise
            # first-call staging failure (non-arrayable values, dynamic
            # takes count downstream, ...) — permanent fallback, oracle
            # semantics preserved
            self._broken = True
            return self.closure(env)
        # device -> numpy on the way out for SMALL leaves: the
        # surrounding interpreter's per-item work runs ~50x faster on
        # numpy than through jnp dispatch, so leaving jax Arrays in
        # scalar/control refs would poison every downstream sample loop
        # (measured: erased the whole win). LARGE arrays stay on the
        # device — they are frame buffers flowing into the NEXT jit
        # block (or a jax-capable ext), and converting them forced a
        # 0.5 MB sync/copy per symbol for data the host never touches.
        def out(x):
            if hasattr(x, "size") and x.size > 4096:
                return x
            return np.asarray(x)

        host = jax.tree_util.tree_map(out, (ret, list(refs)))
        ret, refs = host
        _env_write_refs(env, struct, refs)
        return ret


def hybridize(comp: ir.Comp, min_weight: int = MIN_JIT_WEIGHT,
              dump=None, chunk_loops: bool = True) -> ir.Comp:
    """Rewrite heavy do-blocks into `_JitDo` wrappers and stream-I/O
    control loops into chunked state machines (backend/chunked.py);
    everything else is untouched. Running the result on the interpreter
    gives hybrid execution. `dump`, if given, receives one line per
    decision (the --ddump-hybrid flag)."""
    import dataclasses

    if chunk_loops:
        from ziria_tpu.backend.chunked import wrap_loops
        comp = wrap_loops(comp, dump=dump)

    def walk(c: ir.Comp) -> ir.Comp:
        if isinstance(c, ir.Return) and callable(c.expr):
            stmts = getattr(c.expr, "z_stmts", None)
            if stmts is None:
                return c
            ctx = getattr(c.expr, "z_ctx", None)
            w = _stmts_weight(stmts)
            fx = _has_effects(stmts, ctx)
            jit_it = not fx and w >= min_weight
            if dump is not None:
                loc = getattr(stmts[0], "loc", ("?", "?")) if stmts \
                    else ("?", "?")
                why = ("jit" if jit_it else
                       "effects" if fx else f"below {min_weight}")
                dump(f"  do-block @{loc[0]}:{loc[1]} weight={w} "
                     f"-> {why}")
            if jit_it:
                return dataclasses.replace(c, expr=_JitDo(c.expr))
            return c
        return ir.map_children(c, lambda ch, _b: walk(ch))

    return walk(comp)


def run_hybrid(comp: ir.Comp, inputs, max_out: Optional[int] = None,
               min_weight: int = MIN_JIT_WEIGHT):
    """Interpreter driver over the hybridized program."""
    from ziria_tpu.interp.interp import run
    return run(hybridize(comp, min_weight), inputs, max_out=max_out)
