"""Chunked state machines: compile stream-control loops to the device.

The reference compiles EVERY component — including per-sample `take`
loops with data-dependent branches — into C state machines driven by a
tick/process loop (SURVEY.md §2.1 CgComp continuations, §3.2). Round 2's
hybrid executor jitted the heavy *do-blocks* but left the loops that
walk the stream sample-by-sample (packet detection, the OFDM
symbol-gather, chunked bit emission) on the host interpreter: at 1000
bytes the receiver spent ~1.3 s firing two small jit calls per OFDM
symbol — and on a real TPU each firing is a full host round-trip.

This module is the TPU-native answer (ROADMAP r2 #2): a whole
stream-control loop (`ir.For` / `ir.While` containing takes/emits)
becomes ONE jitted **chunked masked state machine**:

- the host bulk-pulls a window of input items and ships it as a chunk;
- a `lax.while_loop` steps the loop body — takes become
  `dynamic_slice`s at a carried cursor, emits become
  `dynamic_update_slice`s into an output buffer, refs the body writes
  become loop carries (entry-pinned dtypes, the staged statement
  evaluator's discipline) — running as many iterations as fit entirely
  inside the window (guard: cursor + worst-case-take <= available);
- the step reports (iterations done, items consumed, items emitted,
  updated refs); the host flushes emissions, refills the window,
  repeats; unconsumed items are pushed back to the shared
  `interp.Source` so the enclosing stream sees them;
- at EOF the remaining iterations (at most a bound-sized sliver) run
  on the item-level interpreter, preserving exact reference EOF
  semantics — including mid-iteration upstream termination.

Host involvement drops to chunk granularity: the 1000-byte receiver
frame runs in a handful of device calls instead of ~80 — and on a real
TPU behind a host link, a handful of round-trips instead of ~80.

Safety: a loop is wrapped only when its body is *provably* stageable —
no Pipe/Repeat/Map inside, no print/error effects anywhere (they must
fire per execution, not at trace time), every comp-level expression
closure carries its source AST (`z_expr`/`z_stmts`, attached by the
elaborator), and per-iteration take/emit counts have static bounds
whose free variables the loop does not write. Anything else — and any
staging failure at runtime — falls back to the interpreter, which
remains the semantics.
"""

from __future__ import annotations

from typing import Any, List, Optional, Set

import numpy as np

from ziria_tpu.core import ir
from ziria_tpu.frontend import ast as A

# a For loop moving fewer items than this (takes+emits, whole loop)
# stays on the interpreter: jit dispatch would cost more than it saves
MIN_ITEMS_FOR = 192
# While bodies lighter than this stay interpreted (a wrapped While pays
# a compile on first execution; only sample-walking loops earn it)
MIN_WHILE_WEIGHT = 16
# unroll nested For loops below this trip count instead of fori staging
UNROLL_N = 16
# input window capacity (items) — fixed so one compile serves every
# frame length; raised per-node to cover one iteration's worst-case take
CHUNK_CAP = 4096
# emitting While loops: output-buffer budget (items) shared between the
# per-iteration emission bound and the per-chunk iteration cap — the
# step runs at most out_cap//emit_b iterations per call so emissions
# can never overflow the buffer (VERDICT r3 next #7)
WHILE_OUT_ITEMS = 65536


class _Unstageable(Exception):
    """Structural reason this subtree cannot be chunk-compiled."""


# ---------------------------------------------------------------------
# Device-step indirection: every chunk step goes through _step_call so a
# frame batcher (backend/framebatch.py) can intercept it. Single-frame
# runs call the node's jitted fn directly; under run_many each frame
# thread parks here and N lanes ride ONE vmapped call. STATS counts
# device calls either way — the unit tests' call-budget assertions and
# bench.py's call-amortization evidence both read it.

import threading as _threading

_TLS = _threading.local()
STATS = {"device_calls": 0}


def _step_call(node: "_ChunkLoop", key, args):
    b = getattr(_TLS, "batcher", None)
    if b is not None:
        return b.call(node, key, args)
    out = node._fns[key](*args)
    STATS["device_calls"] += 1   # after: a failed first trace is not a call
    return out


def step_meta(it_a, pos_a, out_n_a):
    """(it, pos, out_n) as host ints in ONE device->host transfer.
    Through a high-latency host link (the ~68 ms axon tunnel), three
    separate int() reads are three blocking round trips; stacking on
    device first makes them one. Values already on the host (batched
    fire, interpreter fallback) pass straight through."""
    if isinstance(it_a, (int, np.integer, np.ndarray)):
        return int(it_a), int(pos_a), int(out_n_a)
    import jax.numpy as jnp
    m = np.asarray(jnp.stack([jnp.asarray(it_a), jnp.asarray(pos_a),
                              jnp.asarray(out_n_a)]))
    return int(m[0]), int(m[1]), int(m[2])


class _Unboundable(_Unstageable):
    pass


# ------------------------------------------------------------ analysis


def _children(c: ir.Comp):
    if isinstance(c, ir.Bind):
        return (c.first, c.rest)
    if isinstance(c, ir.LetRef):
        return (c.body,)
    if isinstance(c, (ir.For, ir.While, ir.Repeat)):
        return (c.body,)
    if isinstance(c, ir.Branch):
        return (c.then, c.els)
    if isinstance(c, (ir.Pipe, ir.ParPipe)):
        return (c.up, c.down)
    return ()


def _walk(c: ir.Comp):
    yield c
    for ch in _children(c):
        yield from _walk(ch)


def has_stream_io(c: ir.Comp) -> bool:
    return any(isinstance(x, (ir.Take, ir.Takes, ir.Emit, ir.Emits))
               for x in _walk(c))


def _closure_ast(e) -> Optional[A.Expr]:
    """Surface AST of a comp-level Expr, if the elaborator attached it."""
    return getattr(e, "z_expr", None) if callable(e) else None


def _expr_has_effects(e: A.Expr, ctx, seen: Set[str]) -> bool:
    from ziria_tpu.backend.hybrid import _has_effects
    for x in A.iter_exprs(e):
        if isinstance(x, A.ECall):
            if x.name in ("print", "println", "error"):
                return True
            if ctx is not None and x.name in getattr(ctx, "funs", {}) \
                    and x.name not in seen:
                seen.add(x.name)
                if _has_effects(ctx.funs[x.name].decl.body, ctx, seen):
                    return True
    return False


def check_stageable(comp: ir.Comp) -> None:
    """Raise _Unstageable unless every node/closure in `comp` is the
    kind the stager knows how to trace (structure + effects only;
    runtime bounds are checked per execution)."""
    from ziria_tpu.backend.hybrid import _has_effects
    seen: Set[str] = set()
    for c in _walk(comp):
        if isinstance(c, (ir.Repeat, ir.Pipe, ir.ParPipe, ir.Map,
                          ir.MapAccum, ir.JaxBlock)):
            raise _Unstageable(f"{type(c).__name__} inside loop")
        exprs: List[Any] = []
        if isinstance(c, (ir.Emit, ir.Emits)):
            exprs.append(c.expr)
        elif isinstance(c, ir.Return):
            if callable(c.expr):
                stmts = getattr(c.expr, "z_stmts", None)
                if stmts is not None:
                    ctx = getattr(c.expr, "z_ctx", None)
                    if _has_effects(stmts, ctx, seen):
                        raise _Unstageable("print/error in do-block")
                    continue
                exprs.append(c.expr)
        elif isinstance(c, ir.LetRef):
            exprs.append(c.init)
        elif isinstance(c, ir.Assign):
            exprs.append(c.expr)
        elif isinstance(c, ir.For):
            exprs.append(c.count)
        elif isinstance(c, (ir.While, ir.Branch)):
            exprs.append(c.cond)
        for e in exprs:
            if not callable(e):
                continue  # plain constant
            ast = _closure_ast(e)
            if ast is None:
                raise _Unstageable("opaque expression closure")
            ctx = getattr(e, "z_ctx", None)
            if _expr_has_effects(ast, ctx, seen):
                raise _Unstageable("print/error in expression")


def comp_writes(comp: ir.Comp,
                shadow: frozenset = frozenset()) -> Set[str]:
    """Names of enclosing-scope refs this subtree may assign — the
    loop-carried set. Locally-declared (LetRef / bind / loop-var) names
    are shadowed out. Over-approximates through do-blocks via the
    statement-level write analysis (same as the staged evaluator)."""
    from ziria_tpu.frontend.eval import _stmt_writes
    out: Set[str] = set()
    if isinstance(comp, ir.Assign):
        if comp.var not in shadow:
            out.add(comp.var)
    elif isinstance(comp, ir.Return) and callable(comp.expr):
        stmts = getattr(comp.expr, "z_stmts", None)
        if stmts is not None:
            w: Set[str] = set()
            _stmt_writes(stmts, w)
            out |= w - shadow
    elif isinstance(comp, ir.Bind):
        out |= comp_writes(comp.first, shadow)
        sh = shadow | {comp.var} if comp.var is not None else shadow
        out |= comp_writes(comp.rest, sh)
    elif isinstance(comp, ir.LetRef):
        out |= comp_writes(comp.body, shadow | {comp.var})
    elif isinstance(comp, ir.For):
        sh = shadow | {comp.var} if comp.var is not None else shadow
        out |= comp_writes(comp.body, sh)
    elif isinstance(comp, (ir.While, ir.Repeat)):
        out |= comp_writes(comp.body, shadow)
    elif isinstance(comp, ir.Branch):
        out |= comp_writes(comp.then, shadow)
        out |= comp_writes(comp.els, shadow)
    elif isinstance(comp, (ir.Pipe, ir.ParPipe)):
        out |= comp_writes(comp.up, shadow)
        out |= comp_writes(comp.down, shadow)
    else:
        orig = getattr(comp, "orig", None)
        if orig is not None:
            out |= comp_writes(orig, shadow)
    return out


def _count_bound(count, env: ir.Env, wset: Set[str]) -> int:
    """Evaluate a nested loop count against the ENTRY env. Only safe if
    the wrapped region never writes the count's free variables."""
    if not callable(count):
        return int(count)
    ast = _closure_ast(count)
    if ast is None:
        raise _Unboundable("opaque count")
    from ziria_tpu.frontend.elab import free_vars
    if free_vars(ast) & wset:
        raise _Unboundable("count depends on loop-written state")
    return int(ir.eval_expr(count, env))


def take_bound(comp: ir.Comp, env: ir.Env, wset: Set[str]) -> int:
    """Max items one execution of `comp` can take (static per entry)."""
    if isinstance(comp, ir.Take):
        return 1
    if isinstance(comp, ir.Takes):
        return comp.n
    if isinstance(comp, ir.Bind):
        return (take_bound(comp.first, env, wset)
                + take_bound(comp.rest, env, wset))
    if isinstance(comp, ir.LetRef):
        return take_bound(comp.body, env, wset)
    if isinstance(comp, ir.Branch):
        return max(take_bound(comp.then, env, wset),
                   take_bound(comp.els, env, wset))
    if isinstance(comp, ir.For):
        b = take_bound(comp.body, env, wset)
        if b == 0:
            return 0
        return max(0, _count_bound(comp.count, env, wset)) * b
    if isinstance(comp, ir.While):
        if has_stream_io(comp.body):
            raise _Unboundable("stream I/O inside nested while")
        return 0
    orig = getattr(comp, "orig", None)
    if orig is not None:
        return take_bound(orig, env, wset)
    return 0


def emit_bound(comp: ir.Comp, env: ir.Env, wset: Set[str]) -> int:
    if isinstance(comp, ir.Emit):
        return 1
    if isinstance(comp, ir.Emits):
        return comp.n
    if isinstance(comp, ir.Bind):
        return (emit_bound(comp.first, env, wset)
                + emit_bound(comp.rest, env, wset))
    if isinstance(comp, ir.LetRef):
        return emit_bound(comp.body, env, wset)
    if isinstance(comp, ir.Branch):
        return max(emit_bound(comp.then, env, wset),
                   emit_bound(comp.els, env, wset))
    if isinstance(comp, ir.For):
        b = emit_bound(comp.body, env, wset)
        if b == 0:
            return 0
        return max(0, _count_bound(comp.count, env, wset)) * b
    if isinstance(comp, ir.While):
        if has_stream_io(comp.body):
            raise _Unboundable("stream I/O inside nested while")
        return 0
    orig = getattr(comp, "orig", None)
    if orig is not None:
        return emit_bound(orig, env, wset)
    return 0


def _body_weight(comp: ir.Comp) -> int:
    """Rough op weight of a loop body (for the wrap/no-wrap gate)."""
    from ziria_tpu.backend.hybrid import _stmts_weight
    w = 0
    for c in _walk(comp):
        w += 1
        if isinstance(c, ir.Return) and callable(c.expr):
            stmts = getattr(c.expr, "z_stmts", None)
            if stmts is not None:
                w += _stmts_weight(stmts)
    return w


# ------------------------------------------------------------ stager


class _St:
    """Mutable staging state threaded through one traced step.

    `spy`, when set, records emitted item values instead of writing the
    output buffer — the trace-time discovery pass that learns the
    emission dtype/shape before the real while_loop is built (its dead
    traced ops are DCE'd by XLA).
    """

    __slots__ = ("chunk", "pos", "out_buf", "out_n", "spy")

    def __init__(self, chunk, pos, out_buf, out_n, spy=None):
        self.chunk = chunk
        self.pos = pos
        self.out_buf = out_buf
        self.out_n = out_n
        self.spy = spy


def _is_traced_val(v) -> bool:
    from ziria_tpu.frontend.eval import _is_traced
    return _is_traced(v)


def _stage(comp: ir.Comp, env: ir.Env, st: _St):
    """Trace one execution of `comp` under jax. Returns its value."""
    import jax.numpy as jnp
    from jax import lax

    orig = getattr(comp, "orig", None)
    if orig is not None:               # nested _ChunkLoop: stage inline
        return _stage(orig, env, st)

    if isinstance(comp, ir.Take):
        x = lax.dynamic_index_in_dim(st.chunk, st.pos, 0, keepdims=False)
        st.pos = st.pos + 1
        return x

    if isinstance(comp, ir.Takes):
        xs = lax.dynamic_slice_in_dim(st.chunk, st.pos, comp.n, 0)
        st.pos = st.pos + comp.n
        return xs

    if isinstance(comp, ir.Emit):
        v = jnp.asarray(ir.eval_expr(comp.expr, env))
        if st.spy is not None:
            st.spy.append(v)
            return None
        st.out_buf = lax.dynamic_update_slice_in_dim(
            st.out_buf, v[None].astype(st.out_buf.dtype), st.out_n, 0)
        st.out_n = st.out_n + 1
        return None

    if isinstance(comp, ir.Emits):
        v = jnp.asarray(ir.eval_expr(comp.expr, env))
        if st.spy is not None:
            st.spy.append(v[0])
            return None
        st.out_buf = lax.dynamic_update_slice_in_dim(
            st.out_buf, v.astype(st.out_buf.dtype), st.out_n, 0)
        st.out_n = st.out_n + comp.n
        return None

    if isinstance(comp, ir.Return):
        return ir.eval_expr(comp.expr, env)

    if isinstance(comp, ir.Bind):
        v = _stage(comp.first, env, st)
        if comp.var is not None:
            env = env.child()
            env.bind(comp.var, v)
        return _stage(comp.rest, env, st)

    if isinstance(comp, ir.LetRef):
        env = env.child()
        env.bind_ref(comp.var, ir.eval_expr(comp.init, env))
        return _stage(comp.body, env, st)

    if isinstance(comp, ir.Assign):
        env.set(comp.var, ir.eval_expr(comp.expr, env))
        return None

    if isinstance(comp, ir.Branch):
        pred = ir.eval_expr(comp.cond, env)
        if not _is_traced_val(pred):
            return _stage(comp.then if bool(pred) else comp.els, env, st)
        return _staged_branch(comp, pred, env, st)

    if isinstance(comp, ir.For):
        n = ir.eval_expr(comp.count, env)
        if not _is_traced_val(n) and int(n) <= UNROLL_N:
            v = None
            for i in range(int(n)):
                e = env
                if comp.var is not None:
                    e = env.child()
                    e.bind(comp.var, i)
                v = _stage(comp.body, e, st)
            return v
        return _staged_loop(comp.body, env, st, var=comp.var,
                            n=n, cond=None)

    if isinstance(comp, ir.While):
        return _staged_loop(comp.body, env, st, var=None,
                            n=None, cond=comp.cond)

    raise _Unstageable(f"cannot stage {type(comp).__name__}")


def _resolves_ref(env: ir.Env, name: str) -> bool:
    e = env
    while e is not None:
        if name in e._refs:
            return True
        if name in e._vars:
            return False
        e = e._parent
    return False


def _carry_refs(comp: ir.Comp, env: ir.Env) -> List[str]:
    """Written ref names that resolve in `env` (outer carries), in a
    deterministic order. Names that resolve to immutable binds (or
    nothing) are body-local declarations — not carried."""
    return [n for n in sorted(comp_writes(comp))
            if _resolves_ref(env, n)]


def _pin(vals):
    """jnp-ify and remember dtypes (entry-pinned, like _staged_for)."""
    import jax.numpy as jnp
    arrs = [jnp.asarray(v) for v in vals]
    return arrs, [a.dtype for a in arrs]


def _staged_branch(comp: ir.Branch, pred, env: ir.Env, st: _St):
    import jax.numpy as jnp
    from jax import lax

    if st.spy is not None:
        # discovery pass: trace both arms eagerly (no cond needed —
        # the ops are dead, only the recorded emission avals matter)
        _stage(comp.then, env, st)
        _stage(comp.els, env, st)
        return None

    io = has_stream_io(comp)
    names = _carry_refs(comp, env)
    vals0, dts = _pin([env.lookup(n) for n in names])
    with_out = io and st.out_buf is not None
    oper = (st.pos,
            st.out_n if with_out else jnp.int32(0),
            st.out_buf if with_out else jnp.int32(0),
            tuple(vals0))

    def arm(body):
        def f(op):
            pos, out_n, out_buf, vals = op
            st2 = _St(st.chunk, pos,
                      out_buf if with_out else st.out_buf,
                      out_n if with_out else st.out_n)
            for n, v in zip(names, vals):
                env.set(n, v)
            v = _stage(body, env, st2)
            if v is not None:
                raise _Unstageable("Branch arm value with traced "
                                   "condition")
            outv = tuple(jnp.asarray(env.lookup(n)).astype(dt)
                         for n, dt in zip(names, dts))
            return (st2.pos,
                    st2.out_n if with_out else jnp.int32(0),
                    st2.out_buf if with_out else jnp.int32(0),
                    outv)
        return f

    res = lax.cond(jnp.asarray(pred), arm(comp.then), arm(comp.els), oper)
    st.pos = res[0]
    if with_out:
        st.out_n, st.out_buf = res[1], res[2]
    for n, v in zip(names, res[3]):
        env.set(n, v)
    return None


def _staged_loop(body: ir.Comp, env: ir.Env, st: _St,
                 var: Optional[str], n, cond):
    """Nested For (traced or large count) / While as lax.while_loop."""
    import jax.numpy as jnp
    from jax import lax

    if st.spy is not None:
        # discovery pass: one body iteration records the emission avals
        e = env
        if var is not None:
            e = env.child()
            e.bind(var, jnp.int32(0))
        _stage(body, e, st)
        return None

    io = has_stream_io(body)
    names = _carry_refs(body, env)
    if cond is not None:
        # mutable refs the condition reads must ride the carry too
        ast = _closure_ast(cond)
        if ast is None:
            raise _Unstageable("opaque nested while condition")
        from ziria_tpu.frontend.elab import free_vars
        names = names + [m for m in sorted(free_vars(ast))
                         if m not in names and _resolves_ref(env, m)]
    vals0, dts = _pin([env.lookup(m) for m in names])
    with_out = io and st.out_buf is not None

    carry0 = (jnp.int32(0), st.pos,
              st.out_n if with_out else jnp.int32(0),
              st.out_buf if with_out else jnp.int32(0),
              tuple(vals0))

    def put(vals):
        for m, v in zip(names, vals):
            env.set(m, v)

    def cond_fn(carry):
        i, pos, out_n, out_buf, vals = carry
        if cond is None:
            return i < jnp.asarray(n, jnp.int32)
        put(vals)
        return jnp.asarray(ir.eval_expr(cond, env), bool)

    def body_fn(carry):
        i, pos, out_n, out_buf, vals = carry
        put(vals)
        st2 = _St(st.chunk, pos,
                  out_buf if with_out else st.out_buf,
                  out_n if with_out else st.out_n)
        e = env
        if var is not None:
            e = env.child()
            e.bind(var, i)
        v = _stage(body, e, st2)
        if v is not None:
            raise _Unstageable("loop body value used across iterations")
        outv = tuple(jnp.asarray(env.lookup(m)).astype(dt)
                     for m, dt in zip(names, dts))
        return (i + 1, st2.pos,
                st2.out_n if with_out else jnp.int32(0),
                st2.out_buf if with_out else jnp.int32(0), outv)

    res = lax.while_loop(cond_fn, body_fn, carry0)
    st.pos = res[1]
    if with_out:
        st.out_n, st.out_buf = res[2], res[3]
    put(res[4])
    return None


# ------------------------------------------------------------ the node


def _bucket(n: int) -> int:
    b = 16
    while b < n:
        b *= 2
    return b


# write-back policy (shared with _JitDo): small leaves become numpy —
# the interpreter's per-item fast path — while buffers over this many
# elements stay device-resident for the next jit block
HOST_SMALL_MAX = 4096


class _ChunkLoop(ir.Comp):
    """A For/While stream-control loop compiled as a chunked state
    machine. Executed by the interpreter through the `run_gen` hook;
    every structural failure falls back to interpreting `self.orig`
    (the oracle semantics). Post-compile runtime errors re-raise — a
    silent demotion would hide real bugs (ADVICE r2)."""

    def __init__(self, orig: ir.Comp):
        object.__setattr__(self, "orig", orig)
        object.__setattr__(self, "_fns", {})
        object.__setattr__(self, "_steps", {})
        object.__setattr__(self, "_ok_keys", set())
        object.__setattr__(self, "_broken", False)
        object.__setattr__(self, "_fb", None)

    def _fallback_comp(self) -> ir.Comp:
        """Interpreter fallback still deserves jitted do-blocks: a loop
        below the chunking threshold must not run slower than the plain
        hybrid executor would have run it."""
        if self._fb is None:
            from ziria_tpu.backend.hybrid import hybridize
            object.__setattr__(
                self, "_fb", hybridize(self.orig, chunk_loops=False))
        return self._fb

    def label(self) -> str:
        return f"ChunkLoop({self.orig.label()})"

    # ---------------------------------------------------- jit step

    def _get_fn(self, struct, names, take_b: int, out_cap: int,
                is_for: bool, var, iter_cap: int = 0):
        import jax
        import jax.numpy as jnp
        from ziria_tpu.backend.hybrid import _env_rebuild
        from ziria_tpu.frontend.externals import viterbi_mode

        # the staged viterbi_soft ext reads ZIRIA_VITERBI_WINDOW /
        # ZIRIA_VITERBI_METRIC at trace time, so the decode mode is
        # part of this trace's identity: fold it into the cache key so
        # an in-process env change re-traces instead of silently
        # reusing the old mode (ADVICE r5 #1)
        key = (struct, tuple(names), take_b, out_cap, is_for, iter_cap,
               viterbi_mode())
        fn = self._fns.get(key)
        if fn is not None:
            return key, fn

        body = self.orig.body
        cond = self.orig.cond if isinstance(self.orig, ir.While) else None

        def step(chunk, avail, n, it0, vals):
            env = _env_rebuild(struct, list(vals))
            rvals0, dts = _pin([env.lookup(m) for m in names])

            if out_cap:
                # discovery pass: learn the emitted item aval by staging
                # one throwaway iteration on a fresh env (ops are dead,
                # XLA DCEs them)
                spy: List[Any] = []
                env_spy = _env_rebuild(struct, list(vals))
                st_spy = _St(chunk, jnp.int32(0), None, None, spy=spy)
                e = env_spy
                if var is not None:
                    e = env_spy.child()
                    e.bind(var, jnp.int32(0))
                _stage(body, e, st_spy)
                if not spy:
                    raise _Unstageable("emit bound > 0 but no emission "
                                       "site reached in discovery")
                item = spy[0]
                dt = jnp.result_type(*spy) if len(spy) > 1 else item.dtype
                for s in spy:
                    if jnp.shape(s) != jnp.shape(item):
                        raise _Unstageable("emission shapes disagree")
                out_buf0 = jnp.zeros((out_cap,) + jnp.shape(item), dt)
            else:
                out_buf0 = jnp.int32(0)

            def put(vals_):
                for m, v in zip(names, vals_):
                    env.set(m, v)

            def cond_fn(carry):
                it, pos, out_n, out_buf, rvals = carry
                fits = pos + take_b <= avail
                if is_for:
                    return jnp.logical_and(it < n, fits)
                put(rvals)
                c = jnp.asarray(ir.eval_expr(cond, env), bool)
                if iter_cap:
                    # emitting While: stop before the output buffer
                    # can overflow; the host flushes and re-enters
                    c = jnp.logical_and(c, it - it0 < iter_cap)
                return jnp.logical_and(c, fits)

            def body_fn(carry):
                it, pos, out_n, out_buf, rvals = carry
                put(rvals)
                st = _St(chunk, pos,
                         out_buf if out_cap else None,
                         out_n if out_cap else None)
                e = env
                if var is not None:
                    e = env.child()
                    e.bind(var, it)
                v = _stage(body, e, st)
                if v is not None:
                    raise _Unstageable("loop body value is used")
                outv = tuple(jnp.asarray(env.lookup(m)).astype(d)
                             for m, d in zip(names, dts))
                return (it + 1, st.pos,
                        st.out_n if out_cap else jnp.int32(0),
                        st.out_buf if out_cap else jnp.int32(0), outv)

            carry = (it0, jnp.int32(0), jnp.int32(0), out_buf0,
                     tuple(rvals0))
            return jax.lax.while_loop(cond_fn, body_fn, carry)

        fn = jax.jit(step)
        # _steps must be visible before _fns: a concurrent frame thread
        # that sees the cached fn may immediately park a request whose
        # batched fire reads _steps[key]
        self._steps[key] = step
        self._fns[key] = fn
        return key, fn

    # ---------------------------------------------------- driver

    def run_gen(self, env: ir.Env, source, xp=np):
        from ziria_tpu.interp.interp import Source, _run

        orig = self.orig
        is_for = isinstance(orig, ir.For)

        def fallback():
            return _run(self._fallback_comp(), env, source, xp)

        if self._broken or not isinstance(source, Source):
            return (yield from fallback())

        # ---- per-execution bounds & the is-it-worth-it gate
        try:
            wset = comp_writes(orig.body)
            take_b = take_bound(orig.body, env, wset)
            emit_b = emit_bound(orig.body, env, wset)
            if is_for:
                n = int(ir.eval_expr(orig.count, env))
                if n <= 0:
                    return None
                if n * (take_b + emit_b) < MIN_ITEMS_FOR:
                    return (yield from fallback())
                out_cap = _bucket(n * emit_b) if emit_b else 0
            else:
                n = 0
                if emit_b:
                    # bound emissions per chunk by capping iterations:
                    # the step stops after iter_cap body iterations (or
                    # when the condition/input guard stops it), reports
                    # its counts, and the host re-enters — a
                    # detect-then-emit While runs fully chunked
                    iter_cap = WHILE_OUT_ITEMS // emit_b
                    if iter_cap < 1:
                        raise _Unstageable("while emission bound "
                                           "exceeds the output budget")
                    iter_cap = min(iter_cap, 2048)
                    out_cap = _bucket(emit_b * iter_cap)
                else:
                    out_cap = 0
        except _Unstageable:
            return (yield from fallback())

        import jax.numpy as jnp
        from ziria_tpu.backend.hybrid import _env_signature

        if is_for or not emit_b:
            iter_cap = 0
        cap = max(CHUNK_CAP, _bucket(take_b)) if take_b else 0
        if is_for and take_b:
            cap = min(cap, _bucket(max(1, n * take_b)))
            cap = max(cap, _bucket(take_b))

        try:
            struct, vals = _env_signature(env)
            names = _carry_refs(orig.body, env)
            if not is_for:
                ast = _closure_ast(orig.cond)
                if ast is None and callable(orig.cond):
                    raise _Unstageable("opaque while condition")
                if ast is not None:
                    from ziria_tpu.frontend.elab import free_vars
                    names = names + [
                        m for m in sorted(free_vars(ast))
                        if m not in names and _resolves_ref(env, m)]
            key, _ = self._get_fn(struct, names, take_b, out_cap,
                                  is_for, orig.var if is_for else None,
                                  iter_cap)
        except _Unstageable:
            return (yield from fallback())

        name_idx = {}
        # vals indices of carried names, for updating between steps
        flat_names: List[str] = []
        for (vnames, rnames, _w) in struct:
            flat_names.extend(vnames)
            flat_names.extend(rnames)
        for m in names:
            # innermost occurrence wins (matches Env.set semantics)
            for i in range(len(flat_names) - 1, -1, -1):
                if flat_names[i] == m:
                    name_idx[m] = i
                    break

        vals = list(vals)
        it = 0
        buf: List[Any] = []
        eof = False

        def host_cond() -> bool:
            if is_for:
                return it < n
            return bool(ir.eval_expr(orig.cond, env))

        def write_back(final: bool) -> None:
            wvals = [vals[name_idx[m]] for m in names]
            if final:
                # ALL small leaves come back in one device_get instead
                # of a blocking read per leaf (each a host-link round
                # trip); big buffers stay device-resident
                import jax
                small = [i for i, v in enumerate(wvals)
                         if getattr(v, "size", 0) <= HOST_SMALL_MAX]
                if small:
                    got = jax.device_get([wvals[i] for i in small])
                    for i, g in zip(small, got):
                        wvals[i] = np.asarray(g)
            for m, v in zip(names, wvals):
                env.set(m, v)

        while host_cond():
            if take_b:
                need = cap if not is_for else min(cap, (n - it) * take_b)
                if not eof and len(buf) < need:
                    got, eof = source.pull_block(need - len(buf))
                    buf.extend(got)
                if len(buf) < take_b:
                    # not enough input for even one worst-case
                    # iteration: run ONE iteration on the interpreter
                    # (exact EOF semantics — it may consume fewer than
                    # the bound, or legitimately raise UpstreamDone out
                    # of this loop)
                    source.push_back(buf)
                    buf = []
                    e = env
                    if is_for and orig.var is not None:
                        e = env.child()
                        e.bind(orig.var, it)
                    yield from _run(self._fallback_comp().body, e,
                                    source, xp)
                    # the interpreter mutated carried refs directly in
                    # env; refresh vals so a later chunk step (or the
                    # final/fallback write_back) doesn't clobber them
                    # with stale pre-tail device values
                    for m in names:
                        vals[name_idx[m]] = env.lookup(m)
                    it += 1
                    continue

            if take_b:
                avail = min(len(buf), cap)
                chunk = np.stack([np.asarray(x) for x in buf[:cap]])
                if chunk.shape[0] < cap:
                    pad = np.zeros((cap - chunk.shape[0],)
                                   + chunk.shape[1:], chunk.dtype)
                    chunk = np.concatenate([chunk, pad], axis=0)
            else:
                avail = 0
                chunk = np.zeros((1,), np.int32)

            try:
                it_a, pos_a, out_n_a, out_buf_a, rvals_a = _step_call(
                    self, key,
                    (jnp.asarray(chunk), jnp.int32(avail), jnp.int32(n),
                     jnp.int32(it), tuple(vals)))
                self._ok_keys.add(key)
            except Exception:
                if key in self._ok_keys:
                    raise  # runtime error after a proven compile: do
                    #        not mask it behind a silent slow path
                # first-trace failure: permanent structural fallback
                object.__setattr__(self, "_broken", True)
                source.push_back(buf)
                write_back(final=True)
                return (yield from fallback())

            new_it, consumed, out_k = step_meta(it_a, pos_a, out_n_a)
            for m, v in zip(names, rvals_a):
                vals[name_idx[m]] = v
            write_back(final=False)

            if out_cap:
                k = out_k
                if k:
                    flush = np.asarray(out_buf_a[:k])
                    for row in flush:
                        yield row
            if consumed:
                buf = buf[consumed:]
            progress = new_it > it or consumed > 0
            it = new_it
            if is_for and it >= n:
                break
            if not progress and take_b and len(buf) >= take_b:
                # guard said an iteration fits but none ran — a stager
                # bug; surface it rather than spin
                raise RuntimeError(
                    f"chunked loop made no progress with {len(buf)} "
                    f"items buffered (take_bound={take_b})")
            # else: insufficient buffered input; the next round pulls
            # more or enters the interpreter tail path

        source.push_back(buf)
        write_back(final=True)
        return None


def wrap_loops(comp: ir.Comp, dump=None) -> ir.Comp:
    """Walk `comp`, replacing stageable stream-I/O For/While loops with
    _ChunkLoop nodes (called from backend.hybrid.hybridize)."""

    def walk(c: ir.Comp) -> ir.Comp:
        if isinstance(c, (ir.For, ir.While)) and has_stream_io(c.body):
            try:
                check_stageable(c.body)
                if isinstance(c, ir.While):
                    if callable(c.cond):
                        ast = _closure_ast(c.cond)
                        if ast is None:
                            raise _Unstageable("opaque while condition")
                        if _expr_has_effects(ast, getattr(c.cond, "z_ctx",
                                                          None), set()):
                            raise _Unstageable("effects in while "
                                               "condition")
                    if _body_weight(c.body) < MIN_WHILE_WEIGHT:
                        raise _Unstageable("while body too light")
                node = _ChunkLoop(
                    ir.map_children(c, lambda ch, _b: walk(ch)))
                if dump is not None:
                    dump(f"  chunked {c.label()}")
                return node
            except _Unstageable as e:
                if dump is not None:
                    dump(f"  loop {c.label()} stays interpreted: {e}")
        return ir.map_children(c, lambda ch, _b: walk(ch))

    return walk(comp)
