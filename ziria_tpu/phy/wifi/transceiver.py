"""Transceiver + MAC-lite: the closed TX↔RX loop.

Counterpart of the reference's `code/WiFi/transceiver/` (SURVEY.md §2.3
— the real-time loop coupling TX+RX over SORA/BladeRF hardware, with a
minimal MAC). No radio hardware in this build, so the "air" is an
explicit channel function (phy/channel.py) and time is sample counts at
20 Msps; everything else mirrors the reference's split:

- PHY: `tx.encode_frame` / `rx.receive` — the encode dispatches
  through tx's lru-cached jit per (rate, bit bucket, symbol bucket),
  so repeated sends (DATA frames AND the per-receive ACKs) reuse
  compiled encoders instead of re-tracing; pinned by
  test_transceiver.py::test_emit_reuses_compiled_encoder;
- MAC-lite: a 4-byte header [type, seq, dst, src] + CRC32 FCS inside
  the PSDU; DATA frames are ACKed after SIFS; the sender retransmits on
  ACK timeout up to a retry limit (stop-and-wait ARQ — the shape of the
  reference's transceiver demo, not the full 802.11 DCF).

`Station` is a host-side state machine (send queue, pending-ACK timer,
dedup by sequence number); `run_link` steps two stations over a shared
channel. The PHY work stays on device inside the jitted encode/decode;
the MAC logic is control-flow over a handful of scalars per frame —
exactly the host/device split the runtime uses everywhere else.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ziria_tpu.ops.crc import append_crc32, check_crc32
from ziria_tpu.phy.wifi import rx, tx
from ziria_tpu.utils.bits import np_bits_to_bytes, np_bytes_to_bits

# MAC-lite frame types (first header byte)
TYPE_DATA = 0x08
TYPE_ACK = 0xD4

HDR_BYTES = 4          # [type, seq, dst, src]
FCS_BYTES = 4

SIFS_SAMPLES = 320     # 16 us at 20 Msps
ACK_RATE_MBPS = 6      # control frames go at the base rate
ACK_TIMEOUT = 8192     # samples the sender waits before retransmitting


def mac_frame_psdu(ftype: int, seq: int, dst: int, src: int,
                   payload: bytes = b"") -> np.ndarray:
    """Build the PSDU bytes: header + payload + CRC32 FCS."""
    hdr = np.array([ftype & 0xFF, seq & 0xFF, dst & 0xFF, src & 0xFF],
                   np.uint8)
    body = np.concatenate([hdr, np.frombuffer(payload, np.uint8)])
    # header bit-twiddling stays host-side (np); only the CRC helper is jnp
    bits = append_crc32(np_bytes_to_bits(body))
    return np_bits_to_bytes(np.asarray(bits))


@dataclass
class MacFrame:
    ftype: int
    seq: int
    dst: int
    src: int
    payload: bytes

    @staticmethod
    def parse(psdu_bytes: np.ndarray) -> Optional["MacFrame"]:
        b = np.asarray(psdu_bytes, np.uint8)
        if b.size < HDR_BYTES + FCS_BYTES:
            return None
        if not bool(np.asarray(check_crc32(np_bytes_to_bits(b)))):
            return None
        return MacFrame(int(b[0]), int(b[1]), int(b[2]), int(b[3]),
                        bytes(b[HDR_BYTES:-FCS_BYTES].tobytes()))


@dataclass
class _Pending:
    psdu: np.ndarray
    rate: int
    seq: int
    dst: int
    deadline: int
    tries: int


@dataclass
class Station:
    """Half-duplex stop-and-wait station.

    fxp=True receives through the Q15 integer interior
    (rx.receive(fxp=True) — phy/wifi/rx_fxp.py): the MAC loop on the
    reference's fixed-point discipline."""

    addr: int
    rate_mbps: int = 24
    max_tries: int = 4
    fxp: bool = False
    now: int = 0                      # local clock, in samples
    delivered: List[Tuple[int, bytes]] = field(default_factory=list)
    acked: List[int] = field(default_factory=list)
    failed: List[int] = field(default_factory=list)
    counters: Dict[str, int] = field(default_factory=lambda: {
        "tx_data": 0, "rx_data": 0, "tx_ack": 0, "rx_ack": 0,
        "retries": 0, "drops": 0, "dups": 0})
    _next_seq: int = 0
    _pending: Optional[_Pending] = None
    _last_rx_seq: Dict[int, int] = field(default_factory=dict)

    # ------------------------------------------------------------- sending

    def send(self, payload: bytes, dst: int) -> np.ndarray:
        """Queue a DATA frame; returns the samples to put on the air."""
        if self._pending is not None:
            raise RuntimeError("stop-and-wait: previous frame not yet "
                               "ACKed or failed")
        seq = self._next_seq
        self._next_seq = (self._next_seq + 1) & 0xFF
        psdu = mac_frame_psdu(TYPE_DATA, seq, dst, self.addr, payload)
        self.counters["tx_data"] += 1
        samples = self._emit(psdu, self.rate_mbps)
        # the ACK timer starts when the frame has LEFT the air (_emit
        # advanced the clock by the frame duration) — anchoring it before
        # would expire mid-transmission for frames longer than the timeout
        self._pending = _Pending(psdu, self.rate_mbps, seq, dst,
                                 self.now + ACK_TIMEOUT, 1)
        return samples

    def poll(self) -> Optional[np.ndarray]:
        """Clock tick: retransmit if the ACK timer expired; returns
        samples to transmit, or None."""
        p = self._pending
        if p is None or self.now < p.deadline:
            return None
        if p.tries >= self.max_tries:
            self.failed.append(p.seq)
            self.counters["drops"] += 1
            self._pending = None
            return None
        p.tries += 1
        self.counters["retries"] += 1
        self.counters["tx_data"] += 1
        samples = self._emit(p.psdu, p.rate)
        p.deadline = self.now + ACK_TIMEOUT   # timer from end of transmit
        return samples

    # ----------------------------------------------------------- receiving

    def on_air(self, samples: np.ndarray) -> Optional[np.ndarray]:
        """Process received samples; returns response samples (an ACK
        after a SIFS of silence) or None."""
        self.now += int(np.asarray(samples).shape[0])
        res = rx.receive(samples, check_fcs=False, fxp=self.fxp)
        if not res.ok:
            return None
        psdu_bytes = np_bits_to_bytes(np.asarray(res.psdu_bits, np.uint8))
        fr = MacFrame.parse(psdu_bytes)
        if fr is None or fr.dst != self.addr:
            return None
        if fr.ftype == TYPE_ACK:
            p = self._pending
            if p is not None and fr.seq == p.seq and fr.src == p.dst:
                self.acked.append(p.seq)
                self.counters["rx_ack"] += 1
                self._pending = None
            return None
        if fr.ftype == TYPE_DATA:
            self.counters["rx_data"] += 1
            if self._last_rx_seq.get(fr.src) == fr.seq:
                self.counters["dups"] += 1     # retransmit of a frame we
            else:                              # ACKed — re-ACK, don't
                self._last_rx_seq[fr.src] = fr.seq   # re-deliver
                self.delivered.append((fr.src, fr.payload))
            ack = mac_frame_psdu(TYPE_ACK, fr.seq, fr.src, self.addr)
            self.counters["tx_ack"] += 1
            sifs = np.zeros((SIFS_SAMPLES, 2), np.float32)
            return np.concatenate(
                [sifs, self._emit(ack, ACK_RATE_MBPS)], axis=0)
        return None

    def _emit(self, psdu: np.ndarray, rate: int) -> np.ndarray:
        # encode_frame routes through tx._jit_encode_frame (cached per
        # (rate, bit bucket, symbol bucket)): every send after the
        # first at a given geometry is a pure dispatch, no re-trace
        samples = np.asarray(tx.encode_frame(psdu, rate), np.float32)
        self.now += samples.shape[0]
        return samples


# --------------------------------------------------------------------------
# Link driver
# --------------------------------------------------------------------------


Channel = Callable[[np.ndarray, int], np.ndarray]  # (samples, k) -> samples


def perfect_channel(samples: np.ndarray, _k: int) -> np.ndarray:
    return samples


def run_link(a: Station, b: Station, payloads: List[bytes],
             channel: Channel = perfect_channel,
             max_steps: int = 64) -> None:
    """Send `payloads` from `a` to `b` over `channel` with stop-and-wait
    ARQ. The channel sees every transmission (indexed by k) and may
    corrupt/attenuate it — dropped frames exercise the retransmit path.
    """
    k = 0
    for payload in payloads:
        on_air = a.send(payload, b.addr)
        for _ in range(max_steps):
            # propagate A -> B; B may answer (ACK after SIFS)
            reply = b.on_air(channel(on_air, k))
            k += 1
            if reply is not None:
                a.on_air(channel(reply, k))
                k += 1
            if a._pending is None:       # ACKed or given up
                break
            a.now = max(a.now, a._pending.deadline)  # timeout advance
            nxt = a.poll()
            if nxt is None:
                break                    # retry limit hit
            on_air = nxt
        if a._pending is not None:
            # step budget exhausted with the frame still in flight: fail
            # it explicitly so the next send() isn't poisoned and the
            # outcome is visible in failed/drops
            a.failed.append(a._pending.seq)
            a.counters["drops"] += 1
            a._pending = None
