"""802.11a/g OFDM transmitter chain.

Counterpart of the reference's `code/WiFi/transmitter/` top-level
`tx.blk` (SURVEY.md §2.3, §3.5): crc >>> scramble >>> convEncode+puncture
>>> interleave >>> modulate >>> map_ofdm >>> ifft >>> preamble/CP.

Two forms, per the framework's TPU-first design:

- ``encode_frame`` — a *frame-level* pure jax function: the whole PSDU
  to time-domain samples in one traced graph. This is the batched path:
  ``jax.vmap(encode_frame_bits, ...)`` processes a batch of frames as
  one device program (frame batching = the new data-parallel axis,
  SURVEY.md §2.4).
- ``tx_symbol_pipeline`` — the same DATA-symbol steady state expressed
  as a DSL pipeline (map_accum stages carrying scrambler phase, encoder
  tail, and symbol counter), demonstrating that the combinator IR
  expresses the chain; it lowers through backend/lower like any stream
  program.

Frame assembly (preamble, SIGNAL symbol, padding) is inherently
per-frame and lives only in the frame-level form.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ziria_tpu.ops import coding, interleave, modulate, ofdm, scramble
from ziria_tpu.ops.crc import append_crc32
from ziria_tpu.phy.wifi.params import (N_SERVICE_BITS, N_TAIL_BITS,
                                       RateParams, RATES, n_symbols)
from ziria_tpu.utils.bits import bytes_to_bits, uint_to_bits

# the standard's example frame seed; callers may override per frame
DEFAULT_SCRAMBLER_SEED = 0b1011101


def _seed_bits_np(seed_val: int) -> np.ndarray:
    return np.array([(seed_val >> k) & 1 for k in range(7)], np.uint8)


def signal_field_bits(rate: RateParams, length_bytes: int) -> jnp.ndarray:
    """The 24-bit SIGNAL field: RATE(4) R1-first, reserved(1), LENGTH(12)
    LSB-first, even parity(1), tail(6)."""
    rate_bits = uint_to_bits(np.uint32(rate.signal_bits), 4,
                             msb_first=True)
    length_bits = uint_to_bits(jnp.asarray(length_bytes, jnp.uint32), 12)
    head = jnp.concatenate([rate_bits, jnp.zeros(1, jnp.uint8),
                            length_bits])
    parity = (head.sum() % 2).astype(jnp.uint8)
    return jnp.concatenate([head, parity[None], jnp.zeros(6, jnp.uint8)])


def encode_signal_symbol(rate: RateParams, length_bytes: int) -> jnp.ndarray:
    """SIGNAL OFDM symbol (BPSK, rate 1/2, not scrambled): (80, 2)
    pair samples."""
    bits = signal_field_bits(rate, length_bytes)
    coded = coding.conv_encode(bits)          # 48 bits
    inter = interleave.interleave(coded, 48, 1)
    syms = modulate.modulate(inter, 1)        # (48, 2) BPSK
    bins = ofdm.map_subcarriers(syms[None, :, :], symbol_index0=0)
    return ofdm.ofdm_modulate(bins)[0]


def data_field_bits(psdu_bits, rate: RateParams,
                    n_sym: int) -> jnp.ndarray:
    """SERVICE + PSDU + tail + pad, scrambled, tail re-zeroed.

    `n_sym` must be static (it sets array sizes); psdu_bits length is
    static per trace.
    """
    n_bits = n_sym * rate.n_dbps
    psdu_bits = jnp.asarray(psdu_bits, jnp.uint8)
    n_data = N_SERVICE_BITS + psdu_bits.shape[0] + N_TAIL_BITS
    pad = n_bits - n_data
    raw = jnp.concatenate([
        jnp.zeros(N_SERVICE_BITS, jnp.uint8), psdu_bits,
        jnp.zeros(N_TAIL_BITS + pad, jnp.uint8)])
    seed = jnp.asarray(_seed_bits_np(DEFAULT_SCRAMBLER_SEED))
    scrambled = scramble.scramble_bits(raw, seed)
    # tail bits are zeroed AFTER scrambling so the decoder returns to the
    # zero state
    tail_at = N_SERVICE_BITS + psdu_bits.shape[0]
    return scrambled.at[tail_at: tail_at + N_TAIL_BITS].set(0)


def encode_frame_bits(psdu_bits, rate: RateParams) -> jnp.ndarray:
    """PSDU bits -> full frame time samples as pairs
    (320 preamble + 80 SIGNAL + 80*n_sym DATA, 2) float32."""
    if psdu_bits.shape[0] % 8:
        raise ValueError(
            f"PSDU must be whole bytes; got {psdu_bits.shape[0]} bits "
            f"(SIGNAL LENGTH is in bytes)")
    length_bytes = psdu_bits.shape[0] // 8
    n_sym = n_symbols(length_bytes, rate)
    bits = data_field_bits(psdu_bits, rate, n_sym)
    coded = coding.puncture(coding.conv_encode(bits), rate.coding)
    inter = interleave.interleave(coded, rate.n_cbps, rate.n_bpsc)
    syms = modulate.modulate(inter, rate.n_bpsc).reshape(n_sym, 48, 2)
    bins = ofdm.map_subcarriers(syms, symbol_index0=1)
    data_t = ofdm.ofdm_modulate(bins).reshape(-1, 2)
    sig_t = encode_signal_symbol(rate, length_bytes)
    return jnp.concatenate([ofdm.preamble(), sig_t, data_t], axis=0)


def encode_frame(psdu_bytes, rate_mbps: int,
                 add_fcs: bool = False) -> jnp.ndarray:
    """Byte-level convenience wrapper. ``add_fcs`` appends the 32-bit
    CRC (the reference TX's crc block) to the PSDU first."""
    rate = RATES[rate_mbps]
    bits = bytes_to_bits(jnp.asarray(psdu_bytes, jnp.uint8))
    if add_fcs:
        bits = append_crc32(bits)
    return encode_frame_bits(bits, rate)


# --------------------------------------------------------------------------
# DSL pipeline form (DATA-symbol steady state)
# --------------------------------------------------------------------------


def tx_symbol_pipeline(rate_mbps: int):
    """DSL pipeline: n_dbps raw data bits in -> 80 time samples out per
    firing, carrying scrambler phase / encoder tail / pilot index as
    map_accum state. Compose with backend.lower like any stream program.
    """
    import ziria_tpu as z

    rate = RATES[rate_mbps]
    n_dbps, n_cbps, n_bpsc = rate.n_dbps, rate.n_cbps, rate.n_bpsc

    seq_np = scramble.np_lfsr_sequence_127(
        _seed_bits_np(DEFAULT_SCRAMBLER_SEED))

    def stage_scramble(state, bits):
        phase = state  # scalar int32: position in the 127-periodic sequence
        seq = jnp.asarray(seq_np)
        idx = (phase + jnp.arange(n_dbps)) % 127
        out = jnp.asarray(bits, jnp.uint8) ^ seq[idx]
        return (phase + n_dbps) % 127, out

    def stage_encode(state, bits):
        tail = state  # last 6 input bits of the previous symbol
        ext = jnp.concatenate([tail, jnp.asarray(bits, jnp.int32)])
        a = jnp.convolve(ext, jnp.asarray(coding.G0))[6: 6 + n_dbps] % 2
        b = jnp.convolve(ext, jnp.asarray(coding.G1))[6: 6 + n_dbps] % 2
        coded = jnp.stack([a, b], 1).reshape(-1).astype(jnp.uint8)
        punct = coding.puncture(coded, rate.coding)
        return ext[-6:], punct

    def stage_map(state, coded_syms):
        sym_idx = state
        inter = interleave.interleave(coded_syms, n_cbps, n_bpsc)
        syms = modulate.modulate(inter, n_bpsc)
        pol = jnp.asarray(ofdm.PILOT_POLARITY, jnp.float32)[
            (sym_idx + 1) % 127]
        bins = jnp.zeros((64, 2), jnp.float32)
        bins = bins.at[jnp.asarray(ofdm.DATA_BINS), :].set(syms)
        p_re = jnp.asarray(ofdm.PILOT_VALS, jnp.float32) * pol
        bins = bins.at[jnp.asarray(ofdm.PILOT_BINS), :].set(
            jnp.stack([p_re, jnp.zeros_like(p_re)], axis=-1))
        t = ofdm.ofdm_modulate(bins[None, :, :])[0]
        return sym_idx + 1, t

    return z.pipe(
        z.map_accum(stage_scramble, np.int32(0),
                    in_arity=n_dbps, out_arity=n_dbps, name="scramble"),
        z.map_accum(stage_encode, np.zeros(6, np.int32),
                    in_arity=n_dbps, out_arity=n_cbps, name="encode"),
        z.map_accum(stage_map, np.int32(0),
                    in_arity=n_cbps, out_arity=80, name="map_ofdm_ifft"),
    )
