"""802.11a/g OFDM transmitter chain.

Counterpart of the reference's `code/WiFi/transmitter/` top-level
`tx.blk` (SURVEY.md §2.3, §3.5): crc >>> scramble >>> convEncode+puncture
>>> interleave >>> modulate >>> map_ofdm >>> ifft >>> preamble/CP.

Three forms, per the framework's TPU-first design:

- ``encode_frame`` — the per-frame entry: the whole PSDU to time-domain
  samples. Routed through an lru-cached jit per (rate, bit bucket,
  symbol bucket) — repeated sends at varied lengths reuse O(log
  buckets) compiled encoders instead of re-tracing eagerly per call
  (``encode_frame_bits`` stays the untraced-oracle graph form for
  callers composing their own jit/vmap).
- ``encode_many`` — the one-dispatch batched TX (the transmit twin of
  rx.decode_data_mixed): an N-frame batch of MIXED rates and lengths
  encodes as ONE jitted ``vmap(lax.switch)`` over per-rate bucketed
  encoders at a common (bit-bucket, symbol-bucket) geometry,
  bit-identical lane for lane to per-frame ``encode_frame``, with
  per-lane valid sample counts returned. ``encode_batch`` is the
  single-rate vmapped sibling (one cheap branch, the BER-sweep lane).
- ``tx_symbol_pipeline`` — the same DATA-symbol steady state expressed
  as a DSL pipeline (map_accum stages carrying scrambler phase, encoder
  tail, and symbol counter), demonstrating that the combinator IR
  expresses the chain; it lowers through backend/lower like any stream
  program.

Frame assembly (preamble, SIGNAL symbol, padding) is inherently
per-frame and lives in the frame-level forms.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ziria_tpu.ops import coding, interleave, modulate, ofdm, scramble
from ziria_tpu.ops.crc import append_crc32
from ziria_tpu.phy.wifi.params import (N_SERVICE_BITS, N_TAIL_BITS,
                                       RATE_INDEX, RATE_MBPS_ORDER,
                                       RateParams, RATES, n_symbols)
from ziria_tpu.utils import geometry as _geometry
from ziria_tpu.utils.bits import bytes_to_bits, uint_to_bits
from ziria_tpu.utils.dispatch import pad_lanes

# the standard's example frame seed; callers may override per frame
DEFAULT_SCRAMBLER_SEED = 0b1011101


def _seed_bits_np(seed_val: int) -> np.ndarray:
    return np.array([(seed_val >> k) & 1 for k in range(7)], np.uint8)


def signal_field_bits(rate: RateParams, length_bytes: int) -> jnp.ndarray:
    """The 24-bit SIGNAL field: RATE(4) R1-first, reserved(1), LENGTH(12)
    LSB-first, even parity(1), tail(6)."""
    rate_bits = uint_to_bits(np.uint32(rate.signal_bits), 4,
                             msb_first=True)
    length_bits = uint_to_bits(jnp.asarray(length_bytes, jnp.uint32), 12)
    head = jnp.concatenate([rate_bits, jnp.zeros(1, jnp.uint8),
                            length_bits])
    parity = (head.sum() % 2).astype(jnp.uint8)
    return jnp.concatenate([head, parity[None], jnp.zeros(6, jnp.uint8)])


def encode_signal_symbol(rate: RateParams, length_bytes: int) -> jnp.ndarray:
    """SIGNAL OFDM symbol (BPSK, rate 1/2, not scrambled): (80, 2)
    pair samples."""
    bits = signal_field_bits(rate, length_bytes)
    coded = coding.conv_encode(bits)          # 48 bits
    inter = interleave.interleave(coded, 48, 1)
    syms = modulate.modulate(inter, 1)        # (48, 2) BPSK
    bins = ofdm.map_subcarriers(syms[None, :, :], symbol_index0=0)
    return ofdm.ofdm_modulate(bins)[0]


def data_field_bits(psdu_bits, rate: RateParams,
                    n_sym: int) -> jnp.ndarray:
    """SERVICE + PSDU + tail + pad, scrambled, tail re-zeroed.

    `n_sym` must be static (it sets array sizes); psdu_bits length is
    static per trace.
    """
    n_bits = n_sym * rate.n_dbps
    psdu_bits = jnp.asarray(psdu_bits, jnp.uint8)
    n_data = N_SERVICE_BITS + psdu_bits.shape[0] + N_TAIL_BITS
    pad = n_bits - n_data
    raw = jnp.concatenate([
        jnp.zeros(N_SERVICE_BITS, jnp.uint8), psdu_bits,
        jnp.zeros(N_TAIL_BITS + pad, jnp.uint8)])
    seed = jnp.asarray(_seed_bits_np(DEFAULT_SCRAMBLER_SEED))
    scrambled = scramble.scramble_bits(raw, seed)
    # tail bits are zeroed AFTER scrambling so the decoder returns to the
    # zero state
    tail_at = N_SERVICE_BITS + psdu_bits.shape[0]
    return scrambled.at[tail_at: tail_at + N_TAIL_BITS].set(0)


def encode_frame_bits(psdu_bits, rate: RateParams) -> jnp.ndarray:
    """PSDU bits -> full frame time samples as pairs
    (320 preamble + 80 SIGNAL + 80*n_sym DATA, 2) float32."""
    if psdu_bits.shape[0] % 8:
        raise ValueError(
            f"PSDU must be whole bytes; got {psdu_bits.shape[0]} bits "
            f"(SIGNAL LENGTH is in bytes)")
    length_bytes = psdu_bits.shape[0] // 8
    n_sym = n_symbols(length_bytes, rate)
    bits = data_field_bits(psdu_bits, rate, n_sym)
    coded = coding.puncture(coding.conv_encode(bits), rate.coding)
    inter = interleave.interleave(coded, rate.n_cbps, rate.n_bpsc)
    syms = modulate.modulate(inter, rate.n_bpsc).reshape(n_sym, 48, 2)
    bins = ofdm.map_subcarriers(syms, symbol_index0=1)
    data_t = ofdm.ofdm_modulate(bins).reshape(-1, 2)
    sig_t = encode_signal_symbol(rate, length_bytes)
    return jnp.concatenate([ofdm.preamble(), sig_t, data_t], axis=0)


# --------------------------------------------------------------------------
# Bucketed / batched encode (the one-dispatch TX)
# --------------------------------------------------------------------------


def _sym_bucket(n_sym: int) -> int:
    """Power-of-two symbol bucket — the SAME rule as rx._sym_bucket
    (both sides share the Geometry object's bucket rule), so a
    loopback's encode and decode geometries agree by construction."""
    return _geometry.DEFAULT.sym_bucket(n_sym)


def _bit_bucket(n_bits: int) -> int:
    """Power-of-two PSDU bit bucket (the Geometry floor keeps tiny
    frames — ACKs, MAC control — in one compile class)."""
    return _geometry.DEFAULT.bit_bucket(n_bits)


def encode_frame_bits_bucketed(psdu_bits_padded, n_bits_real,
                               rate: RateParams,
                               n_sym_bucket: int) -> jnp.ndarray:
    """PSDU bits at a *bucketed* geometry -> frame time samples padded
    to ``n_sym_bucket`` DATA symbols: `psdu_bits_padded` is the PSDU
    zero-padded to a power-of-two bit bucket, `n_bits_real` the true
    bit count as a TRACED scalar. The first 400 + 80*n_symbols(real)
    samples are bit-identical to `encode_frame_bits`; the caller
    slices to the valid length.

    Why the pad is free: the raw DATA field already pads with zeros
    after the tail, and every stage before the IFFT is position-local
    — the scrambler XORs a fixed position-indexed sequence, the
    convolutional encoder is causal, puncture/interleave/modulate are
    per-position/per-symbol maps — so bucket-pad bits only ever append
    garbage *symbols* after the real ones, never perturb them. Only
    the 6 tail-bit positions depend on the true length, re-zeroed by a
    traced mask exactly as the unbucketed path re-zeroes them.
    """
    n_bits = n_sym_bucket * rate.n_dbps
    bits_pad = jnp.asarray(psdu_bits_padded, jnp.uint8)
    room = n_bits - N_SERVICE_BITS
    if bits_pad.shape[0] >= room:
        body = bits_pad[:room]
    else:
        body = jnp.concatenate(
            [bits_pad, jnp.zeros(room - bits_pad.shape[0], jnp.uint8)])
    raw = jnp.concatenate([jnp.zeros(N_SERVICE_BITS, jnp.uint8), body])
    seed = jnp.asarray(_seed_bits_np(DEFAULT_SCRAMBLER_SEED))
    scrambled = scramble.scramble_bits(raw, seed)
    # tail bits re-zeroed AFTER scrambling at the TRACED tail position
    t = jnp.arange(n_bits)
    tail_at = N_SERVICE_BITS + n_bits_real
    scrambled = jnp.where((t >= tail_at) & (t < tail_at + N_TAIL_BITS),
                          0, scrambled)
    coded = coding.puncture(coding.conv_encode(scrambled), rate.coding)
    inter = interleave.interleave(coded, rate.n_cbps, rate.n_bpsc)
    syms = modulate.modulate(inter, rate.n_bpsc).reshape(
        n_sym_bucket, 48, 2)
    bins = ofdm.map_subcarriers(syms, symbol_index0=1)
    data_t = ofdm.ofdm_modulate(bins).reshape(-1, 2)
    sig_t = encode_signal_symbol(rate, n_bits_real // 8)
    return jnp.concatenate([ofdm.preamble(), sig_t, data_t], axis=0)


@lru_cache(maxsize=None)
def _jit_encode_frame(rate_mbps: int, bit_bucket: int,
                      n_sym_bucket: int):
    """ONE compiled single-frame encoder per (rate, bit bucket, symbol
    bucket) — what `encode_frame` (and so the transceiver's every
    send) dispatches through: O(rates x log buckets) compiles total,
    zero re-tracing across repeated sends."""
    rate = RATES[rate_mbps]

    def f(bits_pad, n_bits_real):
        return encode_frame_bits_bucketed(bits_pad, n_bits_real, rate,
                                          n_sym_bucket)

    return jax.jit(f)


@lru_cache(maxsize=None)
def _jit_encode_batch(rate_mbps: int, bit_bucket: int,
                      n_sym_bucket: int):
    """Single-rate vmapped encoder (one cheap branch, no switch): the
    BER-sweep lane, where every frame in the batch shares one rate."""
    rate = RATES[rate_mbps]

    def f(bits_b, n_bits_real):
        return jax.vmap(
            lambda b: encode_frame_bits_bucketed(
                b, n_bits_real, rate, n_sym_bucket))(bits_b)

    return jax.jit(f)


def encode_many_graph(bits_b, nbits_b, ridx_b,
                      n_sym_bucket: int) -> jnp.ndarray:
    """The traced mixed-rate batch encode: ``vmap(lax.switch)`` over
    all 8 per-rate bucketed encoders at one symbol-bucket geometry —
    the graph `_jit_encode_many` jits, exposed as a plain function so
    larger programs can FUSE it (the one-dispatch loopback link traces
    it inline with the channel and receiver). bits_b (R, bit_bucket)
    zero-padded PSDU bits, nbits_b/ridx_b (R,) int32 true bit counts
    and RATE_MBPS_ORDER indices, all traced. Returns
    (R, 400 + 80*n_sym_bucket, 2); each lane's first
    400 + 80*n_symbols(real) samples are bit-identical to
    `encode_frame`."""
    branches = [
        (lambda b, n, _r=RATES[m]: encode_frame_bits_bucketed(
            b, n, _r, n_sym_bucket))
        for m in RATE_MBPS_ORDER]
    return jax.vmap(
        lambda b, n, r: jax.lax.switch(r, branches, b, n))(
            bits_b, jnp.asarray(nbits_b, jnp.int32),
            jnp.asarray(ridx_b, jnp.int32))


@lru_cache(maxsize=None)
def _jit_encode_many(bit_bucket: int, n_sym_bucket: int):
    """ONE jitted `encode_many_graph` per (bit bucket, symbol bucket)
    geometry — the TX twin of rx._jit_decode_data_mixed. Under vmap
    the switch lowers to a select over the branches; each lane's
    samples come from its own rate's encoder, bit-identical to the
    single-rate trace."""
    def f(bits_b, nbits_b, ridx_b):
        return encode_many_graph(bits_b, nbits_b, ridx_b, n_sym_bucket)

    return jax.jit(f)


def _host_psdu_bits(psdu_bytes, add_fcs: bool) -> np.ndarray:
    from ziria_tpu.utils.bits import np_bytes_to_bits
    bits = np_bytes_to_bits(np.asarray(psdu_bytes, np.uint8))
    if add_fcs:
        bits = np.asarray(append_crc32(bits), np.uint8)
    return bits


class TxBatch(NamedTuple):
    """One-dispatch encoded frame batch, device-resident.

    `samples` rows past the real lanes repeat lane 0 (the pad_lanes
    rule); `n_valid[i]` is lane i's true sample count — its frame is
    `samples[i, :n_valid[i]]`, bit-identical to `encode_frame`."""
    samples: jnp.ndarray          # (R_pow2, 400 + 80*n_sym_bucket, 2)
    n_valid: np.ndarray           # (B,) int32 valid sample counts
    n_sym: np.ndarray             # (B,) int32 true DATA symbol counts
    rates_mbps: tuple             # (B,) the lanes' rates
    n_sym_bucket: int


class TxHostPrep(NamedTuple):
    """The host-side batch prep every mixed-rate TX surface shares —
    THE one place the padded-batch rule lives (`encode_many` consumes
    it; the loopback link's `_LinkGeometry` wraps it, so the fused /
    staged / per-frame bit-identity contract can never be broken by
    the two drifting apart)."""
    bits_list: list               # per-lane true PSDU(+FCS) bits
    n_sym: np.ndarray             # (B,) int32 true DATA symbol counts
    bit_bucket: int
    n_sym_bucket: int
    bits_b: np.ndarray            # (R_pow2, bit_bucket) padded rows
    nbits_b: np.ndarray           # (R_pow2,) int32 true bit counts
    ridx_b: np.ndarray            # (R_pow2,) int32 RATE_MBPS_ORDER idx


def batch_host_prep(psdus: Sequence, rates_mbps: Sequence[int],
                    add_fcs: bool = False) -> TxHostPrep:
    """Byte PSDUs -> the padded (bit-bucket, symbol-bucket) batch
    arrays of the mixed-rate encode: bits (FCS appended when asked),
    per-lane symbol counts, the common buckets, and pad_lanes-rule
    rows (lane 0 repeated to the next power of two)."""
    if len(psdus) != len(rates_mbps):
        raise ValueError(f"{len(psdus)} PSDUs but {len(rates_mbps)} "
                         f"rates")
    if not len(psdus):
        raise ValueError("need at least one frame")
    bits_list = [_host_psdu_bits(p, add_fcs) for p in psdus]
    n_sym = np.asarray([n_symbols(b.shape[0] // 8, RATES[m])
                        for b, m in zip(bits_list, rates_mbps)],
                       np.int32)
    bb = _bit_bucket(max(b.shape[0] for b in bits_list))
    sb = max(_sym_bucket(int(s)) for s in n_sym)

    lanes = pad_lanes(list(range(len(psdus))))
    bits_b = np.zeros((len(lanes), bb), np.uint8)
    nbits_b = np.zeros(len(lanes), np.int32)
    ridx_b = np.zeros(len(lanes), np.int32)
    for row, i in enumerate(lanes):
        bits_b[row, :bits_list[i].shape[0]] = bits_list[i]
        nbits_b[row] = bits_list[i].shape[0]
        ridx_b[row] = RATE_INDEX[rates_mbps[i]]
    return TxHostPrep(bits_list, n_sym, bb, sb, bits_b, nbits_b,
                      ridx_b)


def encode_many(psdus: Sequence, rates_mbps: Sequence[int],
                add_fcs: bool = False) -> TxBatch:
    """One-dispatch mixed-rate, mixed-length TX: N PSDUs encode as ONE
    jitted ``vmap(lax.switch)`` at a common padded (bit-bucket,
    symbol-bucket) geometry. Lane for lane bit-identical to per-frame
    `encode_frame`; compile count is O(log bit buckets x log symbol
    buckets), independent of how many (rate, length) combinations the
    traffic mixes. The output stays device-resident — the loopback
    link (phy/link.py) feeds it straight into the channel and
    receiver without a host round trip."""
    from ziria_tpu.utils import dispatch, programs

    prep = batch_host_prep(psdus, rates_mbps, add_fcs)
    n_valid = (400 + 80 * prep.n_sym).astype(np.int32)
    enc_fn = _jit_encode_many(prep.bit_bucket, prep.n_sym_bucket)
    enc_args = (jnp.asarray(prep.bits_b), jnp.asarray(prep.nbits_b),
                jnp.asarray(prep.ridx_b))
    programs.note_site("tx.encode_many", enc_fn, *enc_args)
    with dispatch.timed("tx.encode_many"):
        samples = enc_fn(*enc_args)
    return TxBatch(samples, n_valid, prep.n_sym, tuple(rates_mbps),
                   prep.n_sym_bucket)


def encode_batch(psdus, rate_mbps: int,
                 add_fcs: bool = False) -> jnp.ndarray:
    """Single-rate equal-length batch: (B, n_bytes) PSDUs -> (B,
    frame_len, 2) device-resident frames in ONE dispatch, sliced to
    the true frame length (every lane shares it). Bit-identical per
    lane to `encode_frame` — the TX side of the BER waterfall sweep."""
    from ziria_tpu.utils import dispatch, programs

    from ziria_tpu.utils.dispatch import pow2_ceil

    psdus = np.asarray(psdus, np.uint8)
    n_frames = psdus.shape[0]
    bits = np.stack([_host_psdu_bits(p, add_fcs) for p in psdus])
    n_bits = bits.shape[1]
    n_sym = n_symbols(n_bits // 8, RATES[rate_mbps])
    bb = _bit_bucket(n_bits)
    bits_b = np.zeros((pow2_ceil(n_frames), bb), np.uint8)
    bits_b[:n_frames, :n_bits] = bits
    bits_b[n_frames:] = bits_b[0]
    enc_fn = _jit_encode_batch(rate_mbps, bb, _sym_bucket(n_sym))
    enc_args = (jnp.asarray(bits_b), jnp.int32(n_bits))
    programs.note_site("tx.encode_batch", enc_fn, *enc_args)
    with dispatch.timed("tx.encode_batch"):
        out = enc_fn(*enc_args)
    return out[:n_frames, :400 + 80 * n_sym]


def encode_frame(psdu_bytes, rate_mbps: int,
                 add_fcs: bool = False) -> jnp.ndarray:
    """Byte-level per-frame entry. ``add_fcs`` appends the 32-bit
    CRC (the reference TX's crc block) to the PSDU first.

    Dispatches through the lru-cached bucketed jit (one compiled
    encoder per (rate, bit bucket, symbol bucket), sliced to the true
    frame length) — bit-identical to the eager `encode_frame_bits`
    graph, without the per-call re-trace. Traced inputs (callers
    composing their own jit/vmap) fall through to the graph form."""
    rate = RATES[rate_mbps]
    if isinstance(psdu_bytes, jax.core.Tracer):
        bits = bytes_to_bits(jnp.asarray(psdu_bytes, jnp.uint8))
        if add_fcs:
            bits = append_crc32(bits)
        return encode_frame_bits(bits, rate)
    from ziria_tpu.utils import dispatch, programs

    bits = _host_psdu_bits(psdu_bytes, add_fcs)
    n_bits = bits.shape[0]
    n_sym = n_symbols(n_bits // 8, rate)
    bb = _bit_bucket(n_bits)
    bits_pad = np.zeros(bb, np.uint8)
    bits_pad[:n_bits] = bits
    enc_fn = _jit_encode_frame(rate_mbps, bb, _sym_bucket(n_sym))
    enc_args = (jnp.asarray(bits_pad), jnp.int32(n_bits))
    programs.note_site("tx.encode_frame", enc_fn, *enc_args)
    with dispatch.timed("tx.encode_frame"):
        out = enc_fn(*enc_args)
    return out[:400 + 80 * n_sym]


# --------------------------------------------------------------------------
# DSL pipeline form (DATA-symbol steady state)
# --------------------------------------------------------------------------


def tx_symbol_pipeline(rate_mbps: int):
    """DSL pipeline: n_dbps raw data bits in -> 80 time samples out per
    firing, carrying scrambler phase / encoder tail / pilot index as
    map_accum state. Compose with backend.lower like any stream program.
    """
    import ziria_tpu as z

    rate = RATES[rate_mbps]
    n_dbps, n_cbps, n_bpsc = rate.n_dbps, rate.n_cbps, rate.n_bpsc

    seq_np = scramble.np_lfsr_sequence_127(
        _seed_bits_np(DEFAULT_SCRAMBLER_SEED))

    def stage_scramble(state, bits):
        phase = state  # scalar int32: position in the 127-periodic sequence
        seq = jnp.asarray(seq_np)
        idx = (phase + jnp.arange(n_dbps)) % 127
        out = jnp.asarray(bits, jnp.uint8) ^ seq[idx]
        return (phase + n_dbps) % 127, out

    def stage_encode(state, bits):
        tail = state  # last 6 input bits of the previous symbol
        ext = jnp.concatenate([tail, jnp.asarray(bits, jnp.int32)])
        a = jnp.convolve(ext, jnp.asarray(coding.G0))[6: 6 + n_dbps] % 2
        b = jnp.convolve(ext, jnp.asarray(coding.G1))[6: 6 + n_dbps] % 2
        coded = jnp.stack([a, b], 1).reshape(-1).astype(jnp.uint8)
        punct = coding.puncture(coded, rate.coding)
        return ext[-6:], punct

    def stage_map(state, coded_syms):
        sym_idx = state
        inter = interleave.interleave(coded_syms, n_cbps, n_bpsc)
        syms = modulate.modulate(inter, n_bpsc)
        pol = jnp.asarray(ofdm.PILOT_POLARITY, jnp.float32)[
            (sym_idx + 1) % 127]
        bins = jnp.zeros((64, 2), jnp.float32)
        bins = bins.at[jnp.asarray(ofdm.DATA_BINS), :].set(syms)
        p_re = jnp.asarray(ofdm.PILOT_VALS, jnp.float32) * pol
        bins = bins.at[jnp.asarray(ofdm.PILOT_BINS), :].set(
            jnp.stack([p_re, jnp.zeros_like(p_re)], axis=-1))
        t = ofdm.ofdm_modulate(bins[None, :, :])[0]
        return sym_idx + 1, t

    return z.pipe(
        z.map_accum(stage_scramble, np.int32(0),
                    in_arity=n_dbps, out_arity=n_dbps, name="scramble"),
        z.map_accum(stage_encode, np.zeros(6, np.int32),
                    in_arity=n_dbps, out_arity=n_cbps, name="encode"),
        z.map_accum(stage_map, np.int32(0),
                    in_arity=n_cbps, out_arity=80, name="map_ofdm_ifft"),
    )
