"""802.11a/g OFDM receiver chain.

Counterpart of the reference's `code/WiFi/receiver/` top-level `rx.blk`
(SURVEY.md §2.3, §3.4): packet detect (STS autocorr) ; CFO est/correct ;
channel est (LTS) ; PLCP header parse ; then per-rate FFT >>> pilot
tracking >>> soft demap >>> deinterleave >>> Viterbi >>> descramble >>>
CRC.

TPU-first structure: the steady-state DATA decode is one traced graph
over ALL symbols of a frame at once — (n_sym, 64) matmul-FFTs, batched
pilot tracking, one Viterbi scan — and batches over frames with vmap.
The data-dependent part (header-derived rate/length — the motivating
example for the reference's computers-returning-values, §3.4) is a
two-phase dispatch: decode SIGNAL (fixed shape), then select the
per-rate compiled decoder — the jit analogue of `parsePLCPHeader ;
per-rate loop`. ``receive()`` drives the whole thing host-side;
``decode_data_static`` is the fully-jitted flagship used by the bench.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ziria_tpu.ops import cplx, coding, demap as demap_mod, interleave, ofdm, \
    scramble, sync, viterbi, viterbi_pallas
from ziria_tpu.ops.crc import check_crc32
# MAX_DBPS / RATE_INDEX / RATE_MBPS_ORDER: the lax.switch branch
# order shared with TX encode_many (hoisted to params so both sides
# of the link agree by construction), re-exported here because this
# module is where the switch-order contract is consumed
from ziria_tpu.phy.wifi.params import (MAX_DBPS, N_SERVICE_BITS,
                                       N_TAIL_BITS, RATE_INDEX,
                                       RATE_MBPS_ORDER, RateParams,
                                       RATES, SIGNAL_BITS_TO_MBPS,
                                       n_symbols)
from ziria_tpu.utils.bits import bits_to_uint

FRAME_DATA_START = 400  # 320 preamble + 80 SIGNAL


def equalize(bins, H):
    """Zero-forcing equalization of (..., 64, 2) bins by H (64, 2)."""
    return cplx.cdiv(bins, jnp.broadcast_to(H, bins.shape))


#: bounded-|H| equalizer guard: a used subcarrier whose estimated
#: channel gain |H|^2 falls below this fraction of the MEAN used-bin
#: gain is treated as a NULL — its equalized symbols, its demap gain,
#: and (crucially) its pilot contribution zero out EXACTLY, so a deep
#: multipath fade degrades to zero-LLR erasures instead of feeding
#: noise amplified by 1/|H| into the demapper, and a nulled PILOT
#: stops poisoning the common-phase estimate of every other
#: subcarrier in its symbol. 1e-3 sits far below any healthy gain
#: (flat channels estimate |H|^2 ~ 1 +- noise), so on flat channels
#: the guard never trips and the select ops pass values through
#: bitwise — the flat-profile identity contract holds through it.
H_GUARD_REL = 1e-3


def guard_subcarriers(data, pilots, H):
    """The bounded-|H| null-subcarrier guard (docs/robustness.md):
    given extracted data (..., n_sym, 48, 2) and pilots
    (..., n_sym, 4, 2) plus the channel estimate H (64, 2), zero the
    bins whose gain is under ``H_GUARD_REL`` x the mean used-bin gain
    and return ``(data, pilots, gain)`` with `gain` the (48,)
    demap weight, zeroed at nulls (an exact-zero equalized symbol
    times an exact-zero gain = a true erasure LLR, the same
    adds-no-likelihood argument as the bucket padding)."""
    g = cplx.cabs2(H)                                     # (64,)
    gd = g[jnp.asarray(ofdm.DATA_BINS)]                   # (48,)
    gp = g[jnp.asarray(ofdm.PILOT_BINS)]                  # (4,)
    floor = H_GUARD_REL * jnp.mean(jnp.concatenate([gd, gp]))
    data = jnp.where((gd < floor)[:, None], 0.0, data)
    pilots = jnp.where((gp < floor)[:, None], 0.0, pilots)
    gain = jnp.where(gd < floor, 0.0, gd)
    return data, pilots, gain


def sco_track_enabled(sco_track=None) -> bool:
    """The ONE reading of the --rx-sco-track / ZIRIA_RX_SCO_TRACK
    knob (default OFF — the flat-profile bit-identity contract pins
    the default DATA decode bitwise, and a fitted slope is never
    exactly zero): whether `pilot_phase_correct` additionally fits
    and removes the per-subcarrier phase RAMP a sampling-clock
    offset induces (docs/robustness.md). Callers resolve once and
    pass the bool into the decode jit factories' cache keys. The env
    read itself lives with the geometry object's designated reader
    (utils/geometry.env_sco_track)."""
    if sco_track is not None:
        return bool(sco_track)
    from ziria_tpu.utils.geometry import env_sco_track
    return env_sco_track()


def pilot_phase_correct(data, pilots, symbol_index0: int,
                        sco_track: bool = False):
    """Common-phase derotation per symbol from the 4 pilots.

    data (..., n_sym, 48, 2), pilots (..., n_sym, 4, 2); pilot polarity
    index starts at symbol_index0.

    ``sco_track=True`` additionally fits the per-subcarrier phase
    RAMP across the pilots and derotates the data by it: a
    sampling-clock offset is a timing drift tau(t), which in the
    frequency domain is a phase slope ~ k * tau growing over the
    frame — the common phase tracks its mean, the ramp is what is
    left. Slope per symbol by least squares through the origin over
    the pilot subcarrier indices (-21, -7, 7, 21), weighted by pilot
    energy so a guarded-out null pilot carries zero weight. Off by
    default: the flat-path decode must stay bit-identical, and a
    fitted slope is never exactly zero."""
    n_sym = data.shape[-3]
    pol = jnp.asarray(ofdm.PILOT_POLARITY, jnp.float32)[
        (jnp.arange(n_sym) + symbol_index0) % 127]
    expect_re = jnp.asarray(ofdm.PILOT_VALS, jnp.float32)[None, :] * \
        pol[:, None]                                   # (n_sym, 4)
    # phase of sum_k pilots_k * expected_k (expected is real)
    weighted = pilots * expect_re[..., :, None]
    ph = jnp.arctan2(weighted[..., 1].sum(-1), weighted[..., 0].sum(-1))
    derot = cplx.cexp(-ph)                             # (..., n_sym, 2)
    data = cplx.cmul(data, derot[..., None, :])
    if not sco_track:
        return data
    w = cplx.cmul(weighted, derot[..., None, :])   # common phase out
    res = jnp.arctan2(w[..., 1], w[..., 0])        # (..., n_sym, 4)
    k_p = jnp.asarray(ofdm.PILOT_SC, jnp.float32)
    e = cplx.cabs2(w)
    num = jnp.sum(e * k_p * res, axis=-1)
    den = jnp.sum(e * k_p * k_p, axis=-1)
    slope = num / jnp.maximum(den, 1e-12)          # rad / subcarrier
    k_d = jnp.asarray(ofdm.DATA_SC, jnp.float32)
    ramp = cplx.cexp(-slope[..., None] * k_d)      # (..., n_sym, 48, 2)
    return cplx.cmul(data, ramp)


def decode_signal(frame):
    """Decode the SIGNAL symbol of an aligned, CFO-corrected frame.

    Returns (rate_bits_uint (traced), length (traced), parity_ok
    (traced)). Fixed shapes — jits once."""
    H = sync.estimate_channel(frame)
    bins = ofdm.ofdm_demodulate(frame[320:400][None])  # (1, 64, 2)
    eq = equalize(bins, H)
    data, pilots = ofdm.extract_subcarriers(eq)
    data, pilots, gain = guard_subcarriers(data, pilots, H)
    data = pilot_phase_correct(data, pilots, symbol_index0=0)
    llr = demap_mod.demap(data, 1, gain=gain[None])[0]
    deint = interleave.deinterleave(llr, 48, 1)
    bits = viterbi.viterbi_decode(deint, n_bits=24)
    rate_bits = bits_to_uint(bits[0:4], msb_first=True)
    length = bits_to_uint(bits[5:17])
    parity_ok = (bits[:18].astype(jnp.uint32).sum() % 2) == 0
    return rate_bits, length, parity_ok


def _front_symbols(frame, n_sym: int, sco_track: bool = False):
    """Aligned frame -> (data (n_sym, 48, 2), gain (48,)): channel est
    (two-repeat LTS average) + (n_sym x 64) matmul-FFT + equalize +
    bounded-|H| guard + pilot track — the shared pre-demap front.
    Split out so the fused-demap decode can hand the raw equalized
    subcarriers straight to the Pallas kernel
    (ops/viterbi_pallas.viterbi_decode_batch_fused) while the XLA
    demap path keeps consuming the identical values. ``sco_track``
    adds the pilot phase-ramp fit (resolved by the caller — part of
    every decode factory's cache key)."""
    H = sync.estimate_channel(frame)
    syms = frame[FRAME_DATA_START: FRAME_DATA_START + 80 * n_sym]
    bins = ofdm.ofdm_demodulate(syms.reshape(n_sym, 80, 2))
    eq = equalize(bins, H)
    data, pilots = ofdm.extract_subcarriers(eq)
    data, pilots, gain = guard_subcarriers(data, pilots, H)
    data = pilot_phase_correct(data, pilots, symbol_index0=1,
                               sco_track=sco_track)
    return data, gain


def _decode_front(frame, rate: RateParams, n_sym: int,
                  sco_track: bool = False):
    """Aligned frame -> depunctured soft LLR pairs (T, 2): channel est +
    (n_sym x 64) matmul-FFT + equalize + pilot track + demap +
    deinterleave + depuncture — everything before the Viterbi."""
    data, gain = _front_symbols(frame, n_sym, sco_track)
    llrs = demap_mod.demap(data, rate.n_bpsc,
                           gain=jnp.broadcast_to(gain, data.shape[:-1]))
    deint = interleave.deinterleave(
        llrs.reshape(-1), rate.n_cbps, rate.n_bpsc)
    return coding.depuncture(deint, rate.coding, fill=0.0).reshape(-1, 2)


def fused_demap_enabled(fused_demap=None) -> bool:
    """The ONE reading of the --fused-demap / ZIRIA_FUSED_DEMAP knob
    (default OFF — the XLA front end is the oracle): whether the
    known-rate DATA decodes run demap + deinterleave + depuncture as
    an in-kernel prologue of the Pallas ACS (LLRs produced and
    consumed in VMEM, never round-tripping HBM). The env read itself
    lives with the geometry object's designated reader
    (utils/geometry.env_fused_demap)."""
    if fused_demap is not None:
        return fused_demap
    from ziria_tpu.utils.geometry import env_fused_demap
    return env_fused_demap()


def _fused_front_applies(viterbi_window, viterbi_metric) -> bool:
    """Where the fused front end composes: full-frame decodes at f32
    metrics. The windowed decode cuts LLR-domain windows the symbol
    tile cannot express, and the quantized metrics scale by the whole
    frame's LLR peak before the first ACS step — both fall back to
    the (bit-identical) unfused front, documented in
    docs/architecture.md's decode-roofline section."""
    return not viterbi_window and (viterbi_metric or "float32") == "float32"


def _decode_back(bits, n_psdu_bits: int):
    """Decoded bits -> (psdu_bits, descrambled service bits)."""
    seed = scramble.recover_seed(bits[:7])
    clear = scramble.descramble_bits(bits, seed)
    psdu = clear[N_SERVICE_BITS: N_SERVICE_BITS + n_psdu_bits]
    return psdu, clear[:N_SERVICE_BITS]


def decode_data_static(frame, rate: RateParams, n_sym: int,
                       n_psdu_bits: int, sco_track: bool = False):
    """Fully-jitted DATA decode for a known rate/symbol count: aligned
    CFO-corrected frame -> (psdu_bits, descrambled service bits).

    The flagship fused graph: channel est + (n_sym x 64) matmul-FFT +
    equalize + pilot track + demap + deinterleave + depuncture + Viterbi
    + descramble in one jit."""
    depunct = _decode_front(frame, rate, n_sym, sco_track)
    bits = viterbi.viterbi_decode(depunct, n_bits=n_sym * rate.n_dbps)
    return _decode_back(bits, n_psdu_bits)


def decode_data_batch(frames, rate: RateParams, n_sym: int,
                      n_psdu_bits: int, interpret: bool = None,
                      viterbi_window: int = None,
                      viterbi_metric: str = None,
                      viterbi_radix: int = None,
                      fused_demap: bool = None,
                      sco_track: bool = False):
    """Batched DATA decode: (B, frame_len, 2) -> ((B, n_psdu_bits),
    (B, 16)).

    The TPU fast path: the per-frame front end (FFT/equalize/demap/...)
    runs under vmap, then the whole batch hits the Pallas Viterbi kernel
    with frames laid out across the 128 VPU lanes (~8x the vmapped
    lax.scan ACS; see ops/viterbi_pallas.py).

    ``viterbi_window`` opts into the sliding-window PARALLEL Viterbi
    (viterbi_decode_batch_windowed): the ~8k-step sequential trellis is
    cut into overlapping windows decoded as extra batch lanes — the
    standard truncated-traceback trade every production decoder
    (including the reference's SORA brick) makes, bit-identical to the
    exact decode at operating SNR (tests/test_viterbi_windowed.py).

    ``viterbi_metric="int16"`` opts into the quantized saturating-
    metric kernel (the SORA int16 discipline; docs/quantized_viterbi.md
    — the other half of the device-residency trade); ``"int8"`` into
    the int8+LUT kernel below it (BER-envelope accuracy).

    ``viterbi_radix=4`` runs two trellis steps per ACS iteration
    (bit-identical at f32/int16); ``fused_demap=True`` moves demap +
    deinterleave + depuncture into the Pallas kernel (known-rate
    surfaces only; composes with radix, falls back to the unfused
    front under windowed/quantized modes)."""
    if fused_demap_enabled(fused_demap) \
            and _fused_front_applies(viterbi_window, viterbi_metric):
        data, gain = jax.vmap(
            lambda f: _front_symbols(f, n_sym, sco_track))(frames)
        bits = viterbi_pallas.viterbi_decode_batch_fused(
            data, gain, rate, n_bits=n_sym * rate.n_dbps,
            radix=viterbi_radix, interpret=interpret)
    else:
        dep = jax.vmap(
            lambda f: _decode_front(f, rate, n_sym, sco_track))(frames)
        bits = viterbi_pallas.viterbi_decode_batch_opt(
            dep, n_bits=n_sym * rate.n_dbps, window=viterbi_window,
            interpret=interpret, metric_dtype=viterbi_metric,
            radix=viterbi_radix)
    return jax.vmap(lambda b: _decode_back(b, n_psdu_bits))(bits)


def sync_frame(samples):
    """Locate and align ONE frame in a pre-segmented capture: STS
    detection gate, LTS cross-correlation timing, coarse+fine CFO.
    Returns (found, frame_start_index, cfo_estimate). Fixed shapes ->
    jits.

    The graph itself lives in ``ops/sync.locate_frame`` (vmap-ready so
    ``acquire_many`` can batch it); this name is the receiver-side
    oracle entry the per-capture path and tests use. It is the K=1
    special case of the streaming front end — first crossing, global
    peak-pick — that ``ops/sync.locate_frames``' multi-peak chunk scan
    (the ``receive_stream`` detector) generalizes and is judged
    against; a one-frame capture gives identical (found, start) either
    way."""
    return sync.locate_frame(samples)


class RxResult(NamedTuple):
    ok: bool
    rate_mbps: int
    length_bytes: int
    psdu_bits: np.ndarray
    crc_ok: Optional[bool]


def decode_data_bucketed(frame, rate: RateParams, n_sym_bucket: int,
                         n_bits_real, viterbi_window: int = None,
                         viterbi_metric: str = None,
                         viterbi_radix: int = None,
                         fused_demap: bool = None,
                         sco_track: bool = False):
    """DATA decode over a *bucketed* symbol count: `frame` is padded to
    FRAME_DATA_START + 80*n_sym_bucket samples, `n_bits_real` is the
    true data-bit count as a TRACED scalar. Returns the full descrambled
    bit stream (n_sym_bucket * n_dbps); the caller slices the PSDU.

    This is what makes `receive()` streaming-grade (VERDICT r1 weak #3):
    one compile per (rate, power-of-two bucket) instead of one per PSDU
    length. LLR rows at or beyond `n_bits_real` are zeroed — true
    erasures — so the pad region adds no likelihood and the Viterbi path
    over the real prefix is exactly the unpadded ML path (the tail bits
    still steer it into state 0 before the pad)."""
    if fused_demap_enabled(fused_demap) \
            and _fused_front_applies(viterbi_window, viterbi_metric):
        # the fused kernel applies the SAME n_bits_real erasure mask
        # in its prologue; this single frame rides one pad-to-128 lane
        # tile of the fused Pallas decode
        data, gain = _front_symbols(frame, n_sym_bucket, sco_track)
        bits = viterbi_pallas.viterbi_decode_batch_fused(
            data[None], gain[None], rate,
            n_bits=n_sym_bucket * rate.n_dbps,
            nbits_real=jnp.asarray(n_bits_real, jnp.int32)[None],
            radix=viterbi_radix)[0]
    else:
        bits = _decode_data_bits_unfused(
            frame, rate, n_sym_bucket, n_bits_real,
            viterbi_window, viterbi_metric, viterbi_radix, sco_track)
    seed = scramble.recover_seed(bits[:7])
    return scramble.descramble_bits(bits, seed)


def _decode_data_bits_unfused(frame, rate, n_sym_bucket, n_bits_real,
                              viterbi_window, viterbi_metric,
                              viterbi_radix, sco_track=False):
    """The XLA-front-end decode body of `decode_data_bucketed`: demap
    front end, traced erasure mask, then whichever Viterbi engine the
    (window, metric, radix) mode selects. Raw coded bits out — the
    caller owns the descramble tail."""
    depunct = _decode_front(frame, rate, n_sym_bucket,
                            sco_track)                    # (T_b, 2)
    t = jnp.arange(depunct.shape[0])
    depunct = jnp.where((t < n_bits_real)[:, None], depunct, 0.0)
    if viterbi_window:
        # the windowed PARALLEL decoder: this single frame's windows
        # become a small batch through the Pallas kernel, cutting the
        # sequential trellis depth ~T/window-fold (see
        # ops/viterbi_pallas.viterbi_decode_batch_windowed)
        bits = viterbi_pallas.viterbi_decode_batch_windowed(
            depunct[None], n_bits=n_sym_bucket * rate.n_dbps,
            window=viterbi_window, metric_dtype=viterbi_metric,
            radix=viterbi_radix)[0]
    elif (viterbi._check_radix(viterbi_radix) != 2
          or (viterbi_metric or "float32") == "int8"):
        # the radix knob (and the int8 kernel) live in the Pallas
        # batch decode; ride it as a single-lane batch so the bucketed
        # per-capture path inherits the faster core too
        bits = viterbi_pallas.viterbi_decode_batch(
            depunct[None], n_bits=n_sym_bucket * rate.n_dbps,
            metric_dtype=viterbi_metric, radix=viterbi_radix)[0]
    else:
        bits = viterbi.viterbi_decode(
            depunct, n_bits=n_sym_bucket * rate.n_dbps,
            metric_dtype=viterbi_metric)
    return bits


@lru_cache(maxsize=None)
def _jit_decode_data_bucketed(rate_mbps: int, n_sym_bucket: int,
                              fxp: bool = False,
                              viterbi_window: int = None,
                              viterbi_metric: str = None,
                              viterbi_radix: int = None,
                              sco_track: bool = False,
                              fused_demap: bool = None):
    """Callers pass RESOLVED radix/sco/fused values (never None-
    meaning-env): the decode mode is part of the compile-cache key, so
    an in-process env change must re-trace (ADVICE r5 #1 discipline).
    ``fused_demap`` stays the LAST parameter — tests/test_lint.py's R1
    acceptance demo AST-drops it by position."""
    rate = RATES[rate_mbps]

    if fxp:
        from ziria_tpu.phy.wifi import rx_fxp

        def f(frame_q, n_bits_real):
            return rx_fxp.decode_data_bucketed_fxp(
                frame_q, rate, n_sym_bucket, n_bits_real)
    else:
        def f(frame, n_bits_real):
            return decode_data_bucketed(frame, rate, n_sym_bucket,
                                        n_bits_real, viterbi_window,
                                        viterbi_metric, viterbi_radix,
                                        fused_demap, sco_track)

    return jax.jit(f)


def _sym_bucket(n_sym: int) -> int:
    """Power-of-two symbol bucket (the floor keeps tiny frames in one
    compile class). Shared with the TX batch path (tx.encode_many
    buckets its symbol counts with the same rule, so a loopback's
    encode and decode geometries agree) — the rule itself lives on the
    Geometry object (utils/geometry; jaxlint R6 flags literal
    floors)."""
    from ziria_tpu.utils.geometry import DEFAULT
    return DEFAULT.sym_bucket(n_sym)


# ------------------------------------------------------- mixed-rate dispatch


def decode_data_mixed(frames, rate_idx, n_bits_real, n_sym_bucket: int,
                      viterbi_window: int = None,
                      viterbi_metric: str = None,
                      viterbi_radix: int = None,
                      interpret: bool = None,
                      sco_track: bool = False,
                      fused_demap: bool = None):
    """Mixed-rate batched DATA decode in ONE device dispatch — the
    compiled-program analogue of Ziria's in-language rate dispatch
    (the reference's `parsePLCPHeader ; per-rate loop` runs INSIDE the
    compiled receiver; SURVEY.md §3.4, §7 step 6).

    frames: (B, FRAME_DATA_START + 80*n_sym_bucket, 2) aligned,
    CFO-corrected frames padded to ONE common symbol bucket;
    rate_idx: (B,) int32 indices into RATE_MBPS_ORDER (traced);
    n_bits_real: (B,) int32 true data-bit counts (traced).
    Returns (B, n_sym_bucket * MAX_DBPS) descrambled bit streams; the
    caller slices each lane's PSDU.

    Geometry trick that makes one `lax.switch` serve all 8 rates: each
    per-rate branch runs only the CHEAP front end (FFT/equalize/demap/
    deinterleave/depuncture) at its own rate and pads the depunctured
    LLRs to the bucket's maximal trellis (n_sym_bucket * MAX_DBPS)
    with zero-LLR erasures — the same "adds no likelihood" argument as
    the symbol-bucket padding, so the surviving path over each lane's
    real prefix is exactly its unpadded ML path. The EXPENSIVE Viterbi
    then runs once, rate-agnostic, over the whole mixed batch through
    the Pallas kernel with every lane riding the same 128-lane tiles —
    mixed traffic no longer fragments the hot kernel's batch. Under
    vmap the switch lowers to a select over the (cheap) front-end
    branches; the per-lane trellis work is never duplicated.

    vs the host-side bucketed path (`receive`): compile count for the
    DATA stage drops from O(rates x log lengths) to O(log lengths),
    and a mixed-rate batch costs ONE device call instead of one per
    rate group.

    ``viterbi_radix``/``viterbi_metric`` reach the shared Pallas ACS,
    so every mixed surface (receive_many, the streaming receiver, the
    fused link) inherits the faster core. ``fused_demap=True`` moves
    demap + deinterleave + depuncture into the kernel here too
    (ISSUE 20): the rate-SWITCHED fused prologue row-selects each
    lane's slot tables from one stacked all-rates constant bank
    (ops/viterbi_pallas.viterbi_decode_mixed_fused), the XLA front
    collapses from 8 per-rate branches to ONE rate-independent
    `_front_symbols` vmap, and the LLRs are produced and consumed in
    VMEM — the one rate-agnostic Viterbi this dispatch exists to
    share stays one kernel. Windowed/quantized modes fall back to the
    (bit-identical) unfused front, exactly like the known-rate path.
    """
    t_max = n_sym_bucket * MAX_DBPS
    rate_idx = jnp.asarray(rate_idx, jnp.int32)
    n_bits_real = jnp.asarray(n_bits_real, jnp.int32)
    if fused_demap_enabled(fused_demap) \
            and _fused_front_applies(viterbi_window, viterbi_metric):
        data, gain = jax.vmap(
            lambda f: _front_symbols(f, n_sym_bucket, sco_track))(frames)
        bits = viterbi_pallas.viterbi_decode_mixed_fused(
            data, gain, rate_idx, n_bits_real, radix=viterbi_radix,
            interpret=interpret)
    else:
        def _branch(rate):
            def f(frame):
                dep = _decode_front(frame, rate, n_sym_bucket, sco_track)
                return jnp.pad(dep, ((0, t_max - dep.shape[0]), (0, 0)))
            return f

        branches = [_branch(RATES[m]) for m in RATE_MBPS_ORDER]
        dep = jax.vmap(
            lambda f, r: jax.lax.switch(r, branches, f))(frames, rate_idx)
        # rows at/after each lane's true bit count become erasures
        # (covers both the in-rate bucket pad and the cross-rate pad
        # to MAX_DBPS)
        t = jnp.arange(t_max)
        dep = jnp.where((t[None, :] < n_bits_real[:, None])[..., None],
                        dep, 0.0)
        bits = viterbi_pallas.viterbi_decode_batch_opt(
            dep, window=viterbi_window, metric_dtype=viterbi_metric,
            radix=viterbi_radix, interpret=interpret)

    def _descramble(b):
        seed = scramble.recover_seed(b[:7])
        return scramble.descramble_bits(b, seed)

    return jax.vmap(_descramble)(bits)


def crc_psdu_many_graph(clear_b, n_psdu_bits):
    """Batched FCS check over the mixed decode's output: for each lane
    of `clear_b` (B, n_sym_bucket * MAX_DBPS descrambled bit streams)
    with `n_psdu_bits` (B,) traced true PSDU bit counts, True iff the
    PSDU's trailing 32 bits are the CRC-32 of the rest — ONE vmapped
    masked-scan CRC at the common bucket instead of a host
    `check_crc32` dispatch per lane (`ops/crc.check_crc32_masked`),
    boolean-identical lane for lane. Traced, so the fused loopback
    link inlines it after the decode."""
    from ziria_tpu.ops.crc import check_crc32_masked

    return jax.vmap(check_crc32_masked)(
        clear_b[:, N_SERVICE_BITS:], jnp.asarray(n_psdu_bits, jnp.int32))


@lru_cache(maxsize=None)
def _jit_crc_many():
    """ONE jitted batched FCS check serving every (lane count, bucket)
    geometry (jit retraces per shape)."""
    return jax.jit(crc_psdu_many_graph)


@lru_cache(maxsize=None)
def _jit_decode_data_mixed(n_sym_bucket: int, viterbi_window: int = None,
                           viterbi_metric: str = None,
                           viterbi_radix: int = None,
                           sco_track: bool = False,
                           fused_demap: bool = False):
    """ONE jit per (symbol bucket, decode mode) serving ALL rates —
    the decode-mode knobs (window, metric, radix, sco_track,
    fused_demap) are part of the cache key, so an in-process change
    can never silently reuse the other mode's trace (ADVICE r5 #1
    discipline; callers pass RESOLVED radix/sco/fused values, never
    None-meaning-env). ``fused_demap`` stays the LAST parameter —
    tests/test_lint.py's R1 acceptance demo AST-drops it by
    position."""
    def f(frames, rate_idx, n_bits_real):
        return decode_data_mixed(frames, rate_idx, n_bits_real,
                                 n_sym_bucket, viterbi_window,
                                 viterbi_metric, viterbi_radix,
                                 sco_track=sco_track,
                                 fused_demap=fused_demap)
    return jax.jit(f)


# ------------------------------------------------------ frame acquisition
#
# Two structurally-identical paths share one decision tree:
#  - `_acquire_frame`: the per-capture oracle (host-driven, 2 fixed-
#    shape jits + one eager CFO rotation per capture);
#  - `acquire_many`: the whole front end for N captures as ONE vmapped
#    dispatch (`acquire_frame_graph` under vmap), the host reduced to
#    integer header parsing between dispatches.
# Lane-for-lane bit-identity between them is the pinned contract
# (tests/test_rx_batched_acquire.py).


@lru_cache(maxsize=None)
def _jit_sync_fn():
    """jit(sync_frame), built once. `lru_cache` (not a checked global)
    so concurrent first calls from `framebatch` worker threads can
    never observe a half-initialized pair; a racing duplicate build is
    harmless — one value wins the cache and both are valid."""
    return jax.jit(sync_frame)


@lru_cache(maxsize=None)
def _jit_signal_fn():
    return jax.jit(decode_signal)


class _Acquired(NamedTuple):
    """A detected, SIGNAL-parsed capture, ready for a DATA decode."""
    frame_np: np.ndarray        # samples from the frame start (f32)
    avail: int                  # true capture samples past the start
    eps: float                  # CFO estimate
    rate_mbps: int
    length_bytes: int
    n_sym: int


def _stream_bucket(n: int) -> int:
    """Power-of-two capture bucket: the ONE padding formula the
    per-capture and batched acquisition paths share — their
    bit-identity contract assumes identical padded geometry rules.
    The rule (and its floor) lives on the Geometry object
    (utils/geometry; jaxlint R6 flags literal floors)."""
    from ziria_tpu.utils.geometry import DEFAULT
    return DEFAULT.capture_bucket(n)


def _bucket_pad(x: np.ndarray):
    """Pad a capture to its power-of-two bucket so the sync/acquire
    jits compile once per bucket, not once per stream length (zeros
    are inert to detection). Returns (padded, n_valid)."""
    n_valid = x.shape[0]
    bucket = _stream_bucket(n_valid)
    if bucket != n_valid:
        x = np.concatenate(
            [x, np.zeros((bucket - n_valid, 2), np.float32)], axis=0)
    return x, n_valid


def _classify_acquire(found: bool, avail: int, rate_bits: int,
                      length_bytes: int, parity_ok: bool):
    """The shared host decision tree over acquisition outputs — all
    integer/bool parsing, no device work. Returns (RxResult, None) on
    any failure, (None, (rate_mbps, n_sym)) for a decodable frame.

    All length checks use the true capture length — decoding padding
    zeros as DATA must fail, not silently "succeed".
    `classify_acquire_graph` is the traced twin the fused loopback
    link runs on-device; their branch-for-branch agreement is pinned
    by tests/test_link_fused.py."""
    fail = RxResult(False, 0, 0, np.zeros(0, np.uint8), None)
    if not found or avail < 400 or not parity_ok:
        return fail, None
    rate_mbps = SIGNAL_BITS_TO_MBPS.get(rate_bits)
    if rate_mbps is None:
        return fail, None
    n_sym = n_symbols(length_bytes, RATES[rate_mbps])
    if avail < FRAME_DATA_START + 80 * n_sym:
        return RxResult(False, rate_mbps, length_bytes,
                        np.zeros(0, np.uint8), None), None
    return None, (rate_mbps, n_sym)


# 16-entry lookup tables over the 4-bit SIGNAL RATE field: mbps (0 for
# the 8 invalid codes) and n_dbps — what lets `classify_acquire_graph`
# run `SIGNAL_BITS_TO_MBPS.get` + `n_symbols` as traced integer ops
_RB_TO_MBPS = np.zeros(16, np.int32)
_RB_TO_DBPS = np.zeros(16, np.int32)
for _rb, _m in SIGNAL_BITS_TO_MBPS.items():
    _RB_TO_MBPS[_rb] = _m
    _RB_TO_DBPS[_rb] = RATES[_m].n_dbps

# classification codes shared by the traced tree and its host readers
ACQ_FAIL, ACQ_TRUNCATED, ACQ_DECODABLE = 0, 1, 2


def classify_acquire_graph(found, avail, rate_bits, length_bytes,
                           parity_ok):
    """The traced twin of `_classify_acquire` — the same pure-integer
    decision tree as jnp ops, so the fused loopback link keeps it
    on-device (no acquisition metadata crosses the host link mid-
    batch). All inputs traced, elementwise over any batch shape.

    Returns ``(status, rate_mbps, length_bytes, n_sym)``:
    status `ACQ_FAIL` (no detect / short capture / bad parity /
    unknown rate; rate/length forced 0 exactly as the host tree's fail
    RxResult), `ACQ_TRUNCATED` (SIGNAL parsed but the capture can't
    hold the claimed DATA field; rate/length are the parsed values),
    or `ACQ_DECODABLE`."""
    rb = jnp.asarray(rate_bits, jnp.uint32) & 15
    mbps = jnp.asarray(_RB_TO_MBPS)[rb]
    dbps = jnp.asarray(_RB_TO_DBPS)[rb]
    avail = jnp.asarray(avail, jnp.int32)
    length_bytes = jnp.asarray(length_bytes, jnp.int32)
    known = (jnp.asarray(found, bool) & (avail >= 400)
             & jnp.asarray(parity_ok, bool) & (mbps > 0))
    n_bits = N_SERVICE_BITS + 8 * length_bytes + N_TAIL_BITS
    n_sym = (n_bits + dbps - 1) // jnp.maximum(dbps, 1)
    fits = avail >= FRAME_DATA_START + 80 * n_sym
    status = jnp.where(known,
                       jnp.where(fits, ACQ_DECODABLE, ACQ_TRUNCATED),
                       ACQ_FAIL)
    zero = jnp.zeros_like(mbps)
    return (jnp.asarray(status, jnp.int32),
            jnp.where(known, mbps, zero),
            jnp.where(known, length_bytes, zero),
            jnp.where(known, n_sym, zero))


def _acquire_frame(samples, max_samples: int = 1 << 16):
    """Detect/align/CFO-correct a capture and parse its SIGNAL field:
    the per-capture acquisition front of `receive` — and the single-
    lane oracle of the batched `acquire_many`. Returns (RxResult,
    None) on any failure, (None, _Acquired) on success."""
    from ziria_tpu.utils import dispatch, programs

    x, n_valid = _bucket_pad(
        np.asarray(samples, np.float32)[:max_samples])
    sync_fn = _jit_sync_fn()
    programs.note_site("rx.sync", sync_fn, x)
    with dispatch.timed("rx.sync"):
        found, start, eps = sync_fn(x)
    found = bool(np.asarray(found))
    start = int(np.asarray(start))
    eps = float(np.asarray(eps))
    avail = n_valid - start
    rate_bits = length_bytes = 0
    parity_ok = False
    if found and avail >= 400:
        # CFO-correct only fixed-size regions so device code caches:
        # the 400-sample head now, the (rate, n_sym)-sized data region
        # after the SIGNAL parse (both slices start at the frame
        # start, keeping the rotation phase-continuous)
        with dispatch.timed("rx.cfo_head"):
            head = sync.correct_cfo(jnp.asarray(x[start:start + 400]),
                                    eps)
        sig_fn = _jit_signal_fn()
        programs.note_site("rx.signal", sig_fn, head)
        with dispatch.timed("rx.signal"):
            rb, ln, pk = sig_fn(head)
        rate_bits = int(np.asarray(rb))
        length_bytes = int(np.asarray(ln))
        parity_ok = bool(np.asarray(pk))
    res, ok = _classify_acquire(found, avail, rate_bits, length_bytes,
                                parity_ok)
    if ok is None:
        return res, None
    rate_mbps, n_sym = ok
    return None, _Acquired(x[start:], avail, eps, rate_mbps,
                           length_bytes, n_sym)


def acquire_frame_graph(x, n_valid, limit):
    """Fully-traceable single-capture acquisition: STS detect, LTS
    peak-pick, coarse+fine CFO, on-device frame alignment
    (`lax.dynamic_slice` at the traced start), CFO rotation of the
    400-sample head, and the SIGNAL decode — fused into ONE graph.

    x: (L, 2) bucket-padded capture; n_valid: true capture length
    (traced int32); limit: the lane's OWN power-of-two bucket (traced
    int32) — caps detection/peak-pick positions so a lane padded past
    its own bucket to the batch's common one evaluates exactly the
    positions the per-capture path does (sync.locate_frame). Returns
    per-lane (found, start, eps, rate_bits, length, parity_ok) —
    `found` already folds in the >= 400-sample availability gate, so
    every downstream field of a not-found lane is garbage-by-
    construction and masked by the host decision tree. Under `vmap`
    this is the whole acquisition front end of a batch in one
    dispatch."""
    detected, start, eps = sync.locate_frame(x, limit=limit)
    avail = n_valid - start
    head = jax.lax.dynamic_slice(x, (start, jnp.int32(0)), (400, 2))
    head = sync.correct_cfo(head, eps)
    rate_bits, length, parity_ok = decode_signal(head)
    found = jnp.logical_and(detected, avail >= 400)
    return found, start, eps, rate_bits, length, parity_ok


@lru_cache(maxsize=None)
def _jit_acquire_many():
    """ONE jitted vmap of the acquisition graph serves every
    (lane count, bucket) geometry (jit retraces per shape)."""
    return jax.jit(jax.vmap(acquire_frame_graph))


class _LaneAcq(NamedTuple):
    """A decodable lane of a batched acquisition: everything the
    gather+decode dispatches need, as host integers/floats."""
    row: int                    # row in the padded capture batch
    start: int
    eps: float
    avail: int
    rate_mbps: int
    length_bytes: int
    n_sym: int


def acquire_batch(x_dev, n_valid, limits, n_lanes: int):
    """Batched acquisition over an ALREADY device-resident capture
    batch: ONE vmapped dispatch + the host integer decision tree.

    x_dev: (R, L, 2) device array, R a power-of-two lane count and L
    a power-of-two capture bucket, rows past the real lanes repeating
    row 0 (the `utils/dispatch.pad_lanes` rule); n_valid/limits: (R,)
    int arrays (true capture lengths and per-lane own-bucket caps for
    the detector). The first `n_lanes` rows are real. Returns
    (results, lanes) as `acquire_many` does. This is the entry the
    device-resident loopback link uses — the TX/channel output feeds
    acquisition without ever crossing the host link."""
    from ziria_tpu.utils import dispatch, programs

    acq_fn = _jit_acquire_many()
    acq_args = (x_dev, jnp.asarray(n_valid, jnp.int32),
                jnp.asarray(limits, jnp.int32))
    programs.note_site("rx.acquire_many", acq_fn, *acq_args)
    with dispatch.timed("rx.acquire_many"):
        found_b, start_b, eps_b, rb_b, ln_b, pk_b = acq_fn(*acq_args)
    found_b = np.asarray(found_b)
    start_b = np.asarray(start_b)
    eps_b = np.asarray(eps_b)
    rb_b = np.asarray(rb_b)
    ln_b = np.asarray(ln_b)
    pk_b = np.asarray(pk_b)
    n_valid = np.asarray(n_valid)

    results = [None] * n_lanes
    lanes = []
    for i in range(n_lanes):
        start = int(start_b[i])
        avail = int(n_valid[i]) - start
        res, ok = _classify_acquire(bool(found_b[i]), avail,
                                    int(rb_b[i]), int(ln_b[i]),
                                    bool(pk_b[i]))
        if ok is None:
            results[i] = res
            continue
        rate_mbps, n_sym = ok
        lanes.append((i, _LaneAcq(i, start, float(eps_b[i]), avail,
                                  rate_mbps, int(ln_b[i]), n_sym)))
    return results, lanes


def acquire_many(captures, max_samples: int = 1 << 16):
    """Batched acquisition front end: N captures -> per-lane
    (found, start, eps, rate_bits, length, parity_ok) in ONE device
    dispatch, then the host decision tree (integer parsing only).

    Returns (results, x_dev, lanes): `results[i]` is the failure
    RxResult for undecodable lanes and None for decodable ones,
    `x_dev` is the (N_pow2, L, 2) bucket-padded capture batch as the
    DEVICE array the acquire dispatch already uploaded (kept resident
    so the gather dispatch slices data regions without a second trip
    through the host link), `lanes` is [(i, _LaneAcq)] for the
    decodable lanes. Lane-for-lane, the classification and every
    parsed field are bit-identical to per-capture `_acquire_frame`."""
    from ziria_tpu.utils.dispatch import pow2_ceil

    if not len(captures):
        return [], jnp.zeros((0, 0, 2), jnp.float32), []
    xs = [np.asarray(s, np.float32)[:max_samples] for s in captures]
    n_valid = np.asarray([x.shape[0] for x in xs], np.int32)
    # ONE common bucket for the whole batch (zeros are inert to the
    # detector and to the conv outputs at real-sample positions, so a
    # longer pad does not change any lane's values), and lane counts
    # pad to a power of two (lane 0 repeated) so XLA compiles O(log N)
    # batch variants
    bucket = _stream_bucket(int(n_valid.max()))
    n_lanes = len(xs)
    n_rows = pow2_ceil(n_lanes)
    x_pad = np.zeros((n_rows, bucket, 2), np.float32)
    for i, x in enumerate(xs):
        x_pad[i, :x.shape[0]] = x
    if n_lanes < n_rows:
        x_pad[n_lanes:] = x_pad[0]
    nv_pad = np.full((n_rows,), n_valid[0], np.int32)
    nv_pad[:n_lanes] = n_valid
    # each lane's OWN bucket caps its detect/peak-pick positions so
    # sharing a longer common bucket cannot expose tail windows the
    # per-capture path never evaluates (sync.locate_frame's limit)
    limits = np.asarray([_stream_bucket(int(v)) for v in nv_pad],
                        np.int32)

    x_dev = jnp.asarray(x_pad)
    results, lanes = acquire_batch(x_dev, nv_pad, limits, n_lanes)
    return results, x_dev, lanes


def gather_segment_graph(x, start, eps, avail, n_sym_bucket: int):
    """One lane of the batched "gather+derotate" graph: slice the
    frame region at the lane's own (traced) start, zero everything
    past its true available samples, and apply its own CFO phase —
    the traced twin of `_padded_segment`, fused for the whole batch
    under vmap. `x` must be padded so start + need_b never clamps."""
    need_b = FRAME_DATA_START + 80 * n_sym_bucket
    seg = jax.lax.dynamic_slice(x, (start, jnp.int32(0)), (need_b, 2))
    n = jnp.minimum(avail, need_b)
    seg = jnp.where((jnp.arange(need_b) < n)[:, None], seg, 0.0)
    return sync.correct_cfo(seg, eps)


@lru_cache(maxsize=None)
def _jit_gather_segments(n_sym_bucket: int):
    """ONE jitted gather per symbol bucket (shapes retrace per
    (lane count, capture bucket) pair). The row gather and the tail
    pad both happen INSIDE the jit, on the device-resident capture
    batch the acquire dispatch uploaded — the batch never crosses the
    host link a second time."""
    need_b = FRAME_DATA_START + 80 * n_sym_bucket

    def f(x_all, rows, start, eps, avail):
        # tail-pad so start + need_b is always in bounds:
        # dynamic_slice clamps out-of-range starts, which would
        # silently shift a lane
        x = jnp.pad(x_all[rows], ((0, 0), (0, need_b), (0, 0)))
        return jax.vmap(
            lambda xi, s, e, a: gather_segment_graph(
                xi, s, e, a, n_sym_bucket))(x, start, eps, avail)

    return jax.jit(f)


def gather_segments_many(x_dev, lanes, n_sym_bucket: int):
    """Slice every decodable lane's data region at its own offset and
    apply its own CFO rotation at the common symbol bucket — ONE
    device dispatch over the device-resident capture batch from
    `acquire_many`; output stays on device for the mixed-rate decode.
    `lanes` rows must already be padded to the target lane count
    (repeat the first entry, like every batch path here)."""
    from ziria_tpu.utils import dispatch, programs

    gather_fn = _jit_gather_segments(n_sym_bucket)
    gather_args = (
        x_dev,
        jnp.asarray([la.row for la in lanes], jnp.int32),
        jnp.asarray([la.start for la in lanes], jnp.int32),
        jnp.asarray([la.eps for la in lanes], jnp.float32),
        jnp.asarray([la.avail for la in lanes], jnp.int32))
    programs.note_site("rx.gather", gather_fn, *gather_args)
    with dispatch.timed("rx.gather"):
        return gather_fn(*gather_args)


def _padded_segment(acq: _Acquired, n_sym_bucket: int):
    """The acquired frame's data region padded to `n_sym_bucket`
    symbols and CFO-corrected: the fixed-geometry device input of the
    bucketed and mixed-rate DATA decodes. Per-lane host path — the
    batched `gather_segments_many` produces the identical values for
    a whole batch in one dispatch."""
    from ziria_tpu.utils import dispatch

    need_b = FRAME_DATA_START + 80 * n_sym_bucket
    frame_pad = np.zeros((need_b, 2), np.float32)
    n = min(acq.avail, need_b)
    frame_pad[:n] = acq.frame_np[:n]
    with dispatch.timed("rx.cfo_segment"):
        return sync.correct_cfo(jnp.asarray(frame_pad), acq.eps)


# ------------------------------------------------------ streaming receiver
#
# The per-chunk device half of `backend/framebatch.receive_stream`:
# ONE jitted graph turns a long multi-frame chunk into K dense
# candidate lanes — multi-peak detect (`ops/sync.locate_frames`),
# per-candidate window extraction at the traced aligned starts, the
# vmapped per-window acquisition (`acquire_frame_graph`, the SAME
# graph the batched per-capture path runs, so every window decodes
# bit-identically to `receive` over that window), and the
# gather+derotate at ONE fixed symbol bucket. A second fixed-geometry
# jit decodes the chunk's decodable lanes (mixed-rate switch + masked
# CRC). Between the two sits only the integer `_classify_acquire`
# tree — the blind receive's genuinely data-dependent step.


def _stream_bucket_graph(n_valid, cap: int):
    """Traced twin of `_stream_bucket` (power-of-two capture bucket,
    floor 512) for per-lane true sample counts up to the static window
    length `cap` — the streaming windows share one common buffer, so
    each lane's detector cap must be ITS OWN bucket for bit-identity
    with per-capture `receive` (the `acquire_many` limit rule). The
    unrolled compare ladder is exact where float log2 would not be;
    `tests/test_rx_stream.py` pins it against the host rule."""
    b = jnp.full(jnp.shape(n_valid), 512, jnp.int32)
    m = 512
    while m < cap:
        m *= 2
        b = jnp.where(jnp.asarray(n_valid) > m // 2, m, b)
    return b


def stream_chunk_graph(chunk, chunk_valid, own_lo, own_hi, k: int,
                       win_len: int, n_sym_bucket: int,
                       threshold: float = 0.75, min_run: int = 33,
                       dead_zone: int = 320):
    """One streaming chunk, fully traced (dispatch 1 of 2 per chunk):

    1. `sync.locate_frames`: up to `k` exact frame starts (plateau
       gate, dead-zone suppression, local LTS alignment) over the
       chunk's `chunk_valid` real samples.
    2. ownership mask: only starts in ``[own_lo, own_hi)`` are this
       chunk's (`own_hi` = the chunk stride, or the valid length on
       the final chunk; `own_lo` = 0 except on the STREAM's first
       chunk, where -192 admits a head-truncated preamble whose LTS
       peak-pick lands below the 192-sample offset — per-capture
       `locate_frame` clamps such a start to 0 and still reports,
       and so must we; on later chunks a negative start is a frame
       owned by the PREVIOUS chunk). Boundary-straddling frames
       re-detect fully inside the NEXT chunk's overlap and are owned
       exactly once.
    3. per-candidate `win_len`-sample window extraction at the traced
       starts, clamped to 0 exactly as `locate_frame` clamps
       (`dynamic_slice` — the window IS the capture the per-capture
       oracle would see for `stream[max(start,0) : +win_len]`).
    4. the vmapped per-window acquisition (detect gate, LTS timing,
       CFO, SIGNAL decode) with per-lane true counts and own-bucket
       detector caps, and
    5. gather+derotate of every window's data region at the ONE static
       symbol bucket (garbage on failed lanes, masked host-side).

    Returns ``(own, starts, overflow, found, fstart, eps, rate_bits,
    length, parity_ok, n_valid, segs)`` — everything before `segs` is
    K scalars per lane (one host transfer; `starts` already clamped),
    `segs` stays device-resident for the decode dispatch."""
    # overflow scan cap: the scan sees plateau CROSSING indices, and a
    # frame aligned at start s can cross as late as s + 224 (the
    # alignment window spans [d-32, d+384) and start = peak - 192, so
    # s >= d - 224). Capping at own_hi + 224 therefore counts every
    # surplus frame THIS chunk owns (never a silent drop), at the cost
    # of flagging deferred frames in a 224-sample sliver past the
    # bound — the conservative side for a widen-K diagnostic.
    found, starts, overflow = sync.locate_frames(
        chunk, k, limit=chunk_valid, threshold=threshold,
        min_run=min_run, dead_zone=dead_zone,
        overflow_limit=own_hi + 224)
    own = found & (starts >= own_lo) & (starts < own_hi)
    starts = jnp.where(own, jnp.maximum(starts, 0), starts)
    # tail-pad before slicing: a final-chunk start may sit within
    # win_len of the chunk end (the stream genuinely ends there, so
    # the window's zero tail is exactly the oracle slice's bucket
    # pad); clamping the slice instead would silently shift the lane
    safe = jnp.clip(starts, 0, chunk.shape[0])
    chunk_pad = jnp.pad(chunk, ((0, win_len), (0, 0)))
    wins = jax.vmap(lambda s: jax.lax.dynamic_slice(
        chunk_pad, (s, jnp.int32(0)), (win_len, 2)))(safe)
    nv = jnp.clip(jnp.asarray(chunk_valid, jnp.int32) - safe,
                  0, win_len).astype(jnp.int32)
    lim = _stream_bucket_graph(nv, win_len)
    f2, fstart, eps, rb, ln, pk = jax.vmap(acquire_frame_graph)(
        wins, nv, lim)
    need_b = FRAME_DATA_START + 80 * n_sym_bucket
    wins_pad = jnp.pad(wins, ((0, 0), (0, need_b), (0, 0)))
    segs = jax.vmap(lambda xi, s, e, a: gather_segment_graph(
        xi, s, e, a, n_sym_bucket))(wins_pad, fstart, eps, nv - fstart)
    return own, starts, overflow, f2, fstart, eps, rb, ln, pk, nv, segs


@lru_cache(maxsize=None)
def _jit_stream_chunk(k: int, win_len: int, n_sym_bucket: int,
                      threshold: float = 0.75, min_run: int = 33,
                      dead_zone: int = 320):
    """ONE compiled chunk scan per (K, window, symbol bucket, detector
    params) — chunk length retraces per shape; a stream of uniform
    chunks compiles ONCE and every chunk is a re-dispatch."""
    def f(chunk, chunk_valid, own_lo, own_hi):
        return stream_chunk_graph(chunk, chunk_valid, own_lo, own_hi,
                                  k, win_len, n_sym_bucket, threshold,
                                  min_run, dead_zone)
    return jax.jit(f)


@lru_cache(maxsize=None)
def _jit_stream_decode(n_sym_bucket: int, viterbi_window: int = None,
                       viterbi_metric: str = None,
                       viterbi_radix: int = None,
                       sco_track: bool = False,
                       fused_demap: bool = False):
    """Dispatch 2 of the streaming chunk: row-select the decodable
    lanes INSIDE the jit (the segment batch never re-crosses the host
    link), the one-`lax.switch` mixed-rate decode at the stream's
    fixed symbol bucket, and the vmapped masked-CRC check. The CRC
    flags are always computed (noise next to the Viterbi), so one
    compile serves both `check_fcs` modes — the fused-link rule. The
    decode-mode knobs are cache keys (resolved radix/fused values,
    like every jit factory here); ``fused_demap`` is LAST so the R1
    lint demo can AST-drop it by position."""
    def f(segs, rows, ridx, nbits, npsdu):
        clear = decode_data_mixed(segs[rows], ridx, nbits, n_sym_bucket,
                                  viterbi_window, viterbi_metric,
                                  viterbi_radix, sco_track=sco_track,
                                  fused_demap=fused_demap)
        return clear, crc_psdu_many_graph(clear, npsdu)
    return jax.jit(f)


# --------------------------------------------------- multi-stream fleet
#
# The S-stream twins of the two streaming programs: S independent I/Q
# streams' chunks ride a LEADING STREAM AXIS through the same per-lane
# graphs (`stream_chunk_graph` under one more vmap; the mixed decode
# over the flattened (S*K) lane axis), so an entire fleet of streams
# still runs on TWO compiled programs and <= 2 dispatches per
# chunk-step — Ziria's `|>>>|` stage placement re-expressed as a mesh
# axis. With a `mesh`, both programs wrap in `shard_map` (via the
# utils/compat shim) over the dp stream axis: an identical per-device
# program per shard of streams, no collectives (streams are
# independent), multihost-ready through parallel/multihost.build_mesh.


def multi_stream_chunk_graph(chunks, valid, own_lo, own_hi, k: int,
                             win_len: int, n_sym_bucket: int,
                             threshold: float = 0.75, min_run: int = 33,
                             dead_zone: int = 320):
    """The stream-axis twin of `stream_chunk_graph`: `chunks`
    (S, chunk_len, 2) stacked per-stream windows, `valid`/`own_lo`/
    `own_hi` (S,) per-stream scalars (an idle lane rides `valid == 0`
    — the detector's position cap masks it to zero candidates, the
    valid-mask of the host packer). Per lane, values are the SINGLE-
    stream graph's values by construction — the vmap adds the stream
    axis, nothing else — which is what makes the fleet bit-identical
    to S separate receivers."""
    return jax.vmap(
        lambda c, v, lo, hi: stream_chunk_graph(
            c, v, lo, hi, k, win_len, n_sym_bucket, threshold,
            min_run, dead_zone))(chunks, valid, own_lo, own_hi)


@lru_cache(maxsize=None)
def _jit_stream_chunk_multi(k: int, win_len: int, n_sym_bucket: int,
                            threshold: float = 0.75, min_run: int = 33,
                            dead_zone: int = 320, mesh=None,
                            axis: str = "dp"):
    """ONE compiled S-stream chunk scan per (K, window, symbol bucket,
    detector params, mesh) — stream count and chunk length retrace per
    shape, so a fleet of uniform chunk-steps compiles ONCE. With a
    `mesh`, the graph wraps in shard_map over the leading stream axis
    (`parallel/batch.stream_specs` placement, compat shim): each
    device runs the identical per-shard program over its S/n streams.
    `mesh` is part of the lru key (a Mesh hashes by device layout), so
    sharded and unsharded fleets never share a trace."""
    def f(chunks, valid, own_lo, own_hi):
        return multi_stream_chunk_graph(chunks, valid, own_lo, own_hi,
                                        k, win_len, n_sym_bucket,
                                        threshold, min_run, dead_zone)

    if mesh is None:
        return jax.jit(f)
    from ziria_tpu.parallel.batch import stream_specs
    from ziria_tpu.utils.compat import shard_map
    # outputs: own/starts (S,K), overflow (S,), 7x per-lane (S,K)
    # scalars, segs (S,K,need_b,2) — every one leads with the stream
    # axis, so the specs are rank-driven
    return jax.jit(shard_map(
        f, mesh=mesh, in_specs=stream_specs((3, 1, 1, 1), axis),
        out_specs=stream_specs((2, 2, 1) + (2,) * 7 + (4,), axis)))


@lru_cache(maxsize=None)
def _jit_stream_decode_multi(n_sym_bucket: int, viterbi_window: int = None,
                             viterbi_metric: str = None,
                             viterbi_radix: int = None, mesh=None,
                             axis: str = "dp",
                             sco_track: bool = False,
                             fused_demap: bool = False):
    """Dispatch 2 of the multi-stream chunk-step: per-stream row-
    select of the decodable lanes (all inside the jit, over the still
    device-resident (S, K, ...) segment batch), then the (S*K)-lane
    FLATTENED mixed-rate decode + masked CRC — one rate-agnostic
    Pallas Viterbi batch for the whole fleet, every lane riding the
    same 128-lane tiles (lane values are batch-independent, the
    pinned receive_many contract, so each lane is bit-identical to
    its single-stream K-lane decode). Decode-mode knobs (including
    the resolved ``fused_demap``, LAST for the R1 lint demo) and the
    mesh are cache keys, as in every jit factory here."""
    def f(segs, rows, ridx, nbits, npsdu):
        sel = jax.vmap(lambda sg, r: sg[r])(segs, rows)
        s, kk = rows.shape
        clear = decode_data_mixed(
            sel.reshape((s * kk,) + sel.shape[2:]), ridx.reshape(-1),
            nbits.reshape(-1), n_sym_bucket, viterbi_window,
            viterbi_metric, viterbi_radix, sco_track=sco_track,
            fused_demap=fused_demap)
        crc = crc_psdu_many_graph(clear, npsdu.reshape(-1))
        return (clear.reshape(s, kk, -1), crc.reshape(s, kk))

    if mesh is None:
        return jax.jit(f)
    from ziria_tpu.parallel.batch import stream_specs
    from ziria_tpu.utils.compat import shard_map
    # check_vma=False (compat: check_rep on this image's jax): the
    # Pallas ACS inside the decode has no replication rule; nothing
    # here is replicated anyway — every operand leads with the
    # sharded stream axis
    return jax.jit(shard_map(
        f, mesh=mesh, in_specs=stream_specs((4, 2, 2, 2, 2), axis),
        out_specs=stream_specs((3, 2), axis), check_vma=False))


def receive(samples, check_fcs: bool = False,
            max_samples: int = 1 << 16, fxp: bool = False,
            viterbi_window: int = None,
            viterbi_metric: str = None,
            viterbi_radix: int = None,
            fused_demap: bool = None,
            sco_track: bool = None,
            geometry=None) -> RxResult:
    """Host-side receiver driver: detect, align, CFO-correct, parse
    SIGNAL, dispatch the per-rate decoder — the jit analogue of the
    reference's header-driven rate dispatch. The data decode compiles
    once per (rate, power-of-two symbol bucket) with the true bit count
    traced (see decode_data_bucketed), so varied traffic stays within
    O(rates x log lengths) compiles.

    fxp=True routes the DATA decode through the Q15 integer interior
    (phy/wifi/rx_fxp.py — the reference's fixed-point discipline):
    acquisition and SIGNAL stay f32; the aligned data region is
    AGC-normalized by the preamble RMS and quantized to Q11 at the
    fixed-point boundary, after which every decode op is exact integer
    arithmetic (bit-identical across backends for identical quantized
    input).

    viterbi_window opts the (float) DATA decode into the sliding-
    window parallel Viterbi — same result at operating SNR, ~T/window
    less sequential trellis depth on the chip; viterbi_metric="int16"
    opts it into the quantized saturating-metric kernel and "int8"
    into the int8+LUT kernel below it; viterbi_radix=4 runs two
    trellis steps per ACS iteration and fused_demap=True moves the
    demap/deinterleave/depuncture front end into the decode kernel
    (all ignored under fxp, whose decode keeps the exact scan).

    sco_track=True (--rx-sco-track / ZIRIA_RX_SCO_TRACK) adds the
    pilot phase-RAMP tracking for sampling-clock-offset channels
    (docs/robustness.md; default off — the flat-path decode is
    pinned bit-identical and a fitted slope is never exactly zero);
    the bounded-|H| null-subcarrier guard is always on and value-
    inert on flat channels. Both ignored under fxp.

    ``geometry`` (a utils/geometry.Geometry) supplies the default for
    every decode-mode knob the caller leaves None — one declarative
    object instead of five threaded parameters; explicit per-knob
    arguments still win. The default Geometry reproduces the legacy
    env-resolution path exactly (same compiled programs, same bits).
    """
    if geometry is not None:
        viterbi_window = (geometry.viterbi_window
                          if viterbi_window is None else viterbi_window)
        viterbi_metric = (geometry.viterbi_metric
                          if viterbi_metric is None else viterbi_metric)
        viterbi_radix = (geometry.viterbi_radix
                         if viterbi_radix is None else viterbi_radix)
        fused_demap = (geometry.fused_demap
                       if fused_demap is None else fused_demap)
        sco_track = (geometry.sco_track
                     if sco_track is None else sco_track)
    res, acq = _acquire_frame(samples, max_samples)
    if acq is None:
        return res
    rate = RATES[acq.rate_mbps]

    # bucketed dispatch: pad the frame to a power-of-two symbol count so
    # the decode jit-caches O(rates x log lengths), not once per PSDU
    # length; the true bit count flows in as a traced scalar
    n_sym_b = _sym_bucket(acq.n_sym)
    seg = _padded_segment(acq, n_sym_b)
    if fxp:
        from ziria_tpu.phy.wifi import rx_fxp
        # AGC at the fixed-point boundary: unit average power over the
        # real preamble (numpy host math — stable for a given capture)
        rms = float(np.sqrt(np.mean(acq.frame_np[:320].astype(np.float64)
                                    ** 2) * 2.0))
        seg = rx_fxp.quantize_frame(np.asarray(seg) / max(rms, 1e-12))
    dec = _jit_decode_data_bucketed(
        acq.rate_mbps, n_sym_b, fxp,
        None if fxp else viterbi_window,
        None if fxp else viterbi_metric,
        None if fxp else viterbi._check_radix(viterbi_radix),
        False if fxp else sco_track_enabled(sco_track),
        None if fxp else fused_demap_enabled(fused_demap))
    from ziria_tpu.utils import dispatch, programs
    programs.note_site("rx.decode_bucketed", dec, seg,
                       jnp.int32(acq.n_sym * rate.n_dbps))
    # the host pull stays OUTSIDE the timed block: the site times the
    # dispatch, not the device wait (jaxlint R2 — docs/static_analysis.md)
    with dispatch.timed("rx.decode_bucketed"):
        clear_dev = dec(seg, jnp.int32(acq.n_sym * rate.n_dbps))
    clear = np.asarray(clear_dev, np.uint8)
    psdu = clear[N_SERVICE_BITS: N_SERVICE_BITS + 8 * acq.length_bytes]
    crc = bool(np.asarray(check_crc32(psdu))) if check_fcs else None
    return RxResult(True, acq.rate_mbps, acq.length_bytes, psdu, crc)
