"""802.11a/g OFDM receiver chain.

Counterpart of the reference's `code/WiFi/receiver/` top-level `rx.blk`
(SURVEY.md §2.3, §3.4): packet detect (STS autocorr) ; CFO est/correct ;
channel est (LTS) ; PLCP header parse ; then per-rate FFT >>> pilot
tracking >>> soft demap >>> deinterleave >>> Viterbi >>> descramble >>>
CRC.

TPU-first structure: the steady-state DATA decode is one traced graph
over ALL symbols of a frame at once — (n_sym, 64) matmul-FFTs, batched
pilot tracking, one Viterbi scan — and batches over frames with vmap.
The data-dependent part (header-derived rate/length — the motivating
example for the reference's computers-returning-values, §3.4) is a
two-phase dispatch: decode SIGNAL (fixed shape), then select the
per-rate compiled decoder — the jit analogue of `parsePLCPHeader ;
per-rate loop`. ``receive()`` drives the whole thing host-side;
``decode_data_static`` is the fully-jitted flagship used by the bench.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ziria_tpu.ops import cplx, coding, demap as demap_mod, interleave, ofdm, \
    scramble, sync, viterbi, viterbi_pallas
from ziria_tpu.ops.crc import check_crc32
from ziria_tpu.phy.wifi.params import (N_SERVICE_BITS, N_TAIL_BITS,
                                       RateParams, RATES,
                                       SIGNAL_BITS_TO_MBPS, n_symbols)
from ziria_tpu.utils.bits import bits_to_uint

FRAME_DATA_START = 400  # 320 preamble + 80 SIGNAL


def equalize(bins, H):
    """Zero-forcing equalization of (..., 64, 2) bins by H (64, 2)."""
    return cplx.cdiv(bins, jnp.broadcast_to(H, bins.shape))


def pilot_phase_correct(data, pilots, symbol_index0: int):
    """Common-phase derotation per symbol from the 4 pilots.

    data (..., n_sym, 48, 2), pilots (..., n_sym, 4, 2); pilot polarity
    index starts at symbol_index0."""
    n_sym = data.shape[-3]
    pol = jnp.asarray(ofdm.PILOT_POLARITY, jnp.float32)[
        (jnp.arange(n_sym) + symbol_index0) % 127]
    expect_re = jnp.asarray(ofdm.PILOT_VALS, jnp.float32)[None, :] * \
        pol[:, None]                                   # (n_sym, 4)
    # phase of sum_k pilots_k * expected_k (expected is real)
    weighted = pilots * expect_re[..., :, None]
    ph = jnp.arctan2(weighted[..., 1].sum(-1), weighted[..., 0].sum(-1))
    derot = cplx.cexp(-ph)                             # (..., n_sym, 2)
    return cplx.cmul(data, derot[..., None, :])


def decode_signal(frame):
    """Decode the SIGNAL symbol of an aligned, CFO-corrected frame.

    Returns (rate_bits_uint (traced), length (traced), parity_ok
    (traced)). Fixed shapes — jits once."""
    H = sync.estimate_channel(frame)
    bins = ofdm.ofdm_demodulate(frame[320:400][None])  # (1, 64, 2)
    eq = equalize(bins, H)
    data, pilots = ofdm.extract_subcarriers(eq)
    data = pilot_phase_correct(data, pilots, symbol_index0=0)
    gain = cplx.cabs2(H)[jnp.asarray(ofdm.DATA_BINS)]
    llr = demap_mod.demap(data, 1, gain=gain[None])[0]
    deint = interleave.deinterleave(llr, 48, 1)
    bits = viterbi.viterbi_decode(deint, n_bits=24)
    rate_bits = bits_to_uint(bits[0:4], msb_first=True)
    length = bits_to_uint(bits[5:17])
    parity_ok = (bits[:18].astype(jnp.uint32).sum() % 2) == 0
    return rate_bits, length, parity_ok


def _decode_front(frame, rate: RateParams, n_sym: int):
    """Aligned frame -> depunctured soft LLR pairs (T, 2): channel est +
    (n_sym x 64) matmul-FFT + equalize + pilot track + demap +
    deinterleave + depuncture — everything before the Viterbi."""
    H = sync.estimate_channel(frame)
    syms = frame[FRAME_DATA_START: FRAME_DATA_START + 80 * n_sym]
    bins = ofdm.ofdm_demodulate(syms.reshape(n_sym, 80, 2))
    eq = equalize(bins, H)
    data, pilots = ofdm.extract_subcarriers(eq)
    data = pilot_phase_correct(data, pilots, symbol_index0=1)
    gain = cplx.cabs2(H)[jnp.asarray(ofdm.DATA_BINS)]
    llrs = demap_mod.demap(data, rate.n_bpsc,
                           gain=jnp.broadcast_to(gain, data.shape[:-1]))
    deint = interleave.deinterleave(
        llrs.reshape(-1), rate.n_cbps, rate.n_bpsc)
    return coding.depuncture(deint, rate.coding, fill=0.0).reshape(-1, 2)


def _decode_back(bits, n_psdu_bits: int):
    """Decoded bits -> (psdu_bits, descrambled service bits)."""
    seed = scramble.recover_seed(bits[:7])
    clear = scramble.descramble_bits(bits, seed)
    psdu = clear[N_SERVICE_BITS: N_SERVICE_BITS + n_psdu_bits]
    return psdu, clear[:N_SERVICE_BITS]


def decode_data_static(frame, rate: RateParams, n_sym: int,
                       n_psdu_bits: int):
    """Fully-jitted DATA decode for a known rate/symbol count: aligned
    CFO-corrected frame -> (psdu_bits, descrambled service bits).

    The flagship fused graph: channel est + (n_sym x 64) matmul-FFT +
    equalize + pilot track + demap + deinterleave + depuncture + Viterbi
    + descramble in one jit."""
    depunct = _decode_front(frame, rate, n_sym)
    bits = viterbi.viterbi_decode(depunct, n_bits=n_sym * rate.n_dbps)
    return _decode_back(bits, n_psdu_bits)


def decode_data_batch(frames, rate: RateParams, n_sym: int,
                      n_psdu_bits: int, interpret: bool = None,
                      viterbi_window: int = None,
                      viterbi_metric: str = None):
    """Batched DATA decode: (B, frame_len, 2) -> ((B, n_psdu_bits),
    (B, 16)).

    The TPU fast path: the per-frame front end (FFT/equalize/demap/...)
    runs under vmap, then the whole batch hits the Pallas Viterbi kernel
    with frames laid out across the 128 VPU lanes (~8x the vmapped
    lax.scan ACS; see ops/viterbi_pallas.py).

    ``viterbi_window`` opts into the sliding-window PARALLEL Viterbi
    (viterbi_decode_batch_windowed): the ~8k-step sequential trellis is
    cut into overlapping windows decoded as extra batch lanes — the
    standard truncated-traceback trade every production decoder
    (including the reference's SORA brick) makes, bit-identical to the
    exact decode at operating SNR (tests/test_viterbi_windowed.py).

    ``viterbi_metric="int16"`` opts into the quantized saturating-
    metric kernel (the SORA int16 discipline; docs/quantized_viterbi.md
    — the other half of the device-residency trade)."""
    dep = jax.vmap(lambda f: _decode_front(f, rate, n_sym))(frames)
    bits = viterbi_pallas.viterbi_decode_batch_opt(
        dep, n_bits=n_sym * rate.n_dbps, window=viterbi_window,
        interpret=interpret, metric_dtype=viterbi_metric)
    return jax.vmap(lambda b: _decode_back(b, n_psdu_bits))(bits)


def sync_frame(samples):
    """Locate and align a frame in a sample stream: STS detection gate,
    LTS cross-correlation timing, coarse+fine CFO. Returns
    (found, frame_start_index, cfo_estimate). Fixed shapes -> jits."""
    x = jnp.asarray(samples, jnp.float32)
    detected, coarse_start = sync.detect_packet(x)

    # LTS timing: cross-correlate with the known long symbol; the two
    # LTS peaks are 64 apart; first LTS starts at frame_start + 192
    lts = jnp.asarray(ofdm.lts_time_symbol())           # (64, 2)
    n = x.shape[0]

    def xcorr(sig):
        # correlation of sig against lts at all lags (valid region)
        ref = cplx.conj(lts)[::-1]                      # reversed conj

        def conv1(u, v):
            return jnp.convolve(u, v, precision="highest")

        re = conv1(sig[:, 0], ref[:, 0]) - conv1(sig[:, 1], ref[:, 1])
        im = conv1(sig[:, 0], ref[:, 1]) + conv1(sig[:, 1], ref[:, 0])
        # full conv index 63+k = correlation at lag k
        return (re[63:n] ** 2 + im[63:n] ** 2)

    c = xcorr(x)                                        # (n-63,)
    pair = c[:-64] + c[64:]                             # two-peak sum
    lts1 = jnp.argmax(pair).astype(jnp.int32)
    frame_start = jnp.maximum(lts1 - 192, 0)

    # CFO from the aligned preamble: coarse (lag-16 STS, wide range) then
    # fine (lag-64 LTS, 4x resolution) on the coarse-corrected head
    frame_head = jax.lax.dynamic_slice(x, (frame_start, 0), (320, 2))
    eps_c = sync.estimate_cfo_sts(frame_head)
    head2 = sync.correct_cfo(frame_head, eps_c)
    eps_f = sync.estimate_cfo_lts(head2)
    return detected, frame_start, eps_c + eps_f


class RxResult(NamedTuple):
    ok: bool
    rate_mbps: int
    length_bytes: int
    psdu_bits: np.ndarray
    crc_ok: Optional[bool]


def decode_data_bucketed(frame, rate: RateParams, n_sym_bucket: int,
                         n_bits_real, viterbi_window: int = None,
                         viterbi_metric: str = None):
    """DATA decode over a *bucketed* symbol count: `frame` is padded to
    FRAME_DATA_START + 80*n_sym_bucket samples, `n_bits_real` is the
    true data-bit count as a TRACED scalar. Returns the full descrambled
    bit stream (n_sym_bucket * n_dbps); the caller slices the PSDU.

    This is what makes `receive()` streaming-grade (VERDICT r1 weak #3):
    one compile per (rate, power-of-two bucket) instead of one per PSDU
    length. LLR rows at or beyond `n_bits_real` are zeroed — true
    erasures — so the pad region adds no likelihood and the Viterbi path
    over the real prefix is exactly the unpadded ML path (the tail bits
    still steer it into state 0 before the pad)."""
    depunct = _decode_front(frame, rate, n_sym_bucket)   # (T_b, 2)
    t = jnp.arange(depunct.shape[0])
    depunct = jnp.where((t < n_bits_real)[:, None], depunct, 0.0)
    if viterbi_window:
        # the windowed PARALLEL decoder: this single frame's windows
        # become a small batch through the Pallas kernel, cutting the
        # sequential trellis depth ~T/window-fold (see
        # ops/viterbi_pallas.viterbi_decode_batch_windowed)
        bits = viterbi_pallas.viterbi_decode_batch_windowed(
            depunct[None], n_bits=n_sym_bucket * rate.n_dbps,
            window=viterbi_window, metric_dtype=viterbi_metric)[0]
    else:
        bits = viterbi.viterbi_decode(
            depunct, n_bits=n_sym_bucket * rate.n_dbps,
            metric_dtype=viterbi_metric)
    seed = scramble.recover_seed(bits[:7])
    return scramble.descramble_bits(bits, seed)


@lru_cache(maxsize=None)
def _jit_decode_data_bucketed(rate_mbps: int, n_sym_bucket: int,
                              fxp: bool = False,
                              viterbi_window: int = None,
                              viterbi_metric: str = None):
    rate = RATES[rate_mbps]

    if fxp:
        from ziria_tpu.phy.wifi import rx_fxp

        def f(frame_q, n_bits_real):
            return rx_fxp.decode_data_bucketed_fxp(
                frame_q, rate, n_sym_bucket, n_bits_real)
    else:
        def f(frame, n_bits_real):
            return decode_data_bucketed(frame, rate, n_sym_bucket,
                                        n_bits_real, viterbi_window,
                                        viterbi_metric)

    return jax.jit(f)


def _sym_bucket(n_sym: int) -> int:
    """Power-of-two symbol bucket (min 4 keeps tiny frames in one
    compile class)."""
    return 1 << max(2, (n_sym - 1).bit_length())


# ------------------------------------------------------- mixed-rate dispatch

MAX_DBPS = max(p.n_dbps for p in RATES.values())     # 216 (54 Mbps)
RATE_MBPS_ORDER = tuple(sorted(RATES))               # lax.switch branch order
RATE_INDEX = {m: i for i, m in enumerate(RATE_MBPS_ORDER)}


def decode_data_mixed(frames, rate_idx, n_bits_real, n_sym_bucket: int,
                      viterbi_window: int = None,
                      viterbi_metric: str = None,
                      interpret: bool = None):
    """Mixed-rate batched DATA decode in ONE device dispatch — the
    compiled-program analogue of Ziria's in-language rate dispatch
    (the reference's `parsePLCPHeader ; per-rate loop` runs INSIDE the
    compiled receiver; SURVEY.md §3.4, §7 step 6).

    frames: (B, FRAME_DATA_START + 80*n_sym_bucket, 2) aligned,
    CFO-corrected frames padded to ONE common symbol bucket;
    rate_idx: (B,) int32 indices into RATE_MBPS_ORDER (traced);
    n_bits_real: (B,) int32 true data-bit counts (traced).
    Returns (B, n_sym_bucket * MAX_DBPS) descrambled bit streams; the
    caller slices each lane's PSDU.

    Geometry trick that makes one `lax.switch` serve all 8 rates: each
    per-rate branch runs only the CHEAP front end (FFT/equalize/demap/
    deinterleave/depuncture) at its own rate and pads the depunctured
    LLRs to the bucket's maximal trellis (n_sym_bucket * MAX_DBPS)
    with zero-LLR erasures — the same "adds no likelihood" argument as
    the symbol-bucket padding, so the surviving path over each lane's
    real prefix is exactly its unpadded ML path. The EXPENSIVE Viterbi
    then runs once, rate-agnostic, over the whole mixed batch through
    the Pallas kernel with every lane riding the same 128-lane tiles —
    mixed traffic no longer fragments the hot kernel's batch. Under
    vmap the switch lowers to a select over the (cheap) front-end
    branches; the per-lane trellis work is never duplicated.

    vs the host-side bucketed path (`receive`): compile count for the
    DATA stage drops from O(rates x log lengths) to O(log lengths),
    and a mixed-rate batch costs ONE device call instead of one per
    rate group.
    """
    t_max = n_sym_bucket * MAX_DBPS

    def _branch(rate):
        def f(frame):
            dep = _decode_front(frame, rate, n_sym_bucket)
            return jnp.pad(dep, ((0, t_max - dep.shape[0]), (0, 0)))
        return f

    branches = [_branch(RATES[m]) for m in RATE_MBPS_ORDER]
    rate_idx = jnp.asarray(rate_idx, jnp.int32)
    n_bits_real = jnp.asarray(n_bits_real, jnp.int32)
    dep = jax.vmap(
        lambda f, r: jax.lax.switch(r, branches, f))(frames, rate_idx)
    # rows at/after each lane's true bit count become erasures (covers
    # both the in-rate bucket pad and the cross-rate pad to MAX_DBPS)
    t = jnp.arange(t_max)
    dep = jnp.where((t[None, :] < n_bits_real[:, None])[..., None],
                    dep, 0.0)
    bits = viterbi_pallas.viterbi_decode_batch_opt(
        dep, window=viterbi_window, metric_dtype=viterbi_metric,
        interpret=interpret)

    def _descramble(b):
        seed = scramble.recover_seed(b[:7])
        return scramble.descramble_bits(b, seed)

    return jax.vmap(_descramble)(bits)


@lru_cache(maxsize=None)
def _jit_decode_data_mixed(n_sym_bucket: int, viterbi_window: int = None,
                           viterbi_metric: str = None):
    """ONE jit per (symbol bucket, decode mode) serving ALL rates —
    the decode-mode knobs are part of the cache key, so an in-process
    change can never silently reuse the other mode's trace (ADVICE r5
    #1 discipline)."""
    def f(frames, rate_idx, n_bits_real):
        return decode_data_mixed(frames, rate_idx, n_bits_real,
                                 n_sym_bucket, viterbi_window,
                                 viterbi_metric)
    return jax.jit(f)


_jit_sync = None
_jit_signal = None


class _Acquired(NamedTuple):
    """A detected, SIGNAL-parsed capture, ready for a DATA decode."""
    frame_np: np.ndarray        # samples from the frame start (f32)
    avail: int                  # true capture samples past the start
    eps: float                  # CFO estimate
    rate_mbps: int
    length_bytes: int
    n_sym: int


def _acquire_frame(samples, max_samples: int = 1 << 16):
    """Detect/align/CFO-correct a capture and parse its SIGNAL field:
    the shared acquisition front of `receive` and the frame-batched
    `backend.framebatch.receive_many`. Returns (RxResult, None) on any
    failure, (None, _Acquired) on success."""
    global _jit_sync, _jit_signal
    if _jit_sync is None:
        _jit_sync = jax.jit(sync_frame)
        _jit_signal = jax.jit(
            lambda fr: decode_signal(fr))

    fail = RxResult(False, 0, 0, np.zeros(0, np.uint8), None)
    x = np.asarray(samples, np.float32)[:max_samples]
    n_valid = x.shape[0]  # true capture length, before bucket padding
    # pad to a power-of-two bucket so the sync jit compiles once per
    # bucket, not once per stream length (zeros are inert to detection)
    bucket = 1 << max(9, (n_valid - 1).bit_length())
    if bucket != n_valid:
        x = np.concatenate(
            [x, np.zeros((bucket - n_valid, 2), np.float32)], axis=0)
    found, start, eps = _jit_sync(x)
    if not bool(np.asarray(found)):
        return fail, None
    start = int(np.asarray(start))
    eps = float(np.asarray(eps))

    # all length checks use the true capture length — decoding padding
    # zeros as DATA must fail, not silently "succeed"
    frame_np = x[start:]
    avail = n_valid - start
    if avail < 400:
        return fail, None
    # CFO-correct only fixed-size regions so device code caches: the
    # 400-sample head now, the (rate, n_sym)-sized data region after the
    # SIGNAL parse (both slices start at the frame start, keeping the
    # rotation phase-continuous)
    head = sync.correct_cfo(jnp.asarray(frame_np[:400]), eps)
    rate_bits, length, parity_ok = _jit_signal(head)
    if not bool(np.asarray(parity_ok)):
        return fail, None
    rate_mbps = SIGNAL_BITS_TO_MBPS.get(int(np.asarray(rate_bits)))
    if rate_mbps is None:
        return fail, None
    length_bytes = int(np.asarray(length))
    rate = RATES[rate_mbps]
    n_sym = n_symbols(length_bytes, rate)
    need = FRAME_DATA_START + 80 * n_sym
    if avail < need:
        return RxResult(False, rate_mbps, length_bytes,
                        np.zeros(0, np.uint8), None), None
    return None, _Acquired(frame_np, avail, eps, rate_mbps,
                           length_bytes, n_sym)


def _padded_segment(acq: _Acquired, n_sym_bucket: int):
    """The acquired frame's data region padded to `n_sym_bucket`
    symbols and CFO-corrected: the fixed-geometry device input of the
    bucketed and mixed-rate DATA decodes."""
    need_b = FRAME_DATA_START + 80 * n_sym_bucket
    frame_pad = np.zeros((need_b, 2), np.float32)
    n = min(acq.avail, need_b)
    frame_pad[:n] = acq.frame_np[:n]
    return sync.correct_cfo(jnp.asarray(frame_pad), acq.eps)


def receive(samples, check_fcs: bool = False,
            max_samples: int = 1 << 16, fxp: bool = False,
            viterbi_window: int = None,
            viterbi_metric: str = None) -> RxResult:
    """Host-side receiver driver: detect, align, CFO-correct, parse
    SIGNAL, dispatch the per-rate decoder — the jit analogue of the
    reference's header-driven rate dispatch. The data decode compiles
    once per (rate, power-of-two symbol bucket) with the true bit count
    traced (see decode_data_bucketed), so varied traffic stays within
    O(rates x log lengths) compiles.

    fxp=True routes the DATA decode through the Q15 integer interior
    (phy/wifi/rx_fxp.py — the reference's fixed-point discipline):
    acquisition and SIGNAL stay f32; the aligned data region is
    AGC-normalized by the preamble RMS and quantized to Q11 at the
    fixed-point boundary, after which every decode op is exact integer
    arithmetic (bit-identical across backends for identical quantized
    input).

    viterbi_window opts the (float) DATA decode into the sliding-
    window parallel Viterbi — same result at operating SNR, ~T/window
    less sequential trellis depth on the chip; viterbi_metric="int16"
    opts it into the quantized saturating-metric kernel (both ignored
    under fxp, whose decode keeps the exact scan).
    """
    res, acq = _acquire_frame(samples, max_samples)
    if acq is None:
        return res
    rate = RATES[acq.rate_mbps]

    # bucketed dispatch: pad the frame to a power-of-two symbol count so
    # the decode jit-caches O(rates x log lengths), not once per PSDU
    # length; the true bit count flows in as a traced scalar
    n_sym_b = _sym_bucket(acq.n_sym)
    seg = _padded_segment(acq, n_sym_b)
    if fxp:
        from ziria_tpu.phy.wifi import rx_fxp
        # AGC at the fixed-point boundary: unit average power over the
        # real preamble (numpy host math — stable for a given capture)
        rms = float(np.sqrt(np.mean(acq.frame_np[:320].astype(np.float64)
                                    ** 2) * 2.0))
        seg = rx_fxp.quantize_frame(np.asarray(seg) / max(rms, 1e-12))
    dec = _jit_decode_data_bucketed(acq.rate_mbps, n_sym_b, fxp,
                                    None if fxp else viterbi_window,
                                    None if fxp else viterbi_metric)
    clear = np.asarray(
        dec(seg, jnp.int32(acq.n_sym * rate.n_dbps)), np.uint8)
    psdu = clear[N_SERVICE_BITS: N_SERVICE_BITS + 8 * acq.length_bytes]
    crc = bool(np.asarray(check_crc32(psdu))) if check_fcs else None
    return RxResult(True, acq.rate_mbps, acq.length_bytes, psdu, crc)
