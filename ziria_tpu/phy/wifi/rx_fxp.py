"""Fixed-point (Q15/complex16-style) 802.11a DATA decode interior.

The reference RX ran its whole steady-state chain in int16 fixed point
(SORA bricks, SURVEY.md §2.2-2.3); this framework's RX interior is
deliberately f32 (docs/language.md) — EXCEPT here. This module is the
ROADMAP §3 option made real: a division-free integer decode path whose
every op is exact int32 arithmetic, so its output is **bit-identical
across backends, jit vs interp, and vmap widths**. That reproducibility
is the fixed-point path's reason to exist (the f32 path only promises
tolerance-bounded equality; see tests/test_rx_fxp.py).

Design (classic fixed-point receiver, restructured for the VPU):

- the aligned, CFO-corrected frame is quantized to Q11 int16 IQ
  (`quantize_frame`), the fixed-point boundary;
- the 64-pt FFT is `ops/fxp.dft64_q14` — integer GEMMs against split
  Q14 twiddles (the MXU formulation of SORA's SSE FFT);
- **no zero-forcing division**: instead of eq = y / H we carry
  z = y * conj(H) and demap against thresholds scaled by G = |H|^2 —
  algebraically the same LLRs the float path computes (its demapper
  multiplies by the gain |H|^2 right back; demap.py:47), with the
  divide gone;
- pilot common-phase tracking is integer CORDIC: vectoring recovers
  the pilot phase, rotation derotates the data bins. The pilot sum
  weights each pilot by its subcarrier gain G_k (a maximal-ratio
  combine) where the float path weights uniformly — documented
  intentional divergence, same operating behavior;
- LLRs leave as int16; the Viterbi ACS on exact small integers in f32
  is itself exact (|metric spread| << 2^24), so the decoded bits —
  and therefore descramble/CRC — inherit bit-exactness end to end.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ziria_tpu.ops import coding, fxp, interleave, ofdm, scramble, \
    viterbi, viterbi_pallas
from ziria_tpu.phy.wifi.params import N_SERVICE_BITS, RateParams
from ziria_tpu.phy.wifi.rx import FRAME_DATA_START

Q_IN = 11              # input quantization: Q11 (4 bits of PAPR headroom)
_DFT_SHIFT = 10        # dft64_q14 shift: bins ~= DFT * 2^-3 of Q11 input
_Z_SHIFT = 4           # pre-add shift inside y*conj(H) and |H|^2
_W_SHIFT = 3           # working shift down to demap precision
# overflow audit (Q11 input, |H| <= 4, 64-QAM corners): bins <= 2^16,
# z products <= 2^27, zw <= 2^20.5, zw * NORM_Q7 <= 2^30.2 — all int32
LLR_SHIFT = 5          # int32 LLR -> int16 output scale

# level-domain norm constants in Q7 — DERIVED from the float
# demapper's table so the two can never drift (the whole fxp demap
# contract is "algebraically the same LLRs as demap.py")
from ziria_tpu.ops.demap import _NORM as _NORM_F
_NORM_Q7 = {k: int(round(v * 128)) for k, v in _NORM_F.items()}


def quantize_frame(frame_f32):
    """Float aligned frame (..., 2) -> int32-held Q11 int16 samples."""
    return fxp.quantize_q(frame_f32, Q_IN)


def _fft_bins(sym_pairs):
    """(..., 80, 2) int Q11 time samples -> (..., 64, 2) int bins
    (CP stripped; unnormalized DFT scaled 2^-3)."""
    return fxp.dft64_q14(sym_pairs[..., ofdm.N_CP:, :], shift=_DFT_SHIFT)


def _estimate_channel_q(frame_q):
    """Integer channel estimate from the two LTS symbols: bin average
    times the known +-1 reference — same scale as the data bins."""
    l1 = fxp.dft64_q14(frame_q[192:256], shift=_DFT_SHIFT)
    l2 = fxp.dft64_q14(frame_q[256:320], shift=_DFT_SHIFT)
    avg = fxp.rsra(l1 + l2, 1)
    ref = np.zeros(ofdm.N_FFT, np.int32)
    ref[(np.arange(-26, 27) % ofdm.N_FFT)] = \
        ofdm.LTS_FREQ.astype(np.int32)
    return avg * jnp.asarray(ref)[:, None]


def _demap_q(i_lvl, gw, n_bpsc: int):
    """Level-domain max-log LLRs, all-integer: i_lvl ~ lvl * Gw where
    Gw is the per-subcarrier gain; thresholds are multiples of Gw
    (demap.py level formulas with |H|^2 folded through)."""
    if n_bpsc in (1, 2):
        return i_lvl[..., None] if n_bpsc == 1 else i_lvl
    a = jnp.abs(i_lvl)
    if n_bpsc == 4:
        return jnp.stack([i_lvl, 2 * gw - a], axis=-1)
    return jnp.stack([i_lvl, 4 * gw - a,
                      2 * gw - jnp.abs(a - 4 * gw)], axis=-1)


def decode_front_fxp(frame_q, rate: RateParams, n_sym: int):
    """Quantized aligned frame -> depunctured int16 LLR pairs (T, 2).

    The integer mirror of rx._decode_front: channel est + integer
    GEMM-FFT + conj-multiply 'equalize' + CORDIC pilot derotation +
    gain-scaled demap + deinterleave + depuncture."""
    frame_q = jnp.asarray(frame_q, fxp.I32)
    H = _estimate_channel_q(frame_q)                       # (64, 2)
    syms = frame_q[FRAME_DATA_START: FRAME_DATA_START + 80 * n_sym]
    bins = _fft_bins(syms.reshape(n_sym, 80, 2))           # (n_sym, 64, 2)

    # division-free equalize: z = y * conj(H), gain G = |H|^2, both at
    # working precision
    z = fxp.cmul_conj_i32(bins, H, _Z_SHIFT)
    zw = fxp.rsra(z, _W_SHIFT)
    G = fxp.cabs2_i32(H, _Z_SHIFT)                         # (64,)
    gw = fxp.rsra(G, _W_SHIFT)

    data = zw[:, jnp.asarray(ofdm.DATA_BINS)]              # (n_sym, 48, 2)
    pilots = zw[:, jnp.asarray(ofdm.PILOT_BINS)]           # (n_sym, 4, 2)
    g_data = gw[jnp.asarray(ofdm.DATA_BINS)]               # (48,)

    # pilot common phase, symbol polarity applied; CORDIC vectoring.
    # (z already carries G_k per pilot: a gain-weighted pilot sum.)
    pol = jnp.asarray(np.rint(ofdm.PILOT_POLARITY).astype(np.int32))[
        (jnp.arange(n_sym) + 1) % 127]
    expect = jnp.asarray(np.rint(ofdm.PILOT_VALS).astype(np.int32))
    w = pol[:, None] * expect[None, :]                     # (n_sym, 4)
    p = (pilots * w[..., None]).sum(axis=-2)               # (n_sym, 2)
    ang, _mag = fxp.cordic_atan2(p[..., 1], p[..., 0])     # (n_sym,)

    # derotate every data bin by -phase (kinv_bits=10: zw reaches
    # ~2^20.5 at |H|=4, above the Q15-compensation input limit)
    data = fxp.cordic_rotate(data, -ang[:, None], kinv_bits=10)

    # level scale: i_lvl ~= lvl * Gw via the Q7 norm constant
    cn = fxp.I32(_NORM_Q7[rate.n_bpsc])
    i_lvl = fxp.rsra(data[..., 0] * cn, 7)
    q_lvl = fxp.rsra(data[..., 1] * cn, 7)
    gvec = jnp.broadcast_to(g_data, i_lvl.shape)
    if rate.n_bpsc == 1:
        llr = _demap_q(i_lvl, gvec, 1)
    else:
        half = rate.n_bpsc // 2
        llr = jnp.concatenate(
            [_demap_q(i_lvl, gvec, rate.n_bpsc).reshape(
                i_lvl.shape + (half,)),
             _demap_q(q_lvl, gvec, rate.n_bpsc).reshape(
                 q_lvl.shape + (half,))], axis=-1)
    llr16 = fxp.sat16(fxp.rsra(llr.reshape(n_sym, -1), LLR_SHIFT))

    deint = interleave.deinterleave(
        llr16.reshape(-1), rate.n_cbps, rate.n_bpsc)
    return coding.depuncture(deint, rate.coding, fill=0).reshape(-1, 2)


def decode_data_fxp(frame_q, rate: RateParams, n_sym: int,
                    n_psdu_bits: int):
    """Quantized aligned frame -> (psdu_bits, service_bits), all-integer
    front end + exact-integer-in-f32 Viterbi + descramble."""
    dep = decode_front_fxp(frame_q, rate, n_sym)
    bits = viterbi.viterbi_decode(
        dep.astype(jnp.float32), n_bits=n_sym * rate.n_dbps)
    seed = scramble.recover_seed(bits[:7])
    clear = scramble.descramble_bits(bits, seed)
    return (clear[N_SERVICE_BITS: N_SERVICE_BITS + n_psdu_bits],
            clear[:N_SERVICE_BITS])


def decode_data_bucketed_fxp(frame_q, rate: RateParams,
                             n_sym_bucket: int, n_bits_real):
    """Bucketed fixed-point DATA decode (rx.decode_data_bucketed's
    integer twin): `frame_q` is quantized and padded to
    FRAME_DATA_START + 80*n_sym_bucket samples, `n_bits_real` is the
    true data-bit count as a TRACED scalar. LLR rows at or beyond
    n_bits_real are zeroed (0 = exact erasure in integer land too),
    so the pad adds no likelihood. Returns the full descrambled
    stream; the caller slices the PSDU."""
    dep = decode_front_fxp(frame_q, rate, n_sym_bucket)
    t = jnp.arange(dep.shape[0])
    dep = jnp.where((t < n_bits_real)[:, None], dep, 0)
    bits = viterbi.viterbi_decode(
        dep.astype(jnp.float32),
        n_bits=n_sym_bucket * rate.n_dbps)
    seed = scramble.recover_seed(bits[:7])
    return scramble.descramble_bits(bits, seed)


def decode_data_batch_fxp(frames_q, rate: RateParams, n_sym: int,
                          n_psdu_bits: int, interpret: bool = None,
                          viterbi_window: int = None):
    """Batched integer decode: (B, frame_len, 2) int -> ((B, n), (B, 16)).
    Same lane layout as rx.decode_data_batch: vmapped integer front
    end, Pallas Viterbi across the batch.

    ``viterbi_window`` opts into the sliding-window parallel Viterbi,
    exactly as on the float path. The integer LLRs reaching the kernel
    are unchanged, so the cross-backend bit-identity contract holds
    per-window too; what changes is the (measured-zero-BER) windowed
    approximation vs the exact trellis — see docs/windowed_viterbi.md.
    """
    dep = jax.vmap(
        lambda f: decode_front_fxp(f, rate, n_sym))(frames_q)
    bits = viterbi_pallas.viterbi_decode_batch_opt(
        dep.astype(jnp.float32), n_bits=n_sym * rate.n_dbps,
        window=viterbi_window, interpret=interpret)

    def back(b):
        seed = scramble.recover_seed(b[:7])
        clear = scramble.descramble_bits(b, seed)
        return (clear[N_SERVICE_BITS: N_SERVICE_BITS + n_psdu_bits],
                clear[:N_SERVICE_BITS])

    return jax.vmap(back)(bits)
