"""802.11a/g OFDM PHY rate parameters.

Counterpart of the per-rate dispatch tables inside the reference's
`modulating.blk`/`encoding.blk`/`parsePLCPHeader` (SURVEY.md §2.3).
Values are the standard's Table 78 (§17.3.2.2) from standard knowledge.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict


@dataclass(frozen=True)
class RateParams:
    mbps: int
    n_bpsc: int        # coded bits per subcarrier
    n_cbps: int        # coded bits per OFDM symbol
    n_dbps: int        # data bits per OFDM symbol
    coding: str        # "1/2" | "2/3" | "3/4"
    signal_bits: int   # 4-bit RATE field, R1 (transmitted first) = MSB here


RATES: Dict[int, RateParams] = {
    6:  RateParams(6,  1, 48,  24,  "1/2", 0b1101),
    9:  RateParams(9,  1, 48,  36,  "3/4", 0b1111),
    12: RateParams(12, 2, 96,  48,  "1/2", 0b0101),
    18: RateParams(18, 2, 96,  72,  "3/4", 0b0111),
    24: RateParams(24, 4, 192, 96,  "1/2", 0b1001),
    36: RateParams(36, 4, 192, 144, "3/4", 0b1011),
    48: RateParams(48, 6, 288, 192, "2/3", 0b0001),
    54: RateParams(54, 6, 288, 216, "3/4", 0b0011),
}

SIGNAL_BITS_TO_MBPS = {p.signal_bits: m for m, p in RATES.items()}

# the ONE rate ordering every mixed-rate ``lax.switch`` uses (TX
# encode_many and RX decode_data_mixed build their branch lists from
# it; a disagreement would decode a lane at the wrong rate) — pinned
# by tests/test_rx_mixed_dispatch.py::test_rate_index_order...
RATE_MBPS_ORDER = tuple(sorted(RATES))
RATE_INDEX = {m: i for i, m in enumerate(RATE_MBPS_ORDER)}
MAX_DBPS = max(p.n_dbps for p in RATES.values())     # 216 (54 Mbps)

N_SERVICE_BITS = 16
N_TAIL_BITS = 6


def n_symbols(length_bytes: int, rate: RateParams) -> int:
    """Number of DATA OFDM symbols for a PSDU of `length_bytes`."""
    n_bits = N_SERVICE_BITS + 8 * length_bytes + N_TAIL_BITS
    return -(-n_bits // rate.n_dbps)
