"""Device-resident TX → channel → RX loopback link.

The closed loop the reference ran over SORA/BladeRF hardware (Sora's
NSDI 2009 real-time link; the Ziria transceiver demo drives it
in-language) — here the "air" is the batched synthetic channel and the
whole N-frame round trip compiles to ONE device program:

    link.loopback_fused     encode_many → impair_many → acquire →
                            classify → gather → mixed decode →
                            batched CRC, fused into ONE jitted graph

— 1 device dispatch for any N-frame, all-rates, multi-SNR batch. The
host `_classify_acquire` decision tree is pure integer logic, so in
the loopback — where the frame geometry is already known from the TX
side and the SIGNAL parse is therefore NOT data-dependent — it traces
(`rx.classify_acquire_graph`) and no acquisition metadata crosses the
host link mid-batch; the decoded SIGNAL fields come back as device-
side validity flags, so no-detect / bad-parity / truncated lanes keep
their exact staged-path classification.

``fused=False`` (or ``--no-fused-link`` / ``ZIRIA_FUSED_LINK=0``) runs
the STAGED 5-dispatch path — encode_many, impair_many, then the
acquire → gather → mixed-decode triple — the fused graph's
bit-identical oracle (same capture bucket, so the noise draws agree);
``batched_tx=False`` (``--no-batched-tx`` / ``ZIRIA_BATCHED_TX=0``)
drops further to the per-frame loop: encode_frame + single-lane
channel + rx.receive per frame, >= 5 dispatches per lane. All three
agree lane for lane (tests/test_link_fused.py, test_tx_batched.py;
tools/rx_dispatch_bench.py ``fused_link_stats`` measures it).

On top of the fused step, ``sweep_ber`` runs an entire BER waterfall —
(rate grid) x (SNR grid x seeds) — as ONE ``lax.scan`` dispatch with a
donated error-count carry, and ``sweep_ber_sharded`` shards its frame
lanes over ``parallel/batch.frame_mesh``'s dp axis so the sweep scales
across chips (integer error counts, so the numbers are identical on 1
device and any mesh).
"""

from __future__ import annotations

import os
from functools import lru_cache
from typing import List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ziria_tpu.backend import framebatch
from ziria_tpu.ops.viterbi import _check_radix
from ziria_tpu.phy import channel
from ziria_tpu.phy import profiles as chanprof
from ziria_tpu.phy.wifi import rx, tx
from ziria_tpu.phy.wifi.params import N_SERVICE_BITS, \
    RATE_MBPS_ORDER, RATES, n_symbols
from ziria_tpu.utils import dispatch, programs
from ziria_tpu.utils.dispatch import pad_lanes, pow2_ceil


def _note_link_degraded(counter: str) -> None:
    """The ONE link-side degrade-visibility ritual (the fused link
    and the sweep share it, so the recording can never drift): the
    ``link.degraded_mode`` gauge plus the per-site degrade counter."""
    from ziria_tpu.utils import telemetry
    dispatch.record_gauge("link.degraded_mode", 1.0)
    telemetry.count(counter)


def batched_tx_enabled(batched_tx: Optional[bool] = None) -> bool:
    """The ONE reading of the --batched-tx / ZIRIA_BATCHED_TX knob
    (default ON), shared by every TX-batch surface."""
    if batched_tx is not None:
        return batched_tx
    return os.environ.get("ZIRIA_BATCHED_TX", "1") != "0"


def fused_link_enabled(fused: Optional[bool] = None) -> bool:
    """The ONE reading of the --fused-link / ZIRIA_FUSED_LINK knob
    (default ON): whether `loopback_many` routes through the
    one-dispatch fused graph or the staged 5-dispatch oracle."""
    if fused is not None:
        return fused
    return os.environ.get("ZIRIA_FUSED_LINK", "1") != "0"


def transmit_many(psdus: Sequence, rates_mbps: Sequence[int],
                  add_fcs: bool = False,
                  batched_tx: Optional[bool] = None) -> List[np.ndarray]:
    """N mixed-rate, mixed-length frames -> per-frame sample arrays at
    their true lengths: ONE encode_many dispatch plus one batched
    copy-out (default), or the per-frame encode_frame oracle loop
    (``ZIRIA_BATCHED_TX=0``). Bit-identical either way — including
    the empty batch, which is [] in both modes (receive_many's
    convention), never a mode-dependent raise."""
    if not len(psdus):
        return []
    if not batched_tx_enabled(batched_tx):
        return [np.asarray(tx.encode_frame(p, m, add_fcs=add_fcs))
                for p, m in zip(psdus, rates_mbps)]
    txb = tx.encode_many(psdus, rates_mbps, add_fcs=add_fcs)
    arr = np.asarray(txb.samples[:len(psdus)])   # pad rows never move
    return [arr[i, :int(v)] for i, v in enumerate(txb.n_valid)]


def _lane_param(v, n: int, dtype) -> np.ndarray:
    return np.broadcast_to(np.asarray(v, dtype), (n,)).copy()


def _link_buckets(psdus, rates_mbps, add_fcs: bool, dly_max: int,
                  tap_pad: int = 0):
    """The ONE derivation of the link's (symbol bucket, capture
    bucket): the common symbol bucket's frame length plus the worst
    delay, at the receiver's capture-bucket rule. ``tap_pad`` is the
    profiled channel's FIR ring headroom (max tap count - 1, zero for
    the unprofiled/flat link so those buckets are untouched): the
    multipath tail smears that many samples past the frame, and
    without the margin a lane whose delay + frame length lands
    exactly on the power-of-two bucket would wrap the ring onto the
    capture HEAD via the delay roll. Every loopback mode — fused,
    staged, per-frame — calls this, because a lane's noise field is
    drawn over the whole capture buffer: buffer sizes ARE semantics,
    and a drift here would silently break the lane-for-lane
    bit-identity contract."""
    fcs_bytes = 4 if add_fcs else 0
    sym_b = max(tx._sym_bucket(n_symbols(
        int(np.asarray(p).size) + fcs_bytes, RATES[m]))
        for p, m in zip(psdus, rates_mbps))
    return sym_b, rx._stream_bucket(400 + 80 * sym_b + int(dly_max)
                                    + int(tap_pad))


class _LinkGeometry:
    """The host-known batch geometry of the staged/fused loopback: the
    shared TX batch prep (`tx.batch_host_prep` — the SAME padded-batch
    rule `encode_many` consumes, so the link can never drift from the
    transmit surfaces) plus the link-side row tables (channel params,
    capture bucket, per-lane decode bit counts)."""

    def __init__(self, psdus, rates_mbps, snr, eps, dly, add_fcs,
                 tap_pad: int = 0):
        n = len(psdus)
        self.n = n
        prep = tx.batch_host_prep(psdus, rates_mbps, add_fcs)
        self.n_sym = prep.n_sym
        self.sym_b = prep.n_sym_bucket
        self.bit_b = prep.bit_bucket
        self.bits_b = prep.bits_b
        self.nbits_b = prep.nbits_b
        self.ridx_b = prep.ridx_b
        _sym_b2, self.l_cap = _link_buckets(psdus, rates_mbps,
                                            add_fcs, int(dly.max()),
                                            tap_pad)
        if _sym_b2 != self.sym_b:       # one rule, two call shapes
            raise AssertionError(
                f"link bucket rule drifted: {_sym_b2} != {self.sym_b}")
        self.rows = pow2_ceil(n)
        lanes = pad_lanes(list(range(n)))
        self.nv_tx = np.zeros(self.rows, np.int32)
        self.ndata_b = np.zeros(self.rows, np.int32)
        for row, i in enumerate(lanes):
            self.nv_tx[row] = 400 + 80 * int(self.n_sym[i])
            self.ndata_b[row] = int(self.n_sym[i]) * \
                RATES[rates_mbps[i]].n_dbps

        def _pad_rows(a):
            return np.concatenate(
                [a, np.broadcast_to(a[0], (self.rows - n,)
                                    + a.shape[1:])])
        self.snr = _pad_rows(snr)
        self.eps = _pad_rows(eps)
        self.dly = _pad_rows(dly)


def loopback_many(psdus, rates_mbps: Sequence[int],
                  snr_db=np.inf, cfo=0.0, delay=0, seed: int = 0,
                  add_fcs: bool = False, check_fcs: bool = False,
                  batched_tx: Optional[bool] = None,
                  fused: Optional[bool] = None,
                  viterbi_window: int = None,
                  viterbi_metric: str = None,
                  viterbi_radix: int = None,
                  channel_profile=None,
                  sco_track: Optional[bool] = None,
                  fused_demap: Optional[bool] = None,
                  geometry=None) -> List:
    """The full N-frame mixed-rate loopback. Default: the FUSED path —
    encode → per-lane channel impairments → acquire → classify →
    gather → mixed-rate decode → batched CRC as ONE jitted device
    program (1 dispatch). ``fused=False`` / ``ZIRIA_FUSED_LINK=0``:
    the staged ~5-dispatch path (encode_many + impair_many + the
    acquire/gather/decode triple), the fused graph's bit-identical
    oracle. ``batched_tx=False``: the per-frame loop (>= 5 dispatches
    per lane), the staged path's oracle in turn.

    ``snr_db``/``cfo``/``delay`` are scalars or per-lane sequences
    (``np.inf`` SNR disables noise exactly); lane noise keys derive
    from ``seed`` by counter fold-in, so lane i sees the same channel
    whether it runs fused, staged, or alone. ``channel_profile`` is a
    profile name / per-lane sequence / None (-> the
    ``ZIRIA_CHANNEL_PROFILE`` default; `profiles.resolve_profiles` —
    all-flat IS the unprofiled channel by construction), applied as
    vmapped per-lane taps/SCO/drift/bursts inside the SAME dispatches;
    ``sco_track`` opts the decode into the pilot phase-ramp tracking
    (``ZIRIA_RX_SCO_TRACK``). Returns per-frame :class:`rx.RxResult`,
    lane-for-lane bit-identical across all three modes — including
    no-detect / bad-parity / truncated lanes and ``check_fcs=True``.
    (Profiled lanes' channel SAMPLES may differ by one float32 ulp
    between the separately compiled mode programs — the
    FMA-contraction rule — but the decoded RxResults are pinned
    equal lane for lane: tests/test_channel_profiles.py.)"""
    n = len(psdus)
    if len(rates_mbps) != n:
        raise ValueError(f"{n} PSDUs but {len(rates_mbps)} rates")
    if n == 0:
        return []          # match receive_many's empty-batch behavior
    snr = _lane_param(snr_db, n, np.float32)
    eps = _lane_param(cfo, n, np.float32)
    dly = _lane_param(delay, n, np.int32)
    if (dly < 0).any():
        raise ValueError("negative delay")
    # a Geometry fills only the knobs the caller left at None — explicit
    # per-call arguments still win (utils/geometry contract)
    if geometry is not None:
        viterbi_window = (geometry.viterbi_window
                          if viterbi_window is None else viterbi_window)
        viterbi_metric = (geometry.viterbi_metric
                          if viterbi_metric is None else viterbi_metric)
        viterbi_radix = (geometry.viterbi_radix
                         if viterbi_radix is None else viterbi_radix)
        sco_track = (geometry.sco_track
                     if sco_track is None else sco_track)
        fused_demap = (geometry.fused_demap
                       if fused_demap is None else fused_demap)
    # resolved ONCE here so the per-frame oracle, the staged path, and
    # the fused graph's compile-cache key all see the same radix,
    # per-lane profile names, sco_track, and fused_demap values
    viterbi_radix = _check_radix(viterbi_radix)
    prof_key = chanprof.resolve_profiles(channel_profile, n)
    sco_track = rx.sco_track_enabled(sco_track)
    fused_demap = rx.fused_demap_enabled(fused_demap)
    # profiled links reserve FIR-ring headroom in the capture bucket
    # (max taps - 1; zero for flat/None, so those buckets — and their
    # noise-draw geometry — are byte-for-byte today's)
    tap_pad = 0 if prof_key is None else max(
        len(chanprof.get_profile(nm).taps) for nm in prof_key) - 1
    # the shared bucket rule, from byte counts alone — the per-frame
    # oracle never pays the padded-batch construction
    _sym_b, l_cap = _link_buckets(psdus, rates_mbps, add_fcs,
                                  int(dly.max()), tap_pad)
    if not batched_tx_enabled(batched_tx):
        # the per-frame oracle: same channel physics, one frame at a
        # time, through the per-capture receiver
        results = []
        for i in range(n):
            s = np.asarray(tx.encode_frame(psdus[i], rates_mbps[i],
                                           add_fcs=add_fcs))
            cap = channel.impair_one(
                s, snr[i], eps[i], int(dly[i]), seed, i, l_cap,
                profile=None if prof_key is None else prof_key[i])
            results.append(rx.receive(np.asarray(cap),
                                      check_fcs=check_fcs,
                                      viterbi_window=viterbi_window,
                                      viterbi_metric=viterbi_metric,
                                      viterbi_radix=viterbi_radix,
                                      fused_demap=fused_demap,
                                      sco_track=sco_track))
        return results

    geo = _LinkGeometry(psdus, rates_mbps, snr, eps, dly, add_fcs,
                        tap_pad)
    # lane-pad the profile names exactly as every other row table
    # (lane 0 repeated), so pad rows ride lane 0's channel
    prof_rows = None if prof_key is None else tuple(
        prof_key[i] for i in pad_lanes(list(range(n))))
    if fused_link_enabled(fused):
        return _loopback_fused(geo, seed, check_fcs,
                               viterbi_window, viterbi_metric,
                               viterbi_radix, prof_rows, sco_track,
                               fused_demap)
    return _loopback_staged(geo, seed, check_fcs, viterbi_window,
                            viterbi_metric, viterbi_radix, prof_rows,
                            sco_track, fused_demap)


def _loopback_staged(geo: _LinkGeometry, seed, check_fcs,
                     viterbi_window, viterbi_metric,
                     viterbi_radix=None, prof_rows=None,
                     sco_track: bool = False,
                     fused_demap: bool = False) -> List:
    """The staged ~5-dispatch batched loopback (the fused graph's
    bit-identical oracle): one encode_many dispatch, one impair_many
    dispatch, then receive_many_device's acquire → gather → decode
    (+ CRC) over the device-resident capture batch."""
    enc_fn = tx._jit_encode_many(geo.bit_b, geo.sym_b)
    enc_args = (jnp.asarray(geo.bits_b), jnp.asarray(geo.nbits_b),
                jnp.asarray(geo.ridx_b))
    programs.note_site("tx.encode_many", enc_fn, *enc_args)
    with dispatch.timed("tx.encode_many"):
        samples = enc_fn(*enc_args)
    caps = channel.impair_many(
        samples, geo.nv_tx, geo.snr, geo.eps, geo.dly, seed,
        out_len=geo.l_cap, profile=prof_rows)
    return framebatch.receive_many_device(
        caps, geo.n, check_fcs=check_fcs,
        viterbi_window=viterbi_window, viterbi_metric=viterbi_metric,
        viterbi_radix=viterbi_radix, sco_track=sco_track,
        fused_demap=fused_demap)


@lru_cache(maxsize=None)
def _jit_fused_link(rows: int, bit_bucket: int, sym_bucket: int,
                    l_cap: int, viterbi_window: int = None,
                    viterbi_metric: str = None,
                    viterbi_radix: int = None, profile_key=None,
                    sco_track: bool = False,
                    fused_demap: bool = False):
    """ONE compiled loopback link per (lane count, bit bucket, symbol
    bucket, capture bucket, decode mode, per-lane channel-profile
    names): the whole TX → channel → RX chain — including the
    acquisition classify tree and the batched FCS check — as a single
    XLA program. A profiled link is STILL one dispatch: the profile's
    taps/SCO/drift/bursts trace into the channel stage as per-lane
    constants (callers pass RESOLVED names — jaxlint R1). The CRC
    flags are always computed (a ~200-byte masked scan per lane —
    noise next to the Viterbi), so one compile serves both
    ``check_fcs`` modes."""
    need_b = rx.FRAME_DATA_START + 80 * sym_bucket

    def f(bits_b, nbits_b, ridx_b, nv_tx, snr, eps, dly, seed,
          ndata_b):
        # 1. mixed-rate encode at the common bucketed geometry
        samples = tx.encode_many_graph(bits_b, nbits_b, ridx_b,
                                       sym_bucket)
        # 2. per-lane channel impairments (counter fold-in keys:
        #    lane i's noise is the same fused, staged, or alone —
        #    profiled lanes included)
        caps = channel.impair_many_graph(samples, nv_tx, snr, eps,
                                         dly, seed, l_cap,
                                         profile_key)
        # 3. batched acquisition: detect / LTS timing / CFO / SIGNAL
        #    (the whole capture is the lane's buffer, so n_valid and
        #    the detector's position cap are both l_cap — exactly what
        #    receive_many_device passes)
        nv = jnp.full((caps.shape[0],), l_cap, jnp.int32)
        found, start, eps_hat, rate_bits, length, parity_ok = \
            jax.vmap(rx.acquire_frame_graph)(caps, nv, nv)
        # 4. the classify tree, traced — the host decision that used
        #    to force a sync point stays on-device
        status, mbps_sig, len_sig, nsym_sig = rx.classify_acquire_graph(
            found, nv - start, rate_bits, length, parity_ok)
        # 5. gather+derotate EVERY lane at the common symbol bucket
        #    (failed lanes produce garbage segments, masked by status
        #    host-side; per-lane values are batch-independent)
        caps_pad = jnp.pad(caps, ((0, 0), (0, need_b), (0, 0)))
        segs = jax.vmap(
            lambda xi, s, e, a: rx.gather_segment_graph(
                xi, s, e, a, sym_bucket))(caps_pad, start, eps_hat,
                                          nv - start)
        # 6. mixed-rate DATA decode at the TX-known geometry (the
        #    loopback's SIGNAL parse is not data-dependent: rate and
        #    bit count per lane are known a priori; the decoded
        #    SIGNAL only gates validity via `status`)
        clear = rx.decode_data_mixed(segs, ridx_b, ndata_b, sym_bucket,
                                     viterbi_window, viterbi_metric,
                                     viterbi_radix,
                                     sco_track=sco_track,
                                     fused_demap=fused_demap)
        # 7. batched FCS check over the decoded PSDUs
        crc_ok = rx.crc_psdu_many_graph(clear, nbits_b)
        return status, mbps_sig, len_sig, nsym_sig, clear, crc_ok

    return jax.jit(f)


def _loopback_fused(geo: _LinkGeometry, seed, check_fcs,
                    viterbi_window, viterbi_metric,
                    viterbi_radix=None, prof_rows=None,
                    sco_track: bool = False,
                    fused_demap: bool = False) -> List:
    """Host wrapper of the fused graph: ONE device dispatch, then the
    per-lane RxResult assembly from the returned validity flags —
    integer reads only, exactly mirroring `_classify_acquire`'s
    outcomes. If a decodable lane's decoded SIGNAL disagrees with the
    TX-side geometry (possible only when noise corrupts the SIGNAL
    into a *different valid* header — a 1-in-2^~16 parity escape), the
    fused decode geometry would diverge from the staged one, so the
    whole batch falls back to the staged oracle; the common case pays
    nothing for the guard."""
    from ziria_tpu.runtime import resilience

    fn = _jit_fused_link(geo.rows, geo.bit_b, geo.sym_b, geo.l_cap,
                         viterbi_window, viterbi_metric, viterbi_radix,
                         prof_rows, sco_track, fused_demap)
    fused_args = (
        jnp.asarray(geo.bits_b), jnp.asarray(geo.nbits_b),
        jnp.asarray(geo.ridx_b), jnp.asarray(geo.nv_tx),
        jnp.asarray(geo.snr), jnp.asarray(geo.eps),
        jnp.asarray(geo.dly), jnp.uint32(seed),
        jnp.asarray(geo.ndata_b))
    programs.note_site("link.fused", fn, *fused_args)
    try:
        # guarded dispatch (runtime/resilience): a transient failure
        # retries with backoff to the identical result (the graph is
        # pure); a fatal or retry-exhausted one degrades the batch to
        # the staged oracle below — bit-identical by the pinned
        # fused-vs-staged contract, recorded, never a crash
        status, mbps_sig, len_sig, nsym_sig, clear, crc_ok = \
            resilience.guarded("link.fused", fn, *fused_args)
    except resilience.DispatchFailed:
        _note_link_degraded("link.fused_degraded")
        return _loopback_staged(geo, seed, check_fcs, viterbi_window,
                                viterbi_metric, viterbi_radix,
                                prof_rows, sco_track, fused_demap)
    try:
        # on an async backend a mid-execution runtime failure
        # surfaces HERE at the host pull, after the guarded dispatch
        # already returned — the fused batch is lost, so degrade
        # exactly as for a fatal dispatch
        status = np.asarray(status)
        mbps_sig = np.asarray(mbps_sig)
        len_sig = np.asarray(len_sig)
        nsym_sig = np.asarray(nsym_sig)
    except Exception:        # noqa: BLE001 - async loss, degrade
        _note_link_degraded("link.fused_degraded")
        return _loopback_staged(geo, seed, check_fcs, viterbi_window,
                                viterbi_metric, viterbi_radix,
                                prof_rows, sco_track, fused_demap)
    # healthy pass: re-record the gauge LEVEL so a past degrade does
    # not latch forever on dashboards (the rx receivers' per-chunk
    # level discipline)
    dispatch.record_gauge("link.degraded_mode", 0.0)

    results: List = [None] * geo.n
    clear_np = None
    crc_np = None
    for i in range(geo.n):
        st = int(status[i])
        if st == rx.ACQ_FAIL:
            results[i] = rx.RxResult(False, 0, 0,
                                     np.zeros(0, np.uint8), None)
            continue
        m, ln = int(mbps_sig[i]), int(len_sig[i])
        if st == rx.ACQ_TRUNCATED:
            results[i] = rx.RxResult(False, m, ln,
                                     np.zeros(0, np.uint8), None)
            continue
        if (m != RATE_MBPS_ORDER[int(geo.ridx_b[i])]
                or 8 * ln != int(geo.nbits_b[i])
                or int(nsym_sig[i]) != int(geo.n_sym[i])):
            # SIGNAL decoded to a different valid header than the one
            # TX sent: the staged path would decode at ITS claimed
            # geometry — replay the batch through the oracle
            return _loopback_staged(geo, seed, check_fcs,
                                    viterbi_window, viterbi_metric,
                                    viterbi_radix, prof_rows,
                                    sco_track, fused_demap)
        if clear_np is None:
            try:
                clear_np = np.asarray(clear, np.uint8)
                crc_np = np.asarray(crc_ok) if check_fcs else None
            except Exception:    # noqa: BLE001 - async loss, degrade
                _note_link_degraded("link.fused_degraded")
                return _loopback_staged(geo, seed, check_fcs,
                                        viterbi_window, viterbi_metric,
                                        viterbi_radix, prof_rows,
                                        sco_track, fused_demap)
        psdu = clear_np[i][N_SERVICE_BITS: N_SERVICE_BITS + 8 * ln]
        crc = bool(crc_np[i]) if check_fcs else None
        results[i] = rx.RxResult(True, m, ln, psdu, crc)
    return results


def stream_many(psdus, rates_mbps: Sequence[int], gaps=None,
                snr_db=np.inf, cfo: float = 0.0, delay: int = 0,
                seed: int = 0, add_fcs: bool = False,
                tail: int = 2048,
                batched_tx: Optional[bool] = None,
                channel_profile=None, _lane: int = 0):
    """Synthesize a continuous multi-frame I/Q stream — the stimulus
    of the streaming receiver (`framebatch.receive_stream`) and its
    bench: N mixed-rate frames at random (or given) inter-frame gaps,
    an initial `delay` of idle air, whole-stream CFO, and AWGN over
    everything (`channel.impair_stream` — SNR referenced to frame
    power, so gap length never changes the noise level). Frames ride
    the ONE-dispatch batched TX (`transmit_many`; per-frame oracle
    under ``batched_tx=False``, bit-identical).

    Returns ``(stream, starts)``: the (n, 2) f32 stream and the TRUE
    frame-start indices — the ground truth the streaming identity
    contract slices at. `gaps` is a length-(N-1) sequence of samples
    between a frame's end and the next frame's start; default: seeded
    random in [300, 600) — wide enough that a `frame_len`-tight
    receive window over one frame never also spans the NEXT frame's
    long preamble (per-capture `receive`'s global LTS peak-pick could
    otherwise time onto the stronger neighbor; identity would hold,
    per-frame decode would not). `tail` idle samples close the stream
    so the last frame's window is full-length.

    ``channel_profile`` (a profile name or None -> the
    ``ZIRIA_CHANNEL_PROFILE`` default; flat IS the unprofiled stream)
    applies the profile's multipath/SCO/drift/bursts over the WHOLE
    stream via `channel.impair_stream` — the streaming fleet's
    physical-fault campaign stimulus. Under an ``sco`` profile the
    returned `starts` are the PRE-resample positions (true positions
    drift by up to ``sco * len(stream)`` samples — slice-at-truth
    identity contracts should use flat-tap profiles)."""
    n = len(psdus)
    prof_names = chanprof.resolve_profiles(channel_profile, 1)
    prof_name = None if prof_names is None else prof_names[0]
    if len(rates_mbps) != n:
        raise ValueError(f"{n} PSDUs but {len(rates_mbps)} rates")
    if n == 0:
        if np.isfinite(snr_db):
            # SNR is referenced to frame power; with no frames there
            # is nothing to reference, and silently returning zeros
            # would masquerade as a noise stimulus
            raise ValueError("stream_many with zero frames has no "
                             "frame power to reference snr_db against;"
                             " synthesize noise directly")
        return (np.zeros((int(tail), 2), np.float32),
                np.zeros((0,), np.int64))
    frames = transmit_many(psdus, rates_mbps, add_fcs=add_fcs,
                           batched_tx=batched_tx)
    rng = np.random.default_rng(seed)
    if gaps is None:
        gaps = rng.integers(300, 600, size=max(n - 1, 0))
    gaps = np.asarray(gaps, np.int64)
    if gaps.shape[0] != n - 1:
        raise ValueError(f"{n} frames need {n - 1} gaps, "
                         f"got {gaps.shape[0]}")
    if n > 1 and (gaps < 0).any():
        raise ValueError("negative gap")
    if int(delay) < 0:
        raise ValueError("negative delay")

    starts = np.zeros(n, np.int64)
    pos = int(delay)
    for i, f in enumerate(frames):
        starts[i] = pos
        pos += f.shape[0] + (int(gaps[i]) if i < n - 1 else 0)
    stream = np.zeros((pos + int(tail), 2), np.float32)
    n_signal = 0
    for s, f in zip(starts, frames):
        stream[s: s + f.shape[0]] = f
        n_signal += f.shape[0]
    return (channel.impair_stream(stream, n_signal, snr_db, cfo, seed,
                                  profile=prof_name, lane=_lane),
            starts)


class ArrivalSpec(NamedTuple):
    """A seeded ragged-arrival shape for `stream_many_multi`: slab
    sizes drawn uniformly in ``[slab_lo, slab_hi)`` samples and
    inter-arrival gaps in ``[gap_lo, gap_hi]`` scheduler ticks (gap 0
    = the next slab lands on the same tick — a burst). One spec
    describes the whole fleet; each stream draws its OWN schedule
    from its folded seed, so the traffic is ragged ACROSS streams
    too, and every replay is identical."""
    slab_lo: int = 256
    slab_hi: int = 2048
    gap_lo: int = 0
    gap_hi: int = 2


def arrival_schedule(stream: np.ndarray, spec: ArrivalSpec,
                     seed: int) -> List:
    """Cut one synthesized stream into a seeded arrival schedule:
    ``[(tick, slab), ...]`` with ticks non-decreasing and the slabs
    concatenating back to the stream EXACTLY (the load generator
    replays real ragged traffic, it never invents or drops samples).
    Deterministic per (stream length, spec, seed)."""
    if spec.slab_lo < 1 or spec.slab_hi <= spec.slab_lo:
        raise ValueError(
            f"arrival slab range [{spec.slab_lo}, {spec.slab_hi}) "
            f"is empty or non-positive")
    if spec.gap_lo < 0 or spec.gap_hi < spec.gap_lo:
        raise ValueError(
            f"arrival gap range [{spec.gap_lo}, {spec.gap_hi}] "
            f"is empty or negative")
    rng = np.random.default_rng(seed)
    out, pos, tick, n = [], 0, 0, int(stream.shape[0])
    while pos < n:
        k = int(rng.integers(spec.slab_lo, spec.slab_hi))
        out.append((tick, stream[pos: pos + k]))
        pos += k
        tick += int(rng.integers(spec.gap_lo, spec.gap_hi + 1))
    return out


def _stream_seed(seed: int, i: int) -> int:
    """Per-stream seed fold-in for `stream_many_multi`: deterministic
    and collision-free across the fleet for any base seed (the affine
    map is injective mod the prime, so stream i's gap and noise draws
    never depend on which other streams ride the load). The offset
    also keeps lanes off the bare base seed for ordinary seeds — not
    a universal guarantee (every lane's affine map has one fixed
    point mod 2^31-1); callers needing a lane provably disjoint from
    a `stream_many(seed=seed)` stimulus should pick a different base
    seed."""
    return (int(seed) * 1000003 + 7919 * (int(i) + 1)) % (2 ** 31 - 1)


def stream_many_multi(psdus_per_stream, rates_per_stream, snr_db=np.inf,
                      cfo=0.0, delay=0, seed: int = 0,
                      add_fcs: bool = False, tail: int = 2048,
                      gaps=None, batched_tx: Optional[bool] = None,
                      arrival: Optional[ArrivalSpec] = None,
                      channel_profile=None):
    """The S-stream load synthesizer — the stimulus of the multi-
    stream receiver (`framebatch.receive_streams`) and its bench:
    stream i is exactly ``stream_many(psdus_per_stream[i],
    rates_per_stream[i], ...)`` at the per-stream folded seed
    (`_stream_seed`), so every stream carries its own frames, gaps,
    CFO rotation, and noise draws, mutually independent and
    reproducible per lane. ``snr_db``/``cfo``/``delay`` broadcast
    scalar-or-per-stream (the `loopback_many` rule); ``gaps`` is
    None or a length-S sequence of per-stream gap sequences.

    Returns ``(streams, starts_per_stream)``: S (n_i, 2) f32 streams
    (lengths ragged — the receiver's packer handles that) and each
    stream's TRUE frame-start indices, the ground truth the fleet
    identity contract slices at.

    ``arrival`` (an :class:`ArrivalSpec`) additionally returns a
    third element: per-stream seeded arrival SCHEDULES —
    ``schedules[i]`` is ``[(tick, slab), ...]`` cutting stream *i*
    into ragged slabs with inter-arrival gaps (the serving load
    generator's replayable traffic shape, `runtime/serve.py`); the
    slabs concatenate back to the stream exactly, so pushing a
    schedule through a receiver emits bit-identically to pushing the
    whole stream. Default ``None`` keeps the two-element return —
    existing call sites unchanged.

    ``channel_profile`` is a name or per-STREAM sequence (cycling, the
    `profiles.resolve_profiles` rule; None -> the env default): each
    stream rides its own physical channel — the fleet-scale
    physical-fault campaign stimulus of the soak harness."""
    s = len(psdus_per_stream)
    if len(rates_per_stream) != s:
        raise ValueError(f"{s} streams of PSDUs but "
                         f"{len(rates_per_stream)} of rates")
    if gaps is not None and len(gaps) != s:
        raise ValueError(f"{s} streams need {s} gap sequences, "
                         f"got {len(gaps)}")
    prof_key = chanprof.resolve_profiles(channel_profile, s)
    snr = _lane_param(snr_db, s, np.float64)
    eps = _lane_param(cfo, s, np.float64)
    dly = _lane_param(delay, s, np.int64)
    streams, starts = [], []
    for i in range(s):
        st, sts = stream_many(
            psdus_per_stream[i], rates_per_stream[i],
            gaps=None if gaps is None else gaps[i],
            snr_db=float(snr[i]), cfo=float(eps[i]),
            delay=int(dly[i]), seed=_stream_seed(seed, i),
            add_fcs=add_fcs, tail=tail, batched_tx=batched_tx,
            # "flat" (not None) when the fleet resolved to no profile:
            # the per-stream call must not resurrect the env default
            # the fleet-level resolution already consumed
            channel_profile=("flat" if prof_key is None
                             else prof_key[i]))
        streams.append(st)
        starts.append(sts)
    if arrival is None:
        return streams, starts
    schedules = [arrival_schedule(streams[i], arrival,
                                  _stream_seed(seed, i) + 1)
                 for i in range(s)]
    return streams, starts, schedules


def loopback_ber_bits(psdus, rate_mbps: int, snr_db: float, seed: int,
                      batched_tx: Optional[bool] = None,
                      profile=None,
                      sco_track: Optional[bool] = None) -> np.ndarray:
    """Perfect-sync single-rate BER loopback — the statistical lane of
    the link (BER waterfalls measure the equalize/demap/Viterbi chain,
    not packet detection): (B, n_bytes) PSDUs encode in ONE dispatch
    (`tx.encode_batch`; per-frame `encode_frame` loop when batched TX
    is off — bit-identical), AWGN rides one vmapped dispatch with
    per-lane split keys, and the batched DATA decode returns the
    decoded PSDU bits (B, 8*n_bytes). `sweep_ber` is the ONE-dispatch
    sweep of exactly this step over a (SNR x seed x profile) grid —
    equal error counts point for point.

    ``profile`` (one name; None/"flat" = today's AWGN path, exactly)
    routes the batch through `channel.impair_profile_point_graph` —
    multipath/SCO/drift before the SAME awgn expression at the SAME
    split keys, seeded bursts after — so the profiled sweep's loop
    twin stays integer-identical. ``sco_track`` is the RX knob."""
    psdus = np.asarray(psdus, np.uint8)
    rate = RATES[rate_mbps]
    n_bytes = psdus.shape[1]
    n_sym = n_symbols(n_bytes, rate)
    names = chanprof.resolve_profiles(profile, 1, use_env=False)
    sco_track = rx.sco_track_enabled(sco_track)
    if batched_tx_enabled(batched_tx):
        frames = tx.encode_batch(psdus, rate_mbps)
    else:
        frames = jnp.stack([jnp.asarray(tx.encode_frame(p, rate_mbps))
                            for p in psdus])
    keys = jax.random.split(jax.random.PRNGKey(seed), psdus.shape[0])
    with dispatch.timed("channel.awgn_batch"):
        if names is None:
            noisy = jax.vmap(
                lambda k, f: channel.awgn(k, f, snr_db))(keys, frames)
        else:
            noisy = channel.impair_profile_point_graph(
                frames, keys, snr_db, names[0])
    with dispatch.timed("rx.decode_batch"):
        got, _ = rx.decode_data_batch(noisy, rate, n_sym, 8 * n_bytes,
                                      sco_track=sco_track)
    return np.asarray(got)


# ------------------------------------------------- device-resident sweeps
#
# The serving workload: BER / waterfall studies over (rate, SNR, seed)
# grids. Point-by-point through the per-batch path every point pays
# the host round trips; here the whole grid rides ONE compiled
# `lax.scan` whose carry — the error-count buffer — is donated, and
# whose per-point body is the same perfect-sync step as
# `loopback_ber_bits` (same split keys, same ops), so the counts agree
# integer-for-integer with a python loop of batches.


def _sweep_point_graph(frames_by_rate, want_bits, rate_list, snr, seed,
                       profiles_key=None, sco_track: bool = False):
    """One sweep point, traced: AWGN at `snr` with keys split from
    `seed` (the SAME key schedule as loopback_ber_bits — lane i's
    noise never depends on which rates ride the sweep), the batched
    DATA decode per rate, and integer error counts vs the known TX
    bits. Returns (n_rates,) int32 — or, with ``profiles_key`` (a
    tuple of profile names), (n_profiles * n_rates,) profile-major:
    each profile column applies its taps/SCO/drift before the SAME
    awgn expression at the SAME keys and its bursts after
    (`channel.impair_profile_point_graph`), while a ``flat`` column
    skips the profile ops entirely — it IS the unprofiled expression,
    so its counts are bit-identical to the profile-less sweep."""
    errs = []
    for pname in (profiles_key or (None,)):
        prof = None if pname is None else chanprof.get_profile(pname)
        for frames, (m, n_sym, n_psdu_bits) in zip(frames_by_rate,
                                                   rate_list):
            keys = jax.random.split(jax.random.PRNGKey(seed),
                                    frames.shape[0])
            if prof is None or prof.is_flat:
                noisy = jax.vmap(
                    lambda k, f, _s=snr: channel.awgn(k, f, _s))(
                        keys, frames)
            else:
                noisy = channel.impair_profile_point_graph(
                    frames, keys, snr, prof.name)
            got, _ = rx.decode_data_batch(noisy, RATES[m], n_sym,
                                          n_psdu_bits,
                                          sco_track=sco_track)
            errs.append(jnp.sum(got != want_bits, dtype=jnp.int32))
    return jnp.stack(errs)


@lru_cache(maxsize=None)
def _jit_sweep_ber(rates_key: tuple, n_bytes: int, donate: bool,
                   profiles_key=None, sco_track: bool = False):
    """ONE compiled sweep per (rate tuple, frame bytes, profile
    tuple, sco_track): encode every rate's frame batch once
    (scan-invariant — XLA hoists it), then `lax.scan` the point step
    over the (snr, seed) grid, writing each point's error counts —
    (n_profiles x n_rates) wide under a profile axis — into the
    carried buffer. STILL one dispatch for the whole rates x SNR x
    profile waterfall. The buffer is DONATED (where the backend
    supports donation), so repeated sweeps reuse its pages instead of
    allocating per call."""
    rate_list = tuple(
        (m, n_symbols(n_bytes, RATES[m]), 8 * n_bytes)
        for m in rates_key)

    def f(bits_b, snr_flat, seed_flat, errbuf):
        # bits_b doubles as the decode's expected output: the TX bits
        # ARE the truth the decoded PSDU is scored against (one upload,
        # one traced operand)
        frames_by_rate = []
        for m, n_sym, _nb in rate_list:
            rate = RATES[m]
            full = jax.vmap(
                lambda b, _r=rate, _sb=tx._sym_bucket(n_sym):
                tx.encode_frame_bits_bucketed(
                    b, jnp.int32(8 * n_bytes), _r, _sb))(bits_b)
            frames_by_rate.append(full[:, :400 + 80 * n_sym])

        def body(carry, xs):
            i, buf = carry
            snr, seed = xs
            e = _sweep_point_graph(frames_by_rate, bits_b,
                                   rate_list, snr, seed,
                                   profiles_key, sco_track)
            buf = jax.lax.dynamic_update_slice(
                buf, e[None], (i, jnp.int32(0)))
            return (i + 1, buf), None

        (_, buf), _ = jax.lax.scan(
            body, (jnp.int32(0), errbuf), (snr_flat, seed_flat))
        return buf

    return jax.jit(f, donate_argnums=(3,) if donate else ())


def _sweep_dispatch(sweep_fn, bits_d, snr_d, seed_d, n_points: int,
                    n_rates: int):
    """One guarded sweep attempt. The error-count carry is DONATED on
    non-CPU backends, so it must be allocated fresh per attempt — a
    retry after a mid-execution transient would otherwise re-pass a
    donated (hence deleted) buffer and turn every retryable failure
    fatal."""
    errbuf = jnp.zeros((n_points, n_rates), jnp.int32)
    return sweep_fn(bits_d, snr_d, seed_d, errbuf)


def sweep_ber(psdus, rates_mbps: Sequence[int],
              snr_grid: Sequence[float], seeds: Sequence[int],
              profiles: Optional[Sequence] = None,
              sco_track: Optional[bool] = None,
              _shard=None) -> np.ndarray:
    """An entire BER waterfall in ONE device dispatch: every rate in
    `rates_mbps` over every (snr, seed) point of the grid, via one
    `lax.scan` of the perfect-sync link step. Returns int64 error
    counts shaped (len(rates), len(snr_grid), len(seeds)); divide by
    ``psdus.shape[0] * 8 * psdus.shape[1]`` for BER. Counts are
    IDENTICAL to a python loop of `loopback_ber_bits` batches over the
    same points (pinned by tests/test_link_fused.py) — vs ~3 host
    round trips per point through that loop and ~5 per point through
    the staged full link.

    ``profiles`` (a sequence of channel-profile names) grows the
    waterfall a PROFILE axis — rates x profiles x SNR x seeds, STILL
    one `lax.scan` dispatch — returning (len(rates), len(profiles),
    len(snr_grid), len(seeds)); the ``"flat"`` column's counts are
    bit-identical to the profile-less sweep by construction (it IS
    the unprofiled expression — tests/test_channel_profiles.py), and
    hostile columns gate the BER envelopes the channel_sweep bench
    stage records. ``sco_track`` opts every column's decode into the
    pilot phase-ramp tracking (one more cache-key bit). None keeps
    today's 3-axis return exactly.

    `_shard` (internal — `sweep_ber_sharded` passes it) is a callable
    placing the lane-axis arrays on a device mesh before the call."""
    psdus = np.asarray(psdus, np.uint8)
    if psdus.ndim != 2:
        raise ValueError("psdus must be (B, n_bytes)")
    b, n_bytes = psdus.shape
    rates_key = tuple(int(m) for m in rates_mbps)
    profiles_key = None if profiles is None else tuple(
        chanprof.get_profile(p).name for p in profiles)
    if profiles_key == ():
        # a zero-width profile axis would compile a zero-column error
        # buffer and die deep in the reshape — a caller bug, not a
        # backend fault, so fail HERE with the fix in the message
        raise ValueError("profiles must be a non-empty sequence of "
                         "profile names, or None for the unprofiled "
                         "3-axis sweep")
    n_prof = 1 if profiles_key is None else len(profiles_key)
    sco_track = rx.sco_track_enabled(sco_track)
    bits = np.stack([tx._host_psdu_bits(p, False) for p in psdus])
    snrs = np.asarray(snr_grid, np.float32)
    seed_arr = np.asarray(seeds, np.int32)
    # the scanned point order is (snr major, seed minor)
    snr_flat = np.repeat(snrs, seed_arr.shape[0])
    seed_flat = np.tile(seed_arr, snrs.shape[0])
    n_points = snr_flat.shape[0]
    # shape/dtype witness for note_site only (the REAL donated carry
    # is allocated fresh per attempt inside _sweep_dispatch): a host
    # array carries the aval without a wasted device allocation
    errbuf = np.zeros((n_points, n_prof * len(rates_key)), np.int32)
    bits_d = jnp.asarray(bits)
    if _shard is not None:
        bits_d = _shard(bits_d)
    donate = jax.devices()[0].platform != "cpu"   # no-op (+warn) on CPU
    sweep_fn = _jit_sweep_ber(rates_key, n_bytes, donate,
                              profiles_key, sco_track)
    snr_d = jnp.asarray(snr_flat)
    seed_d = jnp.asarray(seed_flat)
    programs.note_site("link.sweep", sweep_fn, bits_d, snr_d, seed_d,
                       errbuf)

    def _shape(errs):
        # (points, P*R) profile-major -> (R, S, K) or (R, P, S, K)
        errs = errs.reshape(snrs.shape[0], seed_arr.shape[0], n_prof,
                            len(rates_key))
        out = np.transpose(errs, (3, 2, 0, 1))
        return out[:, 0] if profiles_key is None else out

    from ziria_tpu.runtime import resilience
    try:
        # guarded (runtime/resilience): transient failures retry to
        # the identical counts (pure graph, fixed keys); a fatal one
        # degrades to the python loop of per-batch link steps — the
        # pinned integer-identical twin (test_link_fused), recorded.
        # The dispatch wrapper allocates the DONATED carry buffer
        # fresh per attempt: a retry after a mid-execution failure
        # must not re-pass a donated (hence deleted) buffer
        out = resilience.guarded(
            "link.sweep", _sweep_dispatch, sweep_fn, bits_d, snr_d,
            seed_d, n_points, n_prof * len(rates_key))
    except resilience.DispatchFailed:
        _note_link_degraded("link.sweep_degraded")
        return _shape(_sweep_ber_loop(psdus, rates_key, snr_flat,
                                      seed_flat, bits, profiles_key,
                                      sco_track))
    # host pull outside the timed block (jaxlint R2): the site times
    # the dispatch, not the device wait. On an async backend a
    # mid-execution failure surfaces at THIS pull — one guarded
    # re-dispatch (fresh donated buffer), then the loop twin
    try:
        errs = np.asarray(out, np.int64)
    except Exception:            # noqa: BLE001 - async loss
        try:
            out = resilience.guarded(
                "link.sweep", _sweep_dispatch, sweep_fn, bits_d,
                snr_d, seed_d, n_points, n_prof * len(rates_key))
            errs = np.asarray(out, np.int64)
        except Exception:        # noqa: BLE001 - degrade to the loop
            _note_link_degraded("link.sweep_degraded")
            return _shape(_sweep_ber_loop(psdus, rates_key, snr_flat,
                                          seed_flat, bits,
                                          profiles_key, sco_track))
    dispatch.record_gauge("link.degraded_mode", 0.0)   # healthy pass
    return _shape(errs)


def _sweep_ber_loop(psdus, rates_key, snr_flat, seed_flat, bits,
                    profiles_key=None,
                    sco_track: bool = False) -> np.ndarray:
    """The sweep's degraded twin: the python loop of per-batch
    `loopback_ber_bits` steps over the same (snr, seed[, profile])
    points — the exact loop `sweep_ber` is pinned integer-identical
    against (loopback_ber_bits applies a point's profile through the
    SAME `impair_profile_point_graph` at the SAME split keys). ~3
    host round trips per point instead of one total, but counts are
    bit-identical; used only when the compiled sweep fails for good.
    Returns flat (points, n_prof * n_rates) counts, profile-major —
    the caller owns the waterfall reshape."""
    n_rates = len(rates_key)
    profs = profiles_key or (None,)
    errs = np.zeros((len(snr_flat), len(profs) * n_rates), np.int64)
    for p, (snr, seed) in enumerate(zip(snr_flat, seed_flat)):
        for pi, pname in enumerate(profs):
            for r, m in enumerate(rates_key):
                got = loopback_ber_bits(psdus, m, float(snr),
                                        int(seed), profile=pname,
                                        sco_track=sco_track)
                errs[p, pi * n_rates + r] = int((got != bits).sum())
    return errs


def sweep_ber_sharded(psdus, rates_mbps: Sequence[int],
                      snr_grid: Sequence[float], seeds: Sequence[int],
                      mesh=None, axis: str = "dp",
                      profiles: Optional[Sequence] = None,
                      sco_track: Optional[bool] = None) -> np.ndarray:
    """`sweep_ber` with the frame-lane axis sharded over a device mesh
    (`parallel/batch.frame_mesh()` by default — every visible chip):
    each device encodes/impairs/decodes its shard of lanes, XLA
    inserts the error-count reduction. Error counts are exact integer
    sums, so the result is bit-identical to the single-device sweep on
    ANY mesh shape — on 1 device this IS `sweep_ber` — and the frame
    batch must divide the mesh (`shard_batch`'s rule). The MULTICHIP
    dryrun (`__graft_entry__.dryrun_multichip`) pins the multi-device
    path; `parallel/batch.data_parallel` is the same placement pattern
    this reuses. The profile axis shards with it (per-lane profile
    ops are lane-local — no new collectives)."""
    from ziria_tpu.parallel import batch as pbatch

    if mesh is None:
        mesh = pbatch.frame_mesh()
    return sweep_ber(psdus, rates_mbps, snr_grid, seeds,
                     profiles=profiles, sco_track=sco_track,
                     _shard=lambda x: pbatch.shard_batch(mesh, x, axis))
