"""Device-resident TX → channel → RX loopback link.

The closed loop the reference ran over SORA/BladeRF hardware (Sora's
NSDI 2009 real-time link; the Ziria transceiver demo drives it
in-language) — here the "air" is the batched synthetic channel and the
whole N-frame round trip compiles to a handful of device programs:

    tx.encode_many          ONE vmap(lax.switch) mixed-rate encode
    channel.impair_many     ONE vmapped per-lane AWGN/CFO/delay
    rx.acquire_batch        ONE vmapped detect/align/CFO/SIGNAL
    rx.gather_segments_many ONE gather+derotate at the common bucket
    rx.decode_data_mixed    ONE mixed-rate DATA decode

— ~5 device dispatches for any N-frame, all-rates, multi-SNR batch,
with the sample arrays staying device-resident between stages (the
TX batch never crosses the host link until the decoded bits come
back). That makes BER-waterfall-style sweeps — this repo's serving
workload — O(1)-dispatch in the batch size.

``batched_tx=False`` (or ``--no-batched-tx`` / ``ZIRIA_BATCHED_TX=0``
through the CLI's scoped-env pattern) runs the per-frame oracle loop
instead: encode_frame + single-lane channel + rx.receive per frame,
>= 5 dispatches per lane — bit-identical lane for lane to the batched
path (tests/test_tx_batched.py pins it; tools/rx_dispatch_bench.py
``link_loopback_stats`` measures it).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ziria_tpu.backend import framebatch
from ziria_tpu.phy import channel
from ziria_tpu.phy.wifi import rx, tx
from ziria_tpu.phy.wifi.params import RATES, n_symbols


def batched_tx_enabled(batched_tx: Optional[bool] = None) -> bool:
    """The ONE reading of the --batched-tx / ZIRIA_BATCHED_TX knob
    (default ON), shared by every TX-batch surface."""
    if batched_tx is not None:
        return batched_tx
    return os.environ.get("ZIRIA_BATCHED_TX", "1") != "0"


def transmit_many(psdus: Sequence, rates_mbps: Sequence[int],
                  add_fcs: bool = False,
                  batched_tx: Optional[bool] = None) -> List[np.ndarray]:
    """N mixed-rate, mixed-length frames -> per-frame sample arrays at
    their true lengths: ONE encode_many dispatch plus one batched
    copy-out (default), or the per-frame encode_frame oracle loop
    (``ZIRIA_BATCHED_TX=0``). Bit-identical either way — including
    the empty batch, which is [] in both modes (receive_many's
    convention), never a mode-dependent raise."""
    if not len(psdus):
        return []
    if not batched_tx_enabled(batched_tx):
        return [np.asarray(tx.encode_frame(p, m, add_fcs=add_fcs))
                for p, m in zip(psdus, rates_mbps)]
    txb = tx.encode_many(psdus, rates_mbps, add_fcs=add_fcs)
    arr = np.asarray(txb.samples[:len(psdus)])   # pad rows never move
    return [arr[i, :int(v)] for i, v in enumerate(txb.n_valid)]


def _lane_param(v, n: int, dtype) -> np.ndarray:
    return np.broadcast_to(np.asarray(v, dtype), (n,)).copy()


def loopback_many(psdus: Sequence, rates_mbps: Sequence[int],
                  snr_db=np.inf, cfo=0.0, delay=0, seed: int = 0,
                  add_fcs: bool = False, check_fcs: bool = False,
                  batched_tx: Optional[bool] = None,
                  viterbi_window: int = None,
                  viterbi_metric: str = None) -> List:
    """The full N-frame mixed-rate loopback: encode → per-lane channel
    impairments → batched acquire → gather → mixed-rate decode, in ~5
    device dispatches total, arrays device-resident between stages.

    ``snr_db``/``cfo``/``delay`` are scalars or per-lane sequences
    (``np.inf`` SNR disables noise exactly); lane noise keys derive
    from ``seed`` by counter fold-in, so lane i sees the same channel
    whether it runs batched or alone. Returns per-frame
    :class:`rx.RxResult`, lane-for-lane bit-identical to the per-frame
    oracle loop (``batched_tx=False``: encode_frame + single-lane
    `channel.impair_graph` + `rx.receive` per frame)."""
    n = len(psdus)
    if len(rates_mbps) != n:
        raise ValueError(f"{n} PSDUs but {len(rates_mbps)} rates")
    if n == 0:
        return []          # match receive_many's empty-batch behavior
    snr = _lane_param(snr_db, n, np.float32)
    eps = _lane_param(cfo, n, np.float32)
    dly = _lane_param(delay, n, np.int32)
    if (dly < 0).any():
        raise ValueError("negative delay")
    # ONE capture length for the whole link, batched or not: the
    # common symbol bucket's frame length plus the worst delay, at the
    # receiver's capture-bucket rule. The per-frame oracle MUST use
    # the same length — a lane's noise field is drawn over the whole
    # buffer, so per-lane buffer sizes would change the draws and the
    # bit-identity contract with the batched path.
    fcs_bytes = 4 if add_fcs else 0
    sym_b = max(tx._sym_bucket(n_symbols(
        int(np.asarray(p).size) + fcs_bytes, RATES[m]))
        for p, m in zip(psdus, rates_mbps))
    l_cap = rx._stream_bucket(400 + 80 * sym_b + int(dly.max()))

    if not batched_tx_enabled(batched_tx):
        # the per-frame oracle: same channel physics, one frame at a
        # time, through the per-capture receiver
        results = []
        for i in range(n):
            s = np.asarray(tx.encode_frame(psdus[i], rates_mbps[i],
                                           add_fcs=add_fcs))
            cap = channel.impair_one(s, snr[i], eps[i], int(dly[i]),
                                     seed, i, l_cap)
            results.append(rx.receive(np.asarray(cap),
                                      check_fcs=check_fcs,
                                      viterbi_window=viterbi_window,
                                      viterbi_metric=viterbi_metric))
        return results

    txb = tx.encode_many(psdus, rates_mbps, add_fcs=add_fcs)
    rows = int(txb.samples.shape[0])
    assert int(txb.samples.shape[1]) == 400 + 80 * sym_b
    nv_tx = np.full((rows,), txb.n_valid[0], np.int32)
    nv_tx[:n] = txb.n_valid

    def _pad_rows(a):
        out = np.concatenate([a, np.broadcast_to(a[0], (rows - n,)
                                                 + a.shape[1:])])
        return out

    caps = channel.impair_many(
        txb.samples, nv_tx, _pad_rows(snr), _pad_rows(eps),
        _pad_rows(dly), seed, out_len=l_cap)
    return framebatch.receive_many_device(
        caps, n, check_fcs=check_fcs, viterbi_window=viterbi_window,
        viterbi_metric=viterbi_metric)


def loopback_ber_bits(psdus, rate_mbps: int, snr_db: float, seed: int,
                      batched_tx: Optional[bool] = None) -> np.ndarray:
    """Perfect-sync single-rate BER loopback — the statistical lane of
    the link (BER waterfalls measure the equalize/demap/Viterbi chain,
    not packet detection): (B, n_bytes) PSDUs encode in ONE dispatch
    (`tx.encode_batch`; per-frame `encode_frame` loop when batched TX
    is off — bit-identical), AWGN rides one vmapped dispatch with
    per-lane split keys, and the batched DATA decode returns the
    decoded PSDU bits (B, 8*n_bytes)."""
    psdus = np.asarray(psdus, np.uint8)
    rate = RATES[rate_mbps]
    n_bytes = psdus.shape[1]
    n_sym = n_symbols(n_bytes, rate)
    if batched_tx_enabled(batched_tx):
        frames = tx.encode_batch(psdus, rate_mbps)
    else:
        frames = jnp.stack([jnp.asarray(tx.encode_frame(p, rate_mbps))
                            for p in psdus])
    keys = jax.random.split(jax.random.PRNGKey(seed), psdus.shape[0])
    noisy = jax.vmap(
        lambda k, f: channel.awgn(k, f, snr_db))(keys, frames)
    got, _ = rx.decode_data_batch(noisy, rate, n_sym, 8 * n_bytes)
    return np.asarray(got)
