"""Named physical-channel profiles — the seeded channel-chaos registry.

PRs 12-14 made the runtime survive *software* faults; a radio's
dominant faults are *physical*: frequency-selective multipath, a
sampling-clock offset (SCO) between TX DAC and RX ADC, Doppler /
oscillator drift, and interference bursts. This module is the
jax-free catalogue of those impairments — a :class:`ChannelProfile`
names a deterministic parameter set, and the jax application graphs
live in :mod:`ziria_tpu.phy.channel` (``impair_profile_graph``); the
chaos layer (:mod:`ziria_tpu.utils.faults`, kind ``channel``) and the
``tools/chaos_smoke.py`` precommit gate consume this module WITHOUT
importing jax, the same no-jax discipline as the lint subcommand.

The identity anchor is ``flat``: :func:`resolve_profiles` normalizes
an all-``flat`` request to ``None`` — the unprofiled code path — so
``profile="flat"`` is bit-identical to today's AWGN+CFO+delay channel
*by construction* (no new compiled program, no new dispatch). A flat
lane riding a MIXED profiled batch goes through the profiled graph
with neutral parameters, which are exact identities op for op
(one-hot FIR taps, zero-fraction resample, zero phase, zero burst
amplitude); tests/test_channel_profiles.py pins that lane bitwise
against the unprofiled graph EAGERLY and to one float32 ulp across
the separately compiled programs (XLA FMA contraction can round the
shared ops differently between two jits).

Knob: ``--channel-profile NAME`` / ``ZIRIA_CHANNEL_PROFILE`` (the cli
scoped-env pattern; :func:`env_channel_profile` is the single reader,
jaxlint R4) sets the default profile of the stimulus surfaces that
resolve with the env default: ``link.stream_many[_multi]``,
``link.loopback_many``, and ``serve.synth_load``. ``link.sweep_ber``
deliberately does NOT consult it — its profile axis changes the
RESULT SHAPE, and a shape that silently follows an env var would be
a footgun; pass ``profiles=[...]`` explicitly there.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Sequence, Tuple, Union

import numpy as np


class ChannelProfile(NamedTuple):
    """One named physical-channel parameter set. All fields are plain
    data (hashable, jax-free); the application order in the impair
    graphs is taps -> SCO resample -> CFO+drift phase -> delay ->
    AWGN -> bursts (docs/robustness.md)."""
    name: str
    #: causal complex FIR taps as (re, im) pairs, unit total energy
    #: (sum |h|^2 == 1, so the SNR reference is tap-invariant); tap k
    #: is the path at k samples excess delay — keep the spread under
    #: the 16-sample cyclic prefix or the equalizer model breaks
    taps: Tuple[Tuple[float, float], ...] = ((1.0, 0.0),)
    #: sampling-clock offset as a fraction (80e-6 = 80 ppm): the RX
    #: resamples at positions n * (1 + sco) — a slowly growing timing
    #: drift, i.e. a per-subcarrier phase ramp growing over the frame
    sco: float = 0.0
    #: residual-CFO / Doppler drift in rad/sample^2: the oscillator
    #: offset itself drifts, phase(n) = eps*n + drift*n^2/2
    drift: float = 0.0
    #: seeded interference bursts: a burst_len-sample noise burst
    #: every burst_every samples (0 = none), at burst_db relative to
    #: the lane's signal power, position offset drawn from the lane
    #: key (deterministic per (seed, lane))
    burst_every: int = 0
    burst_len: int = 0
    burst_db: float = 0.0

    @property
    def is_flat(self) -> bool:
        """True when every parameter is the exact-identity neutral
        value (the profiled graph reproduces the unprofiled one
        bitwise; `resolve_profiles` short-circuits such requests to
        the unprofiled path entirely)."""
        return (len(self.taps) == 1 and self.taps[0] == (1.0, 0.0)
                and self.sco == 0.0 and self.drift == 0.0
                and self.burst_every == 0)


def _norm_taps(raw: Sequence[complex]) -> Tuple[Tuple[float, float], ...]:
    """Normalize a complex tap list to unit total energy and freeze it
    as (re, im) pair tuples (hashable — profile names ride jit-factory
    cache keys, and the tap constants bake into the compiled graph)."""
    e = math.sqrt(sum(abs(t) ** 2 for t in raw))
    return tuple((float(t.real / e), float(t.imag / e)) for t in raw)


def _exp_taps(n: int, decay: float, phase_step: float) -> Tuple:
    """Exponential-decay tap set with golden-angle-style phases (fixed
    constants, nothing drawn): tap k = decay^k * e^{j*k*phase_step}.
    Irrational-looking phases keep the frequency response generic —
    deep fades, no contrived symmetry."""
    return _norm_taps([decay ** k * complex(math.cos(k * phase_step),
                                            math.sin(k * phase_step))
                       for k in range(n)])


#: the named profile registry, flat -> severe delay spread plus the
#: non-FIR physical faults. docs/robustness.md carries the
#: kind -> seam -> gate taxonomy row for each.
CHANNEL_PROFILES = {
    # the identity anchor: today's AWGN+CFO+delay channel, untouched
    "flat": ChannelProfile("flat"),
    # light two-path fading, 1-sample excess delay
    "mild": ChannelProfile("mild", taps=_norm_taps(
        [1.0, 0.35 * complex(math.cos(2.1), math.sin(2.1))])),
    # moderate urban-style spread: 5 paths over 4 samples
    "urban": ChannelProfile("urban", taps=_exp_taps(5, 0.62, 2.399)),
    # severe frequency-selective spread: 10 paths over 9 samples
    # (still inside the 16-sample CP), deep in-band fades
    "severe": ChannelProfile("severe", taps=_exp_taps(10, 0.78, 2.399)),
    # sampling-clock offset alone: 80 ppm timing drift
    "sco": ChannelProfile("sco", sco=80e-6),
    # residual-CFO / Doppler drift alone
    "doppler": ChannelProfile("doppler", drift=2e-7),
    # seeded interference bursts at signal power, ~8% duty
    "bursty": ChannelProfile("bursty", burst_every=1200, burst_len=96,
                             burst_db=0.0),
    # everything at once, each dialed back: the campaign profile
    "hostile": ChannelProfile("hostile", taps=_exp_taps(5, 0.62, 2.399),
                              sco=40e-6, drift=1e-7, burst_every=2000,
                              burst_len=64, burst_db=-3.0),
}

ProfileLike = Union[str, ChannelProfile]


def get_profile(p: ProfileLike) -> ChannelProfile:
    """Name (or a REGISTRY ChannelProfile, passed through) ->
    ChannelProfile; unknown names raise a ValueError NAMING the known
    profiles (the CLI surfaces it as a flag error, never a silent
    flat run). Ad-hoc ChannelProfile objects are rejected loudly:
    every downstream consumer (jit cache keys, the chaos grammar, the
    checkpoint fingerprints) identifies a profile BY NAME, so an
    unregistered object would silently decay to whatever its name
    looks up — register it in CHANNEL_PROFILES instead."""
    if isinstance(p, ChannelProfile):
        reg = CHANNEL_PROFILES.get(p.name)
        if reg is None or reg != p:
            raise ValueError(
                f"ChannelProfile {p.name!r} is not the registry entry "
                f"of that name; ad-hoc profiles are not supported — "
                f"profiles travel BY NAME through compile-cache keys "
                f"and the chaos grammar, so add it to "
                f"profiles.CHANNEL_PROFILES first "
                f"(known: {', '.join(sorted(CHANNEL_PROFILES))})")
        return reg
    prof = CHANNEL_PROFILES.get(p)
    if prof is None:
        raise ValueError(
            f"unknown channel profile {p!r} "
            f"(known: {', '.join(sorted(CHANNEL_PROFILES))})")
    return prof


def parse_profile_spec(text: str) -> Tuple[str, ...]:
    """Parse the ``--channel-profile`` grammar: a single name or a
    comma-separated per-lane list (``"flat,severe"`` — lane i rides
    name i, cycling when the lane count exceeds the list). Validates
    every name; returns the name tuple."""
    names = tuple(s.strip() for s in text.split(",") if s.strip())
    if not names:
        raise ValueError("empty channel-profile spec")
    for n in names:
        get_profile(n)
    return names


def env_channel_profile() -> Optional[Tuple[str, ...]]:
    """The ONE reading of the ``ZIRIA_CHANNEL_PROFILE`` knob (the
    CLI's ``--channel-profile`` writes it via the scoped-env pattern).
    Returns the parsed name tuple, or None when unset/empty."""
    import os

    text = os.environ.get("ZIRIA_CHANNEL_PROFILE")
    if not text:
        return None
    return parse_profile_spec(text)


def resolve_profiles(profile, n_lanes: int,
                     use_env: bool = True) -> Optional[Tuple[str, ...]]:
    """Resolve a channel-profile request to per-lane profile names, or
    None for the unprofiled path. ``profile`` is None (-> the
    ``ZIRIA_CHANNEL_PROFILE`` env default, itself usually unset), a
    name / ChannelProfile, or a per-lane sequence (shorter sequences
    cycle). An all-``flat`` resolution returns None — flat IS the
    unprofiled channel, by construction (module docstring), so no new
    program compiles and the dispatch budget is untouched.

    ``use_env=False`` skips the env default: the low-level channel
    surfaces (`channel.impair_many/one/stream`) pass it so a TOP-level
    surface that already resolved the knob — where an explicit
    ``"flat"`` legitimately collapsed to None — can never have the
    env default resurrected underneath it."""
    if profile is None:
        if not use_env:
            return None
        profile = env_channel_profile()
        if profile is None:
            return None
    if isinstance(profile, str):
        # a bare name or the CLI's comma grammar ("flat,severe")
        profile = parse_profile_spec(profile)
    elif isinstance(profile, ChannelProfile):
        profile = (profile,)
    names = tuple(get_profile(p).name for p in profile)
    if not names:
        return None
    names = tuple(names[i % len(names)] for i in range(n_lanes))
    if all(get_profile(n).is_flat for n in names):
        return None
    return names


def lane_arrays(names: Sequence[str]):
    """Per-lane profile names -> the stacked numpy parameter arrays
    the vmapped impair graph consumes: ``(taps (R, T, 2), sco (R,),
    drift (R,), burst_every (R,), burst_len (R,), burst_db (R,))``
    with T the max tap count (shorter sets zero-padded — trailing
    zero taps are exact no-ops in the FIR). Host-side constants: the
    jit factories bake them into the compiled graph, keyed by the
    name tuple."""
    profs = [get_profile(n) for n in names]
    t_max = max(len(p.taps) for p in profs)
    taps = np.zeros((len(profs), t_max, 2), np.float32)
    for i, p in enumerate(profs):
        taps[i, : len(p.taps)] = np.asarray(p.taps, np.float32)
    return (taps,
            np.asarray([p.sco for p in profs], np.float32),
            np.asarray([p.drift for p in profs], np.float32),
            np.asarray([p.burst_every for p in profs], np.int32),
            np.asarray([p.burst_len for p in profs], np.int32),
            np.asarray([p.burst_db for p in profs], np.float32))


def np_apply_taps(x: np.ndarray, prof: ChannelProfile) -> np.ndarray:
    """Host-side (numpy, float64) complex-FIR application of a
    profile's taps — the streaming-stimulus twin of the jax
    ``channel.multipath`` graph and the oracle the unit test pins it
    against. (n, 2) f32 in -> (n, 2) f32 out, same length, causal."""
    if len(prof.taps) == 1 and prof.taps[0] == (1.0, 0.0):
        return np.asarray(x, np.float32)
    xc = x[:, 0].astype(np.float64) + 1j * x[:, 1].astype(np.float64)
    t = np.asarray([tr + 1j * ti for tr, ti in prof.taps],
                   np.complex128)
    yc = np.convolve(xc, t)[: xc.shape[0]]
    return np.stack([yc.real, yc.imag], axis=-1).astype(np.float32)


def np_apply_sco(x: np.ndarray, sco: float) -> np.ndarray:
    """Host-side SCO resample: linear interpolation at positions
    ``n * (1 + sco)`` (float64 positions — streams run to millions of
    samples). ``sco == 0`` returns the input unchanged."""
    if not sco:
        return np.asarray(x, np.float32)
    n = x.shape[0]
    pos = np.arange(n, dtype=np.float64) * (1.0 + float(sco))
    base = np.arange(n, dtype=np.float64)
    return np.stack(
        [np.interp(pos, base, x[:, 0].astype(np.float64)),
         np.interp(pos, base, x[:, 1].astype(np.float64))],
        axis=-1).astype(np.float32)


def np_apply_drift(x: np.ndarray, drift: float) -> np.ndarray:
    """Host-side Doppler/oscillator-drift rotation: the quadratic
    phase ``drift * n^2 / 2`` (float64 trig). The ONE standalone
    host form of the drift term — `channel.impair_stream` folds the
    same phase into its combined CFO rotation instead (one rotation,
    one f32 cast), which is the only reason it does not call this."""
    if not drift:
        return np.asarray(x, np.float32)
    t = np.arange(x.shape[0], dtype=np.float64)
    theta = 0.5 * float(drift) * t * t
    c, s = np.cos(theta), np.sin(theta)
    return np.stack([x[:, 0] * c - x[:, 1] * s,
                     x[:, 0] * s + x[:, 1] * c],
                    axis=-1).astype(np.float32)


def np_burst_mask(n: int, prof: ChannelProfile,
                  offset: int) -> np.ndarray:
    """The ONE host-side burst-window rule (boolean (n,)): sample i
    is in-burst iff ``(i - offset) % burst_every < burst_len``. Both
    host burst appliers (`channel.impair_stream` and the chaos
    `channel` kind) call this, so the window math can never drift
    from itself — only the offset's RNG differs (jax fold-in vs the
    plan hash), injected by the caller."""
    return ((np.arange(n) - int(offset)) % prof.burst_every) \
        < prof.burst_len


def np_burst_amp(p_sig: float, prof: ChannelProfile) -> float:
    """The ONE host-side burst amplitude rule: per-component noise
    std for a burst at ``burst_db`` relative to signal power `p_sig`
    (the same ``sqrt(p * 10^(db/10) / 2)`` the traced `_burst_graph`
    computes)."""
    return float(np.sqrt(max(p_sig, 0.0)
                         * 10.0 ** (prof.burst_db / 10.0) / 2.0))
