"""Channel impairment models for loopback testing (pair format).

The reference tests its RX against TX output passed through file-based
golden streams (SURVEY.md §4); real-channel impairments came from
SORA/BladeRF hardware. Here the channel is synthetic and explicit: AWGN,
carrier frequency offset, integer delay (with noise padding), phase
offset, and multipath FIR — everything jax, batchable over frames.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ziria_tpu.ops import cplx


def awgn(key, samples, snr_db: float) -> jnp.ndarray:
    """Add complex white noise at the given SNR (dB) relative to the
    average sample power."""
    x = jnp.asarray(samples, jnp.float32)
    p_sig = jnp.mean(cplx.cabs2(x))
    p_noise = p_sig / (10.0 ** (snr_db / 10.0))
    noise = jax.random.normal(key, x.shape) * jnp.sqrt(p_noise / 2.0)
    return x + noise


def apply_cfo(samples, eps: float) -> jnp.ndarray:
    """Rotate samples by e^{+j*eps*n} (eps radians/sample)."""
    x = jnp.asarray(samples, jnp.float32)
    n = jnp.arange(x.shape[0], dtype=jnp.float32)
    return cplx.cmul(x, cplx.cexp(eps * n))


def apply_phase(samples, theta: float) -> jnp.ndarray:
    x = jnp.asarray(samples, jnp.float32)
    return cplx.cmul(x, jnp.broadcast_to(cplx.cexp(jnp.float32(theta)),
                                         x.shape))


def delay(key, samples, n_before: int, n_after: int = 0,
          noise_db: float = -30.0) -> jnp.ndarray:
    """Pad the frame with low-level noise before/after (models idle air
    time around a detected packet)."""
    x = jnp.asarray(samples, jnp.float32)
    p_sig = jnp.mean(cplx.cabs2(x))
    amp = jnp.sqrt(p_sig * 10.0 ** (noise_db / 10.0) / 2.0)
    pad = jax.random.normal(key, (n_before + n_after, 2)) * amp
    return jnp.concatenate([pad[:n_before], x, pad[n_before:]], axis=0)


def multipath(samples, taps_pair) -> jnp.ndarray:
    """Complex FIR channel: taps_pair (L, 2). Causal, same length out."""
    x = jnp.asarray(samples, jnp.float32)
    t = jnp.asarray(taps_pair, jnp.float32)
    n = x.shape[0]

    def conv(u, v):
        return jnp.convolve(u, v, precision="highest")[:n]

    re = conv(x[:, 0], t[:, 0]) - conv(x[:, 1], t[:, 1])
    im = conv(x[:, 0], t[:, 1]) + conv(x[:, 1], t[:, 0])
    return jnp.stack([re, im], axis=-1)


def impaired_capture(mbps: int, n_bytes: int, seed: int,
                     cfo: float = 0.002, pre: int = 60, post: int = 40,
                     noise: float = 0.03, floor: float = 0.02,
                     scale: float = 1024.0, add_fcs: bool = False):
    """A deterministic receiver test vector: one TX frame with CFO,
    surrounded by noise, plus AWGN, quantized to the complex16 wire
    format (int16 IQ pairs). Returns (psdu_bytes, samples).

    The single source of truth for the capture recipe the receiver
    tests AND the checked-in wifi_rx golden use — three copies of this
    pipeline had already appeared before it was hoisted here.
    """
    import numpy as np

    from ziria_tpu.phy.wifi import tx

    rng = np.random.default_rng(seed)
    psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
    frame = np.asarray(tx.encode_frame(psdu, mbps, add_fcs=add_fcs))
    x = np.concatenate([
        rng.normal(scale=floor, size=(pre, 2)).astype(np.float32),
        np.asarray(apply_cfo(jnp.asarray(frame), cfo)),
        rng.normal(scale=floor, size=(post, 2)).astype(np.float32)])
    x = (x + rng.normal(scale=noise, size=x.shape)).astype(np.float32)
    xi = np.clip(np.round(x * scale), -32768, 32767).astype(np.int16)
    return psdu, xi
