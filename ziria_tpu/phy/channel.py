"""Channel impairment models for loopback testing (pair format).

The reference tests its RX against TX output passed through file-based
golden streams (SURVEY.md §4); real-channel impairments came from
SORA/BladeRF hardware. Here the channel is synthetic and explicit: AWGN,
carrier frequency offset, integer delay (with noise padding), phase
offset, and multipath FIR — everything jax, batchable over frames.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ziria_tpu.ops import cplx
from ziria_tpu.phy import profiles as chanprof


def awgn(key, samples, snr_db: float) -> jnp.ndarray:
    """Add complex white noise at the given SNR (dB) relative to the
    average sample power."""
    x = jnp.asarray(samples, jnp.float32)
    p_sig = jnp.mean(cplx.cabs2(x))
    p_noise = p_sig / (10.0 ** (snr_db / 10.0))
    noise = jax.random.normal(key, x.shape) * jnp.sqrt(p_noise / 2.0)
    return x + noise


def apply_cfo(samples, eps: float) -> jnp.ndarray:
    """Rotate samples by e^{+j*eps*n} (eps radians/sample)."""
    x = jnp.asarray(samples, jnp.float32)
    n = jnp.arange(x.shape[0], dtype=jnp.float32)
    return cplx.cmul(x, cplx.cexp(eps * n))


def apply_phase(samples, theta: float) -> jnp.ndarray:
    x = jnp.asarray(samples, jnp.float32)
    return cplx.cmul(x, jnp.broadcast_to(cplx.cexp(jnp.float32(theta)),
                                         x.shape))


def delay(key, samples, n_before: int, n_after: int = 0,
          noise_db: float = -30.0) -> jnp.ndarray:
    """Pad the frame with low-level noise before/after (models idle air
    time around a detected packet)."""
    x = jnp.asarray(samples, jnp.float32)
    p_sig = jnp.mean(cplx.cabs2(x))
    amp = jnp.sqrt(p_sig * 10.0 ** (noise_db / 10.0) / 2.0)
    pad = jax.random.normal(key, (n_before + n_after, 2)) * amp
    return jnp.concatenate([pad[:n_before], x, pad[n_before:]], axis=0)


# ------------------------------------------------- batched link channel
#
# The device-resident loopback link (phy/link.py) needs the channel as
# ONE vmapped dispatch over a frame batch with PER-LANE parameters —
# the composable helpers above are host-loop shaped (python-scalar
# params, shape-changing delay). `impair_graph` is the same physics at
# a fixed geometry: CFO rotation, integer delay as a roll into the
# zero tail, and AWGN at the lane's own SNR, every parameter a traced
# per-lane scalar. Keys derive from one seed by lane-counter fold-in,
# so lane i's noise never depends on the batch composition.


def impair_graph(x, n_valid, snr_db, eps, delay, key) -> jnp.ndarray:
    """One lane of the batched link channel, all shapes static.

    x: (L, 2) TX samples, only the first `n_valid` (traced int32) of
    which are the frame — anything past is masked to zero HERE (an
    encode_many lane's bucket pad carries garbage symbols, which must
    neither transmit nor count as signal power); snr_db/eps/delay
    (traced scalars): the lane's own AWGN SNR (``inf`` disables noise
    exactly — the noise term multiplies to 0), CFO in rad/sample, and
    integer sample delay (must satisfy delay + n_valid <= L, or the
    frame tail wraps around). Returns (L, 2). Under ``vmap`` this is
    the whole channel of an N-frame batch in one dispatch;
    single-lane calls are the per-frame oracle the batched path is
    judged against — the mask makes the two agree bit-for-bit
    whatever the caller's pad region holds (the select passes real
    samples through untouched)."""
    x = jnp.asarray(x, jnp.float32)
    idx = jnp.arange(x.shape[0])
    x = jnp.where((idx < n_valid)[:, None], x, 0.0)
    n = idx.astype(jnp.float32)
    x = cplx.cmul(x, cplx.cexp(jnp.float32(eps) * n))   # zeros stay 0
    x = jnp.roll(x, delay, axis=0)     # circular, but the zero tail
    #                                    makes it a pure shift
    p_sig = jnp.sum(cplx.cabs2(x)) / jnp.maximum(
        jnp.asarray(n_valid, jnp.float32), 1.0)
    p_noise = p_sig / (10.0 ** (jnp.asarray(snr_db, jnp.float32) / 10.0))
    noise = jax.random.normal(key, x.shape) * jnp.sqrt(p_noise / 2.0)
    return x + noise


def lane_key(seed, i):
    """Counter-derived per-lane PRNG key: fold the lane index into the
    batch seed. The same key reaches lane i whether the channel runs
    batched, per-frame, or as a whole stream (`impair_stream`) — the
    bit-identity hinge of the link tests AND the stream/batch seeding
    contract: every noise consumer folds off THIS key, so a lane's
    draws never depend on which surface applies the channel."""
    return jax.random.fold_in(jax.random.PRNGKey(seed), i)


# salts folding the per-lane key into independent draw streams: the
# AWGN consumes the bare lane key (so the profiled and unprofiled
# graphs draw IDENTICAL noise — the flat bit-identity hinge), bursts
# fold these in (position, then the burst noise field)
_BURST_POS_SALT = 0x6B01
_BURST_NOISE_SALT = 0x6B02


def sco_resample_graph(x, sco):
    """Sampling-clock-offset resample, traced: linear interpolation
    at positions ``n * (1 + sco)`` — the RX ADC ticking `sco` faster
    than the TX DAC, a slowly growing timing drift. ``sco == 0``
    reproduces ``x`` exactly (positions are exact integers, the
    interpolation weights collapse to 1/0), the profiled-graph
    neutral-identity argument. Positions past the end extend the last
    sample (the tail is the capture's zero pad anyway)."""
    x = jnp.asarray(x, jnp.float32)
    n = x.shape[0]
    pos = jnp.arange(n, dtype=jnp.float32) \
        * (1.0 + jnp.asarray(sco, jnp.float32))
    i0 = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, n - 1)
    i1 = jnp.clip(i0 + 1, 0, n - 1)
    frac = (pos - i0.astype(jnp.float32))[:, None]
    return x[i0] * (1.0 - frac) + x[i1] * frac


def _burst_graph(x, p_sig, every, blen, bdb, key):
    """Seeded interference bursts, traced: a `blen`-sample wideband
    noise burst every `every` samples at `bdb` dB relative to the
    lane's signal power, burst phase offset drawn from the lane key's
    burst fold-in (deterministic per (seed, lane), independent of the
    AWGN draw). ``every == 0`` adds exactly zero (amp masks to 0.0 —
    finite noise times zero), the neutral-identity argument."""
    every = jnp.asarray(every, jnp.int32)
    on = every > 0
    safe = jnp.maximum(every, 1)
    off = jax.random.randint(
        jax.random.fold_in(key, _BURST_POS_SALT), (), 0, safe)
    idx = jnp.arange(x.shape[0], dtype=jnp.int32)
    in_burst = on & (((idx - off) % safe) < jnp.asarray(blen, jnp.int32))
    amp = jnp.where(
        on,
        jnp.sqrt(p_sig * 10.0 ** (jnp.asarray(bdb, jnp.float32) / 10.0)
                 / 2.0),
        0.0)
    noise = jax.random.normal(
        jax.random.fold_in(key, _BURST_NOISE_SALT), x.shape)
    return x + noise * (amp * in_burst.astype(jnp.float32))[:, None]


def impair_profile_graph(x, n_valid, snr_db, eps, delay, key,
                         taps, sco, drift, burst_every, burst_len,
                         burst_db,
                         with_bursts: bool = True) -> jnp.ndarray:
    """One lane of the PROFILED batched channel — `impair_graph` with
    the physical-layer faults composed in (docs/robustness.md):

        mask pad -> multipath FIR (`taps`) -> SCO resample ->
        CFO + drift phase (theta = eps*n + drift*n^2/2) ->
        integer delay -> AWGN (the lane key, UNCHANGED) ->
        seeded interference bursts (key fold-ins)

    Every profile parameter is a traced per-lane value, so ONE
    compiled graph (per tap count) serves a batch of mixed profiles
    under ``vmap``. At the neutral parameters (one-hot taps, sco =
    drift = 0, burst_every = 0) every added op is an exact identity
    and the AWGN consumes the same key, so a neutral lane is
    BIT-IDENTICAL to `impair_graph` at the op level (pinned eager by
    tests/test_channel_profiles.py; ACROSS separately compiled
    programs XLA's FMA contraction may differ by one float32 ulp —
    the bit-exact ``flat`` guarantee is `resolve_profiles`' collapse
    to the unprofiled path, not this graph). The FIR rings
    `len(taps) - 1` samples past `n_valid`; callers keep
    ``delay + n_valid + len(taps) - 1 <= L`` or the tail wraps."""
    x = jnp.asarray(x, jnp.float32)
    idx = jnp.arange(x.shape[0])
    x = jnp.where((idx < n_valid)[:, None], x, 0.0)
    x = multipath(x, taps)
    x = sco_resample_graph(x, sco)
    n = idx.astype(jnp.float32)
    theta = jnp.asarray(eps, jnp.float32) * n \
        + 0.5 * jnp.asarray(drift, jnp.float32) * n * n
    x = cplx.cmul(x, cplx.cexp(theta))
    x = jnp.roll(x, delay, axis=0)
    p_sig = jnp.sum(cplx.cabs2(x)) / jnp.maximum(
        jnp.asarray(n_valid, jnp.float32), 1.0)
    p_noise = p_sig / (10.0 ** (jnp.asarray(snr_db, jnp.float32) / 10.0))
    noise = jax.random.normal(key, x.shape) * jnp.sqrt(p_noise / 2.0)
    x = x + noise
    if not with_bursts:
        # STATIC skip (callers pass the host-known "no lane bursts"
        # fact): the burst amp is a traced per-lane value, so without
        # this XLA cannot DCE the full-capture normal draw a
        # burst-free profile would multiply by zero
        return x
    return _burst_graph(x, p_sig, burst_every, burst_len, burst_db,
                        key)


def _profile_consts(profile_key):
    """Per-lane profile names -> jnp constant parameter arrays for the
    vmapped profiled graph (None passes through). Host-side: the
    arrays bake into whichever jit closes over them, keyed by the name
    tuple — a handful of name combinations, not one compile per
    parameter value."""
    if profile_key is None:
        return None
    arrs = chanprof.lane_arrays(profile_key)
    return tuple(jnp.asarray(a) for a in arrs)


def impair_many_graph(x_b, n_valid, snr_db, eps, delay, seed,
                      out_len: int, profile_key=None) -> jnp.ndarray:
    """The traced batched channel: pad the TX batch to `out_len`,
    derive per-lane keys from `seed` by counter fold-in, and apply
    every lane's own impairments under one ``vmap`` — the graph
    `_jit_impair_many` jits, exposed as a plain function so larger
    programs can FUSE it (the one-dispatch loopback link traces it
    between the batch encode and the batched receiver).

    ``profile_key`` (a per-lane tuple of channel-profile names, or
    None) routes through `impair_profile_graph` with the profiles'
    taps/SCO/drift/burst parameters as per-lane constants — still ONE
    vmapped graph, same dispatch count; None is today's unprofiled
    graph, untouched."""
    pad = out_len - x_b.shape[1]
    x = jnp.pad(jnp.asarray(x_b, jnp.float32),
                ((0, 0), (0, pad), (0, 0)))
    keys = jax.vmap(lambda i: lane_key(seed, i))(
        jnp.arange(x.shape[0]))
    if profile_key is None:
        return jax.vmap(impair_graph)(x, n_valid, snr_db, eps, delay,
                                      keys)
    taps, sco, drift, b_ev, b_ln, b_db = _profile_consts(profile_key)
    wb = any(chanprof.get_profile(n).burst_every for n in profile_key)
    return jax.vmap(
        lambda xi, nv, s, e, d, k, t, sc, dr, be, bl, bd:
        impair_profile_graph(xi, nv, s, e, d, k, t, sc, dr, be, bl,
                             bd, with_bursts=wb))(
        x, n_valid, snr_db, eps, delay, keys, taps, sco, drift,
        b_ev, b_ln, b_db)


@lru_cache(maxsize=None)
def _jit_impair_many(out_len: int, profile_key=None):
    """ONE jitted `impair_many_graph` per (output length, per-lane
    profile-name tuple) — jit retraces per input shape; the profile
    constants bake into the graph, so the cache key IS the name tuple
    (resolved by the caller, never env-read here — jaxlint R1)."""
    def f(x_b, n_valid, snr_db, eps, delay, seed):
        return impair_many_graph(x_b, n_valid, snr_db, eps, delay,
                                 seed, out_len, profile_key)
    return jax.jit(f)


def impair_many(x_b, n_valid, snr_db, eps, delay, seed,
                out_len: int = None, profile=None) -> jnp.ndarray:
    """Batched per-lane channel: (R, L, 2) device-resident TX batch ->
    (R, out_len, 2) impaired captures in ONE dispatch, staying on
    device for the receiver. Per-lane arrays for n_valid/snr_db/eps/
    delay (scalars broadcast); `seed` one int — lane keys derive by
    counter fold-in (`lane_key`). Bit-identical per lane to a
    single-lane `impair_graph` call with the same key.

    ``profile`` is a channel-profile name, per-lane sequence, or None
    — None means UNPROFILED here: the ``ZIRIA_CHANNEL_PROFILE`` env
    default is deliberately NOT consulted at this low-level surface
    (``use_env=False`` — the top-level surfaces resolve it once, and
    an explicit "flat" there must not have the env resurrected
    underneath; `profiles.resolve_profiles`; all-flat resolves to
    the unprofiled graph by construction). Profiled batches stay ONE
    dispatch; lane i matches `impair_one` at the same profile name
    to within one float32 ulp (separately compiled programs — the
    FMA-contraction rule), exactly when unprofiled."""
    from ziria_tpu.utils import dispatch, programs

    r = int(x_b.shape[0])
    if out_len is None:
        out_len = int(x_b.shape[1])
    profile_key = chanprof.resolve_profiles(profile, r, use_env=False)

    def _vec(v, dtype):
        a = np.broadcast_to(np.asarray(v, dtype), (r,))
        return jnp.asarray(a)

    imp_fn = _jit_impair_many(int(out_len), profile_key)
    imp_args = (x_b, _vec(n_valid, np.int32), _vec(snr_db, np.float32),
                _vec(eps, np.float32), _vec(delay, np.int32),
                jnp.uint32(seed))
    programs.note_site("channel.impair_many", imp_fn, *imp_args)
    with dispatch.timed("channel.impair_many"):
        return imp_fn(*imp_args)


@lru_cache(maxsize=None)
def _jit_impair_one(profile_key=None):
    """ONE jitted single-lane channel per profile name (None = the
    unprofiled `impair_graph`, exactly as before; the name's profile
    constants bake in, resolved by the caller — jaxlint R1)."""
    if profile_key is None:
        return jax.jit(impair_graph)
    taps, sco, drift, b_ev, b_ln, b_db = _profile_consts(
        (profile_key,))
    wb = bool(chanprof.get_profile(profile_key).burst_every)

    def f(x, n_valid, snr_db, eps, delay, key):
        return impair_profile_graph(
            x, n_valid, snr_db, eps, delay, key, taps[0], sco[0],
            drift[0], b_ev[0], b_ln[0], b_db[0], with_bursts=wb)
    return jax.jit(f)


def impair_one(samples, snr_db, eps, delay, seed, lane: int,
               out_len: int, profile=None) -> jnp.ndarray:
    """The per-frame oracle of `impair_many`: one lane's impairments
    through the SAME graph with the SAME counter-derived key
    (`lane_key(seed, lane)`), the frame zero-padded to `out_len`
    host-side. Bit-identical to row `lane` of the batched dispatch;
    with ``profile`` (this LANE's profile name or None) it matches
    row `lane` of the batched dispatch at the same per-lane profile
    to within one float32 ulp (separately compiled programs — the
    FMA-contraction rule tests/test_channel_profiles.py documents)."""
    from ziria_tpu.utils import dispatch, programs

    names = chanprof.resolve_profiles(profile, 1, use_env=False)
    x = np.zeros((int(out_len), 2), np.float32)
    s = np.asarray(samples, np.float32)
    x[:s.shape[0]] = s
    imp_fn = _jit_impair_one(None if names is None else names[0])
    imp_args = (jnp.asarray(x), jnp.int32(s.shape[0]),
                jnp.float32(snr_db), jnp.float32(eps),
                jnp.int32(delay), lane_key(seed, lane))
    programs.note_site("channel.impair", imp_fn, *imp_args)
    with dispatch.timed("channel.impair"):
        return imp_fn(*imp_args)


def impair_stream(stream, n_signal: int, snr_db, eps, seed,
                  profile=None, lane: int = 0) -> np.ndarray:
    """Whole-stream impairments for the streaming-receiver stimulus
    (`phy/link.stream_many`): the channel profile's multipath FIR and
    SCO resample (host numpy twins of the vmapped graph ops —
    `profiles.np_apply_taps` / `np_apply_sco`), one CFO(+drift)
    rotation over the FULL stream (a single oscillator — every frame
    sees the same eps, at its own carrier phase; a profile's `drift`
    adds the quadratic term), AWGN at `snr_db` relative to the
    average *frame* power, then the profile's seeded interference
    bursts. `n_signal` is the count of real signal samples in the
    stream — the inter-frame gaps are idle air and must not deflate
    the reference power the way a whole-stream mean would. ``np.inf``
    disables noise exactly.

    SEEDING CONTRACT (the stream/batch symmetry the batched channel
    already had): every draw folds off ``lane_key(seed, lane)`` —
    the AWGN consumes the bare lane key via ``jax.random.normal``
    (the SAME per-lane fold-in schedule as `impair_many_graph`, so at
    equal geometry — same (seed, lane), same array shape — the
    standard-normal field is element-identical to the batched lane's)
    and bursts fold the same salts the graph folds. Host numpy keeps
    the float64 trig / power math (deterministic test/bench stimulus;
    the receiver under test only ever sees the returned f32 stream)."""
    prof = None
    names = chanprof.resolve_profiles(profile, 1, use_env=False)
    if names is not None:
        prof = chanprof.get_profile(names[0])
    x = np.asarray(stream, np.float32)
    drift = 0.0
    if prof is not None:
        x = chanprof.np_apply_taps(x, prof)
        x = chanprof.np_apply_sco(x, prof.sco)
        drift = float(prof.drift)
    if eps or drift:
        n = np.arange(x.shape[0], dtype=np.float64)
        theta = float(eps) * n + 0.5 * drift * n * n
        c = np.cos(theta)
        s = np.sin(theta)
        x = np.stack([x[:, 0] * c - x[:, 1] * s,
                      x[:, 0] * s + x[:, 1] * c], axis=-1)
        x = x.astype(np.float32)
    # the O(n) power reduction and the jax key are only needed when
    # something will draw (finite-SNR noise or profile bursts): the
    # common snr=inf unprofiled stimulus stays draw-free and cheap
    need_draws = np.isfinite(snr_db) or (prof is not None
                                         and prof.burst_every)
    key = lane_key(seed, lane) if need_draws else None
    p_sig = (float(np.sum(x.astype(np.float64) ** 2)
                   / max(int(n_signal), 1)) if need_draws else 0.0)
    if np.isfinite(snr_db):
        p_noise = p_sig / (10.0 ** (float(snr_db) / 10.0))
        noise = np.asarray(jax.random.normal(key, x.shape), np.float64)
        x = (x + noise * np.sqrt(p_noise / 2.0)).astype(np.float32)
    if prof is not None and prof.burst_every:
        off = int(jax.random.randint(
            jax.random.fold_in(key, _BURST_POS_SALT), (), 0,
            prof.burst_every))
        in_burst = chanprof.np_burst_mask(x.shape[0], prof, off)
        amp = chanprof.np_burst_amp(p_sig, prof)
        bn = np.asarray(jax.random.normal(
            jax.random.fold_in(key, _BURST_NOISE_SALT), x.shape),
            np.float64)
        x = (x + bn * (amp * in_burst.astype(np.float64))[:, None]) \
            .astype(np.float32)
    return x


def impair_profile_point_graph(frames, keys, snr_db,
                               profile_key: str) -> jnp.ndarray:
    """Perfect-sync profiled channel for the BER surfaces
    (`link.loopback_ber_bits` / `link.sweep_ber`'s profile axis),
    traced: per-lane multipath + SCO resample + drift phase (the
    deterministic profile ops — no CFO/delay here, the BER lane is
    perfect-sync by design), AWGN at `snr_db` through `awgn` with the
    caller's split keys (loopback_ber_bits' key schedule, NOT the
    framed link's fold-in lane keys), then seeded bursts off each
    lane's key fold-ins. ``profile_key`` is ONE static profile name —
    its constants bake into the graph. The sweep's flat column skips
    this entirely (flat IS the unprofiled expression), so the
    profiled sweep's flat counts are bit-identical to the unprofiled
    sweep by construction."""
    taps, sco, drift, b_ev, b_ln, b_db = _profile_consts(
        (profile_key,))
    wb = bool(chanprof.get_profile(profile_key).burst_every)

    def lane(f, k):
        x = multipath(jnp.asarray(f, jnp.float32), taps[0])
        x = sco_resample_graph(x, sco[0])
        n = jnp.arange(x.shape[0], dtype=jnp.float32)
        x = cplx.cmul(x, cplx.cexp(0.5 * drift[0] * n * n))
        p_sig = jnp.mean(cplx.cabs2(x))
        x = awgn(k, x, snr_db)
        if not wb:       # static: no wasted full-length burst draw
            return x
        return _burst_graph(x, p_sig, b_ev[0], b_ln[0], b_db[0], k)

    return jax.vmap(lane)(frames, keys)


def multipath(samples, taps_pair) -> jnp.ndarray:
    """Complex FIR channel: taps_pair (L, 2). Causal, same length out.

    The frequency-selective core of the profiled channel
    (`impair_profile_graph` applies it per lane under vmap; the named
    tap sets live in `phy/profiles.CHANNEL_PROFILES`). Pinned against
    a host numpy complex-FIR oracle by
    tests/test_channel_profiles.py. A one-hot tap vector is an exact
    identity (the flat-lane neutral-identity argument)."""
    x = jnp.asarray(samples, jnp.float32)
    t = jnp.asarray(taps_pair, jnp.float32)
    n = x.shape[0]

    def conv(u, v):
        return jnp.convolve(u, v, precision="highest")[:n]

    re = conv(x[:, 0], t[:, 0]) - conv(x[:, 1], t[:, 1])
    im = conv(x[:, 0], t[:, 1]) + conv(x[:, 1], t[:, 0])
    return jnp.stack([re, im], axis=-1)


def impaired_capture(mbps: int, n_bytes: int, seed: int,
                     cfo: float = 0.002, pre: int = 60, post: int = 40,
                     noise: float = 0.03, floor: float = 0.02,
                     scale: float = 1024.0, add_fcs: bool = False):
    """A deterministic receiver test vector: one TX frame with CFO,
    surrounded by noise, plus AWGN, quantized to the complex16 wire
    format (int16 IQ pairs). Returns (psdu_bytes, samples).

    The single source of truth for the capture recipe the receiver
    tests AND the checked-in wifi_rx golden use — three copies of this
    pipeline had already appeared before it was hoisted here.
    """
    import numpy as np

    from ziria_tpu.phy.wifi import tx

    rng = np.random.default_rng(seed)
    psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
    frame = np.asarray(tx.encode_frame(psdu, mbps, add_fcs=add_fcs))
    x = np.concatenate([
        rng.normal(scale=floor, size=(pre, 2)).astype(np.float32),
        np.asarray(apply_cfo(jnp.asarray(frame), cfo)),
        rng.normal(scale=floor, size=(post, 2)).astype(np.float32)])
    x = (x + rng.normal(scale=noise, size=x.shape)).astype(np.float32)
    xi = np.clip(np.round(x * scale), -32768, 32767).astype(np.int16)
    return psdu, xi
