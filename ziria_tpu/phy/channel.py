"""Channel impairment models for loopback testing (pair format).

The reference tests its RX against TX output passed through file-based
golden streams (SURVEY.md §4); real-channel impairments came from
SORA/BladeRF hardware. Here the channel is synthetic and explicit: AWGN,
carrier frequency offset, integer delay (with noise padding), phase
offset, and multipath FIR — everything jax, batchable over frames.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ziria_tpu.ops import cplx


def awgn(key, samples, snr_db: float) -> jnp.ndarray:
    """Add complex white noise at the given SNR (dB) relative to the
    average sample power."""
    x = jnp.asarray(samples, jnp.float32)
    p_sig = jnp.mean(cplx.cabs2(x))
    p_noise = p_sig / (10.0 ** (snr_db / 10.0))
    noise = jax.random.normal(key, x.shape) * jnp.sqrt(p_noise / 2.0)
    return x + noise


def apply_cfo(samples, eps: float) -> jnp.ndarray:
    """Rotate samples by e^{+j*eps*n} (eps radians/sample)."""
    x = jnp.asarray(samples, jnp.float32)
    n = jnp.arange(x.shape[0], dtype=jnp.float32)
    return cplx.cmul(x, cplx.cexp(eps * n))


def apply_phase(samples, theta: float) -> jnp.ndarray:
    x = jnp.asarray(samples, jnp.float32)
    return cplx.cmul(x, jnp.broadcast_to(cplx.cexp(jnp.float32(theta)),
                                         x.shape))


def delay(key, samples, n_before: int, n_after: int = 0,
          noise_db: float = -30.0) -> jnp.ndarray:
    """Pad the frame with low-level noise before/after (models idle air
    time around a detected packet)."""
    x = jnp.asarray(samples, jnp.float32)
    p_sig = jnp.mean(cplx.cabs2(x))
    amp = jnp.sqrt(p_sig * 10.0 ** (noise_db / 10.0) / 2.0)
    pad = jax.random.normal(key, (n_before + n_after, 2)) * amp
    return jnp.concatenate([pad[:n_before], x, pad[n_before:]], axis=0)


# ------------------------------------------------- batched link channel
#
# The device-resident loopback link (phy/link.py) needs the channel as
# ONE vmapped dispatch over a frame batch with PER-LANE parameters —
# the composable helpers above are host-loop shaped (python-scalar
# params, shape-changing delay). `impair_graph` is the same physics at
# a fixed geometry: CFO rotation, integer delay as a roll into the
# zero tail, and AWGN at the lane's own SNR, every parameter a traced
# per-lane scalar. Keys derive from one seed by lane-counter fold-in,
# so lane i's noise never depends on the batch composition.


def impair_graph(x, n_valid, snr_db, eps, delay, key) -> jnp.ndarray:
    """One lane of the batched link channel, all shapes static.

    x: (L, 2) TX samples, only the first `n_valid` (traced int32) of
    which are the frame — anything past is masked to zero HERE (an
    encode_many lane's bucket pad carries garbage symbols, which must
    neither transmit nor count as signal power); snr_db/eps/delay
    (traced scalars): the lane's own AWGN SNR (``inf`` disables noise
    exactly — the noise term multiplies to 0), CFO in rad/sample, and
    integer sample delay (must satisfy delay + n_valid <= L, or the
    frame tail wraps around). Returns (L, 2). Under ``vmap`` this is
    the whole channel of an N-frame batch in one dispatch;
    single-lane calls are the per-frame oracle the batched path is
    judged against — the mask makes the two agree bit-for-bit
    whatever the caller's pad region holds (the select passes real
    samples through untouched)."""
    x = jnp.asarray(x, jnp.float32)
    idx = jnp.arange(x.shape[0])
    x = jnp.where((idx < n_valid)[:, None], x, 0.0)
    n = idx.astype(jnp.float32)
    x = cplx.cmul(x, cplx.cexp(jnp.float32(eps) * n))   # zeros stay 0
    x = jnp.roll(x, delay, axis=0)     # circular, but the zero tail
    #                                    makes it a pure shift
    p_sig = jnp.sum(cplx.cabs2(x)) / jnp.maximum(
        jnp.asarray(n_valid, jnp.float32), 1.0)
    p_noise = p_sig / (10.0 ** (jnp.asarray(snr_db, jnp.float32) / 10.0))
    noise = jax.random.normal(key, x.shape) * jnp.sqrt(p_noise / 2.0)
    return x + noise


def lane_key(seed, i):
    """Counter-derived per-lane PRNG key: fold the lane index into the
    batch seed. The same key reaches lane i whether the channel runs
    batched or per-frame — the bit-identity hinge of the link tests."""
    return jax.random.fold_in(jax.random.PRNGKey(seed), i)


def impair_many_graph(x_b, n_valid, snr_db, eps, delay, seed,
                      out_len: int) -> jnp.ndarray:
    """The traced batched channel: pad the TX batch to `out_len`,
    derive per-lane keys from `seed` by counter fold-in, and apply
    every lane's own impairments under one ``vmap`` — the graph
    `_jit_impair_many` jits, exposed as a plain function so larger
    programs can FUSE it (the one-dispatch loopback link traces it
    between the batch encode and the batched receiver)."""
    pad = out_len - x_b.shape[1]
    x = jnp.pad(jnp.asarray(x_b, jnp.float32),
                ((0, 0), (0, pad), (0, 0)))
    keys = jax.vmap(lambda i: lane_key(seed, i))(
        jnp.arange(x.shape[0]))
    return jax.vmap(impair_graph)(x, n_valid, snr_db, eps, delay,
                                  keys)


@lru_cache(maxsize=None)
def _jit_impair_many(out_len: int):
    """ONE jitted `impair_many_graph` per output length (jit retraces
    per input shape)."""
    def f(x_b, n_valid, snr_db, eps, delay, seed):
        return impair_many_graph(x_b, n_valid, snr_db, eps, delay,
                                 seed, out_len)
    return jax.jit(f)


def impair_many(x_b, n_valid, snr_db, eps, delay, seed,
                out_len: int = None) -> jnp.ndarray:
    """Batched per-lane channel: (R, L, 2) device-resident TX batch ->
    (R, out_len, 2) impaired captures in ONE dispatch, staying on
    device for the receiver. Per-lane arrays for n_valid/snr_db/eps/
    delay (scalars broadcast); `seed` one int — lane keys derive by
    counter fold-in (`lane_key`). Bit-identical per lane to a
    single-lane `impair_graph` call with the same key."""
    from ziria_tpu.utils import dispatch, programs

    r = int(x_b.shape[0])
    if out_len is None:
        out_len = int(x_b.shape[1])

    def _vec(v, dtype):
        a = np.broadcast_to(np.asarray(v, dtype), (r,))
        return jnp.asarray(a)

    imp_fn = _jit_impair_many(int(out_len))
    imp_args = (x_b, _vec(n_valid, np.int32), _vec(snr_db, np.float32),
                _vec(eps, np.float32), _vec(delay, np.int32),
                jnp.uint32(seed))
    programs.note_site("channel.impair_many", imp_fn, *imp_args)
    with dispatch.timed("channel.impair_many"):
        return imp_fn(*imp_args)


@lru_cache(maxsize=None)
def _jit_impair_one():
    return jax.jit(impair_graph)


def impair_one(samples, snr_db, eps, delay, seed, lane: int,
               out_len: int) -> jnp.ndarray:
    """The per-frame oracle of `impair_many`: one lane's impairments
    through the SAME graph with the SAME counter-derived key
    (`lane_key(seed, lane)`), the frame zero-padded to `out_len`
    host-side. Bit-identical to row `lane` of the batched dispatch."""
    from ziria_tpu.utils import dispatch, programs

    x = np.zeros((int(out_len), 2), np.float32)
    s = np.asarray(samples, np.float32)
    x[:s.shape[0]] = s
    imp_fn = _jit_impair_one()
    imp_args = (jnp.asarray(x), jnp.int32(s.shape[0]),
                jnp.float32(snr_db), jnp.float32(eps),
                jnp.int32(delay), lane_key(seed, lane))
    programs.note_site("channel.impair", imp_fn, *imp_args)
    with dispatch.timed("channel.impair"):
        return imp_fn(*imp_args)


def impair_stream(stream, n_signal: int, snr_db, eps, seed) -> np.ndarray:
    """Whole-stream impairments for the streaming-receiver stimulus
    (`phy/link.stream_many`): one CFO rotation over the FULL stream
    (a single oscillator offset — every frame sees the same eps, at
    its own carrier phase) and AWGN at `snr_db` relative to the
    average *frame* power. `n_signal` is the count of real signal
    samples in the stream — the inter-frame gaps are idle air and
    must not deflate the reference power the way a whole-stream mean
    would. ``np.inf`` disables noise exactly. Host numpy (float64
    trig, f32 samples): this is deterministic test/bench stimulus,
    not a serving path — the receiver under test never sees these
    intermediates, only the returned f32 stream."""
    x = np.asarray(stream, np.float32)
    if eps:
        n = np.arange(x.shape[0], dtype=np.float64)
        c = np.cos(float(eps) * n)
        s = np.sin(float(eps) * n)
        x = np.stack([x[:, 0] * c - x[:, 1] * s,
                      x[:, 0] * s + x[:, 1] * c], axis=-1)
        x = x.astype(np.float32)
    if np.isfinite(snr_db):
        p_sig = float(np.sum(x.astype(np.float64) ** 2)
                      / max(int(n_signal), 1))
        p_noise = p_sig / (10.0 ** (float(snr_db) / 10.0))
        rng = np.random.default_rng(seed)
        noise = rng.normal(scale=np.sqrt(p_noise / 2.0), size=x.shape)
        x = (x + noise).astype(np.float32)
    return x


def multipath(samples, taps_pair) -> jnp.ndarray:
    """Complex FIR channel: taps_pair (L, 2). Causal, same length out."""
    x = jnp.asarray(samples, jnp.float32)
    t = jnp.asarray(taps_pair, jnp.float32)
    n = x.shape[0]

    def conv(u, v):
        return jnp.convolve(u, v, precision="highest")[:n]

    re = conv(x[:, 0], t[:, 0]) - conv(x[:, 1], t[:, 1])
    im = conv(x[:, 0], t[:, 1]) + conv(x[:, 1], t[:, 0])
    return jnp.stack([re, im], axis=-1)


def impaired_capture(mbps: int, n_bytes: int, seed: int,
                     cfo: float = 0.002, pre: int = 60, post: int = 40,
                     noise: float = 0.03, floor: float = 0.02,
                     scale: float = 1024.0, add_fcs: bool = False):
    """A deterministic receiver test vector: one TX frame with CFO,
    surrounded by noise, plus AWGN, quantized to the complex16 wire
    format (int16 IQ pairs). Returns (psdu_bytes, samples).

    The single source of truth for the capture recipe the receiver
    tests AND the checked-in wifi_rx golden use — three copies of this
    pipeline had already appeared before it was hoisted here.
    """
    import numpy as np

    from ziria_tpu.phy.wifi import tx

    rng = np.random.default_rng(seed)
    psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
    frame = np.asarray(tx.encode_frame(psdu, mbps, add_fcs=add_fcs))
    x = np.concatenate([
        rng.normal(scale=floor, size=(pre, 2)).astype(np.float32),
        np.asarray(apply_cfo(jnp.asarray(frame), cfo)),
        rng.normal(scale=floor, size=(post, 2)).astype(np.float32)])
    x = (x + rng.normal(scale=noise, size=x.shape)).astype(np.float32)
    xi = np.clip(np.round(x * scale), -32768, 32767).astype(np.int16)
    return psdu, xi
