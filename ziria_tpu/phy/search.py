"""Long-capture packet search — stream parallelism used by the PHY.

The reference receiver detects packets on a live sample stream one at
a time; an offline TPU workflow wants the dual: scan a LONG capture
(seconds of IQ samples) for every packet start. The metric is the same
STS lag-16 autocorrelation the streaming detector uses (ops/sync.py);
at capture scale it is a windowed map over one long stream, exactly
the shape `parallel/streampar.sliding_parallel` shards over an `sp`
mesh axis with a halo exchange (SURVEY.md §2.4's new-capability
column; validated on the virtual 8-device mesh in tests).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ziria_tpu.ops.sync import sts_autocorr


def detection_metric(samples, window: int = 48, mesh=None,
                     axis: str = "sp"):
    """STS autocorrelation metric for every window position of a
    capture. With a mesh, the capture is split across devices with a
    halo exchange; without, single-device.

    samples: (n, 2) float pairs. Returns (n - 16 - window + 1,) f32.
    """
    samples = np.asarray(samples, np.float32)
    span = 16 + window                        # samples per metric value
    if mesh is None:
        m, _ = sts_autocorr(jnp.asarray(samples), window)
        return np.asarray(m)

    from ziria_tpu.parallel.streampar import sliding_parallel
    n_dev = mesh.shape[axis]
    pad = (-len(samples)) % n_dev
    if pad:
        # zero samples produce ~zero metric (energy-normalized), and
        # pad-window values are trimmed below anyway
        samples = np.concatenate(
            [samples, np.zeros((pad, 2), np.float32)])

    def fn(block):
        m, _ = sts_autocorr(block, window)
        return m

    m = sliding_parallel(fn, samples, window=span, mesh=mesh, axis=axis)
    return np.asarray(m)[: len(samples) - pad - span + 1] if pad \
        else np.asarray(m)


def find_packets(samples, threshold: float = 0.75, window: int = 48,
                 min_run: int = 33, min_gap: int = 320, mesh=None,
                 axis: str = "sp") -> np.ndarray:
    """Start indices of detection plateaus in a capture.

    A packet start is the first index of a run of at least `min_run`
    consecutive above-`threshold` windows (the streaming detector's
    n > 32 plateau requirement — a real STS plateau spans the whole
    short preamble, while the energy roll-off at a frame's END can
    produce a brief spurious spike in the normalized metric), at least
    `min_gap` samples after the previous accepted plateau. Returns
    sorted indices into `samples`.
    """
    metric = detection_metric(samples, window=window, mesh=mesh,
                              axis=axis)
    hot = np.flatnonzero(metric > threshold)
    # group into maximal runs of consecutive indices
    runs = []
    start = prev = None
    for i in hot:
        i = int(i)
        if prev is None or i - prev > 1:
            if start is not None:
                runs.append((start, prev))
            start = i
        prev = i
    if start is not None:
        runs.append((start, prev))
    starts = []
    last_end = None                 # end of the last ACCEPTED plateau
    for a, b in runs:
        if b - a + 1 < min_run:
            continue
        if last_end is None or a - last_end > min_gap:
            starts.append(a)
            last_end = b
    return np.asarray(starts, np.int64)


def _receiver():
    """The hybridized in-language receiver, compiled once per process
    (jit caches live on the comp's chunk machines — recompiling per
    call would discard them all)."""
    global _RECEIVER
    if _RECEIVER is None:
        import os

        from ziria_tpu.backend import hybrid as H
        from ziria_tpu.frontend import compile_file
        src = os.path.join(os.path.dirname(__file__), "..", "..",
                           "examples", "wifi_rx.zir")
        if not os.path.exists(src):
            raise FileNotFoundError(
                f"scan_and_decode needs the in-language receiver at "
                f"{src} (pass comp= when running from an installed "
                f"package without the examples tree)")
        _RECEIVER = H.hybridize(compile_file(src).comp)
    return _RECEIVER


_RECEIVER = None


def scan_and_decode(samples, mesh=None, axis: str = "sp",
                    threshold: float = 0.75,
                    max_frame_samples: int = 1 << 17,
                    comp=None):
    """Find every packet in a long capture and decode them ALL as one
    frame batch — the composition of the framework's two new axes:
    the detection metric shards over an `sp` mesh (halo exchange),
    and the per-packet decodes run the in-language receiver
    (examples/wifi_rx.zir) with their chunk-machine device steps
    batched across packets (backend/framebatch), so N packets cost
    ~the device calls of one. Returns [(start_index, payload_bits)]
    for packets whose in-language FCS validated; corrupted packets
    are dropped by the receiver itself.

    samples: (n, 2) int16 IQ pairs (the complex16 wire format).
    `max_frame_samples` defaults past the longest legal 802.11a frame
    (4095-byte PSDU at 6 Mbps ~ 110k samples): a window truncated by
    this limit fails the FCS and would be silently indistinguishable
    from a corrupted packet. `comp` overrides the receiver (any
    hybridized complex16->bit stream computer).
    """
    from ziria_tpu.backend.framebatch import run_many

    arr = np.asarray(samples)
    starts = find_packets(arr, threshold=threshold, mesh=mesh,
                          axis=axis)
    if len(starts) == 0:
        return []
    hyb = comp if comp is not None else _receiver()

    bounds = list(starts[1:]) + [len(arr)]
    wins = []
    for s, nxt in zip(starts, bounds):
        lo = max(0, int(s) - 24)         # margin before the STS start
        hi = min(int(nxt), int(s) + max_frame_samples, len(arr))
        wins.append([p for p in arr[lo:hi]])

    out = []
    for s, r in zip(starts, run_many(hyb, wins)):
        bits = np.asarray(r.out_array(), np.uint8)
        if bits.size:
            out.append((int(s), bits))
    return out
