"""Long-capture packet search — stream parallelism used by the PHY.

The reference receiver detects packets on a live sample stream one at
a time; an offline TPU workflow wants the dual: scan a LONG capture
(seconds of IQ samples) for every packet start. The metric is the same
STS lag-16 autocorrelation the streaming detector uses (ops/sync.py);
at capture scale it is a windowed map over one long stream, exactly
the shape `parallel/streampar.sliding_parallel` shards over an `sp`
mesh axis with a halo exchange (SURVEY.md §2.4's new-capability
column; validated on the virtual 8-device mesh in tests).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ziria_tpu.ops.sync import sts_autocorr


def detection_metric(samples, window: int = 48, mesh=None,
                     axis: str = "sp"):
    """STS autocorrelation metric for every window position of a
    capture. With a mesh, the capture is split across devices with a
    halo exchange; without, single-device.

    samples: (n, 2) float pairs. Returns (n - 16 - window + 1,) f32.
    """
    samples = np.asarray(samples, np.float32)
    span = 16 + window                        # samples per metric value
    if mesh is None:
        m, _ = sts_autocorr(jnp.asarray(samples), window)
        return np.asarray(m)

    from ziria_tpu.parallel.streampar import sliding_parallel
    n_dev = mesh.shape[axis]
    pad = (-len(samples)) % n_dev
    if pad:
        # zero samples produce ~zero metric (energy-normalized), and
        # pad-window values are trimmed below anyway
        samples = np.concatenate(
            [samples, np.zeros((pad, 2), np.float32)])

    def fn(block):
        m, _ = sts_autocorr(block, window)
        return m

    m = sliding_parallel(fn, samples, window=span, mesh=mesh, axis=axis)
    return np.asarray(m)[: len(samples) - pad - span + 1] if pad \
        else np.asarray(m)


def find_packets(samples, threshold: float = 0.75, window: int = 48,
                 min_run: int = 33, min_gap: int = 320, mesh=None,
                 axis: str = "sp") -> np.ndarray:
    """Start indices of detection plateaus in a capture.

    A packet start is the first index of a run of at least `min_run`
    consecutive above-`threshold` windows (the streaming detector's
    n > 32 plateau requirement — a real STS plateau spans the whole
    short preamble, while the energy roll-off at a frame's END can
    produce a brief spurious spike in the normalized metric), at least
    `min_gap` samples after the previous accepted plateau. Returns
    sorted indices into `samples`.
    """
    metric = detection_metric(samples, window=window, mesh=mesh,
                              axis=axis)
    hot = np.flatnonzero(metric > threshold)
    # group into maximal runs of consecutive indices
    runs = []
    start = prev = None
    for i in hot:
        i = int(i)
        if prev is None or i - prev > 1:
            if start is not None:
                runs.append((start, prev))
            start = i
        prev = i
    if start is not None:
        runs.append((start, prev))
    starts = []
    last_end = None                 # end of the last ACCEPTED plateau
    for a, b in runs:
        if b - a + 1 < min_run:
            continue
        if last_end is None or a - last_end > min_gap:
            starts.append(a)
            last_end = b
    return np.asarray(starts, np.int64)
