"""Benchmark entry point — prints ONE JSON line.

Flagship metric (BASELINE.json): **802.11a OFDM RX samples/sec/chip** —
the batched steady-state DATA decode (channel est + matmul-FFT +
equalize + pilot tracking + soft demap + deinterleave + Viterbi +
descramble) at 54 Mbps, frames batched on one chip.

Baseline (BASELINE.md self-measured policy — the reference mount was
empty): the same receiver chain implemented in straightforward
vectorized numpy on the host CPU with the native C Viterbi
(a stand-in for the reference's single-core C backend). The correctness
gate requires the decoded PSDU to equal the transmitted bits before any
number is printed.

Resilience (round-2 hardening): the axon TPU backend has been observed
to hang indefinitely during backend init. The *parent* process
therefore pins itself to the CPU backend (jax.config wins over the
axon plugin, per tests/conftest.py) and always measures the numpy
baseline; the TPU measurement runs in a *subprocess* with bounded
timeouts and retries. On final TPU failure the script still exits 0
and emits a JSON line carrying the numpy baseline and an explicit
``"tpu": "unavailable"`` marker, so the round records something.
"""

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

# Per-attempt timeouts (seconds) for the TPU child. First attempt is
# generous (first axon compile is slow, ~20-40 s healthy, but init
# flakes have hung >9 min). r2 observation: the backend can stay hung
# for an hour and then recover, so later attempts keep a full budget
# and the backoff is long enough for a stale device lease to expire.
TPU_TRY_TIMEOUTS = (600, 600, 600)
TPU_RETRY_BACKOFF = 120  # seconds between attempts

# v5e single-chip peaks for the roofline sanity line.
V5E_HBM_GBPS = 819.0
V5E_BF16_TFLOPS = 197.0


def _block(out):
    """Force completion of everything queued before `out`.

    block_until_ready() under the axon tunnel has been observed to
    return before the device is actually done (it reported rates
    exceeding HBM bandwidth); a tiny device->host copy of the result is
    an honest fence because transfers are ordered after the producing
    computation. The child also measures a chained matmul with both
    fences and reports the ratio as ``fence_audit_bur_over_copy`` so
    the workaround is inspectable rather than folklore (a ratio well
    below 1 = bur returned early).
    """
    import jax
    leaves = [a for a in jax.tree.leaves(out) if hasattr(a, "ndim")]
    for a in leaves[-1:]:
        np.asarray(a.ravel()[:1] if a.ndim else a)


def _time(fn, *args, reps=5, fence=_block):
    """Average seconds per call: queue `reps` async calls, fence once.

    reps amortizes the host<->device round-trip (~70 ms through the
    axon tunnel) which would otherwise dominate millisecond-scale
    kernels.
    """
    fence(fn(*args))  # warm-up / compile, fully drained before timing
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        out = fn(*args)
    fence(out)
    return (time.perf_counter() - t0) / reps


# ------------------------------------------------------------------ numpy RX

def np_rx_decode(frame, rate, n_sym, n_psdu_bits):
    """Host-CPU receiver chain (numpy), the perf baseline."""
    from ziria_tpu.ops.coding import PUNCTURE_KEEP
    from ziria_tpu.ops.interleave import deinterleave_perm
    from ziria_tpu.ops.ofdm import (DATA_BINS, LTS_FREQ, PILOT_BINS,
                                    PILOT_POLARITY, PILOT_VALS, TIME_SCALE)
    from ziria_tpu.ops.scramble import np_lfsr_sequence_127
    x = frame[..., 0] + 1j * frame[..., 1]
    # channel estimate from LTS
    ref = np.zeros(64, np.float32)
    ref[np.arange(-26, 27) % 64] = LTS_FREQ
    H = ((np.fft.fft(x[192:256]) + np.fft.fft(x[256:320])) * 0.5
         / TIME_SCALE) * ref
    Hd = H[DATA_BINS]
    gain = np.abs(Hd) ** 2

    syms = x[400: 400 + 80 * n_sym].reshape(n_sym, 80)[:, 16:]
    bins = np.fft.fft(syms, axis=-1) / TIME_SCALE
    eq = bins / np.where(H == 0, 1.0, H)[None, :]
    data = eq[:, DATA_BINS]
    pilots = eq[:, PILOT_BINS]
    pol = PILOT_POLARITY[(np.arange(n_sym) + 1) % 127]
    expect = PILOT_VALS[None, :] * pol[:, None]
    ph = np.angle((pilots * expect).sum(-1))
    data = data * np.exp(-1j * ph)[:, None]

    # 64-QAM demap
    i = data.real * np.sqrt(42.0)
    q = data.imag * np.sqrt(42.0)
    llr = np.stack([i, 4 - np.abs(i), 2 - np.abs(np.abs(i) - 4),
                    q, 4 - np.abs(q), 2 - np.abs(np.abs(q) - 4)],
                   axis=-1) * gain[None, :, None]
    llr = llr.reshape(n_sym, -1)
    perm = deinterleave_perm(rate.n_cbps, rate.n_bpsc)
    deint = llr[:, perm].reshape(-1)

    keep = PUNCTURE_KEEP[rate.coding]
    nblk = deint.size // keep.sum()
    dep = np.zeros((nblk, keep.size), np.float32)
    dep[:, np.flatnonzero(keep)] = deint.reshape(nblk, keep.sum())
    dep = dep.reshape(-1, 2)

    # Viterbi: native C decoder (the honest C-backend stand-in; the
    # reference's hot kernel is a C SORA brick). Fall back to the shared
    # numpy ACS (ops/viterbi.np_viterbi_decode) only if no toolchain
    # exists — that fallback is NOT a fair baseline and the ratio should
    # be read accordingly.
    from ziria_tpu.runtime.native_lib import load, viterbi_decode_native
    if load() is not None:
        bits = viterbi_decode_native(dep)
    else:
        from ziria_tpu.ops.viterbi import np_viterbi_decode
        bits = np_viterbi_decode(dep)

    from ziria_tpu.phy.wifi.tx import DEFAULT_SCRAMBLER_SEED, _seed_bits_np
    seq = np.resize(
        np_lfsr_sequence_127(_seed_bits_np(DEFAULT_SCRAMBLER_SEED)),
        bits.size)
    clear = bits ^ seq  # descramble with the frame's actual seed
    return clear[16: 16 + n_psdu_bits]  # 16 SERVICE bits, then the PSDU


# ------------------------------------------------------------ shared setup

def _setup():
    """Build the bench frame + expected bits (backend-agnostic)."""
    import jax.numpy as jnp

    from ziria_tpu.phy.wifi import tx
    from ziria_tpu.phy.wifi.params import RATES, n_symbols
    from ziria_tpu.utils.bits import bytes_to_bits

    rate = RATES[54]
    n_bytes = 1000
    n_sym = n_symbols(n_bytes, rate)
    n_psdu_bits = 8 * n_bytes
    frame_len = 400 + 80 * n_sym

    rng = np.random.default_rng(0)
    psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
    frame = np.asarray(tx.encode_frame(psdu, 54))
    want = np.asarray(bytes_to_bits(psdu))
    del jnp
    return rate, n_sym, n_psdu_bits, frame_len, frame, want


def _roofline(B, frame_len, n_sym, n_psdu_bits, t):
    """Rough bytes/flops accounting → % of v5e single-chip peaks.

    Dominant terms per frame: complex input samples (f32 pairs), the
    64-pt FFT per OFDM symbol (~n*log2(n)*5 real flops, complex), the
    Viterbi ACS (64 states x 2 ops x T steps), demap/deinterleave
    elementwise traffic. This is a sanity line, not a profile.
    """
    bytes_per_frame = (
        frame_len * 8                 # input samples f32 (re, im)
        + n_sym * 64 * 8 * 3          # FFT in/out + equalize traffic
        + n_sym * 48 * 6 * 4 * 2      # LLRs write+read
        + n_psdu_bits * 1)            # output bits
    flops_per_frame = (
        n_sym * 64 * 6 * 5 * 2        # FFT (radix-2 estimate, complex)
        + n_sym * 48 * 40             # equalize + pilot track + demap
        + (n_psdu_bits + 16 + 6) * 64 * 4)  # Viterbi ACS add/compare/sel
    achieved_gbps = B * bytes_per_frame / t / 1e9
    achieved_tflops = B * flops_per_frame / t / 1e12
    return {
        "achieved_gbps": round(achieved_gbps, 2),
        "pct_hbm_peak": round(100 * achieved_gbps / V5E_HBM_GBPS, 2),
        "achieved_tflops": round(achieved_tflops, 3),
        "pct_flops_peak": round(100 * achieved_tflops / V5E_BF16_TFLOPS, 3),
    }


# ------------------------------------------------------------ TPU child

def _child_main():
    """Runs in a subprocess with the real (axon/TPU) backend.

    Prints progress to stderr and exactly one JSON object to stdout.
    """
    def note(msg):
        print(f"[bench-child] +{time.time() - t0:.1f}s {msg}",
              file=sys.stderr, flush=True)

    t0 = time.time()
    import jax
    import jax.numpy as jnp
    note("jax imported; touching backend")
    devs = jax.devices()
    dev = devs[0]
    note(f"backend up: {dev.platform} / {getattr(dev, 'device_kind', '?')}"
         f" x{len(devs)}")
    if dev.platform == "cpu":
        # a CPU fallback must NOT be reported as a per-chip number —
        # fail so the parent records tpu: unavailable instead
        note("backend is CPU, not a TPU — refusing to fake a chip metric")
        sys.exit(3)

    from ziria_tpu.phy.wifi import rx

    rate, n_sym, n_psdu_bits, frame_len, frame, want = _setup()
    note("frame encoded")

    # correctness gate (single frame)
    got, _ = rx.decode_data_static(jnp.asarray(frame), rate, n_sym,
                                   n_psdu_bits)
    assert np.array_equal(np.asarray(got), want), "bench RX decode mismatch"
    note("single-frame correctness gate passed")

    # Pallas-on-Mosaic proof: decode with interpret=False explicitly and
    # compare to the lax.scan oracle. On a real TPU this compiles the
    # kernels with Mosaic; any Mosaic rejection fails loudly here.
    pallas_mosaic = False
    if dev.platform != "cpu":
        from ziria_tpu.ops import viterbi, viterbi_pallas
        rng = np.random.default_rng(1)
        llrs = jnp.asarray(rng.normal(size=(4, 1024, 2)).astype(np.float32))
        hard = viterbi_pallas.viterbi_decode_batch(llrs, interpret=False)
        oracle = jax.vmap(viterbi.viterbi_decode)(llrs)
        assert np.array_equal(np.asarray(hard), np.asarray(oracle)), \
            "Pallas (Mosaic) Viterbi != lax.scan oracle"
        pallas_mosaic = True
        note("Pallas kernels compiled by Mosaic, match oracle")

    # batched steady-state decode
    B = 128
    frames = jnp.asarray(np.broadcast_to(frame, (B,) + frame.shape).copy())
    decode = jax.jit(
        lambda f: rx.decode_data_batch(f, rate, n_sym, n_psdu_bits)[0])
    got_b = np.asarray(decode(frames))
    assert np.array_equal(got_b[0], want) and np.array_equal(got_b[-1], want)
    note("batched correctness gate passed; timing")

    # Steady-state throughput, amortized ON DEVICE. Measured r2: the
    # axon tunnel costs ~70 ms per host round-trip and ~2-4 ms per
    # queued call (50 queued 4k matmuls time at 14 TFLOP/s; a device-
    # side chain of the same matmul runs at 213 TFLOP/s ~ peak), so
    # per-call timing measures the tunnel, not the chip. A streaming
    # receiver runs the decode in a device-side loop anyway, so the
    # honest samples/sec/chip is the *marginal* time of one decode step
    # inside a jitted fori_loop, taken between two loop lengths to
    # cancel the fixed round-trip.
    @jax.jit
    def decode_k(f, k):
        # traced loop bound -> ONE compile serves every K
        def body(i, carry):
            s, acc = carry
            x = f + s * 1e-30            # loop-carried: no hoisting
            bits = rx.decode_data_batch(x, rate, n_sym, n_psdu_bits)[0]
            return (bits.astype(jnp.float32).sum() * 1e-30,
                    acc + bits[0, 0].astype(jnp.int32))
        return jax.lax.fori_loop(
            0, k, body, (jnp.float32(0), jnp.int32(0)))[1]

    def timed_k(k, tries=3):
        best = float("inf")
        _block(decode_k(frames, jnp.int32(k)))      # compile + warm
        for _ in range(tries):
            t0 = time.perf_counter()
            _block(decode_k(frames, jnp.int32(k)))
            best = min(best, time.perf_counter() - t0)
        return best

    K1, K2 = 32, 160
    t1, t2 = timed_k(K1), timed_k(K2)
    t_tpu = (t2 - t1) / (K2 - K1)
    note(f"device-loop: K={K1}: {t1*1e3:.1f} ms, K={K2}: {t2*1e3:.1f} ms"
         f" -> marginal {t_tpu*1e3:.3f} ms/step")

    # per-call diagnostic (tunnel-dispatch-bound upper bound on latency)
    t_percall = _time(decode, frames, reps=50)
    sps = B * frame_len / t_tpu
    note(f"t_marginal={t_tpu*1e3:.3f} ms t_percall={t_percall*1e3:.3f} ms")

    # fence audit (VERDICT r1 weak #8): block_until_ready has been
    # observed to return before the device drains through the axon
    # tunnel. Time a chained 2k matmul with both fences; a bur/copy
    # ratio well below 1 proves the copy fence is load-bearing, ~1
    # means bur is currently honest. Recorded every run so the
    # workaround is evidence, not folklore.
    a = jnp.asarray(np.random.default_rng(3).normal(
        size=(2048, 2048)).astype(np.float32))
    mm = jax.jit(lambda x: x @ x * 1e-3)

    def chain(fence_fn, reps=10):
        o = mm(a)
        fence_fn(o)
        t0 = time.perf_counter()
        for _ in range(reps):
            o = mm(o)
        fence_fn(o)
        return (time.perf_counter() - t0) / reps

    t_copy = chain(_block)
    t_bur = chain(jax.block_until_ready)
    fence_audit = round(t_bur / t_copy, 3)
    note(f"fence audit: bur/copy = {fence_audit} "
         f"({'bur returns early — copy fence required' if fence_audit < 0.8 else 'bur honest here'})")

    out = {
        "tpu_sps": sps,
        "t_step_s": t_tpu,
        "t_percall_s": t_percall,
        "fence_audit_bur_over_copy": fence_audit,
        "timing_method": f"marginal device-loop step (K={K1} vs {K2})",
        "batch": B,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", "?"),
        "pallas_mosaic": pallas_mosaic,
        "roofline": _roofline(B, frame_len, n_sym, n_psdu_bits, t_tpu),
    }
    print(json.dumps(out), flush=True)


def _run_one_child(tmo: int):
    """One bounded child attempt. Runs the child in its own process
    group and kills the WHOLE group on timeout: the axon runtime spawns
    helper processes that inherit the output pipes, and killing only
    the direct child would leave subprocess.run blocked on pipe EOF —
    the exact unbounded hang this harness exists to prevent."""
    import signal

    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--tpu-child"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        start_new_session=True)
    try:
        out, errtxt = proc.communicate(timeout=tmo)
        return proc.returncode, out, errtxt
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        return None, "", ""


def _run_child(timeouts):
    """Run the TPU child with bounded retries; return dict or error info."""
    err = None
    for i, tmo in enumerate(timeouts):
        if i:
            time.sleep(TPU_RETRY_BACKOFF)
        rc, out, errtxt = _run_one_child(tmo)
        if rc is None:
            err = f"attempt {i + 1}: timeout after {tmo}s (backend hang)"
        elif rc == 0:
            try:
                return json.loads(out.strip().splitlines()[-1]), None
            except (json.JSONDecodeError, IndexError):
                err = f"attempt {i + 1}: unparseable child stdout"
        else:
            tail = (errtxt or "").strip().splitlines()[-3:]
            err = f"attempt {i + 1}: rc={rc}: " + " | ".join(tail)
        print(f"[bench] {err}", file=sys.stderr, flush=True)
    return None, err


# ------------------------------------------------------------------ parent

def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tpu-child", action="store_true",
                    help="internal: run the TPU measurement")
    ap.add_argument("--no-tpu", action="store_true",
                    help="skip the TPU child (numpy baseline only)")
    ap.add_argument("--tries", type=int, default=len(TPU_TRY_TIMEOUTS))
    args = ap.parse_args()

    if args.tpu_child:
        _child_main()
        return

    # Parent stays on CPU no matter what the axon plugin wants
    # (jax.config wins over the plugin; see tests/conftest.py).
    import jax
    jax.config.update("jax_platforms", "cpu")

    rate, n_sym, n_psdu_bits, frame_len, frame, want = _setup()

    # numpy-baseline correctness gate, then timing
    got_np = np_rx_decode(frame, rate, n_sym, n_psdu_bits)
    assert np.array_equal(got_np, want), "numpy baseline decode mismatch"
    t_np = _time(np_rx_decode, frame, rate, n_sym, n_psdu_bits, reps=3,
                 fence=lambda o: None)
    sps_np = frame_len / t_np

    # the baseline's own hot-kernel throughput, so the ratio's
    # denominator is inspectable (the C ACS loop is portable scalar C,
    # not hand-SIMD like the reference's SORA brick — stated here).
    from ziria_tpu.runtime.native_lib import load, viterbi_decode_native
    vit_c_mbps = None
    if load() is not None:
        nb = (n_psdu_bits + 16 + 6)
        dep = np.random.default_rng(2).normal(
            size=(nb, 2)).astype(np.float32)
        t_v = _time(viterbi_decode_native, dep, reps=5, fence=lambda o: None)
        vit_c_mbps = round(nb / t_v / 1e6, 2)

    result = {
        "metric": "80211a_rx_samples_per_sec_per_chip",
        "unit": "samples/s",
        "numpy_baseline_sps": round(sps_np, 1),
        "viterbi_c_scalar_mbps": vit_c_mbps,
    }

    child, err = (None, "skipped (--no-tpu)") if args.no_tpu else \
        _run_child(TPU_TRY_TIMEOUTS[:args.tries])

    if child is not None:
        result["value"] = round(child["tpu_sps"], 1)
        result["vs_baseline"] = round(child["tpu_sps"] / sps_np, 3)
        for k in ("platform", "device_kind", "batch", "t_step_s",
                  "t_percall_s", "fence_audit_bur_over_copy",
                  "timing_method", "pallas_mosaic", "roofline"):
            result[k] = child.get(k)
    else:
        # TPU unreachable: record the baseline so the round has data.
        result["value"] = round(sps_np, 1)
        result["vs_baseline"] = 1.0
        result["tpu"] = "unavailable"
        result["tpu_error"] = err

    print(json.dumps(result))


if __name__ == "__main__":
    main()
