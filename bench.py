"""Benchmark entry point — prints ONE JSON line.

Flagship metric (BASELINE.json): **802.11a OFDM RX samples/sec/chip** —
the batched steady-state DATA decode (channel est + matmul-FFT +
equalize + pilot tracking + soft demap + deinterleave + Viterbi +
descramble) at 54 Mbps, frames batched on one chip.

Baseline (BASELINE.md self-measured policy — the reference mount was
empty): the same receiver chain implemented in straightforward
vectorized numpy on the host CPU with the native C Viterbi
(a stand-in for the reference's single-core C backend). The correctness
gate requires the decoded PSDU to equal the transmitted bits before any
number is printed.

Resilience (round-3 hardening, after BENCH_r01 rc=1 and BENCH_r02
rc=124): the axon TPU backend hangs for hours at a time, so this script
must *always* finish quickly with rc=0 and useful JSON:

- A global self-deadline (default 540 s, env ``BENCH_SELF_DEADLINE``)
  bounds total wall time below any plausible driver timeout.
- A cheap **probe child** (90 s) checks backend health before the full
  measurement child is attempted; a hung backend costs ~3.5 min total,
  not 30.
- The measurement child appends each completed stage to
  ``BENCH_PARTIAL.jsonl`` so a hang mid-run still yields the headline
  number (the parent recovers it and marks ``"partial": true``).
- If this run cannot reach the TPU, the most recent watcher-harvested
  ``BENCH_LIVE.json`` (tools/tpu_watcher.sh) is attached as
  ``last_good`` with its capture time — clearly labelled as not being
  from this invocation.
- A persistent compilation cache (``.jax_cache/``) makes repeat runs in
  the same round much cheaper.
"""

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
PARTIAL_PATH = os.path.join(REPO, "BENCH_PARTIAL.jsonl")
LIVE_PATH = os.path.join(REPO, "BENCH_LIVE.json")
BASELINE_PATH = os.path.join(REPO, "BASELINE.json")
# the machine-readable probe-availability ledger tools/tpu_watcher.sh
# appends to ({"t": ISO-8601, "probe": "ok|fail|busy"}); bench.py now
# both WRITES its own probe outcomes here and READS recent failures,
# so a hung 90 s probe is paid once per TTL across *invocations*, not
# once per invocation (PR 5 only memoized within one)
PROBES_PATH = os.path.join(REPO, "BENCH_PROBES.jsonl")
PROBE_NEG_TTL = 600.0            # env BENCH_PROBE_NEG_TTL; 0 disables

# Stage-record schema version: bump whenever a stage's semantics change
# so resume (below) can never reuse a measurement whose meaning moved.
BENCH_STAGE_VERSION = 5
# A completed stage this recent (and this code version, same platform)
# is reused instead of re-measured: the axon window flaps, and r4 lost
# two windows re-burning already-captured stages from zero (VERDICT r4
# missing #1). 6 h spans watcher-harvest -> driver-run within a round.
RESUME_WINDOW_DEFAULT = 21600.0

PROBE_TIMEOUT = 90
PROBE_TRIES = 2
PROBE_BACKOFF = 15
CHILD_TIMEOUT_MAX = 700   # raised for the batch sweep's extra compiles

# Perf-ledger trajectory (ISSUE 9): ONE normalized flat record per
# completed stage, appended here by every run (the BENCH_r*.json
# "tail"-wrapped artifacts were unreadable by tooling; this file is
# what tools/perf_report.py diffs and gates on). BENCH_TRAJECTORY env
# overrides the path (tests, smoke runs that must not touch the
# committed ledger).
TRAJECTORY_PATH = os.path.join(REPO, "BENCH_TRAJECTORY.jsonl")

# stage -> (payload key of the stage's primary metric, direction a
# BETTER value moves). The trajectory carries direction per record so
# perf_report never needs this table.
STAGE_METRICS = {
    "headline": ("tpu_sps", "higher"),
    "batch_sweep": ("tpu_sps", "higher"),
    "windowed": ("tpu_sps", "higher"),
    "decompose": ("t_full_step_s", "lower"),
    "framebatch": ("dsl_sps_batched", "higher"),
    "fxp_interior": ("sps", "higher"),
    "tx_chain": ("tx_sps", "higher"),
    "micro_fir": ("items_per_s", "higher"),
    "micro_fft64": ("items_per_s", "higher"),
    "quantized_viterbi": ("sps_i16", "higher"),
    "viterbi_breakdown": ("t_full_s", "lower"),
    "viterbi_kernel_stats": ("sps_base", "higher"),
    "mixed_dispatch": ("sps_mixed", "higher"),
    "fused_mixed": ("sps_fused_mixed", "higher"),
    "batched_acquire": ("sps_batched_acquire", "higher"),
    "link_loopback": ("fps_batched", "higher"),
    "fused_link": ("fps_fused", "higher"),
    "ber_sweep": ("points_per_s_sweep", "higher"),
    "channel_sweep": ("ber_floor_severe", "lower"),
    "streaming_rx": ("sps_streaming", "higher"),
    "multi_stream": ("sps_multi", "higher"),
    "resilience": ("faults_recovered", "higher"),
    "serving": ("sps_serving", "higher"),
    "soak": ("recovery_p99_s", "lower"),
    "autotune": ("sps_tuned", "higher"),
    "lint": ("findings_total", "lower"),
    "programs": ("programs_analyzed", "higher"),
    "numpy_baseline": ("sps", "higher"),
    "result": ("rx_sps", "higher"),
}


def _block(out):
    """Force completion of everything queued before `out`.

    block_until_ready() under the axon tunnel has been observed to
    return before the device is actually done (it reported rates
    exceeding HBM bandwidth); a tiny device->host copy of the result is
    an honest fence because transfers are ordered after the producing
    computation. The child also measures a chained matmul with both
    fences and reports the ratio as ``fence_audit_bur_over_copy`` so
    the workaround is inspectable rather than folklore (a ratio well
    below 1 = bur returned early).
    """
    import jax
    leaves = [a for a in jax.tree.leaves(out) if hasattr(a, "ndim")]
    for a in leaves[-1:]:
        np.asarray(a.ravel()[:1] if a.ndim else a)


def _time(fn, *args, reps=5, fence=_block):
    """Average seconds per call: queue `reps` async calls, fence once.

    reps amortizes the host<->device round-trip (~70 ms through the
    axon tunnel) which would otherwise dominate millisecond-scale
    kernels.
    """
    fence(fn(*args))  # warm-up / compile, fully drained before timing
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        out = fn(*args)
    fence(out)
    return (time.perf_counter() - t0) / reps


# ------------------------------------------------------------------ numpy RX

def np_rx_decode(frame, rate, n_sym, n_psdu_bits):
    """Host-CPU receiver chain (numpy), the perf baseline."""
    from ziria_tpu.ops.coding import PUNCTURE_KEEP
    from ziria_tpu.ops.interleave import deinterleave_perm
    from ziria_tpu.ops.ofdm import (DATA_BINS, LTS_FREQ, PILOT_BINS,
                                    PILOT_POLARITY, PILOT_VALS, TIME_SCALE)
    from ziria_tpu.ops.scramble import np_lfsr_sequence_127
    x = frame[..., 0] + 1j * frame[..., 1]
    # channel estimate from LTS
    ref = np.zeros(64, np.float32)
    ref[np.arange(-26, 27) % 64] = LTS_FREQ
    H = ((np.fft.fft(x[192:256]) + np.fft.fft(x[256:320])) * 0.5
         / TIME_SCALE) * ref
    Hd = H[DATA_BINS]
    gain = np.abs(Hd) ** 2

    syms = x[400: 400 + 80 * n_sym].reshape(n_sym, 80)[:, 16:]
    bins = np.fft.fft(syms, axis=-1) / TIME_SCALE
    eq = bins / np.where(H == 0, 1.0, H)[None, :]
    data = eq[:, DATA_BINS]
    pilots = eq[:, PILOT_BINS]
    pol = PILOT_POLARITY[(np.arange(n_sym) + 1) % 127]
    expect = PILOT_VALS[None, :] * pol[:, None]
    ph = np.angle((pilots * expect).sum(-1))
    data = data * np.exp(-1j * ph)[:, None]

    # 64-QAM demap
    i = data.real * np.sqrt(42.0)
    q = data.imag * np.sqrt(42.0)
    llr = np.stack([i, 4 - np.abs(i), 2 - np.abs(np.abs(i) - 4),
                    q, 4 - np.abs(q), 2 - np.abs(np.abs(q) - 4)],
                   axis=-1) * gain[None, :, None]
    llr = llr.reshape(n_sym, -1)
    perm = deinterleave_perm(rate.n_cbps, rate.n_bpsc)
    deint = llr[:, perm].reshape(-1)

    keep = PUNCTURE_KEEP[rate.coding]
    nblk = deint.size // keep.sum()
    dep = np.zeros((nblk, keep.size), np.float32)
    dep[:, np.flatnonzero(keep)] = deint.reshape(nblk, keep.sum())
    dep = dep.reshape(-1, 2)

    # Viterbi: native C decoder (the honest C-backend stand-in; the
    # reference's hot kernel is a C SORA brick). Fall back to the shared
    # numpy ACS (ops/viterbi.np_viterbi_decode) only if no toolchain
    # exists — that fallback is NOT a fair baseline and the ratio should
    # be read accordingly.
    from ziria_tpu.runtime.native_lib import load, viterbi_decode_native
    if load() is not None:
        bits = viterbi_decode_native(dep)
    else:
        from ziria_tpu.ops.viterbi import np_viterbi_decode
        bits = np_viterbi_decode(dep)

    from ziria_tpu.phy.wifi.tx import DEFAULT_SCRAMBLER_SEED, _seed_bits_np
    seq = np.resize(
        np_lfsr_sequence_127(_seed_bits_np(DEFAULT_SCRAMBLER_SEED)),
        bits.size)
    clear = bits ^ seq  # descramble with the frame's actual seed
    return clear[16: 16 + n_psdu_bits]  # 16 SERVICE bits, then the PSDU


# ------------------------------------------------------------ shared setup

def _setup():
    """Build the bench frame + expected bits (backend-agnostic)."""
    from ziria_tpu.phy.wifi import tx
    from ziria_tpu.phy.wifi.params import RATES, n_symbols
    from ziria_tpu.utils.bits import bytes_to_bits

    rate = RATES[54]
    # ZIRIA_BENCH_NBYTES shrinks the frame for CPU smoke tests of the
    # child path; outside smoke mode a leaked override must not
    # silently change the workload the published number is computed on
    n_bytes = int(os.environ.get("ZIRIA_BENCH_NBYTES", "1000"))
    if n_bytes != 1000 and os.environ.get("ZIRIA_BENCH_ALLOW_CPU") != "1":
        raise RuntimeError(
            f"ZIRIA_BENCH_NBYTES={n_bytes} is only valid in smoke mode "
            "(ZIRIA_BENCH_ALLOW_CPU=1): the headline metric is defined "
            "on the 1000-byte frame")
    n_sym = n_symbols(n_bytes, rate)
    n_psdu_bits = 8 * n_bytes
    frame_len = 400 + 80 * n_sym

    rng = np.random.default_rng(0)
    psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
    frame = np.asarray(tx.encode_frame(psdu, 54))
    want = np.asarray(bytes_to_bits(psdu))
    return rate, n_sym, n_psdu_bits, frame_len, frame, want


def _roofline(B, frame_len, n_sym, n_psdu_bits, t,
              device_kind=None, cost=None):
    """Achieved GB/s / TFLOP/s for one decode step → % of the chip's
    single-chip peaks (per-``device_kind`` table in
    ``ziria_tpu.utils.programs.DEVICE_PEAKS``; unknown kinds report
    absolutes with the pct_* fields omitted — absent, not wrong).

    ``cost`` — XLA's own ``cost_analysis()`` numbers for the batch
    decode program (``{"flops", "bytes_accessed"}`` per dispatch) —
    is the preferred accounting (``source: xla_cost_analysis``); the
    hand-derived per-frame formula that carried rounds 3-8 stays as a
    cross-check column (``hand_gbps``/``hand_tflops``). Without a
    cost dict the hand formula is the estimate, labelled as such.
    """
    bytes_per_frame = (
        frame_len * 8                 # input samples f32 (re, im)
        + n_sym * 64 * 8 * 3          # FFT in/out + equalize traffic
        + n_sym * 48 * 6 * 4 * 2      # LLRs write+read
        + n_psdu_bits * 1)            # output bits
    flops_per_frame = (
        n_sym * 64 * 6 * 5 * 2        # FFT (radix-2 estimate, complex)
        + n_sym * 48 * 40             # equalize + pilot track + demap
        + (n_psdu_bits + 16 + 6) * 64 * 4)  # Viterbi ACS add/compare/sel
    hand_gbps = B * bytes_per_frame / t / 1e9
    hand_tflops = B * flops_per_frame / t / 1e12
    if cost and cost.get("bytes_accessed") and cost.get("flops"):
        gbps = cost["bytes_accessed"] / t / 1e9
        tflops = cost["flops"] / t / 1e12
        out = {
            "achieved_gbps": round(gbps, 2),
            "achieved_tflops": round(tflops, 3),
            "source": "xla_cost_analysis",
            "hand_gbps": round(hand_gbps, 2),
            "hand_tflops": round(hand_tflops, 3),
        }
    else:
        gbps, tflops = hand_gbps, hand_tflops
        out = {
            "achieved_gbps": round(gbps, 2),
            "achieved_tflops": round(tflops, 3),
            "source": "hand_estimate",
        }
    from ziria_tpu.utils.programs import peaks_for
    peaks = peaks_for(device_kind)
    if peaks:
        out["pct_hbm_peak"] = round(100 * gbps / peaks["hbm_gbps"], 2)
        out["pct_flops_peak"] = round(
            100 * tflops / peaks["peak_tflops"], 3)
    return out


# ------------------------------------------------------------ TPU children

def _enable_compile_cache():
    """Persistent XLA compilation cache: repeat runs in the same round
    (watcher harvests + the driver's final run) skip the 20-40 s
    first-compile cost. Best-effort — some PJRT plugins reject it."""
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass


def _traj_path():
    """The ONE reading of the BENCH_TRAJECTORY path override (tests
    and smoke harnesses point it at a scratch file so the committed
    ledger only accumulates real runs)."""
    return os.environ.get("BENCH_TRAJECTORY") or TRAJECTORY_PATH


def _traj_append(stage, metric, value, run_id, platform,
                 direction="higher", partial=False, resumed=False,
                 unit=None, source="bench", t=None, extra=None):
    """Append ONE normalized flat record to the perf-ledger trajectory
    (BENCH_TRAJECTORY.jsonl) — the canonical machine-readable form the
    BENCH_r*.json "tail" wrapper never was. ``extra`` carries
    stage-specific rider fields (the autotune stage's device_kind +
    winning geometry, which Geometry.tuned() and perf_report's
    device_kind matching read back). Best-effort: an unwritable
    ledger never blocks a bench run."""
    rec = {"run_id": run_id, "unix": round(
               time.time() if t is None else t, 1),
           "stage": stage, "metric": metric, "value": value,
           "platform": platform, "partial": bool(partial),
           "direction": direction, "source": source}
    if resumed:
        rec["resumed"] = True
    if unit:
        rec["unit"] = unit
    if extra:
        rec.update(extra)
    try:
        with open(_traj_path(), "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass


def _traj_from_stage(run_id, stage, rec):
    """Mirror a completed stage record into the trajectory when the
    stage has a primary metric and the record carries it (error and
    bookkeeping records don't)."""
    spec = STAGE_METRICS.get(stage)
    if spec is None or rec.get("error"):
        return
    key, direction = spec
    v = rec.get(key)
    if v is None:
        return
    # sweep probes are per-width measurements: key them per width
    # (mirroring _load_resume) so a run that probed B=1024 and a run
    # whose budget stopped at B=256 never compare as one series —
    # that aliasing would fake a 2-4x "regression" in the gate
    if stage == "batch_sweep" and rec.get("batch") is not None:
        stage = f"batch_sweep:{rec['batch']}"
    # the autotune stage's winner rides the ledger record so
    # Geometry.tuned(device_kind) can reconstruct it later, and so
    # perf_report's device_kind matching scopes the gate correctly
    extra = None
    if stage == "autotune":
        extra = {k: rec[k] for k in ("device_kind", "geometry")
                 if k in rec}
    _traj_append(stage, key, v, run_id, rec.get("platform"),
                 direction=direction,
                 resumed=bool(rec.get("resumed_from")),
                 t=rec.get("t"), extra=extra)


def _partial(run_id, stage, **kv):
    """Append one completed stage to BENCH_PARTIAL.jsonl (crash-proof
    evidence: the parent recovers the headline number from here if the
    child is later killed by a timeout) — and its normalized primary
    metric to the perf-ledger trajectory."""
    rec = {"run_id": run_id, "stage": stage, "t": time.time(),
           "ver": BENCH_STAGE_VERSION, **kv}
    with open(PARTIAL_PATH, "a") as f:
        f.write(json.dumps(rec) + "\n")
    _traj_from_stage(run_id, stage, rec)


def _load_resume(platform, window_s, now=None, path=PARTIAL_PATH,
                 workload_bytes=1000):
    """Most recent completed stage records eligible for reuse.

    Eligible = same schema version, same platform, younger than the
    resume window, and not an error record. Batch-sweep probes are
    keyed per width so each width resumes independently. This is what
    makes the child *stage-resumable*: a flapping 480 s window
    accumulates stages across invocations instead of re-burning the
    ones already measured (VERDICT r4 missing #1 / next #1).
    """
    now = time.time() if now is None else now
    out = {}
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                # the window is gated on the ORIGINAL capture time:
                # a resumed re-emission carries captured_t forward so
                # chained resumes cannot keep a measurement alive past
                # the window it was actually taken in
                t_cap = rec.get("captured_t", rec.get("t", 0))
                if (rec.get("ver") != BENCH_STAGE_VERSION
                        or rec.get("platform") != platform
                        or rec.get("workload_bytes") != workload_bytes
                        or t_cap < now - window_s
                        or rec.get("error")):
                    continue
                keys = [rec.get("stage")]
                if keys[0] == "batch_sweep":
                    keys = [f"batch_sweep:{rec.get('batch')}"]
                elif keys[0] == "headline" and rec.get("windowed"):
                    # a windowed-Viterbi promotion is a different
                    # decode method: it must never shadow the exact
                    # step at its width (the "windowed" stage record
                    # is what resumes the measurement itself)
                    keys = ["headline_windowed"]
                elif keys[0] == "headline":
                    # a run emits headline at B=128 and again when the
                    # sweep promotes a wider B — keep each width's
                    # measurement as well as the latest promotion
                    keys.append(f"headline:{rec.get('batch')}")
                for key in keys:
                    if key not in out or rec["t"] > out[key]["t"]:
                        out[key] = rec
    except OSError:
        pass
    return out


_RESUME_META = ("run_id", "stage", "t", "ver", "resumed_from",
                "captured_t", "platform", "workload_bytes")


def _stage_payload(rec):
    """A resumed record's measurement fields, minus bookkeeping
    (platform is re-stamped by the emitting child, not carried)."""
    return {k: v for k, v in rec.items() if k not in _RESUME_META}


def _probe_main():
    """Cheap backend-health probe: init + one tiny computation."""
    import jax
    import jax.numpy as jnp
    _enable_compile_cache()
    dev = jax.devices()[0]
    if dev.platform == "cpu":
        sys.exit(3)
    x = jnp.ones((8, 8), jnp.float32)
    np.asarray((x @ x).ravel()[:1])
    print(json.dumps({"platform": dev.platform,
                      "device_kind": getattr(dev, "device_kind", "?")}),
          flush=True)


def _child_main(run_id):
    """Runs in a subprocess with the real (axon/TPU) backend.

    Prints progress to stderr and exactly one JSON object to stdout.
    Stage order is headline-first: the samples/sec/chip measurement is
    recorded to BENCH_PARTIAL.jsonl before the auxiliary proofs, so
    even a backend hang halfway through leaves the metric on disk.
    """
    def note(msg):
        print(f"[bench-child] +{time.time() - t0:.1f}s {msg}",
              file=sys.stderr, flush=True)

    t0 = time.time()
    # the kill budget the parent will enforce on this process — stage
    # guards below are fractions of it, so they actually fire
    budget = float(os.environ.get("BENCH_CHILD_BUDGET",
                                  str(CHILD_TIMEOUT_MAX)))
    import jax
    import jax.numpy as jnp
    if os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1":
        # smoke mode MUST stay off the tunnel: JAX_PLATFORMS env is
        # ignored by the axon plugin; only a config update before
        # backend init actually pins the child to CPU (same mechanism
        # as tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    note("jax imported; touching backend")
    devs = jax.devices()
    dev = devs[0]
    note(f"backend up: {dev.platform} / {getattr(dev, 'device_kind', '?')}"
         f" x{len(devs)}")
    if dev.platform == "cpu":
        if os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1":
            # smoke-test mode: exercises the full child path on CPU;
            # the parent still refuses platform=="cpu" results, so
            # this can never masquerade as a chip number
            note("CPU allowed for smoke test (ZIRIA_BENCH_ALLOW_CPU=1)")
        else:
            # a CPU fallback must NOT be reported as a per-chip number —
            # fail so the parent records tpu: unavailable instead
            note("backend is CPU, not a TPU — refusing to fake a chip metric")
            sys.exit(3)
    _partial(run_id, "backend_up", platform=dev.platform,
             device_kind=getattr(dev, "device_kind", "?"))

    from ziria_tpu.phy.wifi import rx

    rate, n_sym, n_psdu_bits, frame_len, frame, want = _setup()
    note("frame encoded")

    def part(stage, **kv):
        kv.setdefault("platform", dev.platform)
        kv.setdefault("workload_bytes", n_psdu_bits // 8)
        _partial(run_id, stage, **kv)

    # stage resume: reuse measurements a recent same-version,
    # same-platform, same-workload child already recorded, re-emitting
    # them under THIS run_id (tagged resumed_from) so partial recovery
    # and the ledger both see what this run published
    resume = {}
    if os.environ.get("ZIRIA_BENCH_RESUME", "1") != "0":
        window = float(os.environ.get("BENCH_RESUME_WINDOW",
                                      str(RESUME_WINDOW_DEFAULT)))
        resume = _load_resume(dev.platform, window,
                              workload_bytes=n_psdu_bits // 8)
        resume.pop("backend_up", None)   # always re-proven above
        resume.pop("complete", None)     # always re-merged below
        if resume:
            note(f"resume: reusable stages {sorted(resume)}")
    resumed_stages = []

    def reuse(rec):
        resumed_stages.append(rec["stage"])
        part(rec["stage"], **_stage_payload(rec),
             resumed_from=rec.get("resumed_from", rec["run_id"]),
             captured_t=rec.get("captured_t", rec["t"]))
        return _stage_payload(rec)

    # seed the batch-width table from resumable measurements: the
    # headline record carries the B it was promoted at, sweep probes
    # carry theirs — each width already measured is not re-burned
    sweep = {}
    width_cap = {}   # batch -> original capture time (resume provenance)
    for key, rec in resume.items():
        # windowed-Viterbi headline promotions are a different decode
        # method — they resume via the "windowed" stage and must not
        # seed the EXACT-decode width table
        if (key.startswith("headline:") or key.startswith("batch_sweep:")) \
                and "t_step_s" in rec and "batch" in rec \
                and not rec.get("windowed"):
            sweep.setdefault(rec["batch"], rec["t_step_s"])
            width_cap.setdefault(rec["batch"],
                                 rec.get("captured_t", rec["t"]))
    fresh_widths = set()   # widths actually measured by THIS child

    B = 128
    frames = jnp.asarray(np.broadcast_to(frame, (B,) + frame.shape).copy())
    decode = jax.jit(
        lambda f: rx.decode_data_batch(f, rate, n_sym, n_psdu_bits)[0])
    dev_kind = getattr(dev, "device_kind", "?")

    _cost_memo = {}

    def _decode_cost(b):
        """XLA's own cost analysis for the batch decode at width b —
        the compiled-graph accounting the roofline block now prefers
        over the hand formula (ISSUE 9). Never fatal and budget-
        guarded (lower+compile off the jit fast path costs a compile
        per width); None falls back to the hand estimate."""
        if b in _cost_memo:
            return _cost_memo[b]
        cost = None
        try:
            if time.time() - t0 < 0.80 * budget:
                from ziria_tpu.utils import programs as _prog
                cost = _prog.cost_of(decode, jax.ShapeDtypeStruct(
                    (b,) + frame.shape, jnp.float32))
        except Exception as e:
            note(f"decode cost analysis failed at B={b}: {e!r}")
        _cost_memo[b] = cost
        return cost
    if B in sweep and "correctness" in resume:
        reuse(resume["correctness"])
        note("correctness + B=128 timing resumed from prior window")
    else:
        # batched correctness gate (also the single-frame gate: row 0)
        got_b = np.asarray(decode(frames))
        assert np.array_equal(got_b[0], want) \
            and np.array_equal(got_b[-1], want)
        note("batched correctness gate passed; timing")
        part("correctness", batch=B)

    # Steady-state throughput, amortized ON DEVICE. Measured r2: the
    # axon tunnel costs ~70 ms per host round-trip and ~2-4 ms per
    # queued call (50 queued 4k matmuls time at 14 TFLOP/s; a device-
    # side chain of the same matmul runs at 213 TFLOP/s ~ peak), so
    # per-call timing measures the tunnel, not the chip. A streaming
    # receiver runs the decode in a device-side loop anyway, so the
    # honest samples/sec/chip is the *marginal* time of one decode step
    # inside a jitted fori_loop, taken between two loop lengths to
    # cancel the fixed round-trip.
    # integrity checksum folded into the timed loop: a lane- and
    # bit-position-weighted reduction of the decoded bits, masked to 20
    # bits so the accumulator cannot overflow. Catches decode
    # corruption in ANY lane/bit at ANY width (the earlier ride-along
    # watched a single bit of lane 0), at a cost negligible relative to
    # the decode — and identical across widths, keeping the sweep fair.
    CHK_MASK = (1 << 20) - 1

    def _chk_expected(b, k):
        i = np.arange(b, dtype=np.int64)[:, None]
        j = np.arange(want.size, dtype=np.int64)[None, :]
        w = (i * 131 + j * 7) % 17 - 8
        one = int((w * want.astype(np.int64)).sum())
        # k masked additions == multiplication mod 2^20 (Python's &
        # on negative ints is two's complement, matching the device)
        return (k * one) & CHK_MASK

    def make_decode_k(decode_rows):
        """Jitted K-step device loop around `decode_rows` ((B, len, 2)
        -> (B, n_psdu_bits) bits) with the integrity checksum — ONE
        definition shared by the f32 and fxp paths so their timing
        methodology and corruption detection cannot drift apart."""
        @jax.jit
        def dk(f, k):
            # traced loop bound -> ONE compile serves every K
            i = jnp.arange(f.shape[0], dtype=jnp.int32)[:, None]
            j = jnp.arange(n_psdu_bits, dtype=jnp.int32)[None, :]
            chk_w = (i * 131 + j * 7) % 17 - 8

            def body(_i, carry):
                s, acc = carry
                bits = decode_rows(f + s)    # s is 0 at runtime but
                chk = (bits.astype(jnp.int32) * chk_w).sum()
                # bits are 0/1 so b>>1 == 0, yet data-dependent: the
                # next iteration's input cannot be hoisted
                return (bits[0, 0].astype(jnp.int32) >> 1,
                        (acc + chk) & CHK_MASK)
            return jax.lax.fori_loop(
                0, k, body, (jnp.int32(0), jnp.int32(0)))[1]
        return dk

    decode_k = make_decode_k(
        lambda x: rx.decode_data_batch(x, rate, n_sym, n_psdu_bits)[0])

    def timed_k(dk, f, k, tries=3):
        best = float("inf")
        _block(dk(f, jnp.int32(k)))            # compile + warm
        for _ in range(tries):
            ts = time.perf_counter()
            _block(dk(f, jnp.int32(k)))
            best = min(best, time.perf_counter() - ts)
        return best

    def emit_headline(stage, b, t, method, **fields):
        """One definition of a measured-throughput partial record, so
        the headline, sweep probes, and promotion can't drift apart.
        A record whose width was NOT measured by this child carries the
        original capture time so chained resumes age out honestly."""
        extra = dict(fields)
        if b not in fresh_widths and b in width_cap:
            extra.setdefault("captured_t", width_cap[b])
        # the cost analysis describes the EXACT batch decode program;
        # a windowed-Viterbi promotion is a different program, so its
        # roofline keeps the hand formula (labelled hand_estimate)
        cost = None if extra.get("windowed") else _decode_cost(b)
        part(stage, tpu_sps=b * frame_len / t, t_step_s=t, batch=b,
             device_kind=dev_kind,
             timing_method=method,
             roofline=_roofline(b, frame_len, n_sym, n_psdu_bits, t,
                                device_kind=dev_kind, cost=cost),
             **extra)

    K1, K2 = 32, 160
    if f"headline:{B}" in resume:
        # resumed: the base-width step was measured by a recent child
        # on this platform (checksum-gated before it was recorded)
        hl = reuse(resume[f"headline:{B}"])
        t_tpu = hl["t_step_s"]
        sweep[B] = t_tpu
        timing_method = (f"marginal device-loop step (K={K1} vs {K2}), "
                         f"resumed from prior window")
        note(f"device-loop: B={B} step {t_tpu*1e3:.3f} ms (resumed)")
    else:
        t1, t2 = timed_k(decode_k, frames, K1), timed_k(decode_k, frames, K2)
        t_tpu = (t2 - t1) / (K2 - K1)
        timing_method = f"marginal device-loop step (K={K1} vs {K2})"
        note(f"device-loop: K={K1}: {t1*1e3:.1f} ms, K={K2}: {t2*1e3:.1f} ms"
             f" -> marginal {t_tpu*1e3:.3f} ms/step")
        # verify the loop body's decode BEFORE the record exists: a
        # failed checksum must leave nothing for partial recovery
        a128 = int(decode_k(frames, jnp.int32(2)))
        assert a128 == _chk_expected(B, 2), (a128, _chk_expected(B, 2))
        fresh_widths.add(B)
        emit_headline("headline", B, t_tpu, timing_method)
        sweep[B] = t_tpu
    sps = B * frame_len / t_tpu

    # Pallas-on-Mosaic proof: decode with interpret=False explicitly and
    # compare to the lax.scan oracle. On a real TPU this compiles the
    # kernels with Mosaic; any Mosaic rejection fails loudly here.
    # Ordered BEFORE the batch sweep: this is load-bearing round
    # evidence and must land even if the sweep eats the remaining
    # child budget.
    if "pallas_mosaic" in resume:
        pallas_mosaic = bool(resume["pallas_mosaic"].get("pallas_mosaic"))
        reuse(resume["pallas_mosaic"])
        note("Pallas-Mosaic proof resumed from prior window")
    else:
        from ziria_tpu.ops import viterbi, viterbi_pallas
        rng = np.random.default_rng(1)
        llrs = jnp.asarray(rng.normal(size=(4, 1024, 2)).astype(np.float32))
        # interpret=False means Mosaic — except in the CPU smoke mode,
        # where Pallas has no backend and interpret mode stands in
        hard = viterbi_pallas.viterbi_decode_batch(
            llrs, interpret=(dev.platform == "cpu"))
        oracle = jax.vmap(viterbi.viterbi_decode)(llrs)
        assert np.array_equal(np.asarray(hard), np.asarray(oracle)), \
            "Pallas (Mosaic) Viterbi != lax.scan oracle"
        pallas_mosaic = dev.platform != "cpu"
        note("Pallas kernels compiled by Mosaic, match oracle"
             if pallas_mosaic else "Pallas kernels in interpret mode (smoke)")
        part("pallas_mosaic", pallas_mosaic=pallas_mosaic)

    # Batch-width sweep: the B=128 headline leaves the chip ~96% idle
    # (roofline above) — the decode is dependency-chain-bound, so wider
    # batches are nearly free until a VMEM/HBM cliff. Measure wider
    # widths with the same marginal methodology and promote the best
    # to the headline. Each width is one fresh compile of decode_k;
    # its result is recorded as a partial before the next compile
    # starts, so a flapping tunnel keeps whatever was measured.
    # ZIRIA_BENCH_SWEEP=0 pins the headline at B=128. Widths already
    # seeded from a resumed window are skipped, so re-entry spends the
    # budget on the widths still missing (B=1024 never ran in r4).
    if os.environ.get("ZIRIA_BENCH_SWEEP", "1") != "0":
        Ks1, Ks2 = 8, 40
        for Bs in (256, 512, 1024):
            if Bs in sweep:
                note(f"sweep: B={Bs} resumed "
                     f"({sweep[Bs]*1e3:.3f} ms/step)")
                continue
            # guard on the REAL kill budget the parent runs us under
            # (review: a constant above the parent's hard timeout can
            # never fire and every harvest died mid-aux as a partial)
            if time.time() - t0 > 0.55 * budget:
                note(f"sweep: out of time budget before B={Bs}")
                break
            try:
                fs = jnp.asarray(
                    np.broadcast_to(frame, (Bs,) + frame.shape).copy())
                # integrity ride-along at this width: the weighted
                # whole-batch checksum, not one bit of lane 0
                acc = int(decode_k(fs, jnp.int32(4)))
                assert acc == _chk_expected(Bs, 4), \
                    (acc, _chk_expected(Bs, 4))
                ts1, ts2 = (timed_k(decode_k, fs, Ks1),
                            timed_k(decode_k, fs, Ks2))
                t_b = (ts2 - ts1) / (Ks2 - Ks1)
                # plausibility: a step over MORE frames cannot take
                # less absolute time than the B=128 step (80% slack
                # for noise) — the sweep's K-spread is only 32 steps,
                # and a congested-window glitch there must not
                # publish an inflated headline
                if t_b < 0.8 * t_tpu:
                    note(f"sweep: B={Bs} marginal {t_b*1e3:.3f} ms "
                         f"implausible (< B=128's {t_tpu*1e3:.3f} ms)"
                         f" — discarded")
                    continue
                fresh_widths.add(Bs)
                sweep[Bs] = t_b
                note(f"sweep: B={Bs} marginal {t_b*1e3:.3f} ms/step"
                     f" ({Bs * frame_len / t_b / 1e6:.0f} M sps)")
                emit_headline(
                    "batch_sweep", Bs, t_b,
                    f"marginal device-loop step (K={Ks1} vs {Ks2}), "
                    f"batch sweep probe")
            except Exception as e:
                note(f"sweep: B={Bs} failed: {e!r}")
                break
        B_best = max(sweep, key=lambda b: b * frame_len / sweep[b])
        if B_best != B:
            B, t_tpu = B_best, sweep[B_best]
            sps = B * frame_len / t_tpu
            timing_method = (f"marginal device-loop step (K={Ks1} vs "
                             f"{Ks2}), best of batch sweep "
                             f"{sorted(sweep)}")
            if B_best not in fresh_widths:
                # the winning width's measurement came from a prior
                # window — the published result must say so, not just
                # the buried partial record (review finding)
                timing_method += ", width resumed from prior window"
                resumed_stages.append("headline")
            note(f"sweep: promoting B={B} to headline"
                 f" ({sps/1e6:.0f} M sps)")
            emit_headline("headline", B, t_tpu, timing_method)

    # Sliding-window parallel Viterbi (r5): the exact decode's ~8k-step
    # trellis chain is the suspected bound (see decompose below);
    # windowing converts that serial depth into batch lanes — the
    # truncated-traceback trade the reference's own SORA decoder makes,
    # bit-identical at operating SNR (tests/test_viterbi_windowed.py).
    # The integrity checksum gates it on-chip before any timing is
    # recorded; if it beats the exact headline it is promoted with the
    # method stated in timing_method. ZIRIA_BENCH_WINDOWED=0 disables.
    def _windowed_stage():
        if time.time() - t0 > 0.65 * budget:
            raise TimeoutError("skipped: child time budget")
        win, ov = 1024, 96
        if n_sym * rate.n_dbps <= win + 2 * ov:
            # too short to window (smoke frames): the decoder would
            # fall back to the exact path and any "win" would be noise
            raise TimeoutError("skipped: frame too short to window")
        # measure at the CURRENT headline width: after the sweep this
        # is the best exact-decode batch, so windowed x best-B stack
        Bw = B
        fw = frames if Bw == 128 else jnp.asarray(
            np.broadcast_to(frame, (Bw,) + frame.shape).copy())
        dkw = make_decode_k(lambda x: rx.decode_data_batch(
            x, rate, n_sym, n_psdu_bits, viterbi_window=win)[0])
        acc = int(dkw(fw, jnp.int32(2)))
        assert acc == _chk_expected(Bw, 2), (acc, _chk_expected(Bw, 2))
        tw1, tw2 = timed_k(dkw, fw, 8), timed_k(dkw, fw, 40)
        t_w = (tw2 - tw1) / 32
        t_ex = sweep.get(Bw, t_tpu)
        # same glitch guard as the sweep: a marginal step implausibly
        # below 1/50 of the exact step is a timing artifact
        if not t_w > 0.02 * t_ex:
            raise RuntimeError(
                f"implausible windowed marginal {t_w*1e3:.4f} ms "
                f"(exact step {t_ex*1e3:.3f} ms) — timing glitch")
        rec = {"batch": Bw, "window": win, "overlap": ov,
               "t_step_s": round(t_w, 6),
               "tpu_sps": round(Bw * frame_len / t_w, 1),
               "vs_exact_step": round(t_w / t_ex, 3)}
        note(f"windowed viterbi: B={Bw} {t_w*1e3:.3f} ms/step "
             f"({rec['tpu_sps']/1e6:.0f} M sps, "
             f"{rec['vs_exact_step']:.2f}x the exact step)")
        part("windowed", **rec)
        return rec

    windowed_captured_t = None
    can_window = n_sym * rate.n_dbps > 1024 + 2 * 96
    if "windowed" in resume and can_window:
        rec_w = resume["windowed"]
        windowed_captured_t = rec_w.get("captured_t", rec_w["t"])
        winrec = reuse(rec_w)
        note("windowed stage resumed from prior window")
    elif not can_window:
        winrec = {"skipped": "frame too short to window"}
    elif os.environ.get("ZIRIA_BENCH_WINDOWED", "1") == "0":
        winrec = {"skipped": "ZIRIA_BENCH_WINDOWED=0"}
    else:
        try:
            winrec = _windowed_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"windowed stage failed: {e!r}")
            winrec = {"error": repr(e)}

    headline_is_windowed = False
    if (winrec.get("tpu_sps") and
            winrec["tpu_sps"] > B * frame_len / t_tpu):
        B, t_tpu = winrec["batch"], winrec["t_step_s"]
        sps = winrec["tpu_sps"]
        headline_is_windowed = True
        timing_method = (
            f"marginal device-loop step (K=8 vs 40), windowed "
            f"Viterbi (window={winrec['window']}, "
            f"overlap={winrec['overlap']}; truncated-traceback "
            f"parallel decode, checksum-gated on-chip)")
        extra = {"windowed": True, "window": winrec.get("window"),
                 "overlap": winrec.get("overlap")}
        if windowed_captured_t is not None:
            # promotion of a RESUMED windowed measurement: say so and
            # carry the original capture time so chained resumes age
            # it out honestly (review finding)
            timing_method += ", resumed from prior window"
            extra["captured_t"] = windowed_captured_t
        else:
            # freshly measured this run (even when the exact step at
            # this width was resumed)
            fresh_widths.add(B)
        note(f"windowed decode promoted to headline "
             f"({sps/1e6:.0f} M sps)")
        emit_headline("headline", B, t_tpu, timing_method, **extra)

    # Step decomposition (VERDICT r4 next #3): the B=128 step runs at
    # ~4% of HBM peak — dependency-chain-bound, but WHERE? Time the
    # vmapped front end (channel est + matmul-FFT + equalize + demap +
    # deinterleave + depuncture) and the Pallas Viterbi kernel
    # separately with the same marginal-K method, so the round closes
    # with a measured bound decomposition even if nothing else lands.
    def _decompose_stage():
        if time.time() - t0 > 0.70 * budget:
            raise TimeoutError("skipped: child time budget")
        from ziria_tpu.ops import viterbi_pallas
        from ziria_tpu.phy.wifi.rx import _decode_front

        @jax.jit
        def front_k(f, k):
            def body(_i, carry):
                s, acc = carry
                dep = jax.vmap(
                    lambda x: _decode_front(x, rate, n_sym))(f + s)
                # tiny data-dependent feedback: the next iteration's
                # input depends on this one's output, so XLA cannot
                # hoist the body out of the loop
                return (dep[0, 0, 0] * 1e-30, acc + dep.sum() * 1e-30)
            return jax.lax.fori_loop(
                0, k, body, (jnp.float32(0), jnp.float32(0)))[1]

        dep0 = jax.jit(jax.vmap(
            lambda x: _decode_front(x, rate, n_sym)))(frames)
        n_bits = n_sym * rate.n_dbps

        @jax.jit
        def vit_k(d, k):
            def body(_i, carry):
                s, acc = carry
                bits = viterbi_pallas.viterbi_decode_batch(
                    d + s, n_bits=n_bits,
                    interpret=(dev.platform == "cpu"))
                return (bits[0, 0].astype(jnp.float32) * 1e-30,
                        acc + bits.sum().astype(jnp.float32) * 1e-30)
            return jax.lax.fori_loop(
                0, k, body, (jnp.float32(0), jnp.float32(0)))[1]

        Kd1, Kd2 = 8, 40
        tf = (timed_k(front_k, frames, Kd2) -
              timed_k(front_k, frames, Kd1)) / (Kd2 - Kd1)
        tv = (timed_k(vit_k, dep0, Kd2) -
              timed_k(vit_k, dep0, Kd1)) / (Kd2 - Kd1)
        t_full = sweep.get(128, t_tpu)
        dec = {"batch": 128,
               "t_front_s": round(tf, 6), "t_viterbi_s": round(tv, 6),
               "t_full_step_s": round(t_full, 6),
               "front_frac": round(tf / t_full, 3),
               "viterbi_frac": round(tv / t_full, 3)}
        note(f"decompose: front {tf*1e3:.3f} ms "
             f"({dec['front_frac']:.0%}) + viterbi {tv*1e3:.3f} ms "
             f"({dec['viterbi_frac']:.0%}) of {t_full*1e3:.3f} ms step")
        part("decompose", **dec)
        return dec

    if "decompose" in resume:
        decomp = reuse(resume["decompose"])
        note("decompose resumed from prior window")
    else:
        try:
            decomp = _decompose_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"decompose stage failed: {e!r}")
            decomp = {"error": repr(e)}

    # Frame batching on-chip (r4): any compiled .zir program amortizes
    # the host link across frames — 16 captures through the in-language
    # receiver should ride ~the single-frame device-call count. Timed
    # here because the win is exactly the per-call tunnel cost the
    # marginal-step methodology above factors out.
    def _framebatch_stage():
        if time.time() - t0 > 0.75 * budget:
            raise TimeoutError("skipped: child time budget")
        from ziria_tpu.backend import chunked as CH
        from ziria_tpu.backend import hybrid as HY
        from ziria_tpu.backend.framebatch import StepBatcher, run_many
        from ziria_tpu.frontend import compile_file
        from ziria_tpu.interp.interp import run as interp_run
        from ziria_tpu.phy import channel

        hyb = HY.hybridize(compile_file(
            os.path.join(REPO, "examples", "wifi_rx.zir")).comp)
        caps = [channel.impaired_capture(24, 60, seed=100 + k,
                                         add_fcs=True)
                for k in range(16)]
        streams = [[p for p in xi] for _ps, xi in caps]
        interp_run(hyb, streams[0])              # compile single path
        CH.STATS["device_calls"] = 0
        ts = time.perf_counter()
        for s in streams:
            interp_run(hyb, s)
        t_seq = time.perf_counter() - ts
        calls_seq = CH.STATS["device_calls"]
        run_many(hyb, streams,
                 batcher=StepBatcher(len(streams)))  # compile vmap path
        b2 = StepBatcher(len(streams))
        ts = time.perf_counter()
        run_many(hyb, streams, batcher=b2)
        t_bat = time.perf_counter() - ts
        samples_total = sum(len(s) for s in streams)
        fb = {"frames": len(streams), "calls_sequential": calls_seq,
              "calls_batched": b2.device_calls,
              "t_sequential_s": round(t_seq, 3),
              "t_batched_s": round(t_bat, 3),
              # compiled-DSL throughput, comparable (roughly — 24 Mbps
              # short captures vs the headline's 54 Mbps frames) with
              # the library receiver's headline: the DSL-vs-library
              # gap factor VERDICT r4 #5 asks to state
              "samples_total": samples_total,
              "dsl_sps_batched": round(samples_total / t_bat, 1),
              "dsl_sps_sequential": round(samples_total / t_seq, 1)}
        note(f"framebatch: {calls_seq} calls / {t_seq:.2f}s sequential"
             f" -> {b2.device_calls} calls / {t_bat:.2f}s batched")
        part("framebatch", **fb)
        return fb

    if "framebatch" in resume:
        fb = reuse(resume["framebatch"])
        note("framebatch resumed from prior window")
    else:
        try:
            fb = _framebatch_stage()
        except Exception as e:        # evidence stage: never fatal
            note(f"framebatch stage failed: {e!r}")
            fb = {"error": repr(e)}

    # Fixed-point interior on-chip (r4 session 3): the Q15 integer
    # decode (phy/wifi/rx_fxp.py) timed with the same marginal-step
    # methodology at B=128 — evidence of what the reference's int16
    # discipline costs/earns on the VPU vs the f32 fast path.
    # Non-fatal, budget-guarded.
    def _fxp_stage():
        if time.time() - t0 > 0.85 * budget:
            raise TimeoutError("skipped: child time budget")
        from ziria_tpu.phy.wifi import rx_fxp
        fq = rx_fxp.quantize_frame(jnp.asarray(frame))
        fqs = jnp.broadcast_to(fq, (128,) + fq.shape)
        decode_k_fxp = make_decode_k(
            lambda x: rx_fxp.decode_data_batch_fxp(
                x, rate, n_sym, n_psdu_bits)[0])

        acc = int(decode_k_fxp(fqs, jnp.int32(2)))
        assert acc == _chk_expected(128, 2), \
            (acc, _chk_expected(128, 2))

        tf1 = timed_k(decode_k_fxp, fqs, 8)
        tf2 = timed_k(decode_k_fxp, fqs, 40)
        t_fxp = (tf2 - tf1) / 32
        t128 = sweep.get(128, t_tpu)
        # plausibility (same reasoning as the sweep's guard): an fxp
        # step 5x faster than the f32 step is a timing glitch on the
        # 32-step K-spread, not physics
        if not t_fxp > 0.2 * t128:
            raise RuntimeError(
                f"implausible fxp marginal {t_fxp*1e3:.3f} ms "
                f"(f32 step {t128*1e3:.3f} ms) — timing glitch")
        fxp_ev = {"t_step_s": round(t_fxp, 6), "batch": 128,
                  "sps": round(128 * frame_len / t_fxp, 1),
                  "vs_f32_interior": round(t_fxp / t128, 3)}
        note(f"fxp interior: {t_fxp*1e3:.3f} ms/step "
             f"({fxp_ev['sps']/1e6:.0f} M sps, "
             f"{fxp_ev['vs_f32_interior']:.2f}x the f32 step)")
        part("fxp_interior", **fxp_ev)
        return fxp_ev

    if "fxp_interior" in resume:
        fxp_ev = reuse(resume["fxp_interior"])
        note("fxp interior resumed from prior window")
    else:
        try:
            fxp_ev = _fxp_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"fxp stage failed: {e!r}")
            fxp_ev = {"error": repr(e)}

    # TX chain on-chip (r5; BASELINE config #3): the batched transmit
    # encode (scramble + conv + interleave + modulate + matmul-IFFT +
    # preamble/SIGNAL assembly) with the same marginal-step method.
    # All-parallel work — the counterpoint to the trellis-bound RX.
    def _tx_stage():
        if time.time() - t0 > 0.88 * budget:
            raise TimeoutError("skipped: child time budget")
        from ziria_tpu.phy.wifi import tx as txm
        Bt = 128
        bits = jnp.asarray(np.broadcast_to(
            np.asarray(want, np.uint8), (Bt, want.size)).copy())
        enc = jax.jit(jax.vmap(
            lambda b: txm.encode_frame_bits(b, rate)))
        got0 = np.asarray(enc(bits))
        # correctness gate: every encoded row equals the committed
        # reference frame (the same PSDU _setup encoded)
        assert np.allclose(got0[0], frame, atol=1e-4) \
            and np.allclose(got0[-1], frame, atol=1e-4)

        @jax.jit
        def tx_k(bb, k):
            def body(_i, carry):
                s, acc = carry
                out = jax.vmap(
                    lambda b: txm.encode_frame_bits(b, rate)
                )(jnp.bitwise_xor(bb, s))
                # runtime-zero, data-dependent feedback (cf. the RX
                # loop): the next iteration's input depends on this
                # one's output, so the body cannot be hoisted
                s2 = (out[0, 0, 0] * 1e-30).astype(jnp.uint8)
                return (jnp.broadcast_to(s2, bb.shape),
                        acc + out.sum() * 1e-30)
            z0 = jnp.zeros_like(bits)
            return jax.lax.fori_loop(
                0, k, body, (z0, jnp.float32(0)))[1]

        tt1, tt2 = timed_k(tx_k, bits, 8), timed_k(tx_k, bits, 40)
        t_tx = (tt2 - tt1) / 32
        # plausibility (cf. the fxp stage's guard): the marginal step
        # can't be negative or far below the K=40 run's average step —
        # that's scheduler noise on the K-spread, not physics, and it
        # must not persist as a resumable record
        if not t_tx > 0.02 * (tt2 / 40):
            raise RuntimeError(
                f"implausible tx marginal {t_tx*1e3:.4f} ms "
                f"(K=40 avg {tt2/40*1e3:.3f} ms) — timing glitch")
        rec = {"batch": Bt, "t_step_s": round(t_tx, 6),
               "tx_sps": round(Bt * frame_len / t_tx, 1)}
        note(f"tx chain: {t_tx*1e3:.3f} ms/step "
             f"({rec['tx_sps']/1e6:.0f} M samples/s generated)")
        part("tx_chain", **rec)
        return rec

    if "tx_chain" in resume:
        tx_ev = reuse(resume["tx_chain"])
        note("tx chain resumed from prior window")
    else:
        try:
            tx_ev = _tx_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"tx stage failed: {e!r}")
            tx_ev = {"error": repr(e)}

    # Micro configs on-chip (r5; BASELINE configs #1/#2): the FIR
    # pipeline and the registered 64-pt FFT-block pipeline, each at
    # the vectorizer's chosen width, timed with the calibration tool's
    # own device-loop method (imported, not re-implemented, so the two
    # cannot drift). Two independently resumable stages: a window that
    # dies between them keeps the finished half.
    def _micro_config(prog_name):
        if time.time() - t0 > 0.92 * budget:
            raise TimeoutError("skipped: child time budget")
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "calibrate_vect", os.path.join(REPO, "tools",
                                           "calibrate_vect.py"))
        cv = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cv)

        from ziria_tpu.core.vectorize import vectorize
        from ziria_tpu.runtime.cli import PROGS
        comp = PROGS[prog_name]()
        W = vectorize(comp).segments[0].width
        shape = {"fir": (), "fft64": (2,)}[prog_name]  # complex pairs
        # _time_width clamps the marginal >= 1e-9 (no glitch records)
        t_s, take = cv._time_width(comp, W, item_shape=shape)
        ev = {"config": prog_name, "width": W,
              "s_per_step": round(t_s, 9),
              "items_per_s": round(take / t_s, 1)}
        note(f"micro: {prog_name} W={W} "
             f"{take / t_s / 1e6:.2f} M items/s")
        part(f"micro_{prog_name}", **ev)
        return ev

    micro_ev = {}
    for prog_name in ("fir", "fft64"):
        key = f"micro_{prog_name}"
        if key in resume:
            micro_ev[prog_name] = reuse(resume[key])
            note(f"micro {prog_name} resumed from prior window")
        else:
            try:
                micro_ev[prog_name] = _micro_config(prog_name)
            except Exception as e:      # evidence stage: never fatal
                note(f"micro {prog_name} failed: {e!r}")
                micro_ev[prog_name] = {"error": repr(e)}

    # RX hot-path levers (ISSUE 1): the quantized-metric Viterbi and
    # the one-dispatch mixed-rate decode, measured by the shared tools
    # module (tools/rx_dispatch_bench.py — imported, not re-implemented,
    # per the VERDICT #9 tools-not-monolith discipline). Two
    # independently resumable, never-fatal stages so the next chip
    # window captures both levers without a code change.
    def _load_rx_dispatch_bench():
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "rx_dispatch_bench", os.path.join(REPO, "tools",
                                              "rx_dispatch_bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _quantized_stage():
        if time.time() - t0 > 0.90 * budget:
            raise TimeoutError("skipped: child time budget")
        # smoke mode shrinks the batch with the frame: the point there
        # is path coverage, and B=128 interpret-mode Pallas on a CPU
        # child would eat the whole budget
        smoke = os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
        ev = _load_rx_dispatch_bench().quantized_sweep(
            B=8 if smoke else 128, n_bytes=n_psdu_bits // 8,
            k1=2 if smoke else 4, k2=4 if smoke else 12)
        note(f"quantized viterbi: f32 {ev['t_step_f32_s']*1e3:.3f} ms "
             f"-> i16 {ev['t_step_i16_s']*1e3:.3f} ms/step "
             f"({ev['i16_over_f32']:.2f}x, bit-match="
             f"{ev['i16_matches_f32']})")
        part("quantized_viterbi", **ev)
        return ev

    if "quantized_viterbi" in resume:
        quant_ev = reuse(resume["quantized_viterbi"])
        note("quantized viterbi resumed from prior window")
    else:
        try:
            quant_ev = _quantized_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"quantized viterbi stage failed: {e!r}")
            quant_ev = {"error": repr(e)}

    # ISSUE 6 satellite: the decode step split into front-end / ACS /
    # traceback / full (the measured answer to the decompose stage's
    # "dependency-chain-bound, but WHERE?"), emitted alongside the
    # roofline block. Resumable, never-fatal.
    def _viterbi_breakdown_stage():
        if time.time() - t0 > 0.91 * budget:
            raise TimeoutError("skipped: child time budget")
        smoke = os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
        ev = _load_rx_dispatch_bench().viterbi_breakdown(
            B=8 if smoke else 128, n_bytes=n_psdu_bits // 8,
            k1=2 if smoke else 4, k2=4 if smoke else 12)
        note(f"viterbi breakdown: front {ev['t_front_s']*1e3:.3f} ms "
             f"({ev['front_frac']:.0%}) + acs {ev['t_acs_s']*1e3:.3f} "
             f"ms ({ev['acs_frac']:.0%}) + traceback "
             f"{ev['t_traceback_s']*1e3:.3f} ms "
             f"({ev['traceback_frac']:.0%}) of {ev['t_full_s']*1e3:.3f}"
             f" ms full step")
        part("viterbi_breakdown", **ev)
        return ev

    if "viterbi_breakdown" in resume:
        vbrk_ev = reuse(resume["viterbi_breakdown"])
        note("viterbi breakdown resumed from prior window")
    else:
        try:
            vbrk_ev = _viterbi_breakdown_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"viterbi breakdown stage failed: {e!r}")
            vbrk_ev = {"error": repr(e)}

    # ISSUE 6 tentpole evidence: per-lever decode-core samples/s for
    # the rebuilt ACS (radix-4 / int16 / int8+LUT / fused demap /
    # stacked), identity-gated, with the ROOFLINE percentage each
    # lever achieves annotated from the same accounting as the
    # headline's roofline block — the per-lever deltas the issue asks
    # the roofline reporting to carry.
    def _viterbi_kernel_stats_stage():
        if time.time() - t0 > 0.92 * budget:
            raise TimeoutError("skipped: child time budget")
        smoke = os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
        rdb = _load_rx_dispatch_bench()
        # smoke mode drops the fused levers: their per-rate unrolled
        # kernels take minutes in interpret mode on CPU (milliseconds
        # of Mosaic compile on the chip); the fused identity is
        # covered by tier-1 pytest at a cheap rate either way
        levers = rdb.VITERBI_LEVERS[:5] if smoke else rdb.VITERBI_LEVERS
        ev = rdb.viterbi_kernel_stats(
            B=8 if smoke else 128, n_bytes=n_psdu_bits // 8,
            k1=2 if smoke else 4, k2=4 if smoke else 12,
            levers=levers)
        lever_roofline = {}
        for name, _kw in levers:
            t_l = ev.get(f"t_step_{name}_s")
            if t_l:
                lever_roofline[name] = _roofline(
                    ev["batch"], ev["frame_len"], n_sym, n_psdu_bits,
                    t_l, device_kind=dev_kind)
        ev["roofline_by_lever"] = lever_roofline
        best = max((ev[f"sps_{n}"], n) for n, _k in levers)
        note(f"viterbi levers: base {ev['sps_base']/1e6:.0f} M sps -> "
             f"best {best[1]} {best[0]/1e6:.0f} M sps "
             f"(i8 ber delta {ev.get('ber_int8_delta', 0):+.4f}, "
             f"gates green)")
        part("viterbi_kernel_stats", **ev)
        return ev

    if "viterbi_kernel_stats" in resume:
        vlev_ev = reuse(resume["viterbi_kernel_stats"])
        note("viterbi kernel stats resumed from prior window")
    else:
        try:
            vlev_ev = _viterbi_kernel_stats_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"viterbi kernel stats stage failed: {e!r}")
            vlev_ev = {"error": repr(e)}

    def _mixed_dispatch_stage():
        if time.time() - t0 > 0.93 * budget:
            raise TimeoutError("skipped: child time budget")
        ev = _load_rx_dispatch_bench().mixed_dispatch_stats(
            n_bytes=24 if os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
            else 100)
        note(f"mixed dispatch: {ev['compiles_bucketed']} bucketed "
             f"compiles / {ev['t_bucketed_s']:.3f}s -> "
             f"{ev['compiles_mixed']} compile / {ev['t_mixed_s']:.3f}s")
        part("mixed_dispatch", **ev)
        return ev

    if "mixed_dispatch" in resume:
        mixed_ev = reuse(resume["mixed_dispatch"])
        note("mixed dispatch resumed from prior window")
    else:
        try:
            mixed_ev = _mixed_dispatch_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"mixed dispatch stage failed: {e!r}")
            mixed_ev = {"error": repr(e)}

    # ISSUE 20 tentpole evidence: the rate-switched fused decode on
    # the mixed/stream path — identity-gated (lane-for-lane vs the
    # unfused mixed trellis, radix 2 and 4) with the analytical
    # cost_of(_jit_stream_decode) bytes_accessed delta fused vs
    # unfused at the suite-shared geometry. On CPU the fused sps pays
    # interpret-mode dispatch overhead for the in-kernel 8-rate front
    # (the win is priced by the bytes delta until the TPU probe
    # lands); the stage records both sides either way. Same
    # resumable, never-fatal discipline as mixed_dispatch above.
    def _fused_mixed_stage():
        if time.time() - t0 > 0.935 * budget:
            raise TimeoutError("skipped: child time budget")
        smoke = os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
        ev = _load_rx_dispatch_bench().fused_mixed_stats(
            B=8 if smoke else 64, n_bytes=24 if smoke else 100,
            k1=2, k2=4 if smoke else 6)
        note(f"fused mixed: identity "
             f"{ev['fused_mixed_bit_identical']}, stream decode bytes "
             f"{ev['stream_decode_bytes_unfused']/1e6:.1f}M -> "
             f"{ev['stream_decode_bytes_fused']/1e6:.1f}M "
             f"({ev['stream_decode_bytes_ratio']:.2f}x), "
             f"sps {ev['sps_unfused_mixed']/1e3:.0f}k -> "
             f"{ev['sps_fused_mixed']/1e3:.0f}k")
        part("fused_mixed", **ev)
        return ev

    if "fused_mixed" in resume:
        fused_mixed_ev = reuse(resume["fused_mixed"])
        note("fused mixed resumed from prior window")
    else:
        try:
            fused_mixed_ev = _fused_mixed_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"fused mixed stage failed: {e!r}")
            fused_mixed_ev = {"error": repr(e)}

    # ISSUE 2 tentpole evidence: the acquisition front end's
    # O(N) -> O(1) dispatch collapse (receive_many batched_acquire),
    # measured by the instrumented dispatch counter. Same resumable,
    # never-fatal stage discipline as mixed_dispatch above.
    def _batched_acquire_stage():
        if time.time() - t0 > 0.95 * budget:
            raise TimeoutError("skipped: child time budget")
        ev = _load_rx_dispatch_bench().batched_acquire_stats(
            n_bytes=24 if os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
            else 100)
        note(f"batched acquire: {ev['dispatches_host_acquire']} "
             f"dispatches / {ev['t_host_acquire_s']:.3f}s -> "
             f"{ev['dispatches_batched_acquire']} dispatches / "
             f"{ev['t_batched_acquire_s']:.3f}s")
        part("batched_acquire", **ev)
        return ev

    if "batched_acquire" in resume:
        acq_ev = reuse(resume["batched_acquire"])
        note("batched acquire resumed from prior window")
    else:
        try:
            acq_ev = _batched_acquire_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"batched acquire stage failed: {e!r}")
            acq_ev = {"error": repr(e)}

    # ISSUE 3 tentpole evidence: the closed TX -> channel -> RX
    # loopback's dispatch collapse (per-frame >= 5N vs batched <= 5)
    # and frames/s, measured by the instrumented counter through the
    # shared tools module. Same resumable, never-fatal discipline.
    def _link_loopback_stage():
        if time.time() - t0 > 0.96 * budget:
            raise TimeoutError("skipped: child time budget")
        ev = _load_rx_dispatch_bench().link_loopback_stats(
            n_bytes=24 if os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
            else 100)
        note(f"link loopback: {ev['dispatches_perframe']} dispatches / "
             f"{ev['fps_perframe']:.1f} fps -> "
             f"{ev['dispatches_batched']} dispatches / "
             f"{ev['fps_batched']:.1f} fps")
        part("link_loopback", **ev)
        return ev

    if "link_loopback" in resume:
        link_ev = reuse(resume["link_loopback"])
        note("link loopback resumed from prior window")
    else:
        try:
            link_ev = _link_loopback_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"link loopback stage failed: {e!r}")
            link_ev = {"error": repr(e)}

    # ISSUE 4 tentpole evidence: the fused ONE-dispatch loopback graph
    # vs the staged path (counts, per-site dispatch times, identity
    # gate incl. batched CRC), and the one-scan BER sweep's points/s
    # vs the per-batch python loop. Same resumable never-fatal stage
    # discipline: the BENCH_* trajectory stays populated even when the
    # backend flakes.
    def _fused_link_stage():
        if time.time() - t0 > 0.96 * budget:
            raise TimeoutError("skipped: child time budget")
        ev = _load_rx_dispatch_bench().fused_link_stats(
            n_bytes=24 if os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
            else 100)
        note(f"fused link: {ev['dispatches_staged']} dispatches / "
             f"{ev['fps_staged']:.1f} fps -> "
             f"{ev['dispatches_fused']} dispatch / "
             f"{ev['fps_fused']:.1f} fps")
        part("fused_link", **ev)
        return ev

    if "fused_link" in resume:
        fused_ev = reuse(resume["fused_link"])
        note("fused link resumed from prior window")
    else:
        try:
            fused_ev = _fused_link_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"fused link stage failed: {e!r}")
            fused_ev = {"error": repr(e)}

    def _ber_sweep_stage():
        if time.time() - t0 > 0.97 * budget:
            raise TimeoutError("skipped: child time budget")
        cpu = os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
        ev = _load_rx_dispatch_bench().ber_sweep_stats(
            n_frames=8 if cpu else 16,
            n_bytes=24 if cpu else 50,
            rates=(6, 54) if cpu else (6, 24, 54))
        note(f"ber sweep: {ev['points']} points, "
             f"{ev['dispatches_loop']} loop dispatches -> "
             f"{ev['dispatches_sweep']} "
             f"({ev['points_per_s_sweep']:.2f} points/s, "
             f"{ev['sweep_sps']:.0f} bit/s)")
        part("ber_sweep", **ev)
        return ev

    if "ber_sweep" in resume:
        sweep_ev = reuse(resume["ber_sweep"])
        note("ber sweep resumed from prior window")
    else:
        try:
            sweep_ev = _ber_sweep_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"ber sweep stage failed: {e!r}")
            sweep_ev = {"error": repr(e)}

    # ISSUE 15 tentpole evidence: the channel-hostile BER gate — a
    # rates x SNR x PROFILE waterfall (named multipath/SCO/Doppler/
    # burst profiles, phy/profiles) through sweep_ber's profile axis,
    # STILL one lax.scan dispatch, asserting the flat column is
    # bit-identical to the unprofiled sweep and every hostile
    # profile's high-SNR error floor stays inside its envelope
    # (tools/rx_dispatch_bench.channel_sweep_stats). The per-profile
    # ber_floor_* values land in BENCH_TRAJECTORY (severe is the
    # ledger's gated metric, lower = better). Same resumable
    # never-fatal stage discipline.
    def _channel_sweep_stage():
        if time.time() - t0 > 0.97 * budget:
            raise TimeoutError("skipped: child time budget")
        cpu = os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
        ev = _load_rx_dispatch_bench().channel_sweep_stats(
            n_frames=4 if cpu else 8,
            n_bytes=24 if cpu else 50,
            rates=(6, 54) if cpu else (6, 24, 54),
            profiles=(("flat", "severe", "sco", "bursty", "hostile")
                      if cpu else
                      ("flat", "mild", "urban", "severe", "sco",
                       "doppler", "bursty", "hostile")))
        floors = {p: ev[f"ber_floor_{p}"] for p in ev["profiles"]}
        note(f"channel sweep: {ev['points']} points over "
             f"{len(ev['profiles'])} profiles in "
             f"{ev['dispatches_sweep']} dispatch(es), flat column "
             f"bit-identical, floors {floors} all inside envelopes")
        part("channel_sweep", **ev)
        return ev

    if "channel_sweep" in resume:
        chan_ev = reuse(resume["channel_sweep"])
        note("channel sweep resumed from prior window")
    else:
        try:
            chan_ev = _channel_sweep_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"channel sweep stage failed: {e!r}")
            chan_ev = {"error": repr(e)}

    # ISSUE 5 tentpole evidence: the streaming receiver's O(chunks)
    # dispatch count vs the per-capture path's O(frames) over the same
    # multi-frame stream, identity-gated, with the double-buffer
    # in-flight gauge. Since ISSUE 7 the stage also reports per-chunk
    # p50/p99 latency from the telemetry histogram layer and leaves a
    # Chrome trace (BENCH_TRACE_streaming.json) plus its
    # tools/trace_report.py summary next to the JSON artifacts, so
    # every bench run ships a readable timeline of the streaming loop.
    # Same resumable never-fatal stage discipline.
    def _streaming_rx_stage():
        if time.time() - t0 > 0.97 * budget:
            raise TimeoutError("skipped: child time budget")
        cpu = os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
        trace_path = os.path.join(REPO, "BENCH_TRACE_streaming.json")
        ev = _load_rx_dispatch_bench().streaming_stats(
            n_frames=8 if cpu else 16, trace_path=trace_path)
        chunk_lat = ev.get("latency_ms_streaming", {}).get(
            "rx.stream_chunk", {})
        note(f"streaming rx: {ev['frames']} frames / "
             f"{ev['chunks']} chunks, "
             f"{ev['dispatches_percapture']} dispatches -> "
             f"{ev['dispatches_streaming']} "
             f"({ev['sps_streaming']:.0f} sps, in-flight "
             f"{ev['max_in_flight']}, chunk p50/p99 "
             f"{chunk_lat.get('p50', '?')}/{chunk_lat.get('p99', '?')}"
             f" ms)")
        # trace summary smoke: the trace the stage just wrote must
        # parse; its table rides the artifact so the timeline is
        # readable without loading Perfetto
        try:
            import importlib.util
            spec = importlib.util.spec_from_file_location(
                "trace_report", os.path.join(REPO, "tools",
                                             "trace_report.py"))
            tr = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(tr)
            _summary, table = tr.summarize_file(trace_path)
            ev["trace_summary"] = table
            note("trace summary:\n" + table)
        except Exception as e:          # summary is evidence, not a gate
            ev["trace_summary_error"] = repr(e)
        part("streaming_rx", **ev)
        return ev

    if "streaming_rx" in resume:
        stream_ev = reuse(resume["streaming_rx"])
        note("streaming rx resumed from prior window")
    else:
        try:
            stream_ev = _streaming_rx_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"streaming rx stage failed: {e!r}")
            stream_ev = {"error": repr(e)}

    # ISSUE 11 tentpole evidence: S concurrent streams through the
    # stream-axis fleet receiver vs S independent single-stream
    # receivers — dispatches per chunk-step pinned <= 2 independent
    # of S, lane-for-lane bit-identity gate, and aggregate samples/s
    # vs dp device count (sps_by_devices — the mesh-scaling record).
    # Same resumable never-fatal stage discipline.
    def _multi_stream_stage():
        if time.time() - t0 > 0.97 * budget:
            raise TimeoutError("skipped: child time budget")
        cpu = os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
        ev = _load_rx_dispatch_bench().multi_stream_stats(
            n_streams=4 if cpu else 8,
            frames_per_stream=2 if cpu else 4)
        if len(ev.get("sps_by_devices", {})) <= 1:
            # a single visible device (the CPU smoke child) has no
            # in-process mesh point; measure it in a subprocess with
            # virtual devices — the dryrun_multichip mechanism, via
            # the tool's --multi-stream-mesh mode. Never fatal, and
            # genuinely bounded by the child's remaining budget:
            # under a minimum window the probe is SKIPPED, never
            # granted time the later stages no longer have.
            remaining = budget - (time.time() - t0) - 30.0
            if remaining < 60.0:
                ev["mesh_probe_error"] = "skipped: child time budget"
            else:
                env = dict(os.environ)
                n_dev = 4 if cpu else 8
                env["XLA_FLAGS"] = (
                    env.get("XLA_FLAGS", "") +
                    f" --xla_force_host_platform_device_count={n_dev}"
                ).strip()
                env["ZIRIA_TOOL_ALLOW_CPU"] = "1"
                try:
                    probe = subprocess.run(
                        [sys.executable,
                         os.path.join(REPO, "tools",
                                      "rx_dispatch_bench.py"),
                         "--multi-stream-mesh", str(n_dev)],
                        capture_output=True, text=True,
                        timeout=min(300.0, remaining), env=env,
                        cwd=REPO)
                    j = json.loads(
                        probe.stdout.strip().splitlines()[-1])
                    if "error" in j:
                        raise RuntimeError(j["error"])
                    ev["sps_by_devices_virtual"] = j["sps_by_devices"]
                    ev["mesh_scaling_virtual"] = j.get("mesh_scaling")
                    ev["mesh_virtual_devices"] = n_dev
                    note(f"multi stream mesh probe ({n_dev} virtual "
                         f"devices): sps by devices "
                         f"{j['sps_by_devices']} "
                         f"(x{j.get('mesh_scaling', '?')})")
                except Exception as e:  # probe: evidence, not a gate
                    ev["mesh_probe_error"] = repr(e)
        note(f"multi stream: {ev['streams']} streams / "
             f"{ev['chunk_steps']} chunk-steps, "
             f"{ev['dispatches_oracle']} dispatches -> "
             f"{ev['dispatches_multi']} "
             f"({ev['dispatches_per_chunk_step']}/step, "
             f"{ev['sps_multi']:.0f} sps aggregate, by devices "
             f"{ev['sps_by_devices']})")
        part("multi_stream", **ev)
        return ev

    if "multi_stream" in resume:
        multi_ev = reuse(resume["multi_stream"])
        note("multi stream resumed from prior window")
    else:
        try:
            multi_ev = _multi_stream_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"multi stream stage failed: {e!r}")
            multi_ev = {"error": repr(e)}

    # ISSUE 12 tentpole evidence: the chaos run of the multi-stream
    # fleet (tools/rx_dispatch_bench.resilience_stats) — injected
    # transient/fatal/latency/NaN-slab faults over the chunk-steps,
    # asserting ZERO crashes, healthy-lane bit-identity, quarantine
    # rejoin, and checkpoint/restore resumption; retries/fallbacks/
    # quarantines recorded. Same resumable never-fatal discipline.
    def _resilience_stage():
        if time.time() - t0 > 0.95 * budget:
            raise TimeoutError("skipped: child time budget")
        cpu = os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
        ev = _load_rx_dispatch_bench().resilience_stats(
            n_streams=4 if cpu else 8,
            frames_per_stream=2 if cpu else 3)
        note(f"resilience: {ev['faults_injected']} fault(s) injected "
             f"over {ev['chunk_steps']} chunk-steps "
             f"({ev['faults_per_100_steps']}/100 steps, by kind "
             f"{ev['faults_by_kind']}): {ev['retries']} retried, "
             f"degraded={ev['degraded']}, "
             f"{ev['quarantines']} quarantine(s) "
             f"({ev['frames_dropped_quarantined']} frame(s) dropped, "
             f"rejoined), healthy lanes bit-identical, "
             f"checkpoint roundtrip bit-identical, zero crashes")
        part("resilience", **ev)
        return ev

    if "resilience" in resume:
        res_ev = reuse(resume["resilience"])
        note("resilience resumed from prior window")
    else:
        try:
            res_ev = _resilience_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"resilience stage failed: {e!r}")
            res_ev = {"error": repr(e)}

    # ISSUE 13 tentpole evidence: the chaos SLO run of the
    # continuous-batching SERVER (tools/rx_dispatch_bench
    # .serving_stats) — N client sessions (NaN/flood/stall/oversize
    # misbehavers included) over S lanes under injected
    # transient+fatal+hang+delay dispatch faults, gating zero
    # crashes, healthy-session bit-identity, the evict→restore
    # round trip, exact shed/evict/admit accounting, and the
    # ≤ 2-dispatches-per-chunk-step budget under admission churn;
    # p50/p99 chunk latency and sustained aggregate samples/s land
    # in the artifact. Same resumable never-fatal discipline.
    def _serving_stage():
        if time.time() - t0 > 0.95 * budget:
            raise TimeoutError("skipped: child time budget")
        cpu = os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
        ev = _load_rx_dispatch_bench().serving_stats(
            n_sessions=6 if cpu else 12,
            n_lanes=4 if cpu else 8,
            frames_per_session=2 if cpu else 3)
        note(f"serving: {ev['sessions']} sessions / {ev['lanes']} "
             f"lanes, {ev['dispatches_per_chunk_step']} "
             f"dispatches/chunk-step, {ev['sps_serving']:.0f} sps "
             f"sustained, p50/p99 chunk "
             f"{ev['chunk_latency_ms'].get('p50')}/"
             f"{ev['chunk_latency_ms'].get('p99')} ms, "
             f"{ev['faults_injected']} fault(s) injected, "
             f"shed={ev['shed']} evicted={ev['evicted']} "
             f"restored={ev['restored']}, healthy sessions "
             f"bit-identical, zero crashes")
        part("serving", **ev)
        return ev

    if "serving" in resume:
        serving_ev = reuse(resume["serving"])
        note("serving resumed from prior window")
    else:
        try:
            serving_ev = _serving_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"serving stage failed: {e!r}")
            serving_ev = {"error": repr(e)}

    # ISSUE 14 tentpole evidence: the chaos-SOAK of the DURABLE
    # serving runtime (tools/soak.py) — seeded fault campaign over
    # every fault kind (dispatch + push + the new io_torn/io_enospc
    # durability seams) plus a real subprocess SIGKILL mid-chunk-step,
    # each round crash -> ServeRuntime.recover(), gating zero crashes,
    # per-session bit-identity vs the uninterrupted oracle, the
    # <= 2-dispatches-per-chunk-step budget under no_recompile after
    # recovery, and the recovery-latency SLO; recovery_p99_s (lower is
    # better) lands in the trajectory. Same resumable never-fatal
    # stage discipline.
    def _load_soak():
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "soak", os.path.join(REPO, "tools", "soak.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _soak_stage():
        if time.time() - t0 > 0.95 * budget:
            raise TimeoutError("skipped: child time budget")
        cpu = os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
        ev = _load_soak().soak_stats(
            n_sessions=3 if cpu else 6,
            n_lanes=4 if cpu else 8,
            frames_per_session=3 if cpu else 4,
            rounds=2 if cpu else 4,
            sigkill_rounds=1 if cpu else 2)
        note(f"soak: {ev['faults_injected']} fault(s) "
             f"({ev['faults_by_kind']}) over {ev['rounds']} crash "
             f"round(s) + {ev['sigkill_rounds']} SIGKILL round(s) "
             f"(killed={ev['kills']['killed']}), recovery p50/p99 "
             f"{ev['recovery_p50_s']}/{ev['recovery_p99_s']} s, "
             f"{ev['dispatches_per_chunk_step_post_recovery']} "
             f"dispatches/chunk-step after recovery, "
             f"{ev['duplicates']} at-least-once duplicate(s) "
             f"deduped by (sid, start), bit-identical, zero crashes")
        part("soak", **ev)
        return ev

    if "soak" in resume:
        soak_ev = reuse(resume["soak"])
        note("soak resumed from prior window")
    else:
        try:
            soak_ev = _soak_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"soak stage failed: {e!r}")
            soak_ev = {"error": repr(e)}

    # ISSUE 16 tentpole evidence: the geometry autotuner
    # (utils/autotune) — candidates around the default Geometry,
    # cost-pruned through the PR 9 observatory's analytical model,
    # survivors measured on the streaming + fused-link surfaces under
    # the identity gates, best-vs-default speedup recorded. The ledger
    # record (sps_tuned, higher = better) rides this stage's part()
    # with device_kind + winning geometry attached, so
    # Geometry.tuned() reconstructs it. Same resumable never-fatal
    # stage discipline.
    def _autotune_stage():
        if time.time() - t0 > 0.90 * budget:
            raise TimeoutError("skipped: child time budget")
        cpu = os.environ.get("ZIRIA_BENCH_ALLOW_CPU") == "1"
        from ziria_tpu.utils import autotune as at
        ev_full = at.run(n_frames=4 if cpu else 12,
                         n_bytes=16 if cpu else 50,
                         reps=1 if cpu else 3,
                         record=False, log=note)
        ev = {k: ev_full[k] for k in (
            "winner", "geometry", "sps_tuned", "baseline_sps",
            "speedup", "device_kind", "platform", "candidates",
            "pruned", "identity_rejected", "measured")}
        note(f"autotune: winner '{ev['winner']}' "
             f"{ev['sps_tuned']:.0f} sps ({ev['speedup']}x default), "
             f"{len(ev['pruned'])} cost-pruned, "
             f"{len(ev['identity_rejected'])} identity-rejected")
        part("autotune", **ev)
        return ev

    if "autotune" in resume:
        tune_ev = reuse(resume["autotune"])
        note("autotune resumed from prior window")
    else:
        try:
            tune_ev = _autotune_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"autotune stage failed: {e!r}")
            tune_ev = {"error": repr(e)}

    # ISSUE 8 tentpole evidence: the jaxlint static-analysis sweep —
    # per-rule finding counts (and the suppression count) over
    # ziria_tpu/, recorded in the artifact so the trend — and any
    # suppression creep — stays visible across PRs. Pure AST, never
    # touches the backend (it cannot flake with the tunnel), but it
    # rides the same resumable never-fatal stage discipline anyway.
    def _lint_stage():
        from ziria_tpu.analysis import lint_paths
        t_l = time.perf_counter()
        res = lint_paths([os.path.join(REPO, "ziria_tpu")])
        ev = {"files": res.files,
              "findings_total": len(res.findings),
              "findings_by_rule": res.counts,
              "suppressed": res.suppressed,
              "t_lint_s": round(time.perf_counter() - t_l, 3)}
        note(f"lint: {ev['findings_total']} finding(s) over "
             f"{ev['files']} file(s), {ev['suppressed']} suppressed, "
             f"{ev['t_lint_s']}s")
        part("lint", **ev)
        return ev

    if "lint" in resume:
        lint_ev = reuse(resume["lint"])
        note("lint resumed from prior window")
    else:
        try:
            lint_ev = _lint_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"lint stage failed: {e!r}")
            lint_ev = {"error": repr(e)}

    # ISSUE 9 tentpole evidence: the compiled-program observatory —
    # XLA cost/memory attribution for every live jit-factory program
    # (utils/programs), with the factory-coverage cross-check. Runs on
    # whatever backend this child has (CPU-only safe by design: the
    # observatory is exactly the attribution that must survive the
    # probe hangs). Resumable, never-fatal, budget-guarded.
    def _programs_stage():
        if time.time() - t0 > 0.90 * budget:
            raise TimeoutError("skipped: child time budget")
        from ziria_tpu.utils import programs as P
        t_p = time.perf_counter()
        rep = P.collect_programs()
        ev = {"programs_analyzed": rep["programs_analyzed"],
              "factories_discovered": rep["factories_discovered"],
              "factories_covered": rep["factories_covered"],
              "uncovered": rep["uncovered"],
              "total_flops": rep["total_flops"],
              "total_bytes_accessed": rep["total_bytes_accessed"],
              "programs": [
                  {k: r.get(k) for k in ("label", "in_avals", "flops",
                                         "bytes_accessed", "peak_bytes",
                                         "error") if r.get(k) is not None}
                  for r in rep["programs"]],
              "t_programs_s": round(time.perf_counter() - t_p, 3)}
        note(f"programs: {ev['programs_analyzed']} analyzed, "
             f"{ev['factories_covered']}/{ev['factories_discovered']} "
             f"factories covered, {ev['t_programs_s']}s")
        part("programs", **ev)
        return ev

    if "programs" in resume:
        prog_ev = reuse(resume["programs"])
        note("programs resumed from prior window")
    else:
        try:
            prog_ev = _programs_stage()
        except Exception as e:          # evidence stage: never fatal
            note(f"programs stage failed: {e!r}")
            prog_ev = {"error": repr(e)}

    def _percall_fence_stage():
        # per-call diagnostic (tunnel-dispatch-bound upper bound on
        # latency) — always taken at the base batch of 128, which may
        # differ from the promoted headline batch; recorded as such
        t_percall = _time(decode, frames, reps=50)
        note(f"t_marginal={t_tpu*1e3:.3f} ms "
             f"t_percall={t_percall*1e3:.3f} ms")

        # fence audit (VERDICT r1 weak #8): block_until_ready has been
        # observed to return before the device drains through the axon
        # tunnel. Time a chained 2k matmul with both fences; a bur/copy
        # ratio well below 1 proves the copy fence is load-bearing, ~1
        # means bur is currently honest. Recorded every run so the
        # workaround is evidence, not folklore.
        a = jnp.asarray(np.random.default_rng(3).normal(
            size=(2048, 2048)).astype(np.float32))
        mm = jax.jit(lambda x: x @ x * 1e-3)

        def chain(fence_fn, reps=10):
            o = mm(a)
            fence_fn(o)
            ts = time.perf_counter()
            for _ in range(reps):
                o = mm(o)
            fence_fn(o)
            return (time.perf_counter() - ts) / reps

        t_copy = chain(_block)
        t_bur = chain(jax.block_until_ready)
        fence_audit = round(t_bur / t_copy, 3)
        note(f"fence audit: bur/copy = {fence_audit} "
             f"({'bur returns early — copy fence required' if fence_audit < 0.8 else 'bur honest here'})")
        pf = {"t_percall_s": t_percall, "t_percall_batch": 128,
              "fence_audit_bur_over_copy": fence_audit}
        part("percall_fence", **pf)
        return pf

    if "percall_fence" in resume:
        pf = reuse(resume["percall_fence"])
        note("per-call + fence audit resumed from prior window")
    else:
        try:
            pf = _percall_fence_stage()
        except Exception as e:          # diagnostic: never fatal
            note(f"percall/fence stage failed: {e!r}")
            pf = {"error": repr(e)}

    out = {
        "tpu_sps": sps,
        "t_step_s": t_tpu,
        "timing_method": timing_method,
        "batch": B,
        "frame_bytes": n_psdu_bits // 8,
        "batch_sweep": {str(b): round(t, 6) for b, t in sorted(sweep.items())},
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", "?"),
        "pallas_mosaic": pallas_mosaic,
        "windowed": winrec,
        "decompose": decomp,
        "framebatch": fb,
        "fxp_interior": fxp_ev,
        "tx_chain": tx_ev,
        "micro": micro_ev,
        "quantized_viterbi": quant_ev,
        "viterbi_breakdown": vbrk_ev,
        "viterbi_kernel_stats": vlev_ev,
        "mixed_dispatch": mixed_ev,
        "batched_acquire": acq_ev,
        "link_loopback": link_ev,
        "fused_link": fused_ev,
        "ber_sweep": sweep_ev,
        "channel_sweep": chan_ev,
        "streaming_rx": stream_ev,
        "multi_stream": multi_ev,
        "resilience": res_ev,
        "serving": serving_ev,
        "soak": soak_ev,
        "autotune": tune_ev,
        "lint": lint_ev,
        "programs": prog_ev,
        "roofline": _roofline(
            B, frame_len, n_sym, n_psdu_bits, t_tpu,
            device_kind=dev_kind,
            cost=None if headline_is_windowed else _decode_cost(B)),
        "resumed_stages": sorted(set(resumed_stages)),
    }
    for k in ("t_percall_s", "t_percall_batch",
              "fence_audit_bur_over_copy"):
        if k in pf:
            out[k] = pf[k]
    _partial(run_id, "complete", **out)
    print(json.dumps(out), flush=True)


def _run_one_child(argv, tmo: int):
    """One bounded child attempt. Runs the child in its own process
    group and kills the WHOLE group on timeout: the axon runtime spawns
    helper processes that inherit the output pipes, and killing only
    the direct child would leave subprocess.run blocked on pipe EOF —
    the exact unbounded hang this harness exists to prevent."""
    import signal

    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)] + argv,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, start_new_session=True)
    try:
        out, errtxt = proc.communicate(timeout=tmo)
        return proc.returncode, out, errtxt
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        return None, "", ""


_PROBE_NEG = None     # this-invocation memo of a definitive probe failure


def _probe_record_time(rec):
    """A ledger record's unix time: the `unix` stamp bench.py writes,
    else the watcher's ISO-8601 `t` parsed as UTC; None if neither."""
    if isinstance(rec.get("unix"), (int, float)):
        return float(rec["unix"])
    try:
        import calendar
        return float(calendar.timegm(time.strptime(
            rec.get("t", ""), "%Y-%m-%dT%H:%M:%SZ")))
    except (ValueError, TypeError):
        return None


def _probe_ledger_recent_failure(now=None, path=None, ttl=None):
    """The most recent probe outcome within `ttl`, if it was a
    failure: returns an age-stamped description, else None. A later
    "ok" supersedes an earlier "fail" (the tunnel came back); "busy"
    records are neither (another client held the flag — says nothing
    about tunnel health). Garbage lines are skipped."""
    now = time.time() if now is None else now
    path = PROBES_PATH if path is None else path
    if ttl is None:
        try:
            ttl = float(os.environ.get("BENCH_PROBE_NEG_TTL",
                                       str(PROBE_NEG_TTL)))
        except ValueError:
            ttl = PROBE_NEG_TTL
    if ttl <= 0:
        return None
    last_t, last_kind, last_err = None, None, None
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                kind = rec.get("probe")
                if kind not in ("ok", "fail"):
                    continue
                t = _probe_record_time(rec)
                if t is None or t > now:
                    continue
                if last_t is None or t >= last_t:
                    last_t, last_kind = t, kind
                    last_err = rec.get("err")
    except OSError:
        return None
    if last_kind == "fail" and now - last_t < ttl:
        return (f"probe failed {now - last_t:.0f}s ago"
                + (f" ({last_err})" if last_err else "")
                + f" — skipped (BENCH_PROBES.jsonl, ttl {ttl:.0f}s)")
    return None


def _probe_ledger_record(kind: str, err=None) -> None:
    """Append this probe outcome to the availability ledger (the same
    file/format tools/tpu_watcher.sh appends to, plus a unix stamp and
    the error text). Best-effort: an unwritable ledger never blocks a
    bench run."""
    rec = {"t": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "probe": kind, "unix": round(time.time(), 1),
           "src": "bench.py"}
    if err:
        rec["err"] = err
    try:
        with open(PROBES_PATH, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass


def _probe(deadline):
    """Health-check the backend cheaply. Returns (ok, err).

    A NEGATIVE result is cached for the rest of this invocation
    (module-level memo) and a probe *timeout* is treated as
    definitive immediately: a hang means the axon tunnel is down, not
    a transient child flake, and BENCH_r05 measured the same 90 s
    hang re-paid 2-3x per run (~200 s of a ~540 s deadline burned on
    repeats of a known answer). Transient non-zero exits still retry
    up to PROBE_TRIES; only the retry-proof failure modes memoize.

    Definitive outcomes also persist to BENCH_PROBES.jsonl, and a
    ledger failure younger than BENCH_PROBE_NEG_TTL (default 600 s,
    0 disables) is trusted WITHOUT re-probing — repeat invocations
    inside one dark window (driver retries, back-to-back harvests)
    stop re-paying the same 90 s hang. A later "ok" in the ledger
    (e.g. the watcher's) supersedes the failure.
    """
    global _PROBE_NEG
    if _PROBE_NEG is not None:
        return False, f"{_PROBE_NEG} (cached: probed once this " \
                      f"invocation, not re-paying the probe)"
    ledger = _probe_ledger_recent_failure()
    if ledger is not None:
        _PROBE_NEG = ledger
        return False, ledger
    err = None
    for i in range(PROBE_TRIES):
        if time.time() + PROBE_TIMEOUT + 30 > deadline:
            return False, err or "deadline before probe"
        if i:
            time.sleep(PROBE_BACKOFF)
        rc, out, errtxt = _run_one_child(["--tpu-probe"], PROBE_TIMEOUT)
        if rc is None:
            err = f"probe {i + 1}: timeout after {PROBE_TIMEOUT}s (hang)"
            print(f"[bench] {err}", file=sys.stderr, flush=True)
            _PROBE_NEG = err
            _probe_ledger_record("fail", err)
            return False, err
        elif rc == 0:
            _probe_ledger_record("ok")
            return True, None
        else:
            tail = (errtxt or "").strip().splitlines()[-2:]
            err = f"probe {i + 1}: rc={rc}: " + " | ".join(tail)
        print(f"[bench] {err}", file=sys.stderr, flush=True)
    _PROBE_NEG = err
    _probe_ledger_record("fail", err)
    return False, err


def _recover_partial(run_id):
    """Pull the best measured stage out of BENCH_PARTIAL.jsonl for this
    run (the child was killed after measuring but before printing).
    "Best" = highest tpu_sps: batch-sweep partials also carry tpu_sps,
    and a slower sweep width must not shadow the recorded headline."""
    try:
        best = None
        with open(PARTIAL_PATH) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if (rec.get("run_id") == run_id and "tpu_sps" in rec
                        and (best is None
                             or rec["tpu_sps"] >= best["tpu_sps"])):
                    best = rec
        return best
    except OSError:
        return None


BUSY_FLAG = "/tmp/tpu_busy"
BUSY_STALE_S = 35 * 60


def _acquire_tpu(deadline):
    """Take the /tmp/tpu_busy mutual-exclusion flag the watcher honors.

    Two clients touching the axon backend concurrently both hang, so
    every TPU consumer (watcher harvest, driver bench, manual runs)
    serializes on this flag. If another holder is active we wait for it
    to clear (it may be the watcher mid-harvest — whose result then
    lands in BENCH_LIVE.json and becomes our ``last_good``); a flag
    older than BUSY_STALE_S is treated as leaked and taken over.
    Returns True if acquired.

    ``TPU_BUSY_HELD=1`` means the invoker (tools/tpu_watcher.sh) already
    holds the flag on our behalf — skip acquisition (and release).
    """
    if os.environ.get("TPU_BUSY_HELD") == "1":
        return True
    while True:
        try:
            fd = os.open(BUSY_FLAG, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, f"bench.py pid={os.getpid()}\n".encode())
            os.close(fd)
            return True
        except FileExistsError:
            try:
                age = time.time() - os.path.getmtime(BUSY_FLAG)
            except OSError:
                continue  # holder just released; retry the create
            if age > BUSY_STALE_S:
                print(f"[bench] stale {BUSY_FLAG} ({age:.0f}s) — taking over",
                      file=sys.stderr, flush=True)
                try:
                    os.unlink(BUSY_FLAG)
                except OSError:
                    pass
                continue
            if time.time() + 30 > deadline:
                return False
            time.sleep(10)


def _release_tpu():
    if os.environ.get("TPU_BUSY_HELD") == "1":
        return
    try:
        with open(BUSY_FLAG) as f:
            if "bench.py" not in f.read():
                return  # not ours
        os.unlink(BUSY_FLAG)
    except OSError:
        pass


def _pinned_baseline():
    """The committed, load-isolated baseline denominator (VERDICT r4
    missing #2): BASELINE.json's ``pinned_baseline`` entry, written by
    ``bench.py --pin-baseline`` on an idle box. Every published chip
    multiple divides by THIS number so the flagship claim cannot float
    with whatever else the host happens to be running."""
    try:
        with open(BASELINE_PATH) as f:
            pin = json.load(f).get("pinned_baseline")
        if pin and pin.get("sps"):
            return pin
    except (OSError, json.JSONDecodeError):
        pass
    return None


def _pin_baseline_main(n_runs):
    """Measure the numpy+C-AVX2 baseline N times and pin the max.

    The denominator must not swing with host load (r4 saw 4.08-6.40 M
    sps for the same code depending on what else was running), and it
    must be the number most favorable to the BASELINE: concurrent load
    can only slow the baseline down, so the fastest of N runs is the
    closest observation of the uncontended machine — and dividing by
    it yields the SMALLEST (most conservative) chip multiple. The max,
    the median, and every raw run are committed so the spread is
    inspectable.
    """
    import jax
    jax.config.update("jax_platforms", "cpu")
    rate, n_sym, n_psdu_bits, frame_len, frame, want = _setup()
    got = np_rx_decode(frame, rate, n_sym, n_psdu_bits)
    assert np.array_equal(got, want), "baseline decode mismatch"

    sps_runs, vit_runs = [], []
    from ziria_tpu.runtime.native_lib import load, viterbi_decode_native
    have_native = load() is not None
    nb = n_psdu_bits + 16 + 6
    dep = np.random.default_rng(2).normal(size=(nb, 2)).astype(np.float32)
    for i in range(n_runs):
        t_np = _time(np_rx_decode, frame, rate, n_sym, n_psdu_bits,
                     reps=3, fence=lambda o: None)
        sps_runs.append(frame_len / t_np)
        if have_native:
            t_v = _time(viterbi_decode_native, dep, reps=5,
                        fence=lambda o: None)
            vit_runs.append(nb / t_v / 1e6)
        print(f"[pin-baseline] run {i + 1}/{n_runs}: "
              f"{sps_runs[-1] / 1e6:.2f} M sps"
              + (f", viterbi {vit_runs[-1]:.1f} Mb/s"
                 if vit_runs else ""), file=sys.stderr, flush=True)
        time.sleep(1)

    # historical observations are REPORTED CONTEXT ONLY, never
    # denominator inputs (ADVICE r5 #3): folding every committed
    # BENCH_r0*.json into the max made the pin a one-way upward
    # ratchet — a single noisy-high historical point permanently
    # deflated all future chip multiples and no re-pin could revise it
    # down. The pin now comes from THIS pin's controlled runs alone.
    import glob
    hist = {}
    for p in sorted(glob.glob(os.path.join(REPO, "BENCH_r0*.json"))):
        try:
            with open(p) as f:
                j = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        j = (j.get("parsed") or j) if isinstance(j, dict) else {}
        for node in (j, j.get("last_good") or {}):
            v = node.get("numpy_baseline_sps")
            if v:
                hist[os.path.basename(p)] = max(
                    hist.get(os.path.basename(p), 0.0), float(v))

    # trimmed max of the current runs: with >= 4 runs the single
    # highest observation is dropped before taking the max, so one
    # spurious timer glitch cannot set the denominator; below that
    # there is no headroom to trim and the plain max stands
    srt = sorted(sps_runs)
    trimmed = srt[:-1] if n_runs >= 4 else srt
    pin = {
        "sps": round(max(trimmed), 1),
        "sps_max_this_pin": round(max(sps_runs), 1),
        "sps_historical": {k: round(v, 1) for k, v in hist.items()},
        "sps_median": round(float(np.median(sps_runs)), 1),
        "sps_runs": [round(s, 1) for s in sps_runs],
        "viterbi_c_simd_mbps": (round(max(vit_runs), 2)
                                if vit_runs else None),
        "n_runs": n_runs,
        "pinned_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "recipe": ("python bench.py --pin-baseline: numpy RX chain + C "
                   "AVX2 Viterbi, 1000-byte 54 Mbps frame, N runs of "
                   "_time(reps=3); pinned value = TRIMMED MAX over "
                   "these controlled runs only (top run dropped when "
                   "N >= 4 — one timer glitch must not set the "
                   "denominator); committed BENCH_r0*.json "
                   "observations are recorded as sps_historical "
                   "context and do NOT enter the denominator, so a "
                   "legitimate re-pin can revise it in either "
                   "direction (ADVICE r5 #3)"),
        "spread_pct": round(100 * (max(sps_runs) - min(sps_runs))
                            / float(np.median(sps_runs)), 1),
    }
    try:
        with open(BASELINE_PATH) as f:
            base = json.load(f)
    except (OSError, json.JSONDecodeError):
        base = {}
    base["pinned_baseline"] = pin
    tmp = BASELINE_PATH + ".pin.tmp"
    with open(tmp, "w") as f:
        json.dump(base, f, indent=2)
        f.write("\n")
    os.replace(tmp, BASELINE_PATH)
    print(json.dumps(pin))


def _last_good():
    """Most recent watcher-harvested full result, if any.

    A result carrying ``value_source`` is itself a promotion of an
    older capture (the TPU was unreachable when it was produced) —
    never re-accept one as a fresh capture, or a week-old number could
    be re-dated on every watcher cycle. The capture time comes from
    the ``captured_at_unix`` stamped INSIDE a fresh chip result (file
    mtime only as a legacy fallback) for the same reason: an mtime
    resets whenever anything rewrites the file.
    """
    try:
        with open(LIVE_PATH) as f:
            j = json.load(f)
        if (j.get("platform") and j["platform"] != "cpu"
                and not j.get("value_source")):
            j["captured_unix_mtime"] = j.get(
                "captured_at_unix", os.path.getmtime(LIVE_PATH))
            return j
    except (OSError, json.JSONDecodeError):
        pass
    return None


# ------------------------------------------------------------------ parent

def main():
    start = time.time()
    ap = argparse.ArgumentParser()
    ap.add_argument("--tpu-child", action="store_true",
                    help="internal: run the TPU measurement")
    ap.add_argument("--tpu-probe", action="store_true",
                    help="internal: cheap backend health check")
    ap.add_argument("--run-id", default=None)
    ap.add_argument("--no-tpu", action="store_true",
                    help="skip the TPU child (numpy baseline only)")
    ap.add_argument("--pin-baseline", nargs="?", const=7, type=int,
                    default=None, metavar="N",
                    help="measure the CPU baseline N times and pin the "
                         "max (incl. historical BENCH_r0*.json "
                         "observations) into BASELINE.json")
    args = ap.parse_args()

    if args.tpu_probe:
        _probe_main()
        return
    if args.tpu_child:
        _child_main(args.run_id or "adhoc")
        return
    if args.pin_baseline is not None:
        _pin_baseline_main(max(3, args.pin_baseline))
        return

    deadline = start + float(os.environ.get("BENCH_SELF_DEADLINE", "540"))
    run_id = f"r{int(start)}"

    # Parent stays on CPU no matter what the axon plugin wants
    # (jax.config wins over the plugin; see tests/conftest.py).
    import jax
    jax.config.update("jax_platforms", "cpu")

    rate, n_sym, n_psdu_bits, frame_len, frame, want = _setup()

    # numpy-baseline correctness gate, then timing
    got_np = np_rx_decode(frame, rate, n_sym, n_psdu_bits)
    assert np.array_equal(got_np, want), "numpy baseline decode mismatch"
    t_np = _time(np_rx_decode, frame, rate, n_sym, n_psdu_bits, reps=3,
                 fence=lambda o: None)
    sps_np = frame_len / t_np

    # the baseline's own hot-kernel throughput, so the ratio's
    # denominator is inspectable. Since round 3 the C ACS is AVX2
    # SIMD (runtime/native/viterbi.c) — a fair stand-in for the
    # reference's hand-SIMD SORA brick, per VERDICT r2 #4.
    from ziria_tpu.runtime.native_lib import load, viterbi_decode_native
    vit_c_mbps = None
    if load() is not None:
        nb = (n_psdu_bits + 16 + 6)
        dep = np.random.default_rng(2).normal(
            size=(nb, 2)).astype(np.float32)
        t_v = _time(viterbi_decode_native, dep, reps=5, fence=lambda o: None)
        vit_c_mbps = round(nb / t_v / 1e6, 2)

    # the ratio denominator: the pinned, load-isolated baseline if one
    # is committed (BASELINE.json pinned_baseline), else this run's
    # measurement. The this-run number is always reported alongside so
    # host-load contamination of the box is visible, not hidden.
    pin = _pinned_baseline()
    denom = pin["sps"] if pin else sps_np
    # perf ledger: this run's baseline measurement, normalized
    _traj_append("numpy_baseline", "sps", round(sps_np, 1), run_id,
                 "cpu")

    result = {
        "metric": "80211a_rx_samples_per_sec_per_chip",
        "unit": "samples/s",
        "numpy_baseline_sps": round(sps_np, 1),
        "viterbi_c_simd_mbps": vit_c_mbps,
    }
    if pin:
        result["pinned_baseline_sps"] = pin["sps"]
        result["baseline_pinned_at"] = pin.get("pinned_at")

    child, err = None, None
    if args.no_tpu:
        err = "skipped (--no-tpu)"
    elif not _acquire_tpu(deadline):
        err = "TPU busy (another holder of /tmp/tpu_busy) until deadline"
    else:
        try:
            ok, perr = _probe(deadline)
            if not ok:
                err = perr or "probe failed"
            else:
                # retry while the deadline allows — BENCH_r01 died to a
                # single transient rc=1 that a cheap retry would have fixed
                attempt = 0
                while child is None:
                    attempt += 1
                    budget = int(min(CHILD_TIMEOUT_MAX,
                                     deadline - time.time() - 20))
                    if budget < 60:
                        err = err or "deadline too close after probe"
                        break
                    # the child's stage guards key off the REAL kill
                    # budget, not a guess (inherited environment)
                    os.environ["BENCH_CHILD_BUDGET"] = str(budget)
                    rc, out, errtxt = _run_one_child(
                        ["--tpu-child", "--run-id", run_id], budget)
                    if rc == 0:
                        try:
                            child = json.loads(out.strip().splitlines()[-1])
                            err = None
                            break
                        except (json.JSONDecodeError, IndexError):
                            err = f"attempt {attempt}: unparseable child stdout"
                    else:
                        err = (f"attempt {attempt}: child timeout after "
                               f"{budget}s" if rc is None
                               else "attempt %d: child rc=%s: %s" % (
                                   attempt, rc,
                                   " | ".join((errtxt or "").strip()
                                              .splitlines()[-3:])))
                    print(f"[bench] {err}", file=sys.stderr, flush=True)
                    # the child logs each completed stage — recover the
                    # headline measurement if it got that far (covers
                    # both kill-after-measure and corrupted stdout)
                    part = _recover_partial(run_id)
                    if part is not None:
                        child = part
                        child["partial"] = True
                        print(f"[bench] recovered partial headline from "
                              f"{PARTIAL_PATH}", file=sys.stderr, flush=True)
                        break
                    if time.time() + 90 > deadline:
                        break
                    time.sleep(10)
        finally:
            _release_tpu()
        if err and child is None:
            print(f"[bench] {err}", file=sys.stderr, flush=True)

    if child is not None and child.get("platform") == "cpu":
        # a smoke-mode child (ZIRIA_BENCH_ALLOW_CPU leaked into a real
        # run) must never publish CPU throughput as a per-chip number
        err = "child ran on cpu (smoke mode leaked?) — result refused"
        child = None

    if child is not None:
        result["value"] = round(child["tpu_sps"], 1)
        result["vs_baseline"] = round(child["tpu_sps"] / denom, 3)
        # the capture time rides INSIDE the JSON so later copies /
        # rewrites of the file cannot re-date the measurement
        result["captured_at_unix"] = round(time.time(), 1)
        for k in ("platform", "device_kind", "batch", "t_step_s",
                  "t_percall_s", "t_percall_batch",
                  "fence_audit_bur_over_copy",
                  "timing_method", "pallas_mosaic", "roofline",
                  "batch_sweep", "windowed", "decompose", "framebatch",
                  "fxp_interior", "tx_chain", "micro", "frame_bytes",
                  "viterbi_breakdown", "viterbi_kernel_stats",
                  "programs", "partial", "resumed_stages"):
            if k in child:
                result[k] = child.get(k)
        if err:
            result["tpu_error"] = err
    else:
        # TPU unreachable this run. A recent watcher-harvested capture
        # is promoted to the FIRST-CLASS headline (VERDICT r4 weak #1:
        # four rounds of "value = CPU baseline" buried the real chip
        # number in a nested appendix), clearly labelled with its
        # capture time; the full capture rides along as last_good.
        result["tpu"] = "unavailable_this_invocation"
        result["tpu_error"] = err
        lg = _last_good()
        if lg is not None:
            result["last_good"] = lg
        age_h = (None if lg is None else
                 (time.time() - lg["captured_unix_mtime"]) / 3600.0)
        # 48 h window: the axon backend stays dark for >24 h at a
        # stretch (probe ledger), and an honestly-dated real capture
        # in the primary field beats reprinting the CPU baseline —
        # the exact failure VERDICT r4 weak #1 flagged. value_source
        # always states the capture time and age.
        if lg is not None and age_h < 48.0:
            result["value"] = lg["value"]
            result["vs_baseline"] = round(lg["value"] / denom, 3)
            for k in ("platform", "device_kind", "batch", "t_step_s",
                      "timing_method", "pallas_mosaic", "roofline",
                      "partial"):
                if k in lg:
                    result[k] = lg[k]
            result["value_source"] = (
                "watcher-harvested TPU capture "
                + time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                time.gmtime(lg["captured_unix_mtime"]))
                + f" ({age_h:.1f}h before this invocation); backend "
                  "was unreachable during this invocation itself")
        else:
            # no chip capture fresh enough to stand behind: the
            # baseline is the only honest number this invocation has
            result["value"] = round(sps_np, 1)
            result["vs_baseline"] = round(sps_np / denom, 3)

    result["bench_wall_s"] = round(time.time() - start, 1)
    # perf ledger: the run's published headline, normalized (platform
    # tells a cpu-fallback value apart from a chip number; resumed
    # marks a last_good promotion rather than a fresh capture)
    if result.get("value") is not None:
        _traj_append("result", "rx_sps", result["value"], run_id,
                     result.get("platform") or "cpu",
                     partial=bool(result.get("partial")),
                     resumed=bool(result.get("value_source")),
                     unit="samples/s")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
