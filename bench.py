"""Benchmark entry point — prints ONE JSON line.

Current flagship config (will upgrade as the PHY lands, BASELINE.md):
config #1, the FIR low-pass stream pipeline, fused by the jit backend and
run on the default JAX device. Baseline is a self-measured numpy
(C-speed, vectorized) implementation of the same semantics on the host
CPU, per BASELINE.md's "self-measured baseline" policy — the reference
mount was empty, so there are no published numbers to compare against.
"""

import json
import time

import numpy as np


def _block(out):
    if hasattr(out, "block_until_ready"):
        out.block_until_ready()
    elif isinstance(out, tuple) and hasattr(out[0], "block_until_ready"):
        out[0].block_until_ready()


def _time(fn, *args, reps=5):
    _block(fn(*args))  # warm-up / compile, fully drained before timing
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _block(out)  # jax async dispatch: drain before stopping the clock
    return (time.perf_counter() - t0) / reps


def main():
    import jax
    import jax.numpy as jnp

    import ziria_tpu as z
    from ziria_tpu.backend.lower import lower

    n = 1 << 20  # 1M samples
    taps = np.array([0.0625, 0.25, 0.375, 0.25, 0.0625], dtype=np.float32)
    k = taps.size
    xs = np.random.default_rng(0).standard_normal(n).astype(np.float32)

    # --- numpy baseline: same FIR semantics (causal, zero-initial state)
    def np_fir(x):
        return np.convolve(x, taps)[: x.size].astype(np.float32)

    t_np = _time(np_fir, xs)

    # --- ziria_tpu: chunked FIR block (overlap-save) as an arity-N map_accum
    CH = 4096

    def fir_chunk(state, chunk):
        ext = jnp.concatenate([state, chunk])
        y = jnp.convolve(ext, jnp.asarray(taps), mode="valid",
                         precision="highest")
        return ext[-(k - 1):], y

    prog = z.map_accum(fir_chunk, np.zeros(k - 1, np.float32),
                       in_arity=CH, out_arity=CH, name="fir_os")
    lw = lower(prog, width=1)
    scan = jax.jit(lw.scan_steps())
    chunks = jnp.asarray(xs.reshape(-1, CH))

    def run(c):
        carry, ys = scan(lw.init_carry, c)
        return ys

    t_jax = _time(run, chunks)

    # correctness gate: bench numbers only count if outputs agree
    got = np.asarray(run(chunks)).reshape(-1)
    ref = np_fir(xs)
    assert np.allclose(got, ref, atol=1e-4), "bench output mismatch"

    sps = n / t_jax
    print(json.dumps({
        "metric": "fir_lowpass_samples_per_sec",
        "value": round(sps, 1),
        "unit": "samples/s",
        "vs_baseline": round(t_np / t_jax, 3),
    }))


if __name__ == "__main__":
    main()
