"""Benchmark entry point — prints ONE JSON line.

Flagship metric (BASELINE.json): **802.11a OFDM RX samples/sec/chip** —
the batched steady-state DATA decode (channel est + matmul-FFT +
equalize + pilot tracking + soft demap + deinterleave + Viterbi +
descramble) at 54 Mbps, frames batched on one chip.

Baseline (BASELINE.md self-measured policy — the reference mount was
empty): the same receiver chain implemented in straightforward
vectorized numpy on the host CPU (np.fft, gather deinterleave, 64-state
vectorized-ACS Viterbi) — a stand-in for the reference's single-core C
backend. The correctness gate requires the decoded PSDU to equal the
transmitted bits before any number is printed.
"""

import json
import time

import numpy as np


def _block(out):
    """Force completion of everything queued before `out`.

    block_until_ready() under the axon tunnel returns before the device
    is actually done (measured: it reported rates exceeding HBM
    bandwidth); a tiny device->host copy of the result is an honest
    fence because transfers are ordered after the producing computation.
    """
    import jax
    leaves = [a for a in jax.tree.leaves(out) if hasattr(a, "ndim")]
    for a in leaves[-1:]:
        np.asarray(a.ravel()[:1] if a.ndim else a)


def _time(fn, *args, reps=5):
    """Average seconds per call: queue `reps` async calls, fence once.

    reps amortizes the host<->device round-trip (~70 ms through the axon
    tunnel) which would otherwise dominate millisecond-scale kernels.
    """
    _block(fn(*args))  # warm-up / compile, fully drained before timing
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _block(out)
    return (time.perf_counter() - t0) / reps


# ------------------------------------------------------------------ numpy RX

def np_rx_decode(frame, rate, n_sym, n_psdu_bits):
    """Host-CPU receiver chain (numpy), the perf baseline."""
    from ziria_tpu.ops.coding import PUNCTURE_KEEP
    from ziria_tpu.ops.interleave import deinterleave_perm
    from ziria_tpu.ops.ofdm import (DATA_BINS, LTS_FREQ, PILOT_BINS,
                                    PILOT_POLARITY, PILOT_VALS, TIME_SCALE)
    from ziria_tpu.ops.scramble import np_lfsr_sequence_127
    from ziria_tpu.ops.viterbi import _OUT_A, _OUT_B, _PRED

    x = frame[..., 0] + 1j * frame[..., 1]
    # channel estimate from LTS
    ref = np.zeros(64, np.float32)
    ref[np.arange(-26, 27) % 64] = LTS_FREQ
    H = ((np.fft.fft(x[192:256]) + np.fft.fft(x[256:320])) * 0.5
         / TIME_SCALE) * ref
    Hd = H[DATA_BINS]
    gain = np.abs(Hd) ** 2

    syms = x[400: 400 + 80 * n_sym].reshape(n_sym, 80)[:, 16:]
    bins = np.fft.fft(syms, axis=-1) / TIME_SCALE
    eq = bins / np.where(H == 0, 1.0, H)[None, :]
    data = eq[:, DATA_BINS]
    pilots = eq[:, PILOT_BINS]
    pol = PILOT_POLARITY[(np.arange(n_sym) + 1) % 127]
    expect = PILOT_VALS[None, :] * pol[:, None]
    ph = np.angle((pilots * expect).sum(-1))
    data = data * np.exp(-1j * ph)[:, None]

    # 64-QAM demap
    i = data.real * np.sqrt(42.0)
    q = data.imag * np.sqrt(42.0)
    llr = np.stack([i, 4 - np.abs(i), 2 - np.abs(np.abs(i) - 4),
                    q, 4 - np.abs(q), 2 - np.abs(np.abs(q) - 4)],
                   axis=-1) * gain[None, :, None]
    llr = llr.reshape(n_sym, -1)
    perm = deinterleave_perm(rate.n_cbps, rate.n_bpsc)
    deint = llr[:, perm].reshape(-1)

    keep = PUNCTURE_KEEP[rate.coding]
    nblk = deint.size // keep.sum()
    dep = np.zeros((nblk, keep.size), np.float32)
    dep[:, np.flatnonzero(keep)] = deint.reshape(nblk, keep.sum())
    dep = dep.reshape(-1, 2)

    # Viterbi: native C decoder (the honest C-backend stand-in; the
    # reference's hot kernel is a C SORA brick). Fall back to a python
    # ACS loop only if no toolchain exists — that fallback is NOT a fair
    # baseline and the ratio should be read accordingly.
    from ziria_tpu.runtime.native_lib import load, viterbi_decode_native
    if load() is not None:
        bits = viterbi_decode_native(dep)
    else:
        metrics = np.full(64, -1e30, np.float32)
        metrics[0] = 0.0
        T = dep.shape[0]
        decisions = np.zeros((T, 64), np.uint8)
        for k in range(T):
            cand = metrics[_PRED] + _OUT_A * dep[k, 0] + _OUT_B * dep[k, 1]
            decisions[k] = np.argmax(cand, 1)
            metrics = cand.max(1)
            metrics -= metrics.max()
        state = int(np.argmax(metrics))
        bits = np.zeros(T, np.uint8)
        for k in range(T - 1, -1, -1):
            bits[k] = state >> 5
            state = _PRED[state, decisions[k, state]]

    seq = np.resize(np_lfsr_sequence_127(np.ones(7, np.uint8)), bits.size)
    clear = bits ^ seq  # descramble (fixed seed stand-in, same op count)
    return clear[16: 16 + n_psdu_bits]  # 16 SERVICE bits, then the PSDU


def main():
    import jax
    import jax.numpy as jnp

    from ziria_tpu.phy.wifi import rx, tx
    from ziria_tpu.phy.wifi.params import RATES, n_symbols
    from ziria_tpu.utils.bits import bytes_to_bits

    rate = RATES[54]
    n_bytes = 1000
    n_sym = n_symbols(n_bytes, rate)
    n_psdu_bits = 8 * n_bytes
    frame_len = 400 + 80 * n_sym

    rng = np.random.default_rng(0)
    psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
    frame = np.asarray(tx.encode_frame(psdu, 54))

    # correctness gate
    got, _ = rx.decode_data_static(jnp.asarray(frame), rate, n_sym,
                                   n_psdu_bits)
    want = np.asarray(bytes_to_bits(psdu))
    assert np.array_equal(np.asarray(got), want), "bench RX decode mismatch"

    # --- TPU: batched frames through the Pallas-Viterbi fast path
    B = 128
    frames = jnp.asarray(np.broadcast_to(frame, (B,) + frame.shape).copy())

    decode = jax.jit(
        lambda f: rx.decode_data_batch(f, rate, n_sym, n_psdu_bits)[0])
    got_b = np.asarray(decode(frames))
    assert np.array_equal(got_b[0], want) and np.array_equal(got_b[-1], want)
    t_tpu = _time(decode, frames, reps=50)
    sps = B * frame_len / t_tpu

    # --- numpy baseline (single frame, scaled)
    t_np = _time(np_rx_decode, frame, rate, n_sym, n_psdu_bits, reps=3)
    sps_np = frame_len / t_np

    print(json.dumps({
        "metric": "80211a_rx_samples_per_sec_per_chip",
        "value": round(sps, 1),
        "unit": "samples/s",
        "vs_baseline": round(sps / sps_np, 3),
    }))


if __name__ == "__main__":
    main()
