"""Hybrid backend (backend/hybrid.py): interpreter-driven control with
jit-compiled heavy do-blocks. The flagship DSL receiver must produce
bit-identical output to the pure interpreter (the oracle), with its DSP
blocks running as compiled XLA — the TPU answer to the reference
compiling ALL of its dynamic control to C (SURVEY.md §2.1)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ziria_tpu.backend import hybrid as H
from ziria_tpu.core import ir
from ziria_tpu.frontend import compile_file, compile_source
from ziria_tpu.interp.interp import run

SRC = os.path.join(os.path.dirname(__file__), "..", "examples",
                   "wifi_rx.zir")


def _capture(mbps, n_bytes, seed, cfo=0.002):
    from ziria_tpu.phy import channel
    # FCS appended: the in-language receiver validates and strips it
    return channel.impaired_capture(mbps, n_bytes, seed, cfo=cfo,
                                    add_fcs=True)


@pytest.mark.parametrize("mbps,n_bytes", [(6, 30), (24, 60), (54, 90)])
def test_wifi_rx_hybrid_matches_interp(mbps, n_bytes):
    psdu, xi = _capture(mbps, n_bytes, seed=mbps)
    prog = compile_file(SRC)
    want = run(prog.comp, [p for p in xi]).out_array()
    got = H.run_hybrid(prog.comp, [p for p in xi]).out_array()
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert np.asarray(want).shape[0] == 8 * n_bytes


def test_hybrid_blocks_actually_jit():
    # the receiver's heavy blocks must be wrapped (not silently broken):
    # run once, then check every wrapper that fired compiled a fn and
    # is not in fallback mode
    psdu, xi = _capture(6, 30, seed=99)
    hyb = H.hybridize(compile_file(SRC).comp)
    wrappers = []

    def walk(c):
        if isinstance(c, ir.Return) and isinstance(c.expr, H._JitDo):
            wrappers.append(c.expr)
        ir.map_children(c, lambda ch, _b: (walk(ch), ch)[1])

    walk(hyb)
    assert len(wrappers) >= 9          # window block + 8 rate branches
    run(hyb, [p for p in xi])
    fired = [w for w in wrappers if w._fns]
    assert fired, "no do-block ever reached jit"
    assert all(not w._broken for w in fired), \
        [w for w in fired if w._broken]


def test_bit_arithmetic_promotes_past_uint8_under_jit():
    # `pw * b` with pw=256 and a data-dependent bit must be 256, not
    # uint8-wrapped 0: C promotion covers the unsigned narrows on BOTH
    # paths (found as a SIGNAL-length misparse on 1000-byte frames —
    # bits 8/9 of the length field silently vanished under jit)
    from ziria_tpu.backend.execute import run_jit
    src = """
    fun weigh(b: arr[12] bit) : int32 {
      var acc : int32 := 0;
      var pw : int32 := 1;
      for t in [0, 12] {
        acc := acc + pw * b[t];
        pw := pw * 2
      }
      return acc
    }
    let comp main = read[bit] >>> repeat {
      (v : arr[12] bit) <- takes 12; emit weigh(v)
    } >>> write[int32]
    """
    prog = compile_source(src)
    bits = np.array([0, 0, 0, 1, 0, 1, 1, 1, 1, 1, 0, 0], np.uint8)
    want = 8 + 32 + 64 + 128 + 256 + 512                 # = 1000
    got_i = run(prog.comp, list(bits)).out_array()
    got_j = np.asarray(run_jit(prog.comp, bits))
    assert int(np.asarray(got_i)[0]) == want
    assert int(got_j[0]) == want


def test_bit_comparison_promotes_under_jit():
    # C's usual arithmetic conversions apply to comparisons: a bit
    # compared against a negative/out-of-range value must not demote
    # the scalar to uint8 on the traced path
    from ziria_tpu.backend.execute import run_jit
    src = """
    fun probe(b: bit) : int32 {
      var r : int32 := 0;
      if b > (0 - 1) then { r := 1 };      -- always true in C
      if b == 256 then { r := r + 10 };    -- never true in C
      return r
    }
    let comp main = read[bit] >>> map probe >>> write[int32]
    """
    prog = compile_source(src)
    bits = np.array([0, 1, 1, 0], np.uint8)
    want = run(prog.comp, list(bits)).out_array()
    got = np.asarray(run_jit(prog.comp, bits))
    np.testing.assert_array_equal(got, np.asarray(want))
    np.testing.assert_array_equal(got, [1, 1, 1, 1])


def test_wifi_rx_hybrid_long_frame():
    # 1000-byte PSDU at 54 Mbps: the enlarged whole-frame buffers hold
    # a max-size decode, and the hybrid path matches the transmitted
    # bits exactly (this length exposed the uint8 promotion bug and
    # the old 8192-entry buffer cap)
    from ziria_tpu.utils.bits import bytes_to_bits
    psdu, xi = _capture(54, 1000, seed=99)
    prog = compile_file(SRC)
    out = H.run_hybrid(prog.comp, [p for p in xi]).out_array()
    np.testing.assert_array_equal(np.asarray(out, np.uint8),
                                  np.asarray(bytes_to_bits(psdu)))


def test_jitdo_writes_back_numpy():
    # refs must come back as numpy so downstream per-item interpretation
    # stays on the fast path
    src = """
    let comp main = read[int32] >>> repeat {
      x <- take;
      var acc : arr[64] int32;
      do {
        for k in [0, 64] {
          var s : int32 := 0;
          for i in [0, 32] { s := s + x * (k + i) };
          acc[k] := s
        }
      };
      emit acc[63]
    } >>> write[int32]
    """
    prog = compile_source(src)
    hyb = H.hybridize(prog.comp, min_weight=100)
    xs = np.arange(1, 5, dtype=np.int32)
    want = run(prog.comp, list(xs)).out_array()
    got = H.run_hybrid(prog.comp, list(xs), min_weight=100).out_array()
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    del hyb


def test_cli_jit_falls_back_to_hybrid(tmp_path, capsys):
    # --backend=jit on a dynamic-control program must not error: it
    # falls back to the hybrid executor with a stderr note
    from ziria_tpu.runtime.buffers import (StreamSpec, read_stream,
                                           write_stream)
    from ziria_tpu.runtime.cli import main as cli_main
    psdu, xi = _capture(6, 30, seed=13)
    inf, outf = tmp_path / "in.bin", tmp_path / "out.bin"
    write_stream(StreamSpec(ty="complex16", path=str(inf), mode="bin"), xi)
    rc = cli_main([
        f"--src={SRC}",
        "--input=file", f"--input-file-name={inf}",
        "--input-file-mode=bin",
        "--output=file", f"--output-file-name={outf}",
        "--output-file-mode=bin", "--backend=jit",
    ])
    assert rc == 0
    assert "falling back to --backend=hybrid" in capsys.readouterr().err
    got = read_stream(StreamSpec(ty="bit", path=str(outf), mode="bin"))
    from ziria_tpu.utils.bits import bytes_to_bits
    np.testing.assert_array_equal(got[: 8 * 30],
                                  np.asarray(bytes_to_bits(psdu)))


def test_cli_profile_handles_dynamic_stage(tmp_path, capsys):
    # --profile on a dynamic-control program: the dynamic stage falls
    # back to the hybrid executor inside the per-stage breakdown
    # instead of crashing with a LowerError
    from ziria_tpu.runtime.buffers import (StreamSpec, read_stream,
                                           write_stream)
    from ziria_tpu.runtime.cli import main as cli_main
    from ziria_tpu.utils.bits import bytes_to_bits
    psdu, xi = _capture(6, 30, seed=21)
    inf, outf = tmp_path / "in.bin", tmp_path / "out.bin"
    write_stream(StreamSpec(ty="complex16", path=str(inf), mode="bin"), xi)
    rc = cli_main([
        f"--src={SRC}", "--profile",
        "--input=file", f"--input-file-name={inf}",
        "--input-file-mode=bin",
        "--output=file", f"--output-file-name={outf}",
        "--output-file-mode=bin", "--backend=jit",
    ])
    assert rc == 0
    err = capsys.readouterr().err
    assert "profile:" in err and "stage" in err
    got = read_stream(StreamSpec(ty="bit", path=str(outf), mode="bin"))
    np.testing.assert_array_equal(got[: 8 * 30],
                                  np.asarray(bytes_to_bits(psdu)))


def test_env_ref_shadowing_excluded():
    from ziria_tpu.frontend.elab import _env_ref_names
    env = ir.Env()
    env.bind_ref("n", 1)
    env.bind_ref("m", 2)
    child = env.child()
    child.bind("n", 10)               # immutable bind shadows outer ref
    names = _env_ref_names(child)
    assert "m" in names and "n" not in names


def test_viterbi_soft_traced_with_static_lengths():
    from ziria_tpu.frontend.externals import EXTERNALS
    vs = EXTERNALS["viterbi_soft"]
    rng = np.random.default_rng(0)
    # encode a known 24-bit message with the 802.11 conv code via the
    # shared tx encoder bricks is overkill here: decode of random soft
    # values just needs jit path == numpy path
    llrs = rng.normal(size=128).astype(np.float32)
    want = vs(llrs, 32, 24)
    got = jax.jit(lambda x: vs(x, 32, 24))(jnp.asarray(llrs))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_weight_heuristic_pinned():
    # the wrap/no-wrap decision is a performance contract: pin the
    # weights of two canonical bodies so heuristic drift (e.g. during
    # walker refactors) is a conscious, test-visible choice
    from ziria_tpu.frontend.parser import parse_program
    loopy = parse_program("""
      fun f1(x: int32) : int32 {
        var acc : int32 := 0;
        for k in [0, 64] {
          var s : int32 := 0;
          for i in [0, 32] { s := s + x * (k + i) };
          acc := acc + s
        }
        return acc
      }
    """, "<w>").decls[0]
    flat = parse_program("""
      fun f2(x: int32) : int32 {
        var a : int32 := x + 1;
        if a > 0 then { a := a * 2 } else { a := a - 2 };
        return a
      }
    """, "<w>").decls[0]
    assert H._stmts_weight(loopy.body) == 21191   # >> MIN_JIT_WEIGHT
    assert H._stmts_weight(flat.body) == 20       # << MIN_JIT_WEIGHT
    assert H.MIN_JIT_WEIGHT == 300


def test_print_inside_called_fun_never_wrapped():
    # effects hidden behind a helper fun must also block wrapping —
    # a trace-time print would fire once instead of per firing
    src = """
    fun shout(x: int32) : int32 { println x; return x }
    let comp main = read[int32] >>> repeat {
      x <- take;
      var s : int32 := 0;
      do {
        for k in [0, 64] { for i in [0, 32] { s := s + x } };
        s := shout(s)
      };
      emit s
    } >>> write[int32]
    """
    hyb = H.hybridize(compile_source(src).comp, min_weight=100)
    found = []

    def walk(c):
        if isinstance(c, ir.Return) and isinstance(c.expr, H._JitDo):
            found.append(c)
        ir.map_children(c, lambda ch, _b: (walk(ch), ch)[1])

    walk(hyb)
    assert not found


def test_print_blocks_never_wrapped():
    src = """
    let comp main = read[int32] >>> repeat {
      x <- take;
      do {
        var s : int32 := 0;
        for k in [0, 64] { for i in [0, 32] { s := s + x } };
        println s
      };
      emit x
    } >>> write[int32]
    """
    hyb = H.hybridize(compile_source(src).comp, min_weight=100)
    found = []

    def walk(c):
        if isinstance(c, ir.Return) and isinstance(c.expr, H._JitDo):
            found.append(c)
        ir.map_children(c, lambda ch, _b: (walk(ch), ch)[1])

    walk(hyb)
    assert not found


def test_viterbi_soft_windowed_flag(monkeypatch):
    """ZIRIA_VITERBI_WINDOW routes every STAGED viterbi_soft through
    the sliding-window parallel Pallas decode — same bits on a real
    coded stream, no program change (the --viterbi-window driver
    flag's contract)."""
    import importlib.util
    import os as _os

    from ziria_tpu.frontend.externals import EXTERNALS
    vs = EXTERNALS["viterbi_soft"]
    _spec = importlib.util.spec_from_file_location(
        "windowed_ber", _os.path.join(
            _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
            "tools", "windowed_ber.py"))
    _wb = importlib.util.module_from_spec(_spec)
    _spec.loader.exec_module(_wb)
    rng = np.random.default_rng(5)
    n = 600
    msgs, frames = _wb.make_coded_frames(rng, 1, n, amp=3.0)
    bits, llrs = msgs[0], frames[0].reshape(-1)
    monkeypatch.delenv("ZIRIA_VITERBI_WINDOW", raising=False)
    exact = np.asarray(jax.jit(lambda x: vs(x, n, n))(jnp.asarray(llrs)))
    # window=256 << n: the staged call genuinely windows (3 windows)
    monkeypatch.setenv("ZIRIA_VITERBI_WINDOW", "256")
    win = np.asarray(jax.jit(lambda x: vs(x, n, n))(jnp.asarray(llrs)))
    np.testing.assert_array_equal(win, exact)
    np.testing.assert_array_equal(win[:n], bits)
