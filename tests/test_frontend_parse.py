"""Lexer/parser tests for the surface syntax (SURVEY.md §2.1 frontend)."""

import pytest

from ziria_tpu.frontend import (LexError, ParseError, parse_comp,
                                parse_expr, parse_program, tokenize)
from ziria_tpu.frontend import lexer
from ziria_tpu.frontend import ast as A


# ------------------------------------------------------------------- lexer

def test_lex_ops_longest_match():
    toks = tokenize("a |>>>| b >>> c := d <- e << f <= g")
    ops = [t.text for t in toks if t.kind == "op"]
    assert ops == ["|>>>|", ">>>", ":=", "<-", "<<", "<="]


def test_lex_bit_and_numbers():
    toks = tokenize("'0 '1 42 0x1F 3.5 2e-3 1.")
    kinds = [(t.kind, t.text) for t in toks[:-1]]
    assert ("bit", "0") in kinds and ("bit", "1") in kinds
    assert ("int", "42") in kinds and ("int", "0x1F") in kinds
    assert ("float", "3.5") in kinds and ("float", "2e-3") in kinds
    # "1." lexes as int 1 then op '.' (field access needs this)
    assert kinds[-2:] == [("int", "1"), ("op", ".")]


def test_lex_comments():
    toks = tokenize("a -- line comment\nb {- block {- nested -} -} c // x")
    ids = [t.text for t in toks if t.kind == "id"]
    assert ids == ["a", "b", "c"]


def test_lex_string_escape():
    toks = tokenize('"he\\"llo\\n"')
    assert toks[0].kind == "str" and toks[0].text == 'he"llo\n'


def test_lex_error_position():
    with pytest.raises(LexError, match="2:3"):
        tokenize("ab\nc `d")


# ------------------------------------------------------------------- exprs

def test_expr_precedence():
    e = parse_expr("1 + 2 * 3 == 7 && true")
    assert isinstance(e, A.EBin) and e.op == "&&"
    assert isinstance(e.a, A.EBin) and e.a.op == "=="
    assert isinstance(e.a.a, A.EBin) and e.a.a.op == "+"
    assert isinstance(e.a.a.b, A.EBin) and e.a.a.b.op == "*"


def test_expr_slice_index_field():
    e = parse_expr("x[3, 4]")
    assert isinstance(e, A.ESlice)
    e = parse_expr("x[i].re")
    assert isinstance(e, A.EField) and e.f == "re"
    assert isinstance(e.e, A.EIdx)


def test_expr_cast_and_arrlit():
    e = parse_expr("int16({1, 2, 3})")
    assert isinstance(e, A.ECall) and e.name == "int16"
    assert isinstance(e.args[0], A.EArrLit) and len(e.args[0].elems) == 3


def test_expr_cond():
    e = parse_expr("if a > 0 then b else c")
    assert isinstance(e, A.ECond)


# ------------------------------------------------------------------- comps

def test_comp_pipe_assoc_and_par():
    c = parse_comp("a >>> b |>>>| c")
    assert isinstance(c, A.CPipe) and c.par
    assert isinstance(c.up, A.CPipe) and not c.up.par


def test_comp_block_binds():
    c = parse_comp("{ x <- take; emit x + 1 }")
    assert isinstance(c, A.CBind) and c.var == "x"
    assert isinstance(c.first, A.CTake)
    assert isinstance(c.rest, A.CEmit)


def test_comp_typed_bind():
    c = parse_comp("{ (x: arr[64] complex16) <- takes 64; emits x }")
    assert isinstance(c, A.CBind) and c.var == "x"
    assert isinstance(c.var_ty, A.TArr)


def test_comp_repeat_var_do():
    c = parse_comp("""
      { var st : arr[7] bit := {'1,'1,'1,'1,'1,'1,'1};
        repeat {
          x <- take;
          do { st[0] := x };
          emit x
        }
      }""")
    assert isinstance(c, A.CVarDecl)
    assert isinstance(c.rest, A.CRepeat)


def test_comp_block_must_end_in_comp():
    with pytest.raises(ParseError, match="end with a computation"):
        parse_comp("{ emit 1; var x : bit := '0 }")

    with pytest.raises(ParseError, match="cannot be a bind"):
        parse_comp("{ x <- take }")


def test_comp_control():
    c = parse_comp("for i in [0, 8] { emit i }")
    assert isinstance(c, A.CFor)
    c = parse_comp("while (n > 0) { emit n }")
    assert isinstance(c, A.CWhile)
    c = parse_comp("until (done) { x <- take; emit x }")
    assert isinstance(c, A.CUntil)
    c = parse_comp("times 4 take")
    assert isinstance(c, A.CTimes)
    c = parse_comp("if r > 1 then map f else map g")
    assert isinstance(c, A.CIf)


def test_comp_read_write():
    c = parse_comp("read[complex16] >>> map f >>> write[bit]")
    assert isinstance(c.up.up, A.CRead)
    assert isinstance(c.down, A.CWrite)


# ------------------------------------------------------------------- decls

def test_program_decls():
    p = parse_program("""
      struct Hdr = { rate: int32; len: int32 }
      let n = 64
      ext fun v_fft(x: arr[64] complex16) : arr[64] complex16
      fun f(x: int16) : int16 { return x + 1 }
      fun comp pipe_a(k: int32) { repeat { x <- take; emit x + k } }
      let comp main = read[int16] >>> pipe_a(3) >>> write[int16]
    """)
    kinds = [type(d).__name__ for d in p.decls]
    assert kinds == ["DStruct", "DLet", "DExt", "DFun", "DFunComp",
                     "DLetComp"]
    fc = p.decls[4]
    assert fc.name == "pipe_a" and fc.params[0].name == "k"


def test_parse_error_position():
    with pytest.raises(ParseError, match="3:"):
        parse_program("let x = 1\nlet y = 2\nfun ( broken")


def test_stmt_forms():
    p = parse_program("""
      fun g(a: arr[4] int32) : int32 {
        var acc : int32 := 0;
        for i in [0, 4] { acc := acc + a[i] };
        while (acc > 100) { acc := acc - 100 };
        if acc > 10 then { acc := acc - 1 } else { acc := acc + 1 };
        println "acc=", acc;
        return acc
      }
    """)
    body = p.decls[0].body
    names = [type(s).__name__ for s in body]
    assert names == ["SVar", "SFor", "SWhile", "SIf", "SExpr", "SReturn"]
