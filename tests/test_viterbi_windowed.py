"""Sliding-window parallel Viterbi vs the exact full-frame decode.

The windowed variant (ops/viterbi_pallas.viterbi_decode_batch_windowed)
trades the T-step sequential dependency chain for parallel overlapping
windows — the standard truncated-Viterbi accuracy argument. These tests
pin the claim that matters: on clean and operating-SNR inputs the output
is BIT-IDENTICAL to the exact decode, across window counts, ragged
tails, batch padding, and the short-frame fallback.
"""

import numpy as np
import pytest

from ziria_tpu.ops import coding, viterbi, viterbi_pallas


def _encoded_llrs(rng, n_bits, snr=None):
    """Terminated frame -> (message bits, (T, 2) LLRs)."""
    bits = rng.integers(0, 2, n_bits).astype(np.uint8)
    bits[-coding.K + 1:] = 0                   # zero-tail termination
    coded = np.asarray(coding.np_conv_encode_ref(bits), np.float32)
    llr = 2.0 * coded - 1.0
    if snr is not None:
        llr = llr * snr + rng.normal(0, 1.0, coded.size)
    return bits, llr.astype(np.float32).reshape(-1, 2)


def test_clean_bit_identical_many_windows():
    rng = np.random.default_rng(0)
    B, n = 4, 1000                             # window=128 -> 8 windows
    msgs, llrs = zip(*[_encoded_llrs(rng, n) for _ in range(B)])
    llrs = np.stack(llrs)
    got = np.asarray(viterbi_pallas.viterbi_decode_batch_windowed(
        llrs, window=128, overlap=32))
    full = np.asarray(viterbi_pallas.viterbi_decode_batch(llrs))
    np.testing.assert_array_equal(got, full)
    for k in range(B):
        np.testing.assert_array_equal(got[k], msgs[k])


def test_noisy_bit_identical_to_full_decode():
    # operating SNR: the exact decode recovers the message; windowed
    # must agree with the exact decode bit-for-bit (not just payload)
    rng = np.random.default_rng(1)
    B, n = 3, 900
    llrs = np.stack([_encoded_llrs(rng, n, snr=3.0)[1]
                     for _ in range(B)])
    got = np.asarray(viterbi_pallas.viterbi_decode_batch_windowed(
        llrs, window=256, overlap=64))
    full = np.asarray(viterbi_pallas.viterbi_decode_batch(llrs))
    np.testing.assert_array_equal(got, full)
    # and the exact decode equals the lax.scan oracle on these inputs
    for k in range(B):
        np.testing.assert_array_equal(
            full[k], np.asarray(viterbi.viterbi_decode(llrs[k])))


def test_ragged_tail_and_nbits():
    # T not a multiple of window; n_bits slicing
    rng = np.random.default_rng(2)
    msg, llr = _encoded_llrs(rng, 700)
    got = np.asarray(viterbi_pallas.viterbi_decode_batch_windowed(
        llr[None], window=256, overlap=48, n_bits=690))
    assert got.shape == (1, 690)
    full = np.asarray(viterbi_pallas.viterbi_decode_batch(
        llr[None], n_bits=690))
    np.testing.assert_array_equal(got, full)


def test_short_frame_falls_back_to_exact():
    rng = np.random.default_rng(3)
    _, llr = _encoded_llrs(rng, 200, snr=2.0)
    got = np.asarray(viterbi_pallas.viterbi_decode_batch_windowed(
        llr[None], window=512, overlap=96))
    full = np.asarray(viterbi_pallas.viterbi_decode_batch(llr[None]))
    np.testing.assert_array_equal(got, full)


def test_flat_llr_layout():
    rng = np.random.default_rng(4)
    _, llr = _encoded_llrs(rng, 600)
    flat = llr.reshape(1, -1)
    got = np.asarray(viterbi_pallas.viterbi_decode_batch_windowed(
        flat, window=200, overlap=40))
    full = np.asarray(viterbi_pallas.viterbi_decode_batch(flat))
    np.testing.assert_array_equal(got, full)


@pytest.mark.parametrize("n", [1024, 1025, 1151])
def test_window_boundary_alignment(n):
    # boundaries landing on/off UNROLL and window multiples
    rng = np.random.default_rng(5)
    msg, llr = _encoded_llrs(rng, n)
    got = np.asarray(viterbi_pallas.viterbi_decode_batch_windowed(
        llr[None], window=256, overlap=64))
    np.testing.assert_array_equal(got[0], msg)


def test_fuzz_random_geometry_matches_exact():
    """Property check across random (T, window, overlap) geometries —
    boundary/stitch errors tend to hide at odd alignments. Uses the
    lax.scan engine through the production windowing math (_decode
    hook) so 12 configurations stay fast; Pallas==scan is pinned by
    the other tests in this file."""
    import jax

    def eng(x):
        return jax.vmap(viterbi.viterbi_decode)(x)

    rng = np.random.default_rng(77)
    for _ in range(12):
        n = int(rng.integers(300, 2600))
        window = int(rng.integers(48, 700))
        overlap = int(rng.integers(16, 160))
        msg, llr = _encoded_llrs(rng, n, snr=2.5)
        got = np.asarray(viterbi_pallas.viterbi_decode_batch_windowed(
            llr[None], window=window, overlap=overlap, _decode=eng))
        want = np.asarray(eng(llr[None]))
        np.testing.assert_array_equal(
            got, want, err_msg=f"n={n} window={window} overlap={overlap}")
