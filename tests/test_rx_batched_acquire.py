"""One-dispatch batched acquisition (phy/wifi/rx.acquire_many +
gather_segments_many + backend/framebatch.receive_many): the whole
receive of an N-capture mixed-rate batch in O(1) device dispatches —
acquire -> gather -> mixed decode — with every RxResult bit-identical
lane-for-lane to per-capture `rx.receive`, including the failure
classes (no detect, bad parity, capture shorter than the parsed
length).

Budget discipline (the tier-1 870 s cutoff is real): ONE module
fixture pays all the expensive geometry compiles — 8 lanes, 1024-
sample capture bucket, 8-symbol decode bucket, the same geometry
tests/test_rx_mixed_dispatch.py uses so the two files share compiled
dispatches through the process-wide jit caches — and every test is a
cheap re-dispatch of those compiled graphs. Dispatch counts come from
utils/dispatch.count_dispatches (the instrumented-call-site counter),
compile counts from utils/dispatch.cache_growth (lru deltas, never
cache_clear).
"""

import threading

import numpy as np
import jax.numpy as jnp
import pytest

from ziria_tpu.backend import framebatch
from ziria_tpu.ops import coding, interleave, modulate, ofdm, sync
from ziria_tpu.phy.wifi import rx, tx
from ziria_tpu.phy.wifi.params import RATES
from ziria_tpu.utils import dispatch
from ziria_tpu.utils.bits import bytes_to_bits

N_BYTES = 16    # the mixed-dispatch corpus size: 8-symbol common
                # bucket, 1024-sample capture bucket at every rate


def _capture(rng, mbps, n_bytes, offset, eps0=0.0):
    """A frame at `mbps` behind `offset` silent samples, optionally
    rotated by a synthetic CFO of `eps0` rad/sample."""
    psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
    s = np.asarray(tx.encode_frame(psdu, mbps))
    cap = np.concatenate([np.zeros((offset, 2), np.float32), s], axis=0)
    if eps0:
        # receiver derotates by its eps estimate; impose the offset
        # with the opposite sign through the same rotation op
        cap = np.asarray(sync.correct_cfo(cap, -eps0))
    return cap, np.asarray(bytes_to_bits(psdu))


def _same_result(a, b) -> bool:
    return (a.ok == b.ok and a.rate_mbps == b.rate_mbps
            and a.length_bytes == b.length_bytes
            and np.array_equal(a.psdu_bits, b.psdu_bits)
            and a.crc_ok == b.crc_ok)


@pytest.fixture(scope="module")
def corpus():
    """All 8 rates, each with its own start offset and CFO; reference
    results from per-capture `receive` (the oracle), plus one batched
    and one host-acquire `receive_many` pass."""
    rng = np.random.default_rng(20260803)
    caps, wants = [], []
    for k, m in enumerate(sorted(RATES)):
        off = int(rng.integers(5, 60))
        eps0 = float((-1) ** k * 1e-4 * (k + 1))
        c, w = _capture(rng, m, N_BYTES, off, eps0)
        caps.append(c)
        wants.append(w)
    ref = [rx.receive(c) for c in caps]
    with dispatch.count_dispatches() as d_bat:
        batched = framebatch.receive_many(caps, batched_acquire=True)
    with dispatch.count_dispatches() as d_host:
        host = framebatch.receive_many(caps, batched_acquire=False)
    return caps, wants, ref, batched, host, d_bat, d_host


def test_all_8_rates_bit_identical_to_receive(corpus):
    _caps, wants, ref, batched, _host, _db, _dh = corpus
    assert [r.rate_mbps for r in batched] == sorted(RATES)
    for r, g, w in zip(ref, batched, wants):
        assert r.ok and g.ok
        np.testing.assert_array_equal(g.psdu_bits, w)
        assert _same_result(r, g)


def test_host_acquire_path_is_the_same_oracle(corpus):
    # the opt-out path (--no-batched-acquire) stays available and
    # stays exact: it is the oracle the batched path is judged against
    _caps, _wants, ref, _batched, host, _db, _dh = corpus
    for r, g in zip(ref, host):
        assert _same_result(r, g)


def test_o1_dispatches_vs_o_n(corpus):
    # the tentpole number: acquire + gather + mixed decode = 3
    # dispatches for the whole batch, vs >= 3N+1 for the host loop
    # (sync, head CFO, SIGNAL per capture, a per-lane segment CFO,
    # one mixed decode)
    _caps, _wants, _ref, _batched, _host, d_bat, d_host = corpus
    n = len(_caps)
    assert d_bat.total <= 3, dict(d_bat.counts)
    assert d_bat.counts["rx.acquire_many"] == 1
    assert d_bat.counts["rx.gather"] == 1
    assert d_bat.counts["rx.decode_mixed"] == 1
    assert d_host.total >= 3 * n + 1, dict(d_host.counts)


def test_dispatch_count_constant_in_batch_size(corpus):
    # O(1) means O(1): fewer lanes, same three dispatches, results
    # still exact. 7 captures pad back to the fixture's 8-lane
    # power-of-two geometry (and keep the 6 Mbps lane, so the decode
    # bucket stays 8): every graph is a compiled-cache hit.
    caps, wants, ref, _b, _h, _db, _dh = corpus
    with dispatch.count_dispatches() as d:
        got = framebatch.receive_many(caps[:7], batched_acquire=True)
    assert d.total <= 3
    for r, g in zip(ref[:7], got):
        assert _same_result(r, g)


def test_degenerate_lanes_bit_identical(corpus):
    """No-detect, bad-parity, and truncated lanes classify and report
    exactly as per-capture receive — at the fixture's compiled
    geometry (a 6 Mbps lane keeps the 8-symbol decode bucket; every
    capture stays inside the 1024-sample bucket)."""
    caps, _wants, _ref, _b, _h, _db, _dh = corpus
    rng = np.random.default_rng(11)
    good24, _ = _capture(rng, 24, N_BYTES, 50)

    # bad parity, deterministically: the SIGNAL symbol re-encoded from
    # the 24-bit field with its even-parity bit flipped
    sig_bits = np.array(tx.signal_field_bits(RATES[24], N_BYTES))
    sig_bits[17] ^= 1
    coded = coding.conv_encode(jnp.asarray(sig_bits))
    syms = modulate.modulate(interleave.interleave(coded, 48, 1), 1)
    bins = ofdm.map_subcarriers(syms[None, :, :], symbol_index0=0)
    parity_cap = good24.copy()
    parity_cap[50 + 320: 50 + 400] = np.asarray(
        ofdm.ofdm_modulate(bins)[0])

    silent = np.zeros((600, 2), np.float32)      # never detects
    trunc = good24[:50 + 400 + 80]               # 1 of 2 DATA symbols

    lanes = [caps[0], silent, parity_cap, trunc,
             good24, caps[7], caps[0], good24]
    ref = [rx.receive(c) for c in lanes]
    got = framebatch.receive_many(lanes, batched_acquire=True)
    for r, g in zip(ref, got):
        assert _same_result(r, g)
    # and the classes really were exercised:
    assert not ref[1].ok and ref[1].rate_mbps == 0          # no detect
    assert not ref[2].ok and ref[2].rate_mbps == 0          # parity
    assert not ref[3].ok and ref[3].rate_mbps == 24 \
        and ref[3].length_bytes == N_BYTES                  # truncated
    assert ref[0].ok and ref[4].ok


def test_mixed_capture_buckets_stay_bit_identical(corpus):
    """Lanes whose OWN power-of-two capture buckets differ share one
    batch: the common bucket is LONGER than some lanes' own bucket,
    and the detection metric / LTS peak-pick arrays gain positions
    whose windows overlap those lanes' real tail samples — positions
    the per-capture path never evaluates. sync.locate_frame's `limit`
    caps each lane at its own bucket; this pins the contract with
    real content at the capture tails (a frame ending right before
    the bucket edge, and a tail-truncated frame)."""
    caps, _wants, ref0, _b, _h, _db, _dh = corpus
    rng = np.random.default_rng(5)
    # long lane: own bucket 2048, drags the common bucket past the
    # other lanes' 1024
    long_cap, _ = _capture(rng, 6, N_BYTES, 400)
    # tail-heavy lane: frame plus junk filling right up to its own
    # 1024 bucket edge — the masked region's windows see real samples
    tail_cap, _ = _capture(rng, 54, N_BYTES, 30)
    tail_cap = np.concatenate(
        [tail_cap, rng.normal(scale=0.3, size=(
            1020 - tail_cap.shape[0], 2)).astype(np.float32)])
    # truncated frame ending at the very tail of its own bucket
    trunc_cap = _capture(rng, 6, N_BYTES, 60)[0][:1000]
    lanes = [caps[0], long_cap, tail_cap, trunc_cap,
             caps[3], caps[4], caps[5], caps[7]]
    ref = [rx.receive(c) for c in lanes]
    got = framebatch.receive_many(lanes, batched_acquire=True)
    for r, g in zip(ref, got):
        assert _same_result(r, g)
    assert ref[1].ok and ref[2].ok          # the odd buckets decode


def test_acquire_many_fields_match_single_lane_oracle(corpus):
    # the per-lane acquisition fields themselves (not just the end
    # result): start/eps/rate/length/n_sym from the ONE-dispatch path
    # vs _acquire_frame, lane for lane
    caps, _wants, _ref, _b, _h, _db, _dh = corpus
    results, _x_dev, lanes = rx.acquire_many(caps)
    assert all(r is None for r in results)       # every lane decodable
    assert len(lanes) == len(caps)
    for (i, la), cap in zip(lanes, caps):
        res, acq = rx._acquire_frame(cap)
        assert res is None
        assert la.row == i
        assert la.avail == acq.avail
        assert la.eps == acq.eps                 # bit-equal f32
        assert la.rate_mbps == acq.rate_mbps
        assert la.length_bytes == acq.length_bytes
        assert la.n_sym == acq.n_sym


def test_jit_init_is_thread_safe():
    # the old lazy `_jit_sync = None` global pair raced under
    # framebatch.run_many's worker threads; the lru_cache getters
    # guarantee every concurrent first call gets a VALID callable (a
    # racing duplicate build is allowed — one value wins the cache),
    # and all subsequent calls converge on the one cached object
    errs = []

    def grab():
        try:
            assert rx._jit_sync_fn() is not None
            assert rx._jit_signal_fn() is not None
        except BaseException as e:   # pragma: no cover - fail the test
            errs.append(e)

    threads = [threading.Thread(target=grab) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert rx._jit_sync_fn() is rx._jit_sync_fn()
    assert rx._jit_signal_fn() is rx._jit_signal_fn()
