"""Checkpoint/resume of stream state (runtime/state.py + run_jit_carry).

The invariant: feeding a stream in pieces with the carry threaded
through — optionally through an on-disk checkpoint — produces exactly
the one-shot output."""

import os

import numpy as np
import pytest

import ziria_tpu as z
from ziria_tpu.backend.execute import lower, run_jit, run_jit_carry
from ziria_tpu.frontend import compile_source
from ziria_tpu.runtime.state import load_state, save_state


def _stateful_prog():
    """Scrambler-shaped stateful pipeline from surface syntax."""
    return compile_source("""
      let comp main = read[bit] >>> {
        var st : arr[7] bit := {'1,'0,'1,'1,'1,'0,'1};
        repeat {
          x <- take;
          var fb : bit := '0;
          do { fb := st[3] ^ st[0];
               st[0, 6] := st[1, 6];
               st[6] := fb };
          emit x ^ fb
        }
      } >>> write[bit]
    """).comp


def test_split_stream_equals_one_shot():
    prog = _stateful_prog()
    rng = np.random.default_rng(0)
    xs = rng.integers(0, 2, 1024).astype(np.uint8)
    want = run_jit(prog, xs)

    ys1, carry = run_jit_carry(prog, xs[:300])
    ys2, carry = run_jit_carry(prog, xs[300:700], carry=carry)
    ys3, _ = run_jit_carry(prog, xs[700:], carry=carry)
    got = np.concatenate([ys1, ys2, ys3])
    np.testing.assert_array_equal(got, want)


def test_checkpoint_through_disk(tmp_path):
    prog = _stateful_prog()
    rng = np.random.default_rng(1)
    xs = rng.integers(0, 2, 512).astype(np.uint8)
    want = run_jit(prog, xs)

    ys1, carry = run_jit_carry(prog, xs[:256])
    ck = str(tmp_path / "ck.npz")
    save_state(ck, carry)

    carry2 = load_state(ck, like=lower(prog).init_carry)
    ys2, _ = run_jit_carry(prog, xs[256:], carry=carry2)
    np.testing.assert_array_equal(np.concatenate([ys1, ys2]), want)


def test_checkpoint_wrong_program_rejected(tmp_path):
    prog = _stateful_prog()
    _, carry = run_jit_carry(prog, np.zeros(64, np.uint8))
    ck = str(tmp_path / "ck.npz")
    save_state(ck, carry)

    other = z.map_accum(lambda s, x: (s + x, s + x),
                        np.zeros((3,), np.float32), name="acc3")
    with pytest.raises(ValueError, match="wrong program|shape"):
        load_state(ck, like=lower(other).init_carry)


def test_cli_state_roundtrip(tmp_path):
    """--state-out then --state-in through the CLI equals one shot."""
    from ziria_tpu.runtime.buffers import StreamSpec, read_stream, \
        write_stream
    from ziria_tpu.runtime.cli import main as cli_main

    src = os.path.join(os.path.dirname(__file__), "..", "examples",
                       "scrambler.zir")
    rng = np.random.default_rng(2)
    xs = rng.integers(0, 2, 512).astype(np.uint8)

    def run_cli(in_arr, tag, extra):
        inf, outf = tmp_path / f"i{tag}.dbg", tmp_path / f"o{tag}.dbg"
        write_stream(StreamSpec(ty="bit", path=str(inf)), in_arr)
        rc = cli_main([f"--src={src}", "--input=file",
                       f"--input-file-name={inf}", "--output=file",
                       f"--output-file-name={outf}", *extra])
        assert rc == 0
        return read_stream(StreamSpec(ty="bit", path=str(outf)))

    want = run_cli(xs, "all", [])
    ck = str(tmp_path / "cli_ck.npz")
    y1 = run_cli(xs[:256], "a", [f"--state-out={ck}"])
    y2 = run_cli(xs[256:], "b", [f"--state-in={ck}"])
    np.testing.assert_array_equal(np.concatenate([y1, y2]), want)


def test_stats_and_ddump_vect_flags(tmp_path, capsys):
    from ziria_tpu.runtime.buffers import StreamSpec, write_stream
    from ziria_tpu.runtime.cli import main as cli_main

    src = os.path.join(os.path.dirname(__file__), "..", "examples",
                       "fir.zir")
    inf, outf = tmp_path / "i.dbg", tmp_path / "o.dbg"
    write_stream(StreamSpec(ty="int32", path=str(inf)),
                 np.arange(64, dtype=np.int32))
    rc = cli_main([f"--src={src}", "--input=file",
                   f"--input-file-name={inf}", "--output=file",
                   f"--output-file-name={outf}", "--stats",
                   "--ddump-vect"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "plan: width=" in err and "firings/iter" in err
    assert "segment 0" in err and "utility" in err


def test_split_not_multiple_of_take_carries_leftover():
    """Chunk boundaries inside a steady-state iteration must not lose
    items: the sub-iteration remainder rides in carry['leftover']."""
    prog = compile_source("""
      ext fun v_fft(x: arr[64] complex16) : arr[64] complex16
      let comp main = read[complex16] >>>
        repeat { (s: arr[64] complex16) <- takes 64; emits v_fft(s) }
        >>> write[complex16]
    """).comp
    rng = np.random.default_rng(3)
    xs = rng.integers(-500, 500, (256, 2)).astype(np.int16)
    want = run_jit(prog, xs)

    ys1, carry = run_jit_carry(prog, xs[:100])    # 100 = 1 iter + 36 left
    assert ys1.shape[0] == 64
    assert carry["leftover"].shape[0] == 36
    ys2, carry = run_jit_carry(prog, xs[100:129], carry=carry)  # 65 avail
    ys3, carry = run_jit_carry(prog, xs[129:], carry=carry)
    got = np.concatenate([ys1, ys2, ys3])
    np.testing.assert_allclose(got.astype(np.float64),
                               want.astype(np.float64), atol=1.0)


def test_checkpoint_dtype_mismatch_rejected(tmp_path):
    prog = _stateful_prog()
    _, carry = run_jit_carry(prog, np.zeros(64, np.uint8))
    ck = str(tmp_path / "ck.npz")
    save_state(ck, carry)

    # same leaf count/shapes as the scrambler state but float dtype
    import jax
    shapes = [np.asarray(v).shape
              for v in jax.tree.leaves(carry["stages"])]
    other = z.map_accum(lambda s, x: (s, x),
                        tuple(np.zeros(s, np.float32) for s in shapes),
                        name="floaty")
    with pytest.raises(ValueError, match="dtype"):
        load_state(ck, like=lower(other).init_carry)


def test_resume_with_empty_and_list_chunks():
    """Zero-length / plain-list chunks must not crash or corrupt the
    leftover's dtype."""
    prog = compile_source("""
      ext fun v_fft(x: arr[64] complex16) : arr[64] complex16
      let comp main = read[complex16] >>>
        repeat { (s: arr[64] complex16) <- takes 64; emits v_fft(s) }
        >>> write[complex16]
    """).comp
    xs = np.random.default_rng(5).integers(
        -500, 500, (128, 2)).astype(np.int16)
    want = run_jit(prog, xs)
    ys1, carry = run_jit_carry(prog, xs[:100])
    ys_mid, carry = run_jit_carry(prog, [], carry=carry)   # empty list
    assert ys_mid.shape[0] == 0
    assert carry["leftover"].dtype == np.int16             # unchanged
    ys2, _ = run_jit_carry(prog, xs[100:], carry=carry)
    np.testing.assert_allclose(
        np.concatenate([ys1, ys2]).astype(np.float64),
        want.astype(np.float64), atol=1.0)


def test_malformed_carry_dict_rejected():
    prog = _stateful_prog()
    with pytest.raises(ValueError, match="stages"):
        run_jit_carry(prog, np.zeros(8, np.uint8),
                      carry={"stage": None, "leftover": np.empty(0)})


def test_stats_counts_resumed_leftover(tmp_path, capsys):
    """--stats on a resumed run counts the checkpoint's leftover items
    toward the iteration total (uses the pre-run carry, not post-run)."""
    from ziria_tpu.runtime.buffers import StreamSpec, write_stream
    from ziria_tpu.runtime.cli import main as cli_main

    src = os.path.join(os.path.dirname(__file__), "..", "examples",
                       "fft64.zir")
    rng = np.random.default_rng(8)
    xs = rng.integers(-500, 500, (256, 2)).astype(np.int16)
    ck = str(tmp_path / "ck.npz")

    def run_cli(arr, tag, extra):
        inf = tmp_path / f"i{tag}.dbg"
        outf = tmp_path / f"o{tag}.dbg"
        write_stream(StreamSpec(ty="complex16", path=str(inf)), arr)
        rc = cli_main([f"--src={src}", "--input=file",
                       f"--input-file-name={inf}", "--output=file",
                       f"--output-file-name={outf}", "--stats", *extra])
        assert rc == 0
        return capsys.readouterr().err

    run_cli(xs[:100], "a", [f"--state-out={ck}"])   # 1 iter + 36 left
    err = run_cli(xs[100:], "b", [f"--state-in={ck}"])
    # 36 + 156 = 192 items = 3 full iterations
    assert "remainder_iters=3" in err, err.splitlines()[0]


def test_resume_lossy_dtype_rejected_and_none_leftover_ok():
    prog = compile_source("""
      ext fun v_fft(x: arr[64] complex16) : arr[64] complex16
      let comp main = read[complex16] >>>
        repeat { (s: arr[64] complex16) <- takes 64; emits v_fft(s) }
        >>> write[complex16]
    """).comp
    xs = np.random.default_rng(6).integers(
        -500, 500, (128, 2)).astype(np.int16)
    _, carry = run_jit_carry(prog, xs[:100])
    # float chunk into an int16 stream: lossy kind change -> rejected
    with pytest.raises(ValueError, match="dtype"):
        run_jit_carry(prog, xs[100:].astype(np.float64) + 0.9,
                      carry=carry)
    # explicit leftover=None is treated as absent, not a 0-d array
    ys, _ = run_jit_carry(prog, xs[:64],
                          carry={"stages": carry["stages"],
                                 "leftover": None})
    assert ys.shape[0] == 64


def test_resume_narrowing_within_kind_rejected():
    """int32 chunk into an int16 stream: lossy narrowing is refused."""
    prog = compile_source("""
      ext fun v_fft(x: arr[64] complex16) : arr[64] complex16
      let comp main = read[complex16] >>>
        repeat { (s: arr[64] complex16) <- takes 64; emits v_fft(s) }
        >>> write[complex16]
    """).comp
    xs = np.random.default_rng(7).integers(
        -500, 500, (128, 2)).astype(np.int16)
    _, carry = run_jit_carry(prog, xs[:100])
    with pytest.raises(ValueError, match="losslessly"):
        run_jit_carry(prog, xs[100:].astype(np.int32), carry=carry)


def test_fingerprint_mismatch_rejected(tmp_path):
    """Same state layout, different program: the fingerprint must catch
    it (ADVICE r1 — layout checks alone are not identity checks)."""
    from ziria_tpu.runtime.state import program_fingerprint
    import ziria_tpu as z

    p1 = z.pipe(z.zmap(np.negative), z.zmap(np.abs))
    p2 = z.pipe(z.zmap(np.negative), z.zmap(np.exp))
    f1, f2 = program_fingerprint(p1), program_fingerprint(p2)
    assert isinstance(f1, str) and len(f1) == 16
    assert f1 != f2, "structurally different programs must differ"
    assert f1 == program_fingerprint(
        z.pipe(z.zmap(np.negative), z.zmap(np.abs)))
    # lambdas differing only in body must fingerprint differently
    # (review r2: __name__ alone collapses every lambda to '<lambda>')
    l1 = z.pipe(z.zmap(lambda x: x + 1))
    l2 = z.pipe(z.zmap(lambda x: x * 2))
    assert program_fingerprint(l1) != program_fingerprint(l2)

    ck = tmp_path / "s.npz"
    save_state(str(ck), {"stages": [], "leftover": np.empty(0)},
               fingerprint="aaaabbbbccccdddd")
    with pytest.raises(ValueError, match="different program"):
        load_state(str(ck), [], fingerprint="0000111122223333")
    # matching fingerprint (or none provided) loads fine
    load_state(str(ck), [], fingerprint="aaaabbbbccccdddd")
    load_state(str(ck), [])
