"""jaxlint engine + rules (ziria_tpu/analysis): per-rule fixture
snippets — one true positive and one near-miss negative each — plus
pragma suppression, the JSON schema, CLI exit codes, and the
acceptance demo: R1 re-flags a deliberately dropped cache-key
parameter in a MUTATED copy of a real rx.py jit factory.

All pure-AST and CPU-only: nothing here imports jax (pinned by
test_lint_no_jax_import in a fresh interpreter), so the whole module
is tier-1 cheap.
"""

import ast
import json
import os
import subprocess
import sys

import pytest

from ziria_tpu.analysis import lint_paths, lint_source
from ziria_tpu.analysis.__main__ import main as lint_main
from ziria_tpu.analysis.rules import RULES_BY_ID

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RX_PY = os.path.join(REPO, "ziria_tpu", "phy", "wifi", "rx.py")


def _findings(src, rules=None, path="fixture.py"):
    rule_objs = [RULES_BY_ID[r] for r in rules] if rules else None
    return lint_source(src, path, rules=rule_objs).findings


def _rules_of(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------------ R1

R1_TP_ENV = '''
import os
import jax
from functools import lru_cache

@lru_cache(maxsize=None)
def _jit_decode(n_sym):
    win = int(os.environ.get("ZIRIA_VITERBI_WINDOW", "0"))
    def f(x):
        return x[:win]
    return jax.jit(f)
'''

R1_TP_RESOLVER = '''
import jax
from functools import lru_cache

def fused_demap_enabled(v):
    return bool(v)

@lru_cache(maxsize=None)
def _jit_decode(n_sym):
    fused = fused_demap_enabled(None)     # mode never reaches the key
    def f(x):
        return x if fused else -x
    return jax.jit(f)
'''

R1_TP_KNOB = '''
import os
import jax
from functools import lru_cache

_WINDOW = os.environ.get("ZIRIA_WINDOW")   # module-level knob

@lru_cache(maxsize=None)
def _jit_decode(n_sym):
    def f(x):
        return x[: int(_WINDOW or 0)]
    return jax.jit(f)
'''

R1_NEGATIVE = '''
import os
import jax
from functools import lru_cache

def window_of():                 # env read OUTSIDE any factory: not R1
    return int(os.environ.get("ZIRIA_VITERBI_WINDOW", "0"))

@lru_cache(maxsize=None)
def _jit_decode(n_sym, window):  # every knob rides the cache key
    def f(x):
        return x[:window][:n_sym]
    return jax.jit(f)

def caller(x):
    return _jit_decode(4, window_of())(x)
'''


def test_r1_env_read_in_factory_flagged():
    f = _findings(R1_TP_ENV, rules=["R1"])
    assert _rules_of(f) == ["R1"] and "_jit_decode" in f[0].message


def test_r1_mode_resolver_in_factory_flagged():
    f = _findings(R1_TP_RESOLVER, rules=["R1"])
    assert _rules_of(f) == ["R1"]
    assert "fused_demap_enabled" in f[0].message


def test_r1_module_knob_in_factory_flagged():
    f = _findings(R1_TP_KNOB, rules=["R1"])
    assert _rules_of(f) == ["R1"] and "_WINDOW" in f[0].message


def test_r1_near_miss_clean():
    # the same reads OUTSIDE the factory, and a factory whose every
    # knob is a parameter, are exactly the sanctioned pattern
    assert _findings(R1_NEGATIVE, rules=["R1"]) == []


def test_r1_reflags_dropped_cache_key_param_in_real_rx_factory():
    """THE acceptance demo: take the real rx.py, drop `fused_demap`
    from `_jit_decode_data_bucketed`'s signature (= its lru_cache
    key) and resolve it inside the body instead — the exact regression
    PR 1/PR 6 closed by hand. R1 must re-flag the mutated factory,
    and the unmutated file must be clean."""
    with open(RX_PY, encoding="utf-8") as fh:
        src = fh.read()
    assert _findings(src, rules=["R1"], path=RX_PY) == []

    tree = ast.parse(src)

    class DropKeyParam(ast.NodeTransformer):
        mutated = False

        def visit_FunctionDef(self, node):
            self.generic_visit(node)
            if node.name != "_jit_decode_data_bucketed":
                return node
            assert node.args.args[-1].arg == "fused_demap"
            node.args.args = node.args.args[:-1]
            node.args.defaults = node.args.defaults[:-1]

            class Resolve(ast.NodeTransformer):
                def visit_Name(self, n):
                    if n.id == "fused_demap" and isinstance(
                            n.ctx, ast.Load):
                        return ast.copy_location(ast.Call(
                            func=ast.Name("fused_demap_enabled",
                                          ast.Load()),
                            args=[ast.Constant(None)], keywords=[]), n)
                    return n

            Resolve().visit(node)
            DropKeyParam.mutated = True
            return node

    mutated = ast.unparse(ast.fix_missing_locations(
        DropKeyParam().visit(tree)))
    assert DropKeyParam.mutated
    f = _findings(mutated, rules=["R1"], path="rx_mutated.py")
    assert f, "R1 must re-flag the dropped cache-key parameter"
    assert any("_jit_decode_data_bucketed" in x.message
               and "fused_demap_enabled" in x.message for x in f)


@pytest.mark.parametrize("factory", ["_jit_decode_data_mixed",
                                     "_jit_stream_decode",
                                     "_jit_stream_decode_multi"])
def test_r1_guards_fused_demap_key_in_mixed_decode_factories(factory):
    """ISSUE 20 satellite: every MIXED-decode jit factory now carries
    `fused_demap` as its LAST cache-key parameter (the rate-switched
    fused front). Same demo as the bucketed factory above: AST-drop
    the parameter by position and resolve it in the body — R1 must
    re-flag each mutated factory, and the real file stays clean (the
    clean check rides the bucketed test; one parse per mutation
    here)."""
    with open(RX_PY, encoding="utf-8") as fh:
        src = fh.read()
    tree = ast.parse(src)

    class DropKeyParam(ast.NodeTransformer):
        mutated = False

        def visit_FunctionDef(self, node):
            self.generic_visit(node)
            if node.name != factory:
                return node
            assert node.args.args[-1].arg == "fused_demap"
            node.args.args = node.args.args[:-1]
            node.args.defaults = node.args.defaults[:-1]

            class Resolve(ast.NodeTransformer):
                def visit_Name(self, n):
                    if n.id == "fused_demap" and isinstance(
                            n.ctx, ast.Load):
                        return ast.copy_location(ast.Call(
                            func=ast.Name("fused_demap_enabled",
                                          ast.Load()),
                            args=[ast.Constant(None)], keywords=[]), n)
                    return n

            Resolve().visit(node)
            DropKeyParam.mutated = True
            return node

    mutated = ast.unparse(ast.fix_missing_locations(
        DropKeyParam().visit(tree)))
    assert DropKeyParam.mutated, f"{factory} not found in rx.py"
    f = _findings(mutated, rules=["R1"], path="rx_mutated.py")
    assert any(factory in x.message
               and "fused_demap_enabled" in x.message for x in f), \
        f"R1 must re-flag {factory}'s dropped fused_demap key"


# ------------------------------------------------------------------ R2

R2_TP = '''
import numpy as np
from ziria_tpu.utils import dispatch

def receive(x):
    dec = _jit_decode(4)
    with dispatch.timed("rx.decode"):
        out = np.asarray(dec(x))     # device wait billed as dispatch
    return out
'''

R2_NEGATIVE = '''
import numpy as np
from ziria_tpu.utils import dispatch

def receive(x):
    meta = np.asarray(x)             # host value: not a sync
    dec = _jit_decode(4)
    with dispatch.timed("rx.decode"):
        out = dec(meta)              # dispatch only inside the block
    return np.asarray(out)           # sync OUTSIDE the timed region
'''


def test_r2_host_sync_inside_timed_flagged():
    f = _findings(R2_TP, rules=["R2"])
    assert _rules_of(f) == ["R2"] and "np.asarray" in f[0].message


def test_r2_near_miss_clean():
    assert _findings(R2_NEGATIVE, rules=["R2"]) == []


def test_r2_builtin_sync_on_jit_result_flagged():
    src = R2_TP.replace("np.asarray(dec(x))", "float(dec(x))")
    f = _findings(src, rules=["R2"])
    assert _rules_of(f) == ["R2"] and "float" in f[0].message


# ------------------------------------------------------------------ R3

R3_TP = '''
def receive(x):
    return _jit_decode(4)(x)         # fired blind: no span, no count
'''

R3_NEGATIVE = '''
from ziria_tpu.utils import dispatch

def receive(x):
    dec = _jit_decode(4)             # building the callable is free
    with dispatch.timed("rx.decode"):
        return dec(x)
'''


def test_r3_untimed_dispatch_flagged():
    f = _findings(R3_TP, rules=["R3"])
    assert _rules_of(f) == ["R3"] and "_jit_decode" in f[0].message


def test_r3_near_miss_clean():
    assert _findings(R3_NEGATIVE, rules=["R3"]) == []


def test_r3_self_attr_dispatch_tracked():
    src = '''
from ziria_tpu.utils import dispatch

class Rx:
    def __init__(self):
        self._jit1 = _jit_chunk(8)
    def scan(self, x):
        return self._jit1(x)
'''
    f = _findings(src, rules=["R3"])
    assert _rules_of(f) == ["R3"] and "self._jit1" in f[0].message


# ------------------------------------------------------------------ R4

R4_TP_IMPORT_TIME = '''
import os
DEBUG = os.environ.get("ZIRIA_DEBUG")
'''

R4_TP_SCATTERED = '''
import os

def receive(x):
    if os.environ.get("ZIRIA_STREAMING_RX") == "0":
        return None
    return x
'''

R4_TP_WRITE = '''
import os

def set_flag():
    os.environ["ZIRIA_STREAMING_RX"] = "0"
'''

R4_NEGATIVE = '''
import os

def streaming_rx_enabled(v=None):     # THE designated single reader
    if v is not None:
        return v
    return os.environ.get("ZIRIA_STREAMING_RX", "1") != "0"

def env_trace_path():
    return os.environ.get("ZIRIA_TRACE") or None
'''


def test_r4_import_time_read_flagged():
    f = _findings(R4_TP_IMPORT_TIME, rules=["R4"])
    assert _rules_of(f) == ["R4"] and "import time" in f[0].message


def test_r4_scattered_read_flagged():
    f = _findings(R4_TP_SCATTERED, rules=["R4"])
    assert _rules_of(f) == ["R4"] and "single-reader" in f[0].message


def test_r4_env_write_flagged():
    f = _findings(R4_TP_WRITE, rules=["R4"])
    assert _rules_of(f) == ["R4"] and "write" in f[0].message


def test_r4_designated_readers_clean():
    assert _findings(R4_NEGATIVE, rules=["R4"]) == []


# ------------------------------------------------------------------ R5

R5_TP_ANNOTATION = '''
import numpy as np
from functools import lru_cache

@lru_cache(maxsize=None)
def _table(x: np.ndarray):
    return x.sum()
'''

R5_TP_NESTED = '''
from functools import lru_cache

def build(arr):
    @lru_cache(maxsize=None)         # new cache per build() call,
    def _inner(n):                   # closing over arr
        return arr[:n]
    return _inner
'''

R5_TP_CALLSITE = '''
import numpy as np
import jax
from functools import lru_cache

@lru_cache(maxsize=None)
def _jit_decode(x):
    return jax.jit(lambda y: y)

def go(samples):
    return _jit_decode(np.asarray(samples))
'''

R5_NEGATIVE = '''
import jax
from functools import lru_cache

@lru_cache(maxsize=None)
def _jit_decode(rate_mbps: int, n_sym_bucket: int, window: int):
    return jax.jit(lambda y: y)

def go(samples):
    return _jit_decode(6, 8, 0)(samples)
'''


def test_r5_array_annotation_flagged():
    f = _findings(R5_TP_ANNOTATION, rules=["R5"])
    assert _rules_of(f) == ["R5"] and "'x'" in f[0].message


def test_r5_nested_lru_cache_flagged():
    f = _findings(R5_TP_NESTED, rules=["R5"])
    assert _rules_of(f) == ["R5"] and "inside another function" \
        in f[0].message


def test_r5_array_callsite_flagged():
    f = _findings(R5_TP_CALLSITE, rules=["R5"])
    assert _rules_of(f) == ["R5"] and "np.asarray" in f[0].message


def test_r5_scalar_keys_clean():
    assert _findings(R5_NEGATIVE, rules=["R5"]) == []


# ------------------------------------------------------------------ R6

R6_TP_BUCKET_FLOOR = '''
from ziria_tpu.utils.dispatch import pow2_bucket

def n_sym_bucket(n_sym):
    return pow2_bucket(n_sym, 4)         # literal floor forks Geometry
'''

R6_TP_BUCKET_KW = '''
from ziria_tpu.utils import dispatch

def cap_bucket(n):
    return dispatch.pow2_bucket(n, min_bucket=1 << 9)
'''

R6_TP_TUNABLE_KW = '''
import jax
from functools import lru_cache

@lru_cache(maxsize=None)
def _jit_decode(n_sym_bucket, viterbi_window=0):
    return jax.jit(lambda y: y)

def go(samples, n):
    return _jit_decode(n, viterbi_window=64)(samples)
'''

R6_NEGATIVE = '''
import jax
from functools import lru_cache
from ziria_tpu.utils.dispatch import pow2_bucket

@lru_cache(maxsize=None)
def _jit_decode(n_sym_bucket, viterbi_window=0):
    return jax.jit(lambda y: y)

def go(samples, n, geo):
    b = pow2_bucket(n, geo.sym_bucket_min)   # floor from Geometry: ok
    w = geo.resolve().viterbi_window
    return _jit_decode(b, viterbi_window=w)(samples)

def configure(report):
    # a KNOWN tunable keyword at a NON-factory call: not R6's business
    return report(chunk_len=8192)

def shape_literal(samples):
    # positional literals are shape-like plumbing, not named tunables
    return _jit_decode(8)(samples)
'''


def test_r6_literal_bucket_floor_flagged():
    f = _findings(R6_TP_BUCKET_FLOOR, rules=["R6"])
    assert _rules_of(f) == ["R6"] and "pow2_bucket floor" in \
        f[0].message
    f = _findings(R6_TP_BUCKET_KW, rules=["R6"])
    assert _rules_of(f) == ["R6"] and "1 << 9" in f[0].message


def test_r6_literal_tunable_keyword_flagged():
    f = _findings(R6_TP_TUNABLE_KW, rules=["R6"])
    assert _rules_of(f) == ["R6"]
    assert "viterbi_window=64" in f[0].message
    assert "Geometry" in f[0].message


def test_r6_near_miss_clean():
    assert _findings(R6_NEGATIVE, rules=["R6"]) == []


def test_r6_is_registered_and_tree_is_clean():
    # the shipped tree itself passes the new rule — no suppressions
    # were added to buy this (the cli pragma file predates R6)
    assert "R6" in RULES_BY_ID
    src_root = os.path.join(REPO, "ziria_tpu")
    res = lint_paths([src_root], rules=[RULES_BY_ID["R6"]])
    assert [f.message for f in res.findings] == []


# ------------------------------------------------- pragmas + engine

def test_pragma_suppresses_same_and_previous_line():
    same = R4_TP_SCATTERED.replace(
        'os.environ.get("ZIRIA_STREAMING_RX") == "0":',
        'os.environ.get("ZIRIA_STREAMING_RX") == "0":  '
        '# ziria: lint-ignore[R4] fixture justification')
    assert _findings(same, rules=["R4"]) == []
    prev = R4_TP_SCATTERED.replace(
        "    if os.environ",
        "    # ziria: lint-ignore[R4] fixture justification\n"
        "    if os.environ")
    assert _findings(prev, rules=["R4"]) == []


def test_file_pragma_suppresses_whole_file():
    src = "# ziria: lint-ignore-file[R4] fixture justification\n" \
        + R4_TP_SCATTERED + R4_TP_WRITE.replace("import os\n", "")
    res = lint_source(src, "f.py",
                      rules=[RULES_BY_ID["R4"]])
    assert res.findings == [] and res.suppressed == 2


def test_pragma_without_reason_is_itself_a_finding():
    src = R4_TP_SCATTERED.replace(
        '== "0":', '== "0":  # ziria: lint-ignore[R4]')
    f = _findings(src, rules=["R4"])
    assert _rules_of(f) == ["lint"]
    assert "justification" in f[0].message


def test_pragma_does_not_cover_other_rules():
    src = R4_TP_SCATTERED.replace(
        '== "0":', '== "0":  # ziria: lint-ignore[R1] wrong rule id')
    f = _findings(src, rules=["R4"])
    assert _rules_of(f) == ["R4"]


def test_pragma_in_string_literal_does_not_suppress():
    """Only real COMMENT tokens register: a docstring that merely
    QUOTES the pragma syntax (docs, examples) must never become a
    live whole-file suppression."""
    src = (
        '"""Suppress with `# ziria: lint-ignore-file[R4] reason`."""\n'
        + R4_TP_SCATTERED)
    f = _findings(src, rules=["R4"])
    assert _rules_of(f) == ["R4"]


def test_unused_pragma_is_a_finding():
    """A pragma whose finding was since fixed is stale creep — it
    would silently mask the NEXT finding of that rule there."""
    src = ("import os\n"
           "# ziria: lint-ignore[R4] justified once, finding fixed\n"
           "def env_window():\n"
           "    return os.environ.get('ZIRIA_WINDOW')\n")
    f = _findings(src)
    assert _rules_of(f) == ["lint"]
    assert "unused" in f[0].message and f[0].line == 2


def test_unused_pragma_not_reported_for_unrun_rules():
    """Under a --rules subset, 'unused' is undecidable for the rules
    that did not run — their pragmas are left alone."""
    src = ("import os\n"
           "# ziria: lint-ignore[R4] justified once, finding fixed\n"
           "def env_window():\n"
           "    return os.environ.get('ZIRIA_WINDOW')\n")
    assert _findings(src, rules=["R1"]) == []


def test_syntax_error_is_a_finding_not_a_crash():
    f = _findings("def broken(:\n", rules=["R1"])
    assert _rules_of(f) == ["lint"] and "syntax" in f[0].message


# ------------------------------------------------- JSON + CLI surface

def test_json_schema(tmp_path):
    p = tmp_path / "tp.py"
    p.write_text(R4_TP_SCATTERED + R3_TP)
    res = lint_paths([str(tmp_path)])
    doc = json.loads(res.to_json())
    assert doc["version"] == 1 and doc["files"] == 1
    assert doc["counts"] == {"R3": 1, "R4": 1}
    assert doc["suppressed"] == 0
    for f in doc["findings"]:
        assert set(f) == {"file", "line", "col", "rule", "message"}
        assert f["file"].endswith("tp.py") and f["line"] > 0


def test_cli_exit_codes(tmp_path, capsys):
    clean = tmp_path / "clean"
    clean.mkdir()
    (clean / "ok.py").write_text(R4_NEGATIVE)   # clean under ALL rules
    assert lint_main([str(clean)]) == 0

    for i, tp in enumerate([R1_TP_ENV, R2_TP, R3_TP,
                            R4_TP_SCATTERED, R5_TP_ANNOTATION]):
        d = tmp_path / f"tp{i}"
        d.mkdir()
        (d / "bad.py").write_text(tp)
        assert lint_main([str(d)]) == 1, f"fixture {i} must fail"
    capsys.readouterr()

    assert lint_main(["--rules", "R9", str(clean)]) == 2
    assert lint_main([str(tmp_path / "nope")]) == 2
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("R1", "R2", "R3", "R4", "R5"):
        assert rid in out


def test_cli_json_flag(tmp_path, capsys):
    d = tmp_path / "j"
    d.mkdir()
    (d / "bad.py").write_text(R3_TP)
    assert lint_main(["--json", str(d)]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["counts"] == {"R3": 1}


def test_lint_no_jax_import():
    """The pure-AST contract: linting the whole tree must never pull
    in jax (the gate has to work when the TPU backend probe hangs)."""
    code = (
        "import sys\n"
        "from ziria_tpu.analysis import lint_paths\n"
        "lint_paths([r'%s'])\n"
        "assert 'jax' not in sys.modules, 'lint imported jax'\n"
        % os.path.join(REPO, "ziria_tpu"))
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
