"""Viterbi decoder: roundtrip through the encoder, oracle equivalence,
puncturing with erasures, and noise tolerance."""

import numpy as np
import pytest

from ziria_tpu.ops import coding, viterbi
from ziria_tpu.utils.diff import assert_stream_eq

RNG = np.random.default_rng(11)


def tailed_bits(n):
    """random bits with 6 zero tail bits (zero-terminates the trellis)."""
    b = RNG.integers(0, 2, n).astype(np.uint8)
    b[-6:] = 0
    return b


def test_hard_decision_roundtrip():
    bits = tailed_bits(120)
    coded = np.asarray(coding.conv_encode(bits))
    dec = np.asarray(viterbi.viterbi_decode_bits(coded))
    assert_stream_eq(dec, bits)


def test_vs_oracle_on_noisy_llrs():
    bits = tailed_bits(40)
    coded = np.asarray(coding.conv_encode(bits)).astype(np.float64)
    llr = (2 * coded - 1) + 0.6 * RNG.standard_normal(coded.size)
    got = np.asarray(viterbi.viterbi_decode(llr.astype(np.float32)))
    want = viterbi.np_viterbi_ref(llr)
    assert_stream_eq(got, want)


def test_soft_decode_corrects_errors():
    bits = tailed_bits(200)
    coded = np.asarray(coding.conv_encode(bits)).astype(np.float64)
    tx = 2 * coded - 1
    rx = tx + 0.6 * RNG.standard_normal(tx.size)  # ~7 dB Eb/N0
    dec = np.asarray(viterbi.viterbi_decode(rx.astype(np.float32)))
    # rate-1/2 K=7 at this Eb/N0 decodes 200 bits error-free
    assert_stream_eq(dec, bits)


@pytest.mark.parametrize("rate", ["2/3", "3/4"])
def test_punctured_roundtrip(rate):
    n = 216  # multiple of both puncture periods after encoding
    bits = tailed_bits(n)
    coded = coding.conv_encode(bits)
    punct = coding.puncture(coded, rate)
    llr = 2.0 * np.asarray(punct, np.float32) - 1.0
    depunct = coding.depuncture(llr, rate, fill=0.0)
    dec = np.asarray(viterbi.viterbi_decode(depunct))
    assert_stream_eq(dec, bits)


def test_batched_vmap_frames():
    import jax
    frames = np.stack([tailed_bits(64) for _ in range(8)])
    coded = np.stack([np.asarray(coding.conv_encode(f)) for f in frames])
    llrs = 2.0 * coded.astype(np.float32) - 1.0
    dec = np.asarray(jax.jit(jax.vmap(viterbi.viterbi_decode))(llrs))
    assert_stream_eq(dec.astype(np.uint8), frames)


def test_n_bits_slice():
    bits = tailed_bits(50)
    coded = np.asarray(coding.conv_encode(bits))
    dec = np.asarray(viterbi.viterbi_decode_bits(coded, n_bits=30))
    assert dec.shape == (30,)
    assert_stream_eq(dec, bits[:30])


def test_native_c_viterbi_matches_jax():
    from ziria_tpu.runtime.native_lib import load, viterbi_decode_native
    if load() is None:
        pytest.skip("no native toolchain")
    bits = tailed_bits(300)
    coded = np.asarray(coding.conv_encode(bits)).astype(np.float64)
    llr = (2 * coded - 1) + 0.5 * RNG.standard_normal(coded.size)
    llr = llr.astype(np.float32)
    got_c = viterbi_decode_native(llr)
    got_jax = np.asarray(viterbi.viterbi_decode(llr))
    assert_stream_eq(got_c, got_jax)
    assert_stream_eq(got_c, bits)


def test_native_simd_acs_bit_exact_with_scalar():
    # the AVX2 ACS (runtime/native/viterbi.c, the SORA-SSE-class
    # baseline kernel) must match the portable scalar path bit-for-bit
    # on random soft values — same op order, same tie-breaks, same
    # per-step renorm (BASELINE.md r3)
    import ctypes

    from ziria_tpu.runtime.native_lib import load, viterbi_decode_native
    lib = load()
    if lib is None:
        pytest.skip("no native toolchain")
    if not hasattr(lib, "ziria_viterbi_decode_scalar"):
        pytest.skip("old native build without the scalar hook")
    rng = np.random.default_rng(42)
    for T in (64, 1000, 8208):
        llrs = rng.normal(size=(T, 2)).astype(np.float32)
        fast = viterbi_decode_native(llrs)
        ref = np.zeros(T, np.uint8)
        lib.ziria_viterbi_decode_scalar(
            llrs.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int64(T),
            ref.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        np.testing.assert_array_equal(fast, ref, err_msg=f"T={T}")
        oracle = np.asarray(viterbi.viterbi_decode(llrs.reshape(-1)))
        np.testing.assert_array_equal(fast, oracle, err_msg=f"T={T}")
