"""Fixed-point math library tests (ops/ext_math.py — the reference's
ext_math.c equivalents, SURVEY.md §2.2)."""

import numpy as np
import pytest

from ziria_tpu.ops import ext_math as xm


def test_sin_cos_int16_accuracy():
    a = np.arange(-32768, 32768, 17, dtype=np.int16)
    got_s = np.asarray(xm.sin_int16(a)).astype(np.float64) / 16384.0
    got_c = np.asarray(xm.cos_int16(a)).astype(np.float64) / 16384.0
    th = xm.q15_to_rad(a)
    # one LUT step of error budget (2π/1024 rad)
    assert np.max(np.abs(got_s - np.sin(th))) < 7e-3
    assert np.max(np.abs(got_c - np.cos(th))) < 7e-3


def test_sin_int16_wraps_like_phase():
    """int16 overflow of the angle is phase wrap — the point of Q15."""
    a = np.int16(32000)
    step = np.int16(2000)    # wraps past +32767
    wrapped = np.asarray(xm.sin_int16(
        np.array(int(a) + int(step), np.int64).astype(np.int16)))
    direct = np.asarray(xm.sin_int16(
        xm.rad_to_q15(xm.q15_to_rad(a) + xm.q15_to_rad(step))))
    assert abs(int(wrapped) - int(direct)) <= 32  # 1 LUT step


def test_atan2_int16_roundtrip():
    rng = np.random.default_rng(0)
    th = rng.uniform(-np.pi, np.pi, 512)
    r = rng.uniform(100, 30000, 512)
    y = np.round(r * np.sin(th)).astype(np.int16)
    x = np.round(r * np.cos(th)).astype(np.int16)
    got = xm.q15_to_rad(np.asarray(xm.atan2_int16(y, x)))
    want = np.arctan2(y.astype(np.float64), x.astype(np.float64))
    d = np.angle(np.exp(1j * (got - want)))
    assert np.max(np.abs(d)) < 2e-3


def test_usqrt_exact():
    x = np.concatenate([np.arange(0, 4096),
                        np.array([2**31 - 1, 2**30, 999999937])])
    got = np.asarray(xm.usqrt(x.astype(np.int32)))
    want = np.floor(np.sqrt(x.astype(np.float64))).astype(np.int64)
    np.testing.assert_array_equal(got.astype(np.int64), want)


def test_ulog2_exact():
    x = np.concatenate([np.arange(1, 4096),
                        2 ** np.arange(1, 31),
                        2 ** np.arange(2, 31) - 1]).astype(np.int32)
    got = np.asarray(xm.ulog2(x))
    want = np.floor(np.log2(x.astype(np.float64))).astype(np.int64)
    np.testing.assert_array_equal(got.astype(np.int64), want)


def test_jit_traceable():
    import jax

    @jax.jit
    def f(a, y, x):
        return xm.sin_int16(a), xm.atan2_int16(y, x), xm.usqrt(x)

    a = np.arange(64, dtype=np.int16)
    out = f(a, a, (a + 1).astype(np.int32))
    assert all(np.asarray(o).shape == (64,) for o in out)


def test_zir_source_can_declare_ext_math():
    """`.zir` programs bind the fixed-point library via ext fun."""
    from ziria_tpu.frontend import compile_source
    from ziria_tpu.interp.interp import run
    from ziria_tpu.backend.execute import run_jit

    prog = compile_source("""
      ext fun sin_int16(a: int16) : int16
      let comp main = read[int16] >>> map sin_int16 >>> write[int16]
    """)
    a = np.arange(-512, 512, 8, dtype=np.int16)
    ref = run(prog.comp, list(a)).out_array()
    got = run_jit(prog.comp, a)
    np.testing.assert_array_equal(np.asarray(ref, np.int64),
                                  np.asarray(got, np.int64))
    np.testing.assert_array_equal(np.asarray(ref),
                                  np.asarray(xm.sin_int16(a)))
