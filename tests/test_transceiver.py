"""Transceiver/MAC-lite tests: closed TX↔RX loop with stop-and-wait ARQ
(phy/wifi/transceiver.py — the reference's transceiver/ + mac/ role,
SURVEY.md §2.3)."""

import numpy as np
import pytest

import jax

from ziria_tpu.phy import channel as ch
from ziria_tpu.phy.wifi import transceiver as trx, tx
from ziria_tpu.phy.wifi.transceiver import (MacFrame, Station, TYPE_ACK,
                                            TYPE_DATA, mac_frame_psdu,
                                            run_link)
from ziria_tpu.utils.dispatch import cache_growth


def test_mac_frame_roundtrip():
    psdu = mac_frame_psdu(TYPE_DATA, 7, dst=2, src=1, payload=b"hello")
    fr = MacFrame.parse(psdu)
    assert fr is not None
    assert (fr.ftype, fr.seq, fr.dst, fr.src, fr.payload) == \
        (TYPE_DATA, 7, 2, 1, b"hello")


def test_mac_frame_crc_reject():
    psdu = mac_frame_psdu(TYPE_ACK, 3, dst=2, src=1)
    bad = psdu.copy()
    bad[1] ^= 0x40
    assert MacFrame.parse(bad) is None


def test_perfect_link_delivers_and_acks():
    a = Station(addr=1, rate_mbps=24)
    b = Station(addr=2)
    payloads = [b"frame-one", b"frame-two longer payload", b"x"]
    run_link(a, b, payloads)
    assert [p for _, p in b.delivered] == payloads
    assert all(src == 1 for src, _ in b.delivered)
    assert a.acked == [0, 1, 2] and a.failed == []
    assert a.counters["retries"] == 0
    assert b.counters["tx_ack"] == 3 and a.counters["rx_ack"] == 3


def test_lost_data_frame_retransmits():
    """Channel kills the first copy of each DATA frame; ARQ recovers."""
    a = Station(addr=1, rate_mbps=12)
    b = Station(addr=2)
    seen = []

    def lossy(samples, k):
        seen.append(k)
        # transmissions alternate DATA/ACK on a clean link; kill the
        # very first transmission only
        if k == 0:
            return np.zeros_like(samples)
        return samples

    run_link(a, b, [b"payload"], channel=lossy)
    assert [p for _, p in b.delivered] == [b"payload"]
    assert a.counters["retries"] == 1
    assert a.acked == [0] and a.failed == []
    assert seen == [0, 1, 2]   # DATA (lost), DATA (retry), ACK


def test_lost_ack_dedups_on_retransmit():
    """ACK lost: sender retransmits, receiver re-ACKs but must not
    deliver the payload twice."""
    a = Station(addr=1, rate_mbps=12)
    b = Station(addr=2)

    def drop_first_ack(samples, k):
        if k == 1:     # k=0 DATA, k=1 the first ACK
            return np.zeros_like(samples)
        return samples

    run_link(a, b, [b"only-once"], channel=drop_first_ack)
    assert [p for _, p in b.delivered] == [b"only-once"]
    assert b.counters["dups"] == 1 and b.counters["rx_data"] == 2
    assert a.acked == [0]


def test_retry_limit_gives_up():
    a = Station(addr=1, rate_mbps=12, max_tries=2)
    b = Station(addr=2)

    def dead(samples, k):
        return np.zeros_like(samples)

    run_link(a, b, [b"void"], channel=dead)
    assert b.delivered == []
    assert a.failed == [0] and a.acked == []
    assert a.counters["drops"] == 1
    # a later frame over a good channel still goes through
    run_link(a, b, [b"after"], channel=trx.perfect_channel)
    assert [p for _, p in b.delivered] == [b"after"]


def test_noisy_channel_link():
    """AWGN + idle-air padding + small CFO: the full sync path in the
    loop, both directions."""
    a = Station(addr=1, rate_mbps=24)
    b = Station(addr=2)
    keys = iter(jax.random.split(jax.random.PRNGKey(0), 64))

    def noisy(samples, k):
        x = ch.delay(next(keys), samples, n_before=180, n_after=64,
                     noise_db=-28.0)
        x = ch.apply_cfo(x, 0.0012)
        return np.asarray(ch.awgn(next(keys), x, snr_db=18.0))

    payloads = [b"noisy link frame", b"second"]
    run_link(a, b, payloads, channel=noisy)
    assert [p for _, p in b.delivered] == payloads
    assert a.failed == []


def test_long_frame_timer_starts_after_transmit():
    """A frame longer than ACK_TIMEOUT samples must not expire during
    its own transmission (timer anchored at end of emit)."""
    a = Station(addr=1, rate_mbps=6)      # ~1KB at 6 Mbps >> ACK_TIMEOUT
    payload = bytes(1000)
    a.send(payload, dst=2)
    assert a._pending is not None
    assert a._pending.deadline > a.now    # not already expired
    assert a.poll() is None               # no spurious retransmit


def test_run_link_step_exhaustion_fails_cleanly():
    """max_steps exhausted with the frame in flight: frame is failed,
    next send() is not poisoned."""
    a = Station(addr=1, rate_mbps=12, max_tries=100)
    b = Station(addr=2)

    def dead(samples, k):
        return np.zeros_like(samples)

    run_link(a, b, [b"lost", b"also-lost"], channel=dead, max_steps=3)
    assert a.failed == [0, 1]
    assert a.counters["drops"] == 2


def test_emit_reuses_compiled_encoder():
    """The module docstring's claim, made true and pinned: repeated
    sends re-dispatch the cached jitted encoder, zero re-compiles —
    Station._emit (DATA and ACK alike) must never re-trace once its
    (rate, bit bucket, symbol bucket) geometry is compiled. Payload
    lengths differ on purpose: varied lengths inside one bit bucket
    share one compiled encoder (the bucketed-geometry contract)."""
    a = Station(addr=1, rate_mbps=24)
    b = Station(addr=2)
    run_link(a, b, [b"warm-up frame"])        # pays any compiles once
    with cache_growth(tx._jit_encode_frame) as g:
        run_link(a, b, [b"second frame!!", b"third, longer."])
    assert a.acked == [0, 1, 2] and a.failed == []
    assert g.total == 0, "Station._emit re-compiled across sends"


def test_perfect_link_fxp_stations():
    # both stations receive through the Q15 integer interior — the
    # MAC loop on the reference's fixed-point discipline
    a = Station(addr=1, rate_mbps=24, fxp=True)
    b = Station(addr=2, fxp=True)
    payloads = [b"integer frame one", b"and two"]
    run_link(a, b, payloads)
    assert [p for _, p in b.delivered] == payloads
    assert a.acked == [0, 1] and a.failed == []
    assert a.counters["retries"] == 0
