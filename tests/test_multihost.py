"""Multi-host scale-out helpers (parallel/multihost.py): DCN/ICI-aware
mesh construction driving the same dp x pp machinery, on the 8-device
virtual CPU mesh (tests/conftest.py). The reference has no distributed
backend at all (SURVEY.md §2.5) — these pin the new framework's
equivalent of the NCCL/MPI layer."""

import jax
import numpy as np
import pytest

import ziria_tpu as z
from ziria_tpu.parallel import (build_mesh, init_multihost,
                                lower_stage_parallel, mesh_info,
                                shard_batch)


def test_init_multihost_single_process_noop():
    assert init_multihost() is False          # no args, single process
    assert init_multihost(num_processes=1) is False


def test_build_mesh_shapes_and_info():
    mesh = build_mesh(dp=2, pp=4)
    assert mesh.devices.shape == (2, 4)
    assert mesh.axis_names == ("dp", "pp")
    info = mesh_info(mesh)
    assert info["shape"] == {"dp": 2, "pp": 4}
    assert info["n_processes"] == 1
    assert info["dcn_axes"] == []             # single process: all ICI


def test_build_mesh_too_few_devices():
    with pytest.raises(ValueError, match="needs 16"):
        build_mesh(dp=4, pp=4)


def test_build_mesh_drives_dp_x_pp_pipeline():
    """The built mesh runs the composed frame-batching x stage-parallel
    pipeline and matches the sequential result."""
    mesh = build_mesh(dp=2, pp=4)
    stages = [
        z.zmap(lambda x: x * 2.0, name="s0"),
        z.map_accum(lambda s, x: (s + x, s + x), 0.0, name="cumsum"),
        z.zmap(lambda x: x + 1.0, name="s2"),
        z.zmap(lambda x: x * 0.5, name="s3"),
    ]
    pp = lower_stage_parallel(z.par_pipe(*stages), mesh, width=4,
                              batch_axis="dp")
    rng = np.random.default_rng(1)
    xs = rng.normal(size=(4, 5, pp.take)).astype(np.float32)
    ys = np.asarray(pp.run(shard_batch(mesh, xs, axis="dp")))

    # sequential oracle per stream
    want = np.empty_like(xs.reshape(4, -1))
    for b in range(4):
        v = xs[b].reshape(-1) * 2.0
        v = np.cumsum(v)
        v = (v + 1.0) * 0.5
        want[b] = v
    np.testing.assert_allclose(ys.reshape(4, -1), want, rtol=1e-5)


def test_build_mesh_dp_axis_would_cross_dcn():
    """dp-must-divide-process-count guard: simulate the error path by
    asking for a layout the policy forbids. With one process this can
    only be exercised through the validation logic directly."""
    devs = jax.devices()[:8]
    n_proc = len({d.process_index for d in devs})
    assert n_proc == 1   # virtual mesh is single-process: guard inert
    # the mesh builder still accepts every single-process layout
    for dp, pp in ((1, 8), (8, 1), (4, 2)):
        m = build_mesh(dp=dp, pp=pp, devices=devs)
        assert m.devices.size == 8
