"""DSP op library vs independent numpy oracles (golden-file pattern,
SURVEY.md §4: generate ground truth from an obvious loop implementation,
compare the vectorized TPU path against it)."""

import numpy as np
import pytest

from ziria_tpu.utils.bits import (bytes_to_bits, bits_to_bytes,
                                  bits_to_uint, uint_to_bits)
from ziria_tpu.ops import (crc, scramble, coding, interleave, modulate,
                           ofdm, cplx)
from ziria_tpu.utils.diff import assert_stream_eq

RNG = np.random.default_rng(42)


def rand_bits(n):
    return RNG.integers(0, 2, n).astype(np.uint8)


# ---------------------------------------------------------------- bits

def test_bits_bytes_roundtrip():
    data = RNG.integers(0, 256, 33).astype(np.uint8)
    bits = bytes_to_bits(data)
    assert bits.shape == (33 * 8,)
    back = bits_to_bytes(bits)
    assert_stream_eq(np.asarray(back), data)


def test_bit_order_lsb_first():
    bits = np.asarray(bytes_to_bits(np.array([0b00000001], np.uint8)))
    assert bits[0] == 1 and bits[1:].sum() == 0


def test_uint_roundtrip():
    v = np.asarray(bits_to_uint(uint_to_bits(np.uint32(0xDEADBEEF), 32)))
    assert v == 0xDEADBEEF


# ---------------------------------------------------------------- crc

def test_crc32_check_value():
    # classic CRC-32 check: crc32(b"123456789") == 0xCBF43926
    data = np.frombuffer(b"123456789", np.uint8)
    assert int(np.asarray(crc.crc32_bytes(data))) == 0xCBF43926


def test_crc32_bits_vs_oracle():
    bits = rand_bits(8 * 41)
    got = np.asarray(crc.crc32_bits(bits))
    want = crc.np_crc32_bits_ref(bits)
    assert_stream_eq(got, want)


def test_crc32_append_check_roundtrip():
    bits = rand_bits(8 * 17)
    with_fcs = crc.append_crc32(bits)
    assert bool(np.asarray(crc.check_crc32(with_fcs)))
    corrupted = np.asarray(with_fcs).copy()
    corrupted[5] ^= 1
    assert not bool(np.asarray(crc.check_crc32(corrupted)))


# ---------------------------------------------------------------- scrambler

def test_scramble_vs_oracle():
    bits = rand_bits(300)
    seed = uint_to_bits(np.uint32(0b1011101), 7)
    got = np.asarray(scramble.scramble_bits(bits, seed))
    want = scramble.np_scramble_ref(bits, np.asarray(seed))
    assert_stream_eq(got, want)


def test_scramble_involution():
    bits = rand_bits(500)
    seed = uint_to_bits(np.uint32(0x5B), 7)
    twice = scramble.descramble_bits(scramble.scramble_bits(bits, seed), seed)
    assert_stream_eq(np.asarray(twice), bits)


def test_scrambler_sequence_period_127_and_balance():
    seq = np.asarray(scramble.lfsr_sequence_127(np.ones(7, np.uint8)))
    assert seq.shape == (127,)
    # maximal-length sequence: 64 ones, 63 zeros
    assert seq.sum() == 64


def test_seed_recovery():
    for seed_val in [1, 0b1011101, 0x7F, 0x2A]:
        seed = uint_to_bits(np.uint32(seed_val), 7)
        zeros = np.zeros(7, np.uint8)
        first7 = np.asarray(scramble.scramble_bits(zeros, seed))
        rec = np.asarray(scramble.recover_seed(first7))
        assert_stream_eq(rec, np.asarray(seed))


# ---------------------------------------------------------------- coding

def test_conv_encode_vs_oracle():
    bits = rand_bits(200)
    got = np.asarray(coding.conv_encode(bits))
    want = coding.np_conv_encode_ref(bits)
    assert_stream_eq(got, want)


def test_conv_encode_impulse_generators():
    # impulse response = generator taps interleaved
    x = np.zeros(7, np.uint8)
    x[0] = 1
    out = np.asarray(coding.conv_encode(x)).reshape(-1, 2)
    assert_stream_eq(out[:, 0], coding.G0.astype(np.uint8))
    assert_stream_eq(out[:, 1], coding.G1.astype(np.uint8))


@pytest.mark.parametrize("rate,period,kept", [("1/2", 2, 2), ("2/3", 4, 3),
                                              ("3/4", 6, 4)])
def test_puncture_lengths(rate, period, kept):
    coded = rand_bits(12 * period)
    p = np.asarray(coding.puncture(coded, rate))
    assert p.size == 12 * kept


@pytest.mark.parametrize("rate", ["1/2", "2/3", "3/4"])
def test_depuncture_inverse_on_kept_positions(rate):
    coded = rand_bits(24).astype(np.float32)
    p = coding.puncture(coded.astype(np.uint8), rate)
    d = np.asarray(coding.depuncture(np.asarray(p, np.float32), rate,
                                     fill=-1.0))
    keep = np.tile(coding.PUNCTURE_KEEP[rate], 24 // coding.PUNCTURE_KEEP[rate].size)
    assert_stream_eq(d[keep], coded[keep], atol=0)
    assert (d[~keep] == -1.0).all()


# ---------------------------------------------------------------- interleaver

@pytest.mark.parametrize("n_cbps,n_bpsc", [(48, 1), (96, 2), (192, 4),
                                           (288, 6)])
def test_interleave_vs_oracle(n_cbps, n_bpsc):
    bits = rand_bits(n_cbps * 3)
    got = np.asarray(interleave.interleave(bits, n_cbps, n_bpsc))
    want = interleave.np_interleave_ref(bits, n_cbps, n_bpsc)
    assert_stream_eq(got, want)


@pytest.mark.parametrize("n_cbps,n_bpsc", [(48, 1), (96, 2), (192, 4),
                                           (288, 6)])
def test_deinterleave_inverse(n_cbps, n_bpsc):
    bits = rand_bits(n_cbps * 2)
    round_trip = interleave.deinterleave(
        interleave.interleave(bits, n_cbps, n_bpsc), n_cbps, n_bpsc)
    assert_stream_eq(np.asarray(round_trip), bits)


# ---------------------------------------------------------------- modulation

@pytest.mark.parametrize("n_bpsc", [1, 2, 4, 6])
def test_modulate_vs_oracle(n_bpsc):
    bits = rand_bits(n_bpsc * 96)
    got = cplx.to_complex(np.asarray(modulate.modulate(bits, n_bpsc)))
    want = modulate.np_modulate_ref(bits, n_bpsc)
    assert_stream_eq(got, want, atol=1e-6)


@pytest.mark.parametrize("n_bpsc", [1, 2, 4, 6])
def test_modulate_unit_average_power(n_bpsc):
    # over all bit patterns, constellation has unit average energy
    n_sym = 1 << n_bpsc
    patterns = np.asarray(
        [[(v >> k) & 1 for k in range(n_bpsc)][::-1] for v in range(n_sym)],
        np.uint8).reshape(-1)
    syms = cplx.to_complex(np.asarray(modulate.modulate(patterns, n_bpsc)))
    assert abs(np.mean(np.abs(syms) ** 2) - 1.0) < 1e-6


# ---------------------------------------------------------------- ofdm

def test_map_extract_roundtrip():
    syms_c = (RNG.standard_normal((5, 48))
              + 1j * RNG.standard_normal((5, 48))).astype(np.complex64)
    syms = cplx.from_complex(syms_c)
    bins = ofdm.map_subcarriers(syms, symbol_index0=1)
    data, pilots = ofdm.extract_subcarriers(bins)
    assert_stream_eq(cplx.to_complex(np.asarray(data)), syms_c, atol=1e-6)
    # pilot polarity follows the 127-sequence
    pol = ofdm.PILOT_POLARITY[1:6]
    want_p = ofdm.PILOT_VALS[None, :] * pol[:, None]
    assert_stream_eq(cplx.to_complex(np.asarray(pilots)),
                     want_p.astype(np.complex64), atol=1e-6)


def test_ofdm_modulate_demodulate_roundtrip():
    syms = cplx.from_complex(
        (RNG.standard_normal((4, 48)) + 1j * RNG.standard_normal((4, 48))
         ).astype(np.complex64))
    bins = ofdm.map_subcarriers(syms)
    t = ofdm.ofdm_modulate(bins)
    assert t.shape == (4, 80, 2)
    # cyclic prefix is a copy of the tail
    assert_stream_eq(np.asarray(t[:, :16]), np.asarray(t[:, -16:]),
                     atol=1e-6)
    back = ofdm.ofdm_demodulate(t)
    assert_stream_eq(np.asarray(back), np.asarray(bins), atol=1e-4)


def test_dft_pair_matches_numpy_fft():
    x = (RNG.standard_normal((3, 64)) + 1j * RNG.standard_normal((3, 64))
         ).astype(np.complex64)
    p = cplx.from_complex(x)
    fwd = cplx.to_complex(np.asarray(cplx.fft_pair(p)))
    assert_stream_eq(fwd, np.fft.fft(x, axis=-1).astype(np.complex64),
                     atol=1e-3)
    inv = cplx.to_complex(np.asarray(cplx.ifft_pair(p)))
    assert_stream_eq(inv, np.fft.ifft(x, axis=-1).astype(np.complex64),
                     atol=1e-4)


def test_preamble_shape_and_sts_periodicity():
    p = cplx.to_complex(np.asarray(ofdm.preamble()))
    assert p.shape == (320,)
    # short training: 16-sample periodicity over the first 160 samples
    assert np.allclose(p[:144], p[16:160], atol=1e-5)
    # long training: the two 64-sample symbols are identical
    assert np.allclose(p[192:256], p[256:320], atol=1e-5)
    # GI2 is the tail of the long symbol
    assert np.allclose(p[160:192], p[224:256], atol=1e-5)
