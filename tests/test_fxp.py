"""Int16 fixed-point complex16 policy (VERDICT r1 #6, SURVEY.md §7
hard-part (b)): complex16 values are integer IQ pairs with C shorts
semantics — int32 mid-expression, wrap to int16 at assignment/cast —
and the TX chain's golden outputs are EXACT integers."""

import numpy as np
import pytest

from ziria_tpu.frontend import ZiriaRuntimeError, compile_source
from ziria_tpu.interp.interp import run


def run_fxp(src, xs, backend="interp"):
    prog = compile_source(src, fxp_complex16=True)
    if backend == "interp":
        return np.asarray(run(prog.comp, list(xs)).out_array())
    from ziria_tpu.backend.execute import run_jit
    return np.asarray(run_jit(prog.comp, xs))


MUL_SRC = """
  let comp main = read[complex16] >>>
    repeat {
      x <- take;
      var y : complex16 := complex16(0, 0);
      do { y := x * x };
      emit y
    } >>> write[complex16]
"""


@pytest.mark.parametrize("backend", ["interp", "jit"])
def test_fx_multiply_wraps_at_store(backend):
    """(300 + 200j)^2 = 50000 + 120000j in int32; storing to complex16
    wraps each component to int16: 50000 -> -15536, 120000 -> -11072."""
    iq = np.array([[300, 200], [1, 2], [-5, 7]], np.int16)
    out = run_fxp(MUL_SRC, iq, backend)
    want = []
    for re, im in iq.astype(np.int64):
        wre = (re * re - im * im)
        wim = (2 * re * im)
        wrap = lambda v: ((int(v) + 2**15) % 2**16) - 2**15  # noqa: E731
        want.append([wrap(wre), wrap(wim)])
    np.testing.assert_array_equal(out, np.asarray(want, np.int16))


def test_fx_no_midexpression_wrap():
    """x*x followed by a real shift happens in int32 — the intermediate
    product must NOT wrap before the shift (C promotion semantics)."""
    src = """
      let comp main = read[complex16] >>>
        repeat {
          x <- take;
          var y : complex16 := complex16(0, 0);
          do { y := (x * x) >> 8 };
          emit y
        } >>> write[complex16]
    """
    iq = np.array([[300, 200]], np.int16)
    out = run_fxp(src, iq)
    # int32 products: (50000, 120000) >> 8 = (195, 468) — in-range, so
    # the store doesn't wrap; a premature int16 wrap would give garbage
    np.testing.assert_array_equal(out, [[195, 468]])


def test_fx_re_im_are_ints():
    src = """
      let comp main = read[complex16] >>>
        repeat {
          x <- take;
          var r : int32 := 0;
          do { r := x.re * x.re + x.im * x.im };
          emit r
        } >>> write[int32]
    """
    prog = compile_source(src, fxp_complex16=True)   # typechecker: ok
    iq = np.array([[300, -200]], np.int16)
    out = np.asarray(run(prog.comp, list(iq)).out_array())
    np.testing.assert_array_equal(out, [300 * 300 + 200 * 200])


def test_fx_complex_division_rejected():
    src = """
      let comp main = read[complex16] >>>
        repeat { x <- take; emit x / x } >>> write[complex16]
    """
    with pytest.raises(ZiriaRuntimeError, match="fixed-point"):
        run_fxp(src, np.array([[3, 4]], np.int16))


def test_fx_interp_equals_jit_on_chain():
    """The golden fxp TX chain: interp == jit bit for bit, and every
    output level is exactly +-362."""
    import os
    here = os.path.dirname(__file__)
    src = open(os.path.join(here, "..", "examples",
                            "tx_qpsk_fxp.zir")).read()
    rng = np.random.default_rng(5)
    bits = rng.integers(0, 2, 192).astype(np.uint8)
    a = run_fxp(src, bits, "interp")
    b = run_fxp(src, bits, "jit")
    np.testing.assert_array_equal(a, b)
    assert set(np.unique(a)) <= {-362, 362}


def test_fx_chain_matches_ops_oracle():
    """tx_qpsk_fxp == the ops/ chain (scramble ^ seq -> conv_encode ->
    interleave(96, 2) -> QPSK at round(512/sqrt(2))) — exact ints."""
    import os

    from ziria_tpu.ops.coding import np_conv_encode_ref
    from ziria_tpu.ops.interleave import interleave
    from ziria_tpu.ops.scramble import np_lfsr_sequence_127

    here = os.path.dirname(__file__)
    src = open(os.path.join(here, "..", "examples",
                            "tx_qpsk_fxp.zir")).read()
    rng = np.random.default_rng(6)
    n_bits = 96 * 2      # -> 192*2 coded bits = 4 interleaver blocks
    bits = rng.integers(0, 2, n_bits).astype(np.uint8)
    got = run_fxp(src, bits, "jit")

    seed = np.array([1, 0, 1, 1, 1, 0, 1], np.uint8)
    scr = bits ^ np.resize(np_lfsr_sequence_127(seed), n_bits)
    coded = np_conv_encode_ref(scr)
    inter = np.concatenate([
        np.asarray(interleave(coded[k:k + 96], 96, 2))
        for k in range(0, coded.size, 96)])
    lvl = 362
    want = np.stack([np.where(inter[0::2] == 1, lvl, -lvl),
                     np.where(inter[1::2] == 1, lvl, -lvl)],
                    axis=-1).astype(np.int16)
    np.testing.assert_array_equal(got, want)


def test_default_policy_unchanged():
    """Without the flag, complex16 still evaluates as complex64."""
    prog = compile_source(MUL_SRC)
    iq = np.array([[3, 4]], np.int16)
    out = np.asarray(run(prog.comp, list(iq)).out_array())
    np.testing.assert_array_equal(out, [[-7, 24]])   # (3+4j)^2


def test_fx_declared_int_pairs_stay_elementwise():
    """Review r2: a declared arr[2] int under the policy must multiply
    elementwise, not complex-wise (declared types beat the pair
    heuristic)."""
    src = """
      let comp main = read[int32] >>>
        repeat {
          (p : arr[2] int32) <- takes 2;
          var a : arr[2] int32 := {0, 0};
          do { a := p * p };
          emits a
        } >>> write[int32]
    """
    xs = np.array([3, 4], np.int32)
    out = run_fxp(src, xs)
    np.testing.assert_array_equal(out, [9, 16])   # NOT (-7, 24)


def test_fx_fractional_scale_rejected():
    src = """
      let comp main = read[complex16] >>>
        repeat { x <- take; emit x * 0.5 } >>> write[complex16]
    """
    with pytest.raises(ZiriaRuntimeError, match="fractional"):
        run_fxp(src, np.array([[100, 100]], np.int16))


def test_fx_fft_ext_boundary():
    """v_fft under the policy: pairs convert to complex64 at the ext
    boundary (the documented f32 interior), and the complex16 return
    requantizes — matching the f32 reference FFT to +-1 LSB."""
    src = """
      ext fun v_fft(x: arr[64] complex16) : arr[64] complex16
      let comp main = read[complex16] >>>
        repeat {
          (x : arr[64] complex16) <- takes 64;
          var y : arr[64] complex16;
          do { y := v_fft(x) };
          emits y
        } >>> write[complex16]
    """
    rng = np.random.default_rng(8)
    iq = rng.integers(-500, 500, (64, 2)).astype(np.int16)
    out = run_fxp(src, iq)
    z = iq[:, 0].astype(np.float64) + 1j * iq[:, 1]
    want = np.fft.fft(z)
    got = out[:, 0] + 1j * out[:, 1]
    assert np.abs(got - want).max() <= 1.0


def test_fx_map_ext_boundary():
    """Review r2: the `map <ext>` form must apply the same ext-boundary
    conversion as expression calls — v_fft over a complex16 stream
    under the policy matches the reference FFT."""
    src = """
      ext fun v_fft(x: arr[64] complex16) : arr[64] complex16
      let comp main = read[complex16] >>> map v_fft >>> write[complex16]
    """
    rng = np.random.default_rng(9)
    iq = rng.integers(-400, 400, (64, 2)).astype(np.int16)
    out = run_fxp(src, iq)
    assert out.shape == (64, 2)
    z = iq[:, 0].astype(np.float64) + 1j * iq[:, 1]
    want = np.fft.fft(z)
    got = out[:, 0] + 1j * out[:, 1]
    assert np.abs(got - want).max() <= 1.0


@pytest.mark.parametrize("backend", ["interp", "jit"])
def test_fx_overflowing_float_wrap_deterministic(backend):
    """Review r2: float values beyond int16 range (e.g. full-scale FFT
    components) wrap MODULARLY and identically on both backends —
    astype(int16) alone saturates under XLA but wraps under numpy."""
    src = """
      ext fun v_fft(x: arr[64] complex16) : arr[64] complex16
      let comp main = read[complex16] >>> map v_fft >>> write[complex16]
    """
    iq = np.full((64, 2), 20000, np.int16)   # DC -> bin0 ~ 1.28e6
    out = run_fxp(src, iq, backend)
    z = iq[:, 0].astype(np.float64) + 1j * iq[:, 1]
    f = np.fft.fft(z)
    wrap = lambda v: ((int(round(v)) + 2**15) % 2**16) - 2**15  # noqa
    want = np.stack([[wrap(c.real), wrap(c.imag)] for c in f])
    np.testing.assert_array_equal(out.astype(np.int64), want)


def test_in_trace_probe_works_on_this_jax():
    """ADVICE r4: _in_trace() probes the private jax._src.core
    trace_ctx API. If a JAX upgrade moves the attribute, the fallback
    silently disables the device-constant cache (perf-only) — this
    test turns that silent regression into a visible failure on the
    pinned JAX version."""
    import jax
    import jax.numpy as jnp

    from ziria_tpu.ops import fxp

    assert fxp._in_trace() is False      # eval context
    seen = {}

    @jax.jit
    def f(x):
        seen["in_trace"] = fxp._in_trace()
        return x + 1

    f(jnp.int32(1))
    assert seen["in_trace"] is True      # jit trace context
    # and the probe path itself did not fall back with a warning
    assert fxp._TRACE_PROBE_WARNED is False
