"""AutoLUT pass: declared-domain maps become table gathers with
identical semantics on both backends (the reference's --autolut flag
invariance, SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

import ziria_tpu as z
from ziria_tpu.backend.execute import run_jit
from ziria_tpu.core import ir
from ziria_tpu.core.autolut import LutError, autolut, lut_map
from ziria_tpu.core.opt import fold
from ziria_tpu.interp.interp import run
from ziria_tpu.utils.diff import assert_stream_eq


def popcount8(x):
    x = jnp.asarray(x, jnp.int32)
    n = jnp.zeros_like(x)
    for k in range(8):
        n = n + ((x >> k) & 1)
    return n


def test_lut_matches_direct_both_backends():
    prog = z.zmap(popcount8, name="popcount", in_domain=256)
    lutted = autolut(prog)
    assert isinstance(lutted, ir.Map) and lutted.label().startswith("lut[")
    xs = np.arange(256, dtype=np.int32)
    want = run(prog, list(xs)).out_array()
    got_i = run(lutted, list(xs)).out_array()
    assert_stream_eq(np.asarray(got_i), want, name="lut/interp")
    got_j = run_jit(lutted, xs, width=4)
    assert_stream_eq(np.asarray(got_j), want, name="lut/jit")


def test_lut_in_pipeline_and_fuses():
    prog = z.pipe(z.zmap(lambda x: (x * 7) % 64, name="hash"),
                  z.zmap(popcount8, name="pc", in_domain=256))
    lutted = fold(autolut(prog))
    assert isinstance(lutted, ir.Map)  # fused to one stage
    xs = np.arange(64, dtype=np.int32)
    want = run(prog, list(xs)).out_array()
    got = run_jit(lutted, xs, width=8)
    assert_stream_eq(np.asarray(got), np.asarray(want))


def test_vector_valued_lut():
    # table rows are arrays: byte -> its 8 bits (used by scrambler-style
    # bit unpacking)
    def bits_of(x):
        return (jnp.asarray(x, jnp.int32)[None] >> jnp.arange(8)) & 1

    prog = z.zmap(bits_of, out_arity=1, name="bits", in_domain=256)
    lutted = autolut(prog)
    xs = np.array([0, 1, 170, 255], np.int32)
    want = run(prog, list(xs)).out_array()
    got = run(lutted, list(xs)).out_array()
    assert_stream_eq(np.asarray(got), np.asarray(want))


def test_bad_domains_rejected():
    with pytest.raises(LutError):
        lut_map(ir.Map(lambda x: x, 1, 1, "m", None))
    with pytest.raises(LutError):
        lut_map(ir.Map(lambda x: x, 1, 1, "m", 0))
    with pytest.raises(LutError):
        lut_map(ir.Map(lambda v: v, 2, 1, "m", 16))
    with pytest.raises(LutError):
        lut_map(ir.Map(lambda x: jnp.zeros((1 << 23,)) + x, 1, 1, "m", 2))


def test_nested_structure_rewritten():
    inner = z.repeat(z.let("x", z.take, z.emit1(lambda e: e["x"])))
    prog = z.pipe(inner, z.zmap(popcount8, in_domain=256, name="pc"))
    lutted = autolut(prog)
    assert isinstance(lutted, ir.Pipe)
    assert lutted.down.label().startswith("lut[")


def test_fusion_preserves_in_domain():
    """Map-map fusion keeps the upstream's declared domain, so
    autolut(fold(p)) still applies the LUT rewrite (the documented
    order is autolut-then-fold, but the other order must not silently
    lose the declaration)."""
    from ziria_tpu.core.opt import fold
    prog = z.pipe(z.zmap(popcount8, in_domain=256, name="pc"),
                  z.zmap(lambda x: x + 1, name="inc"))
    fused = fold(prog)
    assert isinstance(fused, ir.Map) and fused.in_domain == 256
    assert autolut(fused).label().startswith("lut[")
