"""End-to-end statistical validation: BER waterfalls under AWGN
(VERDICT r2 #8). The golden pairs prove bit-exactness on one capture;
this proves the *statistics* of the demod+decode chain behave like an
802.11a receiver should: BER falls monotonically with SNR, reaches
zero at documented operating points, denser constellations pay more at
equal SNR, and soft-decision decoding shows real coding gain over the
theoretical UNCODED channel-bit error rate.

Setup is the standard BER-sim isolation: perfect timing/CFO (frames
from the batched TX + AWGN only), rate forced — measuring the
equalize/demap/deinterleave/Viterbi/descramble chain, not packet
detection (detection robustness is exercised by the golden captures'
impairments).

The measurement rides the device-resident sweep engine
(phy/link.sweep_ber): each BER point is one `lax.scan` step of the
perfect-sync link inside ONE compiled dispatch — the same BERs as the
per-batch `loopback_ber_bits` path point for point (same TX bits,
same AWGN keys; integer-identical error counts, pinned by
tests/test_link_fused.py), a fraction of the per-point host round
trips. The pre-batched per-frame path is kept as the `slow` oracle
lane, pinned EQUAL to the batched one.
"""

import numpy as np
import pytest

from ziria_tpu.phy import link
from ziria_tpu.phy.wifi.params import RATES
from ziria_tpu.utils.bits import bytes_to_bits

N_FRAMES = 16
N_BYTES = 100


def _psdus(seed: int) -> np.ndarray:
    return np.random.default_rng(seed).integers(
        0, 256, (N_FRAMES, N_BYTES)).astype(np.uint8)


def _ber_from_bits(got: np.ndarray, psdus: np.ndarray) -> float:
    want = np.stack([np.asarray(bytes_to_bits(p, xp=np)) for p in psdus])
    return float(np.mean(got != want))


def _ber_at(mbps: int, snr_db: float, seed: int) -> float:
    """One BER point through the sweep engine (a 1-point sweep: the
    jitted scan compiles once per rate and every (snr, seed) after
    that is a value, not a trace)."""
    errs = link.sweep_ber(_psdus(seed), (mbps,), (snr_db,), (seed,))
    return float(int(errs[0, 0, 0]) / (N_FRAMES * 8 * N_BYTES))




@pytest.mark.slow
@pytest.mark.parametrize("mbps,snr", [(24, 8.0), (6, 2.0)])
def test_perframe_oracle_lane_equals_batched(mbps, snr):
    """The pre-batched per-frame TX path (one encode_frame per frame)
    is the oracle the batched lane is judged against: same seeds, same
    AWGN keys, EQUAL BER — the frames are bit-identical, so the noisy
    captures and the decode are too. The sweep engine (the fast lane's
    carrier) must agree with both at this full waterfall geometry."""
    psdus = _psdus(7)
    got_b = link.loopback_ber_bits(psdus, mbps, snr, 7, batched_tx=True)
    got_f = link.loopback_ber_bits(psdus, mbps, snr, 7, batched_tx=False)
    np.testing.assert_array_equal(got_b, got_f)
    assert _ber_at(mbps, snr, 7) == _ber_from_bits(got_b, psdus)


def _q(x):
    from math import erfc, sqrt
    return 0.5 * erfc(x / sqrt(2.0))


def _uncoded_ber_theory(mbps: int, snr_db: float) -> float:
    """Theoretical uncoded channel-bit error rate on a data subcarrier.

    SNR here is total-signal/noise over the 64-sample symbol; energy
    rides on 52 of 64 subcarriers, so per-subcarrier Es/N0 = SNR*64/52.
    Gray-mapped M-QAM nearest-neighbor approximations (standard texts):
    BPSK Q(sqrt(2g)); QPSK Q(sqrt(g)) per bit; 16-QAM (3/4)Q(sqrt(g/5));
    64-QAM (7/12)Q(sqrt(g/21)) with g = Es/N0.
    """
    g = (10.0 ** (snr_db / 10.0)) * 64.0 / 52.0
    n_bpsc = RATES[mbps].n_bpsc
    if n_bpsc == 1:
        return _q(np.sqrt(2.0 * g))
    if n_bpsc == 2:
        return _q(np.sqrt(g))
    if n_bpsc == 4:
        return 0.75 * _q(np.sqrt(g / 5.0))
    return (7.0 / 12.0) * _q(np.sqrt(g / 21.0))


@pytest.mark.parametrize("mbps,snrs,clean_snr", [
    (6, [-4.0, -1.0, 2.0], 6.0),
    (24, [2.0, 5.0, 8.0], 14.0),
    (54, [10.0, 13.0, 16.0], 24.0),
])
def test_waterfall_monotone_and_clean_at_operating_snr(mbps, snrs,
                                                       clean_snr):
    bers = [_ber_at(mbps, s, seed=7) for s in snrs]
    # waterfall: strictly falling across the transition region (allow
    # equality only when both are already tiny)
    for lo, hi in zip(bers[1:], bers[:-1]):
        assert lo < hi or hi < 1e-3, (mbps, bers)
    # the lowest point must sit in the transition (noise is real)
    assert bers[0] > 1e-3, (mbps, bers)
    # error-free at the documented operating SNR
    assert _ber_at(mbps, clean_snr, seed=8) == 0.0, mbps


def test_denser_constellations_pay_more_at_equal_snr():
    snr = 8.0
    b6, b24, b54 = (_ber_at(m, snr, seed=9) for m in (6, 24, 54))
    assert b6 <= b24 <= b54, (b6, b24, b54)
    assert b54 > 1e-2        # 64-QAM 3/4 is far from clean at 8 dB
    assert b6 == 0.0         # BPSK 1/2 is comfortably clean at 8 dB


@pytest.mark.parametrize("mbps,snr", [(6, 3.0), (24, 11.0), (54, 20.0)])
def test_soft_decoding_beats_uncoded_theory(mbps, snr):
    # above the code's cutoff region the K=7 soft-decision decode must
    # show real coding gain: measured coded BER well under the
    # theoretical UNCODED channel-bit error rate at the same SNR.
    # (Below cutoff, convolutional codes legitimately do worse than
    # uncoded — the anchors sit where uncoded BER ~ 1e-2..5e-3,
    # measured crossover: 6 Mbps ~2.5 dB, 24 ~10 dB, 54 ~18.5 dB.)
    coded = _ber_at(mbps, snr, seed=10)
    uncoded = _uncoded_ber_theory(mbps, snr)
    assert uncoded > 1e-3, (mbps, snr, uncoded)   # in-transition check
    assert coded < 0.5 * uncoded, (mbps, snr, coded, uncoded)
