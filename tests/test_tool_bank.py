"""Shared scratch-dir resume bank for the TPU harvest tools
(tools/_bank.py): per-entry aging, platform/match gating, atomicity
side contracts. Review r5: the first bank implementation re-stamped
the whole file's age on every write, reviving stale entries — these
tests pin the per-entry rule."""

import importlib.util
import os
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bank(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "_bank", os.path.join(REPO, "tools", "_bank.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "SCRATCH", str(tmp_path))
    return mod


def test_entries_age_individually(tmp_path, monkeypatch):
    bank = _bank(tmp_path, monkeypatch)
    bank.save_entry("b", "tpu", "old", {"v": 1})
    # simulate a much later write of a second entry
    bank.save_entry("b", "tpu", "new", {"v": 2})
    now = time.time()
    out = bank.load_bank("b", "tpu", now=now + 7 * 3600)
    assert out == {}                       # both aged out
    out = bank.load_bank("b", "tpu", now=now)
    assert set(out) == {"old", "new"}
    # an old entry does NOT ride a fresh one's timestamp: age the
    # first artificially and confirm only it drops
    saved = bank.load_bank("b", "tpu", now=now)
    assert saved["old"]["_t"] <= saved["new"]["_t"]
    import json
    with open(os.path.join(str(tmp_path), "b.json")) as f:
        j = json.load(f)
    j["entries"]["old"]["_t"] = now - 7 * 3600
    with open(os.path.join(str(tmp_path), "b.json"), "w") as f:
        json.dump(j, f)
    out = bank.load_bank("b", "tpu", now=now)
    assert set(out) == {"new"}


def test_platform_and_match_gate(tmp_path, monkeypatch):
    bank = _bank(tmp_path, monkeypatch)
    bank.save_entry("b", "tpu", "k", {"v": 1}, match={"T": 8208})
    assert bank.load_bank("b", "cpu") == {}
    assert bank.load_bank("b", "tpu", match={"T": 1040}) == {}
    assert "k" in bank.load_bank("b", "tpu", match={"T": 8208})
    # a write under a different match discards the stale bank
    bank.save_entry("b", "tpu", "k2", {"v": 2}, match={"T": 1040})
    out = bank.load_bank("b", "tpu", match={"T": 1040})
    assert set(out) == {"k2"}


def test_strip_removes_bookkeeping(tmp_path, monkeypatch):
    bank = _bank(tmp_path, monkeypatch)
    bank.save_entry("b", "tpu", "k", {"v": 1})
    e = bank.load_bank("b", "tpu")["k"]
    assert bank.strip(e) == {"v": 1}


def test_corrupt_file_is_empty(tmp_path, monkeypatch):
    bank = _bank(tmp_path, monkeypatch)
    with open(os.path.join(str(tmp_path), "b.json"), "w") as f:
        f.write("not json")
    assert bank.load_bank("b", "tpu") == {}


def test_concurrent_writers_lose_no_entries(tmp_path, monkeypatch):
    # ADVICE r5 #4: save_entry's read-modify-write runs under the
    # bank's lock file, so concurrent bankers serialize — every
    # writer's entries survive. Without the lock this interleaving
    # (read, read, write, write) loses entries.
    from concurrent.futures import ThreadPoolExecutor

    bank = _bank(tmp_path, monkeypatch)

    def writer(w):
        for i in range(25):
            bank.save_entry("b", "tpu", f"w{w}_k{i}", {"v": i})

    with ThreadPoolExecutor(max_workers=4) as ex:
        list(ex.map(writer, range(4)))
    out = bank.load_bank("b", "tpu")
    assert len(out) == 4 * 25
    assert {f"w{w}_k{i}" for w in range(4) for i in range(25)} \
        == set(out)


def test_lock_file_does_not_pollute_bank(tmp_path, monkeypatch):
    # the sidecar .lock must never be read back as a bank
    bank = _bank(tmp_path, monkeypatch)
    bank.save_entry("b", "tpu", "k", {"v": 1})
    assert os.path.exists(os.path.join(str(tmp_path), "b.json.lock"))
    assert "k" in bank.load_bank("b", "tpu")
