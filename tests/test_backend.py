"""jit backend vs interpreter oracle — the flag-matrix equivalence tests.

The reference's key invariant is flag-independence of output (same golden
result with/without --vectorize etc., SURVEY.md §4). Here the matrix is
{interpreter} x {jit width=1, width=7, planned} — outputs must agree to
tolerance on every program."""

import numpy as np
import pytest

import ziria_tpu as z
from ziria_tpu.backend.execute import run_jit
from ziria_tpu.backend.lower import LowerError, lower
from ziria_tpu.interp.interp import run
from ziria_tpu.utils.diff import assert_stream_eq

WIDTHS = [1, 7, None]  # None = planner-chosen


def check(prog, xs, atol=0.0, rtol=0.0):
    """Run prog on oracle and jit backend at several widths; compare."""
    want = run(prog, list(xs)).out_array()
    for w in WIDTHS:
        got = run_jit(prog, np.asarray(xs), width=w)
        assert_stream_eq(np.asarray(got), want, atol=atol, rtol=rtol,
                         name=f"width={w}")


def test_scalar_map_chain():
    prog = z.pipe(z.zmap(lambda x: x + 1), z.zmap(lambda x: x * 3))
    check(prog, np.arange(40, dtype=np.int32))


def test_map_accum_fir():
    import jax.numpy as jnp
    taps = np.array([0.25, 0.5, 0.25], dtype=np.float32)

    def fir_step(state, x):
        state = jnp.roll(state, 1).at[0].set(x)
        return state, (state * taps).sum()

    prog = z.map_accum(fir_step, np.zeros(3, np.float32), name="fir3")
    check(prog, np.arange(64, dtype=np.float32), atol=1e-5)


def test_rate_change_pipeline():
    # 1->3 expander then 2->1 reducer: exercises the reshape algebra
    up = z.zmap(lambda x: x * np.arange(1, 4, dtype=np.int32),
                in_arity=1, out_arity=3)
    down = z.zmap(lambda v: v[0] - v[1], in_arity=2, out_arity=1)
    prog = z.pipe(up, down)
    check(prog, np.arange(30, dtype=np.int32))


def test_repeat_body_traced():
    # repeat { v <- takes 2; emits [v0+v1, v0-v1, v0*v1] }
    import jax.numpy as jnp
    body = z.let("v", z.takes(2),
                 z.emits(lambda env: jnp.stack(
                     [env["v"][0] + env["v"][1],
                      env["v"][0] - env["v"][1],
                      env["v"][0] * env["v"][1]]), 3))
    prog = z.repeat(body)
    check(prog, np.arange(28, dtype=np.int32))


def test_repeat_with_for_loop_traced():
    # repeat { v <- takes 4; for i in 0..3 { emit v[i]*2 } } — static For
    body = z.let("v", z.takes(4),
                 z.for_loop(4, z.emit1(
                     lambda env: env["v"][env["i"]] * 2), var="i"))
    prog = z.repeat(body)
    check(prog, np.arange(32, dtype=np.int32))


def test_mixed_stateful_stateless_chain():
    import jax.numpy as jnp

    def acc(s, x):
        s = s + x
        return s, s

    prog = z.pipe(z.zmap(lambda x: x * 2),
                  z.map_accum(acc, np.int32(0), name="cumsum"),
                  z.zmap(lambda x: x + 1))
    check(prog, np.arange(50, dtype=np.int32))


def test_chunked_block_map():
    # a 4-point "block transform" (here a reversal) as an arity-4 map
    prog = z.zmap(lambda v: v[::-1], in_arity=4, out_arity=4, name="rev4")
    check(prog, np.arange(40, dtype=np.int32))


def test_tail_full_iterations_not_dropped():
    # width 7 over 10 iterations: 1 bulk chunk + 3 width-1 steps
    prog = z.zmap(lambda x: x + 1)
    xs = np.arange(10, dtype=np.int32)
    got = run_jit(prog, xs, width=7)
    assert_stream_eq(got, xs + 1)


def test_partial_iteration_dropped_vectorized_eof():
    # 2->1 reducer over 9 items: 4 full iterations, 1 leftover item dropped
    prog = z.zmap(lambda v: v[0] + v[1], in_arity=2, out_arity=1)
    got = run_jit(prog, np.arange(9, dtype=np.int32), width=2)
    want = np.array([1, 5, 9, 13], dtype=np.int32)
    assert_stream_eq(got, want)


def test_dynamic_program_refused_with_guidance():
    prog = z.repeat(z.let("x", z.take,
                          z.branch(lambda env: env["x"] > 0,
                                   z.emit1(lambda env: env["x"]),
                                   z.emit1(lambda env: -env["x"]))))
    # structure lowers (cardinality is static: both branch arms emit 1),
    # but tracing the body hits the data-dependent bool and refuses with
    # guidance at first execution
    with pytest.raises(LowerError, match="data-dependent"):
        run_jit(prog, np.arange(8, dtype=np.int32), width=2)


def test_unlowerable_stage_refused():
    prog = z.while_loop(lambda env: True, z.emit1(1))
    with pytest.raises(LowerError):
        lower(prog, width=1)


def test_planner_picks_width():
    prog = z.zmap(lambda x: x)
    lw = lower(prog)
    assert lw.width >= 1024  # default target 8192 items, rate 1
    lw2 = lower(z.zmap(lambda v: v, in_arity=64, out_arity=64))
    assert lw2.take == lw2.width * 64


def test_sink_repeat_refused():
    prog = z.repeat(z.seq(z.take, z.ret(0)))
    with pytest.raises(LowerError, match="sink"):
        run_jit(prog, np.arange(8, dtype=np.int32), width=2)
