"""Seeded surface-language fuzzing: random `.zir` fun bodies run as
`map f` must agree exactly between the interpreter oracle, the fused
jit backend (which stages the SAME body: dynamic ifs -> selects, large
for-loops -> fori, data-dependent whiles -> while_loop), and the
--autolut rewrite where the function is LUT-able. This stresses the
staged-evaluator control-flow paths over a program space; failures
print the seed for replay."""

import numpy as np
import pytest

from ziria_tpu.backend.execute import run_jit
from ziria_tpu.frontend import compile_source
from ziria_tpu.interp.interp import run

N_CASES = 20


def _gen_expr(rng, depth, names):
    """A random int32 expression over `names` (always valid)."""
    if depth <= 0 or rng.random() < 0.3:
        if names and rng.random() < 0.7:
            return str(rng.choice(names))
        return str(int(rng.integers(-20, 21)))
    op = rng.choice(["+", "-", "*", "%", "&", "|", "^", ">>", "<<"])
    a = _gen_expr(rng, depth - 1, names)
    b = _gen_expr(rng, depth - 1, names)
    if op == "%":
        return f"(({a}) % {int(rng.integers(2, 40))})"
    if op in (">>", "<<"):
        return f"(({a}) {op} {int(rng.integers(0, 5))})"
    return f"(({a}) {op} ({b}))"


def _gen_stmts(rng, depth, names, indent, arrs=()):
    """Random statements mutating `acc`/locals; returns source lines.
    `arrs` lists in-scope arr[16] int32 names for indexed reads/writes
    (dynamic indices exercise gather/scatter staging)."""
    pad = "  " * indent
    lines = []
    for _ in range(int(rng.integers(1, 4))):
        kind = rng.choice(["assign", "if", "for", "while", "local",
                           "arr", "aset"])
        if kind == "arr" and depth > 0 and not arrs:
            nm = f"v{int(rng.integers(0, 1000))}"
            lines.append(f"{pad}var {nm} : arr[16] int32;")
            arrs = arrs + (nm,)
            continue
        if kind in ("arr", "aset") and arrs:
            a = rng.choice(arrs)
            idx = f"((({_gen_expr(rng, 1, names)}) % 16 + 16) % 16)"
            if kind == "aset":
                lines.append(f"{pad}{a}[{idx}] := "
                             f"{_gen_expr(rng, 1, names)};")
            else:
                lines.append(f"{pad}acc := acc + {a}[{idx}];")
            continue
        if kind in ("arr", "aset"):
            kind = "assign"
        if kind == "local" and depth > 0:
            nm = f"t{int(rng.integers(0, 1000))}"
            lines.append(f"{pad}var {nm} : int32 := "
                         f"{_gen_expr(rng, 2, names)};")
            names = names + [nm]
        elif kind == "assign":
            lines.append(f"{pad}acc := {_gen_expr(rng, 2, names)};")
        elif kind == "if" and depth > 0:
            cond = f"({_gen_expr(rng, 1, names)}) > " \
                   f"{int(rng.integers(-10, 10))}"
            lines.append(f"{pad}if {cond} then {{")
            lines += _gen_stmts(rng, depth - 1, names, indent + 1, arrs)
            lines.append(f"{pad}}} else {{")
            lines += _gen_stmts(rng, depth - 1, names, indent + 1, arrs)
            lines.append(f"{pad}}};")
        elif kind == "for" and depth > 0:
            # mix small (unrolled) and large (fori-staged) trip counts
            n = int(rng.choice([3, 7, 30, 40]))
            v = f"i{int(rng.integers(0, 1000))}"
            lines.append(f"{pad}for {v} in [0, {n}] {{")
            lines += _gen_stmts(rng, depth - 1, names + [v], indent + 1,
                                arrs)
            lines.append(f"{pad}}};")
        elif kind == "while" and depth > 0:
            # bounded data-dependent loop: guard counter always local
            g = f"g{int(rng.integers(0, 1000))}"
            lines.append(f"{pad}var {g} : int32 := "
                         f"(({_gen_expr(rng, 1, names)}) % 7 + 7) % 7;")
            lines.append(f"{pad}while ({g} > 0) {{")
            body = _gen_stmts(rng, depth - 1, names + [g], indent + 1,
                              arrs)
            lines += body
            lines.append(f"{pad}  {g} := {g} - 1")
            lines.append(f"{pad}}};")
        else:
            lines.append(f"{pad}acc := {_gen_expr(rng, 2, names)};")
    return lines


def _gen_program(seed):
    rng = np.random.default_rng(seed)
    body = "\n".join(_gen_stmts(rng, 2, ["x", "acc"], 1))
    src = f"""
fun f(x: int32) : int32 {{
  var acc : int32 := x;
{body};
  return acc
}}
let comp main = read[int32] >>> map f >>> write[int32]
"""
    n = int(rng.integers(8, 64))
    xs = rng.integers(-1000, 1000, n).astype(np.int32)
    return src, xs


@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_surface_backend_agreement(seed):
    src, xs = _gen_program(seed)
    prog = compile_source(src)
    want = np.asarray(run(prog.comp, list(xs)).out_array())
    got = np.asarray(run_jit(prog.comp, xs))
    np.testing.assert_array_equal(
        got, want, err_msg=f"seed {seed}: jit != interp\n{src}")


def test_fuzz_surface_bit_mixing_agreement():
    # bit (uint8) operands mixed with out-of-range constants and
    # comparisons — the C-promotion class where the backends silently
    # diverged (SIGNAL-length bug): random programs over bit arrays
    for seed in range(8):
        rng = np.random.default_rng(2000 + seed)
        terms = []
        for t in range(int(rng.integers(2, 6))):
            c = int(rng.integers(1, 1025))
            i = int(rng.integers(0, 8))
            if rng.random() < 0.5:
                terms.append(f"{c} * b[{i}]")
            else:
                cmp_v = int(rng.integers(-4, 300))
                terms.append(f"(if b[{i}] > {cmp_v} then {c} else "
                             f"(0 - {c}))")
        body = " + ".join(terms)
        src = f"""
fun f(b: arr[8] bit) : int32 {{
  return {body}
}}
let comp main = read[bit] >>> map f >>> write[int32]
"""
        xs = rng.integers(0, 2, 8 * 16).astype(np.uint8)
        prog = compile_source(src)
        want = np.asarray(run(prog.comp, list(xs)).out_array())
        got = np.asarray(run_jit(prog.comp, xs))
        np.testing.assert_array_equal(
            got, want, err_msg=f"seed {2000+seed}\n{src}")


def test_fuzz_surface_int8_autolut_agreement():
    # int8-domain variants additionally run the --autolut rewrite:
    # table gathers must equal both direct paths exactly
    for seed in range(8):
        rng = np.random.default_rng(1000 + seed)
        body = "\n".join(_gen_stmts(rng, 2, ["x", "acc"], 1))
        src = f"""
fun f(x: int8) : int8 {{
  var acc : int32 := int32(x);
{body};
  return int8(acc)
}}
let comp main = read[int8] >>> map f >>> write[int8]
"""
        xs = rng.integers(-128, 128, 40).astype(np.int8)
        direct = compile_source(src)
        want = np.asarray(run(direct.comp, list(xs)).out_array())
        got = np.asarray(run_jit(direct.comp, xs))
        np.testing.assert_array_equal(
            got, want, err_msg=f"seed {1000+seed}: jit != interp\n{src}")
        from ziria_tpu.core.autolut import autolut
        lutted = autolut(compile_source(src, autolut=True).comp)
        got_lut = np.asarray(run_jit(lutted, xs))
        np.testing.assert_array_equal(
            got_lut, want,
            err_msg=f"seed {1000+seed}: autolut != interp\n{src}")
