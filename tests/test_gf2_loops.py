"""GF(2) affine loop compression (frontend/gf2.py, "autolin").

LFSR-family loops — CRC registers, scramblers/descramblers — are true
bit recurrences the lane vectorizer rightly refuses, but they are
affine over GF(2), so K iterations collapse into one bit-matrix block
step. The contract is BIT-exactness with the interpreter oracle and
with the uncompressed staging (ZIRIA_NO_GF2_LOOPS=1), for static AND
traced trip counts, including remainder tails and range splits at
loop-variable comparisons. The reference kept these loops fast as C
scalar code (SURVEY.md §2.3 scramble/crc blocks); the TPU-idiomatic
equivalent is linear algebra, not a faster scalar loop.
"""

import os

import numpy as np
import pytest

import ziria_tpu.frontend.gf2 as G
from ziria_tpu.backend.execute import run_jit
from ziria_tpu.frontend import compile_source
from ziria_tpu.interp.interp import run

# LSB-first CRC-32 polynomial bits (0xEDB88320), as in examples
_POLY = ("{'0, '0, '0, '0, '0, '1, '0, '0, '1, '1, '0, '0, '0, '0, "
         "'0, '1, '0, '0, '0, '1, '1, '1, '0, '1, '1, '0, '1, '1, "
         "'0, '1, '1, '1}")


def _crc_src(n: int) -> str:
    return f"""
    let comp main = read[bit] >>> repeat {{
      (v : arr[{n}] bit) <- takes {n};
      var reg : arr[32] bit;
      do {{
        var poly : arr[32] bit := {_POLY};
        for t in [0, 32] {{ reg[t] := '1 }};
        for p in [0, {n}] {{
          let fb = reg[0] ^ v[p];
          reg[0, 31] := reg[1, 31];
          reg[31] := '0;
          if (fb == '1) then {{
            for t in [0, 32] {{ reg[t] := reg[t] ^ poly[t] }}
          }}
        }}
      }};
      emits reg
    }} >>> write[bit]
    """


def _both(src, xs):
    prog = compile_source(src)
    want = run(prog.comp, list(xs)).out_array()
    got = np.asarray(run_jit(prog.comp, xs))
    np.testing.assert_array_equal(np.asarray(want, np.uint8), got)
    return got


def _engaged(src, xs, expect: bool):
    hits = []
    orig = G.gf2_for

    def spy(*a):
        r = orig(*a)
        hits.append(r)
        return r

    G.gf2_for = spy
    try:
        _both(src, xs)
    finally:
        G.gf2_for = orig
    assert any(hits) == expect, hits


def _bits(n, seed=0):
    return np.random.RandomState(seed).randint(0, 2, n).astype(np.uint8)


def test_crc_register_compresses_exact():
    _engaged(_crc_src(4096), _bits(4096), True)


@pytest.mark.parametrize("n", [160, 257, 500, 4096 + 37])
def test_tail_remainders_exact(n):
    # lengths off the K=64 block grid exercise the staged tail
    _engaged(_crc_src(n), _bits(n, seed=n), True)


@pytest.mark.parametrize("n", [96, 127])
def test_short_loops_fall_back_exact(n):
    # below the 2K engagement floor: ordinary staging, still exact
    _engaged(_crc_src(n), _bits(n, seed=n), False)


def test_traced_count_with_range_split():
    # descrambler shape: data-dependent trip count (traced), a
    # loop-var comparison splitting the domain at p=16, and a stream
    # output written at stride 1 — the wifi_rx.zir descramble pattern
    src = """
    let comp main = read[bit] >>> repeat {
      (v : arr[2048] bit) <- takes 2048;
      var st : arr[7] bit;
      var fb : bit := '0;
      var clear : arr[2048] bit;
      var n : int32 := 1500;
      do {
        if (v[0] == '1) then { n := 1800 };
        for k in [0, 7] { st[k] := v[6 - k] };
        for p in [7, n] {
          fb := st[6] ^ st[3];
          st[1, 6] := st[0, 6];
          st[0] := fb;
          if (p >= 16) then { clear[p - 16] := v[p] ^ fb }
        }
      };
      emits clear[0, 1400]
    } >>> write[bit]
    """
    for seed in (0, 1):
        xs = _bits(2048, seed=seed)
        xs[0] = seed              # exercise both traced trip counts
        _engaged(src, xs, True)


def test_nonlinear_body_bails_exact():
    # AND of two state bits is quadratic over GF(2): must refuse and
    # fall back to ordinary staging, bit-exactly
    src = """
    let comp main = read[bit] >>> repeat {
      (v : arr[512] bit) <- takes 512;
      var reg : arr[8] bit;
      do {
        for p in [0, 512] {
          let fb = (reg[0] & reg[3]) ^ v[p];
          reg[0, 7] := reg[1, 7];
          reg[7] := fb
        }
      };
      emits reg
    } >>> write[bit]
    """
    _engaged(src, _bits(512, seed=3), False)


def test_non_bit_output_array_bails_exact():
    # code review r4: an int32 output stream has no GF(2) form — the
    # pass must refuse, not truncate values mod 2. Traced trip count
    # so there is no 2K engagement floor masking the hole.
    src = """
    let comp main = read[bit] >>> repeat {
      (v : arr[512] bit) <- takes 512;
      var out : arr[512] int32;
      var n : int32 := 400;
      do {
        if (v[0] == '1) then { n := 500 };
        for p in [0, n] { out[p] := 5 }
      };
      emits out[0, 400]
    } >>> write[int32]
    """
    _engaged(src, _bits(512, seed=11), False)


def test_non_bit_scalar_state_bails_exact():
    # code review r4: an int32 scalar written inside an LFSR loop is
    # not 1-bit state; trip count a multiple of K so no remainder tail
    # re-executes (and masks) the bad write-back
    src = """
    let comp main = read[bit] >>> repeat {
      (v : arr[512] bit) <- takes 512;
      var reg : arr[8] bit;
      var last : int32 := 0;
      do {
        for p in [0, 512] {
          let fb = reg[0] ^ v[p];
          reg[0, 7] := reg[1, 7];
          reg[7] := fb;
          last := 3
        }
      };
      emit last;
      emit last
    } >>> write[int32]
    """
    _engaged(src, _bits(512, seed=12), False)


def test_killswitch_ab_exact():
    src = _crc_src(1024)
    xs = _bits(1024, seed=9)
    prog = compile_source(src)
    want = np.asarray(run_jit(prog.comp, xs))
    os.environ["ZIRIA_NO_GF2_LOOPS"] = "1"
    try:
        got = np.asarray(run_jit(compile_source(src).comp, xs))
    finally:
        del os.environ["ZIRIA_NO_GF2_LOOPS"]
    np.testing.assert_array_equal(want, got)


def test_wifi_rx_zir_lfsr_loops_engage():
    # the flagship program's descramble AND FCS loops both compress
    # under the hybrid executor, and the decode stays bit-exact
    from ziria_tpu.backend import hybrid as HY
    from ziria_tpu.frontend import compile_file
    from ziria_tpu.phy import channel
    from ziria_tpu.utils.bits import bytes_to_bits

    srcf = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "wifi_rx.zir")
    psdu, xi = channel.impaired_capture(24, 60, seed=5, add_fcs=True)
    hits = []
    orig = G.gf2_for

    def spy(*a):
        r = orig(*a)
        hits.append(r)
        return r

    G.gf2_for = spy
    try:
        hyb = HY.hybridize(compile_file(srcf).comp)
        out = run(hyb, [p for p in xi]).out_array()
    finally:
        G.gf2_for = orig
    assert sum(hits) >= 2, hits   # descramble + FCS register
    want = np.asarray(bytes_to_bits(psdu))
    np.testing.assert_array_equal(np.asarray(out, np.uint8), want)


def test_wifi_rx_fxp_zir_lfsr_loops_engage():
    # the FIXED-POINT receiver's bit loops (descramble + FCS register)
    # compress the same way — the integer program gets the same
    # compiled-loop treatment as the float flagship
    from ziria_tpu.backend import hybrid as HY
    from ziria_tpu.frontend import compile_file
    from ziria_tpu.phy import channel
    from ziria_tpu.utils.bits import bytes_to_bits

    srcf = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "wifi_rx_fxp.zir")
    psdu, xi = channel.impaired_capture(24, 60, seed=6, add_fcs=True)
    hits = []
    orig = G.gf2_for

    def spy(*a):
        r = orig(*a)
        hits.append(r)
        return r

    G.gf2_for = spy
    try:
        hyb = HY.hybridize(compile_file(srcf, fxp_complex16=True).comp)
        out = run(hyb, [p for p in xi]).out_array()
    finally:
        G.gf2_for = orig
    assert sum(hits) >= 2, hits   # descramble + FCS register
    want = np.asarray(bytes_to_bits(psdu))
    np.testing.assert_array_equal(np.asarray(out, np.uint8), want)
