"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding paths
(mesh/pjit/shard_map) are exercised without TPU hardware; the driver's
separate dryrun validates the same thing. The environment exports
JAX_PLATFORMS=axon and the axon plugin wins over an env-var override, so
force the platform via jax.config before any backend initialization.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the tier-1 suite is COMPILE-
# dominated on CPU (per-geometry jits + interpret-mode Pallas), and
# the cache is keyed on the lowered program + compile flags, so repeat
# suite runs on one box reload executables instead of re-invoking XLA.
# Entries land in the gitignored .jax_cache/; harmless (no-op) where
# the jax build lacks cache support.
try:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:
    pass
