"""Long-capture packet search (phy/search.py): the STS metric over one
long stream, single-device vs sharded over the 8-device virtual mesh
with halo exchange — identical results, correct packet starts."""

import numpy as np
import pytest

from ziria_tpu.parallel.streampar import stream_mesh
from ziria_tpu.phy import search
from ziria_tpu.phy.wifi import tx


def _capture_with_frames(offsets, n_total, seed=0, mbps=12, n_bytes=40):
    rng = np.random.default_rng(seed)
    cap = rng.normal(scale=0.01, size=(n_total, 2)).astype(np.float32)
    frame = np.asarray(tx.encode_frame(
        rng.integers(0, 256, n_bytes).astype(np.uint8), mbps))
    for off in offsets:
        cap[off: off + len(frame)] += frame
    return cap


def test_find_packets_single_device():
    offsets = [1000, 5000, 9000]
    cap = _capture_with_frames(offsets, 12000)
    starts = search.find_packets(cap)
    assert len(starts) == len(offsets)
    for s, off in zip(starts, offsets):
        # the plateau begins just before the nominal offset (the lag-16
        # window correlates while partially overlapping the preamble)
        # and always within the short preamble (160 samples)
        assert off - 32 <= s <= off + 160, (s, off)


def test_find_packets_sharded_matches_host():
    offsets = [700, 4200, 7900, 11500]
    cap = _capture_with_frames(offsets, 8 * 1750 + 9)   # forces padding
    mesh = stream_mesh(8)
    host = search.detection_metric(cap)
    shard = search.detection_metric(cap, mesh=mesh)
    assert shard.shape == host.shape
    np.testing.assert_allclose(shard, host, rtol=2e-4, atol=2e-4)
    s1 = search.find_packets(cap)
    s2 = search.find_packets(cap, mesh=mesh)
    np.testing.assert_array_equal(s1, s2)
    assert len(s2) == len(offsets)


def test_noise_only_capture_finds_nothing():
    rng = np.random.default_rng(5)
    cap = rng.normal(scale=0.05, size=(4000, 2)).astype(np.float32)
    assert search.find_packets(cap).size == 0
    assert search.find_packets(cap, mesh=stream_mesh(8)).size == 0
