"""Long-capture packet search (phy/search.py): the STS metric over one
long stream, single-device vs sharded over the 8-device virtual mesh
with halo exchange — identical results, correct packet starts."""

import os

import numpy as np
import pytest

from ziria_tpu.parallel.streampar import stream_mesh
from ziria_tpu.phy import search
from ziria_tpu.phy.wifi import tx


def _capture_with_frames(offsets, n_total, seed=0, mbps=12, n_bytes=40):
    rng = np.random.default_rng(seed)
    cap = rng.normal(scale=0.01, size=(n_total, 2)).astype(np.float32)
    frame = np.asarray(tx.encode_frame(
        rng.integers(0, 256, n_bytes).astype(np.uint8), mbps))
    for off in offsets:
        cap[off: off + len(frame)] += frame
    return cap


def test_find_packets_single_device():
    offsets = [1000, 5000, 9000]
    cap = _capture_with_frames(offsets, 12000)
    starts = search.find_packets(cap)
    assert len(starts) == len(offsets)
    for s, off in zip(starts, offsets):
        # the plateau begins just before the nominal offset (the lag-16
        # window correlates while partially overlapping the preamble)
        # and always within the short preamble (160 samples)
        assert off - 32 <= s <= off + 160, (s, off)


def test_find_packets_sharded_matches_host():
    offsets = [700, 4200, 7900, 11500]
    cap = _capture_with_frames(offsets, 8 * 1750 + 9)   # forces padding
    mesh = stream_mesh(8)
    host = search.detection_metric(cap)
    shard = search.detection_metric(cap, mesh=mesh)
    assert shard.shape == host.shape
    np.testing.assert_allclose(shard, host, rtol=2e-4, atol=2e-4)
    s1 = search.find_packets(cap)
    s2 = search.find_packets(cap, mesh=mesh)
    np.testing.assert_array_equal(s1, s2)
    assert len(s2) == len(offsets)


def test_noise_only_capture_finds_nothing():
    rng = np.random.default_rng(5)
    cap = rng.normal(scale=0.05, size=(4000, 2)).astype(np.float32)
    assert search.find_packets(cap).size == 0
    assert search.find_packets(cap, mesh=stream_mesh(8)).size == 0


def test_scan_and_decode_batch():
    """sp-sharded search + frame-batched decode: every packet in a
    long capture comes back as validated payload bits; a corrupted
    packet is dropped by the in-language FCS; decodes ride batched
    device calls (backend/framebatch)."""
    from ziria_tpu.phy import channel
    from ziria_tpu.utils.bits import bytes_to_bits

    rng = np.random.default_rng(3)
    caps, psdus = [], []
    for k, (mbps, nb) in enumerate([(12, 40), (24, 60), (6, 30)]):
        psdu, xi = channel.impaired_capture(
            mbps, nb, seed=700 + k, cfo=0.001, pre=0, post=0,
            noise=0.02, add_fcs=True)
        caps.append(np.asarray(xi))
        psdus.append(psdu)

    gap = lambda n: np.clip(np.round(rng.normal(
        scale=20.0, size=(n, 2))), -32768, 32767).astype(np.int16)
    stream = [gap(900)]
    offsets = []
    pos = 900
    for xi in caps:
        offsets.append(pos)
        stream.append(xi)
        pos += len(xi)
        stream.append(gap(900))
        pos += 900
    capture = np.concatenate(stream, axis=0)

    got = search.scan_and_decode(capture, mesh=stream_mesh(8))
    assert len(got) == 3, [g[0] for g in got]
    for (s, bits), off, psdu in zip(got, offsets, psdus):
        assert off - 64 <= s <= off + 160, (s, off)
        np.testing.assert_array_equal(bits,
                                      np.asarray(bytes_to_bits(psdu)))

    # corrupt the middle packet's DATA region: still found, but its
    # decode is FCS-rejected, so only packets 1 and 3 return
    capture2 = np.array(capture)
    d = offsets[1] + 500
    capture2[d:d + 16] = -capture2[d:d + 16]
    got2 = search.scan_and_decode(capture2, mesh=stream_mesh(8))
    assert len(got2) == 2
    np.testing.assert_array_equal(
        got2[0][1], np.asarray(bytes_to_bits(psdus[0])))
    np.testing.assert_array_equal(
        got2[1][1], np.asarray(bytes_to_bits(psdus[2])))


def test_cli_scan(tmp_path):
    """--scan end-to-end: capture file in, concatenated validated
    payloads out; --sp shards the metric."""
    from ziria_tpu.phy import channel
    from ziria_tpu.runtime.buffers import (StreamSpec, read_stream,
                                           write_stream)
    from ziria_tpu.runtime.cli import main as cli_main
    from ziria_tpu.utils.bits import bytes_to_bits

    rng = np.random.default_rng(9)
    psdus, parts = [], []
    gap = lambda n: np.clip(np.round(rng.normal(
        scale=20.0, size=(n, 2))), -32768, 32767).astype(np.int16)
    parts.append(gap(800))
    for k, (mbps, nb) in enumerate([(24, 50), (12, 40)]):
        psdu, xi = channel.impaired_capture(
            mbps, nb, seed=800 + k, cfo=0.001, pre=0, post=0,
            noise=0.02, add_fcs=True)
        psdus.append(psdu)
        parts.append(np.asarray(xi))
        parts.append(gap(800))
    cap = np.concatenate(parts, axis=0)

    inf = tmp_path / "cap.bin"
    outf = tmp_path / "pay.bin"
    write_stream(StreamSpec(ty="complex16", path=str(inf), mode="bin"),
                 cap)
    rc = cli_main([
        "--scan", "--sp=8", "--input=file",
        f"--input-file-name={inf}", "--input-file-mode=bin",
        "--output=file", f"--output-file-name={outf}",
        "--output-file-mode=bin"])
    assert rc == 0
    got = read_stream(StreamSpec(ty="bit", path=str(outf), mode="bin"))
    want = np.concatenate([np.asarray(bytes_to_bits(p))
                           for p in psdus])
    np.testing.assert_array_equal(got[: want.shape[0]], want)
    # nothing beyond the two payloads except bin-mode byte padding
    assert got.shape[0] - want.shape[0] < 8
    assert not np.any(got[want.shape[0]:])


def test_cli_scan_validation(tmp_path):
    from ziria_tpu.runtime.cli import main as cli_main
    with pytest.raises(SystemExit, match="in-language receiver"):
        cli_main(["--scan", "--src=examples/scrambler.zir"])
    with pytest.raises(SystemExit, match="needs --input=file"):
        cli_main(["--scan", "--input=dummy"])


def test_cli_scan_noise_only(tmp_path):
    # a capture with no packets writes an EMPTY bit stream, exit 0
    from ziria_tpu.runtime.buffers import (StreamSpec, read_stream,
                                           write_stream)
    from ziria_tpu.runtime.cli import main as cli_main

    rng = np.random.default_rng(13)
    cap = np.clip(np.round(rng.normal(scale=20.0, size=(4000, 2))),
                  -32768, 32767).astype(np.int16)
    inf = tmp_path / "noise.bin"
    outf = tmp_path / "empty.bin"
    write_stream(StreamSpec(ty="complex16", path=str(inf), mode="bin"),
                 cap)
    rc = cli_main(["--scan", "--input=file",
                   f"--input-file-name={inf}", "--input-file-mode=bin",
                   "--output=file", f"--output-file-name={outf}",
                   "--output-file-mode=bin"])
    assert rc == 0
    got = read_stream(StreamSpec(ty="bit", path=str(outf), mode="bin"))
    assert got.size == 0


def test_scan_and_decode_with_fxp_receiver():
    """The scan's receiver is pluggable, and the FIXED-POINT
    in-language receiver slots straight in: sp-sharded packet search,
    then every hit decoded through the all-integer chain with batched
    chunk steps."""
    from ziria_tpu.backend.hybrid import hybridize
    from ziria_tpu.frontend import compile_file
    from ziria_tpu.phy import channel
    from ziria_tpu.utils.bits import bytes_to_bits

    rng = np.random.default_rng(4)
    caps, psdus = [], []
    for k, (mbps, nb) in enumerate([(24, 50), (54, 70)]):
        psdu, xi = channel.impaired_capture(
            mbps, nb, seed=720 + k, cfo=0.001, pre=0, post=0,
            noise=0.02, add_fcs=True)
        caps.append(np.asarray(xi))
        psdus.append(psdu)

    gap = lambda n: np.clip(np.round(rng.normal(
        scale=20.0, size=(n, 2))), -32768, 32767).astype(np.int16)
    stream, pos, offsets = [gap(900)], 900, []
    for xi in caps:
        offsets.append(pos)
        stream.append(xi)
        pos += len(xi)
        stream.append(gap(900))
        pos += 900
    capture = np.concatenate(stream, axis=0)

    src = os.path.join(os.path.dirname(__file__), "..", "examples",
                       "wifi_rx_fxp.zir")
    hyb = hybridize(compile_file(src, fxp_complex16=True).comp)
    got = search.scan_and_decode(capture, mesh=stream_mesh(8),
                                 comp=hyb)
    assert len(got) == 2, [g[0] for g in got]
    for (s, bits), off, psdu in zip(got, offsets, psdus):
        assert off - 64 <= s <= off + 160, (s, off)
        np.testing.assert_array_equal(
            bits, np.asarray(bytes_to_bits(psdu)))
