"""The ONE-dispatch fused loopback link (phy/link loopback_fused path)
and the device-resident BER sweep engine (link.sweep_ber[_sharded]):

- fused vs staged bit-identity, lane for lane, across all 8 rates with
  mixed lengths, finite SNR, per-lane CFO + delay, a swamped
  (no-detect) lane, and ``check_fcs=True`` — the staged 5-dispatch
  path is the oracle (itself pinned against the per-frame loop by
  test_tx_batched.py);
- the traced classify tree (`rx.classify_acquire_graph`) against the
  host `_classify_acquire` over an exhaustive branch grid — no-detect,
  short capture, flipped-parity SIGNAL, unknown rate, truncated
  capture, decodable — so the failure classifications the loopback
  cannot deterministically synthesize are pinned branch for branch;
- the batched masked CRC against the per-lane host `check_crc32`
  (boolean-identical, corruption detected), plus the dispatch-count
  pin that `check_fcs=True` costs ONE extra dispatch, not one per
  lane;
- `sweep_ber` == python-loop-of-batches (integer-identical error
  counts) at <= 1 dispatch vs >= 3 per point through the loop (and
  >= 5 per point through the staged full link), and
  `sweep_ber_sharded` == `sweep_ber` over the suite's 8-virtual-device
  dp mesh.

Budget discipline: ONE module fixture pays the fused-graph compile at
the suite-shared 8-lane / 8-symbol-bucket geometry (same LENS/MBPS as
test_tx_batched.py so the staged-side jits are shared), and the sweep
tests use small frame geometries.
"""

import numpy as np
import pytest

from ziria_tpu.phy import link
from ziria_tpu.phy.wifi import rx
from ziria_tpu.phy.wifi.params import RATES
from ziria_tpu.utils import dispatch
from ziria_tpu.utils.bits import np_bytes_to_bits

LENS = (16, 10, 16, 5, 16, 12, 9, 16)
MBPS = tuple(sorted(RATES))
CFO = tuple((-1) ** k * 1e-4 * (k + 1) for k in range(8))
DELAY = tuple(20 + 17 * k for k in range(8))
# real AWGN with one swamped lane: the fused graph must classify the
# no-detect lane exactly as the staged path does
SNRS = (25.0, 30.0, -25.0, 28.0, 25.0, 30.0, 27.0, 26.0)
SEED = 20260803


@pytest.fixture(scope="module")
def corpus():
    """PSDUs + one fused and one staged loopback pass (finite per-lane
    SNR + CFO + delay, FCS appended AND checked), each under a
    dispatch counter."""
    rng = np.random.default_rng(SEED)
    psdus = [rng.integers(0, 256, n).astype(np.uint8) for n in LENS]
    kw = dict(snr_db=SNRS, cfo=CFO, delay=DELAY, seed=11,
              add_fcs=True, check_fcs=True)
    with dispatch.count_dispatches() as d_fu:
        got_fu = link.loopback_many(psdus, MBPS, fused=True, **kw)
    with dispatch.count_dispatches() as d_st:
        got_st = link.loopback_many(psdus, MBPS, fused=False, **kw)
    return psdus, got_fu, got_st, d_fu, d_st


def _same_result(a, b) -> bool:
    return (a.ok == b.ok and a.rate_mbps == b.rate_mbps
            and a.length_bytes == b.length_bytes
            and np.array_equal(a.psdu_bits, b.psdu_bits)
            and a.crc_ok == b.crc_ok)


def test_fused_equals_staged_lane_for_lane(corpus):
    # the acceptance contract: RxResults bit-identical lane for lane —
    # all 8 rates, mixed lengths, finite SNR, CFO + delay, the
    # no-detect lane, and CRC flags
    psdus, got_fu, got_st, _d, _d2 = corpus
    assert len(got_fu) == len(psdus)
    for a, b in zip(got_fu, got_st):
        assert _same_result(a, b)
    assert not got_fu[2].ok            # the swamped lane really failed
    for k in (0, 1, 3, 4, 5, 6, 7):    # the healthy lanes decode clean
        assert got_fu[k].ok and got_fu[k].crc_ok
        assert np.array_equal(
            got_fu[k].psdu_bits[:8 * LENS[k]],
            np_bytes_to_bits(psdus[k]))


def test_fused_is_one_dispatch_even_with_fcs(corpus):
    # the tentpole number: the whole mixed-rate multi-SNR batch —
    # including the CRC check — is ONE instrumented device dispatch;
    # the staged oracle pays ~5 plus ONE batched CRC dispatch (the
    # satellite pin: not one check_crc32 dispatch per lane)
    _psdus, _gf, _gs, d_fu, d_st = corpus
    assert d_fu.total <= 1, dict(d_fu.counts)
    assert d_fu.counts["link.fused"] == 1
    assert d_st.total >= 5, dict(d_st.counts)
    assert d_st.counts["rx.crc_many"] == 1, dict(d_st.counts)
    # per-site wall times ride the same counter now (satellite 2)
    assert d_st.times["rx.decode_mixed"] > 0.0
    assert d_fu.times["link.fused"] > 0.0


def test_fused_noise_free_and_compile_reuse(corpus):
    # noise-free channel through the ALREADY-compiled fused geometry:
    # zero fresh fused compiles (lru + jit reuse), still identical to
    # staged, and a 7-lane batch pads back to the same 8-row graph
    psdus, _gf, _gs, _d, _d2 = corpus
    # add_fcs keeps the fixture's (bit bucket, symbol bucket) geometry
    # so the lru-cached fused jit must be a pure reuse
    kw = dict(snr_db=np.inf, cfo=CFO, delay=DELAY, seed=3,
              add_fcs=True)
    with dispatch.cache_growth(link._jit_fused_link) as g:
        got_fu = link.loopback_many(psdus, MBPS, fused=True, **kw)
        got_fu7 = link.loopback_many(
            psdus[:7], MBPS[:7], fused=True, add_fcs=True,
            snr_db=np.inf, cfo=CFO[:7], delay=DELAY[:7], seed=3)
    assert g.total == 0, "fused geometry re-compiled"
    got_st = link.loopback_many(psdus, MBPS, fused=False, **kw)
    for a, b in zip(got_fu, got_st):
        assert _same_result(a, b)
    for a, b in zip(got_fu7, got_fu[:7]):
        assert _same_result(a, b)


def test_classify_graph_matches_host_tree_every_branch():
    """The traced decision tree == the host tree, branch for branch:
    no-detect, short capture, flipped-parity SIGNAL, unknown RATE
    code, truncated capture, decodable — the failure classifications a
    closed loopback cannot deterministically synthesize end-to-end are
    pinned here at the decision-tree seam (the fused graph consumes
    exactly these outputs)."""
    import itertools

    cases = list(itertools.product(
        (False, True),                  # found
        (0, 200, 400, 1040, 4096),      # avail
        (0b1101, 0b0011, 0b0000, 0b1110, 15),   # rate_bits (2 invalid)
        (0, 5, 16, 400, 4095),          # length_bytes
        (False, True),                  # parity_ok
    ))
    found, avail, rb, ln, pk = (np.asarray(v) for v in zip(*cases))
    st_g, mbps_g, len_g, nsym_g = (
        np.asarray(x) for x in rx.classify_acquire_graph(
            found, avail, rb, ln, pk))
    from ziria_tpu.phy.wifi.params import n_symbols

    statuses = set()
    for k, (f, av, r, l, p) in enumerate(cases):
        res, ok = rx._classify_acquire(f, av, r, l, p)
        if ok is not None:
            want = (rx.ACQ_DECODABLE, ok[0], l, ok[1])
        elif res.rate_mbps:
            want = (rx.ACQ_TRUNCATED, res.rate_mbps, res.length_bytes,
                    n_symbols(res.length_bytes, RATES[res.rate_mbps]))
        else:
            want = (rx.ACQ_FAIL, 0, 0, 0)
        got = (int(st_g[k]), int(mbps_g[k]), int(len_g[k]),
               int(nsym_g[k]) if want[0] != rx.ACQ_FAIL else 0)
        assert got == want, (cases[k], got, want)
        statuses.add(got[0])
    assert statuses == {rx.ACQ_FAIL, rx.ACQ_TRUNCATED,
                        rx.ACQ_DECODABLE}   # every branch exercised


def test_masked_crc_matches_host_crc():
    import jax.numpy as jnp

    from ziria_tpu.ops import crc

    rng = np.random.default_rng(5)
    for nb in (5, 16, 64):
        bits = rng.integers(0, 2, 8 * nb).astype(np.uint8)
        full = np.asarray(crc.append_crc32(bits))
        pad = np.zeros(1024, np.uint8)
        pad[:full.shape[0]] = full
        good = bool(np.asarray(crc.check_crc32_masked(
            jnp.asarray(pad), jnp.int32(full.shape[0]))))
        assert good == bool(np.asarray(crc.check_crc32(full))) is True
        # a single flipped bit anywhere in the body must fail, and a
        # flipped PAD bit must NOT (the mask is the contract)
        bad = pad.copy()
        bad[int(rng.integers(0, full.shape[0]))] ^= 1
        assert not bool(np.asarray(crc.check_crc32_masked(
            jnp.asarray(bad), jnp.int32(full.shape[0]))))
        padbit = pad.copy()
        padbit[full.shape[0]] ^= 1
        assert bool(np.asarray(crc.check_crc32_masked(
            jnp.asarray(padbit), jnp.int32(full.shape[0]))))
    # a stream too short to hold the FCS at all (a noise-corrupted
    # SIGNAL claiming a 1..3-byte PSDU) must report False, never a
    # garbage True from an underflowed byte count
    ones = np.ones(1024, np.uint8)
    for short in (0, 8, 24):
        assert not bool(np.asarray(crc.check_crc32_masked(
            jnp.asarray(ones), jnp.int32(short))))


B_SWEEP, NB_SWEEP = 8, 24
SWEEP_RATES = (6, 54)


@pytest.fixture(scope="module")
def sweep_corpus():
    rng = np.random.default_rng(9)
    psdus = rng.integers(0, 256, (B_SWEEP, NB_SWEEP)).astype(np.uint8)
    # -2 dB sits in BPSK 1/2's transition even at short frames; 8 dB
    # is comfortably clean (the waterfall suite pins the full curve)
    snrs, seeds = (-2.0, 8.0), (7,)
    with dispatch.count_dispatches() as d_sw:
        errs = link.sweep_ber(psdus, SWEEP_RATES, snrs, seeds)
    return psdus, snrs, seeds, errs, d_sw


def test_sweep_ber_equals_perbatch_loop(sweep_corpus):
    # integer-identical error counts vs the python loop of per-batch
    # points, and the dispatch pin: ONE scan dispatch vs >= 3 per
    # point through the loop (the staged full link would pay >= 5 per
    # point — pinned by test_fused_is_one_dispatch_even_with_fcs's
    # staged counter)
    psdus, snrs, seeds, errs, d_sw = sweep_corpus
    want = np.stack([np_bytes_to_bits(p) for p in psdus])
    n_points = len(SWEEP_RATES) * len(snrs) * len(seeds)
    with dispatch.count_dispatches() as d_lp:
        for ri, m in enumerate(SWEEP_RATES):
            for si, s in enumerate(snrs):
                for ki, sd in enumerate(seeds):
                    got = link.loopback_ber_bits(psdus, m, s, sd)
                    assert int(np.sum(got != want)) == \
                        int(errs[ri, si, ki]), (m, s, sd)
    assert d_sw.total <= 1, dict(d_sw.counts)
    assert d_sw.counts["link.sweep"] == 1
    assert d_lp.total >= 3 * n_points, dict(d_lp.counts)
    # the transition SNR really errors and the clean one is clean for
    # the BPSK lane (the sweep is measuring, not echoing zeros)
    assert errs[0, 0, 0] > 0 and errs[0, 1, 0] == 0


def test_sweep_ber_sharded_identical_on_dp_mesh(sweep_corpus):
    # the suite runs with 8 virtual devices (conftest): the dp-sharded
    # sweep shards B_SWEEP lanes over frame_mesh() and must return the
    # SAME integers (exact int sums — order-free); the real-chip pin
    # is __graft_entry__.dryrun_multichip
    import jax

    psdus, snrs, seeds, errs, _d = sweep_corpus
    from ziria_tpu.parallel.batch import frame_mesh

    mesh = frame_mesh()
    assert mesh.devices.size == len(jax.devices())
    errs_sh = link.sweep_ber_sharded(psdus, SWEEP_RATES, snrs, seeds,
                                     mesh=mesh)
    np.testing.assert_array_equal(errs, errs_sh)


def test_fused_link_env_knob(monkeypatch):
    # the CLI's scoped-env pattern: default ON, ZIRIA_FUSED_LINK=0
    # forces the staged oracle, an explicit argument wins over the env
    monkeypatch.delenv("ZIRIA_FUSED_LINK", raising=False)
    assert link.fused_link_enabled(None)
    monkeypatch.setenv("ZIRIA_FUSED_LINK", "0")
    assert not link.fused_link_enabled(None)
    assert link.fused_link_enabled(True)
    monkeypatch.setenv("ZIRIA_FUSED_LINK", "1")
    assert link.fused_link_enabled(None)
    assert not link.fused_link_enabled(False)
