"""Durable serving (ISSUE 14): crash-safe journaling, automatic fleet
snapshots, elastic failover, and recovery semantics.

Families:

- JOURNAL/SNAPSHOT mechanics (jax-free): CRC-framed records, segment
  rotation + reopen-seals, the TIER-1 torn-tail pin (a truncated last
  record is dropped cleanly, never corrupts replay), mid-segment
  resync, atomic snapshot write/load/prune/fallback, the io_torn /
  io_enospc chaos seams, checkpoint CRC integrity + legacy blobs.
- STUB recovery: crash -> ``ServeRuntime.recover`` reconstructs the
  session table exactly; elastic repack onto fewer lanes; journal-only
  recovery dedupes re-delivery.
- FLEET recovery at the suite-shared streaming geometry (chunk 4096 /
  window 1024 / K=8, S=8 — the compile keys the other serving suites
  already pay for): crash mid-stream -> recover -> resubmit-from-acked
  emits BIT-IDENTICALLY to the uninterrupted oracle, with the
  ≤ 2-dispatches-per-chunk-step budget held under
  ``dispatch.no_recompile`` after recovery, and elastic recovery onto
  a 1-lane fleet still completing every session.
- the `slow` SIGKILL subprocess round (tools/soak.py): real process
  death mid-chunk-step, recovery in the parent, bit-identity.
"""

import io
import os
from types import SimpleNamespace

import numpy as np
import pytest

from ziria_tpu.runtime import durability, resilience, serve
from ziria_tpu.utils import dispatch, faults, telemetry

N_BYTES = 12
CHUNK, FRAME_LEN, K, S = 4096, 1024, 8, 8
GEO = dict(chunk_len=CHUNK, frame_len=FRAME_LEN,
           max_frames_per_chunk=K, check_fcs=True)


# ------------------------------------------------- journal mechanics


def test_journal_roundtrip_rotation_reopen_prune(tmp_path):
    jd = str(tmp_path / "j")
    j = durability.Journal(jd, segment_records=3)
    for i in range(7):
        assert j.append({"ev": "t", "i": i}) == i + 1
    recs, st = durability.replay(jd)
    assert [r["i"] for r in recs] == list(range(7))
    assert [r["q"] for r in recs] == list(range(1, 8))
    assert st.dropped == 0 and st.segments == 3
    assert sorted(os.listdir(jd)) == [
        "wal-000000000001.log", "wal-000000000004.log",
        "wal-000000000007.open"]
    # reopen (the recovered process): seals the leftover .open,
    # resumes the sequence, never rewrites history
    j2 = durability.Journal(jd, segment_records=3)
    assert j2.seq == 7
    assert not [n for n in os.listdir(jd) if n.endswith(".open")]
    j2.append({"ev": "t", "i": 7})
    recs, _ = durability.replay(jd, after_seq=5)
    assert [r["i"] for r in recs] == [5, 6, 7]
    # prune: segments fully covered by a snapshot watermark vanish,
    # replay past the watermark is unaffected
    j2.prune(6)
    assert "wal-000000000001.log" not in os.listdir(jd)
    recs, _ = durability.replay(jd, after_seq=6)
    assert [r["i"] for r in recs] == [6, 7]


def test_torn_journal_tail_dropped_cleanly(tmp_path):
    """THE tier-1 satellite pin: a record truncated mid-write (crash,
    torn disk write) is dropped cleanly — every record before it
    replays, nothing corrupts, and appends after a torn MID-segment
    record survive via the resync scan."""
    jd = str(tmp_path / "j")
    j = durability.Journal(jd, segment_records=100)
    for i in range(3):
        j.append({"ev": "t", "i": i})
    j.close()
    path = os.path.join(jd, "wal-000000000001.log")
    with open(path, "rb") as f:
        data = f.read()
    third = len(data) // 3          # records are equal-sized here
    # truncate the LAST record at EVERY byte boundary inside it:
    # replay must always yield exactly the first two records
    for cut in range(2 * third + 1, len(data)):
        td = str(tmp_path / f"cut-{cut}")
        jt = durability.Journal(td)     # fresh dir for the fragment
        jt.close()
        with open(os.path.join(td, "wal-000000000001.log"),
                  "wb") as f:
            f.write(data[:cut])
        recs, st = durability.replay(td)
        assert [r["i"] for r in recs] == [0, 1], (cut, recs)
        assert st.dropped == 1
    # a recovering writer TRUNCATES the torn tail away when it seals
    with open(path, "rb+") as f:
        f.truncate(len(data) - 4)
    os.replace(path, os.path.join(jd, "wal-000000000001.open"))
    j2 = durability.Journal(jd)
    assert j2.seq == 2              # the torn record never existed
    recs, st = durability.replay(jd)
    assert [r["i"] for r in recs] == [0, 1] and st.dropped == 0
    # torn MID-segment (injected io_torn): neighbours both survive
    jd2 = str(tmp_path / "j2")
    j = durability.Journal(jd2, segment_records=100)
    j.append({"k": 1})
    with faults.inject(faults.FaultSpec("journal.append", "io_torn",
                                        calls=(0,), fraction=0.5)):
        j.append({"k": "torn"})
    j.append({"k": 2})
    recs, st = durability.replay(jd2)
    assert [r["k"] for r in recs] == [1, 2]
    assert st.dropped >= 1


def test_io_fault_kinds_deterministic(tmp_path):
    data = b"x" * 100
    with faults.inject(faults.FaultSpec("io.site", "io_torn",
                                        every=1, fraction=0.25)):
        got = faults.io_fault("io.site", data)
    assert len(got) == 75
    with faults.inject(faults.FaultSpec("io.site", "io_enospc",
                                        calls=(1,))):
        assert faults.io_fault("io.site", data) == data
        with pytest.raises(OSError, match="No space left"):
            faults.io_fault("io.site", data)
    # unknown kinds still rejected at the grammar
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.FaultPlan([faults.FaultSpec("x", "io_nope", every=1)])
    # the chaos grammar accepts the new kinds
    specs, seed = faults.parse_chaos_spec(
        "seed=3;journal.append:io_torn:every=2,frac=0.5;"
        "snapshot.lane:io_enospc:calls=0")
    assert {s.kind for s in specs} == {"io_torn", "io_enospc"}


def test_snapshot_atomic_write_load_prune_fallback(tmp_path):
    sd = str(tmp_path / "snaps")
    for step in (1, 2, 3):
        p = durability.write_snapshot(
            sd, step, {0: b"lane-%d" % step, 2: b"two"},
            {"jseq": step * 10}, keep=2)
        assert os.path.basename(p) == durability.snapshot_name(step)
    names = sorted(n for n in os.listdir(sd) if n.startswith("snap"))
    assert names == ["snap-0000000002", "snap-0000000003"]
    # a crashed writer's temp dir is invisible and harmless
    os.makedirs(os.path.join(sd, ".tmp-snap-0000000007.1"))
    got = durability.load_snapshot(sd)
    assert (got.step, got.lanes[0], got.lanes[2],
            got.body["jseq"]) == (3, b"lane-3", b"two", 30)
    # corrupting the newest manifest falls back to the previous
    with open(os.path.join(sd, "snap-0000000003", "meta.json"),
              "r+b") as f:
        f.seek(5)
        f.write(b"ZZ")
    got = durability.load_snapshot(sd)
    assert got.step == 2 and got.lanes[0] == b"lane-2"
    # an ENOSPC mid-snapshot leaves the previous snapshot untouched
    # (the failed write cleans its own temp immediately)
    with faults.inject(faults.FaultSpec("snapshot.lane", "io_enospc",
                                        every=1)):
        with pytest.raises(OSError):
            durability.write_snapshot(sd, 9, {0: b"x"}, {})
    assert durability.load_snapshot(sd).step == 2
    assert not [n for n in os.listdir(sd)
                if n.startswith(f".tmp-snap-0000000009")]
    # stale temps from CRASHED writers are collected by the next
    # successful snapshot
    durability.write_snapshot(sd, 4, {0: b"lane-4"}, {"jseq": 40})
    assert not [n for n in os.listdir(sd) if n.startswith(".tmp-")]
    assert durability.load_snapshot(sd).step == 4


def test_checkpoint_crc_integrity_and_legacy_load():
    carry = SimpleNamespace(
        tail=np.arange(10, dtype=np.float32).reshape(5, 2),
        offset=4096, emitted=3, watermark=4000)
    blob = resilience.checkpoint_carry(
        carry, seen=(4100,), geometry={"chunk_len": 4096},
        state={"quarantined": True})
    st = resilience.restore_carry(blob)
    assert st.offset == 4096 and st.state["quarantined"]
    # flip one payload byte: the CRC field must refuse the blob
    bad = bytearray(blob)
    idx = bad.find(np.float32(7.0).tobytes())
    assert idx > 0
    bad[idx] ^= 0x40
    with pytest.raises(resilience.CarryCheckpointError,
                       match="integrity|unreadable"):
        resilience.restore_carry(bytes(bad))
    # a pre-integrity blob (no crc field) still loads — counted
    z = dict(np.load(io.BytesIO(blob), allow_pickle=False))
    z.pop("crc")
    buf = io.BytesIO()
    np.savez(buf, **z)
    reg = telemetry.MetricsRegistry()
    with telemetry.collect(reg):
        st = resilience.restore_carry(buf.getvalue())
    assert st.offset == 4096
    assert "resilience_checkpoint_legacy" in reg.exposition()


def test_save_checkpoint_is_atomic(tmp_path):
    carry = SimpleNamespace(tail=np.zeros((0, 2), np.float32),
                            offset=1, emitted=0, watermark=0)
    blob = resilience.checkpoint_carry(carry, geometry={"k": 8})
    path = str(tmp_path / "lane.ckpt")
    resilience.save_checkpoint(path, blob)
    assert resilience.load_checkpoint(path).offset == 1
    assert [n for n in os.listdir(tmp_path)] == ["lane.ckpt"]
    # overwrite is atomic too: the old content is never torn
    resilience.save_checkpoint(path, blob)
    assert resilience.load_checkpoint(path).offset == 1


# ------------------------------------------------- stub recovery


class _StubStats:
    def __init__(self, chunk_steps):
        self.chunk_steps = chunk_steps


class _Stub:
    """Sample-count stub whose checkpoints are REAL carry blobs (the
    recovery path parses them for acked/dedupe math)."""

    GEO = {"chunk_len": 256, "frame_len": 64}

    def __init__(self, s, chunk_len=256, frame_len=64):
        self.s, self.chunk_len = s, chunk_len
        self.stride = chunk_len - frame_len
        self._tails = [0] * s
        self._offsets = [0] * s
        self._emitted = [0] * s
        self._steps = 0
        self._flushed = False
        self.restored = {}

    @property
    def stats(self):
        return _StubStats(self._steps)

    def quarantined(self, i):
        return False

    def push_many(self, slabs):
        out = []
        for i, a in slabs.items():
            self._tails[i] += int(a.shape[0])
        while any(t >= self.chunk_len for t in self._tails):
            self._steps += 1
            for i in range(self.s):
                if self._tails[i] >= self.chunk_len:
                    out.append((i, ("frame", i, self._offsets[i])))
                    self._emitted[i] += 1
                    self._tails[i] -= self.stride
                    self._offsets[i] += self.stride
        return out

    def drain_pending(self):
        return []

    def flush_stream(self, i):
        out = []
        if self._tails[i]:
            self._steps += 1
            out.append((i, ("frame", i, self._offsets[i])))
            self._emitted[i] += 1
            self._tails[i] = 0
        return out

    def reset_stream(self, i):
        self._tails[i] = 0
        self._offsets[i] = 0
        self._emitted[i] = 0
        self.restored.pop(i, None)
        return []

    def restore_stream(self, i, blob):
        st = resilience.restore_carry(blob)
        self.restored[i] = blob
        self._offsets[i] = int(st.offset)
        self._tails[i] = int(st.tail.shape[0])
        self._emitted[i] = int(st.emitted)
        return []

    def _blob(self, i):
        carry = SimpleNamespace(
            tail=np.zeros((self._tails[i], 2), np.float32),
            offset=self._offsets[i], emitted=self._emitted[i],
            watermark=self._offsets[i])
        return resilience.checkpoint_carry(carry, geometry=self.GEO)

    def checkpoint(self, i):
        return self._blob(i), []

    def checkpoint_fleet(self, lanes=None):
        which = range(self.s) if lanes is None else lanes
        return {i: self._blob(i) for i in which}, []

    def flush(self):
        self._flushed = True
        return []


def _stub_cfg(tmp_path, n_lanes=2, **kw):
    return serve.ServeConfig(
        n_lanes=n_lanes, chunk_len=256, frame_len=64, queue_cap=4,
        default_slo_s=50.0, snapshot_dir=str(tmp_path / "srv"),
        snapshot_every=1, **kw)


def test_stub_crash_recover_session_table_exact(tmp_path):
    clock = [0.0]
    cfg = _stub_cfg(tmp_path)
    slab = np.zeros((300, 2), np.float32)
    srv = serve.ServeRuntime(cfg, receiver=_Stub(2),
                             clock=lambda: clock[0])
    with srv:
        srv.connect("a", slo_s=40.0)
        srv.connect("b")
        srv.connect("q1")                  # queued
        srv.submit("a", slab)
        srv.submit("b", slab)
        srv.step()
        srv.submit("a", slab)
        srv.step()
        srv.close("b")                     # q1 promotes to the lane
        clock[0] = 7.0
        srv._drained = True                # CRASH
    assert srv.stats().snapshots >= 1

    srv2 = serve.ServeRuntime.recover(
        cfg.snapshot_dir, receiver=_Stub(2), clock=lambda: clock[0])
    assert set(srv2._sessions) == {"a", "q1"}
    assert srv2._gone.get("b") == "closed"
    assert srv2.stats().restarts == 1
    # lane state restored; acked names the resubmission coordinate
    assert srv2._rx.restored
    info = srv2.recovered["a"]
    assert info["acked"] > 0 and info["dedupe_until"] >= 1
    # the SLO remainder survives: "a" had 40s from t=0, crash at t=7
    d = srv2._sessions["a"].deadline
    assert d is not None and d <= clock[0] + 40.0
    # terminal sessions answer with their reason, not a KeyError
    r = srv2.submit("b", slab)
    assert not r.accepted and r.reason == "closed"


def test_stub_recover_elastic_repack_onto_fewer_lanes(tmp_path):
    clock = [0.0]
    cfg = _stub_cfg(tmp_path, n_lanes=3)
    slab = np.zeros((300, 2), np.float32)
    srv = serve.ServeRuntime(cfg, receiver=_Stub(3),
                             clock=lambda: clock[0])
    with srv:
        for sid in ("a", "b", "c"):
            srv.connect(sid)
            srv.submit(sid, slab)
        srv.step()
        srv._drained = True                # CRASH
    # the device fleet SHRANK: recover onto one lane — sessions
    # repack into the admission queue instead of being lost
    srv2 = serve.ServeRuntime.recover(
        cfg.snapshot_dir, config=cfg._replace(n_lanes=1),
        receiver=_Stub(1), clock=lambda: clock[0])
    assert set(srv2._sessions) == {"a", "b", "c"}
    assert sum(1 for s in ("a", "b", "c")
               if srv2.is_active(s)) == 1
    assert len(srv2._queue) == 2
    with srv2:
        # closing the active session admits the next queued one —
        # the scheduler's normal repack, restore blob included
        active = [s for s in ("a", "b", "c")
                  if srv2.is_active(s)][0]
        srv2.close(active)
        assert sum(1 for s in ("a", "b", "c")
                   if srv2.is_active(s)) == 1


def test_stub_journal_only_recovery_dedupes_redelivery(tmp_path):
    """No snapshot ever lands (snapshot_every=0): recovery comes from
    the journal alone — the session restores FRESH, the client
    resubmits from zero, and re-emissions up to the journaled
    delivery watermark are suppressed (serve.deduped), so the client
    sees every frame exactly once."""
    cfg = _stub_cfg(tmp_path)._replace(snapshot_every=0)
    slab = np.zeros((300, 2), np.float32)
    srv = serve.ServeRuntime(cfg, receiver=_Stub(2),
                             clock=lambda: 0.0)
    got = []
    with srv:
        srv.connect("a")
        srv.submit("a", slab)
        got += srv.step()              # delivers frame #1
        got += srv.step()              # flushes frame #1's mark
        srv._drained = True            # CRASH (staged+lane lost)
    assert len(got) == 1

    srv2 = serve.ServeRuntime.recover(
        cfg.snapshot_dir, config=cfg, receiver=_Stub(2),
        clock=lambda: 0.0)
    assert srv2.recovered["a"] == {
        "acked": 0, "dedupe_until": 1, "active": True}
    with srv2:
        srv2.submit("a", slab)         # the client's full resend
        srv2.submit("a", slab)
        for _ in range(6):
            got += srv2.step()
    # frame #1 re-emitted but SUPPRESSED; later frames delivered once
    assert srv2.stats().deduped == 1
    starts = [f[2] for _sid, f in got]
    assert len(starts) == len(set(starts))


def test_stub_second_crash_keeps_post_recovery_state(tmp_path):
    """Crash the SAME directory twice: the first recovery must
    continue the absolute snapshot-step and journal-sequence lines
    (the fresh receiver restarts chunk_steps at 0; a fully-pruned
    journal restarts seq at 0), or the second recovery silently
    rolls back to pre-first-crash state — sessions admitted after
    recovery vanish, closed sessions resurrect."""
    clock = [0.0]
    # segment_records=1: every snapshot prunes the journal EMPTY,
    # the seq-restart trap the bump_seq fix exists for
    cfg = _stub_cfg(tmp_path, journal_segment_records=1)
    slab = np.zeros((300, 2), np.float32)
    srv = serve.ServeRuntime(cfg, receiver=_Stub(2),
                             clock=lambda: clock[0])
    with srv:
        srv.connect("a")
        srv.submit("a", slab)
        srv.step()
        srv._drained = True                # CRASH #1
    step1 = durability.load_snapshot(cfg.snapshot_dir).step
    assert step1 >= 1

    srv2 = serve.ServeRuntime.recover(
        cfg.snapshot_dir, receiver=_Stub(2), clock=lambda: clock[0])
    with srv2:
        srv2.connect("b")                  # post-recovery admission
        srv2.close("a")                    # post-recovery terminal
        srv2.submit("b", slab)
        srv2.step()                        # post-recovery snapshot
        srv2.step()                        # flushes b's delivery mark
        srv2._drained = True               # CRASH #2
    snap2 = durability.load_snapshot(cfg.snapshot_dir)
    # the post-recovery snapshot is numbered PAST the first crash's
    # (absolute steps), so it is the one recovery #2 loads — never
    # pruned as "oldest", never shadowed by the stale snapshot
    assert snap2.step > step1

    srv3 = serve.ServeRuntime.recover(
        cfg.snapshot_dir, receiver=_Stub(2), clock=lambda: clock[0])
    assert set(srv3._sessions) == {"b"}    # b survives, a stays gone
    assert srv3._gone.get("a") == "closed"
    assert srv3.recovered["b"]["dedupe_until"] >= 1


def test_journal_enospc_contained_and_counted(tmp_path):
    cfg = _stub_cfg(tmp_path)
    slab = np.zeros((300, 2), np.float32)
    with faults.inject(faults.FaultSpec("journal.append", "io_enospc",
                                        every=2)):
        srv = serve.ServeRuntime(cfg, receiver=_Stub(2),
                                 clock=lambda: 0.0)
        with srv:
            srv.connect("a")
            srv.connect("b")
            srv.submit("a", slab)
            srv.step()
            srv.step()
    st = srv.stats()
    assert st.journal_errors >= 1         # contained, never raised
    assert st.admitted == 2


def test_retry_after_jitter_replay_and_spread(tmp_path):
    cfg = serve.ServeConfig(n_lanes=1, chunk_len=256, frame_len=64,
                            queue_cap=0, retry_after_s=1.0)

    def hints(seed):
        srv = serve.ServeRuntime(
            cfg._replace(jitter_seed=seed), receiver=_Stub(1),
            clock=lambda: 0.0)
        with srv:
            srv.connect("holder")
            one_again = [srv.connect("r0").retry_after_s
                         for _ in range(3)]
            spread = [srv.connect(f"s{i}").retry_after_s
                      for i in range(8)]
        return one_again, spread

    again1, spread1 = hints(0)
    again2, spread2 = hints(0)
    # deterministic: a replay hints identically
    assert again1 == again2 and spread1 == spread2
    # per-attempt jitter: the SAME session's successive rejects vary
    assert len(set(again1)) == 3
    # per-session spread: 8 synchronized rejects get 8 hints — no
    # thundering-herd lockstep — all inside the documented envelope
    assert len(set(spread1)) == 8
    assert all(0.5 * 1.0 <= h < 1.0 for h in spread1)
    # a different seed jitters differently
    _a, spread3 = hints(1)
    assert spread3 != spread1


# ------------------------------------------------- fleet recovery


def _same(a, b) -> bool:
    return (a.start == b.start and a.result.ok == b.result.ok
            and a.result.rate_mbps == b.result.rate_mbps
            and a.result.length_bytes == b.result.length_bytes
            and np.array_equal(np.asarray(a.result.psdu_bits),
                               np.asarray(b.result.psdu_bits))
            and a.result.crc_ok == b.result.crc_ok)


@pytest.fixture(scope="module")
def fleet_corpus():
    from ziria_tpu.backend import framebatch
    clients = serve.synth_load(3, 4, n_bytes=N_BYTES, snr_db=30.0,
                               seed=20260804, tail=FRAME_LEN)
    oracle = {c.sid: framebatch.receive_stream(c.stream, **GEO)[0]
              for c in clients}
    assert all(len(v) == 4 for v in oracle.values())
    return clients, oracle


def _crash_run(cfg, clients, crash_after=3):
    got = {c.sid: [] for c in clients}
    srv = serve.ServeRuntime(cfg)
    delivered = 0
    with srv:
        for c in clients:
            srv.connect(c.sid)
        pos = {c.sid: 0 for c in clients}
        while delivered < crash_after and any(
                pos[c.sid] < c.stream.shape[0] for c in clients):
            for c in clients:
                lo = pos[c.sid]
                hi = min(lo + 1700, c.stream.shape[0])
                if lo < hi:
                    srv.submit(c.sid, c.stream[lo:hi])
                    pos[c.sid] = hi
            for sid, f in srv.step():
                got[sid].append(f)
                delivered += 1
        srv._drained = True                # CRASH: no drain, no close
    return srv, got


def _finish(srv2, clients, got):
    with srv2:
        for sid, f in srv2.replayed:
            got[sid].append(f)
        for c in clients:
            if c.sid not in srv2._sessions:
                srv2.connect(c.sid)
            srv2.submit(c.sid, c.stream[srv2.acked(c.sid):])
        idle = 0
        while idle < 3:
            frames = srv2.step()
            for sid, f in frames:
                got[sid].append(f)
            idle = 0 if frames else idle + 1
        for sid, f in srv2.drain():
            got[sid].append(f)


def _assert_identical_after_dedupe(clients, oracle, got):
    dups = 0
    for c in clients:
        seen = {}
        for f in got[c.sid]:
            if f.start in seen:
                assert _same(f, seen[f.start])
                dups += 1
                continue
            seen[f.start] = f
        want = oracle[c.sid]
        assert sorted(seen) == [f.start for f in want], \
            (c.sid, sorted(seen), [f.start for f in want])
        for w in want:
            assert _same(seen[w.start], w), (c.sid, w.start)
    return dups


def test_fleet_crash_recover_bit_identical_and_budget(
        fleet_corpus, tmp_path):
    """THE acceptance path: crash mid-stream with live lane state,
    recover from disk, resubmit from acked — every session's frames
    bit-identical to the uninterrupted oracle (at-least-once;
    duplicates carry identical bits), with the post-recovery
    dispatch budget <= 2 per chunk-step and ZERO recompiles for the
    unchanged geometry."""
    from ziria_tpu.phy.wifi import rx as _rx
    clients, oracle = fleet_corpus
    cfg = serve.ServeConfig(n_lanes=S, queue_cap=8, sanitize=True,
                            snapshot_dir=str(tmp_path / "d"),
                            snapshot_every=1, **GEO)
    srv, got = _crash_run(cfg, clients)
    assert srv.stats().snapshots >= 1

    srv2 = serve.ServeRuntime.recover(cfg.snapshot_dir)
    # config round-trips through the snapshot manifest
    assert srv2.cfg.chunk_len == CHUNK and srv2.cfg.n_lanes == S
    assert srv2.stats().restarts == 1
    assert set(srv2._sessions) == {c.sid for c in clients}
    with dispatch.no_recompile(_rx._jit_stream_chunk_multi,
                               _rx._jit_stream_decode_multi):
        with dispatch.count_dispatches() as d:
            _finish(srv2, clients, got)
    steps = int(srv2.stats().chunk_steps)
    assert steps >= 1
    assert d.total <= 2 * steps, (dict(d.counts), steps)
    _assert_identical_after_dedupe(clients, oracle, got)


def test_fleet_elastic_recover_onto_one_lane(fleet_corpus, tmp_path):
    """Elastic mesh failover: the fleet shrinks from S=8 lanes to 1
    (lost devices on restart) — lane checkpoints migrate through
    restore_stream onto the smaller S-divisible geometry, sessions
    repack through the queue, and every stream still completes
    bit-identically."""
    clients, oracle = fleet_corpus
    cfg = serve.ServeConfig(n_lanes=S, queue_cap=8, sanitize=True,
                            snapshot_dir=str(tmp_path / "d"),
                            snapshot_every=1, **GEO)
    srv, got = _crash_run(cfg, clients)
    assert srv.stats().snapshots >= 1

    small = cfg._replace(n_lanes=1, queue_cap=8)
    srv2 = serve.ServeRuntime.recover(cfg.snapshot_dir, config=small)
    assert set(srv2._sessions) == {c.sid for c in clients}
    assert sum(1 for c in clients if srv2.is_active(c.sid)) == 1
    assert len(srv2._queue) == 2
    with srv2:
        for sid, f in srv2.replayed:
            got[sid].append(f)
        # serve the sessions one lane at a time: push, drain the
        # active one, close it, let the next restore into the lane
        remaining = [c for c in clients]
        for _round in range(3):
            active = [c for c in remaining
                      if srv2.is_active(c.sid)]
            assert len(active) == 1
            c = active[0]
            srv2.submit(c.sid, c.stream[srv2.acked(c.sid):])
            idle = 0
            while idle < 3:
                frames = srv2.step()
                for sid, f in frames:
                    got[sid].append(f)
                idle = 0 if frames else idle + 1
            for sid, f in srv2.close(c.sid):
                got[sid].append(f)
            remaining.remove(c)
        for sid, f in srv2.drain():
            got[sid].append(f)
    _assert_identical_after_dedupe(clients, oracle, got)


def test_elastic_mesh_helper_divisors():
    from ziria_tpu.parallel import batch as pbatch
    assert pbatch.largest_divisor(8, 8) == 8
    assert pbatch.largest_divisor(8, 5) == 4
    assert pbatch.largest_divisor(6, 4) == 3
    assert pbatch.largest_divisor(7, 3) == 1
    with pytest.raises(ValueError):
        pbatch.largest_divisor(0, 4)
    # one-device degenerate case: None (unsharded receiver)
    assert pbatch.elastic_mesh(4, n_devices=1) is None
    m = pbatch.elastic_mesh(4, n_devices=len(
        __import__("jax").devices()))
    if m is not None:
        assert 4 % m.size == 0


def test_snapshot_rider_redelivers_unmarked_frames(
        fleet_corpus, tmp_path):
    """Frames emitted by the snapshot's own drain are journal-unmarked
    at write time; the snapshot carries them verbatim (the rider) and
    recovery re-delivers them — the at-least-once closure of the one
    loss window atomicity alone cannot cover."""
    clients, oracle = fleet_corpus
    cfg = serve.ServeConfig(n_lanes=S, queue_cap=8, sanitize=True,
                            snapshot_dir=str(tmp_path / "d"),
                            snapshot_every=1, **GEO)
    srv, got = _crash_run(cfg, clients, crash_after=1)
    # the crash hit right after the first delivery: its mark never
    # flushed, so it MUST ride the snapshot
    snap = durability.load_snapshot(cfg.snapshot_dir)
    assert snap is not None and len(snap.body["rider"]) >= 1
    ent = snap.body["rider"][0]
    fr = durability.decode_frame(ent["frame"])
    by_start = {f.start: f for f in oracle[ent["sid"]]}
    assert fr.start in by_start and _same(fr, by_start[fr.start])
    srv2 = serve.ServeRuntime.recover(cfg.snapshot_dir)
    assert srv2.replayed           # re-delivered, dedupable by start
    _finish(srv2, clients, got)
    _assert_identical_after_dedupe(clients, oracle, got)


@pytest.mark.slow
def test_sigkill_subprocess_recovery_bit_identical(tmp_path):
    """Real process death: a serving child is SIGKILLed mid-chunk-step
    (live journal + snapshot traffic); the parent recovers the fleet
    from the directory the corpse left and finishes every stream —
    the union of the child's delivered frames and the recovered run,
    deduped by (sid, start), is bit-identical to the oracle."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "soak", os.path.join(os.path.dirname(__file__), "..",
                             "tools", "soak.py"))
    soak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(soak)

    clients = soak._clients(3, 4, 20260804)
    oracle = soak._oracle(clients)
    ev = soak.run_sigkill_round(clients, oracle,
                                str(tmp_path / "kill"),
                                seed=20260804, n_lanes=4,
                                frames_per_session=4,
                                tick_sleep=0.05)
    assert ev["killed"] or ev["kill_missed"]
    assert ev["frames_checked"] >= sum(
        len(v) for v in oracle.values())
    if ev["killed"] and not ev["kill_missed"]:
        assert ev["recovery_s"] > 0


def test_serve_cli_snapshot_flags_parse():
    # the flags exist and wire into the config (no fleet spin-up:
    # --recover without --snapshot-dir is the cheap failure path)
    with pytest.raises(SystemExit):
        serve.main(["--recover"])
