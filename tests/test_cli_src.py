"""CLI end-to-end over .zir sources: the reference's golden-file flow.

Each examples/*.zir compiles via --src and runs through the driver with
file I/O in both dbg and bin modes, on both backends; outputs must agree
with the interpreter oracle (the reference's BlinkDiff discipline,
SURVEY.md §4)."""

import os

import numpy as np
import pytest

from ziria_tpu.frontend import compile_file
from ziria_tpu.interp.interp import run
from ziria_tpu.runtime.buffers import StreamSpec, read_stream, write_stream
from ziria_tpu.runtime.cli import main as cli_main
from ziria_tpu.utils.diff import stream_diff

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


def _run_cli(src, in_arr, in_ty, tmp_path, mode="dbg", backend="jit",
             extra=()):
    inf = tmp_path / f"in.{mode}"
    outf = tmp_path / f"out.{mode}"
    write_stream(StreamSpec(ty=in_ty, path=str(inf), mode=mode), in_arr)
    rc = cli_main([
        f"--src={src}",
        "--input=file", f"--input-file-name={inf}",
        f"--input-file-mode={mode}",
        "--output=file", f"--output-file-name={outf}",
        f"--output-file-mode={mode}", f"--backend={backend}", *extra,
    ])
    assert rc == 0
    prog = compile_file(str(src))
    return read_stream(StreamSpec(ty=prog.out_ty or in_ty, path=str(outf),
                                  mode=mode))


def _oracle(src, in_arr):
    prog = compile_file(str(src))
    return run(prog.comp, list(np.asarray(in_arr))).out_array()


@pytest.mark.parametrize("mode", ["dbg", "bin"])
@pytest.mark.parametrize("backend", ["interp", "jit"])
def test_scrambler_cli(tmp_path, mode, backend):
    src = os.path.join(EXAMPLES, "scrambler.zir")
    rng = np.random.default_rng(0)
    xs = rng.integers(0, 2, 256).astype(np.uint8)
    out = _run_cli(src, xs, "bit", tmp_path, mode, backend)
    want = _oracle(src, xs)
    np.testing.assert_array_equal(out, want.astype(np.uint8))
    # known-answer: scrambling zeros yields the 127-bit sequence
    from ziria_tpu.ops.scramble import np_lfsr_sequence_127
    zs = np.zeros(127, np.uint8)
    out0 = _run_cli(src, zs, "bit", tmp_path, mode, backend)
    # bin mode pads bit streams to a byte boundary (no length header,
    # same as the reference's buf_bit) — compare the first 127
    np.testing.assert_array_equal(
        out0[:127], np_lfsr_sequence_127(
            np.array([1, 0, 1, 1, 1, 0, 1], np.uint8)))


@pytest.mark.parametrize("backend", ["interp", "jit"])
def test_fir_cli(tmp_path, backend):
    src = os.path.join(EXAMPLES, "fir.zir")
    xs = (100 * np.sin(np.arange(200) / 5)).astype(np.int32)
    out = _run_cli(src, xs, "int32", tmp_path, "dbg", backend)
    want = _oracle(src, xs)
    np.testing.assert_array_equal(out, want)


@pytest.mark.parametrize("mode", ["dbg", "bin"])
def test_fft64_cli(tmp_path, mode):
    src = os.path.join(EXAMPLES, "fft64.zir")
    rng = np.random.default_rng(2)
    xs = rng.integers(-512, 512, (256, 2)).astype(np.int16)
    out = _run_cli(src, xs, "complex16", tmp_path, mode)
    want = _oracle(src, xs)
    # int16 quantization on the way out: tolerance compare (BlinkDiff role)
    rep = stream_diff(out.astype(np.float64), want.astype(np.float64),
                      atol=1.0)
    assert rep, rep.message


def test_interleaver_cli_flag_matrix(tmp_path):
    """Flag matrix: fold/autolut/backends must not change output."""
    src = os.path.join(EXAMPLES, "interleaver.zir")
    rng = np.random.default_rng(3)
    xs = rng.integers(0, 2, 480).astype(np.uint8)
    want = _oracle(src, xs)
    for backend in ("interp", "jit"):
        for extra in ((), ("--no-fold",), ("--autolut",)):
            out = _run_cli(src, xs, "bit", tmp_path, "dbg", backend,
                           extra=extra)
            np.testing.assert_array_equal(out, want.astype(np.uint8),
                                          err_msg=f"{backend} {extra}")
    # and the permutation is its own inverse's inverse: applying it twice
    # on indices returns sorted order only for the identity — sanity-check
    # the known BPSK pattern instead
    blk = want[:48]
    k = np.arange(48)
    perm = 3 * (k % 16) + k // 16
    src_blk = xs[:48]
    np.testing.assert_array_equal(blk[perm], src_blk)


@pytest.mark.parametrize("backend", ["interp", "jit"])
def test_wifi_tx_bpsk_matches_ops_chain(tmp_path, backend):
    """The surface-syntax TX bit pipeline == the ops/ oracle chain
    (scramble ^ seq -> conv_encode -> interleave at N_CBPS=48)."""
    from ziria_tpu.ops.coding import np_conv_encode_ref
    from ziria_tpu.ops.interleave import interleave
    from ziria_tpu.ops.scramble import np_lfsr_sequence_127

    src = os.path.join(EXAMPLES, "wifi_tx_bpsk.zir")
    rng = np.random.default_rng(7)
    n_bits = 24 * 8            # -> 48*8 coded bits, 8 interleaver blocks
    xs = rng.integers(0, 2, n_bits).astype(np.uint8)
    out = _run_cli(src, xs, "bit", tmp_path, "dbg", backend)

    seed = np.array([1, 0, 1, 1, 1, 0, 1], np.uint8)
    scr = xs ^ np.resize(np_lfsr_sequence_127(seed), n_bits)
    coded = np_conv_encode_ref(scr)
    want = np.concatenate([
        np.asarray(interleave(coded[k:k + 48], 48, 1))
        for k in range(0, coded.size, 48)])
    np.testing.assert_array_equal(out.astype(np.uint8), want)


def test_packet_detect_zir_dynamic_control(tmp_path):
    """The streaming STS detector: a while-loop computer terminating
    with a value (interpreter backend — data-dependent control)."""
    src = os.path.join(EXAMPLES, "packet_detect.zir")
    rng = np.random.default_rng(11)
    # 100 noise samples, then a periodic (period-16) STS-like burst
    noise = rng.normal(0, 30, (100, 2))
    sts16 = rng.normal(0, 300, (16, 2))
    burst = np.tile(sts16, (10, 1))
    xs = np.concatenate([noise, burst]).astype(np.int16)
    out = _run_cli(src, xs, "complex16", tmp_path, "dbg", "interp")
    # detection fires once the window is periodic: a little after the
    # burst start + one 16-lag window fill
    assert out.shape[0] == 1
    assert 100 <= int(out[0]) <= 140, int(out[0])


def test_lut_map_autolut_flag_matrix(tmp_path):
    """--autolut must leave output unchanged (table == direct eval)."""
    src = os.path.join(EXAMPLES, "lut_map.zir")
    xs = np.arange(-128, 128, dtype=np.int8)
    outs = {}
    for backend in ("interp", "jit"):
        for extra in ((), ("--autolut",)):
            outs[(backend, extra)] = _run_cli(
                src, xs, "int8", tmp_path, "dbg", backend, extra=extra)
    base = outs[("interp", ())]
    for k, v in outs.items():
        np.testing.assert_array_equal(v, base, err_msg=str(k))
    # spot-check the function: x=0b00001011 -> nibble 1011 reversed
    # 1101=13, parity of high nibble 0000 is 0
    assert base[128 + 0b1011] == 13


@pytest.mark.parametrize("backend", ["interp", "jit"])
def test_qam16_matches_modulate_oracle(tmp_path, backend):
    from ziria_tpu.ops.modulate import np_modulate_ref

    src = os.path.join(EXAMPLES, "qam16.zir")
    rng = np.random.default_rng(21)
    bits = rng.integers(0, 2, 64 * 4).astype(np.uint8)
    out = _run_cli(src, bits, "bit", tmp_path, "dbg", backend)
    want = np_modulate_ref(bits, 4) * 1024.0
    got = out[:, 0].astype(np.float64) + 1j * out[:, 1].astype(np.float64)
    np.testing.assert_allclose(got, want, atol=1.0)


def test_cli_profile_per_stage(tmp_path, capsys):
    """--profile prints per-stage wall time + item counts and still
    produces the golden output (VERDICT r1 #9, SURVEY.md §5)."""
    src = os.path.join(EXAMPLES, "wifi_tx_bpsk.zir")
    infile = os.path.join(EXAMPLES, "golden", "wifi_tx_bpsk.infile")
    ground = os.path.join(EXAMPLES, "golden", "wifi_tx_bpsk.outfile.ground")
    outf = tmp_path / "out.bin"
    from ziria_tpu.runtime.cli import main as cli_main
    rc = cli_main([
        f"--src={src}", "--input=file", f"--input-file-name={infile}",
        "--input-file-mode=bin", "--output=file",
        f"--output-file-name={outf}", "--output-file-mode=bin",
        "--backend=jit", "--profile",
    ])
    assert rc == 0
    err = capsys.readouterr().err
    assert "profile:" in err and "stage" in err
    with open(outf, "rb") as f1, open(ground, "rb") as f2:
        assert f1.read() == f2.read()


def test_cli_profile_trace(tmp_path):
    """--profile-trace writes a jax.profiler trace directory."""
    src = os.path.join(EXAMPLES, "scrambler.zir")
    infile = os.path.join(EXAMPLES, "golden", "scrambler.infile")
    outf = tmp_path / "out.dbg"
    tdir = tmp_path / "trace"
    from ziria_tpu.runtime.cli import main as cli_main
    rc = cli_main([
        f"--src={src}", "--input=file", f"--input-file-name={infile}",
        "--input-file-mode=dbg", "--output=file",
        f"--output-file-name={outf}", "--output-file-mode=dbg",
        "--backend=jit", f"--profile-trace={tdir}",
    ])
    assert rc == 0
    assert tdir.exists() and any(tdir.rglob("*"))


def test_cli_batch_input_files(tmp_path):
    """--batch-input-files: N captures decode in one process with
    frame-batched device calls, each output equal to its own solo
    run (the driver surface of backend/framebatch)."""
    src = os.path.join(EXAMPLES, "scrambler.zir")
    rng = np.random.default_rng(5)
    ins, outs, solo = [], [], []
    for k in range(4):
        xs = rng.integers(0, 2, 256 + 32 * k).astype(np.uint8)
        inf = tmp_path / f"in{k}.dbg"
        write_stream(StreamSpec(ty="bit", path=str(inf), mode="dbg"),
                     xs)
        ins.append(str(inf))
        outs.append(str(tmp_path / f"out{k}.dbg"))
        sof = tmp_path / f"solo{k}.dbg"
        rc = cli_main([
            f"--src={src}", "--input=file",
            f"--input-file-name={inf}", "--input-file-mode=dbg",
            "--output=file", f"--output-file-name={sof}",
            "--output-file-mode=dbg", "--backend=hybrid"])
        assert rc == 0
        solo.append(sof.read_text())
    rc = cli_main([
        f"--src={src}",
        f"--batch-input-files={','.join(ins)}",
        f"--batch-output-files={','.join(outs)}",
        "--input-file-mode=dbg", "--output-file-mode=dbg"])
    assert rc == 0
    for k, out in enumerate(outs):
        assert open(out).read() == solo[k], f"stream {k}"


def test_cli_batch_validation(tmp_path):
    src = os.path.join(EXAMPLES, "scrambler.zir")
    with pytest.raises(SystemExit, match="together"):
        cli_main([f"--src={src}", "--batch-input-files=a,b"])
    with pytest.raises(SystemExit, match="2 inputs but 1"):
        cli_main([f"--src={src}", "--batch-input-files=a,b",
                  "--batch-output-files=c"])
    with pytest.raises(SystemExit, match="cannot combine"):
        cli_main([f"--src={src}", "--batch-input-files=a",
                  "--batch-output-files=c", "--sp=4"])


def test_cli_compile_cache(tmp_path):
    """--compile-cache: the flag configures the persistent XLA cache
    (in-process verification — this process's jit memo means tiny
    graphs may not hit disk) and the run is output-identical."""
    import jax

    src = os.path.join(EXAMPLES, "fir.zir")
    cache = tmp_path / "xla_cache"
    xs = (100 * np.sin(np.arange(200) / 5)).astype(np.int32)
    outs = []
    for k in range(2):
        inf = tmp_path / f"in{k}.dbg"
        outf = tmp_path / f"out{k}.dbg"
        write_stream(StreamSpec(ty="int32", path=str(inf), mode="dbg"),
                     xs)
        rc = cli_main([
            f"--src={src}", "--input=file",
            f"--input-file-name={inf}", "--input-file-mode=dbg",
            "--output=file", f"--output-file-name={outf}",
            "--output-file-mode=dbg", "--backend=jit",
            f"--compile-cache={cache}"])
        assert rc == 0
        outs.append(outf.read_text())
    assert outs[0] == outs[1]
    assert jax.config.jax_compilation_cache_dir == str(cache)
